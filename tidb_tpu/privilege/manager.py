"""MySQL GRANT-system privilege manager.

Reference analog: pkg/privilege + pkg/privilege/privileges (Handle, the
MySQLPrivilege cache of mysql.user/mysql.db/mysql.tables_priv) — but held
as an in-memory authoritative store on the Domain instead of system-table
rows reloaded on FLUSH: one process owns the catalog here, so the cache
IS the store.  Host matching is exact-or-'%' (no netmasks).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from ..utils import auth as P

# statement-level privileges recognised (mysql.user columns analog)
KNOWN_PRIVS = {"SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP",
               "ALTER", "INDEX", "CREATE USER", "PROCESS", "SUPER"}


class PrivilegeError(PermissionError):
    """ER_TABLEACCESS_DENIED / ER_SPECIFIC_ACCESS_DENIED analog."""


@dataclass
class UserRecord:
    user: str
    host: str
    auth_hash: bytes                       # SHA1(SHA1(password))
    global_privs: set = field(default_factory=set)
    db_privs: dict = field(default_factory=dict)      # db -> set
    table_privs: dict = field(default_factory=dict)   # (db, table) -> set

    def key(self):
        return (self.user, self.host)


class PrivilegeManager:
    def __init__(self):
        self._mu = threading.RLock()
        self.users: dict[tuple, UserRecord] = {}
        # bootstrap root@% with ALL, empty password (session/bootstrap.go
        # doDMLWorks analog)
        root = UserRecord("root", "%", P.native_password_hash(""))
        root.global_privs = set(KNOWN_PRIVS) | {"ALL"}
        self.users[root.key()] = root

    # ---------------- snapshot (watch-plane persistence) ------------- #

    def snapshot(self) -> str:
        """JSON of every user record — the mysql.user/db/tables_priv dump
        the watch plane persists and remote domains reload."""
        with self._mu:
            out = []
            for rec in self.users.values():
                out.append({
                    "user": rec.user, "host": rec.host,
                    "auth": rec.auth_hash.hex(),
                    "auth_plugin": getattr(rec, "auth_plugin", ""),
                    "global": sorted(rec.global_privs),
                    "db": {db: sorted(v)
                           for db, v in rec.db_privs.items()},
                    "table": {f"{db}\x00{tb}": sorted(v)
                              for (db, tb), v in rec.table_privs.items()},
                })
        import json
        return json.dumps(out)

    def load_snapshot(self, blob: str) -> None:
        import json
        recs = json.loads(blob)
        with self._mu:
            self.users.clear()
            for r in recs:
                rec = UserRecord(r["user"], r["host"],
                                 bytes.fromhex(r["auth"]))
                if r.get("auth_plugin"):
                    rec.auth_plugin = r["auth_plugin"]
                rec.global_privs = set(r["global"])
                rec.db_privs = {db: set(v) for db, v in r["db"].items()}
                rec.table_privs = {tuple(k.split("\x00", 1)): set(v)
                                   for k, v in r["table"].items()}
                self.users[rec.key()] = rec

    # ---------------- account management ---------------- #

    def create_user(self, user: str, host: str, password: Optional[str],
                    if_not_exists: bool = False):
        with self._mu:
            if (user, host) in self.users:
                if if_not_exists:
                    return
                raise PrivilegeError(
                    f"Operation CREATE USER failed for '{user}'@'{host}'")
            self.users[(user, host)] = UserRecord(
                user, host, P.native_password_hash(password or ""))

    def alter_user(self, user: str, host: str, password: Optional[str]):
        with self._mu:
            rec = self._must_get(user, host)
            rec.auth_hash = P.native_password_hash(password or "")

    def drop_user(self, user: str, host: str, if_exists: bool = False):
        with self._mu:
            if (user, host) not in self.users:
                if if_exists:
                    return
                raise PrivilegeError(
                    f"Operation DROP USER failed for '{user}'@'{host}'")
            del self.users[(user, host)]

    def _must_get(self, user: str, host: str) -> UserRecord:
        rec = self.users.get((user, host))
        if rec is None:
            raise PrivilegeError(f"unknown user '{user}'@'{host}'")
        return rec

    def _match(self, user: str) -> Optional[UserRecord]:
        """Resolve a connecting user by name.  Connections carry no client
        host here (all are local), so: '%' record first, else the record
        with the lexically-smallest host (deterministic)."""
        rec = self.users.get((user, "%"))
        if rec is not None:
            return rec
        cands = [r for (u, _), r in sorted(self.users.items()) if u == user]
        return cands[0] if cands else None

    # ---------------- grants ---------------- #

    def grant(self, privs: list[str], db: str, table: str,
              user: str, host: str):
        with self._mu:
            rec = self._must_get(user, host)
            pset = {p.upper() for p in privs}
            for p in pset - KNOWN_PRIVS - {"ALL"}:
                raise PrivilegeError(f"unknown privilege {p}")
            if db == "*":
                rec.global_privs |= pset
            elif table == "*":
                rec.db_privs.setdefault(db, set()).update(pset)
            else:
                rec.table_privs.setdefault((db, table), set()).update(pset)

    def revoke(self, privs: list[str], db: str, table: str,
               user: str, host: str):
        with self._mu:
            rec = self._must_get(user, host)
            pset = {p.upper() for p in privs}
            def strip(s: set):
                if "ALL" in pset:
                    s.clear()
                else:
                    s -= pset
            if db == "*":
                strip(rec.global_privs)
            elif table == "*":
                strip(rec.db_privs.setdefault(db, set()))
            else:
                strip(rec.table_privs.setdefault((db, table), set()))

    # ---------------- checks ---------------- #

    def check(self, user: str, priv: str, db: str = "",
              table: str = "") -> bool:
        """RequestVerification analog: global > db > table grant levels."""
        rec = self._match(user)
        if rec is None:
            return False
        priv = priv.upper()
        def has(s):
            return "ALL" in s or priv in s
        if has(rec.global_privs):
            return True
        if db and has(rec.db_privs.get(db, ())):
            return True
        if db and table and has(rec.table_privs.get((db, table), ())):
            return True
        return False

    def has_db_access(self, user: str, db: str) -> bool:
        """USE/COM_INIT_DB check: any privilege at global, db, or
        any-table-in-db level grants visibility (mysql checkGrantDB)."""
        rec = self._match(user)
        if rec is None:
            return False
        if rec.global_privs:
            return True
        if rec.db_privs.get(db):
            return True
        return any(d == db and privs
                   for (d, _t), privs in rec.table_privs.items())

    def require(self, user: str, priv: str, db: str = "", table: str = ""):
        if not self.check(user, priv, db, table):
            target = f"table '{db}.{table}'" if table else (
                f"database '{db}'" if db else "this operation")
            raise PrivilegeError(
                f"{priv} command denied to user '{user}' for {target}")

    # ---------------- introspection / auth ---------------- #

    def show_grants(self, user: str, host: str = "%") -> list[str]:
        rec = self.users.get((user, host)) or self._match(user)
        if rec is None:
            raise PrivilegeError(f"unknown user '{user}'@'{host}'")
        ident = f"'{rec.user}'@'{rec.host}'"
        out = []
        gp = sorted(rec.global_privs)
        if "ALL" in rec.global_privs:
            out.append(f"GRANT ALL PRIVILEGES ON *.* TO {ident}")
        elif gp:
            out.append(f"GRANT {', '.join(gp)} ON *.* TO {ident}")
        else:
            out.append(f"GRANT USAGE ON *.* TO {ident}")
        for db in sorted(rec.db_privs):
            ps = sorted(rec.db_privs[db])
            if ps:
                out.append(f"GRANT {', '.join(ps)} ON {db}.* TO {ident}")
        for (db, tbl) in sorted(rec.table_privs):
            ps = sorted(rec.table_privs[(db, tbl)])
            if ps:
                out.append(f"GRANT {', '.join(ps)} ON {db}.{tbl} TO {ident}")
        return out

    def authenticate(self, user: str, auth: bytes, salt: bytes):
        """Wire-auth verify; returns (ok, error_message)."""
        rec = self._match(user)
        if rec is None:
            return False, f"Access denied for user '{user}'"
        if not P.check_scramble(auth, salt, rec.auth_hash):
            return False, f"Access denied for user '{user}' (using password: " \
                          f"{'YES' if auth else 'NO'})"
        return True, None

    def authenticate_cleartext(self, user: str, password: str):
        """caching_sha2_password FULL-auth verify (TLS-protected
        cleartext checks against the stored SHA1(SHA1(pw)))."""
        from ..utils.auth import native_password_hash
        rec = self._match(user)
        if rec is None:
            return False, f"Access denied for user '{user}'"
        if native_password_hash(password) != rec.auth_hash:
            return False, f"Access denied for user '{user}'"
        return True, None


__all__ = ["PrivilegeManager", "PrivilegeError", "UserRecord", "KNOWN_PRIVS"]
