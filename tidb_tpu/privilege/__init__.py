from .manager import PrivilegeError, PrivilegeManager

__all__ = ["PrivilegeManager", "PrivilegeError"]
