"""Micro-bench: parallel host operators vs serial (P10 worker-pool seam,
projection.go:205 / hash-join probe workers analog).  Run on a multi-core
host: `python -m tidb_tpu.testing.bench_host`.  On a 1-core container the
pool clamps to the direct path and this prints ~1.0x parity."""
import time

import numpy as np

from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.physical import (ExecContext, HostProjection,
                                        HostHashJoin, PhysOp, ResultChunk)
from tidb_tpu.expr import ColumnRef, builders as B
from tidb_tpu.types import dtypes as dt

BI = dt.bigint(False)
N, CH = 6_000_000, 64 * 1024
rng = np.random.default_rng(0)
data = rng.integers(0, 1 << 40, N)
d2 = rng.integers(1, 1 << 20, N)

class Src(PhysOp):
    out_names = ["a", "b"]
    out_dtypes = [BI, BI]
    def chunks(self, ctx, required_rows=None):
        for lo in range(0, N, CH):
            yield ResultChunk(["a", "b"], [
                Column(BI, data[lo:lo+CH], np.ones(min(CH, N-lo), bool)),
                Column(BI, d2[lo:lo+CH], np.ones(min(CH, N-lo), bool))])

a, b = ColumnRef(BI, 0, "a"), ColumnRef(BI, 1, "b")
# expensive-ish projection: mixed arithmetic chains
exprs = [B.arith("mul", B.arith("add", a, b), B.arith("mod", a, b)),
         B.arith("mod", B.arith("mul", a, a), B.arith("add", b, B.lit(7))),
         B.arith("add", B.arith("intdiv", a, b), B.arith("mul", b, b))]
proj = HostProjection(Src(), exprs, out_names=["x", "y", "z"])

def run(conc):
    ctx = ExecContext(None, {"tidb_executor_concurrency": conc})
    t = time.time()
    rows = sum(ch.num_rows for ch in proj.chunks(ctx))
    return time.time() - t, rows

run(1)
t1, r1 = run(1)
t8, r8 = run(8)
print(f"projection: serial {t1*1e3:.0f}ms  8-way {t8*1e3:.0f}ms  "
      f"speedup {t1/t8:.2f}x  rows={r1}")
assert r1 == r8 == N

# hash join probe: 6M probe rows vs 100k build
build_n = 100_000
bk = rng.integers(0, 1 << 20, build_n)
class BuildSrc(PhysOp):
    out_names = ["k", "w"]
    out_dtypes = [BI, BI]
    def execute(self, ctx):
        return ResultChunk(["k", "w"], [
            Column(BI, bk, np.ones(build_n, bool)),
            Column(BI, bk * 2, np.ones(build_n, bool))])
join = HostHashJoin("inner", Src(), BuildSrc(), [(1, 0)], [],
                    out_names=["a", "b", "k", "w"],
                    out_dtypes=[BI, BI, BI, BI])
def runj(conc):
    ctx = ExecContext(None, {"tidb_executor_concurrency": conc})
    t = time.time()
    rows = sum(ch.num_rows for ch in join.chunks(ctx))
    return time.time() - t, rows
runj(1)
tj1, rj1 = runj(1)
tj8, rj8 = runj(8)
print(f"hash join:  serial {tj1*1e3:.0f}ms  8-way {tj8*1e3:.0f}ms  "
      f"speedup {tj1/tj8:.2f}x  rows={rj1}")
assert rj1 == rj8
