"""Minimal protocol-faithful MySQL client (v4.1 protocol).

Role: the image ships no third-party MySQL connector (pymysql /
mysql-connector are absent), so interop tests drive the server through
this independent client implementation instead — TLS upgrade
(SSLRequest), mysql_native_password AND caching_sha2_password (fast +
full auth), COM_QUERY text resultsets, and prepared statements with
read-only cursors + COM_STMT_FETCH.  It shares NO code with the server
loop: packets are parsed here from the wire bytes, so a framing or
status-flag bug on either side fails the tests.

Reference analog: the clients TiDB tests itself with (go-sql-driver
semantics; conn.go:2497 upgradeToTLS, conn.go:1436 ComStmtFetch).
"""

from __future__ import annotations

import socket
import ssl as ssl_mod
import struct
from typing import Any, Optional

from ..utils.auth import scramble_password, sha2_scramble

CLIENT_LONG_PASSWORD = 1 << 0
CLIENT_CONNECT_WITH_DB = 1 << 3
CLIENT_PROTOCOL_41 = 1 << 9
CLIENT_SSL = 1 << 11
CLIENT_TRANSACTIONS = 1 << 13
CLIENT_SECURE_CONNECTION = 1 << 15
CLIENT_PLUGIN_AUTH = 1 << 19

COM_QUERY = 0x03
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_FETCH = 0x1C
CURSOR_TYPE_READ_ONLY = 0x01

SERVER_STATUS_CURSOR_EXISTS = 0x0040
SERVER_STATUS_LAST_ROW_SENT = 0x0080

MYSQL_TYPE_LONGLONG = 0x08
MYSQL_TYPE_DOUBLE = 0x05
MYSQL_TYPE_DATE = 0x0A
MYSQL_TYPE_DATETIME = 0x0C


class ClientError(RuntimeError):
    def __init__(self, errno, msg):
        super().__init__(f"({errno}) {msg}")
        self.errno = errno


def _lenenc_int(buf, pos):
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def _lenenc_str(buf, pos):
    n, pos = _lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


class MiniMySQLClient:
    def __init__(self, host: str, port: int, user: str = "root",
                 password: str = "", use_tls: bool = False,
                 auth_plugin: str = "mysql_native_password",
                 database: str = ""):
        self.sock = socket.create_connection((host, port), timeout=30)
        self.seq = 0
        self.tls = False
        self._connect(user, password, use_tls, auth_plugin, database)

    # ---------------- framing ---------------- #

    def _read_n(self, n):
        buf = b""
        while len(buf) < n:
            got = self.sock.recv(n - len(buf))
            if not got:
                raise ConnectionError("server closed")
            buf += got
        return buf

    def _read_packet(self) -> bytes:
        hdr = self._read_n(4)
        ln = int.from_bytes(hdr[:3], "little")
        self.seq = (hdr[3] + 1) & 0xFF
        return self._read_n(ln)

    def _write_packet(self, payload: bytes):
        self.sock.sendall(len(payload).to_bytes(3, "little")
                          + bytes([self.seq]) + payload)
        self.seq = (self.seq + 1) & 0xFF

    def _command(self, cmd: int, body: bytes):
        self.seq = 0
        self._write_packet(bytes([cmd]) + body)

    # ---------------- handshake ---------------- #

    def _connect(self, user, password, use_tls, plugin, database):
        greet = self._read_packet()
        # protocol v10 greeting
        pos = greet.index(0, 1) + 1          # server version NUL
        pos += 4                              # conn id
        salt = greet[pos:pos + 8]
        pos += 9
        caps = struct.unpack_from("<H", greet, pos)[0]
        pos += 2 + 1 + 2                      # caps lo, charset, status
        caps |= struct.unpack_from("<H", greet, pos)[0] << 16
        pos += 2 + 1 + 10
        salt += greet[pos:pos + 12]
        self.server_caps = caps

        my_caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41
                   | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
                   | CLIENT_PLUGIN_AUTH)
        if database:
            my_caps |= CLIENT_CONNECT_WITH_DB
        if use_tls:
            if not caps & CLIENT_SSL:
                raise ClientError(0, "server does not offer TLS")
            my_caps |= CLIENT_SSL
            # SSLRequest: caps + max packet + charset + 23 filler
            self._write_packet(struct.pack("<IIB", my_caps, 1 << 24, 33)
                               + b"\x00" * 23)
            ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl_mod.CERT_NONE   # self-signed server cert
            self.sock = ctx.wrap_socket(self.sock)
            self.tls = True

        if plugin == "caching_sha2_password":
            token = sha2_scramble(password, salt)
        else:
            token = scramble_password(password, salt)
        resp = struct.pack("<IIB", my_caps, 1 << 24, 33) + b"\x00" * 23
        resp += user.encode() + b"\x00"
        resp += bytes([len(token)]) + token
        if database:
            resp += database.encode() + b"\x00"
        resp += plugin.encode() + b"\x00"
        self._write_packet(resp)
        self._auth_loop(password, salt, plugin)

    def _auth_loop(self, password, salt, plugin):
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0x00:        # OK
                return
            if pkt[0] == 0xFF:
                errno = struct.unpack_from("<H", pkt, 1)[0]
                raise ClientError(errno, pkt[9:].decode(errors="replace"))
            if pkt[0] == 0x01:        # AuthMoreData
                if pkt[1:] == b"\x03":      # sha2 fast-auth success
                    continue
                if pkt[1:] == b"\x04":      # perform full authentication
                    if not self.tls:
                        raise ClientError(0, "full auth requires TLS")
                    self._write_packet(password.encode() + b"\x00")
                    continue
            if pkt[0] == 0xFE:        # AuthSwitchRequest
                end = pkt.index(0, 1)
                new_plugin = pkt[1:end].decode()
                new_salt = pkt[end + 1:].rstrip(b"\x00")
                if new_plugin == "caching_sha2_password":
                    self._write_packet(sha2_scramble(password, new_salt))
                else:
                    self._write_packet(scramble_password(password, new_salt))
                continue
            raise ClientError(0, f"unexpected auth packet {pkt[:1].hex()}")

    # ---------------- queries ---------------- #

    def query(self, sql: str) -> list[tuple]:
        """COM_QUERY -> decoded text resultset (or [] for OK)."""
        self._command(COM_QUERY, sql.encode())
        first = self._read_packet()
        if first[0] == 0x00:
            return []
        if first[0] == 0xFF:
            errno = struct.unpack_from("<H", first, 1)[0]
            raise ClientError(errno, first[9:].decode(errors="replace"))
        ncols, _ = _lenenc_int(first, 0)
        cols = [self._read_column_def() for _ in range(ncols)]
        self._read_packet()               # EOF after column defs
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                return rows
            rows.append(self._decode_text_row(pkt, ncols))

    def _read_column_def(self):
        pkt = self._read_packet()
        pos = 0
        fields = []
        for _ in range(6):    # catalog, schema, table, org_table, name, org
            s, pos = _lenenc_str(pkt, pos)
            fields.append(s)
        pos += 1 + 2 + 4      # filler, charset, column length
        type_code = pkt[pos]
        return {"name": fields[4].decode(), "type": type_code}

    @staticmethod
    def _decode_text_row(pkt, ncols):
        out, pos = [], 0
        for _ in range(ncols):
            if pkt[pos] == 0xFB:
                out.append(None)
                pos += 1
            else:
                s, pos = _lenenc_str(pkt, pos)
                out.append(s.decode())
        return tuple(out)

    # ---------------- prepared statements + cursor fetch ------------- #

    def prepare(self, sql: str) -> tuple[int, int]:
        self._command(COM_STMT_PREPARE, sql.encode())
        head = self._read_packet()
        if head[0] == 0xFF:
            errno = struct.unpack_from("<H", head, 1)[0]
            raise ClientError(errno, head[9:].decode(errors="replace"))
        stmt_id = struct.unpack_from("<I", head, 1)[0]
        n_params = struct.unpack_from("<H", head, 7)[0]
        if n_params:
            for _ in range(n_params):
                self._read_packet()
            self._read_packet()    # EOF
        return stmt_id, n_params

    def execute_cursor(self, stmt_id: int) -> list[dict]:
        """COM_STMT_EXECUTE with CURSOR_TYPE_READ_ONLY: returns column
        defs; rows stream through fetch()."""
        body = struct.pack("<IBI", stmt_id, CURSOR_TYPE_READ_ONLY, 1)
        self._command(COM_STMT_EXECUTE, body)
        first = self._read_packet()
        if first[0] == 0xFF:
            errno = struct.unpack_from("<H", first, 1)[0]
            raise ClientError(errno, first[9:].decode(errors="replace"))
        ncols, _ = _lenenc_int(first, 0)
        cols = [self._read_column_def() for _ in range(ncols)]
        eof = self._read_packet()
        status = struct.unpack_from("<H", eof, 3)[0]
        assert status & SERVER_STATUS_CURSOR_EXISTS, \
            "server did not open a cursor"
        self._cursor_cols = cols
        return cols

    def fetch(self, stmt_id: int, count: int) -> tuple[list[tuple], bool]:
        """COM_STMT_FETCH: up to `count` binary rows; (rows, done)."""
        self._command(COM_STMT_FETCH, struct.pack("<II", stmt_id, count))
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                status = struct.unpack_from("<H", pkt, 3)[0]
                return rows, bool(status & SERVER_STATUS_LAST_ROW_SENT)
            rows.append(self._decode_binary_row(pkt, self._cursor_cols))

    @staticmethod
    def _decode_binary_row(pkt, cols):
        n = len(cols)
        nb = (n + 7 + 2) // 8
        bitmap = pkt[1:1 + nb]
        pos = 1 + nb
        out = []
        for i, c in enumerate(cols):
            if bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                out.append(None)
                continue
            t = c["type"]
            if t == MYSQL_TYPE_LONGLONG:
                out.append(struct.unpack_from("<q", pkt, pos)[0])
                pos += 8
            elif t == MYSQL_TYPE_DOUBLE:
                out.append(struct.unpack_from("<d", pkt, pos)[0])
                pos += 8
            elif t in (MYSQL_TYPE_DATE, MYSQL_TYPE_DATETIME):
                ln = pkt[pos]
                pos += 1 + ln
                out.append(f"<temporal:{ln}>")
            else:
                s, pos = _lenenc_str(pkt, pos)
                out.append(s.decode())
        return tuple(out)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


__all__ = ["MiniMySQLClient", "ClientError"]
