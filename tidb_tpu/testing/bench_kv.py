"""benchkv-style micro-benchmark of the native MVCC engine
(cmd/benchkv/main.go analog): loads N committed keys, then measures
random point-gets (in-process, ctypes overhead excluded via
kv_bench_gets) and a full snapshot scan — memtable-only vs flushed to an
immutable sorted run (the LSM read path).

Usage: python -m tidb_tpu.testing.bench_kv   [BENCHKV_KEYS=2000000]
"""

import ctypes, time
import os
lib = ctypes.CDLL(os.path.join(os.path.dirname(__file__), "..", "native",
                               "libtpukv.so"))
for n,r,a in [("kv_open",ctypes.c_void_p,[]),("kv_alloc_ts",ctypes.c_uint64,[ctypes.c_void_p]),
 ("kv_flush",ctypes.c_int64,[ctypes.c_void_p]),
 ("kv_bench_gets",ctypes.c_int64,[ctypes.c_void_p,ctypes.c_int64,ctypes.c_uint64,ctypes.c_uint64]),
 ("kv_set_flush_threshold",None,[ctypes.c_void_p,ctypes.c_int64])]:
    f=getattr(lib,n); f.restype=r; f.argtypes=a
lib.kv_prewrite.restype=ctypes.c_int32
lib.kv_prewrite.argtypes=[ctypes.c_void_p,ctypes.c_char_p,ctypes.c_int32,ctypes.c_char_p,ctypes.c_int32,ctypes.c_char_p,ctypes.c_int32,ctypes.c_uint64,ctypes.c_uint8]
lib.kv_commit.restype=ctypes.c_int32
lib.kv_commit.argtypes=[ctypes.c_void_p,ctypes.c_char_p,ctypes.c_int32,ctypes.c_uint64,ctypes.c_uint64]
lib.kv_scan.restype=ctypes.c_int32
lib.kv_scan.argtypes=[ctypes.c_void_p,ctypes.c_char_p,ctypes.c_int32,ctypes.c_char_p,ctypes.c_int32,ctypes.c_uint64,ctypes.c_int32,ctypes.c_char_p,ctypes.c_int64,ctypes.POINTER(ctypes.c_int64),ctypes.POINTER(ctypes.c_uint8)]
N = int(os.environ.get("BENCHKV_KEYS", "2000000"))
def bench(flush):
    h = ctypes.c_void_p(lib.kv_open())
    lib.kv_set_flush_threshold(h, 0)
    for i in range(N):
        k = b"%012d" % i; v = b"value-%d" % i
        sts = lib.kv_alloc_ts(h)
        lib.kv_prewrite(h, k, len(k), v, len(v), k, len(k), sts, 0)
        lib.kv_commit(h, k, len(k), sts, lib.kv_alloc_ts(h))
    if flush: lib.kv_flush(h)
    ts = lib.kv_alloc_ts(h)
    ns = lib.kv_bench_gets(h, 1_000_000, 42, ts)
    buf = ctypes.create_string_buffer(64<<20)
    used = ctypes.c_int64(); trunc = ctypes.c_uint8()
    t=time.time()
    n = lib.kv_scan(h, b"", 0, b"", 0, ts, 2_100_000, buf, len(buf), ctypes.byref(used), ctypes.byref(trunc))
    st=time.time()-t
    print(("flushed " if flush else "memtable"), f"get {ns/1e3/1e6:.3f} us/op   scan {N/st/1e6:.1f} M rows/s (n={n})")
bench(False)
bench(True)
