"""TPC-H lineitem/part generator (numpy, vectorized).

Distribution-faithful for the columns Q1/Q6/Q19 touch (quantity, discount,
tax, shipdate ranges, returnflag/linestatus derivation); other columns are
uniform fillers.  SF=1 ≈ 6M lineitem rows, as in the spec.

Reference analog: the reference benchmarks against TPC-H via external
tooling (BASELINE.md); this in-repo generator plays the role of the
reference's benchdb data loaders (cmd/benchdb).
"""

from __future__ import annotations

import numpy as np

from ..chunk.column import Column, StringDict
from ..types import dtypes as dt
from ..types.temporal import parse_date

DEC2 = dt.decimal(15, 2)

LINEITEM_NAMES = [
    "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber", "l_quantity",
    "l_extendedprice", "l_discount", "l_tax", "l_returnflag", "l_linestatus",
    "l_shipdate", "l_commitdate", "l_receiptdate", "l_shipinstruct",
    "l_shipmode",
]

SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
SHIPINSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]

_STARTDATE = parse_date("1992-01-01")
_CURRENTDATE = parse_date("1995-06-17")
_ENDDATE = parse_date("1998-12-01")


def gen_lineitem(sf: float = 1.0, seed: int = 0,
                 columns: list[str] | None = None) -> tuple[list[str], list[Column]]:
    """Generate lineitem columns; `columns` restricts output (saves RAM)."""
    n = int(6_000_000 * sf)
    want = set(columns or LINEITEM_NAMES)
    out_names, out_cols = [], []

    def emit(name, col):
        if name in want:
            out_names.append(name)
            out_cols.append(col)

    # each block draws from its own seeded child stream, so restricting
    # `columns` skips unwanted work (the SF=100 bench wants 4 of 15
    # columns — no 600M-row orderkey sort) without changing the values
    # of the columns that ARE produced
    def crng(tag: int):
        return np.random.default_rng([seed, tag])

    if "l_orderkey" in want:
        orderkey = np.sort(
            crng(1).integers(1, max(int(1_500_000 * sf), 1) * 4 + 1, n))
        emit("l_orderkey", Column.from_numpy(dt.bigint(False), orderkey))
    if {"l_partkey", "l_extendedprice"} & want:
        partkey = crng(2).integers(1, max(int(200_000 * sf), 1) + 1, n)
        emit("l_partkey", Column.from_numpy(dt.bigint(False), partkey))
    if "l_suppkey" in want:
        emit("l_suppkey", Column.from_numpy(
            dt.bigint(False),
            crng(3).integers(1, max(int(10_000 * sf), 1) + 1, n)))
    if "l_linenumber" in want:
        emit("l_linenumber", Column.from_numpy(
            dt.bigint(False), crng(4).integers(1, 8, n)))

    if {"l_quantity", "l_extendedprice"} & want:
        qty = crng(5).integers(1, 51, n)
        emit("l_quantity", Column.from_numpy(DEC2, qty * 100))
        if "l_extendedprice" in want:
            # extendedprice = qty * p_retailprice(partkey), in cents
            retail = 90000 + (partkey % 20001) + 100 * (partkey % 1000)
            emit("l_extendedprice", Column.from_numpy(DEC2, qty * retail))

    if "l_discount" in want:
        emit("l_discount", Column.from_numpy(DEC2, crng(6).integers(0, 11, n)))
    if "l_tax" in want:
        emit("l_tax", Column.from_numpy(DEC2, crng(7).integers(0, 9, n)))

    if {"l_returnflag", "l_linestatus", "l_shipdate", "l_commitdate",
            "l_receiptdate"} & want:
        rng = crng(8)
        ship = _STARTDATE + rng.integers(1, 122 + 2406, n)  # orderdate+1..121
        receipt = ship + rng.integers(1, 31, n)
        # returnflag: R or A (50/50) if receipt <= currentdate else N.
        # Codes computed numerically (dict order A=0, N=1, R=2): the
        # per-row python encode loop took minutes at SF>=10.
        returned = receipt <= _CURRENTDATE
        ra = rng.random(n) < 0.5
        fdict = StringDict(["A", "N", "R"])
        codes = np.where(returned, np.where(ra, 2, 0), 1).astype(np.int32)
        emit("l_returnflag", Column(dt.varchar(False), codes,
                                    np.ones(n, bool), fdict))
        sdict = StringDict(["F", "O"])   # F=0, O=1
        scodes = (ship > _CURRENTDATE).astype(np.int32)
        emit("l_linestatus", Column(dt.varchar(False), scodes,
                                    np.ones(n, bool), sdict))
        emit("l_shipdate", Column.from_numpy(dt.date(False), ship))
        emit("l_commitdate", Column.from_numpy(dt.date(False),
                                               ship + rng.integers(-30, 31, n)))
        emit("l_receiptdate", Column.from_numpy(dt.date(False), receipt))

    if "l_shipinstruct" in want:
        d = StringDict(SHIPINSTRUCT)
        emit("l_shipinstruct",
             Column(dt.varchar(False),
                    crng(9).integers(0, len(d), n).astype(np.int32),
                    np.ones(n, bool), d))
    if "l_shipmode" in want:
        d = StringDict(SHIPMODES)
        emit("l_shipmode",
             Column(dt.varchar(False),
                    crng(10).integers(0, len(d), n).astype(np.int32),
                    np.ones(n, bool), d))
    return out_names, out_cols


PART_NAMES = ["p_partkey", "p_brand", "p_size", "p_container"]

CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
              for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]


def gen_part(sf: float = 1.0, seed: int = 1) -> tuple[list[str], list[Column]]:
    n = int(200_000 * sf)
    rng = np.random.default_rng(seed)
    partkey = np.arange(1, n + 1)
    bdict = StringDict([f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)])
    brands = rng.integers(0, len(bdict), n).astype(np.int32)
    cdict = StringDict(CONTAINERS)
    containers = rng.integers(0, len(cdict), n).astype(np.int32)
    cols = [
        Column.from_numpy(dt.bigint(False), partkey),
        Column(dt.varchar(False), brands, np.ones(n, bool), bdict),
        Column.from_numpy(dt.bigint(False), rng.integers(1, 51, n)),
        Column(dt.varchar(False), containers, np.ones(n, bool), cdict),
    ]
    return PART_NAMES, cols


ORDERS_MINI_NAMES = ["o_orderkey", "o_custkey", "o_totalprice"]


def gen_orders_mini(n: int = 1024, seed: int = 7) -> tuple[list[str], list[Column]]:
    """Small orders table keyed to lineitem's l_orderkey domain — enough
    for multi-join fragment validation (dryrun/Q3 shape)."""
    rng = np.random.default_rng(seed)
    okey = np.arange(1, n + 1)
    cols = [
        Column.from_numpy(dt.bigint(False), okey),
        Column.from_numpy(dt.bigint(False), rng.integers(1, n // 4 + 2, n)),
        Column.from_numpy(DEC2, rng.integers(1000, 500000, n)),
    ]
    return ORDERS_MINI_NAMES, cols


__all__ = ["gen_lineitem", "gen_part", "gen_orders_mini", "LINEITEM_NAMES",
           "PART_NAMES", "DEC2"]
