"""TPC-H lineitem/part generator (numpy, vectorized).

Distribution-faithful for the columns Q1/Q6/Q19 touch (quantity, discount,
tax, shipdate ranges, returnflag/linestatus derivation); other columns are
uniform fillers.  SF=1 ≈ 6M lineitem rows, as in the spec.

Reference analog: the reference benchmarks against TPC-H via external
tooling (BASELINE.md); this in-repo generator plays the role of the
reference's benchdb data loaders (cmd/benchdb).
"""

from __future__ import annotations

import numpy as np

from ..chunk.column import Column, StringDict
from ..types import dtypes as dt
from ..types.temporal import parse_date

DEC2 = dt.decimal(15, 2)

LINEITEM_NAMES = [
    "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber", "l_quantity",
    "l_extendedprice", "l_discount", "l_tax", "l_returnflag", "l_linestatus",
    "l_shipdate", "l_commitdate", "l_receiptdate", "l_shipinstruct",
    "l_shipmode",
]

SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
SHIPINSTRUCT = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]

_STARTDATE = parse_date("1992-01-01")
_CURRENTDATE = parse_date("1995-06-17")
_ENDDATE = parse_date("1998-12-01")


def gen_lineitem(sf: float = 1.0, seed: int = 0,
                 columns: list[str] | None = None) -> tuple[list[str], list[Column]]:
    """Generate lineitem columns; `columns` restricts output (saves RAM)."""
    n = int(6_000_000 * sf)
    want = set(columns or LINEITEM_NAMES)
    out_names, out_cols = [], []

    def emit(name, col):
        if name in want:
            out_names.append(name)
            out_cols.append(col)

    # each block draws from its own seeded child stream, so restricting
    # `columns` skips unwanted work (the SF=100 bench wants 4 of 15
    # columns — no 600M-row orderkey sort) without changing the values
    # of the columns that ARE produced
    def crng(tag: int):
        return np.random.default_rng([seed, tag])

    if "l_orderkey" in want:
        orderkey = np.sort(
            crng(1).integers(1, max(int(1_500_000 * sf), 1) * 4 + 1, n))
        emit("l_orderkey", Column.from_numpy(dt.bigint(False), orderkey))
    if {"l_partkey", "l_extendedprice"} & want:
        partkey = crng(2).integers(1, max(int(200_000 * sf), 1) + 1, n)
        emit("l_partkey", Column.from_numpy(dt.bigint(False), partkey))
    if "l_suppkey" in want:
        emit("l_suppkey", Column.from_numpy(
            dt.bigint(False),
            crng(3).integers(1, max(int(10_000 * sf), 1) + 1, n)))
    if "l_linenumber" in want:
        emit("l_linenumber", Column.from_numpy(
            dt.bigint(False), crng(4).integers(1, 8, n)))

    if {"l_quantity", "l_extendedprice"} & want:
        qty = crng(5).integers(1, 51, n)
        emit("l_quantity", Column.from_numpy(DEC2, qty * 100))
        if "l_extendedprice" in want:
            # extendedprice = qty * p_retailprice(partkey), in cents
            retail = 90000 + (partkey % 20001) + 100 * (partkey % 1000)
            emit("l_extendedprice", Column.from_numpy(DEC2, qty * retail))

    if "l_discount" in want:
        emit("l_discount", Column.from_numpy(DEC2, crng(6).integers(0, 11, n)))
    if "l_tax" in want:
        emit("l_tax", Column.from_numpy(DEC2, crng(7).integers(0, 9, n)))

    if {"l_returnflag", "l_linestatus", "l_shipdate", "l_commitdate",
            "l_receiptdate"} & want:
        rng = crng(8)
        ship = _STARTDATE + rng.integers(1, 122 + 2406, n)  # orderdate+1..121
        receipt = ship + rng.integers(1, 31, n)
        # returnflag: R or A (50/50) if receipt <= currentdate else N.
        # Codes computed numerically (dict order A=0, N=1, R=2): the
        # per-row python encode loop took minutes at SF>=10.
        returned = receipt <= _CURRENTDATE
        ra = rng.random(n) < 0.5
        fdict = StringDict(["A", "N", "R"])
        codes = np.where(returned, np.where(ra, 2, 0), 1).astype(np.int32)
        emit("l_returnflag", Column(dt.varchar(False), codes,
                                    np.ones(n, bool), fdict))
        sdict = StringDict(["F", "O"])   # F=0, O=1
        scodes = (ship > _CURRENTDATE).astype(np.int32)
        emit("l_linestatus", Column(dt.varchar(False), scodes,
                                    np.ones(n, bool), sdict))
        emit("l_shipdate", Column.from_numpy(dt.date(False), ship))
        emit("l_commitdate", Column.from_numpy(dt.date(False),
                                               ship + rng.integers(-30, 31, n)))
        emit("l_receiptdate", Column.from_numpy(dt.date(False), receipt))

    if "l_shipinstruct" in want:
        d = StringDict(SHIPINSTRUCT)
        emit("l_shipinstruct",
             Column(dt.varchar(False),
                    crng(9).integers(0, len(d), n).astype(np.int32),
                    np.ones(n, bool), d))
    if "l_shipmode" in want:
        d = StringDict(SHIPMODES)
        emit("l_shipmode",
             Column(dt.varchar(False),
                    crng(10).integers(0, len(d), n).astype(np.int32),
                    np.ones(n, bool), d))
    return out_names, out_cols


PART_NAMES = ["p_partkey", "p_brand", "p_size", "p_container"]

CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
              for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]


def gen_part(sf: float = 1.0, seed: int = 1) -> tuple[list[str], list[Column]]:
    n = int(200_000 * sf)
    rng = np.random.default_rng(seed)
    partkey = np.arange(1, n + 1)
    bdict = StringDict([f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)])
    brands = rng.integers(0, len(bdict), n).astype(np.int32)
    cdict = StringDict(CONTAINERS)
    containers = rng.integers(0, len(cdict), n).astype(np.int32)
    cols = [
        Column.from_numpy(dt.bigint(False), partkey),
        Column(dt.varchar(False), brands, np.ones(n, bool), bdict),
        Column.from_numpy(dt.bigint(False), rng.integers(1, 51, n)),
        Column(dt.varchar(False), containers, np.ones(n, bool), cdict),
    ]
    return PART_NAMES, cols


ORDERS_MINI_NAMES = ["o_orderkey", "o_custkey", "o_totalprice"]


def gen_orders_mini(n: int = 1024, seed: int = 7) -> tuple[list[str], list[Column]]:
    """Small orders table keyed to lineitem's l_orderkey domain — enough
    for multi-join fragment validation (dryrun/Q3 shape)."""
    rng = np.random.default_rng(seed)
    okey = np.arange(1, n + 1)
    cols = [
        Column.from_numpy(dt.bigint(False), okey),
        Column.from_numpy(dt.bigint(False), rng.integers(1, n // 4 + 2, n)),
        Column.from_numpy(DEC2, rng.integers(1000, 500000, n)),
    ]
    return ORDERS_MINI_NAMES, cols


# ------------------------------------------------------------------ #
# plan corpus: the TPC-H-shaped statements every static-analysis gate
# run and tests/test_analysis.py push through analysis.verify_plan.
# Shapes covered: dense scalar/keyed agg, SORT (high-NDV) agg, rollup,
# TopN/Limit, row-returning projections, broadcast lookup join (rows +
# agg + multi-level), semi/anti join, host sort/setop, device window.
# ------------------------------------------------------------------ #

TPCH_PLAN_QUERIES = [
    # Q6: dense scalar aggregation over scan+filter
    """select sum(l_extendedprice * l_discount) as revenue from lineitem
       where l_shipdate >= date '1994-01-01'
         and l_shipdate < date '1995-01-01'
         and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    # Q1: dense keyed aggregation (dict-coded group keys)
    """select l_returnflag, l_linestatus, sum(l_quantity),
              sum(l_extendedprice), avg(l_discount), count(*)
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus
       order by l_returnflag, l_linestatus""",
    # high-NDV group-by: SORT-strategy aggregation (single key: stats NDV
    # stays below the SEGMENT threshold at corpus scale)
    """select l_orderkey, sum(l_extendedprice) from lineitem
       group by l_orderkey""",
    # very-high-NDV group-bys: the per-key stats NDV PRODUCT crosses
    # SEGMENT_MIN_NDV, so these plan as the radix-partitioned SEGMENT
    # strategy (tpch_plan_session ANALYZEs lineitem so the estimates
    # exist at plan time) — the gate keeps them contract-clean and
    # rc-pricing-finite like every other corpus shape
    """select l_orderkey, l_partkey, count(*), sum(l_quantity)
       from lineitem group by l_orderkey, l_partkey""",
    """select l_orderkey, l_suppkey, max(l_extendedprice) from lineitem
       where l_quantity < 45 group by l_orderkey, l_suppkey""",
    # rollup: Expand + grouping sets
    """select l_returnflag, l_linestatus, sum(l_quantity) from lineitem
       group by l_returnflag, l_linestatus with rollup""",
    # device TopN (multi-key) and plain Limit
    """select l_orderkey, l_extendedprice from lineitem
       order by l_extendedprice desc, l_orderkey limit 10""",
    "select l_partkey from lineitem limit 5",
    # row-returning scan chain with projection arithmetic
    """select l_orderkey, l_extendedprice * (1 - l_discount)
       from lineitem where l_quantity < 5""",
    # broadcast lookup join, aggregated (Q19 shape without OR-chains)
    """select p_brand, sum(l_extendedprice) from lineitem, part
       where l_partkey = p_partkey and l_quantity < 10
       group by p_brand""",
    # broadcast lookup join, row-returning
    """select l_orderkey, p_brand from lineitem, part
       where l_partkey = p_partkey and p_size > 40 limit 20""",
    # semi join (IN subquery)
    """select l_orderkey from lineitem
       where l_partkey in (select p_partkey from part where p_size > 45)
       limit 10""",
    # anti join (NOT IN subquery)
    """select count(*) from lineitem
       where l_suppkey not in (select o_custkey from orders)""",
    # multi-table chain: lineitem x orders x part
    """select o_totalprice, p_brand, l_quantity from lineitem, orders, part
       where l_orderkey = o_orderkey and l_partkey = p_partkey
       limit 10""",
    # host sort over join output
    """select o_orderkey, sum(l_extendedprice) as rev from lineitem, orders
       where l_orderkey = o_orderkey
       group by o_orderkey order by rev desc limit 5""",
    # set operation
    """select l_partkey from lineitem where l_quantity < 2
       union select p_partkey from part where p_size = 1""",
    # window function over the sharded table
    """select l_orderkey,
              row_number() over (partition by l_returnflag
                                 order by l_extendedprice desc) as rn
       from lineitem limit 10""",
    # scalar-subquery-free HAVING residue (host filter over agg)
    """select l_returnflag, count(*) as c from lineitem
       group by l_returnflag having count(*) > 1""",
]


def tpch_plan_session(sf: float = 0.001, n_orders: int = 512):
    """In-memory Domain+Session with lineitem/part/orders registered from
    the generators above — the fixture both the analysis gate and the
    verifier tests plan TPCH_PLAN_QUERIES against."""
    from ..session import Domain, Session
    from ..session.catalog import TableInfo
    dom = Domain()
    for name, (names, cols) in (
            ("lineitem", gen_lineitem(sf=sf, seed=42)),
            ("part", gen_part(sf=max(sf * 10, 0.005), seed=7)),
            ("orders", gen_orders_mini(n_orders))):
        t = TableInfo(name, list(names), [c.dtype for c in cols])
        t.register_columns(list(cols))
        dom.catalog.create_table("test", t)
    sess = Session(dom)
    # stats NDV feeds SORT-vs-SEGMENT strategy selection and the
    # group-table capacity seed (executor/plan._ndv_capacity): the
    # corpus' high-NDV queries must plan as SEGMENT
    sess.execute("analyze table lineitem")
    return sess


# planned with the broadcast threshold forced to 0 so the repartition
# (all_to_all shuffle) join path is exercised by the gate too
TPCH_SHUFFLE_QUERIES = [
    """select count(*), sum(l_quantity + o_totalprice) from lineitem
       join orders on l_orderkey = o_orderkey""",
    """select o_custkey, sum(l_quantity) from lineitem join orders
       on l_orderkey = o_orderkey group by o_custkey""",
]


# the MULTICHIP dryrun's plan shapes (__graft_entry__.dryrun_multichip):
# every distributed step the dry run executes on the 8-vdev mesh, as
# plannable SQL — the shardflow gate pass must analyze each clean with
# finite per-link transfer bytes (the pod-scale exchange shapes the
# multi-host runtime PR will inherit)
MULTICHIP_PLAN_QUERIES = [
    # Q1 psum step: dense keyed agg merged in-program
    """select l_returnflag, l_linestatus, sum(l_quantity), count(*)
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus""",
    # TopN shard-merge step
    """select l_extendedprice from lineitem
       order by l_extendedprice desc limit 5""",
    # broadcast-join step (LookupJoin + psum agg)
    """select count(*), sum(l_extendedprice) from lineitem, part
       where p_partkey = l_partkey and p_size < 25""",
    # rollup Expand fragment
    """select l_returnflag, l_linestatus, count(*) from lineitem
       group by l_returnflag, l_linestatus with rollup""",
    # window repartition (all_to_all on PARTITION BY)
    """select l_linestatus, row_number() over
       (partition by l_linestatus order by l_extendedprice desc)
       from lineitem""",
    # window-over-join fragment
    """select l_linestatus, row_number() over
       (partition by l_linestatus order by l_extendedprice desc)
       from lineitem, part
       where p_partkey = l_partkey and p_size < 25""",
]


def built_multichip_plans(session):
    """Plan the MULTICHIP dryrun shapes: the broadcast forms above plus
    the same join re-planned as a repartition shuffle (threshold 0) —
    the all_to_all exchange step of the dry run."""
    yield from built_tpch_plans(session, MULTICHIP_PLAN_QUERIES)
    from ..executor import plan as planmod
    saved = planmod.BROADCAST_BUILD_MAX_ROWS
    planmod.BROADCAST_BUILD_MAX_ROWS = 0
    try:
        yield from built_tpch_plans(
            session, ["""select count(*), sum(l_extendedprice)
                         from lineitem, part
                         where p_partkey = l_partkey and p_size < 25"""])
    finally:
        planmod.BROADCAST_BUILD_MAX_ROWS = saved


def built_tpch_plans(session, queries=None):
    """Plan (without executing) each corpus statement; yields
    (sql, physical plan) pairs for analysis.verify_plan.  With the
    default corpus, also plans TPCH_SHUFFLE_QUERIES under a zeroed
    broadcast threshold to cover the exchange (shuffle-join) path."""
    from ..sql.parser import parse_one

    def plan(sql):
        _built, phys = session._plan_select(parse_one(sql))
        return phys

    for sql in (queries if queries is not None else TPCH_PLAN_QUERIES):
        yield sql, plan(sql)
    if queries is None:
        from ..executor import plan as planmod
        saved = planmod.BROADCAST_BUILD_MAX_ROWS
        planmod.BROADCAST_BUILD_MAX_ROWS = 0
        try:
            for sql in TPCH_SHUFFLE_QUERIES:
                yield sql, plan(sql)
        finally:
            planmod.BROADCAST_BUILD_MAX_ROWS = saved


__all__ = ["gen_lineitem", "gen_part", "gen_orders_mini", "LINEITEM_NAMES",
           "PART_NAMES", "DEC2", "TPCH_PLAN_QUERIES",
           "TPCH_SHUFFLE_QUERIES", "MULTICHIP_PLAN_QUERIES",
           "tpch_plan_session", "built_tpch_plans",
           "built_multichip_plans"]
