"""Chaos stress harness: N concurrent sessions over a mixed corpus.

The proving ground for the copmeter closed loop (ISSUE 10): an
open-loop arrival process (arrivals never wait for completions — the
"millions of users" shape) drives a mixed device corpus — DENSE/scalar
aggregates, SORT group-by, SEGMENT high-NDV group-by, rows-kind
filters, and a shuffle join — through the full admission pipeline with
the PR 8 fault plane armed, across several resource groups.

One library, two consumers:

- the tier-1 smoke (tests/test_stress.py): a 64-session rung asserting
  completion 1.0 and ZERO wrong results with chaos armed;
- the bench ``stress`` rung (bench.py BENCH_MODE=sched): the ~1k-session
  run landing p50/p99 sched wait, fusion rate, RU fairness, completion
  rate, and calibrated-pricing error as first-class BENCH JSON metrics.

Everything is deterministic given the seed (arrival draws, query picks,
the FaultPlan dice) except true thread interleaving.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# mixed corpus over the stress schema (see build_stress_domain):
# (tag, sql) — tags label the per-shape completion breakdown
STRESS_QUERIES = [
    ("dense", "select sum(p * d) from stress_li "
              "where sd >= 200 and sd < 1500"),
    ("dense", "select count(*), max(p) from stress_li where d >= 5"),
    ("dense", "select min(p), sum(q) from stress_li where q > 10"),
    ("sort", "select d, count(*), sum(p) from stress_li "
             "where q < 40 group by d"),
    ("segment", "select k, count(*) from stress_li group by k"),
    ("rows", "select q, p from stress_li where p > 9900"),
    ("shuffle", "select count(*), sum(p + sp) from stress_li "
                "join stress_sup on d = sd2"),
]

DEFAULT_CHAOS = "seed=11,launch:transient:0.05"


def build_stress_domain(n_rows: int = 60_000, seed: int = 7):
    """Domain + seeded mixed-corpus tables, device launch path pinned
    open (the bench/test platform-pin idiom), result cache off so every
    statement actually dispatches."""
    from ..session import Domain, Session
    rng = np.random.default_rng(seed)
    dom = Domain()
    s = Session(dom)
    s.execute("create table stress_li (q bigint, d bigint, p bigint, "
              "sd bigint, k bigint)")
    q = rng.integers(1, 50, n_rows)
    d = rng.integers(0, 10, n_rows)
    p = rng.integers(100, 10_000, n_rows)
    sd = rng.integers(0, 2000, n_rows)
    # high-NDV group key: NDV comfortably above SEGMENT_MIN_NDV (32768)
    # so ANALYZE-driven selection takes the radix SEGMENT path
    k = rng.integers(0, 50_000, n_rows)
    step = 10_000
    for lo in range(0, n_rows, step):
        s.execute("insert into stress_li values " + ",".join(
            f"({a},{b},{c},{e},{f})" for a, b, c, e, f in
            zip(q[lo:lo + step], d[lo:lo + step], p[lo:lo + step],
                sd[lo:lo + step], k[lo:lo + step])))
    s.execute("create table stress_sup (sd2 bigint, sp bigint)")
    s.execute("insert into stress_sup values " + ",".join(
        f"({i},{int(v)})" for i, v in
        enumerate(rng.integers(0, 100, 10))))
    s.execute("analyze table stress_li")
    s.execute("set global tidb_tpu_result_cache_entries = 0")
    dom.client._platform = lambda: "tpu"
    return dom, s


def _expected_results(dom, queries) -> dict:
    """Oracle answers computed BEFORE chaos arms — the zero-wrong-
    results invariant compares every chaos-run result against these."""
    from ..session import Session
    return {sql: sorted(map(repr, Session(dom).must_query(sql)))
            for _tag, sql in queries}


def run_stress_harness(dom, n_sessions: int = 64,
                       rate_per_s: float = 400.0, n_groups: int = 4,
                       chaos: str = DEFAULT_CHAOS, seed: int = 7,
                       join_timeout_s: float = 600.0,
                       queries=None) -> dict:
    """Run the open-loop mixed-corpus stress rung and return its
    metrics dict (the BENCH JSON `stress` payload).

    Every session is one thread: pick a resource group (round-robin
    over ``n_groups`` equal groups — the RU-fairness denominator), wait
    for its pre-drawn exponential arrival time, run one statement from
    the mixed corpus, compare against the pre-chaos oracle."""
    queries = STRESS_QUERIES if queries is None else queries
    sched = dom.client._scheduler()
    assert sched is not None, "scheduler did not engage"
    # zeroed broadcast threshold for the duration of the run: the join
    # statement plans as a CopShuffleJoin (exchange path).  Scoped
    # save/restore of the MODULE global (the built_tpch_plans idiom) —
    # a sysvar write would leak the zero process-wide to later tests.
    from ..executor import plan as _planmod
    saved_bm = _planmod.BROADCAST_BUILD_MAX_ROWS
    _planmod.BROADCAST_BUILD_MAX_ROWS = 0
    try:
        return _run_stress_inner(dom, sched, queries, n_sessions,
                                 rate_per_s, n_groups, chaos, seed,
                                 join_timeout_s)
    finally:
        _planmod.BROADCAST_BUILD_MAX_ROWS = saved_bm


def _run_stress_inner(dom, sched, queries, n_sessions, rate_per_s,
                      n_groups, chaos, seed, join_timeout_s) -> dict:
    from .. import faults
    from ..faults import FaultPlan
    from ..session import Session
    # groups: equal weight, unlimited RUs — fairness must come from the
    # weighted-fair drain, so max/min completion ratio ~ 1.0 is earned
    s0 = Session(dom)
    gnames = []
    for gi in range(n_groups):
        name = f"stress_g{gi}"
        s0.execute(f"create resource group if not exists {name} "
                   "RU_PER_SEC = 0")
        gnames.append(name)
    expected = _expected_results(dom, queries)

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_per_s, n_sessions))
    picks = rng.integers(0, len(queries), n_sessions)

    base = sched.stats()
    calib0 = base.get("calibration", {})
    mu = threading.Lock()
    counts = {"ok": 0, "wrong": 0, "failed": 0, "busy_retries": 0}
    per_group = {g: {"submitted": 0, "ok": 0} for g in gnames}
    per_tag: dict = {}
    errors: dict = {}

    def _is_backpressure(e: BaseException) -> bool:
        # ServerBusyError(9003) overflow/shed: the error TELLS the
        # client to back off and retry — a real MySQL client does
        return getattr(e, "errno", 0) == 9003

    def run(i: int) -> None:
        tag, sql = queries[picks[i]]
        group = gnames[i % n_groups]
        delay = t0 + arrivals[i] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        with mu:
            per_group[group]["submitted"] += 1
            per_tag.setdefault(tag, {"submitted": 0, "ok": 0})
            per_tag[tag]["submitted"] += 1
        sess = Session(dom)
        sess.execute(f"set resource group {group}")
        got = None
        for attempt in range(200):
            try:
                got = sorted(map(repr, sess.must_query(sql)))
                break
            except Exception as e:   # noqa: BLE001 counted, not raised
                if _is_backpressure(e) and attempt < 199:
                    # overload-graceful: bounded-queue backpressure is
                    # an invitation to retry, not a statement failure
                    with mu:
                        counts["busy_retries"] += 1
                    time.sleep(min(0.02 * (attempt + 1), 0.25))
                    continue
                with mu:
                    counts["failed"] += 1
                    key = type(e).__name__
                    errors[key] = errors.get(key, 0) + 1
                return
        with mu:
            if got == expected[sql]:
                counts["ok"] += 1
                per_group[group]["ok"] += 1
                per_tag[tag]["ok"] += 1
            else:
                counts["wrong"] += 1

    threads = [threading.Thread(target=run, args=(i,),
                                name=f"stress-{i}")
               for i in range(n_sessions)]
    if chaos:
        faults.install(FaultPlan.parse(chaos))
    t0 = time.monotonic()
    st = base
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=join_timeout_s)
        st = sched.stats()      # BEFORE faults.clear(): "injected"
    finally:                    # reads the armed plan's counters
        if chaos:
            faults.clear()
    elapsed = time.monotonic() - t0
    tasks = st["tasks_done"] - base["tasks_done"]
    launches = st["launches"] - base["launches"]
    rates = [g["ok"] / g["submitted"] for g in per_group.values()
             if g["submitted"]]
    calib = st.get("calibration", {}) or {}
    out = {
        "sessions": n_sessions,
        "arrival_rate_per_s": rate_per_s,
        "elapsed_s": round(elapsed, 3),
        "chaos": chaos or None,
        "injected": (st.get("faults") or {}).get("total_injected", 0),
        # correctness + completion (the invariants)
        "completion_rate": round(counts["ok"] / max(n_sessions, 1), 4),
        "wrong_results": counts["wrong"],
        "failed": counts["failed"],
        "busy_retries": counts["busy_retries"],
        "failure_kinds": dict(sorted(errors.items())),
        # latency + batching
        "sched_wait_p50_ms": st["wait_p50_ms"],
        "sched_wait_p99_ms": st["wait_p99_ms"],
        "tasks": tasks,
        "launches": launches,
        "fusion_rate": round(
            (st["fused_tasks"] - base["fused_tasks"]) / max(tasks, 1), 4),
        "coalesce_rate": round(
            (st["coalesced_tasks"] - base["coalesced_tasks"])
            / max(tasks, 1), 4),
        "launch_reduction": round(1.0 - launches / max(tasks, 1), 4),
        # RU fairness: max/min per-group completion ratio (1.0 = fair)
        "ru_fairness": round(max(rates) / max(min(rates), 1e-9), 3)
        if rates else None,
        "per_group": {g: dict(v) for g, v in sorted(per_group.items())},
        "per_shape": {t: dict(v) for t, v in sorted(per_tag.items())},
        # copmeter: recovery + shedding + calibrated-pricing error
        "retried_launches": st["retried_launches"]
        - base["retried_launches"],
        "oom_faults": st.get("oom_faults", 0)
        - base.get("oom_faults", 0),
        "shed_rejects": st.get("shed_rejects", 0)
        - base.get("shed_rejects", 0),
        "rc_exhausted": st.get("rc_exhausted", 0)
        - base.get("rc_exhausted", 0),
        # copnum: ANALYZE-stamped watermark drift observed at sched admit
        # (declared stats interval failed to contain observed min/max)
        "value_drifts": st.get("value_drifts", 0)
        - base.get("value_drifts", 0),
        "calibration_entries": calib.get("entries", 0),
        "calibration_observed": calib.get("observed", 0)
        - (calib0.get("observed", 0) or 0),
        "calibrated_err_pct": calib.get("mean_err_pct"),
    }
    return out


__all__ = ["STRESS_QUERIES", "DEFAULT_CHAOS", "build_stress_domain",
           "run_stress_harness"]
