"""Built-in DXF task types bound to a Domain.

Reference analog: the disttask users — ADD INDEX backfill and IMPORT
INTO run as distributed tasks (pkg/disttask/importinto,
pkg/ddl/backfilling_dist_*).  Here: ANALYZE fan-out (one subtask per
table) and CSV import (one subtask per file chunk), each planned into
independent subtasks the worker pool executes.
"""

from __future__ import annotations

from . import TaskManager, TaskTypeRegistry


def build_registry(domain) -> TaskTypeRegistry:
    reg = TaskTypeRegistry()

    # -- analyze: one subtask per table ------------------------------- #

    def plan_analyze(meta: dict) -> list[dict]:
        db = meta.get("db", "test")
        names = meta.get("tables") or sorted(
            domain.catalog.databases.get(db, {}))
        return [{"db": db, "table": n} for n in names]

    def run_analyze(meta: dict):
        tbl = domain.catalog.get_table(meta["db"], meta["table"])
        domain.stats.analyze_table(tbl)
        return tbl.num_rows

    reg.register("analyze", plan_analyze, run_analyze)

    # -- import-csv: one subtask per chunk of lines ------------------- #

    def plan_import(meta: dict) -> list[dict]:
        """One planning pass records each chunk's BYTE offset, so
        subtasks seek straight to their slice instead of rescanning the
        file from line 0 (O(file) total, not O(chunks x file))."""
        chunk = int(meta.get("chunk_rows", 4096))
        offsets = [0]
        rows_in_chunk = 0
        with open(meta["path"], "rb") as f:
            for line in f:
                rows_in_chunk += 1
                if rows_in_chunk == chunk:
                    offsets.append(f.tell())
                    rows_in_chunk = 0
        if rows_in_chunk == 0 and len(offsets) > 1:
            offsets.pop()            # file ended exactly on a boundary
        return [{"db": meta.get("db", "test"), "table": meta["table"],
                 "path": meta["path"], "offset": off,
                 "rows": chunk, "sep": meta.get("sep", ",")}
                for off in offsets]

    def run_import(meta: dict):
        tbl = domain.catalog.get_table(meta["db"], meta["table"])
        rows = []
        with open(meta["path"]) as f:
            f.seek(meta["offset"])
            for _ in range(meta["rows"]):
                line = f.readline()
                if not line:
                    break
                if not line.strip():
                    continue
                vals = [None if v == "\\N" else v
                        for v in line.rstrip("\n").split(meta["sep"])]
                rows.append(tuple(vals))
        return tbl.insert_rows(rows)

    reg.register("import-csv", plan_import, run_import)
    return reg


def manager_for(domain) -> TaskManager:
    return TaskManager(kv=domain.kv, registry=build_registry(domain))


__all__ = ["build_registry", "manager_for"]
