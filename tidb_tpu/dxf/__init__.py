"""DXF-lite: the distributed task framework.

Reference analog: pkg/disttask/framework (scheduler + taskexecutor):
a TASK of a registered type is planned into SUBTASKS, which a worker
pool executes with per-subtask state persisted to the KV meta keyspace —
so a restarted owner resumes unfinished subtasks instead of starting
over.  The reference distributes subtasks across nodes over gRPC; here
the pool is in-process threads (the single-host analog), but the state
machine, persistence, cancel, and resume semantics match:

    pending -> running -> succeed | failed | cancelled
    subtask: pending -> running -> succeed | failed

Task types register a planner (task meta -> list of subtask metas) and
an executor (subtask meta -> result).  ADD INDEX backfill and bulk
import are the reference's flagship DXF users; here the framework is
exercised by the analyze/import paths and directly by tests.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Optional

META_TASK = b"m_dxf_task_"


@dataclass
class Subtask:
    idx: int
    meta: dict
    state: str = "pending"      # pending | running | succeed | failed
    result: Any = None
    error: str = ""


@dataclass
class DistTask:
    task_id: int
    task_type: str
    meta: dict
    state: str = "pending"  # pending|running|succeed|failed|cancelled
    subtasks: list = field(default_factory=list)
    error: str = ""
    start_time: float = 0.0
    finish_time: float = 0.0

    def to_json(self) -> bytes:
        # built by hand, NOT asdict(): results are never persisted and
        # must not be deep-copied either (they may be large or hold
        # non-copyable objects)
        d = {"task_id": self.task_id, "task_type": self.task_type,
             "meta": self.meta, "state": self.state, "error": self.error,
             "start_time": self.start_time,
             "finish_time": self.finish_time,
             "subtasks": [{"idx": s.idx, "meta": s.meta,
                           "state": s.state, "result": None,
                           "error": s.error} for s in self.subtasks]}
        return json.dumps(d).encode()

    @classmethod
    def from_json(cls, b: bytes) -> "DistTask":
        d = json.loads(b.decode())
        subs = [Subtask(**s) for s in d.pop("subtasks")]
        t = cls(**d)
        t.subtasks = subs
        return t


class TaskTypeRegistry:
    def __init__(self):
        self._types: dict[str, tuple[Callable, Callable]] = {}

    def register(self, task_type: str, planner: Callable,
                 executor: Callable) -> None:
        """planner(meta) -> [subtask metas]; executor(meta) -> result."""
        self._types[task_type] = (planner, executor)

    def get(self, task_type: str):
        if task_type not in self._types:
            raise KeyError(f"unregistered task type {task_type!r}")
        return self._types[task_type]


REGISTRY = TaskTypeRegistry()


class TaskManager:
    """Owner-side scheduler (disttask scheduler + taskexecutor pool)."""

    def __init__(self, kv=None, workers: int = 4,
                 registry: TaskTypeRegistry = REGISTRY):
        self.kv = kv
        self.workers = workers
        self.registry = registry
        self._next_id = 0
        self._tasks: dict[int, DistTask] = {}
        self._cancel: set[int] = set()
        self._mu = threading.Lock()
        if kv is not None:
            self._recover()

    # -- persistence -------------------------------------------------- #

    def _persist(self, t: DistTask) -> None:
        if self.kv is None:
            return
        from ..store.codec import encode_int_key
        txn = self.kv.begin()
        txn.put(META_TASK + encode_int_key(t.task_id), t.to_json())
        txn.commit()

    def _recover(self) -> None:
        from ..store.codec import encode_int_key
        ts = self.kv.alloc_ts()
        end = META_TASK[:-1] + bytes([META_TASK[-1] + 1])
        for _, v in self.kv.scan(META_TASK, end, ts):
            t = DistTask.from_json(v)
            self._tasks[t.task_id] = t
            self._next_id = max(self._next_id, t.task_id)
            # a task that was mid-flight when the owner died resumes
            if t.state == "running":
                for s in t.subtasks:
                    if s.state == "running":
                        s.state = "pending"     # re-run unfinished work

    # -- API ----------------------------------------------------------- #

    def submit(self, task_type: str, meta: dict) -> int:
        planner, _ = self.registry.get(task_type)
        # plan BEFORE publishing: a planner failure must not leave a
        # ghost pending task in the registry
        subtasks = [Subtask(i, m) for i, m in enumerate(planner(meta))]
        with self._mu:
            self._next_id += 1
            t = DistTask(self._next_id, task_type, meta)
            t.subtasks = subtasks
            self._tasks[t.task_id] = t
        self._persist(t)
        return t.task_id

    def run(self, task_id: int) -> DistTask:
        """Execute pending subtasks on the worker pool until done (the
        scheduler loop, synchronous form)."""
        t = self._tasks[task_id]
        _, executor = self.registry.get(t.task_type)
        t.state = "running"
        t.start_time = t.start_time or time.time()
        self._persist(t)

        def run_one(s: Subtask):
            if task_id in self._cancel:
                return
            s.state = "running"
            try:
                s.result = executor(s.meta)
                s.state = "succeed"
            except Exception as e:       # noqa: BLE001 - task isolation
                s.state = "failed"
                s.error = str(e)
            # persist EVERY subtask completion: crash-resume must skip
            # finished subtasks (their side effects committed), not
            # re-execute them (_mu serializes concurrent pool persists).
            # O(K) state rows per persist — results are excluded, so each
            # write is small.  A persist failure must not escape subtask
            # isolation (the task would be stuck 'running' forever).
            try:
                with self._mu:
                    self._persist(t)
            except Exception as e:       # noqa: BLE001
                s.error = (s.error + "; " if s.error else "") + \
                    f"persist: {e}"

        pending = [s for s in t.subtasks if s.state != "succeed"]
        with ThreadPoolExecutor(max_workers=self.workers) as pool:
            list(pool.map(run_one, pending))
        if task_id in self._cancel:
            t.state = "cancelled"
            self._cancel.discard(task_id)
        elif any(s.state == "failed" for s in t.subtasks):
            t.state = "failed"
            t.error = "; ".join(s.error for s in t.subtasks
                                if s.state == "failed")[:512]
        else:
            t.state = "succeed"
            t.error = ""           # a re-run that succeeds clears failures
        t.finish_time = time.time()
        self._persist(t)
        return t

    def cancel(self, task_id: int) -> None:
        with self._mu:
            self._cancel.add(task_id)

    def get(self, task_id: int) -> Optional[DistTask]:
        return self._tasks.get(task_id)

    def tasks(self) -> list[DistTask]:
        return sorted(self._tasks.values(), key=lambda t: t.task_id)


__all__ = ["TaskManager", "TaskTypeRegistry", "DistTask", "Subtask",
           "REGISTRY"]
