"""DXF node pool: subtask balancing across store processes.

Reference analog: the disttask framework's scheduler + balancer
(pkg/disttask/framework/doc.go:15-80, scheduler/balancer.go) — subtasks
of one task spread across taskexecutor NODES; when a node dies its
unfinished subtasks rebalance onto survivors and the task still
completes.  Here nodes are the store RPC processes (store/server.py),
and the pool runs one puller thread per node over a shared queue — a
work-stealing balancer: a fast node naturally takes more subtasks, a
dead one's in-flight subtask is requeued for the survivors.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Sequence


class DXFNodeError(RuntimeError):
    """Every node died with subtasks outstanding."""


class DXFNodePool:
    """Balance subtask execution over remote executor nodes."""

    def __init__(self, stores: Sequence[Any]):
        # `stores` are RemoteStore-shaped: .request(msg) raising on a
        # dead peer, .store_id
        self.stores = list(stores)
        self.dead: set[int] = set()
        # observability (the reference's subtask table columns)
        self.per_node: dict[int, int] = {s.store_id: 0 for s in self.stores}
        self.rebalanced = 0
        self._mu = threading.Lock()

    def live_nodes(self):
        return [s for s in self.stores if s.store_id not in self.dead]

    def run_subtasks(self, subtasks: Sequence[Any],
                     make_msg: Callable[[Any], Any],
                     handle_resp: Callable[[Any, Any], None]) -> None:
        """Execute every subtask exactly once on some live node.

        make_msg(subtask) -> RPC message; handle_resp(subtask, resp) runs
        on the puller thread that got the response (callers serialize
        their own state).  A node failure marks it dead, requeues the
        in-flight subtask, and lets the surviving pullers drain the
        queue; DXFNodeError only if ALL nodes die first."""
        q: queue.Queue = queue.Queue()
        for st in subtasks:
            q.put(st)
        n_left = [len(subtasks)]
        errors: list = []
        done = threading.Event()

        def puller(store):
            while not done.is_set():
                try:
                    # block briefly instead of exiting on empty: a dying
                    # node may requeue its in-flight subtask at any time
                    st = q.get(timeout=0.05)
                except queue.Empty:
                    if n_left[0] == 0:
                        return
                    continue
                try:
                    resp = store.request(make_msg(st))
                except Exception:
                    # node loss: requeue for survivors, retire this puller
                    with self._mu:
                        self.dead.add(store.store_id)
                        self.rebalanced += 1
                    q.put(st)
                    return
                try:
                    handle_resp(st, resp)
                except Exception as e:      # executor-side failure
                    errors.append(e)
                    done.set()
                    return
                with self._mu:
                    self.per_node[store.store_id] += 1
                    n_left[0] -= 1
                    if n_left[0] == 0:
                        done.set()

        threads = [threading.Thread(target=puller, args=(s,), daemon=True)
                   for s in self.live_nodes()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        if n_left[0] > 0:
            raise DXFNodeError(
                f"{n_left[0]} subtasks unassigned: all DXF nodes died")


__all__ = ["DXFNodePool", "DXFNodeError"]
