from .sortkeys import float_sortable, sortable_int64, INT64_MIN, INT64_MAX

__all__ = ["float_sortable", "sortable_int64", "INT64_MIN", "INT64_MAX"]
