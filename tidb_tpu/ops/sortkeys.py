"""Order-preserving int64 sort-key encodings for device sort/top-k.

Reference analog: pkg/util/codec's memcomparable encodings (ints with
sign-bit flip, etc.) — the same idea applied on-device: every orderable SQL
value maps to an int64 whose natural order equals SQL order, so TopN/sort
lower to `lax.top_k`/`lax.sort` on one int64 array.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

INT64_MIN = -(2 ** 63)
INT64_MAX = 2 ** 63 - 1


def float_sortable(v):
    """Bijective IEEE754 double -> int64 with the same total order
    (standard radix-sort transform; -NaN sorts lowest, +NaN highest).

    Positive floats keep their bit pattern (already ordered); negative
    floats need order reversal: s = INT64_MIN - 1 - b, computed as
    -(b+1) + INT64_MIN to stay inside int64 range."""
    b = lax.bitcast_convert_type(v.astype(jnp.float64), jnp.int64)
    return jnp.where(b < 0, -(b + 1) + INT64_MIN, b)


def sortable_int64(xp, val, kind_is_float: bool, kind_is_unsigned: bool = False):
    """Map a device value array to order-preserving int64."""
    if kind_is_float:
        return float_sortable(val)
    if kind_is_unsigned:
        # uint64 order as int64: subtract 2^63 (sign-bit flip)
        return (val.astype(jnp.int64) + INT64_MIN)
    return val.astype(jnp.int64)


__all__ = ["float_sortable", "sortable_int64", "INT64_MIN", "INT64_MAX"]
