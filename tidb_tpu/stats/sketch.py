"""CM sketch, TopN, and KMV (FM-analog) NDV sketch.

Reference analog: pkg/statistics/cmsketch.go:56 (CMSketch), :501 (TopN),
fmsketch.go:65 (FMSketch).  The device kernel (stats/build.py) emits the
raw counter tables / minimum-hash sets; these classes wrap estimation and
cross-shard merge (merge = elementwise add / merged k-minimum — both are
`psum`-shaped reductions, so shard-parallel ANALYZE composes over the mesh
exactly like partial aggregation, SURVEY.md §2.10 P2).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .build import CM_DEPTH, CM_WIDTH, FM_MAPS


def _host_hash64(x: np.ndarray, seed: int) -> np.ndarray:
    h = (x.astype(np.uint64) + np.uint64(seed)) * np.uint64(0x9E3779B97F4A7C15)
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return h ^ (h >> np.uint64(31))


@dataclass
class TopN:
    """Most-frequent values (encoded domain) -> exact counts."""
    values: dict[int, int] = field(default_factory=dict)

    def count_of(self, v: int):
        return self.values.get(int(v))

    def merge(self, other: "TopN") -> "TopN":
        out = dict(self.values)
        for v, c in other.values.items():
            out[v] = out.get(v, 0) + c
        top = sorted(out.items(), key=lambda kv: -kv[1])[:max(len(self.values),
                                                              len(other.values))]
        return TopN(dict(top))


@dataclass
class CMSketch:
    table: np.ndarray         # int64[CM_DEPTH, CM_WIDTH]

    def query(self, v: int) -> int:
        x = np.array([v], dtype=np.int64)
        est = None
        for d in range(CM_DEPTH):
            idx = int(_host_hash64(x, 0xABCD + d * 7919)[0] % CM_WIDTH)
            c = int(self.table[d, idx])
            est = c if est is None else min(est, c)
        return est or 0

    def merge(self, other: "CMSketch") -> "CMSketch":
        return CMSketch(self.table + other.table)


@dataclass
class FMSketch:
    """K-minimum-values NDV sketch over 64-bit hashes (mergeable)."""
    kmv: np.ndarray           # uint64[<=FM_MAPS], sorted ascending

    def ndv(self) -> int:
        k = len(self.kmv)
        if k == 0:
            return 0
        mx = np.uint64(0xFFFFFFFFFFFFFFFF)
        vals = self.kmv[self.kmv < mx]
        if len(vals) < FM_MAPS:
            return int(len(np.unique(vals)))   # saw everything
        kth = float(vals[-1]) / float(mx)
        return int((len(vals) - 1) / kth) if kth > 0 else len(vals)

    def merge(self, other: "FMSketch") -> "FMSketch":
        merged = np.unique(np.concatenate([self.kmv, other.kmv]))
        return FMSketch(merged[:FM_MAPS])
