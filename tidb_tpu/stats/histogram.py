"""Equal-depth histogram with range/point estimation.

Reference analog: pkg/statistics/histogram.go:64 (Histogram{Bounds,
Buckets[{Count,Repeat}]}) and pkg/planner/cardinality range estimation
(equalRowCount / betweenRowCount / outOfRangeRowCount).  Values live in the
column's order-preserving int64 encoding (see stats/build.py), so every
comparison here is plain integer compare regardless of SQL type.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class Histogram:
    bounds: np.ndarray        # int64[n_buckets], upper bound of each bucket
    cum_counts: np.ndarray    # rows <= bounds[j] (cumulative)
    repeats: np.ndarray       # rows == bounds[j]
    ndv: int = 0
    null_count: int = 0
    min_val: int = None       # smallest value seen (lower bound of bucket 0)

    def __post_init__(self):
        # drop degenerate trailing buckets (empty table / few rows)
        keep = np.concatenate([[True], np.diff(self.cum_counts) > 0]) \
            if len(self.cum_counts) else np.array([], bool)
        self.bounds = self.bounds[keep]
        self.cum_counts = self.cum_counts[keep]
        self.repeats = self.repeats[keep]

    @property
    def total(self) -> int:
        return int(self.cum_counts[-1]) if len(self.cum_counts) else 0

    def _bucket_lo(self, j: int):
        """Inclusive lower value of bucket j (previous bound + 1)."""
        if j > 0:
            return int(self.bounds[j - 1]) + 1
        return int(self.min_val) if self.min_val is not None else None

    def less_row_count(self, v: int) -> float:
        """Estimated rows with value < v."""
        if not len(self.bounds) or self.total == 0:
            return 0.0
        j = int(np.searchsorted(self.bounds, v, side="left"))
        if j >= len(self.bounds):
            return float(self.total)
        lo_cum = int(self.cum_counts[j - 1]) if j > 0 else 0
        in_bucket = int(self.cum_counts[j]) - lo_cum
        ub, rep = int(self.bounds[j]), int(self.repeats[j])
        if v > ub:
            return float(self.cum_counts[j])
        if v == ub:
            return float(lo_cum + max(in_bucket - rep, 0))
        # linear interpolation inside the bucket body
        lo = self._bucket_lo(j)
        lo = lo if lo is not None else ub - 1
        width = max(ub - lo, 1)
        frac = min(max((v - lo) / width, 0.0), 1.0)
        return lo_cum + frac * max(in_bucket - rep, 0)

    def equal_row_count(self, v: int) -> float:
        if not len(self.bounds) or self.total == 0:
            return 0.0
        j = int(np.searchsorted(self.bounds, v, side="left"))
        if j >= len(self.bounds):
            return 0.0          # out of range
        if v == int(self.bounds[j]):
            return float(self.repeats[j])
        lo0 = self._bucket_lo(0)
        if j == 0 and lo0 is not None and v < lo0:
            return 0.0          # below the histogram's min value
        # in-bucket non-bound value: bucket_ndv-weighted average
        lo_cum = int(self.cum_counts[j - 1]) if j > 0 else 0
        in_bucket = int(self.cum_counts[j]) - lo_cum
        per_val = self.total / max(self.ndv, 1)
        return float(min(per_val, in_bucket))

    def range_row_count(self, low, low_incl: bool, high, high_incl: bool) -> float:
        """Estimated rows in the interval; None bound = unbounded."""
        hi = (self.less_row_count(high) + (self.equal_row_count(high)
              if high_incl else 0.0)) if high is not None else float(self.total)
        lo = (self.less_row_count(low) + (0.0 if low_incl
              else self.equal_row_count(low))) if low is not None else 0.0
        return max(hi - lo, 0.0)
