"""Device-side ANALYZE kernel.

One jitted XLA program per (row-count, bucket, topn) signature computes,
for an int64-encoded column + validity mask:

  count, null_count, exact NDV, equal-depth histogram (bounds / cumulative
  counts / per-bound repeats), TopN (values + counts), FM sketch bitmask,
  and a CM sketch counter table.

Reference analog: pkg/statistics/row_sampler.go + cmsketch.go + fmsketch.go
+ histogram build in pkg/statistics/builder.go — all replaced by a single
sort + segment-sum pass, which is the TPU-idiomatic formulation (sorting is
MXU/VPU-friendly; no hash tables, no per-row host loops).

All dtypes reach this kernel as int64 in an order-preserving encoding
(ints/dates/times/decimals/dict-codes are already ordinal; float64 goes
through the sign-magnitude flip in `sortable_f64`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

FM_MAPS = 64          # fmsketch.go keeps one hash map; we keep 64 KMV-style
CM_DEPTH = 4          # cmsketch.go NewCMSketch(depth=..) default-ish
CM_WIDTH = 2048


def sortable_f64(a: np.ndarray) -> np.ndarray:
    """Map float64 to int64 preserving total order (NaN sorts last)."""
    i = a.view(np.int64).copy()
    i ^= (i >> 63) & np.int64(0x7FFFFFFFFFFFFFFF)
    return i


def unsortable_f64(i: int) -> float:
    v = np.int64(i)
    v ^= (v >> 63) & np.int64(0x7FFFFFFFFFFFFFFF)
    return float(np.array(v, dtype=np.int64).view(np.float64))


def _hash64(x, seed):
    """splitmix64 finalizer — branch-free, vectorizes on device."""
    h = (x + jnp.uint64(seed)) * jnp.uint64(0x9E3779B97F4A7C15)
    h = (h ^ (h >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return h ^ (h >> 31)


@partial(jax.jit, static_argnums=(2, 3))
def _stats_kernel(x, valid, n_buckets, n_top):
    n = x.shape[0]
    nv = valid.sum()
    # two-key sort: invalid rows strictly after valid ones, values exact
    inv = (~valid).astype(jnp.int32)
    _, xs = jax.lax.sort((inv, x), num_keys=2)
    pos = jnp.arange(n)
    in_valid = pos < nv
    # run-length structure over the sorted valid region
    prev = jnp.concatenate([xs[:1] - 1, xs[:-1]])
    boundary = (xs != prev) | (pos == 0)
    ndv = jnp.sum(boundary & in_valid)
    run_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    run_counts = jax.ops.segment_sum(in_valid.astype(jnp.int64), run_id, n)
    run_vals = jax.ops.segment_max(jnp.where(in_valid, xs, jnp.int64(-2**62)),
                                   run_id, n)
    # TopN (tiny tables: fewer rows than n_top slots — clamp, then pad
    # with zero-count entries so the output shape stays static)
    k = min(n_top, n)
    top_counts, top_idx = jax.lax.top_k(run_counts, k)
    top_vals = run_vals[top_idx]
    if k < n_top:
        top_counts = jnp.concatenate(
            [top_counts, jnp.zeros(n_top - k, top_counts.dtype)])
        top_vals = jnp.concatenate(
            [top_vals, jnp.zeros(n_top - k, top_vals.dtype)])
    # equal-depth histogram: bound j at sorted position min((j+1)*size, nv)-1
    size = jnp.maximum((nv + n_buckets - 1) // n_buckets, 1)
    ub_pos = jnp.minimum((jnp.arange(n_buckets) + 1) * size, nv) - 1
    ub_pos_c = jnp.clip(ub_pos, 0, n - 1)
    bounds = xs[ub_pos_c]
    cum_counts = ub_pos + 1                      # rows <= bounds[j]
    # repeats of each bound = pos+1 - first position of that value
    xs_clean = jnp.where(in_valid, xs, jnp.int64(2**62))
    first_pos = jnp.searchsorted(xs_clean, bounds, side="left")
    repeats = jnp.maximum(cum_counts - first_pos, 0)
    # FM/KMV sketch: k minimum hash values over DISTINCT values (run
    # starts of the sorted column) — mergeable across shards
    h = _hash64(xs.astype(jnp.uint64), 0x5bd1e995)
    h = jnp.where(boundary & in_valid, h, jnp.uint64(0xFFFFFFFFFFFFFFFF))
    hs = jnp.sort(h)[:FM_MAPS]
    # CM sketch: depth x width counters
    cm = jnp.zeros((CM_DEPTH, CM_WIDTH), dtype=jnp.int64)
    for d in range(CM_DEPTH):
        idx = (_hash64(xs.astype(jnp.uint64), 0xABCD + d * 7919) %
               jnp.uint64(CM_WIDTH)).astype(jnp.int32)
        cm = cm.at[d, idx].add(in_valid.astype(jnp.int64))
    return dict(count=nv.astype(jnp.int64),
                min_val=xs[0],
                null_count=(n - nv).astype(jnp.int64),
                ndv=ndv.astype(jnp.int64),
                bounds=bounds, cum_counts=cum_counts, repeats=repeats,
                top_vals=top_vals, top_counts=top_counts,
                kmv=hs, cm=cm)


def build_column_stats(data: np.ndarray, valid: np.ndarray,
                       n_buckets: int = 64, n_top: int = 16):
    """Run the ANALYZE kernel; returns plain-numpy dict."""
    if data.dtype == np.float64:
        enc = sortable_f64(data)
    else:
        enc = data.astype(np.int64, copy=False)
    out = _stats_kernel(jnp.asarray(enc), jnp.asarray(valid),
                        int(n_buckets), int(n_top))
    return {k: np.asarray(v) for k, v in out.items()}
