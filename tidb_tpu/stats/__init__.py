"""Statistics subsystem.

Reference analog: pkg/statistics/ (histogram.go:64 Histogram,
cmsketch.go:56/501 CMSketch+TopN, fmsketch.go:65 FMSketch) and
pkg/statistics/handle/ (load/save/cache, auto-analyze).  TPU-first design:
ANALYZE builds every per-column statistic in ONE fused XLA program — sort,
run-length encode, segment-sum, top_k — instead of the reference's
row-at-a-time sampling collectors (SURVEY.md §7 step 9: "histogram/TopN
built on-device via sort+segment-sum").
"""

from .histogram import Histogram
from .sketch import CMSketch, FMSketch, TopN
from .handle import ColumnStats, StatsHandle, TableStats

__all__ = ["Histogram", "CMSketch", "FMSketch", "TopN", "ColumnStats",
           "TableStats", "StatsHandle"]
