"""Stats handle: build, cache, and serve per-table statistics.

Reference analog: pkg/statistics/handle/ — stats cache keyed by table id,
modify-count tracking feeding auto-analyze (autoanalyze.go), and the
ANALYZE executor (pkg/executor/analyze*.go).  Build runs on device
(stats/build.py); estimation is host-side pure math.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..chunk.column import Column
from ..types import dtypes as dt
from .build import build_column_stats, sortable_f64
from .histogram import Histogram
from .sketch import CMSketch, FMSketch, TopN

K = dt.TypeKind


def encode_value(col_type: dt.DataType, v, dictionary=None) -> Optional[int]:
    """Encode a python constant into the column's order-preserving int64
    domain (the same encoding stats/build.py applied to the data)."""
    if v is None:
        return None
    if col_type.kind == K.FLOAT64:
        return int(sortable_f64(np.array([float(v)], dtype=np.float64))[0])
    if col_type.kind == K.STRING:
        if dictionary is None:
            return None
        if isinstance(v, str):
            c = dictionary.code_of(v)
            return c if c >= 0 else dictionary.lower_bound(v)
        return int(v)
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


@dataclass
class ColumnStats:
    name: str
    hist: Histogram
    topn: TopN
    cms: CMSketch
    fms: FMSketch
    ndv: int
    null_count: int
    count: int

    def equal_rows(self, enc: int) -> float:
        c = self.topn.count_of(enc)
        if c is not None:
            return float(c)
        return self.hist.equal_row_count(enc)

    def range_rows(self, low, low_incl, high, high_incl) -> float:
        return self.hist.range_row_count(low, low_incl, high, high_incl)


@dataclass
class TableStats:
    table_id: int
    version: int               # analyze timestamp (ns)
    count: int                 # rows at analyze time
    delta_count: int = 0       # net row delta since analyze (+ins, -del)
    modify_count: int = 0      # total DML churn since analyze
    cols: dict = field(default_factory=dict)   # name(lower) -> ColumnStats

    @property
    def realtime_count(self) -> int:
        return max(self.count + self.delta_count, 0)

    def col(self, name: str) -> Optional[ColumnStats]:
        return self.cols.get(name.lower())


class StatsHandle:
    """Per-Domain stats cache (pkg/statistics/handle Handle analog)."""

    AUTO_ANALYZE_RATIO = 0.5       # tidb_auto_analyze_ratio default
    AUTO_ANALYZE_MIN_COUNT = 1000  # reference: autoAnalyzeMinCnt
    # above this row count ANALYZE samples instead of full-scanning
    # (reference: row_sampler.go ReservoirRowSampleCollector)
    SAMPLE_THRESHOLD = 2_000_000
    SAMPLE_TARGET = 200_000

    def __init__(self):
        self._cache: dict[int, TableStats] = {}
        self._lock = threading.Lock()
        self.auto_analyze_enabled = True
        # predicate-column tracking (tidb_enable_column_tracking /
        # column_stats_usage): which columns queries actually filter on
        self._pred_cols: dict[int, set] = {}
        # async stats load (handle/syncload analog): tables whose first
        # plan found no stats get analyzed in the background
        self._loading: set = set()

    # ------------------------------------------------------------ #

    @staticmethod
    def _key(table):
        # tables built outside the catalog (register_columns test path)
        # share table_id 0; fall back to object identity so they don't
        # collide in the cache
        return getattr(table, "table_id", 0) or id(table)

    def get(self, table) -> Optional[TableStats]:
        return self._cache.get(self._key(table))

    def note_modify(self, table, churn: int, delta: int | None = None):
        """Record DML: churn = rows touched; delta = net row-count change
        (defaults to +churn, i.e. INSERT; DELETE passes -n, UPDATE 0)."""
        ts = self.get(table)
        if ts is not None:
            ts.modify_count += int(churn)
            ts.delta_count += int(churn if delta is None else delta)

    def needs_auto_analyze(self, table) -> bool:
        if not self.auto_analyze_enabled:
            return False
        ts = self.get(table)
        n = table.num_rows
        if ts is None:
            return n >= self.AUTO_ANALYZE_MIN_COUNT
        if ts.realtime_count < self.AUTO_ANALYZE_MIN_COUNT:
            return False
        return abs(ts.modify_count) > self.AUTO_ANALYZE_RATIO * max(ts.count, 1)

    # ------------------------------------------------------------ #

    # -- predicate-column tracking + async load --------------------- #

    def note_predicate_columns(self, table, names) -> None:
        """Record columns that appeared in query predicates; ANALYZE
        TABLE ... PREDICATE COLUMNS restricts collection to this set
        (reference: column_stats_usage.go)."""
        if not names:
            return
        with self._lock:
            self._pred_cols.setdefault(self._key(table), set()).update(
                n.lower() for n in names)

    def predicate_columns(self, table) -> set:
        return set(self._pred_cols.get(self._key(table), ()))

    def request_load(self, table) -> bool:
        """Async stats load (handle/syncload analog): schedule a
        background ANALYZE for a planned-against table with no stats;
        the current plan proceeds on defaults.  Returns True if
        scheduled."""
        if not self.auto_analyze_enabled:
            return False
        key = self._key(table)
        with self._lock:
            if key in self._cache or key in self._loading:
                return False
            if getattr(table, "num_rows", 0) < self.AUTO_ANALYZE_MIN_COUNT:
                return False
            self._loading.add(key)

        def run():
            try:
                self.analyze_table(table)
            except Exception:
                pass
            finally:
                with self._lock:
                    self._loading.discard(key)

        threading.Thread(target=run, name="stats-async-load",
                         daemon=True).start()
        return True

    # ------------------------------------------------------------ #

    def analyze_table(self, table, n_buckets: int = 64,
                      n_top: int = 16, columns=None,
                      sample_rate: Optional[float] = None,
                      predicate_only: bool = False) -> TableStats:
        """ANALYZE TABLE: device-build stats for every analyzable column.

        Large tables sample (systematic row sample, scaled estimates with
        the Duj1 NDV estimator — row_sampler.go's role); `columns`
        restricts collection; `predicate_only` restricts to the tracked
        predicate columns (ANALYZE ... PREDICATE COLUMNS)."""
        snap = table.snapshot()
        cols = snap.columns
        n = len(cols[0]) if cols else 0
        want = None
        if predicate_only:
            want = self.predicate_columns(table)
            if not want and not columns:
                # nothing tracked yet: keep whatever stats exist (TiDB
                # analyzes nothing rather than erasing)
                return self.get(table) or TableStats(
                    table_id=self._key(table), version=time.time_ns(),
                    count=n)
        if columns:
            want = {c.lower() for c in columns} | (want or set())
        if sample_rate is None and n > self.SAMPLE_THRESHOLD:
            sample_rate = self.SAMPLE_TARGET / n
        idx = None
        scale = 1.0
        if n and sample_rate is not None and 0 < sample_rate < 1.0:
            m = max(int(n * sample_rate), 1)
            step = max(n // m, 1)
            rng = np.random.default_rng(n)
            idx = (np.arange(m) * step
                   + rng.integers(0, step, m)).clip(0, n - 1)
            scale = n / m
        ts = TableStats(table_id=self._key(table),
                        version=time.time_ns(), count=n)
        if want is not None:
            # column-restricted analyze MERGES into existing stats
            # (TiDB keeps unlisted columns' histograms)
            prev = self.get(table)
            if prev is not None:
                ts.cols.update(prev.cols)
        for name, col in zip(table.col_names, cols):
            if want is not None and name.lower() not in want:
                continue
            c = col.take(idx) if idx is not None else col
            cs = self._analyze_column(name, c, n_buckets, n_top,
                                      scale=scale)
            if cs is not None:
                ts.cols[name.lower()] = cs
        with self._lock:
            self._cache[ts.table_id] = ts
        # valueflow runtime half: stamp this ANALYZE's observed per-column
        # min/max watermarks so every subsequent launch can check its
        # plan's declared value intervals still contain reality (drift is
        # surfaced on /sched, never a wrong result)
        from ..analysis import valueflow
        valueflow.stamp_watermarks(ts)
        return ts

    def _analyze_column(self, name: str, col: Column, n_buckets: int,
                        n_top: int,
                        scale: float = 1.0) -> Optional[ColumnStats]:
        if len(col) == 0:
            empty = Histogram(np.array([], np.int64), np.array([], np.int64),
                              np.array([], np.int64))
            return ColumnStats(name, empty, TopN(),
                               CMSketch(np.zeros((4, 2048), np.int64)),
                               FMSketch(np.array([], np.uint64)),
                               0, 0, 0)
        raw = build_column_stats(col.data, col.validity, n_buckets, n_top)
        ndv = int(raw["ndv"])
        if scale > 1.0:
            # sampled build: scale counts, estimate full-table NDV with
            # the Duj1 estimator d / (1 - (1-q) f1/n) from the singleton
            # count (statistics/row_sampler.go calculateEstimateNDV)
            vals = col.data[col.validity]
            n_s = len(vals)
            if n_s:
                _u, cnts = np.unique(vals, return_counts=True)
                f1 = int((cnts == 1).sum())
                denom = 1.0 - (1.0 - 1.0 / scale) * f1 / n_s
                est = ndv / max(denom, 1e-3)
                ndv = int(round(min(max(est, ndv),
                                    int(raw["count"]) * scale)))
            raw = dict(raw)
            for k in ("cum_counts", "repeats", "top_counts", "cm"):
                raw[k] = np.round(raw[k] * scale).astype(np.int64)
            raw["count"] = np.int64(round(int(raw["count"]) * scale))
            raw["null_count"] = np.int64(
                round(int(raw["null_count"]) * scale))
        hist = Histogram(raw["bounds"], raw["cum_counts"], raw["repeats"],
                         ndv=ndv, null_count=int(raw["null_count"]),
                         min_val=(int(raw["min_val"])
                                  if int(raw["count"]) else None))
        # keep only TopN entries that are genuinely frequent (count > 1
        # and above the uniform expectation), like cmsketch.go TopN pruning
        tv, tc = raw["top_vals"], raw["top_counts"]
        uniform = max(int(raw["count"]) / max(ndv, 1), 1.0)
        topn = TopN({int(v): int(c) for v, c in zip(tv, tc)
                     if c > 0 and c >= uniform})
        return ColumnStats(name=name, hist=hist, topn=topn,
                           cms=CMSketch(raw["cm"]),
                           fms=FMSketch(raw["kmv"].astype(np.uint64)),
                           ndv=ndv, null_count=int(raw["null_count"]),
                           count=int(raw["count"]))
