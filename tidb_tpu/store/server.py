"""Store server: the coprocessor engine behind a socket.

Reference analog: a unistore/TiKV store process — a region server that
holds replicas and executes coprocessor DAGs shipped from the SQL layer
(/root/reference/pkg/store/mockstore/unistore/tikv/server.go:45
Coprocessor(), cophandler/cop_handler.go handleCopDAGRequest).  The TPU
build's SQL layer fuses shard programs on the device; THIS process is the
remote-store role of the same contract: it stores replicated columnar
tables, executes serialized DAGs over requested row ranges with the host
engines, and returns PARTIAL aggregation states (the psum-seam contract,
copr/aggregate.py) or row columns for the client to merge.

Run: ``python -m tidb_tpu.store.server [--port 0]`` — prints
``PORT <n>`` on stdout once listening.

Protocol (store/rpc.py frames; one request -> one response):
  ("load", table, epoch, names, dtypes, columns)      -> ("ok",)
  ("exec_agg", table, epoch, dag, ranges)             -> ("states", st)
  ("exec_rows", table, epoch, dag, ranges, dtypes)    -> ("rows", cols)
  ("ping",)                                           -> ("pong",)
  ("fail_after", k)    [failpoint: exit before the k-th next response]
Stale ``epoch`` returns ("err", "stale_epoch", have_epoch) — the client
re-ships the table (region-epoch-not-match analog).
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading

import numpy as np

from .rpc import recv_msg, send_msg


class StoreEngine:
    """In-process state of one store: replicated tables + executors."""

    def __init__(self):
        self.tables: dict = {}      # name -> (epoch, snapshot)
        self.mu = threading.Lock()
        self.requests_served = 0

    # ---------------- table replication ---------------- #

    def load(self, table: str, epoch: int, names, dtypes, columns):
        from .columnar import ColumnarSnapshot
        snap = ColumnarSnapshot(list(names), list(dtypes), list(columns),
                                epoch=epoch, n_shards=1)
        with self.mu:
            self.tables[table] = (epoch, snap)

    def _snap_for(self, table: str, epoch: int, ranges):
        from ..chunk.column import Column
        from .columnar import ColumnarSnapshot
        with self.mu:
            ent = self.tables.get(table)
        if ent is None:
            return None, ("err", "no_table", table)
        have, snap = ent
        if have != epoch:
            return None, ("err", "stale_epoch", have)
        if ranges is None or [tuple(r) for r in ranges] == \
                [(0, snap.num_rows)]:
            return snap, None
        cols = []
        for c in snap.columns:
            parts = [c.slice(lo, hi) for lo, hi in ranges]
            cols.append(parts[0] if len(parts) == 1
                        else Column.concat(parts))
        sub = ColumnarSnapshot(snap.names, snap.dtypes, cols,
                               epoch=epoch, n_shards=1)
        return sub, None

    # ---------------- executors ---------------- #

    def exec_agg(self, table: str, epoch: int, agg, ranges):
        from ..copr import dag as D
        from ..copr.hostagg import host_dense_agg, host_sort_agg
        snap, err = self._snap_for(table, epoch, ranges)
        if err is not None:
            return err
        if agg.strategy in D.HOST_MERGE_STRATEGIES:
            st = host_sort_agg(agg, snap)
        else:
            st = host_dense_agg(agg, snap)
        if st is None:
            return ("err", "unsupported", "agg outside host-engine scope")
        return ("states", st)

    def exec_rows(self, table: str, epoch: int, dag, ranges, out_dtypes):
        from ..chunk.column import Column
        from ..copr import dag as D
        from ..copr.hostagg import _host_scan_chain
        snap, err = self._snap_for(table, epoch, ranges)
        if err is not None:
            return err
        root = dag
        topn = None
        limit = None
        if isinstance(root, D.TopN):
            topn, root = root, root.child
        elif isinstance(root, D.Limit):
            limit, root = root.limit, root.child
        chain = _host_scan_chain(root, snap)
        if chain is None:
            return ("err", "unsupported", "row plan outside scan-chain scope")
        cols, live = chain
        n = len(cols[0][0]) if cols else 0
        if live is not None:
            idx = np.nonzero(live)[0]
            cols = [(np.asarray(v)[idx] if np.ndim(v) else v,
                     m if m is True else np.asarray(m)[idx])
                    for v, m in cols]
            n = len(idx)
        if topn is not None:
            keep = _topn_indices(topn, cols, n)
            cols = [(np.asarray(np.broadcast_to(v, (n,)))[keep],
                     m if m is True else np.asarray(m)[keep])
                    for v, m in cols]
            n = len(keep)
        elif limit is not None:
            cols = [(np.asarray(np.broadcast_to(v, (n,)))[:limit],
                     m if m is True else np.asarray(m)[:limit])
                    for v, m in cols]
            n = min(n, limit)
        out = []
        for (v, m), t in zip(cols, out_dtypes):
            v = np.broadcast_to(np.asarray(v), (n,))
            valid = (np.ones(n, bool) if m is True
                     else np.broadcast_to(np.asarray(m), (n,)).copy())
            out.append(Column(t, v.astype(t.np_dtype())
                              if v.dtype != object else v, valid))
        return ("rows", out)


def _topn_indices(topn, cols, n: int) -> np.ndarray:
    """Per-store TopN candidates: rank-sort (uint-safe, MySQL NULL
    ordering — first ASC, last DESC) and trim; the SQL-layer caller
    re-trims the cross-store union (cophandler/topn.go discipline)."""
    from ..expr.compile import eval_expr
    keys = topn.sort_keys or ((topn.sort_key, topn.desc),)
    lex = []
    for e, desc in reversed(list(keys)):
        v, m = eval_expr(np, e, cols)
        v = np.broadcast_to(np.asarray(v), (n,))
        valid = (np.ones(n, bool) if m is True
                 else np.broadcast_to(np.asarray(m), (n,)))
        _, ranks = np.unique(v, return_inverse=True)
        ranks = ranks.astype(np.int64) + 1
        if desc:
            ranks = -ranks
        lex.append(np.where(valid, ranks, 0))
    order = np.lexsort(tuple(lex)) if lex else np.arange(n)
    return order[:topn.limit]


class CatalogStoreEngine(StoreEngine):
    """TiDB-as-coprocessor (executor/coprocessor.go:57): the SQL process
    itself serves coprocessor requests over its OWN catalog tables — a
    peer ships a DAG naming "db.table" and gets partial states / rows
    back, exactly as from a store process.  Snapshots resolve live from
    the catalog; epoch -1 means "latest" (the response carries the
    snapshot epoch the execution bound)."""

    def __init__(self, domain):
        super().__init__()
        self.domain = domain

    def _snap_for(self, table: str, epoch: int, ranges):
        from ..chunk.column import Column
        from .columnar import ColumnarSnapshot
        db, _, name = table.partition(".")
        if not name:
            db, name = "test", db
        try:
            tbl = self.domain.catalog.get_table(db, name)
        except Exception:
            return super()._snap_for(table, epoch, ranges)
        snap = tbl.snapshot()
        if epoch not in (-1, snap.epoch):
            return None, ("err", "stale_epoch", snap.epoch)
        if ranges is None:
            return snap, None
        cols = []
        for c in snap.columns:
            parts = [c.slice(lo, hi) for lo, hi in ranges]
            cols.append(parts[0] if len(parts) == 1
                        else Column.concat(parts))
        return ColumnarSnapshot(snap.names, snap.dtypes, cols,
                                epoch=snap.epoch, n_shards=1), None


def serve_coprocessor(domain, port: int = 0) -> int:
    """Expose this SQL process as a coprocessor endpoint on 127.0.0.1;
    returns the bound port.  Runs the accept loop on a daemon thread."""
    eng = CatalogStoreEngine(domain)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(16)
    bound = srv.getsockname()[1]

    def accept_loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=_handle_conn, args=(eng, conn),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True,
                     name="coprocessor-endpoint").start()
    domain._copr_endpoint = (srv, bound)
    return bound


def _handle_conn(eng: StoreEngine, conn) -> None:
    try:
        while True:
            msg = recv_msg(conn)
            op = msg[0]
            if op == "ping":
                resp = ("pong", eng.requests_served)
            elif op == "load":
                eng.load(*msg[1:])
                resp = ("ok",)
            elif op == "exec_agg":
                resp = eng.exec_agg(*msg[1:])
            elif op == "exec_rows":
                resp = eng.exec_rows(*msg[1:])
            else:
                resp = ("err", "bad_op", op)
            eng.requests_served += 1
            send_msg(conn, resp)
    except (ConnectionError, OSError):
        pass
    finally:
        conn.close()


def _dxf_backfill(table_id, index_id, unique, offs, col_types, rows):
    """DXF taskexecutor role (disttask framework worker): compute the
    index KV entries for one backfill subtask.  The owner ships
    (handle, encoded row) pairs and commits the returned entries — the
    reference's ingest-mode split (workers encode, the owner ingests,
    backfilling_dist_scheduler.go)."""
    from .codec import decode_row, encode_index_entry
    entries = []
    for h, rv in rows:
        row = decode_row(rv, col_types)
        vals = [row[i] for i in offs]
        types = [col_types[i] for i in offs]
        k, v = encode_index_entry(table_id, index_id, vals, types,
                                  int(h), unique)
        entries.append((int(h), k, v))
    return ("entries", entries)


def serve(port: int = 0):
    eng = StoreEngine()
    fail_after = [None]    # failpoint: exit before the k-th next response
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(16)
    print(f"PORT {srv.getsockname()[1]}", flush=True)

    def handle(conn):
        try:
            while True:
                msg = recv_msg(conn)
                op = msg[0]
                if fail_after[0] is not None:
                    fail_after[0] -= 1
                    if fail_after[0] <= 0:
                        os._exit(17)   # simulated store crash mid-query
                if op == "ping":
                    resp = ("pong", eng.requests_served)
                elif op == "load":
                    eng.load(*msg[1:])
                    resp = ("ok",)
                elif op == "exec_agg":
                    resp = eng.exec_agg(*msg[1:])
                elif op == "exec_rows":
                    resp = eng.exec_rows(*msg[1:])
                elif op == "dxf_backfill":
                    resp = _dxf_backfill(*msg[1:])
                elif op == "fail_after":
                    fail_after[0] = int(msg[1])
                    resp = ("ok",)
                else:
                    resp = ("err", "bad_op", op)
                eng.requests_served += 1
                send_msg(conn, resp)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    while True:
        conn, _ = srv.accept()
        threading.Thread(target=handle, args=(conn,), daemon=True).start()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    serve(args.port)


if __name__ == "__main__":
    sys.exit(main())
