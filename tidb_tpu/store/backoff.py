"""Backoffer: typed exponential-backoff retry budgets.

Reference analog: tikv/client-go retry.Backoffer as used by
pkg/store/copr (coprocessor.go backoff on region errors, store
unreachable, etc.).  Each error KIND has its own base/cap growth curve;
the backoffer enforces a TOTAL sleep budget across all kinds — when the
budget is exhausted the original error surfaces with the attempt history
attached (the reference's `backoff timeout, takes too long` path).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field


class RetryBudgetExceeded(RuntimeError):
    def __init__(self, history: list, last: Exception):
        super().__init__(
            f"retry budget exhausted after {len(history)} attempts: {last}")
        self.history = history
        self.last = last


@dataclass(frozen=True)
class BackoffKind:
    name: str
    base_ms: float
    cap_ms: float


# the reference's config set (retry/backoff.go), trimmed to the error
# classes this engine can actually produce
REGION_MISS = BackoffKind("regionMiss", 2, 500)
STALE_EPOCH = BackoffKind("staleEpoch", 2, 500)
STORE_UNAVAILABLE = BackoffKind("storeUnavailable", 100, 2000)
DEVICE_BUSY = BackoffKind("deviceBusy", 20, 1000)
TXN_LOCK = BackoffKind("txnLock", 10, 1000)
# transient device-launch failure (faultline supervised drain): a
# compiled program's launch died in a retryable way — back off and
# re-launch under the statement budget (copIterator rpc-error analog)
DEVICE_FAILED = BackoffKind("deviceFailed", 10, 500)


@dataclass
class Backoffer:
    """One statement-scoped retry budget (max total sleep)."""
    max_sleep_ms: float = 5000.0
    slept_ms: float = 0.0
    attempts: dict = field(default_factory=dict)   # kind name -> count
    history: list = field(default_factory=list)
    sleep_fn: object = time.sleep      # test seam
    # jitter source: the global random module by default; inject a
    # seeded random.Random so retry histories replay bit-identically in
    # tests and under an armed FaultPlan (sleep_fn's twin seam)
    rng: object = random

    def backoff(self, kind: BackoffKind, err: Exception) -> None:
        """Sleep per the kind's curve, or raise RetryBudgetExceeded."""
        n = self.attempts.get(kind.name, 0)
        self.attempts[kind.name] = n + 1
        # exponential with equal-jitter, capped
        raw = min(kind.base_ms * (2 ** n), kind.cap_ms)
        ms = raw / 2 + self.rng.uniform(0, raw / 2)
        self.history.append((kind.name, round(ms, 2), str(err)))
        if self.slept_ms + ms > self.max_sleep_ms:
            raise RetryBudgetExceeded(self.history, err)
        self.slept_ms += ms
        self.sleep_fn(ms / 1000.0)


class RegionError(RuntimeError):
    """Retryable dispatch error (epoch-not-match / region-miss /
    store-unavailable analog); `kind` selects the backoff curve."""

    def __init__(self, kind: BackoffKind, msg: str = ""):
        super().__init__(msg or kind.name)
        self.kind = kind


__all__ = ["Backoffer", "BackoffKind", "RegionError",
           "RetryBudgetExceeded", "REGION_MISS", "STALE_EPOCH",
           "STORE_UNAVAILABLE", "DEVICE_BUSY", "DEVICE_FAILED",
           "TXN_LOCK"]
