from .columnar import ColumnarSnapshot, snapshot_from_columns
from .client import CopClient, CopResult

__all__ = ["ColumnarSnapshot", "snapshot_from_columns", "CopClient", "CopResult"]
