"""ctypes bindings for the native C++ MVCC KV engine + txn client.

Reference analog: the client side of tikv/client-go/v2 (2PC driver, TSO) +
pkg/kv interfaces (kv.Storage / kv.Transaction / kv.Snapshot, kv/kv.go:218,
657, 693).  The engine itself is tidb_tpu/native/kvstore.cpp (built on
first use with make/g++); this module is the Go-interface analog:

- KVStore: open/scan/get at a ts (kv.Snapshot)
- Txn: buffered writes (MemBuffer analog) + percolator 2PC commit
  (prewrite all keys primary-first, allocate commit ts, commit primary
  then secondaries — client-go twoPhaseCommitter analog)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Iterator, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libtpukv.so"))
_build_lock = threading.Lock()
_lib = None


class KVError(RuntimeError):
    def __init__(self, code: int, msg: str = ""):
        super().__init__(f"kv error {code}: {msg or ERR_NAMES.get(code, '?')}")
        self.code = code


ERR_NAMES = {1: "locked", 2: "write conflict", 3: "not found",
             4: "txn mismatch", 5: "already rolled back",
             6: "deadlock", 7: "lock wait timeout", 8: "wal write failed"}
ERR_LOCKED, ERR_WRITE_CONFLICT, ERR_NOT_FOUND = 1, 2, 3
ERR_DEADLOCK, ERR_LOCK_WAIT_TIMEOUT = 6, 7


class DeadlockError(KVError):
    """Waits-for cycle: this transaction was chosen as the victim
    (unistore/tikv/detector.go analog)."""


class LockWaitTimeout(KVError):
    """innodb_lock_wait_timeout analog."""


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "kvstore.cpp")
        if (not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
            subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)],
                           check=True, capture_output=True)
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            # ABI mismatch: the checked-in .so was built against a newer
            # glibc than this host's — force a local rebuild and retry
            subprocess.run(["make", "-B", "-C",
                            os.path.abspath(_NATIVE_DIR)],
                           check=True, capture_output=True)
            lib = ctypes.CDLL(_LIB_PATH)
        lib.kv_open.restype = ctypes.c_void_p
        lib.kv_close.argtypes = [ctypes.c_void_p]
        lib.kv_alloc_ts.restype = ctypes.c_uint64
        lib.kv_alloc_ts.argtypes = [ctypes.c_void_p]
        lib.kv_prewrite.restype = ctypes.c_int32
        lib.kv_prewrite.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint8]
        lib.kv_commit.restype = ctypes.c_int32
        lib.kv_commit.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int32, ctypes.c_uint64,
                                  ctypes.c_uint64]
        lib.kv_rollback.restype = ctypes.c_int32
        lib.kv_rollback.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int32, ctypes.c_uint64]
        lib.kv_get.restype = ctypes.c_int32
        lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int32, ctypes.c_uint64,
                               ctypes.POINTER(ctypes.c_char_p),
                               ctypes.POINTER(ctypes.c_int32)]
        lib.kv_scan.restype = ctypes.c_int32
        lib.kv_scan.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8)]
        lib.kv_versions.restype = ctypes.c_int32
        lib.kv_versions.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8)]
        lib.kv_gc.restype = ctypes.c_int64
        lib.kv_gc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kv_num_keys.restype = ctypes.c_int64
        lib.kv_num_keys.argtypes = [ctypes.c_void_p]
        lib.kv_flush.restype = ctypes.c_int64
        lib.kv_flush.argtypes = [ctypes.c_void_p]
        lib.kv_run_count.restype = ctypes.c_int64
        lib.kv_run_count.argtypes = [ctypes.c_void_p]
        lib.kv_set_flush_threshold.restype = None
        lib.kv_set_flush_threshold.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int64]
        lib.kv_open_at.restype = ctypes.c_void_p
        lib.kv_open_at.argtypes = [ctypes.c_char_p, ctypes.c_int32,
                                   ctypes.c_uint8]
        lib.kv_checkpoint.restype = ctypes.c_int64
        lib.kv_checkpoint.argtypes = [ctypes.c_void_p]
        lib.kv_pessimistic_lock.restype = ctypes.c_int32
        lib.kv_pessimistic_lock.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_int32]
        lib.kv_pessimistic_rollback.restype = ctypes.c_int32
        lib.kv_pessimistic_rollback.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_uint64]
        _lib = lib
    return _lib


class KVStore:
    """kv.Storage analog over the native engine (embedded TSO).

    `path` (a file prefix, e.g. "<dir>/kv") makes the store durable:
    committed writes append to <path>.wal; checkpoint() compacts the state
    into <path>.snap and truncates the log; reopening the same path
    replays both.  `sync` fdatasyncs every commit record."""

    def __init__(self, path: Optional[str] = None, sync: bool = False,
                 keyspace: str = ""):
        self._lib = _load_lib()
        self.path = path
        self._ts_samples: list = []    # (wallclock, ts) for stale reads
        # leaf lock for the sample index: alloc_ts runs on every
        # statement thread, and the thinning pass is a read-modify-write
        # that would drop concurrent appends without it
        self._ts_mu = threading.Lock()
        # close() runs these FIRST (watch pollers etc. join their
        # threads) so no background caller holds the native handle when
        # it frees — a poller racing kv_close segfaulted in the C lib
        self._closers: list = []
        # keyspace (pkg/keyspace analog): a tenant prefix transparently
        # applied to every key, so tenants sharing one physical store
        # cannot observe each other's keys.  "" = the null keyspace.
        self._ks = (keyspace.encode() + b"\x00") if keyspace else b""
        if path is None:
            self._h = ctypes.c_void_p(self._lib.kv_open())
        else:
            p = os.fsencode(path)
            self._h = ctypes.c_void_p(
                self._lib.kv_open_at(p, len(p), 1 if sync else 0))
            if not self._h:
                raise KVError(0, f"cannot open WAL at {path!r} "
                                 "(unwritable directory?)")

    def checkpoint(self) -> int:
        """Compact to <path>.snap + truncate the WAL (BR snapshot-backup
        seam; -1 when in-memory)."""
        n = int(self._lib.kv_checkpoint(self._h))
        if n == -2:
            raise KVError(0, "checkpoint could not reopen the WAL; "
                             "store is no longer durable")
        return n

    def close(self):
        if self._h:
            for cb in list(self._closers):
                try:
                    cb()
                except Exception:
                    pass
            self._lib.kv_close(self._h)
            self._h = None

    def _require_open(self):
        if not self._h:
            raise KVError(-98, "store closed")

    def alloc_ts(self) -> int:
        """TSO allocation (PD analog).  Samples a coarse wallclock->ts
        index so stale reads (AS OF TIMESTAMP, sessiontxn/staleread) can
        map a datetime back to a logical snapshot ts."""
        import time as _time
        self._require_open()
        ts = int(self._lib.kv_alloc_ts(self._h))
        with self._ts_mu:
            self._ts_samples.append((_time.time(), ts))
            if len(self._ts_samples) > 200_000:
                # keep recency exact, thin the old half (staleness
                # windows that far back only need coarse resolution)
                old = self._ts_samples[:100_000:2]
                self._ts_samples = old + self._ts_samples[100_000:]
        return ts

    def ts_at_time(self, epoch_seconds: float) -> int:
        """Largest sampled ts allocated at or before the wallclock time
        (the TSO physical-time mapping of the reference, staleread
        processor.go).  Raises if the time predates the store.  The
        sample index is in-memory only: after reopening a persistent
        store, datetime staleness spans only the current process's
        lifetime (raw integer ts literals always work)."""
        import bisect
        with self._ts_mu:
            i = bisect.bisect_right(self._ts_samples,
                                    (epoch_seconds, float("inf")))
            if i == 0:
                raise KVError(0,
                              "requested staleness predates the store")
            return self._ts_samples[i - 1][1]

    def begin(self, pessimistic: bool = False) -> "Txn":
        return Txn(self, self.alloc_ts(), pessimistic=pessimistic)

    # -- keyspace (tenant prefix) -------------------------------------- #

    def with_keyspace(self, keyspace: str) -> "KVStore":
        """A VIEW of this store under a tenant keyspace: shares the
        engine handle and TSO, prefixes every key (pkg/keyspace)."""
        import copy as _copy
        view = _copy.copy(self)
        view._ks = (keyspace.encode() + b"\x00") if keyspace else b""
        return view

    def _pk(self, key: bytes) -> bytes:
        return self._ks + key if self._ks else key

    def _strip(self, key: bytes) -> bytes:
        return key[len(self._ks):] if self._ks else key

    def _ks_end(self) -> bytes:
        ba = bytearray(self._ks)
        for i in reversed(range(len(ba))):
            if ba[i] != 0xFF:
                ba[i] += 1
                return bytes(ba[: i + 1])
        return b""

    # -- snapshot reads ------------------------------------------------ #

    def get(self, key: bytes, ts: int) -> Optional[bytes]:
        self._require_open()
        key = self._pk(key)
        out = ctypes.c_char_p()
        out_len = ctypes.c_int32()
        rc = self._lib.kv_get(self._h, key, len(key), ts,
                              ctypes.byref(out), ctypes.byref(out_len))
        if rc == ERR_NOT_FOUND:
            return None
        if rc != 0:
            raise KVError(rc)
        return ctypes.string_at(out, out_len.value)

    def scan(self, start: bytes, end: bytes, ts: int,
             limit: int = 1 << 30, page_bytes: int = 1 << 20
             ) -> Iterator[tuple[bytes, bytes]]:
        """Paged snapshot scan (the kv paging analog, SURVEY.md §5.7)."""
        self._require_open()
        buf = ctypes.create_string_buffer(page_bytes)
        cur = self._pk(start)
        end = self._pk(end) if end else (self._ks_end() if self._ks else end)
        remaining = limit
        while remaining > 0:
            used = ctypes.c_int64()
            trunc = ctypes.c_uint8()
            rc = self._lib.kv_scan(self._h, cur, len(cur), end, len(end), ts,
                                   min(remaining, 1 << 20), buf, page_bytes,
                                   ctypes.byref(used), ctypes.byref(trunc))
            if rc < 0:
                raise KVError(-rc)
            if rc == 0 and trunc.value:
                # a single record exceeds the page: grow and retry
                page_bytes *= 4
                buf = ctypes.create_string_buffer(page_bytes)
                continue
            data = buf.raw[: used.value]
            off = 0
            last_key = None
            for _ in range(rc):
                klen = int.from_bytes(data[off:off + 4], "little"); off += 4
                k = data[off:off + klen]; off += klen
                vlen = int.from_bytes(data[off:off + 4], "little"); off += 4
                v = data[off:off + vlen]; off += vlen
                last_key = k
                yield self._strip(k), v
                remaining -= 1
            if not trunc.value or last_key is None:
                return
            cur = last_key + b"\x00"

    def versions(self, key: bytes, max_versions: int = 64
                 ) -> tuple[list[tuple[int, Optional[bytes]]], bool]:
        """MVCC history of one key, newest-first: [(commit_ts, value or
        None-for-delete)], plus a truncation flag.  Served straight from
        the native version chains (memtable + runs) — the status API's
        /mvcc handler reads this instead of probing every ts."""
        key = self._pk(key)
        buf = ctypes.create_string_buffer(1 << 20)
        used = ctypes.c_int64()
        trunc = ctypes.c_uint8()
        n = int(self._lib.kv_versions(self._h, key, len(key), max_versions,
                                      buf, len(buf), ctypes.byref(used),
                                      ctypes.byref(trunc)))
        out: list[tuple[int, Optional[bytes]]] = []
        raw = buf.raw[:used.value]
        off = 0
        import struct as _struct
        for _ in range(max(n, 0)):
            ts, op, vlen = _struct.unpack_from("<QBi", raw, off)
            off += 13
            val = raw[off:off + vlen] if op == 0 else None
            off += max(vlen, 0)
            out.append((ts, val))
        return out, bool(trunc.value)

    def gc(self, safepoint: int) -> int:
        return int(self._lib.kv_gc(self._h, safepoint))

    def num_keys(self) -> int:
        return int(self._lib.kv_num_keys(self._h))

    # ---------------- LSM controls (immutable sorted runs) ------------ #

    def flush(self) -> int:
        """Freeze unlocked memtable keys into an immutable sorted run
        (bloom-filtered, binary-searched); returns keys moved."""
        return int(self._lib.kv_flush(self._h))

    def run_count(self) -> int:
        return int(self._lib.kv_run_count(self._h))

    def set_flush_threshold(self, n: int) -> None:
        """Memtable key count that triggers an automatic flush at
        commit time (amortized check); n <= 0 disables auto-flush."""
        self._lib.kv_set_flush_threshold(self._h, int(n))


_UNSET = object()   # savepoint sentinel: key absent from the membuffer


@dataclass
class Txn:
    """Transaction: membuffer + percolator 2PC on commit (client-go
    twoPhaseCommitter analog).  Pessimistic mode locks every written key
    at DML time (KvPessimisticLock) so conflicting writers BLOCK instead
    of failing at commit; a waits-for cycle aborts the requester
    (DeadlockError)."""
    store: KVStore
    start_ts: int
    mutations: dict = field(default_factory=dict)  # key -> value|None(delete)
    committed: bool = False
    pessimistic: bool = False
    locked: set = field(default_factory=set)
    lock_wait_ms: int = 3000
    for_update_ts: int = 0       # latest lock acquisition ts
    _undo: Optional[dict] = None  # active statement savepoint (undo delta)

    def put(self, key: bytes, value: bytes):
        key = self.store._pk(key)
        if self.pessimistic:
            self._lock_raw([key])
        self._record_undo(key)
        self.mutations[key] = value

    def delete(self, key: bytes):
        key = self.store._pk(key)
        if self.pessimistic:
            self._lock_raw([key])
        self._record_undo(key)
        self.mutations[key] = None

    def lock_keys(self, keys, wait_ms: Optional[int] = None):
        self._lock_raw([self.store._pk(k) for k in keys], wait_ms)

    def _lock_raw(self, keys, wait_ms: Optional[int] = None):
        """Acquire pessimistic locks on PREFIXED keys (SELECT FOR UPDATE /
        DML locking).  for_update_ts is allocated fresh so commits between
        start_ts and now are tolerated — the pessimistic-mode contract."""
        lib = self.store._lib
        h = self.store._h
        wait = self.lock_wait_ms if wait_ms is None else wait_ms
        primary = next(iter(sorted(self.locked | set(keys))))
        for k in keys:
            if k in self.locked:
                continue
            # a commit can land between our for_update_ts and the wait's
            # end; the pessimistic protocol refreshes for_update_ts and
            # retries (client-go's WriteConflict handling)
            for _ in range(64):
                for_update_ts = self.store.alloc_ts()
                self.for_update_ts = max(self.for_update_ts, for_update_ts)
                rc = lib.kv_pessimistic_lock(h, k, len(k), primary,
                                             len(primary), self.start_ts,
                                             for_update_ts, wait)
                if rc != ERR_WRITE_CONFLICT:
                    break
            if rc == ERR_DEADLOCK:
                self.rollback()
                raise DeadlockError(rc, f"lock {k!r}")
            if rc == ERR_LOCK_WAIT_TIMEOUT:
                raise LockWaitTimeout(rc, f"lock {k!r}")
            if rc != 0:
                raise KVError(rc, f"pessimistic lock {k!r}")
            self.locked.add(k)

    @property
    def read_ts(self) -> int:
        """Pessimistic reads see everything up to the lock acquisition
        (for_update_ts); optimistic reads stay at the start snapshot."""
        return max(self.start_ts, self.for_update_ts)

    def get(self, key: bytes) -> Optional[bytes]:
        pk = self.store._pk(key)
        if pk in self.mutations:
            return self.mutations[pk]
        return self.store.get(key, self.read_ts)

    def scan(self, start: bytes, end: bytes, **kw):
        """Union-scan analog: merge membuffer over the snapshot.  Yields
        UNPREFIXED keys; the membuffer holds prefixed ones."""
        snap = dict(self.store.scan(start, end, self.read_ts, **kw))
        for pk, v in self.mutations.items():
            k = self.store._strip(pk)
            if start <= k < (end or k + b"\x00"):
                if v is None:
                    snap.pop(k, None)
                else:
                    snap[k] = v
        for k in sorted(snap):
            yield k, snap[k]

    def commit(self) -> int:
        if not self.mutations:
            self._release_unwritten_locks()
            self.committed = True
            return self.start_ts
        lib = self.store._lib
        h = self.store._h
        keys = sorted(self.mutations)
        primary = keys[0]
        prewritten = []
        for k in keys:
            v = self.mutations[k]
            op = 1 if v is None else 0
            rc = lib.kv_prewrite(h, k, len(k), v or b"", len(v or b""),
                                 primary, len(primary), self.start_ts, op)
            if rc != 0:
                for pk in prewritten:
                    lib.kv_rollback(h, pk, len(pk), self.start_ts)
                raise KVError(rc, f"prewrite {k!r}")
            prewritten.append(k)
        commit_ts = self.store.alloc_ts()
        # commit primary first: the txn is durable once the primary commits
        for k in [primary] + [k for k in keys if k != primary]:
            rc = lib.kv_commit(h, k, len(k), self.start_ts, commit_ts)
            if rc != 0:
                raise KVError(rc, f"commit {k!r}")
        self._release_unwritten_locks()
        self.committed = True
        return commit_ts

    def savepoint(self) -> dict:
        """Statement-level savepoint as an UNDO DELTA: put/delete record a
        key's prior membuffer state on first touch, so staging costs
        O(statement writes), not O(transaction writes) — the client-go
        memdb staging-checkpoint discipline.  Restoring with rollback_to()
        undoes every write since; release_savepoint() on statement success
        stops the recording."""
        self._undo = {}
        return self._undo

    def rollback_to(self, sp: dict):
        for k, prior in sp.items():
            if prior is _UNSET:
                self.mutations.pop(k, None)
            else:
                self.mutations[k] = prior
        self._undo = None

    def release_savepoint(self):
        self._undo = None

    def _record_undo(self, key: bytes):
        if self._undo is not None and key not in self._undo:
            self._undo[key] = self.mutations.get(key, _UNSET)

    def _release_unwritten_locks(self):
        """Pessimistic locks on keys that were locked but never written
        (e.g. SELECT FOR UPDATE rows left unchanged) release at commit."""
        lib = self.store._lib
        h = self.store._h
        for k in self.locked - set(self.mutations):
            lib.kv_pessimistic_rollback(h, k, len(k), self.start_ts)
        self.locked.clear()

    def rollback(self):
        lib = self.store._lib
        h = self.store._h
        for k in self.mutations:
            lib.kv_rollback(h, k, len(k), self.start_ts)
        for k in self.locked - set(self.mutations):
            lib.kv_pessimistic_rollback(h, k, len(k), self.start_ts)
        self.locked.clear()
        self.mutations.clear()


__all__ = ["KVStore", "Txn", "KVError", "DeadlockError", "LockWaitTimeout",
           "ERR_LOCKED", "ERR_WRITE_CONFLICT", "ERR_DEADLOCK",
           "ERR_LOCK_WAIT_TIMEOUT"]
