"""Framed message transport for the store RPC seam.

Reference analog: the gRPC/tikvpb surface of a store
(/root/reference/pkg/store/mockstore/unistore/tikv/server.go:45 —
KvGet/KvScan/Coprocessor service methods over protobuf).  This build's
wire format is a length-prefixed pickle frame over a local TCP socket:
the payloads are numpy column arrays and CopNode DAG trees, for which
pickle-protocol-5 is the natural zero-schema codec between trusted
processes of one cluster (the codec is isolated here so a protobuf
surface can replace it without touching callers).
"""

from __future__ import annotations

import pickle
import socket
import struct

_HDR = struct.Struct("<Q")
MAX_FRAME = 1 << 34


def send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=5)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket):
    n = _HDR.unpack(_recv_exact(sock, _HDR.size))[0]
    if n > MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))


__all__ = ["send_msg", "recv_msg"]
