"""Columnar shard store: the TPU-resident table representation.

Reference analog: a TiKV region holds a key range of rows; the coprocessor
scans rows from the badger LSM per request (unistore/tikv/dbreader).  The
TPU design columnarizes once at snapshot build time (the TiFlash
raft-learner columnarization role, SURVEY.md §7 "hard parts" #6): a table
snapshot is S shards of fixed capacity C, stored as stacked (S, C) numpy
arrays (host) and cached on-device as sharded jax arrays keyed by epoch —
the region-cache analog: epoch bumps invalidate device state
(pkg/store/copr/region_cache.go).

Shard boundaries are row-id ranges (the memcomparable ordering contract of
SURVEY.md §A.2 reduces to row order here; range shards by key come with the
KV path).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
import numpy as np

from ..chunk.column import Column, StringDict
from ..types import dtypes as dt
from ..parallel.mesh import sharded


def _pow2_at_least(n: int) -> int:
    c = 1
    while c < n:
        c <<= 1
    return c


@dataclass
class ColumnarSnapshot:
    """Immutable columnar snapshot of one table at an epoch."""
    names: list[str]
    dtypes: list[dt.DataType]
    columns: list[Column]              # full-length host columns
    epoch: int = 0
    n_shards: int = 8
    min_capacity: int = 1024
    # shard->store topology (store/placement.py).  None = plain even
    # split.  Mutating the placement (split/exclude) bumps its epoch and
    # invalidates the device cache, so the next dispatch re-fans-out
    # under the new topology (region-cache invalidation analog).
    placement: Any = None

    _device_cache: dict = field(default_factory=dict, repr=False)

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def dictionaries(self) -> dict[int, StringDict]:
        return {i: c.dictionary for i, c in enumerate(self.columns)
                if c.dictionary is not None}

    # ---------------- shard plan ---------------- #

    def shard_layout(self) -> tuple[int, int, np.ndarray]:
        """(n_shards, capacity, counts[n_shards]).  Rows are split evenly;
        capacity is a power-of-two bucket so jit programs recompile only on
        bucket changes (padding buckets, SURVEY.md §7 hard part #3)."""
        s = self.n_shards
        n = self.num_rows
        per = -(-n // s) if n else 0
        cap = max(_pow2_at_least(per), self.min_capacity)
        counts = np.minimum(np.maximum(n - np.arange(s) * per, 0), per)
        return s, cap, counts.astype(np.int64)

    def _even_ranges(self) -> list:
        s = self.n_shards
        n = self.num_rows
        per = -(-n // s) if n else 0
        return [(min(i * per, n), min(i * per + per, n)) for i in range(s)]

    def _placement_ranges(self, n_dev: int) -> list:
        """Slot row-ranges in device order (D*K grid, K = max shards on
        any device; short devices pad with empty slots)."""
        per_dev = self.placement.device_slots(n_dev)
        k = max((len(lst) for lst in per_dev), default=1) or 1
        ranges = []
        for lst in per_dev:
            ranges += [(s.lo, s.hi) for s in lst]
            ranges += [(0, 0)] * (k - len(lst))
        return ranges

    def _stacked_ranges(self, ranges) -> tuple[list, np.ndarray]:
        cap = max(_pow2_at_least(max((hi - lo for lo, hi in ranges),
                                     default=0)), self.min_capacity)
        counts = np.array([hi - lo for lo, hi in ranges], np.int64)
        cols = []
        for c in self.columns:
            if c.data.dtype == object:
                # wide (19-65 digit) decimal: host-only object ints.  The
                # planner refuses to fuse any expression touching it
                # (_device_supported), so its slot only keeps TableScan
                # offsets stable — upload a 1-byte placeholder.
                cols.append((np.zeros((len(ranges), cap), np.int8), None))
                continue
            # narrow physical width on device too: H2D bytes and HBM
            # footprint drop 2-8x; the expression compiler re-widens
            # inside the fused program where the logical width matters
            # (expr/compile.py _iwiden — XLA fuses the converts)
            phys = c.narrowed()
            data = np.zeros((len(ranges), cap), dtype=phys.dtype)
            valid = np.zeros((len(ranges), cap), dtype=bool)
            for i, (lo, hi) in enumerate(ranges):
                if hi > lo:
                    data[i, : hi - lo] = phys[lo:hi]
                    valid[i, : hi - lo] = c.validity[lo:hi]
            live = np.arange(cap)[None, :] < counts[:, None]
            all_valid = bool(valid[live].all())
            cols.append((data, None if all_valid else valid))
        return cols, counts

    def stacked_host(self) -> tuple[list, np.ndarray]:
        """Stacked (S, C) host arrays [(data, validity|None), ...] + counts
        (even layout; placement-aware stacking happens in _put)."""
        return self._stacked_ranges(self._even_ranges())

    # ---------------- device cache (region cache analog) ------------- #

    def _put(self, mesh) -> tuple[list, Any]:
        n_dev = mesh.devices.size
        if self.placement is not None:
            host_cols, counts = self._stacked_ranges(
                self._placement_ranges(n_dev))
        else:
            host_cols, counts = self.stacked_host()
        # the shard axis must divide the mesh: pad with empty shards
        # (count 0) so any shard plan runs on any mesh size
        s = len(counts)
        s_pad = -(-s // n_dev) * n_dev
        if s_pad != s:
            counts = np.concatenate([counts, np.zeros(s_pad - s, np.int64)])
            host_cols = [
                (np.concatenate([d, np.zeros((s_pad - s, d.shape[1]), d.dtype)]),
                 None if v is None else
                 np.concatenate([v, np.zeros((s_pad - s, v.shape[1]), bool)]))
                for d, v in host_cols]
        sh = sharded(mesh)
        dev = []
        for data, valid in host_cols:
            d = jax.device_put(data, sh)
            v = None if valid is None else jax.device_put(valid, sh)
            dev.append((d, v))
        dev_counts = jax.device_put(counts, sh)
        return dev, dev_counts

    def device_cols(self, mesh) -> tuple[list, Any]:
        # keyed on the mesh's stable FINGERPRINT (axis names + shape +
        # device ids), not id(mesh): the resident cache must survive a
        # Domain rebuilding its Mesh object over the same chips, and an
        # id() key could false-hit when the allocator reuses a dead
        # mesh's address (the same bug PR 2 fixed for sched task keys)
        from ..sched.task import mesh_fingerprint
        p_epoch = self.placement.epoch if self.placement is not None else -1
        key = (mesh_fingerprint(mesh), self.epoch, p_epoch)
        if key in self._device_cache:
            return self._device_cache[key]
        put = self._put(mesh)
        self._device_cache.clear()     # one epoch resident at a time
        self._device_cache[key] = put
        # lifetime contract (analysis/lifetime): these arrays are
        # PERSISTENT — reused across queries and pages — so a donating
        # launch over them is rejected at sched admission pre-trace.
        # The registration also credits the live HBM ledger (obs/hbm,
        # copgauge) with the resident footprint — array METADATA only,
        # never a device sync — and the ledger's weakref death callback
        # debits it when the cache entry is collected.
        from ..analysis.lifetime import register_resident
        nbytes = sum(
            int(d.nbytes) + (int(v.nbytes) if v is not None else 0)
            for d, v in put[0]) + int(put[1].nbytes)
        register_resident(put[1], nbytes=nbytes, fingerprint=key[0])
        return self._device_cache[key]

    def device_put_uncached(self, mesh) -> tuple[list, Any]:
        """Device placement WITHOUT the resident cache — the streaming
        (rows >> HBM) path places one batch at a time and lets it free as
        soon as its program consumed it (SURVEY.md §5.7 paging analog)."""
        return self._put(mesh)

    # ---------------- streaming batches (rows >> device memory) ------ #

    def device_bytes(self) -> int:
        """Stacked device footprint: S x capacity x (itemsize + validity),
        at the narrow physical width actually placed on device."""
        s, cap, _ = self.shard_layout()
        return s * cap * sum(c.narrowed().dtype.itemsize + 1
                             for c in self.columns)

    def view(self, lo: int, hi: int, min_capacity: int = 0) -> "ColumnarSnapshot":
        """Zero-copy row-range view (same shard count; forced capacity so
        every batch of a stream compiles to ONE program shape)."""
        return ColumnarSnapshot(
            self.names, self.dtypes,
            [c.slice(lo, hi) for c in self.columns], epoch=self.epoch,
            n_shards=self.n_shards,
            min_capacity=max(min_capacity, self.min_capacity))

    def row_batches(self, max_bytes: int) -> Optional[list]:
        """Split into row-range views whose device footprint fits
        max_bytes, or None when the whole snapshot already fits."""
        if max_bytes <= 0 or self.device_bytes() <= max_bytes or \
                not self.num_rows:
            return None
        # device_bytes() above already narrowed every column, so views
        # sliced off here inherit one shared physical width per column
        per_row = sum(c.narrowed().dtype.itemsize + 1 for c in self.columns)
        # pow2 capacity rounding can inflate a batch up to 2x: size for it
        rows = max(int(max_bytes // (2 * per_row)), self.n_shards)
        per_shard_cap = max(_pow2_at_least(-(-rows // self.n_shards)),
                            self.min_capacity)
        rows = per_shard_cap * self.n_shards
        return [self.view(lo, min(lo + rows, self.num_rows), per_shard_cap)
                for lo in range(0, self.num_rows, rows)]


def snapshot_from_columns(names: Sequence[str], cols: Sequence[Column],
                          n_shards: int = 8, epoch: int = 0,
                          min_capacity: int = 1024,
                          placement=None) -> ColumnarSnapshot:
    return ColumnarSnapshot(list(names), [c.dtype for c in cols], list(cols),
                            epoch=epoch, n_shards=n_shards,
                            min_capacity=min_capacity, placement=placement)


__all__ = ["ColumnarSnapshot", "snapshot_from_columns"]
