"""MVCC garbage collection worker.

Reference analog: pkg/store/gcworker (GCWorker gc_worker.go:68) — a
leader-elected background loop computes a safepoint (now - gc_life_time)
and asks the store to drop versions below it.  The native engine's
timestamps are logical (TSO counter), so the worker samples (wall
clock, ts) pairs each run and resolves the safepoint to the newest
sampled ts older than the life window.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional


class GCWorker:
    def __init__(self, kv, life_seconds: float = 600.0):
        self.kv = kv
        self.life_seconds = life_seconds
        self._samples: deque[tuple[float, int]] = deque(maxlen=512)
        self.last_safepoint = 0
        self.total_dropped = 0

    def run_once(self, now: Optional[float] = None) -> int:
        """One GC round: sample the TSO, resolve + apply the safepoint."""
        now = time.time() if now is None else now
        self._samples.append((now, self.kv.alloc_ts()))
        safepoint = 0
        for wall, ts in self._samples:
            if now - wall >= self.life_seconds:
                safepoint = max(safepoint, ts)
        if safepoint <= self.last_safepoint:
            return 0
        dropped = self.kv.gc(safepoint)
        self.last_safepoint = safepoint
        self.total_dropped += dropped
        return dropped


__all__ = ["GCWorker"]
