"""Distributed store client: SQL layer -> remote store processes.

Reference analog: pkg/kv/kv.go:316 — the kv.Client seam that lets the
SAME SQL/planner/executor stack run against an embedded store or remote
TiKV processes, with the region cache routing shards to stores and the
copIterator healing store failures (pkg/store/copr/region_cache.go,
coprocessor.go:337).  Here:

- ``RemoteCluster`` boots N ``tidb_tpu.store.server`` processes (the
  store role) and replicates tables to each (replica placement);
- ``RemoteCopClient`` implements the CopClient surface: it ships the
  serialized DAG + row ranges to each store owning shards (framed-pickle
  RPC), merges the returned PARTIAL aggregation states with the same
  merge/finalize code the device path uses, and falls back to the inner
  local client for shapes outside the remote scope (shuffle joins,
  windows, device-only strategies);
- a dead store surfaces as RegionError(STORE_UNAVAILABLE) -> the
  placement heals (shards re-home to surviving replicas) and the dispatch
  retries — the kill-a-store-mid-query path proven in
  tests/test_remote_store.py.
"""

from __future__ import annotations

import socket
import subprocess
import sys
import threading
import weakref
from typing import Optional

import numpy as np

from ..copr import dag as D
from ..copr.aggregate import (finalize, finalize_sorted,
                              merge_sorted_states, merge_states)
from .backoff import STORE_UNAVAILABLE, Backoffer, RegionError
from .client import CopClient, CopResult
from .placement import Placement
from .rpc import recv_msg, send_msg


class RemoteStore:
    """One store connection; socket failures surface as RegionErrors so
    the shared heal/retry discipline applies."""

    def __init__(self, store_id: int, port: int):
        self.store_id = store_id
        self.port = port
        self._sock: Optional[socket.socket] = None
        self._mu = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(("127.0.0.1", self.port),
                                         timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def request(self, msg):
        with self._mu:
            try:
                sock = self._conn()
                send_msg(sock, msg)
                return recv_msg(sock)
            except (ConnectionError, OSError) as exc:
                self.close()
                err = RegionError(STORE_UNAVAILABLE)
                err.store = self.store_id
                raise err from exc

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class RemoteCluster:
    """Boot + own N store server processes (mock-PD + store lifecycle)."""

    def __init__(self, n_stores: int = 2):
        import os
        self.procs: list = []
        self.stores: list[RemoteStore] = []
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for i in range(n_stores):
            p = subprocess.Popen(
                [sys.executable, "-m", "tidb_tpu.store.server"],
                stdout=subprocess.PIPE, env=env, text=True)
            line = p.stdout.readline().strip()
            assert line.startswith("PORT "), line
            self.procs.append(p)
            self.stores.append(RemoteStore(i, int(line.split()[1])))

    def kill_store(self, i: int) -> None:
        self.procs[i].kill()
        self.procs[i].wait()
        self.stores[i].close()

    def live_ids(self) -> list[int]:
        return [i for i, p in enumerate(self.procs) if p.poll() is None]

    def close(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for s in self.stores:
            s.close()


class _Unsupported(Exception):
    pass


class RemoteCopClient:
    """CopClient-compatible dispatcher against a RemoteCluster.

    Tables ship lazily: the first dispatch of a snapshot replicates its
    columns to every live store under a per-(snapshot, epoch) key; a
    remote placement (shards round-robined over store processes) routes
    each dispatch; anything the remote scope doesn't cover delegates to
    the inner local CopClient (`self.inner`)."""

    def __init__(self, cluster: RemoteCluster, mesh=None):
        self.cluster = cluster
        if mesh is None:
            # factory form: defer device acquisition until first dispatch
            # (library-safe init — same contract as CopClient)
            mesh = __import__("tidb_tpu.parallel.mesh",
                              fromlist=["get_mesh"]).get_mesh
        self.inner = CopClient(mesh)
        self._meta: dict = {}       # id(snap) -> _SnapMeta
        self._mu = threading.Lock()
        self.remote_dispatches = 0
        self.local_fallbacks = 0
        self.n_shards = 4

    # attribute surface (result cache counters, device_mem_cap, ...)
    # delegates to the inner client so ExecContext wiring is unchanged
    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ---------------- snapshot -> remote state ---------------- #

    def _snap_meta(self, snap):
        """Per-snapshot remote routing state.  The routing placement here
        is the remote region cache (shards -> store PROCESSES) and is
        private to this client; the snapshot's own placement stays the
        local device-slot map used by the inner fallback."""
        key = id(snap)
        with self._mu:
            ent = self._meta.get(key)
            if ent is not None and ent["ref"]() is snap \
                    and ent["epoch"] == snap.epoch:
                return ent
        table = f"t{key}_e{snap.epoch}"
        placement = Placement.even(snap.num_rows,
                                   max(self.n_shards,
                                       len(self.cluster.stores)))
        placement.rebalance(len(self.cluster.stores))
        ent = {"ref": weakref.ref(snap), "epoch": snap.epoch,
               "table": table, "placement": placement, "shipped": set()}
        with self._mu:
            self._meta[key] = ent
        return ent

    def _ship(self, ent, snap, store: RemoteStore):
        if store.store_id in ent["shipped"]:
            return
        store.request(("load", ent["table"], snap.epoch, snap.names,
                       snap.dtypes, snap.columns))
        ent["shipped"].add(store.store_id)

    def _store_ranges(self, placement: Placement):
        """store_id -> [(lo, hi), ...] over live shards."""
        by_store: dict = {}
        for sh in placement.shards:
            if sh.num_rows:
                by_store.setdefault(sh.store, []).append((sh.lo, sh.hi))
        return by_store

    # ---------------- dispatch ---------------- #

    def execute_agg(self, agg: D.Aggregation, snap, key_meta,
                    aux_cols=()) -> CopResult:
        if aux_cols:
            return self.inner.execute_agg(agg, snap, key_meta, aux_cols)
        try:
            return self._dispatch(
                snap, lambda ent, rc: self._agg_remote(agg, snap, ent,
                                                       key_meta, rc))
        except _Unsupported:
            with self._mu:
                self.local_fallbacks += 1
            return self.inner.execute_agg(agg, snap, key_meta, aux_cols)

    def execute_rows(self, root: D.CopNode, snap, out_dtypes,
                     dictionaries=None, aux_cols=()):
        if aux_cols:
            return self.inner.execute_rows(root, snap, out_dtypes,
                                           dictionaries, aux_cols)
        try:
            return self._dispatch(
                snap, lambda ent, rc: self._rows_remote(root, snap, ent,
                                                        out_dtypes,
                                                        dictionaries, rc))
        except _Unsupported:
            with self._mu:
                self.local_fallbacks += 1
            return self.inner.execute_rows(root, snap, out_dtypes,
                                           dictionaries, aux_cols)

    def _dispatch(self, snap, fn):
        from ..copr.coordinator import check_killed
        bo = Backoffer(max_sleep_ms=5000.0)
        # batch-cop partial retry (copr/batch_coprocessor.go): stores
        # whose batched task set already succeeded this round are not
        # re-executed after another store's failure heals the placement —
        # only moved/failed range sets re-dispatch
        round_cache: dict = {}
        while True:
            check_killed()
            ent = self._snap_meta(snap)
            self._preflight_liveness(ent)
            try:
                return fn(ent, round_cache)
            except RegionError as e:
                bo.backoff(e.kind, e)
                ent["placement"].heal(e)
                ent["shipped"].discard(getattr(e, "store", None))

    def _preflight_liveness(self, ent) -> None:
        """Store liveness probe BEFORE dispatch (copr/mpp_probe.go
        analog): a store whose process died is excluded from the routing
        placement up front, so the fan-out never pays a failed round
        against it."""
        live = set(self.cluster.live_ids())
        dead = {sh.store for sh in ent["placement"].shards
                if sh.num_rows and sh.store < len(self.cluster.stores)
                and sh.store not in live}
        for sid in dead:
            ent["placement"].exclude_store(sid)
            self.preflight_exclusions = getattr(
                self, "preflight_exclusions", 0) + 1

    def _per_store(self, ent, snap, build_msg, round_cache=None):
        """Fan a request out to every store owning live shards, ONE
        batched request per store covering all its ranges (the
        batch-coprocessor discipline, copr/batch_coprocessor.go).  A
        store failure mid-fan-out aborts this round with its RegionError
        (the retry loop heals and re-fans-out); `round_cache` carries the
        successful (store, ranges) results across those retries so only
        moved/failed task sets re-execute."""
        import concurrent.futures as cf
        by_store = self._store_ranges(ent["placement"])
        if not by_store:
            raise _Unsupported()

        def one(sid, ranges):
            key = (sid, tuple(map(tuple, ranges)))
            if round_cache is not None and key in round_cache:
                return round_cache[key]
            if sid >= len(self.cluster.stores):
                raise _Unsupported()   # every real store excluded
            store = self.cluster.stores[sid]
            self._ship(ent, snap, store)
            resp = store.request(build_msg(ent["table"], ranges))
            if resp[0] == "err":
                if resp[1] == "stale_epoch":
                    ent["shipped"].discard(sid)
                    err = RegionError(STORE_UNAVAILABLE)
                    err.store = sid
                    raise err
                raise _Unsupported()
            if round_cache is not None:
                round_cache[key] = resp[1]
            return resp[1]
        with self._mu:
            self.remote_dispatches += 1
        items = sorted(by_store.items())
        if len(items) == 1:
            return [one(*items[0])]
        with cf.ThreadPoolExecutor(max_workers=len(items)) as ex:
            futs = [ex.submit(one, sid, rngs) for sid, rngs in items]
            return [f.result() for f in futs]

    def _agg_remote(self, agg, snap, ent, key_meta,
                    round_cache=None) -> CopResult:
        per_store = self._per_store(
            ent, snap,
            lambda table, ranges: ("exec_agg", table, snap.epoch, agg,
                                   ranges), round_cache)
        if agg.strategy in D.HOST_MERGE_STRATEGIES:
            merged = merge_sorted_states(agg, per_store)
            key_cols, agg_cols = finalize_sorted(agg, merged, key_meta)
        else:
            merged = merge_states(per_store)
            key_cols, agg_cols = finalize(agg, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    def _rows_remote(self, root, snap, ent, out_dtypes, dictionaries,
                     round_cache=None):
        from ..chunk.column import Column
        per_store = self._per_store(
            ent, snap,
            lambda table, ranges: ("exec_rows", table, snap.epoch, root,
                                   ranges, tuple(out_dtypes)), round_cache)
        cols = [Column.concat([st[j] for st in per_store])
                for j in range(len(out_dtypes))]
        if dictionaries:
            for j, d in dictionaries.items():
                if j < len(cols) and cols[j].dictionary is None:
                    cols[j].dictionary = d
        return cols


__all__ = ["RemoteCluster", "RemoteCopClient", "RemoteStore"]
