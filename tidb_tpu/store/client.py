"""CopClient: dispatch coprocessor DAGs over the shard store.

Reference analog: pkg/store/copr CopClient.Send → buildCopTasks →
copIterator worker pool → per-region RPCs, with backoff/paging/retry
(coprocessor.go:83-1353).  Here the fan-out is one SPMD program
(parallel/spmd.py); what remains of the client is:

- program-cache lookup per (dag digest, shard layout) — the cop cache seam,
- the paging loop for row-returning plans: run with a capacity guess,
  check reported true counts, double and re-run on overflow
  (kv.Request.Paging grow-from-min analog, SURVEY.md §5.7),
- epoch validation: snapshots carry an epoch; a concurrent write bumps it
  and the device cache invalidates (region epoch-not-match analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np

from ..chunk.column import Column
from ..copr import dag as D
from ..copr.aggregate import (GroupKeyMeta, finalize, finalize_sorted,
                              merge_sorted_states, merge_states)
from ..faults import plan as _faults
from ..faults.breaker import LaunchQuarantinedError
from ..obs.trace import flag as _obs_flag
from ..obs.trace import span as _obs_span
from ..parallel.spmd import get_sharded_program
from .columnar import ColumnarSnapshot, _pow2_at_least

# initial fraction of table rows assumed to survive a row-returning plan
INITIAL_SELECTIVITY = 4  # capacity = max(rows/shards/4, 1024)

# SORT-agg group-table sizing: first guess when the planner supplies no
# NDV estimate, and the regrow ceiling
DEFAULT_GROUP_CAPACITY = 4096


@dataclass
class CopResult:
    """Decoded result of one pushdown: either agg groups or row columns."""
    columns: list[Column]
    key_columns: list[Column]


class CopClient:
    def __init__(self, mesh):
        # ``mesh`` may be a jax.sharding.Mesh or a zero-arg callable
        # returning one.  The callable form defers jax backend
        # initialization until a query actually needs device execution:
        # under a pending TPU grant (axon UNAVAILABLE-until-timeout),
        # constructing a Session and running host-only statements must
        # not block on device acquisition (library-safe init).
        from jax.sharding import Mesh as _Mesh
        import threading as _threading
        is_factory = callable(mesh) and not isinstance(mesh, _Mesh)
        self._mesh = None if is_factory else mesh
        self._mesh_fn = mesh if is_factory else None
        self._mesh_mu = _threading.Lock()
        # paging feedback: dag digest -> EWMA of observed per-shard live
        # fraction; replaces the constant first guess with the reference's
        # adaptive min->max paging discipline (pkg/util/paging) fed by
        # actual run results instead of a fixed growth schedule.  LRU-capped:
        # digests embed predicate constants, so point-query workloads would
        # otherwise grow it without bound.
        from collections import OrderedDict
        self._page_feedback: OrderedDict[int, float] = OrderedDict()
        self._page_feedback_cap = 512
        self.last_page_iters = 0       # observability: regrow passes
        # counters above double as status-route payload (sched_stats
        # "client" section): assignments happen under _stat_mu so
        # concurrent connection threads never lose updates
        self._stat_mu = _threading.Lock()
        # failure detection/recovery (copIterator backoff-and-retry):
        # transient dispatch errors retry under a typed backoff budget
        self.retry_budget_ms = 5000.0
        # streaming threshold: tables whose stacked device footprint
        # exceeds this stream through HBM in double-buffered batches
        # (SURVEY.md §5.7; 0 = never stream).  Overridable per-client and
        # via TIDB_TPU_DEVICE_MEM_CAP.
        import os
        self.device_mem_cap = int(
            os.environ.get("TIDB_TPU_DEVICE_MEM_CAP", "0") or 0)
        # last_retries is best-effort observability (per-dispatch); the
        # failpoint queue is lock-guarded since the client is shared by
        # every connection thread
        self.last_retries = 0
        self.last_heals = 0    # topology mutations by the retry loop
        import threading
        self._fp_mu = threading.Lock()
        self._failpoints: list = []    # injected RegionErrors (tests/chaos)
        # _page_feedback is shared across connection threads: guard its
        # get/assign/move_to_end/popitem sequence (ADVICE r2: a concurrent
        # eviction between get and move_to_end raised KeyError)
        self._pf_mu = threading.Lock()
        # coprocessor RESULT cache (copr/coprocessor_cache.go analog):
        # key = (dag digest, snapshot epoch, placement epoch, shard
        # layout); a table write creates a new snapshot + epoch, so stale
        # entries never hit and the LRU ages them out.  Entries hold a
        # weakref to their snapshot: a hit must come from the SAME
        # snapshot object (guards id()/epoch reuse).
        self._result_cache: OrderedDict = OrderedDict()
        self._result_cache_cap = 64
        self._rc_max_bytes = 4 << 20   # only small responses, like the ref
        self._rc_mu = threading.Lock()
        self.result_cache_hits = 0
        self.result_cache_misses = 0
        # device admission scheduler (sched/): every launch onto the mesh
        # goes through a bounded weighted-fair queue that coalesces
        # concurrent compatible tasks.  -1 = scheduler defaults; queue
        # depth 0 (or TIDB_TPU_SCHED_DISABLE=1) bypasses admission.
        self.sched_enable = os.environ.get(
            "TIDB_TPU_SCHED_DISABLE", "") != "1"
        self.sched_queue_depth = -1
        self.sched_max_coalesce = -1
        # cross-query fusion + adaptive micro-batch window knobs
        # (tidb_tpu_sched_fusion / tidb_tpu_sched_window_us); None =
        # scheduler defaults (fusion on, adaptive window)
        self.sched_fusion = None
        self.sched_window_us = None
        # per-mesh HBM admission budget (tidb_tpu_sched_hbm_budget):
        # None = keep scheduler state, -1 = auto from device memory
        # stats, 0 = unlimited, >0 = bytes (analysis/copcost gate)
        self.sched_hbm_budget = None
        # resource control plane (rc/): RU-bucket enforcement at the
        # drain (tidb_tpu_rc_enable) and the bounded overdraft
        # (tidb_tpu_rc_overdraft_ru); None = keep scheduler state
        self.rc_enable = None
        self.rc_overdraft = None
        # copmeter closed-loop cost calibration
        # (tidb_tpu_cost_calibration): None = keep scheduler state
        self.calibration = None
        # copgauge live HBM ledger + measured watermarks + roofline
        # (tidb_tpu_hbm_ledger): None = keep scheduler state
        self.hbm_ledger = None
        # coplace coordination plane (tidb_tpu_pd): None = keep
        # scheduler state (the per-Domain coordinator rides session/;
        # this knob only arms the scheduler-side pd hooks)
        self.pd_enable = None
        self._sched_obj = None
        # graceful degradation (faultline; tidb_tpu_sched_host_fallback):
        # a digest quarantined by the launch circuit breaker falls back
        # to the host oracle path when the plan has a host-executable
        # shape — slow-but-correct instead of unavailable (the Flare
        # unsupported-path degradation pattern)
        self.host_fallback = True
        self.degraded = 0      # statements served by that fallback
        # copmeter OOM recovery (faults.is_oom_error): a launch that
        # exhausted device memory retries through the recovery ladder —
        # streamed half-size batches, then the host oracle — instead of
        # failing the statement or charging the poison breaker
        self.oom_recovered = 0
        # copscope (obs/): the last launch's per-link transfer
        # breakdown, stashed per STATEMENT THREAD by _note_sched so the
        # device->host transfer span that follows the launch can carry
        # the shardflow {intra, ici, dci} attribution without re-costing
        self._obs_tl = threading.local()

    @property
    def mesh(self):
        if self._mesh is None:
            with self._mesh_mu:     # concurrent first dispatches resolve once
                if self._mesh is None:
                    self._mesh = self._mesh_fn()
        return self._mesh

    @mesh.setter
    def mesh(self, value):
        self._mesh = value

    # -- dispatch retry seam (pkg/store/copr backoff loop analog) ------ #

    def inject_failures(self, kind, n: int = 1, shard=None,
                        store=None) -> None:
        """Failpoint: the next n dispatches raise a RegionError of `kind`
        before touching the device (chaos/testing seam, the reference's
        failpoint.Inject on rpc errors).  `shard`/`store` name the failing
        topology element so the retry can heal it (re-split / exclude)."""
        from .backoff import RegionError
        with self._fp_mu:
            for _ in range(n):
                e = RegionError(kind)
                e.shard = shard
                e.store = store
                self._failpoints.append(e)

    def _next_failpoint(self):
        with self._fp_mu:
            return self._failpoints.pop(0) if self._failpoints else None

    def _retry(self, fn, snap: "ColumnarSnapshot" = None):
        """Backoff loop that HEALS the topology before retrying: a
        RegionError naming a shard/store mutates the snapshot's placement
        (split the shard / exclude the store, placement.heal), bumping its
        epoch so the retry dispatches a DIFFERENT fan-out — the
        copr handleTask re-split discipline (coprocessor.go:337,:1308),
        not an identical re-run."""
        from ..copr.coordinator import check_killed
        from .backoff import DEVICE_FAILED, Backoffer, RegionError
        bo = Backoffer(max_sleep_ms=self.retry_budget_ms)
        retries = 0
        while True:
            check_killed()    # KILL QUERY cancels in-flight dispatch loops
            try:
                fp = self._next_failpoint()
                if fp is not None:
                    raise fp
                _faults.check("dispatch")   # faultline store-dispatch seam
                with self._stat_mu:
                    self.last_retries = retries
                return fn()
            except RegionError as e:
                bo.backoff(e.kind, e)
                if snap is not None and snap.placement is not None:
                    healed = snap.placement.heal(e)
                    if healed:
                        with self._stat_mu:
                            self.last_heals += 1
                retries += 1
            except _faults.TransientFault as e:
                # injected retryable dispatch/transfer fault: same typed
                # budget, DEVICE_FAILED curve; poison faults propagate
                bo.backoff(DEVICE_FAILED, e)
                retries += 1

    # ------------------------------------------------------------- #
    # device launch seam: admission scheduler (sched/)
    # ------------------------------------------------------------- #

    def _scheduler(self):
        """This mesh's admission scheduler; None = direct dispatch."""
        if not self.sched_enable or self.sched_queue_depth == 0:
            return None
        s = self._sched_obj
        if s is None:
            from ..sched import scheduler_for
            s = self._sched_obj = scheduler_for(self.mesh)
        s.configure(
            self.sched_queue_depth if self.sched_queue_depth > 0 else None,
            self.sched_max_coalesce if self.sched_max_coalesce > 0
            else None,
            fusion=self.sched_fusion,
            window_us=self.sched_window_us,
            hbm_budget=self.sched_hbm_budget,
            rc_enable=self.rc_enable,
            rc_overdraft=self.rc_overdraft,
            calibration=self.calibration,
            hbm_ledger=self.hbm_ledger,
            pd_enable=self.pd_enable)
        return s

    def _client_stats(self) -> dict:
        with self._stat_mu:
            return {"last_page_iters": self.last_page_iters,
                    "last_retries": self.last_retries,
                    "last_heals": self.last_heals,
                    "degraded": self.degraded,
                    "oom_recovered": self.oom_recovered,
                    "host_fallback": self.host_fallback}

    def sched_stats(self) -> dict:
        """Status-API introspection; never resolves a pending mesh."""
        with self._rc_mu:
            rc = {"result_cache_hits": self.result_cache_hits,
                  "result_cache_misses": self.result_cache_misses}
        client = {**self._client_stats(), **rc}
        from ..compilecache import compile_cache
        cc = {"compile_cache": compile_cache().stats()}
        if self._sched_obj is None:
            return {"enabled": self.sched_enable, "started": False,
                    "client": client, **cc}
        return {"enabled": self.sched_enable, "started": True,
                "client": client, **cc, **self._sched_obj.stats()}

    def _transfer_attrs(self) -> dict:
        """Per-link attrs for the NEXT transfer span on this statement
        thread (stashed by _note_sched from the served task's
        calibrated LaunchCost — shardflow's typed-link split)."""
        bd = getattr(self._obs_tl, "breakdown", None)
        self._obs_tl.breakdown = None
        if not bd or not (bd[0] or bd[1] or bd[2]):
            return {}
        return {"intra_bytes": bd[0], "ici_bytes": bd[1],
                "dci_bytes": bd[2]}

    def _note_sched(self, task) -> None:
        if task.cost is not None:
            self._obs_tl.breakdown = task.cost.transfer_breakdown
        from ..copr.coordinator import QUERY_HANDLE
        h = QUERY_HANDLE.get()
        if h is not None:
            # rus_charged is set at batch admission (before finish) and
            # compile_ns/compile_miss before finish too, so the waiter
            # always observes them; device_ns is attributed post-serve
            # and stays a scheduler-side stat
            h.note_sched(task.wait_ns, task.coalesced, task.fused,
                         rus=task.rus_charged, retried=task.retries,
                         compile_ns=task.compile_ns,
                         compile_miss=task.compile_miss,
                         hbm_predicted=task.hbm_predicted,
                         hbm_measured=task.hbm_measured)

    def _launch(self, dag, cols, counts, aux, row_capacity: int = 0,
                donate: bool = False):
        """One device launch of a sharded cop program, routed through the
        admission queue: the scheduler resolves the compiled program (so
        concurrent identical tasks share ONE compile + launch) and may
        coalesce this task with compatible ones from other sessions.
        ``donate=True`` marks the inputs launch-unique (streamed HBM
        batches): the DonationPlan-derived program variant aliases them
        into outputs (analysis/lifetime) — never set it for snapshot
        residents or regrow-loop inputs.  Returns (program, out)."""
        sched = self._scheduler()
        if sched is None:
            prog = get_sharded_program(dag, self.mesh, row_capacity,
                                       donate=donate)
            return prog, prog(cols, counts, aux)
        from ..sched import CopTask
        est = 0
        if cols:
            s, c = cols[0][0].shape[:2]
            est = s * c
        # copscope: the dispatch span is the parent every scheduler-
        # thread span (queue/compile/launch/retry) stitches under — the
        # CopTask captures the child TraceCtx at construction
        with _obs_span("cop.dispatch"):
            t = sched.submit(CopTask.structured(
                dag, self.mesh, row_capacity, cols, counts, tuple(aux),
                est_rows=est, donate=donate))
            try:
                return t.wait()
            finally:
                self._note_sched(t)

    def _launch_opaque(self, fn, est_rows: int = 0):
        """Admission-controlled launch of a program with a non-standard
        signature (shuffle/window): fair-ordered, never coalesced."""
        sched = self._scheduler()
        if sched is None:
            return fn()
        from ..sched import CopTask
        with _obs_span("cop.dispatch", opaque=True):
            t = sched.submit(CopTask.opaque(fn, est_rows=est_rows))
            try:
                return t.wait()
            finally:
                self._note_sched(t)

    # ------------------------------------------------------------- #

    def execute_agg(self, agg: D.Aggregation, snap: ColumnarSnapshot,
                    key_meta: list[GroupKeyMeta], aux_cols=()) -> CopResult:
        key = None
        if not aux_cols:      # aux (join builds) = host inputs, not cacheable
            key = self._rc_key(agg, snap)
            hit = self._rc_get(key, snap)
            if hit is not None:
                return hit
        try:
            res = self._retry(lambda: self._execute_agg_once(
                agg, snap, key_meta, aux_cols), snap=snap)
        except LaunchQuarantinedError as err:
            # OPEN breaker: the device program keeps failing — degrade
            # to the host oracle where the plan shape allows it
            _obs_flag("quarantined")
            res = self._degraded_agg(agg, snap, key_meta, aux_cols, err)
        except Exception as err:
            # copmeter OOM recovery: a launch that exhausted device
            # memory (injected MemoryFault or a real RESOURCE_EXHAUSTED)
            # walks the recovery ladder; everything else re-raises
            if not _faults.is_oom_error(err):
                raise
            res = self._oom_degraded_agg(agg, snap, key_meta, aux_cols,
                                         err)
        if key is not None:
            self._rc_put(key, snap, res)
        return res

    def _oom_degraded_agg(self, agg: D.Aggregation, snap: ColumnarSnapshot,
                          key_meta, aux_cols, err) -> CopResult:
        """OOM recovery ladder (copmeter): the scheduler already bumped
        the digest's memory correction and demuxed any fused launch;
        a SOLO launch that still did not fit lands here.  Try streamed
        half-size batches first (the launch that OOM'd resident runs
        as >= 2 HBM-streamed batches), then the host oracle — results
        stay bit-identical to the uncontended run on every rung.  Plans
        with neither shape re-raise the original error."""
        _obs_flag("oom")
        if not aux_cols:
            half = max(snap.device_bytes() // 2, 1)
            batches = snap.row_batches(half)
            if batches and len(batches) >= 2:
                try:
                    if agg.strategy in D.HOST_MERGE_STRATEGIES:
                        res = self._stream_sort_agg(agg, batches, key_meta)
                    else:
                        res = self._stream_dense_agg(agg, batches, key_meta)
                    with self._stat_mu:
                        self.oom_recovered += 1
                    return res
                except Exception as e:  # noqa: BLE001 - recovery ladder:
                    # a half-size stream may STILL exhaust memory (or
                    # trip the same injected fault) — fall through to
                    # the host oracle; anything non-OOM re-raises
                    if not _faults.is_oom_error(e):
                        raise
        res = self._degraded_agg(agg, snap, key_meta, aux_cols, err)
        with self._stat_mu:
            self.oom_recovered += 1
        return res

    def _degraded_agg(self, agg: D.Aggregation, snap: ColumnarSnapshot,
                      key_meta, aux_cols, err) -> CopResult:
        """Graceful degradation for a quarantined program digest
        (faultline): serve the aggregation from the host oracle path
        (copr/hostagg) — slow-but-correct instead of unavailable, the
        Flare compiled-path-falls-back-to-interpreter pattern.  Plans
        without a host-executable shape re-raise the quarantine error
        so the client sees the structured failure."""
        res = None
        if self.host_fallback and not aux_cols:
            if agg.strategy in D.HOST_MERGE_STRATEGIES:
                res = self._host_sort_agg(agg, snap, key_meta)
            else:
                from ..copr.hostagg import host_dense_agg
                states = host_dense_agg(agg, snap)
                if states is not None:
                    merged = merge_states([states])
                    key_cols, agg_cols = finalize(agg, merged, key_meta)
                    res = CopResult(agg_cols, key_cols)
        if res is None:
            raise err
        _obs_flag("degraded")
        with self._stat_mu:
            self.degraded += 1
        from ..utils.metrics import global_registry
        global_registry().counter(
            "tidb_tpu_sched_degraded_total",
            "statements served by the host oracle after a launch "
            "quarantine").inc()
        from ..copr.coordinator import QUERY_HANDLE
        h = QUERY_HANDLE.get()
        if h is not None:
            h.note_degraded()
        return res

    def _rc_key(self, dag, snap: ColumnarSnapshot):
        p_epoch = snap.placement.epoch if snap.placement is not None else -1
        return (D.dag_digest(dag), snap.epoch, p_epoch, snap.num_rows,
                snap.n_shards)

    def _rc_get(self, key, snap) -> Optional[CopResult]:
        with self._rc_mu:
            ent = self._result_cache.get(key)
            if ent is not None and ent[0]() is snap:
                self._result_cache.move_to_end(key)
                self.result_cache_hits += 1
                return ent[1]
            # miss counter bumps under the same lock: the client is
            # shared by every connection thread and an unguarded
            # read-modify-write here loses updates under load
            self.result_cache_misses += 1
        return None

    def _rc_put(self, key, snap, res: CopResult) -> None:
        import weakref
        nbytes = sum(c.data.nbytes for c in res.columns + res.key_columns
                     if hasattr(c.data, "nbytes"))
        if nbytes > self._rc_max_bytes:
            return
        with self._rc_mu:
            self._result_cache[key] = (weakref.ref(snap), res)
            self._result_cache.move_to_end(key)
            while len(self._result_cache) > self._result_cache_cap:
                self._result_cache.popitem(last=False)

    def _execute_agg_once(self, agg: D.Aggregation, snap: ColumnarSnapshot,
                          key_meta: list[GroupKeyMeta],
                          aux_cols=()) -> CopResult:
        if agg.strategy in D.HOST_MERGE_STRATEGIES:
            # SORT and SEGMENT share one dispatch path: per-device group
            # tables, host final merge, capacity regrow (the SEGMENT
            # knob is its pow2 bucket space instead of group_capacity)
            if not aux_cols and self._platform() == "cpu":
                res = self._host_sort_agg(agg, snap, key_meta)
                if res is not None:
                    return res
            batches = self._stream_batches(agg, snap)
            if batches is not None:
                return self._stream_sort_agg(agg, batches, key_meta)
            cols, counts = snap.device_cols(self.mesh)
            return self._execute_sort_agg(agg, cols, counts, key_meta,
                                          aux_cols)
        if not aux_cols and self._platform() == "cpu":
            # CPU engine choice for DENSE/SCALAR too: scatter-add limbs
            # beat the XLA-CPU program ~3x (hostagg.host_dense_agg)
            from ..copr.hostagg import host_dense_agg
            states = host_dense_agg(agg, snap)
            if states is not None:
                merged = merge_states([states])
                key_cols, agg_cols = finalize(agg, merged, key_meta)
                return CopResult(agg_cols, key_cols)
        batches = self._stream_batches(agg, snap)
        if batches is not None:
            return self._stream_dense_agg(agg, batches, key_meta)
        cols, counts = snap.device_cols(self.mesh)
        for _ in range(8):
            prog, out = self._launch(agg, cols, counts, tuple(aux_cols))
            if prog.has_extras:
                out, extras = out
                grown = self._grown_join_dag(agg, extras)
                if grown is not None:
                    agg = grown
                    continue
            with _obs_span("cop.transfer", **self._transfer_attrs()):
                states = jax.device_get(out)
            # faultline transfer/host-merge seam, keyed by the digest
            _faults.check("transfer", D.dag_digest(agg))
            break
        else:
            raise RuntimeError("join-capacity regrow did not converge")
        with _obs_span("cop.host_merge",
                       kind="per-device" if prog.host_merge else "root"):
            if prog.host_merge:
                # min/max partials come back per-device (leading axis);
                # the final merge is the host's root-worker role
                per_dev = self._split_devices(states)
                merged = merge_states(per_dev)
            else:
                merged = merge_states([states])
            key_cols, agg_cols = finalize(agg, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    def _platform(self) -> str:
        return self.mesh.devices.reshape(-1)[0].platform

    # ------------------------------------------------------------- #
    # streaming: tables bigger than device memory (SURVEY.md §5.7)
    # ------------------------------------------------------------- #

    def _stream_batches(self, dag, snap: ColumnarSnapshot, aux_cols=()):
        """Row-range batch views when the snapshot exceeds the device
        memory cap; None = run resident.  Plans with expanding joins keep
        the resident path (their capacity-regrow loop re-runs programs)."""
        if not self.device_mem_cap or aux_cols \
                or D.find_expand_join(dag) is not None:
            return None
        return snap.row_batches(self.device_mem_cap)

    def _stream_states(self, agg, batches):
        """Double-buffered dispatch: batch k+1's H2D transfer overlaps
        batch k's compute (jax dispatch is async; nothing blocks until the
        final device_get).  The paging/double-buffer analog of
        kv.Request.Paging (SURVEY.md §5.7)."""
        from ..copr.coordinator import check_killed
        outs = []
        nxt = batches[0].device_put_uncached(self.mesh)
        for i in range(len(batches)):
            check_killed()   # cancellation between streamed HBM batches
            cols, counts = nxt
            # uncached batch, launched exactly once: EPHEMERAL in the
            # lifetime taxonomy — the donating program variant lets XLA
            # alias the batch into its outputs, so the steady-state
            # paging loop stops holding input + output + temp at once
            _prog, out = self._launch(agg, cols, counts, (), donate=True)
            outs.append(out)
            if i + 1 < len(batches):
                nxt = batches[i + 1].device_put_uncached(self.mesh)
            del cols, counts     # free the batch once its program consumed it
        with _obs_span("cop.transfer", batches=len(outs)):
            return [jax.device_get(o) for o in outs]

    def _stream_dense_agg(self, agg, batches, key_meta) -> CopResult:
        states_list = self._stream_states(agg, batches)
        merged = merge_states(states_list)
        key_cols, agg_cols = finalize(agg, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    @staticmethod
    def _warm_cap(dag, needed: int) -> int:
        """copforge regrow/paging re-entry seam: prefer a capacity the
        warm program pool (or the persisted manifest) already compiled
        for this plan FAMILY over the minimal pow2 step — re-entering
        at a warm capacity serves from the pool instead of re-tracing.
        Bounded (<= 4x need) so a warm-but-huge buffer never wins."""
        from ..analysis.compilekey import family_digest
        from ..compilecache import compile_cache
        warm = compile_cache().warm_capacity(family_digest(dag), needed)
        return warm if warm is not None else needed

    @staticmethod
    def _with_capacity(agg: D.Aggregation, cap: int) -> D.Aggregation:
        """Rebuild a host-merged aggregation with a new per-device group
        table capacity: SORT sizes group_capacity directly (pow2, so the
        capacity lands in a shared fusion shape class), SEGMENT/SCATTER
        their power-of-two radix bucket space (the regrow knob)."""
        import dataclasses
        if agg.strategy in D.RADIX_STRATEGIES:
            return dataclasses.replace(agg,
                                       num_buckets=_pow2_at_least(cap))
        return dataclasses.replace(agg,
                                   group_capacity=_pow2_at_least(cap))

    def _stream_sort_agg(self, agg, batches, key_meta) -> CopResult:
        cap = self._warm_cap(agg, agg.state_capacity
                             or DEFAULT_GROUP_CAPACITY)
        per_dev_all = []
        for b in batches:
            cols, counts = b.device_put_uncached(self.mesh)
            for _ in range(10):
                sized = self._with_capacity(agg, cap)
                _prog, out = self._launch(sized, cols, counts, ())
                states = jax.device_get(out)
                true_ng = int(np.max(np.asarray(states["__ngroups__"])))
                if true_ng <= cap:
                    break
                cap = self._warm_cap(agg, _pow2_at_least(true_ng))
            else:
                raise RuntimeError("group-capacity regrow did not converge")
            per_dev_all.extend(self._split_devices(states))
            del cols, counts
        sized = self._with_capacity(agg, cap)
        merged = merge_sorted_states(sized, per_dev_all)
        key_cols, agg_cols = finalize_sorted(sized, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    def _host_sort_agg(self, agg: D.Aggregation, snap: ColumnarSnapshot,
                       key_meta) -> Optional[CopResult]:
        """CPU engine choice for high-NDV group-by.

        The reference's CPU answer is a hash table (parallel HashAgg,
        pkg/executor/aggregate/agg_hash_executor.go:94); XLA's TPU-shaped
        sort+scatter SORT program measured 56x SLOWER than a host
        np.unique on CPU (VERDICT r2 #2).  On a CPU mesh the coprocessor
        therefore runs unbounded-NDV group-by as a host unique + segment
        reduction over the snapshot columns — the per-platform strategy
        split precedented by the dense-reduce path (copr/exec._reduce).
        Returns None when the DAG shape isn't the scan/filter/project
        chain this path handles (falls back to the device program).
        """
        from ..copr.hostagg import host_sort_agg
        states = host_sort_agg(agg, snap)
        if states is None:
            return None
        # single host table: groups are already unique — the cross-device
        # re-group of merge_sorted_states would be a no-op
        merged = {k: v for k, v in states.items() if k != "__ngroups__"}
        key_cols, agg_cols = finalize_sorted(agg, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    def _grown_join_dag(self, dag, extras) -> Optional[D.CopNode]:
        """If the expanding join overflowed its capacity, return the DAG
        rebuilt with a big-enough capacity; None when it fits (the join
        half of the paging grow-from-min discipline)."""
        need = int(np.max(np.asarray(jax.device_get(extras["join_total"]))))
        node = D.find_expand_join(dag)
        if node is not None and need > node.out_capacity:
            return D.rewrite_expand_capacity(dag, _pow2_at_least(need))
        return None

    def _split_devices(self, states):
        n_dev = len(self.mesh.devices.reshape(-1))
        return [jax.tree_util.tree_map(lambda a: np.asarray(a)[d], states)
                for d in range(n_dev)]

    def _execute_sort_agg(self, agg, cols, counts, key_meta,
                          aux_cols) -> CopResult:
        """High-NDV group-by (SORT / SEGMENT / SCATTER): per-device
        partition + segment-reduce group tables, regrown when a device
        sees more distinct groups than capacity (the paging grow-from-
        min analog), then host final merge."""
        # prehash hoist (copr/radix): the avalanche key hash does not
        # depend on the bucket space, so for radix strategies it is
        # computed ONCE by a tiny sharded hash program and appended as
        # an extra scan column — every regrow re-entry (a fresh program
        # at a bigger num_buckets) reuses the hashed keys instead of
        # re-hashing the key tuple per capacity
        if agg.strategy in D.RADIX_STRATEGIES and not aux_cols \
                and not agg.prehashed:
            from ..copr import radix
            pre = radix.prehash_plan(agg, len(cols))
            if pre is not None:
                hashed_dag, leaf_scan = pre
                hprog = radix.get_hash_program(leaf_scan, agg.group_by,
                                               self.mesh)
                hv = self._launch_opaque(lambda: hprog(cols, counts))
                cols = list(cols) + [(hv, None)]
                agg = hashed_dag
        cap = self._warm_cap(agg, agg.state_capacity
                             or DEFAULT_GROUP_CAPACITY)
        for _ in range(10):
            sized = self._with_capacity(agg, cap)
            prog, out = self._launch(sized, cols, counts, tuple(aux_cols))
            if prog.has_extras:
                out, extras = out
                grown = self._grown_join_dag(sized, extras)
                if grown is not None:
                    agg = grown
                    continue
            with _obs_span("cop.transfer", **self._transfer_attrs()):
                states = jax.device_get(out)
            true_ng = int(np.max(np.asarray(states["__ngroups__"])))
            if true_ng <= cap:
                sized = self._with_capacity(agg, cap)
                break
            cap = self._warm_cap(agg, _pow2_at_least(true_ng))
        else:
            raise RuntimeError("group-capacity regrow did not converge")
        with _obs_span("cop.host_merge", kind="sorted"):
            per_dev = self._split_devices(states)
            merged = merge_sorted_states(sized, per_dev)
            key_cols, agg_cols = finalize_sorted(sized, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    # ------------------------------------------------------------- #
    # repartition (shuffle) join — parallel/shuffle.py
    # ------------------------------------------------------------- #

    def _shuffle_initial_caps(self, lsnap, rsnap, row_cap: int):
        from ..parallel.shuffle import ShuffleCaps
        n_dev = len(self.mesh.devices.reshape(-1))
        # expected send-bucket rows under a uniform hash: local/n_dev;
        # 2x headroom, grown from the reported true maxima on overflow
        lcap = _pow2_at_least(
            max(2 * lsnap.num_rows // max(n_dev * n_dev, 1) + 1, 1024))
        rcap = _pow2_at_least(
            max(2 * rsnap.num_rows // max(n_dev * n_dev, 1) + 1, 1024))
        ocap = _pow2_at_least(max(2 * lsnap.num_rows // n_dev + 1, 1024))
        return ShuffleCaps(lcap, rcap, ocap, row_cap)

    def _run_shuffle(self, spec: D.ShuffleJoinSpec, lsnap, rsnap, aux_cols,
                     row_cap: int = 0):
        """Run the shuffle program, regrowing whichever static capacity
        (exchange buckets / join output / group table / row output) the
        extras report as overflowed — the paging discipline."""
        import dataclasses

        from ..parallel.shuffle import ShuffleCaps, get_shuffle_program
        lcols, lcounts = lsnap.device_cols(self.mesh)
        rcols, rcounts = rsnap.device_cols(self.mesh)
        caps = self._shuffle_initial_caps(lsnap, rsnap, row_cap)
        agg = spec.top if isinstance(spec.top, D.Aggregation) else None
        if agg is not None and agg.strategy in D.HOST_MERGE_STRATEGIES \
                and not agg.state_capacity:
            spec = dataclasses.replace(spec, top=self._with_capacity(
                agg, DEFAULT_GROUP_CAPACITY))
        for _ in range(12):
            prog = get_shuffle_program(spec, self.mesh, caps)
            out, extras = self._launch_opaque(
                lambda p=prog: p(lcols, lcounts, rcols, rcounts, aux_cols),
                est_rows=lsnap.num_rows + rsnap.num_rows)
            extras = {k: np.asarray(jax.device_get(v))
                      for k, v in extras.items()}
            grew = False
            need_l = int(extras["lmax"].max())
            if need_l > caps.left:
                caps = dataclasses.replace(caps,
                                           left=_pow2_at_least(need_l))
                grew = True
            need_r = int(extras["rmax"].max())
            if need_r > caps.right:
                caps = dataclasses.replace(caps,
                                           right=_pow2_at_least(need_r))
                grew = True
            need_j = int(extras["join_total"].max())
            if spec.kind in ("inner", "left") and need_j > caps.out:
                caps = dataclasses.replace(caps, out=_pow2_at_least(need_j))
                grew = True
            if grew:
                continue
            agg = spec.top if isinstance(spec.top, D.Aggregation) else None
            if agg is not None and agg.strategy in D.HOST_MERGE_STRATEGIES:
                true_ng = int(np.max(np.asarray(
                    jax.device_get(out["__ngroups__"]))))
                if true_ng > agg.state_capacity:
                    spec = dataclasses.replace(spec, top=self._with_capacity(
                        agg, _pow2_at_least(true_ng)))
                    continue
            if agg is None:
                _cols, counts = out
                counts = np.asarray(jax.device_get(counts))
                if (counts > caps.rows).any():
                    caps = dataclasses.replace(
                        caps, rows=_pow2_at_least(int(counts.max())))
                    continue
            return prog, out
        raise RuntimeError("shuffle capacity regrow did not converge")

    def execute_window(self, spec: D.WindowShuffleSpec,
                       snap: ColumnarSnapshot, out_dtypes,
                       dictionaries=None, aux_cols=()) -> list[Column]:
        return self._retry(lambda: self._execute_window_once(
            spec, snap, out_dtypes, dictionaries, aux_cols))

    def _execute_window_once(self, spec, snap, out_dtypes,
                             dictionaries=None, aux_cols=()) -> list[Column]:
        """Hash-repartitioned window program (TiFlash MPP window analog):
        bucket capacity regrows from the reported true maximum, the
        paging discipline."""
        from ..parallel.window import get_window_program
        cols, counts = snap.device_cols(self.mesh)
        n_dev = len(self.mesh.devices.reshape(-1))
        # expected bucket rows under uniform hashing, 2x headroom
        cap = _pow2_at_least(
            max(2 * snap.num_rows // max(n_dev * n_dev, 1) + 1, 1024))
        for _ in range(10):
            prog = get_window_program(spec, self.mesh, cap)
            (out_cols, out_counts), extras = self._launch_opaque(
                lambda p=prog: p(cols, counts, aux_cols),
                est_rows=snap.num_rows)
            need = int(np.max(np.asarray(jax.device_get(extras["wmax"]))))
            if need <= cap:
                break
            cap = _pow2_at_least(need)
        else:
            raise RuntimeError("window bucket regrow did not converge")
        return self._assemble_rows(out_cols, out_counts,
                                   n_dev * cap, out_dtypes, dictionaries)

    def execute_shuffle_agg(self, spec: D.ShuffleJoinSpec, lsnap, rsnap,
                            key_meta: list[GroupKeyMeta],
                            aux_cols=()) -> CopResult:
        return self._retry(lambda: self._execute_shuffle_agg_once(
            spec, lsnap, rsnap, key_meta, aux_cols))

    def _execute_shuffle_agg_once(self, spec, lsnap, rsnap, key_meta,
                                  aux_cols=()) -> CopResult:
        prog, out = self._run_shuffle(spec, lsnap, rsnap, aux_cols)
        agg = prog.spec.top
        states = jax.device_get(out)
        if prog.host_merge:
            per_dev = self._split_devices(states)
            if agg.strategy in D.HOST_MERGE_STRATEGIES:
                merged = merge_sorted_states(agg, per_dev)
                key_cols, agg_cols = finalize_sorted(agg, merged, key_meta)
                return CopResult(agg_cols, key_cols)
            merged = merge_states(per_dev)
        else:
            merged = merge_states([states])
        key_cols, agg_cols = finalize(agg, merged, key_meta)
        return CopResult(agg_cols, key_cols)

    def execute_shuffle_rows(self, spec: D.ShuffleJoinSpec, lsnap, rsnap,
                             out_dtypes, dictionaries=None,
                             aux_cols=()) -> list[Column]:
        return self._retry(lambda: self._execute_shuffle_rows_once(
            spec, lsnap, rsnap, out_dtypes, dictionaries, aux_cols))

    def _execute_shuffle_rows_once(self, spec, lsnap, rsnap, out_dtypes,
                                   dictionaries=None,
                                   aux_cols=()) -> list[Column]:
        n_dev = len(self.mesh.devices.reshape(-1))
        if isinstance(spec.top, (D.TopN, D.Limit)):
            row_cap = max(spec.top.limit, 16)
        else:
            row_cap = _pow2_at_least(
                max(2 * lsnap.num_rows // max(n_dev, 1) + 1, 1024))
        prog, out = self._run_shuffle(spec, lsnap, rsnap, aux_cols, row_cap)
        out_cols, out_counts = out
        return self._assemble_rows(out_cols, out_counts, prog.caps.rows,
                                   out_dtypes, dictionaries)

    def _assemble_rows(self, out_cols, out_counts, cap, out_dtypes,
                       dictionaries) -> list[Column]:
        """Concatenate per-device compacted outputs into host Columns."""
        _faults.check("transfer")   # faultline device->host seam
        n_dev = len(self.mesh.devices.reshape(-1))
        out_counts = np.asarray(jax.device_get(out_counts))
        out_cols = jax.device_get(out_cols)
        per_dev_take = np.minimum(out_counts, cap)
        result = []
        for j, t in enumerate(out_dtypes):
            data = np.concatenate([np.asarray(out_cols[j][0])[d, :per_dev_take[d]]
                                   for d in range(n_dev)])
            valid = np.concatenate([np.asarray(out_cols[j][1])[d, :per_dev_take[d]]
                                    for d in range(n_dev)])
            dic = dictionaries.get(j) if dictionaries else None
            result.append(Column(t, data.astype(t.np_dtype()), valid, dic))
        return result

    # ------------------------------------------------------------- #

    def execute_rows(self, root: D.CopNode, snap: ColumnarSnapshot,
                     out_dtypes, dictionaries=None, aux_cols=()) -> list[Column]:
        return self._retry(lambda: self._execute_rows_once(
            root, snap, out_dtypes, dictionaries, aux_cols), snap=snap)

    def _execute_rows_once(self, root: D.CopNode, snap: ColumnarSnapshot,
                           out_dtypes, dictionaries=None,
                           aux_cols=()) -> list[Column]:
        """Row-returning plan with the paging loop."""
        batches = self._stream_batches(root, snap, aux_cols)
        if batches is not None:
            # per-batch results concatenate; TopN/Limit callers already
            # re-trim the multi-device candidate union, batches just widen
            # that union
            parts = [self._execute_rows_once(root, b, out_dtypes,
                                             dictionaries, aux_cols)
                     for b in batches]
            return [Column.concat([p[j] for p in parts])
                    for j in range(len(out_dtypes))]
        n_dev = len(self.mesh.devices.reshape(-1))
        is_topn = isinstance(root, D.TopN)
        is_limit = isinstance(root, D.Limit)
        fb_key = D.dag_digest(root)
        per_shard = -(-snap.num_rows // max(snap.n_shards, 1)) \
            if snap.num_rows else 1
        if is_topn or is_limit:
            cap = max(root.limit, 16)
        else:
            with self._pf_mu:
                fb = self._page_feedback.get(fb_key)
            if fb is not None:
                # prior observation + 50% headroom, clamped to the shard
                cap = _pow2_at_least(
                    max(int(per_shard * min(fb * 1.5, 1.0)) + 1, 256))
            else:
                cap = max(_pow2_at_least(
                    max(per_shard // INITIAL_SELECTIVITY, 1)), 1024)
            # copforge: a capacity the warm pool already compiled beats
            # the feedback guess — the paging loop's first launch hits
            # the pool instead of tracing a nearby-but-cold capacity
            cap = self._warm_cap(root, cap)

        cols, counts = snap.device_cols(self.mesh)
        page_iters = 0       # published once, under _stat_mu, at the end
        for _ in range(10):  # paging: grow until fits
            page_iters += 1
            prog, out = self._launch(root, cols, counts, tuple(aux_cols),
                                     row_capacity=cap)
            if prog.has_extras:
                out, extras = out
                grown = self._grown_join_dag(root, extras)
                if grown is not None:
                    root = grown
                    continue
            out_cols, out_counts = out
            out_counts = np.asarray(jax.device_get(out_counts))
            if is_topn or is_limit or (out_counts <= cap).all():
                break
            cap = self._warm_cap(root, _pow2_at_least(int(out_counts.max())))
        else:
            raise RuntimeError("paging loop did not converge")
        with self._stat_mu:
            self.last_page_iters = page_iters

        if not (is_topn or is_limit) and per_shard > 0:
            frac = float(out_counts.max()) / per_shard
            with self._pf_mu:
                old = self._page_feedback.get(fb_key, frac)
                self._page_feedback[fb_key] = 0.5 * old + 0.5 * frac
                self._page_feedback.move_to_end(fb_key)
                while len(self._page_feedback) > self._page_feedback_cap:
                    self._page_feedback.popitem(last=False)
        return self._assemble_rows(out_cols, out_counts, cap, out_dtypes,
                                   dictionaries)


__all__ = ["CopClient", "CopResult"]
