"""Shard/region topology: placement map with epochs, splits, and store
exclusion.

Reference analog: the unistore mock cluster + region cache
(/root/reference/pkg/store/mockstore/unistore/cluster.go — region
split/merge and store topology faked in one process;
pkg/store/copr/region_cache.go — shard->store routing invalidated on
region errors; coprocessor.go:337 buildCopTasks re-splits tasks after a
RegionError instead of re-running the identical dispatch).

TPU mapping: a "region" is a row-range shard of a columnar snapshot; a
"store" is a home slot that the mesh maps onto devices (store % n_dev).
The placement map says which store owns each shard; healing a failure
mutates the map (split the mis-routed shard, move shards off a dead
store) and bumps the epoch, which invalidates the snapshot's device cache
so the next dispatch re-fans-out under the new topology — the exact
region-cache-invalidation path, without per-task RPCs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .backoff import (REGION_MISS, STALE_EPOCH, STORE_UNAVAILABLE,
                      RegionError)


@dataclass
class Shard:
    shard_id: int
    lo: int            # row range [lo, hi)
    hi: int
    store: int

    @property
    def num_rows(self) -> int:
        return self.hi - self.lo


@dataclass
class Placement:
    """shard -> store map for one table snapshot (mock-PD analog)."""
    num_rows: int
    shards: list = field(default_factory=list)
    epoch: int = 0
    excluded: set = field(default_factory=set)
    _next_id: int = 0
    on_change: Optional[object] = None   # callback(placement) on exclusion

    @classmethod
    def even(cls, num_rows: int, n_shards: int) -> "Placement":
        n_shards = max(n_shards, 1)
        per = -(-num_rows // n_shards) if num_rows else 0
        p = cls(num_rows)
        for i in range(n_shards):
            lo = min(i * per, num_rows)
            hi = min(lo + per, num_rows)
            p.shards.append(Shard(i, lo, hi, store=i))
        p._next_id = n_shards
        return p

    # ---------------- topology queries ---------------- #

    def live_stores(self) -> list[int]:
        return [s for s in sorted({sh.store for sh in self.shards})
                if s not in self.excluded]

    def device_slots(self, n_dev: int) -> list[list[Shard]]:
        """Per-device shard lists under the store->device mod mapping."""
        slots: list[list[Shard]] = [[] for _ in range(n_dev)]
        for s in self.shards:
            slots[s.store % n_dev].append(s)
        return slots

    # ---------------- mutations (all bump the epoch) ---------------- #

    def split_shard(self, shard_id: int) -> None:
        """Split one shard at its midpoint (SPLIT TABLE / re-split-on-
        region-error analog, coprocessor.go:337)."""
        for i, s in enumerate(self.shards):
            if s.shard_id == shard_id:
                if s.num_rows < 2:
                    break
                mid = s.lo + s.num_rows // 2
                a = Shard(s.shard_id, s.lo, mid, s.store)
                b = Shard(self._next_id, mid, s.hi, s.store)
                self._next_id += 1
                self.shards[i:i + 1] = [a, b]
                break
        self.epoch += 1

    def exclude_store(self, store: int) -> None:
        """Move every shard off a failed store, round-robin over the
        remaining live stores (store-unavailable healing: re-placement,
        not identical re-dispatch)."""
        self.excluded.add(store)
        live = [st for st in sorted({s.store for s in self.shards})
                if st not in self.excluded]
        if not live:  # last store: re-home everything to virtual store 0
            live = [min(self.excluded) + len(self.excluded)]
        k = 0
        for s in self.shards:
            if s.store in self.excluded:
                s.store = live[k % len(live)]
                k += 1
        self.epoch += 1
        if self.on_change is not None:
            self.on_change(self)

    def rebalance(self, n_stores: int) -> None:
        """Spread shards evenly over n stores (scatter analog)."""
        live = [s for s in range(n_stores) if s not in self.excluded]
        for i, s in enumerate(self.shards):
            s.store = live[i % len(live)]
        self.epoch += 1

    def heal(self, err: Exception) -> bool:
        """Mutate the placement so the retry dispatches DIFFERENT work.

        Returns True when the topology changed.  Mirrors copr handleTask:
        store-unavailable -> exclude + re-place; region-miss/stale-epoch
        -> re-split the named shard (finer tasks) or just bump the epoch
        (drop cached routing)."""
        if not isinstance(err, RegionError):
            return False
        store = getattr(err, "store", None)
        shard = getattr(err, "shard", None)
        if err.kind is STORE_UNAVAILABLE and store is not None:
            self.exclude_store(store)
            return True
        if err.kind in (REGION_MISS, STALE_EPOCH):
            if shard is not None:
                self.split_shard(shard)    # also bumps epoch
            else:
                self.epoch += 1
            return True
        return False


__all__ = ["Placement", "Shard"]
