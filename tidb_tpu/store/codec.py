"""Key and row codecs — the storage contract.

Reference analog (SURVEY.md §A.2):
- record key layout t{tableID}_r{handle} with memcomparable encodings
  (pkg/tablecodec/tablecodec.go:50-103, pkg/util/codec: ints with sign-bit
  flip big-endian so byte order == numeric order)
- row value: versioned compact binary (rowcodec v2 analog,
  pkg/util/rowcodec: ver byte + null bitmap + per-column payloads), decoded
  straight into columns at columnarization time (decode once per snapshot,
  not per query).
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Sequence

from ..types import dtypes as dt
from ..types import decimal as dec
from ..types import temporal as tmp

K = dt.TypeKind

SIGN_FLIP = 1 << 63


# ---------------- memcomparable keys ---------------- #

def encode_int_key(v: int) -> bytes:
    """int64 -> 8 bytes, big-endian with sign bit flipped (byte order ==
    numeric order; util/codec EncodeIntToCmpUint analog)."""
    return struct.pack(">Q", (v + (1 << 63)) & 0xFFFFFFFFFFFFFFFF)


def decode_int_key(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


def record_key(table_id: int, handle: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_r" + encode_int_key(handle)


def record_prefix(table_id: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_r"


def record_prefix_end(table_id: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_s"  # '_r' + 1


def decode_record_key(key: bytes) -> tuple[int, int]:
    assert key[:1] == b"t" and key[9:11] == b"_r", key
    return decode_int_key(key[1:9]), decode_int_key(key[11:19])


def index_key(table_id: int, index_id: int, *parts: bytes) -> bytes:
    out = b"t" + encode_int_key(table_id) + b"_i" + encode_int_key(index_id)
    for p in parts:
        out += p
    return out


# ---------------- row values ---------------- #

ROW_VERSION = 1
_NULL = 0xFF


def encode_row(values: Sequence[Any], types: Sequence[dt.DataType]) -> bytes:
    """values are python-level (str/int/Decimal-string/None)."""
    out = bytearray([ROW_VERSION])
    out += struct.pack("<H", len(values))
    for v, t in zip(values, types):
        if v is None:
            out.append(_NULL)
            continue
        k = t.kind
        if k in (K.INT64, K.UINT64):
            out.append(0)
            out += struct.pack("<q" if k == K.INT64 else "<Q", int(v))
        elif k in (K.FLOAT64, K.FLOAT32):
            out.append(1)
            out += struct.pack("<d", float(v))
        elif k == K.DECIMAL:
            out.append(2)
            out += struct.pack("<q", dec.encode(v, t.scale))
        elif k == K.STRING:
            b = str(v).encode()
            out.append(3)
            out += struct.pack("<I", len(b)) + b
        elif k == K.DATE:
            out.append(4)
            out += struct.pack("<i", v if isinstance(v, int)
                               else tmp.parse_date(str(v)))
        elif k == K.DATETIME:
            out.append(5)
            out += struct.pack("<q", v if isinstance(v, int)
                               else tmp.parse_datetime(str(v)))
        elif k == K.TIME:
            out.append(6)
            out += struct.pack("<q", int(v))
        else:
            raise ValueError(f"cannot encode {t}")
    return bytes(out)


def decode_row(data: bytes, types: Sequence[dt.DataType]) -> list[Any]:
    """Decode to python-level values (Decimal as string, DATE as iso str)."""
    assert data[0] == ROW_VERSION
    (n,) = struct.unpack_from("<H", data, 1)
    off = 3
    out: list[Any] = []
    for i in range(n):
        tag = data[off]
        off += 1
        if tag == _NULL:
            out.append(None)
            continue
        t = types[i]
        if tag == 0:
            fmt = "<q" if t.kind == K.INT64 else "<Q"
            (v,) = struct.unpack_from(fmt, data, off)
            off += 8
            out.append(int(v))
        elif tag == 1:
            (v,) = struct.unpack_from("<d", data, off)
            off += 8
            out.append(float(v))
        elif tag == 2:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(dec.to_string(v, t.scale))
        elif tag == 3:
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            out.append(data[off:off + ln].decode())
            off += ln
        elif tag == 4:
            (v,) = struct.unpack_from("<i", data, off)
            off += 4
            out.append(tmp.date_to_string(v))
        elif tag == 5:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(tmp.datetime_to_string(v))
        elif tag == 6:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(int(v))
        else:
            raise ValueError(f"bad tag {tag}")
    return out


__all__ = [
    "encode_int_key", "decode_int_key", "record_key", "record_prefix",
    "record_prefix_end", "decode_record_key", "index_key",
    "encode_row", "decode_row", "ROW_VERSION",
]
