"""Key and row codecs — the storage contract.

Reference analog (SURVEY.md §A.2):
- record key layout t{tableID}_r{handle} with memcomparable encodings
  (pkg/tablecodec/tablecodec.go:50-103, pkg/util/codec: ints with sign-bit
  flip big-endian so byte order == numeric order)
- row value: versioned compact binary (rowcodec v2 analog,
  pkg/util/rowcodec: ver byte + null bitmap + per-column payloads), decoded
  straight into columns at columnarization time (decode once per snapshot,
  not per query).
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Sequence

import numpy as np

from ..types import dtypes as dt
from ..types import decimal as dec
from ..types import temporal as tmp

K = dt.TypeKind

SIGN_FLIP = 1 << 63


# ---------------- memcomparable keys ---------------- #

def encode_int_key(v: int) -> bytes:
    """int64 -> 8 bytes, big-endian with sign bit flipped (byte order ==
    numeric order; util/codec EncodeIntToCmpUint analog)."""
    return struct.pack(">Q", (v + (1 << 63)) & 0xFFFFFFFFFFFFFFFF)


def decode_int_key(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - (1 << 63)


def record_key(table_id: int, handle: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_r" + encode_int_key(handle)


def record_prefix(table_id: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_r"


def record_prefix_end(table_id: int) -> bytes:
    return b"t" + encode_int_key(table_id) + b"_s"  # '_r' + 1


def decode_record_key(key: bytes) -> tuple[int, int]:
    assert key[:1] == b"t" and key[9:11] == b"_r", key
    return decode_int_key(key[1:9]), decode_int_key(key[11:19])


def index_key(table_id: int, index_id: int, *parts: bytes) -> bytes:
    out = b"t" + encode_int_key(table_id) + b"_i" + encode_int_key(index_id)
    for p in parts:
        out += p
    return out


def index_prefix(table_id: int, index_id: Optional[int] = None) -> bytes:
    p = b"t" + encode_int_key(table_id) + b"_i"
    if index_id is not None:
        p += encode_int_key(index_id)
    return p


def index_prefix_end(table_id: int, index_id: Optional[int] = None) -> bytes:
    if index_id is None:
        return b"t" + encode_int_key(table_id) + b"_j"  # '_i' + 1
    return index_prefix(table_id, index_id + 1)


# ---------------- memcomparable index values ---------------- #
#
# Reference: pkg/util/codec — ints big-endian with sign flip, floats with
# sign-bit manipulation, bytes in 8-byte groups with pad-count markers so
# byte order == value order; NULLs get a 0x00 flag (sort first), non-NULL
# values a 0x01 flag (tablecodec index key layout).

def encode_bytes_key(b: bytes) -> bytes:
    """Order-preserving var-length bytes: 8-byte groups padded with \\x00,
    each followed by a marker 0xF7 + count of real bytes in the group
    (util/codec EncodeBytes analog)."""
    out = bytearray()
    for i in range(0, len(b) + 1, 8):
        group = b[i:i + 8]
        out += group + b"\x00" * (8 - len(group))
        out.append(0xF7 + len(group))
        if len(group) < 8:
            break
    return bytes(out)


def encode_float_key(v: float) -> bytes:
    bits = struct.unpack(">Q", struct.pack(">d", float(v)))[0]
    if bits & SIGN_FLIP:
        bits ^= 0xFFFFFFFFFFFFFFFF    # negative: flip all
    else:
        bits |= SIGN_FLIP             # positive: flip sign bit
    return struct.pack(">Q", bits)


def encode_index_value(v: Any, t: dt.DataType) -> bytes:
    """One python-level column value -> memcomparable bytes incl. the NULL
    flag byte."""
    if v is None:
        return b"\x00"
    k = t.kind
    if k in (K.INT64, K.UINT64):
        return b"\x01" + encode_int_key(int(v))
    if k in (K.FLOAT64, K.FLOAT32):
        return b"\x01" + encode_float_key(float(v))
    if k == K.DECIMAL:
        scaled = v if isinstance(v, int) else dec.encode(v, t.scale)
        return b"\x01" + encode_int_key(scaled)
    if k == K.DATE:
        d = v if isinstance(v, int) else tmp.parse_date(str(v))
        return b"\x01" + encode_int_key(d)
    if k == K.DATETIME:
        d = v if isinstance(v, int) else tmp.parse_datetime(str(v))
        return b"\x01" + encode_int_key(d)
    if k == K.STRING:
        return b"\x01" + encode_bytes_key(str(v).encode())
    if k in (K.TIME, K.ENUM, K.SET):
        return b"\x01" + encode_int_key(int(v))
    if k == K.BIT:
        # uint64 memcomparable via sign-flip (BIT(64) values >= 2^63)
        return b"\x01" + encode_int_key(int(v) - (1 << 63))
    raise ValueError(f"cannot index {t}")


def encode_index_entry(table_id: int, index_id: int, values: Sequence[Any],
                       types: Sequence[dt.DataType], handle: int,
                       unique: bool) -> tuple[bytes, bytes]:
    """Index KV pair.  Unique: key = prefix+values, value = handle.
    Non-unique: key = prefix+values+handle, value = empty (the reference's
    tablecodec layout, SURVEY.md §A.2)."""
    parts = [encode_index_value(v, t) for v, t in zip(values, types)]
    has_null = any(v is None for v in values)
    if unique and not has_null:
        return (index_key(table_id, index_id, *parts),
                struct.pack(">q", handle))
    # NULL-containing unique entries degrade to non-unique form (MySQL
    # allows many NULLs in a unique index)
    parts.append(encode_int_key(handle))
    return index_key(table_id, index_id, *parts), b""


def decode_index_handle(key: bytes, value: bytes) -> int:
    """Handle from an index entry (tail of key, or the value for unique)."""
    if value:
        return struct.unpack(">q", value)[0]
    return decode_int_key(key[-8:])


# ---------------- row values ---------------- #

ROW_VERSION = 1
_NULL = 0xFF


def encode_row(values: Sequence[Any], types: Sequence[dt.DataType]) -> bytes:
    """values are python-level (str/int/Decimal-string/None)."""
    out = bytearray([ROW_VERSION])
    out += struct.pack("<H", len(values))
    for v, t in zip(values, types):
        if v is None:
            out.append(_NULL)
            continue
        k = t.kind
        if k in (K.INT64, K.UINT64):
            out.append(0)
            out += struct.pack("<q" if k == K.INT64 else "<Q", int(v))
        elif k in (K.FLOAT64, K.FLOAT32):
            out.append(1)
            out += struct.pack("<d", float(v))
        elif k == K.DECIMAL:
            scaled = dec.encode(v, t.scale)
            if t.is_wide_decimal:
                # 19-65 digit decimals: length-prefixed little-endian
                # signed magnitude (mydecimal.go's var-width analog)
                nb = (scaled.bit_length() + 8) // 8 or 1
                out.append(8)
                out += struct.pack("<B", nb)
                out += scaled.to_bytes(nb, "little", signed=True)
            else:
                out.append(2)
                out += struct.pack("<q", scaled)
        elif k == K.STRING:
            b = str(v).encode()
            out.append(3)
            out += struct.pack("<I", len(b)) + b
        elif k == K.DATE:
            out.append(4)
            out += struct.pack("<i", v if isinstance(v, int)
                               else tmp.parse_date(str(v)))
        elif k == K.DATETIME:
            out.append(5)
            out += struct.pack("<q", v if isinstance(v, int)
                               else tmp.parse_datetime(str(v)))
        elif k in (K.TIME, K.ENUM, K.SET):
            out.append(6)
            out += struct.pack("<q", int(v))
        elif k == K.BIT:
            out.append(7)
            out += struct.pack("<Q", int(v))
        elif k == K.VECTOR:
            # [u16 dim][f32 x dim] (types VectorFloat32 serialization)
            arr = (dt.parse_vector_text(v, t.prec) if isinstance(v, str)
                   else np.asarray(v, dtype=np.float32))
            out.append(9)
            out += struct.pack("<H", len(arr))
            out += arr.tobytes()
        else:
            raise ValueError(f"cannot encode {t}")
    return bytes(out)


def decode_row(data: bytes, types: Sequence[dt.DataType]) -> list[Any]:
    """Decode to python-level values (Decimal as string, DATE as iso str)."""
    assert data[0] == ROW_VERSION
    (n,) = struct.unpack_from("<H", data, 1)
    off = 3
    out: list[Any] = []
    for i in range(n):
        tag = data[off]
        off += 1
        if tag == _NULL:
            out.append(None)
            continue
        t = types[i]
        if tag == 0:
            fmt = "<q" if t.kind == K.INT64 else "<Q"
            (v,) = struct.unpack_from(fmt, data, off)
            off += 8
            out.append(int(v))
        elif tag == 1:
            (v,) = struct.unpack_from("<d", data, off)
            off += 8
            out.append(float(v))
        elif tag == 2:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(dec.to_string(v, t.scale))
        elif tag == 8:
            nb = data[off]
            off += 1
            v = int.from_bytes(data[off:off + nb], "little", signed=True)
            off += nb
            out.append(dec.to_string(v, t.scale))
        elif tag == 3:
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            out.append(data[off:off + ln].decode())
            off += ln
        elif tag == 4:
            (v,) = struct.unpack_from("<i", data, off)
            off += 4
            out.append(tmp.date_to_string(v))
        elif tag == 5:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(tmp.datetime_to_string(v))
        elif tag == 6:
            (v,) = struct.unpack_from("<q", data, off)
            off += 8
            out.append(int(v))
        elif tag == 7:
            (v,) = struct.unpack_from("<Q", data, off)
            off += 8
            out.append(int(v))
        elif tag == 9:
            (dim,) = struct.unpack_from("<H", data, off)
            off += 2
            out.append(np.frombuffer(data, np.float32, dim, off).copy())
            off += 4 * dim
        else:
            raise ValueError(f"bad tag {tag}")
    return out


__all__ = [
    "encode_int_key", "decode_int_key", "record_key", "record_prefix",
    "record_prefix_end", "decode_record_key", "index_key", "index_prefix",
    "index_prefix_end", "encode_bytes_key", "encode_float_key",
    "encode_index_value", "encode_index_entry", "decode_index_handle",
    "encode_row", "decode_row", "ROW_VERSION",
]
