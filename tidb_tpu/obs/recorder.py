"""copscope flight recorder: bounded ring of completed query traces.

Reference analog: TiDB's continuous-profiling/Top-SQL direction — keep
enough recent per-query evidence in memory that the question "what did
that slow/failed statement actually spend its time on?" is answerable
AFTER the fact, without re-running anything.

Retention contract (tested):

- Interesting traces are ALWAYS admitted: any trace flagged ``failed``,
  ``degraded``, ``quarantined``, ``retried`` or ``slow`` (slower than
  ``tidb_tpu_slow_threshold_ms``).
- Ordinary traces are SAMPLED 1-in-``sample_every`` so the ring keeps
  a background rhythm without interesting traces being washed out by
  a flood of fast OKs.
- The ring is provably bounded: one deque(maxlen=capacity) holds
  everything — admission decides what enters, the ring bounds what
  stays.  No unbounded always-keep side list.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .trace import SpanTree

DEFAULT_CAPACITY = 256
DEFAULT_SAMPLE_EVERY = 16

# flags that force admission regardless of the sampling cadence
KEEP_FLAGS = frozenset(
    {"failed", "degraded", "quarantined", "retried", "slow"})


class FlightRecorder:
    """Bounded ring of completed statement traces (``SpanTree``)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 sample_every: int = DEFAULT_SAMPLE_EVERY):
        self.capacity = max(int(capacity), 1)
        self.sample_every = max(int(sample_every), 1)
        self._ring: deque = deque(maxlen=self.capacity)
        self._mu = threading.Lock()
        self._seen = 0           # completed traces offered (lifetime)
        self.recorded = 0        # admitted to the ring (lifetime)
        self.sampled_out = 0     # ordinary traces the cadence skipped

    def record(self, tree: SpanTree) -> bool:
        """Offer one completed trace; True = admitted to the ring."""
        with self._mu:
            self._seen += 1
            keep = bool(tree.flags & KEEP_FLAGS) \
                or (self._seen % self.sample_every) == 1 \
                or self.sample_every == 1
            if not keep:
                self.sampled_out += 1
                return False
            self.recorded += 1
            self._ring.append(tree)
            return True

    def get(self, trace_id: str) -> Optional[SpanTree]:
        with self._mu:
            for tree in reversed(self._ring):
                if tree.trace_id == trace_id:
                    return tree
        return None

    def index(self) -> list[dict]:
        """Newest-first trace summaries — the ``/trace`` listing."""
        with self._mu:
            trees = list(self._ring)
        return [{
            "trace_id": t.trace_id,
            "conn_id": t.conn_id,
            "sql": t.sql[:200],
            "start_ts": t.wall_start,
            "latency_ms": round(t.latency_ms, 3),
            "flags": sorted(t.flags),
            "spans": len(t.spans),
        } for t in reversed(trees)]

    def stats(self) -> dict:
        with self._mu:
            return {"capacity": self.capacity,
                    "sample_every": self.sample_every,
                    "size": len(self._ring),
                    "seen": self._seen,
                    "recorded": self.recorded,
                    "sampled_out": self.sampled_out}

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)


__all__ = ["FlightRecorder", "KEEP_FLAGS", "DEFAULT_CAPACITY",
           "DEFAULT_SAMPLE_EVERY"]
