"""copscope (ISSUE 13): end-to-end observability for the async serving
stack.

- ``trace``: cross-thread trace propagation (``TraceCtx`` stamped onto
  CopTask at submit) + lock-protected per-statement span trees with
  explicit parent ids — the scheduler drain, copforge resolve, and
  client transfer/merge seams record real spans from their own threads.
- ``recorder``: bounded flight-recorder ring of completed query traces
  (failed/degraded/quarantined/retried/slow always kept, the rest
  sampled), served at ``/trace`` + ``/trace/<id>`` with Chrome
  trace-event export (``?fmt=chrome``).

Latency histograms ride ``utils/metrics`` (label-aware prometheus-text
histograms) — ``tidb_tpu_sched_{wait,launch,compile}_ms`` and the
per-strategy agg launch histogram are wired at the scheduler drain.
"""

from .recorder import FlightRecorder
from .trace import (TRACE_CTX, Span, SpanTree, TraceCtx, annotate,
                    current, flag, new_trace_id, span)

__all__ = ["Span", "SpanTree", "TraceCtx", "TRACE_CTX", "current",
           "span", "flag", "annotate", "new_trace_id", "FlightRecorder"]
