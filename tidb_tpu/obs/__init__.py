"""copscope (ISSUE 13): end-to-end observability for the async serving
stack.

- ``trace``: cross-thread trace propagation (``TraceCtx`` stamped onto
  CopTask at submit) + lock-protected per-statement span trees with
  explicit parent ids — the scheduler drain, copforge resolve, and
  client transfer/merge seams record real spans from their own threads.
- ``recorder``: bounded flight-recorder ring of completed query traces
  (failed/degraded/quarantined/retried/slow always kept, the rest
  sampled), served at ``/trace`` + ``/trace/<id>`` with Chrome
  trace-event export (``?fmt=chrome``).

Latency histograms ride ``utils/metrics`` (label-aware prometheus-text
histograms) — ``tidb_tpu_sched_{wait,launch,compile}_ms`` and the
per-strategy agg launch histogram are wired at the scheduler drain.

copgauge (ISSUE 14) adds the memory/throughput axis:

- ``hbm``: the live per-mesh HBM ledger (persistent residents through
  the PR 7 weakref registry, launch-scoped bytes at admission/finish),
  measured launch watermarks, bounded device ``memory_stats``
  reconciliation, and the on-demand ``/profile`` capture gate.
- ``roofline``: per-program-digest achieved-vs-peak bytes/s and
  FLOPs/s attribution (memory-/compute-/launch-bound) against a
  declared per-backend peak table (CPU: boot-time microbench).
"""

from .hbm import (HbmLedger, all_ledgers, device_memory_stats,
                  hbm_status, ledger_for, profiler_gate)
from .recorder import FlightRecorder
from .roofline import (backend_peaks, peaks_for_mesh, roofline_status,
                       roofline_store)
from .trace import (TRACE_CTX, Span, SpanTree, TraceCtx, annotate,
                    current, flag, new_trace_id, span)

__all__ = ["Span", "SpanTree", "TraceCtx", "TRACE_CTX", "current",
           "span", "flag", "annotate", "new_trace_id", "FlightRecorder",
           "HbmLedger", "ledger_for", "all_ledgers", "hbm_status",
           "device_memory_stats", "profiler_gate", "roofline_store",
           "roofline_status", "backend_peaks", "peaks_for_mesh"]
