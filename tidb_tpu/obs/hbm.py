"""copgauge: live HBM ledger + measured memory watermarks.

Reference analog: the executor memory tracker tree of the reference
engine (util/memory Tracker feeding quota actions, PAPER.md) — per-query
memory is TRACKED while it is resident, not predicted once and
forgotten.  copcost (PR 4) predicts ``peak_hbm_bytes`` and admission
enforces a budget against the prediction, but until this module nothing
ever measured what a launch actually held resident: ``mem_factor``
calibration (PR 10) could only learn by crashing into OOM.

The ledger is a per-mesh accounting structure fed by the existing
lifetime classes (analysis/lifetime, PR 7):

- PERSISTENT residents register through the PR 7 weakref registry
  (``ColumnarSnapshot.device_cols`` -> ``lifetime.register_resident``)
  and UNREGISTER through the weakref's death callback — the ledger can
  never count a dead entry, and a dropped snapshot is debited the
  moment the garbage collector reclaims its arrays.
- EPHEMERAL / LOOP-CARRIED bytes enter at launch admission (the drain's
  ``launch_begin``) and leave at finish (``launch_end``); donated bytes
  (DonationPlan) are credited at dispatch because
  ``LaunchCost.peak_hbm_bytes`` already subtracts ``donated_bytes``.
- The ledger is reconciled against ``device.memory_stats()`` where the
  backend provides it — polled at a BOUNDED interval
  (``RECONCILE_MIN_S``), never on the launch path.  The CPU mesh
  reports no stats and runs on the ledger alone, so tier-1 exercises
  every accounting path.

``device_memory_stats`` below is the ONLY sanctioned raw device memory
poll in the tree (lint rule TPU-MEM-SOURCE): every other module —
copcost's auto budget included — routes through it, so the ledger stays
the single source of memory truth.

The module also owns the on-demand ``jax.profiler`` capture gate behind
``/profile?ms=N`` (sysvar ``tidb_tpu_profile``; refused while a capture
is active — two overlapping traces corrupt each other's xplane dirs).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Optional

# reconcile against device memory_stats at most this often — the poll
# is a backend RPC on real hardware and must NEVER ride the launch path
RECONCILE_MIN_S = 0.5
# recent per-launch measured peaks kept for /hbm (bounded ring)
MEASURED_RING = 64
# /profile capture bounds (ms): a zero-length capture is useless, an
# unbounded one fills the disk with xplane events
PROFILE_MIN_MS = 10
PROFILE_MAX_MS = 60_000


def device_memory_stats(mesh) -> Optional[dict]:
    """Raw device memory stats of one chip of ``mesh`` (None where the
    backend reports nothing — CPU meshes).  The single sanctioned
    ``memory_stats()`` call site (TPU-MEM-SOURCE): the ledger, the
    copcost auto budget, and any future consumer all read through
    here so the memory-truth seam stays one function wide."""
    try:
        dev = mesh.devices.reshape(-1)[0]
        stats = dev.memory_stats()
    except (AttributeError, IndexError, NotImplementedError,
            RuntimeError, TypeError):
        return None
    if not stats:
        return None
    return dict(stats)


class HbmLedger:
    """Live device-memory account of ONE mesh (keyed by the mesh
    fingerprint, like the scheduler registry).

    All mutation takes the leaf lock ``_mu``; the weakref death
    callback runs under it too (CPython fires callbacks outside any
    other ledger frame, so this cannot self-deadlock).  Balances are
    clamped at zero with a ``negative_events`` counter — bookkeeping
    drift must surface as a diagnostic, never as a wedged account."""

    def __init__(self, fingerprint=None):
        self.fingerprint = fingerprint
        self._mu = threading.Lock()
        # id(weakref) -> (weakref, nbytes); the ref's death callback
        # debits the account.  Keyed by the REF's id, not the referent:
        # a weakref hashes through its referent, and device arrays are
        # unhashable — and a ref id can never be recycled while the
        # entry holds the ref alive.
        self._residents: dict = {}
        self.persistent_bytes = 0      # live snapshot-cache residents
        self.inflight_bytes = 0        # launches currently holding HBM
        self.watermark_bytes = 0       # high-water of resident + measured
        self.max_measured_bytes = 0    # largest per-launch measured peak
        self.last_measured_bytes = 0
        self.launches = 0              # launch_begin events (lifetime)
        self.measured_launches = 0     # launches with a measured peak
        self.registered = 0            # resident registrations (lifetime)
        self.unregistered = 0          # weakref deaths debited
        self.negative_events = 0       # clamped would-be-negative balances
        self._measured_ring: deque = deque(maxlen=MEASURED_RING)
        self._recon_ts = 0.0
        self._recon: Optional[dict] = None
        from ..utils.metrics import global_registry
        reg = global_registry()
        self._m_resident = reg.gauge(
            "tidb_tpu_hbm_resident_bytes",
            "ledger-tracked live device bytes (persistent residents "
            "+ in-flight launch footprints)")
        self._m_watermark = reg.gauge(
            "tidb_tpu_hbm_watermark_bytes",
            "high-water of ledger residency and measured launch peaks")

    # ---- persistent residents (PR 7 weakref registry events) -------- #

    def add_resident(self, token, nbytes: int) -> None:
        """One snapshot's device-resident arrays entered the cache:
        credit ``nbytes`` against a weakref on ``token`` (the counts
        array — the same registry token lifetime.register_resident
        uses) whose death callback debits the account."""
        if token is None or nbytes <= 0:
            return
        with self._mu:
            for r, _n in self._residents.values():
                if r() is token:
                    return          # same live object re-registered
            try:
                ref = weakref.ref(token, self._resident_dead)
            except TypeError:
                return
            self._residents[id(ref)] = (ref, int(nbytes))  # planlint: ok - ref held, id stable
            self.persistent_bytes += int(nbytes)
            self.registered += 1
            self._bump_watermark_locked()
        self._publish()

    def _resident_dead(self, ref) -> None:
        """Weakref death callback: the resident arrays were collected —
        the unregister half of the registry contract."""
        with self._mu:
            ent = self._residents.pop(id(ref), None)  # planlint: ok - ref held, id stable
            if ent is None:
                return
            self.persistent_bytes -= ent[1]
            self.unregistered += 1
            if self.persistent_bytes < 0:
                self.negative_events += 1
                self.persistent_bytes = 0
        self._publish()

    # ---- launch-scoped bytes (admission enter, finish leave) -------- #

    def launch_begin(self, nbytes: int) -> None:
        with self._mu:
            self.launches += 1
            if nbytes > 0:
                self.inflight_bytes += int(nbytes)
            self._bump_watermark_locked()
        self._publish()

    def launch_end(self, nbytes: int) -> None:
        with self._mu:
            if nbytes > 0:
                self.inflight_bytes -= int(nbytes)
                if self.inflight_bytes < 0:
                    self.negative_events += 1
                    self.inflight_bytes = 0
        self._publish()

    def note_measured(self, nbytes: int) -> None:
        """One launch's measured peak (memory_stats delta where the
        backend provides it, else the compiled memory analysis of the
        actually-served executable): feeds the watermark so it
        dominates every per-launch measurement by construction."""
        if nbytes <= 0:
            return
        with self._mu:
            self.measured_launches += 1
            self.last_measured_bytes = int(nbytes)
            self._measured_ring.append(int(nbytes))
            if nbytes > self.max_measured_bytes:
                self.max_measured_bytes = int(nbytes)
            if nbytes > self.watermark_bytes:
                self.watermark_bytes = int(nbytes)
        self._publish()

    def _bump_watermark_locked(self) -> None:
        live = self.persistent_bytes + self.inflight_bytes
        if live > self.watermark_bytes:
            self.watermark_bytes = live

    def _publish(self) -> None:
        self._m_resident.set(self.persistent_bytes + self.inflight_bytes)
        self._m_watermark.set(self.watermark_bytes)

    # ---- reconciliation (bounded poll, never the launch path) ------- #

    def reconcile(self, mesh, force: bool = False) -> Optional[dict]:
        """Compare the ledger against the backend's own view where one
        exists.  Rate-limited to RECONCILE_MIN_S; called from status
        routes and stats(), NEVER from the drain.  Returns the last
        reconciliation record (None on backends without stats)."""
        now = time.monotonic()
        with self._mu:
            due = force or (now - self._recon_ts >= RECONCILE_MIN_S)
            if due:
                self._recon_ts = now
        if due and mesh is not None:
            stats = device_memory_stats(mesh)
            if stats is not None:
                n_dev = int(mesh.devices.size)
                in_use = int(stats.get("bytes_in_use", 0) or 0) * n_dev
                with self._mu:
                    self._recon = {
                        "device_bytes_in_use": in_use,
                        "ledger_bytes": self.persistent_bytes
                        + self.inflight_bytes,
                        "drift_bytes": in_use - (self.persistent_bytes
                                                 + self.inflight_bytes),
                        "peak_bytes_in_use": int(
                            stats.get("peak_bytes_in_use", 0) or 0)
                        * n_dev,
                    }
        with self._mu:
            return dict(self._recon) if self._recon is not None else None

    # ---- introspection ---------------------------------------------- #

    @property
    def resident_bytes(self) -> int:
        with self._mu:
            return self.persistent_bytes + self.inflight_bytes

    def residents(self) -> list:
        """[(nbytes, alive)] of tracked resident entries (diagnostics;
        dead entries cannot appear — the callback removed them)."""
        with self._mu:
            return [(n, r() is not None)
                    for r, n in self._residents.values()]

    def stats(self) -> dict:
        with self._mu:
            ring = list(self._measured_ring)
            return {
                "persistent_bytes": self.persistent_bytes,
                "inflight_bytes": self.inflight_bytes,
                "resident_bytes": self.persistent_bytes
                + self.inflight_bytes,
                "watermark_bytes": self.watermark_bytes,
                "max_measured_bytes": self.max_measured_bytes,
                "last_measured_bytes": self.last_measured_bytes,
                "residents": len(self._residents),
                "registered": self.registered,
                "unregistered": self.unregistered,
                "launches": self.launches,
                "measured_launches": self.measured_launches,
                "negative_events": self.negative_events,
                "measured_recent": ring[-8:],
                "reconciled": self._recon,
            }


# ------------------------------------------------------------------ #
# per-mesh registry (the scheduler_for discipline)
# ------------------------------------------------------------------ #

_LEDGERS: dict = {}
_LED_MU = threading.Lock()


def ledger_for(fingerprint) -> HbmLedger:
    """The (process-wide) ledger accounting one mesh's device memory,
    keyed by the mesh FINGERPRINT exactly like scheduler_for — every
    Domain over the same chips shares one account."""
    with _LED_MU:
        led = _LEDGERS.get(fingerprint)
        if led is None:
            led = _LEDGERS[fingerprint] = HbmLedger(fingerprint)
        return led


def all_ledgers() -> list:
    with _LED_MU:
        return list(_LEDGERS.values())


def hbm_status() -> dict:
    """The ledger half of the ``/hbm`` status route."""
    leds = all_ledgers()
    return {
        "ledgers": [led.stats() for led in leds],
        "resident_bytes": sum(led.resident_bytes for led in leds),
        "watermark_bytes": max(
            (led.watermark_bytes for led in leds), default=0),
    }


# ------------------------------------------------------------------ #
# on-demand profiler capture (/profile?ms=N)
# ------------------------------------------------------------------ #

class ProfilerGate:
    """One-at-a-time ``jax.profiler`` trace capture.  ``start`` refuses
    while a capture is active (overlapping traces corrupt each other's
    xplane output); a daemon timer stops the trace after ``ms``."""

    def __init__(self):
        self._mu = threading.Lock()
        self.active = False
        self.dir = ""
        self.captures = 0
        self.last_error = ""

    def start(self, ms: int, base_dir: str = "") -> dict:
        ms = min(max(int(ms), PROFILE_MIN_MS), PROFILE_MAX_MS)
        with self._mu:
            if self.active:
                return {"refused": "a profiler capture is already "
                                   "active", "dir": self.dir}
            self.active = True
        try:
            import tempfile

            import jax
            d = base_dir or tempfile.mkdtemp(prefix="tidb-tpu-profile-")
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:  # noqa: BLE001 - capability probe: some
            # backends/builds ship no profiler; the route must answer,
            # not 500
            with self._mu:
                self.active = False
                self.last_error = f"{type(e).__name__}: {e}"
            return {"refused": f"profiler unavailable: "
                               f"{self.last_error}"}
        with self._mu:
            self.dir = d
            self.captures += 1
        t = threading.Timer(ms / 1000.0, self._stop)
        t.daemon = True
        t.start()
        return {"started": True, "dir": d, "ms": ms}

    def _stop(self) -> None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - a failed stop must
            # still release the gate or no capture could ever run again
            with self._mu:
                self.last_error = f"{type(e).__name__}: {e}"
        finally:
            with self._mu:
                self.active = False

    def stats(self) -> dict:
        with self._mu:
            return {"active": self.active, "dir": self.dir,
                    "captures": self.captures,
                    "last_error": self.last_error}


_GATE: Optional[ProfilerGate] = None
_GATE_MU = threading.Lock()


def profiler_gate() -> ProfilerGate:
    global _GATE
    with _GATE_MU:
        if _GATE is None:
            _GATE = ProfilerGate()
        return _GATE


__all__ = ["HbmLedger", "ledger_for", "all_ledgers", "hbm_status",
           "device_memory_stats", "ProfilerGate", "profiler_gate",
           "RECONCILE_MIN_S", "MEASURED_RING"]
