"""copscope trace core: cross-thread trace propagation + per-statement
span trees.

Reference analog: pkg/util/tracing's StartRegionEx regions rendered by
the TRACE statement (executor/trace.go), grown to the Canopy/Dapper
shape the async stack needs — the statement path crosses seven thread
seams (admission queue, rc throttle, fusion window, copforge compile,
supervised launch, transfer, host merge) so the depth-counter Tracer of
``utils/tracing`` cannot attribute them.  Here every span carries an
EXPLICIT parent id and the per-statement tree is lock-protected, so the
scheduler drain, copforge resolve, and client transfer seams record
real spans from their own threads and the session renderer stitches one
tree.

Propagation is contextvar + task-stamp:

- ``TRACE_CTX`` holds the session-side ``TraceCtx`` (tree + current
  span id); ``span(name)`` nests under it within one thread.
- ``CopTask`` captures ``current()`` at construction (same discipline
  as ``SCHED_GROUP``/``KILL_EVENT``), so the drain thread can record
  spans under the submitting statement's dispatch span via
  ``ctx.add(...)`` — no contextvar crosses the thread boundary.

Recording is deliberately cheap (one tuple append under the tree lock;
``add`` is the only hot-path entry) so tracing can stay on in
production — the bench's ``trace_overhead_pct`` guards it.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager
from typing import Optional

# the active statement's TraceCtx; None = tracing off / no statement
TRACE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "trace_ctx", default=None)

_TRACE_SEQ = itertools.count(1)


def new_trace_id(conn_id: int = 0) -> str:
    """Process-unique trace id: conn + monotonic sequence (readable in
    logs, stable enough for the flight-recorder index)."""
    return f"{conn_id:x}-{next(_TRACE_SEQ):06x}"


class Span:
    """One completed (or open) region.  ``parent_id`` is explicit —
    depth is DERIVED at render time, never tracked by a counter, so
    spans recorded out of order from other threads still nest right."""

    __slots__ = ("span_id", "parent_id", "name", "start_ns", "end_ns",
                 "thread", "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 start_ns: int, end_ns: int = 0,
                 thread: str = "", attrs: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.thread = thread
        self.attrs = attrs or {}

    @property
    def duration_us(self) -> float:
        return (self.end_ns - self.start_ns) / 1e3


class SpanTree:
    """Lock-protected per-statement span collector.

    Every mutation takes ``_mu``; renders snapshot under it.  Span ids
    are tree-local ints; parent links make the tree — the session's
    root span is the statement, client dispatch spans hang under it,
    and scheduler-thread spans hang under the dispatch span whose
    ``TraceCtx`` rode the CopTask."""

    def __init__(self, trace_id: str = "", sql: str = "", conn_id: int = 0):
        self.trace_id = trace_id or new_trace_id(conn_id)
        self.sql = sql
        self.conn_id = conn_id
        self.t0 = time.perf_counter_ns()
        self.wall_start = time.time()
        self.latency_ms = 0.0
        self.flags: set = set()       # failed/degraded/quarantined/
                                      # retried/slow — recorder retention
        self.spans: list[Span] = []
        self._mu = threading.Lock()
        self._next = 0

    # ---- recording (any thread) ---------------------------------- #

    def add(self, name: str, start_ns: int, end_ns: int,
            parent_id: Optional[int] = None, **attrs) -> int:
        """Record one COMPLETED span — the cross-thread hot path (the
        drain records post-measurement, pre-``finish``, so a waiter
        rendering the tree always sees its scheduler spans)."""
        with self._mu:
            sid = self._next = self._next + 1
            self.spans.append(Span(
                sid, parent_id, name, start_ns, end_ns,
                thread=threading.current_thread().name, attrs=attrs))
            return sid

    def add_batch(self, items: list) -> list[int]:
        """Record several completed spans in ONE lock acquisition —
        the drain's per-launch recording path (queue + launch +
        compile + fusion per task would otherwise take the lock four
        times at the scheduler's serialization point).

        ``items``: ``(name, start_ns, end_ns, parent, attrs)`` tuples;
        ``parent`` is a span id, None, or ``("rel", i)`` referring to
        the i-th span OF THIS BATCH (the launch->compile nesting)."""
        thread = threading.current_thread().name
        out: list[int] = []
        with self._mu:
            for name, start_ns, end_ns, parent, attrs in items:
                if isinstance(parent, tuple):
                    parent = out[parent[1]]
                sid = self._next = self._next + 1
                self.spans.append(Span(sid, parent, name, start_ns,
                                       end_ns, thread=thread,
                                       attrs=attrs))
                out.append(sid)
        return out

    def begin(self, name: str, parent_id: Optional[int] = None,
              **attrs) -> int:
        return self.add(name, time.perf_counter_ns(), 0,
                        parent_id, **attrs)

    def end(self, span_id: int, **attrs) -> None:
        now = time.perf_counter_ns()
        with self._mu:
            for sp in reversed(self.spans):
                if sp.span_id == span_id:
                    sp.end_ns = now
                    if attrs:
                        sp.attrs.update(attrs)
                    return

    def flag(self, *names: str) -> None:
        with self._mu:
            self.flags.update(names)

    def annotate(self, span_id: int, **attrs) -> None:
        with self._mu:
            for sp in reversed(self.spans):
                if sp.span_id == span_id:
                    sp.attrs.update(attrs)
                    return

    # ---- rendering ------------------------------------------------ #

    def _snapshot(self) -> list[Span]:
        with self._mu:
            return list(self.spans)

    def ordered(self) -> list[tuple[Span, int]]:
        """(span, depth) depth-first, children ordered by start time —
        the TRACE result-set order.  Orphan parents (span recorded
        before its parent — impossible today, defensive) render at
        root depth rather than vanish."""
        spans = self._snapshot()
        ids = {sp.span_id for sp in spans}
        kids: dict = {}
        roots: list = []
        for sp in spans:
            if sp.parent_id is not None and sp.parent_id in ids:
                kids.setdefault(sp.parent_id, []).append(sp)
            else:
                roots.append(sp)
        out: list = []

        def walk(sp: Span, depth: int) -> None:
            out.append((sp, depth))
            for ch in sorted(kids.get(sp.span_id, ()),
                             key=lambda s: (s.start_ns, s.span_id)):
                walk(ch, depth + 1)

        for sp in sorted(roots, key=lambda s: (s.start_ns, s.span_id)):
            walk(sp, 0)
        return out

    def rows(self) -> list[tuple]:
        """TRACE renderer rows: (indented name [attrs], start_us_rel,
        duration_us)."""
        out = []
        for sp, depth in self.ordered():
            end = sp.end_ns or sp.start_ns
            label = "  " * depth + sp.name
            if sp.attrs:
                kv = ", ".join(f"{k}={_fmt(v)}"
                               for k, v in sorted(sp.attrs.items()))
                label += f" {{{kv}}}"
            out.append((label,
                        round((sp.start_ns - self.t0) / 1e3, 1),
                        round((end - sp.start_ns) / 1e3, 1)))
        return out

    def to_dict(self) -> dict:
        """Flight-recorder / ``/trace/<id>`` JSON shape."""
        return {
            "trace_id": self.trace_id,
            "conn_id": self.conn_id,
            "sql": self.sql,
            "start_ts": self.wall_start,
            "latency_ms": round(self.latency_ms, 3),
            "flags": sorted(self.flags),
            "spans": [{
                "id": sp.span_id, "parent": sp.parent_id,
                "name": sp.name, "thread": sp.thread,
                "start_us": round((sp.start_ns - self.t0) / 1e3, 1),
                "duration_us": round(
                    ((sp.end_ns or sp.start_ns) - sp.start_ns) / 1e3, 1),
                "attrs": {k: _json_safe(v)
                          for k, v in sorted(sp.attrs.items())},
            } for sp, _d in self.ordered()],
        }

    def chrome_trace(self) -> dict:
        """Chrome trace-event / Perfetto JSON (``?fmt=chrome``): one
        complete ("ph": "X") event per span, tids = recording threads
        so the cross-thread seams are visible as separate tracks."""
        tids: dict = {}
        events = []
        for sp, _d in self.ordered():
            tid = tids.setdefault(sp.thread, len(tids) + 1)
            end = sp.end_ns or sp.start_ns
            events.append({
                "name": sp.name, "ph": "X", "pid": 1, "tid": tid,
                "ts": round((sp.start_ns - self.t0) / 1e3, 3),
                "dur": round((end - sp.start_ns) / 1e3, 3),
                "cat": sp.name.split(".", 1)[0],
                "args": {k: _json_safe(v)
                         for k, v in sorted(sp.attrs.items())},
            })
        meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                 "args": {"name": thread}}
                for thread, tid in sorted(tids.items(), key=lambda kv: kv[1])]
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {"trace_id": self.trace_id, "sql": self.sql}}


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def _json_safe(v):
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


class TraceCtx:
    """Propagation unit: (tree, parent span id).  Stamped onto CopTask
    at submit; the drain records under ``span_id`` from its own thread.
    The trace id lives on the tree — one per statement."""

    __slots__ = ("tree", "span_id")

    def __init__(self, tree: SpanTree, span_id: Optional[int] = None):
        self.tree = tree
        self.span_id = span_id

    @property
    def trace_id(self) -> str:
        return self.tree.trace_id

    def add(self, name: str, start_ns: int, end_ns: int, **attrs) -> int:
        """Record a completed child span from ANY thread."""
        return self.tree.add(name, start_ns, end_ns,
                             parent_id=self.span_id, **attrs)

    def child(self, span_id: int) -> "TraceCtx":
        return TraceCtx(self.tree, span_id)


def current() -> Optional[TraceCtx]:
    """The calling thread's active trace context (None = untraced)."""
    return TRACE_CTX.get()


@contextmanager
def span(name: str, **attrs):
    """Session-side nested region: opens a child span under the active
    context and re-points ``TRACE_CTX`` at it for the dynamic extent,
    so tasks submitted inside hang under THIS span.  A no-op (yields
    None) when tracing is off — callers never branch."""
    ctx = TRACE_CTX.get()
    if ctx is None:
        yield None
        return
    t0 = time.perf_counter_ns()
    sid = ctx.tree.add(name, t0, 0, parent_id=ctx.span_id, **attrs)
    sub = TraceCtx(ctx.tree, sid)
    tok = TRACE_CTX.set(sub)
    try:
        yield sub
    finally:
        TRACE_CTX.reset(tok)
        ctx.tree.end(sid)


def flag(*names: str) -> None:
    """Mark the active trace (quarantined/degraded/...); no-op when
    untraced."""
    ctx = TRACE_CTX.get()
    if ctx is not None:
        ctx.tree.flag(*names)


def annotate(**attrs) -> None:
    """Attach attrs to the active span; no-op when untraced."""
    ctx = TRACE_CTX.get()
    if ctx is not None and ctx.span_id is not None:
        ctx.tree.annotate(ctx.span_id, **attrs)


__all__ = ["Span", "SpanTree", "TraceCtx", "TRACE_CTX", "current",
           "span", "flag", "annotate", "new_trace_id"]
