"""copgauge roofline attribution: achieved vs peak bytes/s and FLOPs/s
per program digest.

Reference analog: Flare's roofline framing (PAPERS.md) — a measured
"0.05x numpy" is unactionable until it is decomposed into WHERE the
time went: a digest running at 80% of peak memory bandwidth is
memory-bound (tiling/width levers), one at 60% of peak FLOPs is
compute-bound (algorithmic levers), and one whose whole launch fits in
dispatch overhead is launch-bound (fusion/batching levers).  The
ROADMAP's queued real-TPU window reports the hndv SCATTER-vs-SEGMENT
verdict through exactly this surface.

Per digest, the store combines measured launch wall time (the PR 5/10
marginal-bytes attribution) with the static ``LaunchCost`` flops and
transfer bytes into achieved GB/s and GFLOP/s against a per-backend
peak table: DECLARED constants per TPU device kind (they define the
denominator of a percentage, not a claim about any chip's true ceiling)
and a calibrated-at-boot microbench number for CPU meshes, so tier-1
exercises the whole classification path.

Everything here is measured-nanoseconds + frozen LaunchCost arithmetic:
no jax import, no device touch (the peak microbench runs numpy on the
host exactly once).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..analysis.calibrate import BoundedLRU, CALIB_ALPHA

# bounded per-digest attribution entries (the calibration store's
# eviction policy)
ROOFLINE_STORE_CAP = 128
# a digest whose EWMA launch time sits under this is launch-bound: the
# program is dominated by dispatch/launch overhead, not by data or math
LAUNCH_BOUND_MS = 0.5

# declared per-device-kind peaks: (bytes/s of HBM bandwidth, flops/s).
# Substring-matched against jax's device_kind, most specific first.
# These are roofline DENOMINATORS — deliberately round public numbers.
TPU_PEAKS = (
    ("v5p", (2765e9, 459e12)),
    ("v5e", (819e9, 197e12)),
    ("v5", (819e9, 197e12)),
    ("v6", (1640e9, 918e12)),
    ("v4", (1228e9, 275e12)),
    ("v3", (900e9, 123e12)),
    ("v2", (700e9, 46e12)),
)
DEFAULT_TPU_PEAKS = (900e9, 100e12)

# CPU microbench shape: one stacked copy + one small matmul, best of
# REPS — a stable-enough boot-time denominator, not a benchmark
_CPU_BENCH_MB = 16
_CPU_BENCH_N = 192
_CPU_BENCH_REPS = 3

_cpu_peaks_cache: Optional[tuple] = None
_cpu_mu = threading.Lock()


def _cpu_microbench() -> tuple:
    """Calibrated-at-boot CPU peaks: measured host copy bandwidth and
    matmul flops (best-of-reps).  Cached for the process lifetime."""
    import numpy as np
    a = np.ones((_CPU_BENCH_MB << 20) // 8, dtype=np.float64)
    best_bw = 0.0
    for _ in range(_CPU_BENCH_REPS):
        t0 = time.perf_counter()
        b = a.copy()
        dt = time.perf_counter() - t0
        best_bw = max(best_bw, 2.0 * a.nbytes / max(dt, 1e-9))
    del b
    m = np.ones((_CPU_BENCH_N, _CPU_BENCH_N), dtype=np.float64)
    best_fl = 0.0
    flops = 2.0 * _CPU_BENCH_N ** 3
    for _ in range(_CPU_BENCH_REPS):
        t0 = time.perf_counter()
        m @ m
        dt = time.perf_counter() - t0
        best_fl = max(best_fl, flops / max(dt, 1e-9))
    return (best_bw, best_fl)


def backend_peaks(device_kind: str) -> tuple:
    """(bytes_per_s, flops_per_s, source) for a device kind string."""
    kind = (device_kind or "").lower()
    if "tpu" in kind:
        for sub, peaks in TPU_PEAKS:
            if sub in kind:
                return (*peaks, f"declared:{sub}")
        return (*DEFAULT_TPU_PEAKS, "declared:tpu-default")
    global _cpu_peaks_cache
    with _cpu_mu:
        if _cpu_peaks_cache is None:
            _cpu_peaks_cache = _cpu_microbench()
        bw, fl = _cpu_peaks_cache
    return (bw, fl, "microbench:cpu")


# id(mesh)-free memo: device kind -> peaks (kinds are few)
_mesh_peaks_cache: dict = {}


def peaks_for_mesh(mesh) -> tuple:
    """Per-mesh peak lookup (device kind of chip 0); aggregate peaks
    scale by mesh size — the attribution compares whole-mesh bytes and
    flops against whole-mesh capability."""
    try:
        dev = mesh.devices.reshape(-1)[0]
        kind = str(getattr(dev, "device_kind", "") or dev.platform)
        n_dev = int(mesh.devices.size)
    except (AttributeError, IndexError, TypeError):
        kind, n_dev = "", 1
    ent = _mesh_peaks_cache.get((kind, n_dev))
    if ent is None:
        bw, fl, src = backend_peaks(kind)
        ent = _mesh_peaks_cache[(kind, n_dev)] = (
            bw * n_dev, fl * n_dev, src)
        if len(_mesh_peaks_cache) > 16:
            _mesh_peaks_cache.clear()
    return ent


@dataclass
class RoofStat:
    """One digest's measured utilization state (EWMA over launches)."""
    ewma_ms: float = 0.0
    transfer_bytes: int = 0      # static LaunchCost bytes per launch
    flops: int = 0               # static LaunchCost flops per launch
    measured_hbm: int = 0        # last measured launch peak (copgauge)
    samples: int = 0

    def attribution(self, peaks: tuple) -> dict:
        """Achieved rates vs the peak table + the roofline verdict."""
        t_s = max(self.ewma_ms, 1e-6) / 1e3
        bw, fl = peaks[0], peaks[1]
        bytes_pct = 100.0 * (self.transfer_bytes / t_s) / max(bw, 1.0)
        flops_pct = 100.0 * (self.flops / t_s) / max(fl, 1.0)
        if self.ewma_ms < LAUNCH_BOUND_MS:
            bound = "launch-bound"
        elif bytes_pct >= flops_pct:
            bound = "memory-bound"
        else:
            bound = "compute-bound"
        return {
            "ewma_ms": round(self.ewma_ms, 3),
            "achieved_gbps": round(self.transfer_bytes / t_s / 1e9, 3),
            "achieved_gflops": round(self.flops / t_s / 1e9, 3),
            "bytes_pct": round(min(bytes_pct, 100.0), 3),
            "flops_pct": round(min(flops_pct, 100.0), 3),
            # distance from the roofline: the optimization headroom
            "gap_pct": round(
                100.0 - min(max(bytes_pct, flops_pct), 100.0), 3),
            "bound": bound,
            "measured_hbm": self.measured_hbm,
            "samples": self.samples,
        }


class RooflineStore:
    """Bounded per-digest utilization store; one per process like the
    calibration correction store it mirrors."""

    def __init__(self, cap: int = ROOFLINE_STORE_CAP):
        self._mu = threading.Lock()
        self._entries = BoundedLRU(cap)
        self._peaks: tuple = (0.0, 0.0, "unknown")
        self.observed = 0
        from ..utils.metrics import global_registry
        reg = global_registry()
        self._m_bytes = reg.gauge(
            "tidb_tpu_roofline_bytes_pct",
            "achieved memory bandwidth as % of the backend peak, per "
            "program digest", labels=("digest",))
        self._m_flops = reg.gauge(
            "tidb_tpu_roofline_flops_pct",
            "achieved FLOP rate as % of the backend peak, per program "
            "digest", labels=("digest",))

    def observe(self, digest: str, cost, measured_ns: int,
                peaks: tuple, measured_hbm: int = 0) -> None:
        """Feed one measured launch: EWMA the digest's wall time and
        refresh its static work terms; gauges follow."""
        if cost is None or measured_ns <= 0:
            return
        meas_ms = measured_ns / 1e6
        short = digest[:12]
        with self._mu:
            self._peaks = peaks
            ent = self._entries.get(digest)
            if ent is None:
                ent = RoofStat()
                self._entries.put(digest, ent)
            ent.ewma_ms = meas_ms if ent.samples == 0 else \
                (1.0 - CALIB_ALPHA) * ent.ewma_ms + CALIB_ALPHA * meas_ms
            ent.transfer_bytes = int(cost.transfer_bytes)
            ent.flops = int(cost.flops)
            if measured_hbm > 0:
                ent.measured_hbm = int(measured_hbm)
            ent.samples += 1
            self.observed += 1
            att = ent.attribution(peaks)
        self._m_bytes.set(att["bytes_pct"], digest=short)
        self._m_flops.set(att["flops_pct"], digest=short)

    def get(self, digest: str) -> Optional[dict]:
        with self._mu:
            ent = self._entries.get(digest)
            if ent is None:
                return None
            return ent.attribution(self._peaks)

    def top(self, n: int = 8) -> dict:
        """Top digests by roofline gap (furthest from peak) and by
        measured residency — the /hbm drill-down tables."""
        with self._mu:
            peaks = self._peaks
            rows = [(d, ent.attribution(peaks))
                    for d, ent in self._entries.items()]
        by_gap = sorted(rows, key=lambda kv: -kv[1]["gap_pct"])[:n]
        by_res = sorted(rows, key=lambda kv: -kv[1]["measured_hbm"])[:n]
        return {"by_gap": {d[:16]: att for d, att in by_gap},
                "by_residency": {d[:16]: att for d, att in by_res}}

    def stats(self) -> dict:
        counts: dict = {}
        with self._mu:
            peaks = self._peaks
            n = len(self._entries)
            for _d, ent in self._entries.items():
                b = ent.attribution(peaks)["bound"]
                counts[b] = counts.get(b, 0) + 1
        return {
            "entries": n,
            "observed": self.observed,
            "peak_bytes_per_s": peaks[0],
            "peak_flops_per_s": peaks[1],
            "peak_source": peaks[2],
            "bounds": counts,
        }

    def reset(self) -> None:
        with self._mu:
            self._entries.clear()
            self.observed = 0


_STORE: Optional[RooflineStore] = None
_STORE_MU = threading.Lock()


def roofline_store() -> RooflineStore:
    global _STORE
    with _STORE_MU:
        if _STORE is None:
            _STORE = RooflineStore()
        return _STORE


def roofline_status(n: int = 8) -> dict:
    """The roofline half of the ``/hbm`` status route."""
    store = roofline_store()
    return {**store.stats(), **store.top(n)}


__all__ = ["RoofStat", "RooflineStore", "roofline_store",
           "roofline_status", "backend_peaks", "peaks_for_mesh",
           "LAUNCH_BOUND_MS", "ROOFLINE_STORE_CAP", "TPU_PEAKS"]
