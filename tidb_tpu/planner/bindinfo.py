"""SQL plan management: plan bindings (pkg/bindinfo analog).

A binding maps a NORMALIZED statement digest to a hinted variant of the
same statement.  At plan time, a statement with no hints of its own that
matches a binding digest inherits the binding's optimizer hints — the
production mechanism for pinning a plan without editing application SQL
(bindinfo/binding.go, bind_record.go).  Bindings live per Domain
(GLOBAL) or per Session (SESSION); session bindings shadow global ones.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from ..utils.stmtsummary import normalize_sql


@dataclass
class Binding:
    digest: str          # normalized original statement
    original_sql: str
    bind_sql: str        # the hinted statement
    hints: list = field(default_factory=list)   # parsed [(NAME, [args])]
    status: str = "enabled"


class BindManager:
    """Digest-keyed binding store (bindinfo.BindHandle analog)."""

    def __init__(self):
        self._bindings: dict[str, Binding] = {}
        self._lock = threading.Lock()

    def create(self, original_sql: str, bind_sql: str, hints: list) -> Binding:
        b = Binding(normalize_sql(original_sql), original_sql, bind_sql,
                    hints)
        with self._lock:
            self._bindings[b.digest] = b
        return b

    def drop(self, original_sql: str) -> bool:
        d = normalize_sql(original_sql)
        with self._lock:
            return self._bindings.pop(d, None) is not None

    def match(self, sql: str) -> Optional[Binding]:
        with self._lock:
            b = self._bindings.get(normalize_sql(sql))
        return b if b is not None and b.status == "enabled" else None

    def rows(self) -> list[tuple]:
        with self._lock:
            return [(b.original_sql, b.bind_sql, b.status)
                    for b in self._bindings.values()]


__all__ = ["Binding", "BindManager"]
