"""Instance plan cache.

Reference analog: pkg/planner/core/plan_cache.go + plan_cache_lru.go —
prepared & non-prepared plan cache keyed on statement + schema/stats
state.  Here the key is (sql text, db, per-table schema fingerprints,
plan-relevant sysvars); a table's fingerprint covers its column schema,
index set, and snapshot epoch, so any write or DDL on a referenced table
invalidates naturally (the reference instead checks schema version +
stats version at load time, plan_cache.go:49-61).

Caching the *physical plan object* is sound because executors re-resolve
table snapshots at Open/execute time — the tree holds no row data.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional

# sysvars that change planning decisions -> part of the key
_PLAN_SYSVARS = ("tidb_enable_vectorized_expression",
                 "tidb_opt_agg_push_down", "tidb_isolation_read_engines",
                 "tidb_enable_cascades_planner",
                 "tidb_opt_skew_distinct_agg")


class PlanCacheEntry:
    __slots__ = ("built", "phys", "table_keys")

    def __init__(self, built, phys, table_keys):
        self.built = built
        self.phys = phys
        self.table_keys = table_keys


def table_fingerprint(tbl) -> tuple:
    """Schema + data-epoch fingerprint of one referenced table."""
    return (tbl.table_id, tuple(tbl.col_names),
            tuple(str(t) for t in tbl.col_types),
            tuple((ix.name, tuple(ix.columns), ix.unique, ix.state)
                  for ix in tbl.indexes),
            tbl._epoch)


class PlanCache:
    """LRU over plan entries (plan_cache_lru.go LRUPlanCache analog)."""

    def __init__(self, capacity: int = 128):
        self.capacity = capacity
        self._lru: OrderedDict[tuple, PlanCacheEntry] = OrderedDict()
        self._mu = threading.Lock()   # one thread per server connection
        self.hits = 0
        self.misses = 0

    def _key(self, sql: str, db: str, sysvars: dict) -> tuple:
        return (sql, db, tuple(str(sysvars.get(k, "")) for k in _PLAN_SYSVARS))

    def get(self, sql: str, db: str, sysvars: dict,
            catalog) -> Optional[PlanCacheEntry]:
        key = self._key(sql, db, sysvars)
        with self._mu:
            e = self._lru.get(key)
            if e is None:
                self.misses += 1
                return None
        # validate table fingerprints outside the lock (catalog lookups)
        stale = False
        for (tdb, tname), fp in e.table_keys.items():
            try:
                tbl = catalog.get_table(tdb, tname)
            except Exception:
                tbl = None
            if tbl is None or table_fingerprint(tbl) != fp:
                stale = True
                break
        with self._mu:
            if stale:
                self._lru.pop(key, None)
                self.misses += 1
                return None
            if key in self._lru:
                self._lru.move_to_end(key)
            self.hits += 1
            return e

    def put(self, sql: str, db: str, sysvars: dict, entry: PlanCacheEntry):
        key = self._key(sql, db, sysvars)
        with self._mu:
            self._lru[key] = entry
            self._lru.move_to_end(key)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)


__all__ = ["PlanCache", "PlanCacheEntry", "table_fingerprint"]
