"""Cardinality estimation: predicate selectivity from table statistics.

Reference analog: pkg/planner/cardinality/ (selectivity.go, row_count_*.go)
with the pseudo-stats fallbacks of pseudoEqualRate/pseudoLessRate/
pseudoBetweenRate.  Works over the CNF condition lists the optimizer
collects at each DataSource; values are compared in the column's
order-preserving int64 encoding (stats/build.py).
"""

from __future__ import annotations

from typing import Optional

from ..expr.ir import Expr
from ..stats.handle import TableStats, encode_value
from .ranger import _cmp_parts

# reference: pkg/planner/cardinality/pseudo.go
PSEUDO_LESS_RATE = 3.0
PSEUDO_EQUAL_RATE = 1000.0
PSEUDO_BETWEEN_RATE = 40.0


def _col_meta(ds, ci: int):
    """(name, col_type, dictionary) for schema column ci of a DataSource."""
    name = ds.schema.cols[ci].name
    tbl = ds.table
    ti = tbl.col_names.index(name) if name in tbl.col_names else -1
    if ti < 0:
        return name, None, None
    col_type = tbl.col_types[ti]
    dictionary = None
    if col_type.is_string:
        try:
            dictionary = tbl.snapshot().columns[ti].dictionary
        except Exception:
            dictionary = None
    return name, col_type, dictionary


def cond_selectivity(stats: Optional[TableStats], cond: Expr, ds) -> float:
    """Selectivity in (0, 1] of a single CNF conjunct."""
    p = _cmp_parts(cond)
    if p is None:
        return 0.8           # reference selectionFactor for opaque filters
    op, ci, cst = p
    name, col_type, dictionary = _col_meta(ds, ci)
    cs = stats.col(name) if stats is not None else None
    total = cs.count + cs.null_count if cs is not None else 0
    if cs is None or total == 0 or col_type is None:
        return (1.0 / PSEUDO_EQUAL_RATE if op == "eq"
                else 1.0 / PSEUDO_LESS_RATE)
    enc = encode_value(col_type, cst.value, dictionary)
    if enc is None:
        return 1.0 / PSEUDO_LESS_RATE
    if op == "eq":
        rows = cs.equal_rows(enc)
    elif op in ("lt", "le"):
        rows = cs.range_rows(None, False, enc, op == "le")
    else:
        rows = cs.range_rows(enc, op == "ge", None, False)
    return min(max(rows / total, 1e-9), 1.0)


def conds_selectivity(stats: Optional[TableStats], conds, ds) -> float:
    """Combined selectivity of a CNF list (independence assumption,
    like the reference before its exponential-backoff correlation fix)."""
    s = 1.0
    for c in conds:
        s *= cond_selectivity(stats, c, ds)
    return s


def est_scan_rows(stats: Optional[TableStats], conds, ds) -> float:
    n = ds.table.num_rows
    return n * conds_selectivity(stats, conds, ds)
