"""Partition pruning: conjunctive scan predicates -> surviving partition
ids.

Reference analog: pkg/planner/core/rule/rule_partition_processor.go — the
rule that rewrites a partitioned DataSource into a union of per-partition
scans minus the ones the predicates exclude.  Here partitions are logical
row sets of one columnar snapshot, so "pruning" simply narrows the id
list the CopTask hands to TableInfo.partition_snapshot.

Soundness: predicates are conjunctive; any condition this walker does not
recognize is IGNORED, which can only keep extra partitions — never drop a
live one.
"""

from __future__ import annotations

from typing import Optional

from ..expr.ir import ColumnRef, Const, Func

_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _match_cmp(cond, scan_ix: int):
    """cond as (op, int_value) on the partition column, or None."""
    if not isinstance(cond, Func) or cond.op not in _FLIP:
        return None
    a, b = cond.args if len(cond.args) == 2 else (None, None)
    if isinstance(a, ColumnRef) and a.index == scan_ix \
            and isinstance(b, Const):
        op, v = cond.op, b.value
    elif isinstance(b, ColumnRef) and b.index == scan_ix \
            and isinstance(a, Const):
        op, v = _FLIP[cond.op], a.value
    else:
        return None
    if v is None or isinstance(v, str):
        return None
    try:
        return op, int(v)
    except (TypeError, ValueError):
        return None


def _in_values(cond, scan_ix: int):
    """col IN (c1, c2, ...) -> [ints] (lowered either to an 'in' func or
    an OR-of-eq chain), or None."""
    if not isinstance(cond, Func):
        return None
    if cond.op == "in" and cond.args \
            and isinstance(cond.args[0], ColumnRef) \
            and cond.args[0].index == scan_ix:
        vals = []
        for c in cond.args[1:]:
            if not isinstance(c, Const) or c.value is None \
                    or isinstance(c.value, str):
                return None
            vals.append(int(c.value))
        return vals
    if cond.op == "or":
        vals = []
        for sub in cond.args:
            m = _match_cmp(sub, scan_ix)
            if m is None or m[0] != "eq":
                return None
            vals.append(m[1])
        return vals
    return None


def prune_partitions(spec, scan_ix: int, conds) -> Optional[list]:
    """Surviving partition ids for the conjunction `conds`, or None when
    nothing prunes (all partitions survive)."""
    lo = None   # inclusive lower bound on the partition column
    hi = None   # inclusive upper bound
    eqs: Optional[set] = None
    for cond in conds or ():
        m = _match_cmp(cond, scan_ix)
        if m is not None:
            op, v = m
            if op == "eq":
                eqs = {v} if eqs is None else (eqs & {v})
            elif op == "gt":
                lo = v + 1 if lo is None else max(lo, v + 1)
            elif op == "ge":
                lo = v if lo is None else max(lo, v)
            elif op == "lt":
                hi = v - 1 if hi is None else min(hi, v - 1)
            elif op == "le":
                hi = v if hi is None else min(hi, v)
            continue
        vals = _in_values(cond, scan_ix)
        if vals is not None:
            eqs = set(vals) if eqs is None else (eqs & set(vals))
    if eqs is not None:
        eqs = {v for v in eqs
               if (lo is None or v >= lo) and (hi is None or v <= hi)}
        return sorted({_locate(spec, v) for v in eqs})
    if lo is None and hi is None:
        return None
    n = len(spec.parts)
    if spec.kind == "hash":
        # a narrow interval still prunes hash partitions by enumeration
        if lo is not None and hi is not None and hi - lo < n:
            return sorted({_locate(spec, v) for v in range(lo, hi + 1)})
        return None
    ids = []
    prev = None
    for i, (_, bound) in enumerate(spec.parts):
        p_lo = prev                       # inclusive (None = -inf)
        p_hi = None if bound is None else bound - 1
        prev = bound
        if lo is not None and p_hi is not None and p_hi < lo:
            continue
        if hi is not None and p_lo is not None and p_lo > hi:
            continue
        ids.append(i)
    return ids


def _locate(spec, v: int) -> int:
    if spec.kind == "hash":
        return abs(v) % spec.num
    for i, (_, bound) in enumerate(spec.parts):
        if bound is None or v < bound:
            return i
    return len(spec.parts) - 1


__all__ = ["prune_partitions"]
