"""Cost-based greedy join reorder.

Reference analog: pkg/planner/core/rule/rule_join_reorder.go — the greedy
variant: flatten a maximal inner/cross join tree into a join group, start
from the smallest (post-filter) relation, and repeatedly attach the
relation that minimizes the estimated intermediate result, using table
stats (row counts, per-column NDV) from the ANALYZE subsystem.

The reordered tree is left-deep with a restoring Projection on top so
parent operators keep seeing the original column order.  Left/semi/anti
joins are reorder barriers (they keep their sides, which reorder
internally).
"""

from __future__ import annotations

from typing import Optional

from ..expr.ir import ColumnRef, Expr, Func, referenced_columns
from .cardinality import est_scan_rows
from .logical import (DataSource, LogicalJoin, LogicalPlan,
                      LogicalProjection, LogicalSelection, Schema)
from .optimize import _remap, _subst, map_refs

# joins whose group exceeds this leaf count keep parse order (the
# reference switches from DP to greedy at a threshold; we are greedy-only
# and cap purely defensively)
MAX_GROUP = 12

DEFAULT_ROWS = 1000.0          # leaf estimate without stats


def reorder_joins(plan: LogicalPlan, stats_handle) -> LogicalPlan:
    """Recursively reorder every maximal inner-join group in the plan."""
    if isinstance(plan, LogicalJoin) and plan.kind in ("inner", "cross"):
        return _reorder_group(plan, stats_handle)
    for i, c in enumerate(plan.children):
        plan.children[i] = reorder_joins(c, stats_handle)
    if hasattr(plan, "child"):
        plan.child = plan.children[0]
    if isinstance(plan, LogicalJoin):
        plan.left, plan.right = plan.children
    return plan


# ------------------------------------------------------------------ #

def _flatten(p: LogicalPlan, leaves: list, conds: list, offset: int) -> int:
    """Flatten an inner/cross join tree.  Returns the column count of p.
    conds collect as (expr-over-original-global-order)."""
    if isinstance(p, LogicalJoin) and p.kind in ("inner", "cross"):
        n_left = _flatten(p.left, leaves, conds, offset)
        n_right = _flatten(p.right, leaves, conds, offset + n_left)
        for li, ri in p.eq_keys:
            l = p.left.schema.ref(li)
            r = p.right.schema.ref(ri)
            conds.append(Func(
                l.dtype, "eq",
                (ColumnRef(l.dtype, li + offset, l.name),
                 ColumnRef(r.dtype, ri + offset + n_left, r.name))))
        for c in p.other_conds:
            conds.append(_remap(c, offset))
        return n_left + n_right
    leaves.append((offset, p))
    return len(p.schema)


def _leaf_rows(leaf: LogicalPlan, stats_handle) -> float:
    """Estimated post-filter cardinality of a join-group leaf."""
    conds: list = []
    cur = leaf
    while isinstance(cur, (LogicalSelection, LogicalProjection)):
        if isinstance(cur, LogicalSelection):
            conds += list(cur.conditions)
        else:
            # rebase collected conditions through the projection so they
            # reference DataSource columns (matches _col_ndv's walk)
            try:
                conds = [_subst(c, cur.exprs) for c in conds]
            except IndexError:
                return DEFAULT_ROWS
        cur = cur.children[0]
    if isinstance(cur, DataSource):
        st = stats_handle.get(cur.table) if stats_handle is not None else None
        try:
            return max(est_scan_rows(st, conds, cur), 1.0)
        except Exception:
            return max(float(cur.table.num_rows), 1.0)
    n = getattr(getattr(cur, "table", None), "num_rows", None)
    return float(n) if n else DEFAULT_ROWS


def _col_ndv(leaf: LogicalPlan, local_ci: int, stats_handle,
             fallback: float) -> float:
    """NDV of a leaf's output column (for eq-join size estimation)."""
    cur = leaf
    ci = local_ci
    while isinstance(cur, (LogicalSelection, LogicalProjection)):
        if isinstance(cur, LogicalProjection):
            e = cur.exprs[ci]
            if not isinstance(e, ColumnRef):
                return fallback
            ci = e.index
        cur = cur.children[0]
    if isinstance(cur, DataSource) and stats_handle is not None:
        st = stats_handle.get(cur.table)
        if st is not None and ci < len(cur.col_offsets):
            name = cur.schema.cols[ci].name
            cs = st.col(name)
            if cs is not None and cs.ndv > 0:
                return float(cs.ndv)
    return fallback


def _refs_leaves(e: Expr, spans: list) -> set:
    """Which leaves (by position in spans) an expr references."""
    out = set()
    for r in referenced_columns(e):
        for i, (lo, hi) in enumerate(spans):
            if lo <= r < hi:
                out.add(i)
                break
    return out


def _reorder_inside_leaves(p: LogicalPlan, stats_handle) -> None:
    """Oversized group: keep its order but still reorder nested join
    groups hiding inside the group's leaves (e.g. under outer joins)."""
    if isinstance(p, LogicalJoin) and p.kind in ("inner", "cross"):
        _reorder_inside_leaves(p.left, stats_handle)
        _reorder_inside_leaves(p.right, stats_handle)
        return
    for i, c in enumerate(p.children):
        p.children[i] = reorder_joins(c, stats_handle)
    if hasattr(p, "child"):
        p.child = p.children[0]
    if isinstance(p, LogicalJoin):
        p.left, p.right = p.children


def _reorder_group(root: LogicalJoin, stats_handle) -> LogicalPlan:
    leaves_off: list = []
    conds: list = []
    total_cols = _flatten(root, leaves_off, conds, 0)
    leaves = [l for _, l in leaves_off]
    spans = [(off, off + len(l.schema)) for off, l in leaves_off]
    if not (2 <= len(leaves) <= MAX_GROUP):
        _reorder_inside_leaves(root, stats_handle)
        return root
    # reorder each leaf's own interior first
    leaves = [reorder_joins(l, stats_handle) for l in leaves]

    rows = [_leaf_rows(l, stats_handle) for l in leaves]
    cond_leafsets = [_refs_leaves(c, spans) for c in conds]

    def eq_edge(placed: set, cand: int):
        """eq conds joining the placed set to candidate `cand`; returns
        the max NDV across candidate-side key columns (join fanout)."""
        best = None
        for c, ls in zip(conds, cond_leafsets):
            if not (isinstance(c, Func) and c.op == "eq"):
                continue
            if cand not in ls or not (ls - {cand}) <= placed or len(ls) != 2:
                continue
            for r in referenced_columns(c):
                lo, hi = spans[cand]
                if lo <= r < hi:
                    ndv = _col_ndv(leaves[cand], r - lo, stats_handle,
                                   rows[cand])
                    best = ndv if best is None else max(best, ndv)
        return best

    # greedy: smallest leaf first, then minimize the running estimate.
    # LEADING(t, ...) pins the hinted table as the greedy start.
    order = None
    lead = getattr(root, "hint_leading", None)
    if lead:
        from .logical import find_datasource
        for t in lead:
            hit = next((i for i, l in enumerate(leaves)
                        if find_datasource(l, t) is not None), None)
            if hit is not None:
                order = [hit]
                break
    if order is None:
        order = [min(range(len(leaves)), key=lambda i: rows[i])]
    cur_rows = rows[order[0]]
    remaining = set(range(len(leaves))) - set(order)
    while remaining:
        # connected candidates (an eq edge to the placed set) strictly
        # before cross products — a cheap cross of two filtered tiny
        # tables must not beat joining along the graph (the reference's
        # greedy walks join edges; cartesian only when disconnected)
        best_i, best_est = None, None
        best_cross_i, best_cross_est = None, None
        for i in sorted(remaining):
            ndv = eq_edge(set(order), i)
            if ndv is not None:
                est = cur_rows * rows[i] / max(ndv, 1.0)
                if best_est is None or est < best_est:
                    best_i, best_est = i, est
            else:
                est = cur_rows * rows[i]
                if best_cross_est is None or est < best_cross_est:
                    best_cross_i, best_cross_est = i, est
        if best_i is None:            # disconnected: cross join
            best_i, best_est = best_cross_i, best_cross_est
        order.append(best_i)
        remaining.discard(best_i)
        cur_rows = max(best_est, 1.0)

    # rebuild in greedy order.  Physical orientation: both the broadcast
    # lookup join and the host hash join BUILD on the right, so each join
    # keeps its larger input on the left (probe) — the accumulated small
    # intermediate becomes the build side under a big probe table.
    placed = {order[0]}
    cur: LogicalPlan = leaves[order[0]]
    cur_origin = list(range(*spans[order[0]]))   # original global indexes
    cur_est = rows[order[0]]
    used = [False] * len(conds)
    for i in order[1:]:
        nxt = leaves[i]
        nxt_origin = list(range(*spans[i]))
        swap = rows[i] > cur_est        # bigger side probes (left)
        if swap:
            left, right = nxt, cur
            origin = nxt_origin + cur_origin
        else:
            left, right = cur, nxt
            origin = cur_origin + nxt_origin
        remap = {orig: newi for newi, orig in enumerate(origin)}
        n_left = len(left.schema)
        eq_keys: list = []
        others: list = []
        for j, (c, ls) in enumerate(zip(conds, cond_leafsets)):
            if used[j] or not ls <= placed | {i}:
                continue
            used[j] = True
            c2 = map_refs(c, remap)
            k = _as_local_eq(c2, n_left, len(right.schema))
            if k is not None:
                eq_keys.append(k)
            else:
                others.append(c2)
        placed.add(i)
        cur = LogicalJoin(
            "inner" if (eq_keys or others) else "cross", left, right,
            eq_keys=eq_keys, other_conds=others,
            schema=Schema(list(left.schema.cols) + list(right.schema.cols)))
        cur_origin = origin
        ndv = eq_edge(placed - {i}, i)
        cur_est = (cur_est * rows[i] / max(ndv, 1.0) if ndv is not None
                   else cur_est * rows[i])
    final_map = {orig: newi for newi, orig in enumerate(cur_origin)}
    # any condition not placed (shouldn't happen) goes above
    rest = [map_refs(c, final_map)
            for j, c in enumerate(conds) if not used[j]]
    if rest:
        cur = LogicalSelection(cur, rest)
    if cur_origin == list(range(total_cols)) and order == sorted(order):
        return cur       # layout unchanged; no restore needed
    # restore the original column order for parents
    refs = [cur.schema.ref(final_map[r]) for r in range(total_cols)]
    return LogicalProjection(cur, refs, Schema(list(root.schema.cols)))


def _as_local_eq(e: Expr, n_left: int, n_right: int):
    if (isinstance(e, Func) and e.op == "eq"
            and isinstance(e.args[0], ColumnRef)
            and isinstance(e.args[1], ColumnRef)):
        a, b = e.args[0].index, e.args[1].index
        if a < n_left <= b < n_left + n_right:
            return (a, b - n_left)
        if b < n_left <= a < n_left + n_right:
            return (b, a - n_left)
    return None


__all__ = ["reorder_joins"]
