"""AST -> logical plan builder (name/type resolution).

Reference analog: pkg/planner/core/logical_plan_builder.go (PlanBuilder) —
resolves identifiers against child schemas, types every expression (into
expr/ir.py IR), splits AVG into SUM/COUNT (SURVEY.md §A.4), rewrites
aggregate queries into LogicalAggregate + projection over its output, and
resolves ORDER BY against aliases/positions/underlying columns with hidden
columns, like the reference's havingWindowAndOrderbyExprResolver.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..copr.dag import AggFunc
from ..expr import builders as B
from ..expr.ir import ColumnRef, Const, Expr, Func
from ..sql import ast as A
from ..types import dtypes as dt
from ..types import temporal as tmp
from ..copr.aggregate import sum_out_dtype
from .logical import (AggItem, CTEStorage, DataSource, LogicalAggregate,
                      LogicalCTEScan, LogicalExpand, LogicalJoin,
                      LogicalLimit, LogicalPlan, LogicalProjection,
                      LogicalSelection, LogicalSetOp, LogicalSort,
                      LogicalTopN, LogicalWindow, Schema, SchemaCol,
                      WindowItem)

K = dt.TypeKind

AGG_FUNCS = {"SUM", "COUNT", "AVG", "MIN", "MAX",
             "STDDEV", "STD", "STDDEV_POP", "STDDEV_SAMP",
             "VARIANCE", "VAR_POP", "VAR_SAMP",
             "BIT_AND", "BIT_OR", "BIT_XOR",
             "GROUP_CONCAT", "ANY_VALUE", "APPROX_COUNT_DISTINCT",
             "JSON_ARRAYAGG", "GROUPING"}

_CMP = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
_ARITH = {"+": "add", "-": "sub", "*": "mul", "/": "div", "DIV": "intdiv",
          "%": "mod"}


class PlanError(ValueError):
    pass


# Session-installed hook that plans+executes an uncorrelated subquery AST
# and returns its scalar result as a Const (None when absent — e.g. pure
# parser/planner tests).  ContextVar so concurrent server sessions don't
# stomp each other.
import contextvars

SUBQUERY_EXECUTOR: contextvars.ContextVar = contextvars.ContextVar(
    "subquery_executor", default=None)

# Correlated-subquery ident hook: consulted by _b_ident when the local
# schema misses; returns an Expr or None.  Installed (a) during the
# trial build that discovers a subquery's outer references, and (b)
# during per-row apply execution with actual outer values bound.
OUTER_RESOLVER: contextvars.ContextVar = contextvars.ContextVar(
    "outer_resolver", default=None)

# list the session installs per statement; builders append a reason when
# the plan embeds statement-time state (NOW(), scalar subquery results)
# so the plan cache skips it
# session identity visible to scalar functions (DATABASE()/USER()/
# CONNECTION_ID()/LAST_INSERT_ID()): set by the session around planning
SESSION_INFO: contextvars.ContextVar = contextvars.ContextVar(
    "session_info", default=None)

PLAN_TAINTS: contextvars.ContextVar = contextvars.ContextVar(
    "plan_taints", default=None)

# sequence-name resolver installed by the session: name -> SequenceInfo
SEQUENCE_RESOLVER: contextvars.ContextVar = contextvars.ContextVar(
    "sequence_resolver", default=None)


def _taint_plan(reason: str) -> None:
    t = PLAN_TAINTS.get()
    if t is not None:
        t.append(reason)


# --------------------------------------------------------------------- #
# expression building over a schema
# --------------------------------------------------------------------- #

class ExprBuilder:
    """AST expression -> typed IR over `schema`.  Aggregate calls are
    rejected unless an agg_resolver intercepts them (select-list path);
    window calls likewise require a window_resolver."""

    def __init__(self, schema: Schema, agg_resolver=None,
                 window_resolver=None, outer_resolver=None):
        self.schema = schema
        self.agg_resolver = agg_resolver
        self.window_resolver = window_resolver
        # correlated-subquery hook: called with an Ident the local schema
        # can't resolve; returns an Expr bound to the OUTER query or None
        self.outer_resolver = outer_resolver

    def build(self, n: A.Node) -> Expr:
        m = getattr(self, f"_b_{type(n).__name__.lower()}", None)
        if m is None:
            raise PlanError(f"unsupported expression {type(n).__name__}")
        return m(n)

    # ---- leaves ---- #

    def _b_sysvar(self, n: "A.SysVar") -> Expr:
        """@@sysvar / @uservar -> session-resolved constant (plans
        tainted: the value varies per connection/SET)."""
        info = SESSION_INFO.get() or {}
        _taint_plan("sysvar")
        getter = info.get("getuservar" if n.user else "getvar")
        v = getter(n.name, n.scope) if getter is not None else None
        if v is None:
            return Const(dt.null_type(), None)
        if isinstance(v, bool):
            return Const(dt.bigint(False), int(v))
        if isinstance(v, int):
            return Const(dt.bigint(False), v)
        if isinstance(v, float):
            return Const(dt.double(False), v)
        return Const(dt.varchar(False), str(v))

    def _b_ident(self, n: A.Ident) -> Expr:
        if len(n.parts) == 1:
            q, name = None, n.parts[0]
        else:
            q, name = n.parts[-2], n.parts[-1]
        hits = self.schema.find(name, q)
        # NO unqualified fallback on a qualifier miss: that silently
        # bound t.k inside a subquery over u to u.k — wrong results.
        # An outer_resolver (correlated subquery build) may claim it.
        if not hits:
            res = self.outer_resolver or OUTER_RESOLVER.get()
            if res is not None:
                out = res(n)
                if out is not None:
                    return out
            raise PlanError(f"unknown column {'.'.join(n.parts)!r}")
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {name!r}")
        return self.schema.ref(hits[0])

    def _b_lit(self, n: A.Lit) -> Expr:
        if n.kind == "int":
            return B.lit(int(n.value))
        if n.kind == "bool":
            return B.lit(int(n.value))
        if n.kind == "decimal":
            return B.decimal_lit(str(n.value))
        if n.kind == "float":
            return B.lit(float(n.value))
        if n.kind == "str":
            return B.lit(str(n.value))
        if n.kind == "null":
            return B.lit(None)
        if n.kind == "date":
            return B.lit(str(n.value), dt.date())
        if n.kind == "datetime":
            return B.lit(str(n.value), dt.datetime())
        if n.kind == "interval":
            raise PlanError("INTERVAL only valid in +/- with a date")
        raise PlanError(f"unknown literal kind {n.kind}")

    # ---- operators ---- #

    def _b_binary(self, n: A.Binary) -> Expr:
        op = n.op
        if op in ("AND", "OR", "XOR"):
            return B.logic(op.lower(), self.build(n.left), self.build(n.right))
        if op in _CMP:
            a, b = self.build(n.left), self.build(n.right)
            a, b = _coerce_compare(a, b)
            return B.compare(_CMP[op], a, b)
        if op in _ARITH:
            # temporal interval arithmetic
            if isinstance(n.right, A.Lit) and n.right.kind == "interval":
                return self._interval_arith(n)
            lhs, rhs = self.build(n.left), self.build(n.right)
            # MySQL numeric context: strings coerce to double ('12.7'+1)
            if lhs.dtype.is_string:
                lhs = _coerce_to(dt.double(), lhs)
            if rhs.dtype.is_string:
                rhs = _coerce_to(dt.double(), rhs)
            return B.arith(_ARITH[op], lhs, rhs)
        raise PlanError(f"unsupported operator {op}")

    def _interval_arith(self, n: A.Binary) -> Expr:
        base = self.build(n.left)
        iv: A.Lit = n.right
        amt_e = ExprBuilder(self.schema).build(iv.value) \
            if isinstance(iv.value, A.Node) else B.lit(int(iv.value))
        if not isinstance(amt_e, Const):
            raise PlanError("INTERVAL amount must be constant")
        amount = int(str(amt_e.value)) if not isinstance(amt_e.value, int) \
            else amt_e.value
        if n.op == "-":
            amount = -amount
        unit = iv.unit
        if base.dtype.is_string:
            base = _coerce_to(dt.datetime(), base)
        if base.dtype.kind not in (K.DATE, K.DATETIME):
            raise PlanError("INTERVAL arithmetic needs a date operand")
        if isinstance(base, Const) and base.dtype.kind == K.DATE \
                and unit in ("DAY", "MONTH", "YEAR"):
            return _fold_interval_const(base, amount, unit)
        return B.date_add(base, B.lit(amount), unit)

    def _b_unary(self, n: A.Unary) -> Expr:
        if n.op == "NOT":
            return B.logic("not", self.build(n.arg))
        if n.op == "-":
            a = self.build(n.arg)
            if isinstance(a, Const) and a.dtype.is_numeric:
                return Const(a.dtype, -a.value)
            return B.neg(a)
        raise PlanError(f"unsupported unary {n.op}")

    def _b_inexpr(self, n: A.InExpr) -> Expr:
        if any(isinstance(i, A.SubqueryExpr) for i in n.items):
            raise PlanError("IN (subquery) not supported yet")
        t = self.build(n.target)
        items = [_coerce_to(t.dtype, self.build(i)) for i in n.items]
        e = B.in_list(t, items)
        return B.logic("not", e) if n.negated else e

    def _b_betweenexpr(self, n: A.BetweenExpr) -> Expr:
        t = self.build(n.target)
        lo = _coerce_to(t.dtype, self.build(n.low))
        hi = _coerce_to(t.dtype, self.build(n.high))
        e = B.between(t, lo, hi)
        return B.logic("not", e) if n.negated else e

    def _b_likeexpr(self, n: A.LikeExpr) -> Expr:
        t = self.build(n.target)
        p = self.build(n.pattern)
        e = Func(dt.bigint(t.dtype.nullable), "like", (t, p))
        return B.logic("not", e) if n.negated else e

    def _b_isnullexpr(self, n: A.IsNullExpr) -> Expr:
        e = B.is_null(self.build(n.target))
        return B.logic("not", e) if n.negated else e

    def _b_caseexpr(self, n: A.CaseExpr) -> Expr:
        if n.operand is not None:
            op = self.build(n.operand)
            pairs = []
            for c, v in n.branches:
                cv = _coerce_to(op.dtype, self.build(c))
                pairs.append((B.compare("eq", op, cv), self.build(v)))
        else:
            pairs = [(self.build(c), self.build(v)) for c, v in n.branches]
        els = self.build(n.else_) if n.else_ is not None else None
        return B.case_when(pairs, els)

    def _b_castexpr(self, n: A.CastExpr) -> Expr:
        a = self.build(n.arg)
        tn = n.type_name.upper()
        if isinstance(a, Const) and isinstance(a.value, str):
            folded = _fold_const_str_cast(a.value, tn, n)
            if folded is not None:
                return folded
        if tn in ("SIGNED", "SIGNED INTEGER", "INT", "BIGINT"):
            to = dt.bigint()
        elif tn in ("UNSIGNED", "UNSIGNED INTEGER"):
            to = dt.ubigint()
        elif tn in ("DOUBLE", "REAL", "FLOAT"):
            to = dt.double()
        elif tn == "DECIMAL":
            to = dt.decimal(n.prec if n.prec > 0 else 10,
                            n.scale if n.scale >= 0 else 0)
        elif tn == "DATE":
            to = dt.date()
        elif tn in ("DATETIME", "TIMESTAMP"):
            to = dt.datetime()
        elif tn == "TIME":
            if isinstance(a, Const) and isinstance(a.value, str):
                return _time_literal(a)
            to = dt.time()
        elif tn in ("CHAR", "VARCHAR", "NCHAR", "BINARY"):
            # CAST(x AS CHAR[(n)]): string targets route non-string
            # sources to the host cast_char producer; string sources
            # stay and lower as dictionary truncation/passthrough
            ln = n.prec if n.prec > 0 else None
            if a.dtype.is_string:
                if ln is None:
                    return a
                node = Func(dt.varchar(a.dtype.nullable), "cast", (a,))
            else:
                node = Func(dt.varchar(a.dtype.nullable), "cast_char",
                            (a,))
            if ln is not None:
                object.__setattr__(node, "_char_len", int(ln))
            return node
        else:
            raise PlanError(f"unsupported CAST target {tn}")
        if a.dtype.is_string and to.kind in (dt.TypeKind.DATE,
                                             dt.TypeKind.DATETIME):
            # unparseable strings cast to NULL (relaxed MySQL coercion)
            return Func(to.with_nullable(True), "cast", (a,))
        return B.cast(a, to)

    def _b_funccall(self, n: A.FuncCall) -> Expr:
        name = n.name
        if n.over is not None:
            if self.window_resolver is None:
                raise PlanError(f"window function {name} not allowed here")
            return self.window_resolver(n)
        if name in AGG_FUNCS:
            if self.agg_resolver is None:
                raise PlanError(f"aggregate {name} not allowed here")
            return self.agg_resolver(n)
        if name in ("NEXTVAL", "LASTVAL", "SETVAL"):
            # sequence functions (reference: ddl/sequence.go,
            # expression/builtin_func: nextval/lastval/setval)
            resolver = SEQUENCE_RESOLVER.get()
            if resolver is None:
                raise PlanError(f"{name} requires a session context")
            if not n.args or not isinstance(n.args[0], A.Ident):
                raise PlanError(f"{name} needs a sequence name")
            seq = resolver(n.args[0].parts[-1])
            _taint_plan("sequence")      # side-effecting, never plan-cache
            ref = Const(dt.bigint(False), seq)
            if name == "NEXTVAL":
                return Func(dt.bigint(False), "seq_next", (ref,))
            if name == "LASTVAL":
                return Func(dt.bigint(True), "seq_last", (ref,))
            if len(n.args) != 2:
                raise PlanError("SETVAL needs (sequence, value)")
            return Func(dt.bigint(False), "seq_set",
                        (ref, self.build(n.args[1])))
        if name in ("DATE_ADD", "ADDDATE", "DATE_SUB", "SUBDATE"):
            # the INTERVAL argument is not an expression — don't build it
            base = _coerce_to(dt.date(), self.build(n.args[0]))
            return self._date_addsub(name, n, [base])
        if name in ("TIMESTAMPDIFF", "TIMESTAMPADD"):
            # first argument is a bare unit keyword, not an expression
            if not n.args or not isinstance(n.args[0], A.Ident):
                raise PlanError(f"{name} needs a unit keyword")
            unit = n.args[0].parts[-1].upper()
            rest = [self.build(a) for a in n.args[1:]]
            if name == "TIMESTAMPADD":
                base = rest[1]
                if base.dtype.is_string:
                    base = _coerce_to(dt.datetime(), base)
                if not (isinstance(rest[0], Const)
                        and rest[0].value is not None):
                    raise PlanError("TIMESTAMPADD amount must be constant")
                return B.date_add(base, B.lit(int(rest[0].value)), unit)
            return self._timestampdiff(unit, rest[0], rest[1])
        if name == "GET_FORMAT":
            # first argument is a type keyword (DATE/TIME/DATETIME/...)
            if len(n.args) != 2:
                raise PlanError("GET_FORMAT takes (type, standard)")
            kind = (n.args[0].parts[-1].upper()
                    if isinstance(n.args[0], A.Ident)
                    else str(getattr(n.args[0], "value", "")).upper())
            std_e = self.build(n.args[1])
            std = (str(std_e.value).upper()
                   if isinstance(std_e, Const) else "")
            fmt = _GET_FORMATS.get((kind, std))
            return B.lit(fmt) if fmt is not None else B.lit(None)
        args = [self.build(a) for a in n.args
                if not isinstance(a, A.Star)]
        if name in ("YEAR", "MONTH", "QUARTER", "DAYOFWEEK", "WEEKDAY",
                    "DAYOFYEAR", "HOUR", "MINUTE", "SECOND", "MICROSECOND",
                    "TO_DAYS", "UNIX_TIMESTAMP"):
            base = args[0]
            if base.dtype.is_string:
                to = (dt.datetime() if name in ("HOUR", "MINUTE", "SECOND",
                                                "MICROSECOND",
                                                "UNIX_TIMESTAMP")
                      else dt.date())
                base = _coerce_to(to, base)
            return B.temporal_part(name.lower(), base)
        if name == "FROM_DAYS":
            return Func(dt.date(args[0].dtype.nullable), "from_days",
                        (args[0],))
        if name in ("DAY", "DAYOFMONTH"):
            return B.temporal_part("dayofmonth", args[0])
        if name == "LAST_DAY":
            return B.last_day(args[0])
        if name == "DATEDIFF":
            return B.datediff(_coerce_to(dt.date(), args[0]),
                              _coerce_to(dt.date(), args[1]))
        if name == "EXTRACT":
            # parser encodes EXTRACT(unit FROM x) as FuncCall with the unit
            # name stashed first as a string literal
            unit = n.args[0].value if isinstance(n.args[0], A.Lit) else None
            part = {"YEAR": "year", "MONTH": "month", "DAY": "dayofmonth",
                    "QUARTER": "quarter", "HOUR": "hour", "MINUTE": "minute",
                    "SECOND": "second",
                    "MICROSECOND": "microsecond"}.get(str(unit).upper())
            if part is None:
                raise PlanError(f"unsupported EXTRACT unit {unit}")
            return B.temporal_part(part, args[1])
        if name in ("VEC_COSINE_DISTANCE", "VEC_L2_DISTANCE",
                    "VEC_L1_DISTANCE", "VEC_NEGATIVE_INNER_PRODUCT"):
            # vector similarity (reference: types VectorFloat32 +
            # expression vec builtins); args coerce from '[..]' text
            return Func(dt.double(True), name.lower(),
                        (self._vec_arg(args[0], name),
                         self._vec_arg(args[1], name)))
        if name == "VEC_DIMS":
            return Func(dt.bigint(True), "vec_dims",
                        (self._vec_arg(args[0], name),))
        if name == "VEC_L2_NORM":
            return Func(dt.double(True), "vec_l2_norm",
                        (self._vec_arg(args[0], name),))
        if name == "VEC_FROM_TEXT":
            return self._vec_arg(args[0], name)
        if name == "VEC_AS_TEXT":
            return Func(dt.varchar(True), "vec_as_text",
                        (self._vec_arg(args[0], name),))
        if name == "ABS":
            return Func(args[0].dtype, "abs", tuple(args))
        if name in ("CEIL", "CEILING"):
            return B.math_func("ceil", args[0])
        if name == "FLOOR":
            return B.math_func("floor", args[0])
        if name in ("ROUND", "TRUNCATE"):
            d = 0
            if len(args) > 1:
                if not isinstance(args[1], Const):
                    raise PlanError(f"{name} digits must be constant")
                d = int(args[1].value)
            return B.round_func(args[0], d, truncate=(name == "TRUNCATE"))
        if name in ("POW", "POWER"):
            return B.math_func("pow", args[0], args[1])
        if name == "LOG" and len(args) == 2:
            return B.math_func("log", args[0], args[1])
        if name in ("SQRT", "EXP", "LOG", "LOG2", "LOG10", "SIN", "COS",
                    "TAN", "COT", "ASIN", "ACOS", "ATAN", "RADIANS",
                    "DEGREES"):
            op = "ln" if name == "LOG" else name.lower()
            return B.math_func(op, args[0])
        if name == "LN":
            return B.math_func("ln", args[0])
        if name == "ATAN2":
            return B.math_func("atan2", args[0], args[1])
        if name == "SIGN":
            return B.math_func("sign", args[0])
        if name == "PI":
            return B.lit(float(np.pi))
        if name == "MOD":
            return B.arith("mod", args[0], args[1])
        if name in ("GREATEST", "LEAST"):
            return B.greatest_least(name.lower(), args)
        if name in ("UPPER", "UCASE"):
            return self._str_func("upper", args[0])
        if name in ("LOWER", "LCASE"):
            return self._str_func("lower", args[0])
        if name in ("LENGTH", "OCTET_LENGTH"):
            return self._str_func("length", args[0])
        if name in ("CHAR_LENGTH", "CHARACTER_LENGTH"):
            return self._str_func("char_length", args[0])
        if name in ("SUBSTRING", "SUBSTR", "MID"):
            return self._str_func("substring", *args)
        if name == "CONCAT":
            return self._str_func("concat", *args)
        if name in ("TRIM", "LTRIM", "RTRIM", "REVERSE", "REPLACE",
                    "LEFT", "RIGHT", "LPAD", "RPAD", "ASCII", "LOCATE",
                    "INSTR", "REPEAT", "SUBSTRING_INDEX", "MD5", "SHA1",
                    "SHA2", "SOUNDEX", "CRC32", "STRCMP"):
            return self._str_func(name.lower(), *args)
        if name == "HEX" and args[0].dtype.kind == K.STRING:
            return self._str_func("hex", args[0])
        if name == "WEIGHT_STRING":
            if not args[0].dtype.is_string:
                return B.lit(None)     # MySQL: non-string -> NULL
            return self._str_func("weight_string", args[0],
                                  B.lit(args[0].dtype.collation))
        if name == "SHA":
            return self._str_func("sha1", *args)
        if name in ("WEEK", "WEEKOFYEAR"):
            base = args[0]
            if base.dtype.is_string:
                base = _coerce_to(dt.date(), base)
            if base.dtype.kind not in (K.DATE, K.DATETIME):
                raise PlanError(f"{name} needs a date operand")
            mode = 3 if name == "WEEKOFYEAR" else 0
            if name == "WEEK" and len(args) > 1:
                if not (isinstance(args[1], Const)
                        and args[1].value in (0, 3)):
                    raise PlanError("WEEK supports modes 0 and 3")
                mode = int(args[1].value)
            return Func(dt.bigint(base.dtype.nullable), "week",
                        (base, B.lit(mode)))
        if name == "FROM_UNIXTIME" and len(args) == 1:
            return Func(dt.datetime(args[0].dtype.nullable),
                        "from_unixtime", (args[0],))
        if name == "MAKEDATE":
            return Func(dt.date(True), "makedate", (args[0], args[1]))
        if name == "DATE_FORMAT":
            if not (len(args) == 2 and isinstance(args[1], Const)
                    and isinstance(args[1].value, str)):
                raise PlanError("DATE_FORMAT needs a constant format")
            base = args[0]
            if base.dtype.is_string:
                base = _coerce_to(dt.datetime(), base)
            if base.dtype.kind not in (K.DATE, K.DATETIME):
                raise PlanError("DATE_FORMAT needs a date operand")
            return Func(dt.varchar(base.dtype.nullable), "date_format",
                        (base, args[1]))
        if name == "CONCAT_WS":
            if len(args) < 2:
                raise PlanError("CONCAT_WS needs a separator + arguments")
            sep = args[0]
            if not (isinstance(sep, Const) and isinstance(sep.value, str)):
                raise PlanError("CONCAT_WS needs a constant separator")
            items = list(args[1:])
            null_ix = [i for i, a in enumerate(items) if a.dtype.nullable]
            if not null_ix:
                woven: list = []
                for a in items:
                    if woven:
                        woven.append(sep)
                    woven.append(a)
                return self._str_func("concat", *woven)
            # NULL args are SKIPPED (builtin_string.go concatWS): expand
            # the 2^k null patterns of the k nullable args into a CASE —
            # each branch is a plain concat, so the whole expression
            # lowers to merged-dictionary gathers on device
            if len(null_ix) > 4:
                raise PlanError("CONCAT_WS supports at most 4 nullable "
                                "arguments")
            pairs = []
            for pat in range(1, 1 << len(null_ix)):   # >=1 arg NULL
                conds = []
                skip = set()
                for b, i in enumerate(null_ix):
                    if pat >> b & 1:
                        conds.append(B.is_null(items[i]))
                        skip.add(i)
                    else:
                        conds.append(B.logic("not", B.is_null(items[i])))
                cond = conds[0]
                for c in conds[1:]:
                    cond = B.logic("and", cond, c)
                kept = [a for i, a in enumerate(items) if i not in skip]
                woven = []
                for a in kept:
                    if woven:
                        woven.append(sep)
                    woven.append(a)
                val = (self._str_func("concat", *woven) if woven
                       else B.lit(""))
                pairs.append((cond, val))
            woven = []
            for a in items:
                if woven:
                    woven.append(sep)
                woven.append(a)
            return B.case_when(pairs, self._str_func("concat", *woven))
        if name in ("BIN", "OCT") or (name == "HEX"
                                      and args[0].dtype.kind != K.STRING):
            if not args[0].dtype.is_integer:
                raise PlanError(f"{name} needs an integer operand")
            base = {"BIN": 2, "OCT": 8, "HEX": 16}[name]
            return Func(dt.varchar(args[0].dtype.nullable), "int_to_base",
                        (args[0], B.lit(base)))
        if name == "FORMAT":
            if not (len(args) == 2 and isinstance(args[1], Const)):
                raise PlanError("FORMAT needs a constant decimal count")
            return Func(dt.varchar(args[0].dtype.nullable), "format_num",
                        (args[0], args[1]))
        if name in ("DAYNAME", "MONTHNAME"):
            base = args[0]
            if base.dtype.is_string:
                base = _coerce_to(dt.date(), base)
            if base.dtype.kind not in (K.DATE, K.DATETIME):
                raise PlanError(f"{name} needs a date operand")
            if isinstance(base, Const):
                if base.value is None:
                    return Const(dt.null_type(), None)
                from ..types.temporal import days_to_date
                days = int(base.value)
                if base.dtype.kind == K.DATETIME:
                    from ..types.temporal import MICROS_PER_DAY
                    days //= MICROS_PER_DAY
                d0 = days_to_date(days)
                return B.lit(d0.strftime("%A") if name == "DAYNAME"
                             else d0.strftime("%B"))
            from ..expr.lower_strings import _derived_map
            if name == "DAYNAME":
                names_ = ["Monday", "Tuesday", "Wednesday", "Thursday",
                          "Friday", "Saturday", "Sunday"]
                key = Func(dt.bigint(base.dtype.nullable), "weekday",
                           (base,))
            else:
                names_ = ["", "January", "February", "March", "April",
                          "May", "June", "July", "August", "September",
                          "October", "November", "December"]
                key = Func(dt.bigint(base.dtype.nullable), "month",
                           (base,))
            return _derived_map(
                dt.varchar(base.dtype.nullable), key, names_)
        if name == "POSITION":
            return self._str_func("locate", args[0], args[1])
        if name == "ISNULL":
            return B.is_null(args[0])
        if name in ("QUOTE", "TO_BASE64", "FROM_BASE64", "UNHEX",
                    "BIT_LENGTH", "INET_ATON", "REGEXP_SUBSTR",
                    "REGEXP_REPLACE", "REGEXP_INSTR", "REGEXP_LIKE"):
            return self._str_func(name.lower(), *args)
        if name == "INSERT":
            if len(args) != 4:
                raise PlanError("INSERT needs (str, pos, len, newstr)")
            return self._str_func("insert_str", *args)
        if name == "ELT":
            # ELT(n, s1..sk) -> CASE n WHEN i THEN s_i (control-flow
            # rewrite lowers to merged-dictionary gathers on device)
            if len(args) < 2:
                raise PlanError("ELT needs an index + strings")
            pairs = [(B.compare("eq", args[0], B.lit(i)), a)
                     for i, a in enumerate(args[1:], 1)]
            return B.case_when(pairs, None)
        if name == "FIELD":
            if len(args) < 2:
                raise PlanError("FIELD needs a needle + candidates")
            pairs = [(B.compare("eq", args[0], a), B.lit(i))
                     for i, a in enumerate(args[1:], 1)]
            return B.case_when(pairs, B.lit(0))
        if name == "CONV":
            if len(args) != 3 \
                    or not all(isinstance(a, Const) for a in args[1:]):
                raise PlanError("CONV needs (x, const from_base, "
                                "const to_base)")
            x = args[0]
            if not x.dtype.is_string:
                xs = Func(dt.varchar(x.dtype.nullable), "cast_char", (x,))
            else:
                xs = x
            return self._str_func("conv", xs, args[1], args[2])
        if name == "INET_NTOA":
            return Func(dt.varchar(args[0].dtype.nullable), "inet_ntoa",
                        (args[0],))
        if name == "SPACE":
            if not (isinstance(args[0], Const)
                    and args[0].value is not None):
                raise PlanError("SPACE needs a constant count")
            k = max(int(args[0].value), 0)
            return B.lit(" " * min(k, 1 << 20))
        if name == "CHARSET":
            return B.lit("utf8mb4" if args[0].dtype.is_string else
                         "binary")
        if name == "COLLATION":
            return B.lit(args[0].dtype.collation
                         if args[0].dtype.is_string else "binary")
        if name in ("EXPORT_SET", "MAKE_SET"):
            return self._bit_weave(name, args)
        if name == "FIND_IN_SET":
            return self._str_func("find_in_set", args[0], args[1])
        if name in ("JSON_EXTRACT", "JSON_UNQUOTE", "JSON_TYPE",
                    "JSON_VALID", "JSON_LENGTH", "JSON_CONTAINS"):
            need = {"JSON_EXTRACT": (2, 2), "JSON_UNQUOTE": (1, 1),
                    "JSON_TYPE": (1, 1), "JSON_VALID": (1, 1),
                    "JSON_LENGTH": (1, 2), "JSON_CONTAINS": (2, 3)}[name]
            if not need[0] <= len(args) <= need[1]:
                raise PlanError(f"{name} takes {need[0]}"
                                + (f"..{need[1]}" if need[1] != need[0]
                                   else "") + " arguments")
            path_pos = {"JSON_EXTRACT": 1, "JSON_LENGTH": 1,
                        "JSON_CONTAINS": 2}.get(name)
            if path_pos is not None and path_pos < len(args) \
                    and isinstance(args[path_pos], Const) \
                    and isinstance(args[path_pos].value, str):
                from ..utils.jsonfns import JSONPathError, parse_path
                try:
                    parse_path(args[path_pos].value)
                except JSONPathError as e:
                    raise PlanError(str(e))
            return self._str_func(name.lower(), *args)
        if name in ("JSON_SET", "JSON_INSERT", "JSON_REPLACE",
                    "JSON_REMOVE", "JSON_KEYS", "JSON_SEARCH",
                    "JSON_MERGE_PATCH", "JSON_MERGE_PRESERVE",
                    "JSON_MERGE", "JSON_ARRAY_APPEND", "JSON_PRETTY",
                    "JSON_QUOTE", "JSON_VALUE", "JSON_DEPTH",
                    "JSON_CONTAINS_PATH", "JSON_STORAGE_SIZE",
                    "JSON_OVERLAPS"):
            if name == "JSON_SEARCH" and len(args) >= 4 \
                    and isinstance(args[3], Const) \
                    and args[3].value is None:
                # NULL escape means "default escape", not a NULL result
                args = list(args)
                args[3] = B.lit("")
            return self._str_func(name.lower(), *args)
        if name in ("JSON_ARRAY", "JSON_OBJECT"):
            # constant construction folds at plan time (the common form);
            # column args would need a per-row JSON composer
            vals = []
            for a in args:
                if not isinstance(a, Const):
                    raise PlanError(f"{name} supports constant arguments")
                vals.append(a)
            if name == "JSON_ARRAY":
                from ..utils.jsonfns import _dump
                return B.lit(_dump([_jval(v) for v in vals]))
            if len(vals) % 2:
                raise PlanError("JSON_OBJECT needs key/value pairs")
            from ..utils.jsonfns import _dump
            obj = {str(vals[i].value): _jval(vals[i + 1])
                   for i in range(0, len(vals), 2)}
            return B.lit(_dump(obj))
        if name in ("UUID_TO_BIN", "BIN_TO_UUID", "INET6_ATON",
                    "INET6_NTOA", "COMPRESS", "UNCOMPRESS", "IS_UUID",
                    "ORD"):
            return self._str_func(name.lower(), *args)
        if name == "NAME_CONST":
            if len(args) != 2 or not isinstance(args[0], Const):
                raise PlanError("NAME_CONST needs a constant name")
            return args[1]
        if name == "SEC_TO_TIME":
            return B.reinterpret(B.arith("mul", args[0], B.lit(1_000_000)),
                                 dt.time(args[0].dtype.nullable))
        if name == "TIME_TO_SEC":
            return B.arith("intdiv",
                           B.reinterpret(args[0], dt.bigint()),
                           B.lit(1_000_000))
        if name == "MAKETIME":
            s = B.arith("add",
                        B.arith("mul", args[0], B.lit(3600)),
                        B.arith("add", B.arith("mul", args[1], B.lit(60)),
                                args[2]))
            return B.reinterpret(B.arith("mul", s, B.lit(1_000_000)),
                                 dt.time(True))
        if name in ("PERIOD_ADD", "PERIOD_DIFF"):
            # pure integer algebra over YYYYMM periods — device-fusable
            def months(p):
                return B.arith(
                    "add", B.arith("mul",
                                   B.arith("intdiv", p, B.lit(100)),
                                   B.lit(12)),
                    B.arith("mod", p, B.lit(100)))
            if name == "PERIOD_DIFF":
                return B.arith("sub", months(args[0]), months(args[1]))
            ym = B.arith("add", B.arith("sub", months(args[0]), B.lit(1)),
                         args[1])
            return B.arith(
                "add", B.arith("mul", B.arith("intdiv", ym, B.lit(12)),
                               B.lit(100)),
                B.arith("add", B.arith("mod", ym, B.lit(12)), B.lit(1)))
        if name == "TO_SECONDS":
            base = args[0]
            if base.dtype.is_string:
                base = _coerce_to(dt.datetime(), base)
            if base.dtype.kind == K.DATE:
                days = B.arith("add",
                               B.datediff(base, B.lit(0, dt.date())),
                               B.lit(719_528))
                return B.arith("mul", days, B.lit(86_400))
            secs = B.arith("intdiv", B.cast(base, dt.bigint()),
                           B.lit(1_000_000))
            return B.arith("add", secs, B.lit(719_528 * 86_400))
        if name in ("ADDTIME", "SUBTIME", "TIMEDIFF"):
            dual_base = [False]

            def temporal_arg(x, base):
                if not x.dtype.is_string:
                    return x
                # datetime-shaped literals parse as DATETIME; a LEADING
                # '-' is a negative TIME, not a date separator
                if isinstance(x, Const) and isinstance(x.value, str):
                    if "-" in x.value.lstrip()[1:]:
                        return _coerce_to(dt.datetime(), x)
                    return _time_literal(x)
                if base:
                    # non-const string: MySQL decides datetime-vs-time
                    # per VALUE.  Try the datetime parse first and fall
                    # back to TIME (ADVICE r5) — both casts lower to
                    # per-dictionary-value parse LUTs, so datetime-shaped
                    # columns no longer NULL out through CAST(.. AS TIME)
                    dual_base[0] = True
                    dtv = Func(dt.datetime(True), "cast", (x,))
                    tv = Func(dt.time(True), "cast", (x,))
                    return B.ifnull(
                        B.reinterpret(dtv, dt.bigint(True)),
                        B.reinterpret(tv, dt.bigint(True)))
                return _time_literal(x)
            # the base of ADDTIME/SUBTIME (and both TIMEDIFF sides) may
            # be datetime-shaped; ADDTIME's second arg is always a TIME
            a = temporal_arg(args[0], True)
            b = temporal_arg(args[1], name == "TIMEDIFF")
            if a.dtype.kind == K.NULL or b.dtype.kind == K.NULL:
                return B.lit(None)
            if name == "TIMEDIFF":
                out_t = dt.time(True)
            elif dual_base[0]:
                # dual-parsed string base: type follows the dominant
                # datetime reading (MySQL returns a string and formats
                # per value; a static engine type must pick one)
                out_t = dt.datetime(True)
            else:
                out_t = a.dtype.with_nullable(True)
            op = "sub" if name in ("SUBTIME", "TIMEDIFF") else "add"
            return B.reinterpret(
                B.arith(op, B.reinterpret(a, dt.bigint()),
                        B.reinterpret(b, dt.bigint())), out_t)
        if name == "IF":
            return B.if_(args[0], args[1], args[2])
        if name == "IFNULL":
            return B.ifnull(args[0], args[1])
        if name == "COALESCE":
            return B.coalesce(*args)
        if name == "NULLIF":
            return B.if_(B.compare("eq", args[0], args[1]), B.lit(None), args[0])
        if name == "DATE":
            return B.cast(args[0], dt.date())
        if name in ("VERSION",):
            return Const(dt.varchar(False), "8.0.11-tidb-tpu")
        if name in ("USER", "CURRENT_USER", "SESSION_USER", "SYSTEM_USER",
                    "DATABASE", "SCHEMA", "CONNECTION_ID",
                    "LAST_INSERT_ID", "ROW_COUNT", "FOUND_ROWS"):
            info = SESSION_INFO.get() or {}
            _taint_plan("session")       # identity varies per connection
            if name in ("DATABASE", "SCHEMA"):
                db = info.get("db")
                return Const(dt.varchar(True), db) if db \
                    else Const(dt.null_type(), None)
            if name == "CONNECTION_ID":
                return Const(dt.bigint(False), int(info.get("conn_id", 0)))
            if name == "LAST_INSERT_ID":
                return Const(dt.bigint(False),
                             int(info.get("last_insert_id", 0)))
            if name == "ROW_COUNT":
                return Const(dt.bigint(False), int(info.get("row_count",
                                                            -1)))
            if name == "FOUND_ROWS":
                return Const(dt.bigint(False),
                             int(info.get("found_rows", 0)))
            return Const(dt.varchar(False),
                         f"{info.get('user', 'root')}@%")
        if name == "UUID":
            _taint_plan("uuid")          # fresh per execution, never cache
            return Func(dt.varchar(False), "uuid", ())
        if name == "RAND":
            _taint_plan("rand")
            seed = None
            if args and isinstance(args[0], Const) \
                    and args[0].value is not None:
                seed = int(args[0].value)
            return Func(dt.double(False), "rand",
                        (Const(dt.bigint(False), seed),)
                        if seed is not None else ())
        if name == "BENCHMARK":
            return B.lit(0)              # MySQL: returns 0 (timing tool)
        if name == "COERCIBILITY":
            # literals are coercible (4), column values implicit (2)
            return B.lit(4 if isinstance(args[0], Const) else 2)
        if name == "STR_TO_DATE":
            if not (len(args) == 2 and isinstance(args[1], Const)
                    and isinstance(args[1].value, str)):
                raise PlanError("STR_TO_DATE needs a constant format")
            fmt = str(args[1].value)
            has_time = any(t in fmt for t in
                           ("%H", "%i", "%s", "%T", "%k", "%l", "%p",
                            "%r", "%f"))
            out = (dt.datetime(True) if has_time else dt.date(True))
            if isinstance(args[0], Const):
                if not isinstance(args[0].value, str):
                    return Const(dt.null_type(), None)
                from ..expr.lower_strings import _str_to_date_value
                r = _str_to_date_value(args[0].value, fmt)
                if r is None:
                    return Const(dt.null_type(), None)
                return Const(out, r[1] if has_time else r[0])
            return Func(out, "str_to_date", (args[0], args[1]))
        if name in ("UTC_DATE", "UTC_TIMESTAMP"):
            _taint_plan("now")
            import time as _time
            micros = int(_time.time() * 1_000_000)
            if name == "UTC_DATE":
                return Const(dt.date(False), micros // tmp.MICROS_PER_DAY)
            return Const(dt.datetime(False), micros)
        if name in ("NOW", "CURRENT_TIMESTAMP", "SYSDATE", "CURDATE",
                    "CURRENT_DATE"):
            # statement-start clock (MySQL: constant within a statement);
            # taints the plan so the cache never replays a stale clock
            _taint_plan("now")
            import time as _time
            now = _time.time()
            micros = int(now * 1_000_000)
            if name in ("CURDATE", "CURRENT_DATE"):
                return Const(dt.date(False), micros // tmp.MICROS_PER_DAY)
            return Const(dt.datetime(False), micros)
        from ..expr.compile import EXTENSION_FUNCS
        ext = EXTENSION_FUNCS.get(name.lower())
        if ext is not None:
            fn, arity = ext
            if arity >= 0 and len(args) != arity:
                raise PlanError(
                    f"function {name} expects {arity} arguments")
            _taint_plan("extension")   # host fn: never cache its plan
            return Func(dt.double(True), f"ext:{name.lower()}",
                        tuple(args))
        raise PlanError(f"unsupported function {name}")

    def _concat_ws_items(self, sep: Expr, items: list) -> Expr:
        """CONCAT_WS semantics over built items: NULL args are SKIPPED
        (builtin_string.go concatWS).  Nullable args expand into the 2^k
        null-pattern CASE so the whole expression lowers to dictionary
        gathers on device (shared by CONCAT_WS and MAKE_SET)."""
        null_ix = [i for i, a in enumerate(items) if a.dtype.nullable]
        if not null_ix:
            woven: list = []
            for a in items:
                if woven:
                    woven.append(sep)
                woven.append(a)
            return self._str_func("concat", *woven)
        if len(null_ix) > 4:
            raise PlanError("CONCAT_WS supports at most 4 nullable "
                            "arguments")
        pairs = []
        for pat in range(1, 1 << len(null_ix)):   # >=1 arg NULL
            conds = []
            skip = set()
            for b, i in enumerate(null_ix):
                if pat >> b & 1:
                    conds.append(B.is_null(items[i]))
                    skip.add(i)
                else:
                    conds.append(B.logic("not", B.is_null(items[i])))
            cond = conds[0]
            for c in conds[1:]:
                cond = B.logic("and", cond, c)
            kept = [a for i, a in enumerate(items) if i not in skip]
            woven = []
            for a in kept:
                if woven:
                    woven.append(sep)
                woven.append(a)
            val = (self._str_func("concat", *woven) if woven
                   else B.lit(""))
            pairs.append((cond, val))
        woven = []
        for a in items:
            if woven:
                woven.append(sep)
            woven.append(a)
        return B.case_when(pairs, self._str_func("concat", *woven))

    def _bit_weave(self, name: str, args) -> Expr:
        """EXPORT_SET(bits,on,off[,sep[,k]]) / MAKE_SET(bits,s1..sk):
        per-bit IF selections woven with the separator — the control-flow
        rewrite keeps device lowering possible for small k and falls to
        the row-wise host path beyond the dictionary-product cap."""
        bits = args[0]
        bt = dt.bigint(bits.dtype.nullable)

        def bit(i: int) -> Expr:
            return B.compare("eq", Func(bt, "mod", (
                Func(bt, "intdiv", (bits, B.lit(1 << i))), B.lit(2))),
                B.lit(1))
        if name == "EXPORT_SET":
            if len(args) < 3:
                raise PlanError("EXPORT_SET needs (bits, on, off, ...)")
            on, off = args[1], args[2]
            sep = args[3] if len(args) > 3 else B.lit(",")
            k = int(args[4].value) if len(args) > 4 \
                and isinstance(args[4], Const) else 64
            k = max(1, min(k, 64))
            woven = []
            for i in range(k):
                if woven:
                    woven.append(sep)
                woven.append(B.if_(bit(i), on, off))
            out = self._str_func("concat", *woven)
            if bits.dtype.nullable:   # EXPORT_SET(NULL, ...) is NULL
                out = B.if_(B.is_null(bits), B.lit(None), out)
            return out
        # MAKE_SET: only strings whose bit is set, comma-joined — the
        # CONCAT_WS NULL-skip shape, capped like it
        items = [B.if_(bit(i), a, B.lit(None)) for i, a in
                 enumerate(args[1:])]
        if len(items) > 4:
            raise PlanError("MAKE_SET supports at most 4 members")
        out = self._concat_ws_items(B.lit(","), items)
        if bits.dtype.nullable:       # MAKE_SET(NULL, ...) is NULL
            out = B.if_(B.is_null(bits), B.lit(None), out)
        return out

    def _timestampdiff(self, unit: str, a: Expr, b: Expr) -> Expr:
        """TIMESTAMPDIFF(unit, a, b) = integer units from a to b,
        truncated toward zero (builtin_time.go timestampDiff) — built
        from existing device temporal ops so it fuses on device."""
        if a.dtype.is_string:
            a = _coerce_to(dt.datetime(), a)
        if b.dtype.is_string:
            b = _coerce_to(dt.datetime(), b)
        if a.dtype.kind not in (K.DATE, K.DATETIME) \
                or b.dtype.kind not in (K.DATE, K.DATETIME):
            raise PlanError("TIMESTAMPDIFF needs date operands")
        nullable = a.dtype.nullable or b.dtype.nullable
        bt = dt.bigint(nullable)

        def us(x: Expr) -> Expr:
            from ..types.temporal import MICROS_PER_DAY
            if x.dtype.kind == K.DATE:
                return Func(bt, "mul", (x, Const(dt.bigint(False),
                                                 MICROS_PER_DAY)))
            return x
        if unit in ("SECOND", "MINUTE", "HOUR", "DAY", "WEEK"):
            per = {"SECOND": 1_000_000, "MINUTE": 60_000_000,
                   "HOUR": 3_600_000_000, "DAY": 86_400_000_000,
                   "WEEK": 7 * 86_400_000_000}[unit]
            diff = Func(bt, "sub", (us(b), us(a)))
            return Func(bt, "intdiv", (diff, Const(dt.bigint(False), per)))
        if unit not in ("MONTH", "QUARTER", "YEAR"):
            raise PlanError(f"unsupported TIMESTAMPDIFF unit {unit}")

        def ym(x: Expr) -> Expr:
            y = Func(bt, "year", (x,))
            m = Func(bt, "month", (x,))
            return Func(bt, "add", (Func(bt, "mul",
                                         (y, Const(dt.bigint(False), 12))),
                                    m))

        def intra(x: Expr) -> Expr:
            # progress within the month: day-of-month * 1 day + time
            from ..types.temporal import MICROS_PER_DAY
            d = Func(bt, "mul", (Func(bt, "dayofmonth", (x,)),
                                 Const(dt.bigint(False), MICROS_PER_DAY)))
            if x.dtype.kind == K.DATE:
                return d
            tod = Func(bt, "add", (Func(bt, "mul", (
                Func(bt, "add", (Func(bt, "mul", (
                    Func(bt, "add", (Func(bt, "mul", (
                        Func(bt, "hour", (x,)),
                        Const(dt.bigint(False), 60))),
                        Func(bt, "minute", (x,)))),
                    Const(dt.bigint(False), 60))),
                    Func(bt, "second", (x,)))),
                Const(dt.bigint(False), 1_000_000))),
                Func(bt, "microsecond", (x,))))
            return Func(bt, "add", (d, tod))
        months = Func(bt, "sub", (ym(b), ym(a)))
        gtz = Func(bt, "gt", (months, Const(dt.bigint(False), 0)))
        ltz = Func(bt, "lt", (months, Const(dt.bigint(False), 0)))
        short = Func(bt, "lt", (intra(b), intra(a)))   # partial month fwd
        over = Func(bt, "gt", (intra(b), intra(a)))    # partial month bwd
        adj = Func(bt, "sub",
                   (B.if_(Func(bt, "and", (gtz, short)),
                          Const(dt.bigint(False), 1),
                          Const(dt.bigint(False), 0)),
                    B.if_(Func(bt, "and", (ltz, over)),
                          Const(dt.bigint(False), 1),
                          Const(dt.bigint(False), 0))))
        months = Func(bt, "sub", (months, adj))
        if unit == "MONTH":
            return months
        per = 3 if unit == "QUARTER" else 12
        return Func(bt, "intdiv", (months, Const(dt.bigint(False), per)))

    def _vec_arg(self, a: Expr, fname: str) -> Expr:
        """Coerce one vector-function argument: vector expressions pass
        through; constant '[..]' text parses at plan time (the implicit
        string->VECTOR cast of types/vector.go)."""
        if a.dtype is not None and getattr(a.dtype, "is_vector", False):
            return a
        if isinstance(a, Const) and isinstance(a.value, str):
            try:
                arr = dt.parse_vector_text(a.value)
            except ValueError as e:
                raise PlanError(str(e))
            return Const(dt.vector(len(arr), nullable=False), arr)
        if isinstance(a, Const) and a.value is None:
            return Const(dt.vector(), None)
        raise PlanError(f"{fname} expects a VECTOR column or a constant "
                        "'[...]' literal")

    def _str_func(self, op: str, *args: Expr) -> Expr:
        """String function with plan-time constant folding and a
        structural check that non-column arguments are constants (the
        dictionary-lowering contract — see expr/lower_strings.py)."""
        from ..expr.lower_strings import (fold_string_func,
                                          string_func_arg_error)
        e = B.str_func(op, *args)
        folded = fold_string_func(e)
        if folded is not None:
            return folded
        if isinstance(e, Func):
            err = string_func_arg_error(e)
            if err is not None:
                raise PlanError(err)
        return e

    def _date_addsub(self, name: str, n: A.FuncCall, args) -> Expr:
        """DATE_ADD/DATE_SUB(base, INTERVAL expr unit) — constant bases
        fold at plan time, runtime bases lower to device date arithmetic."""
        iv = n.args[1]
        if not (isinstance(iv, A.Lit) and iv.kind == "interval"):
            raise PlanError(f"{name} needs an INTERVAL argument")
        amt_e = self.build(iv.value) if isinstance(iv.value, A.Node) \
            else B.lit(int(iv.value))
        base = args[0]
        neg = name in ("DATE_SUB", "SUBDATE")
        if base.dtype.is_string:
            base = _coerce_to(dt.datetime(), base)
        if base.dtype.kind not in (K.DATE, K.DATETIME):
            raise PlanError(f"{name} needs a date operand")
        if isinstance(base, Const) and isinstance(amt_e, Const) \
                and base.dtype.kind == K.DATE \
                and iv.unit in ("DAY", "MONTH", "YEAR"):
            amount = int(amt_e.value) * (-1 if neg else 1)
            return _fold_interval_const(base, amount, iv.unit)
        amt = Func(amt_e.dtype, "neg", (amt_e,)) if neg else amt_e
        return B.date_add(base, amt, iv.unit)

    def _b_star(self, n: A.Star) -> Expr:
        raise PlanError("* only valid as a top-level select item")

    def _b_subqueryexpr(self, n: A.SubqueryExpr) -> Expr:
        """Uncorrelated scalar subquery: evaluated once at plan time via
        the session-installed executor (the reference evaluates these
        during optimization: EvalSubqueryFirstRow, expression_rewriter.go)."""
        fn = SUBQUERY_EXECUTOR.get()
        if fn is None:
            raise PlanError("scalar subquery not supported in this context")
        return fn(n.select)

    def _b_existsexpr(self, n: A.ExistsExpr) -> Expr:
        raise PlanError("EXISTS is only supported as a WHERE-clause "
                        "predicate")


def _fold_interval_const(base: Const, amount: int, unit: str) -> Const:
    if base.dtype.kind == K.DATE:
        days = int(base.value)
        if unit == "DAY":
            return Const(base.dtype, days + amount)
        if unit in ("MONTH", "YEAR"):
            import datetime as _dt
            d = tmp.days_to_date(days)
            months = amount * (12 if unit == "YEAR" else 1)
            mi = d.year * 12 + (d.month - 1) + months
            y, m = divmod(mi, 12)
            import calendar
            day = min(d.day, calendar.monthrange(y, m + 1)[1])
            return Const(base.dtype, tmp.date_to_days(y, m + 1, day))
    raise PlanError(f"INTERVAL {unit} on {base.dtype} not supported")


def _coerce_compare(a: Expr, b: Expr) -> tuple[Expr, Expr]:
    """MySQL-ish implicit casts for comparisons: string literal vs
    temporal/decimal/numeric column resolves at plan time."""
    def conv(s: Expr, target: dt.DataType) -> Expr:
        assert isinstance(s, Const)
        v = s.value
        if target.kind == K.DATE:
            return Const(dt.date(False), tmp.parse_date(str(v)))
        if target.kind == K.DATETIME:
            return Const(dt.datetime(False), tmp.parse_datetime(str(v)))
        if target.kind == K.DECIMAL:
            return B.decimal_lit(str(v))
        if target.kind in (K.INT64, K.UINT64, K.FLOAT64, K.FLOAT32):
            return B.lit(float(v))
        if target.kind == K.ENUM:
            # compare by 1-based member ordinal; absent literal never
            # matches (index -1)
            return Const(dt.bigint(False), dt.enum_index(target, str(v)))
        if target.kind == K.SET:
            return Const(dt.bigint(False), dt.set_mask(target, str(v)))
        if target.kind == K.BIT:
            try:
                return Const(dt.bigint(False), int(v))
            except (TypeError, ValueError):
                return s
        return s

    if isinstance(a, Const) and a.dtype.is_string and not b.dtype.is_string:
        return conv(a, b.dtype), b
    if isinstance(b, Const) and b.dtype.is_string and not a.dtype.is_string:
        return a, conv(b, a.dtype)
    return a, b


def _fold_const_str_cast(s: str, tn: str, n: "A.CastExpr") -> Optional[Expr]:
    """Constant-fold CAST('literal' AS T) with the same relaxed MySQL
    coercion the dictionary lowering applies per distinct value."""
    from ..expr.lower_strings import (_round_half_away, _str_num_prefix,
                                      _str_to_days, _str_to_micros)
    if tn in ("SIGNED", "SIGNED INTEGER", "INT", "BIGINT"):
        return Const(dt.bigint(False), _round_half_away(_str_num_prefix(s)))
    if tn in ("UNSIGNED", "UNSIGNED INTEGER"):
        x = _round_half_away(_str_num_prefix(s)) % (1 << 64)
        return Const(dt.ubigint(False), int(np.uint64(x).astype(np.int64)))
    if tn in ("DOUBLE", "REAL", "FLOAT"):
        return Const(dt.double(False), _str_num_prefix(s))
    if tn == "DATE":
        days = _str_to_days(s)
        return Const(dt.date(True), days) if days is not None \
            else Const(dt.null_type(), None)
    if tn in ("DATETIME", "TIMESTAMP"):
        us = _str_to_micros(s)
        return Const(dt.datetime(True), us) if us is not None \
            else Const(dt.null_type(), None)
    if tn == "DECIMAL":
        from decimal import Decimal, InvalidOperation
        scale = n.scale if n.scale >= 0 else 0
        prec = n.prec if n.prec > 0 else 10
        from ..expr.lower_strings import _NUM_PREFIX
        m = _NUM_PREFIX.match(s)
        txt = m.group(0).strip() if m else ""
        try:
            q = Decimal(txt) if txt else Decimal(0)
        except InvalidOperation:
            q = Decimal(0)
        scaled = int(q.scaleb(scale).to_integral_value(
            rounding="ROUND_HALF_UP"))
        return Const(dt.decimal(prec, scale), scaled)
    if tn in ("CHAR", "VARCHAR", "NCHAR", "BINARY"):
        ln = n.prec if n.prec > 0 else None
        return Const(dt.varchar(False), s if ln is None else s[:ln])
    return None


def _jval(c: Const):
    """Const -> JSON-ready python value (decimal consts decode)."""
    if c.value is None:
        return None
    if c.dtype.kind == K.DECIMAL:
        from ..types import decimal as _dec
        return float(_dec.decode(c.value, c.dtype.scale))
    return c.value


def _time_literal(e: Expr) -> Expr:
    """TIME string const -> micros const (tmp.parse_time abbreviation
    rules: 'HH:MM' = HH:MM:00, bare digits group as [H]HMMSS)."""
    if not (isinstance(e, Const) and isinstance(e.value, str)):
        return B.cast(e, dt.time(True))
    us = tmp.parse_time(e.value)
    if us is None:
        return Const(dt.null_type(), None)
    return Const(dt.time(False), us)


# GET_FORMAT(type, standard) result strings (builtin_time.go getFormat)
_GET_FORMATS = {
    ("DATE", "USA"): "%m.%d.%Y", ("DATE", "JIS"): "%Y-%m-%d",
    ("DATE", "ISO"): "%Y-%m-%d", ("DATE", "EUR"): "%d.%m.%Y",
    ("DATE", "INTERNAL"): "%Y%m%d",
    ("DATETIME", "USA"): "%Y-%m-%d %H.%i.%s",
    ("DATETIME", "JIS"): "%Y-%m-%d %H:%i:%s",
    ("DATETIME", "ISO"): "%Y-%m-%d %H:%i:%s",
    ("DATETIME", "EUR"): "%Y-%m-%d %H.%i.%s",
    ("DATETIME", "INTERNAL"): "%Y%m%d%H%i%s",
    ("TIMESTAMP", "USA"): "%Y-%m-%d %H.%i.%s",
    ("TIMESTAMP", "JIS"): "%Y-%m-%d %H:%i:%s",
    ("TIMESTAMP", "ISO"): "%Y-%m-%d %H:%i:%s",
    ("TIMESTAMP", "EUR"): "%Y-%m-%d %H.%i.%s",
    ("TIMESTAMP", "INTERNAL"): "%Y%m%d%H%i%s",
    ("TIME", "USA"): "%h:%i:%s %p", ("TIME", "JIS"): "%H:%i:%s",
    ("TIME", "ISO"): "%H:%i:%s", ("TIME", "EUR"): "%H.%i.%s",
    ("TIME", "INTERNAL"): "%H%i%s",
}


def _coerce_to(target: dt.DataType, e: Expr) -> Expr:
    if isinstance(e, Const) and e.dtype.is_string and not target.is_string:
        return _coerce_compare(e, ColumnRef(target, 0))[0]
    if e.dtype.is_string and not isinstance(e, Const) \
            and not target.is_string:
        # implicit string->T cast over a column/expression: lowers to a
        # per-dictionary-value parse + gather (builtin_cast.go coercion)
        to = target
        if to.kind in (dt.TypeKind.DATE, dt.TypeKind.DATETIME):
            to = to.with_nullable(True)
        return Func(to, "cast", (e,))
    return e


# --------------------------------------------------------------------- #
# SELECT building
# --------------------------------------------------------------------- #

@dataclass
class BuiltSelect:
    plan: LogicalPlan
    output_names: list[str]


@dataclass
class CTEEntry:
    """One WITH-list binding visible while building a query."""
    name: str
    columns: list[str]
    select: A.Node                       # defining AST (non-recursive)
    def_ctes: dict = None                # CTEs visible at definition site
    storage: Optional[CTEStorage] = None  # set for recursive CTEs
    building: bool = False               # inside the recursive part?


def build_query(stmt: A.Node, catalog, default_db: str,
                ctes: Optional[dict] = None) -> BuiltSelect:
    """Entry: SELECT or set operation, with WITH-list handling
    (reference: PlanBuilder.buildSelect / buildSetOpr / buildWith)."""
    ctes = dict(ctes or {})
    for c in getattr(stmt, "ctes", None) or []:
        key = c.name.lower()
        if getattr(stmt, "recursive", False) and _references_cte(c.select, c.name):
            ctes[key] = _build_recursive_cte(c, catalog, default_db, ctes)
        else:
            ctes[key] = CTEEntry(c.name, list(c.columns), c.select,
                                 def_ctes=dict(ctes))
    if isinstance(stmt, A.SetOpStmt):
        return _build_setop(stmt, catalog, default_db, ctes)
    return build_select(stmt, catalog, default_db, ctes)


def _rewrite_scalar_subqueries(node, child, catalog, default_db, ctes,
                               applies: list):
    """Replace CORRELATED bare scalar subqueries with placeholder idents
    served by a LogicalApply column (rule_decorrelate's apply fallback).
    Uncorrelated subqueries are left for the eager-eval path; IN/EXISTS
    forms are left for the semi/anti-join path."""
    import dataclasses as _dc

    def try_correlated(sub_sel):
        import copy as _copy

        # probe builds run on COPIES: build_select rewrites nested
        # subqueries in place, and a discarded trial must not leave
        # placeholders in the AST the real build (or per-row apply
        # execution) will use.  Nested subqueries are NOT executed during
        # the probe (its only purpose is correlation detection): the
        # eager executor is stubbed to a typed-unknown NULL literal.
        probe_tok = SUBQUERY_EXECUTOR.set(lambda _ast: B.lit(None))
        try:
            build_query(_copy.deepcopy(sub_sel), catalog, default_db,
                        dict(ctes))
            return None          # uncorrelated
        except PlanError:
            # unknown column => correlated; any other error may be an
            # artifact of the stubbed nested executor — the dtype trial
            # below (real executor + dummy outer binding) is authoritative
            pass
        finally:
            SUBQUERY_EXECUTOR.reset(probe_tok)

        def dummy_resolver(ident: A.Ident):
            if len(ident.parts) == 1:
                q, name = None, ident.parts[0]
            else:
                q, name = ident.parts[-2], ident.parts[-1]
            hits = child.schema.find(name, q)
            if not hits:
                return None
            t = child.schema.cols[hits[0]].dtype
            return Const(t.with_nullable(True),
                         "" if t.is_string else 0)

        tok = OUTER_RESOLVER.set(dummy_resolver)
        try:
            built = build_query(_copy.deepcopy(sub_sel), catalog,
                                default_db, dict(ctes))
        finally:
            OUTER_RESOLVER.reset(tok)
        if len(built.plan.schema) != 1:
            raise PlanError("scalar subquery must return one column")
        out_t = built.plan.schema.cols[0].dtype.with_nullable(True)
        name = f"__apply_{len(applies)}"
        applies.append((sub_sel, out_t, name))
        _taint_plan("correlated subquery")
        return A.Ident((name,))

    def maybe_correlated(sub_sel) -> bool:
        """Cheap pre-filter: a subquery whose idents all resolve against
        its own FROM tables cannot be correlated — skip the (expensive)
        probe build.  Bails to True (full probe) on derived tables."""
        tables = []
        stack = [sub_sel.from_]
        while stack:
            f = stack.pop()
            if isinstance(f, A.Join):
                stack += [f.left, f.right]
            elif isinstance(f, A.TableName):
                try:
                    t = catalog.get_table(f.db or default_db, f.name)
                except Exception:
                    return True
                tables.append(((f.alias or f.name).lower(),
                               {c.lower() for c in t.col_names}))
            else:
                return True        # derived table / CTE: full probe
        aliases = {a for a, _c in tables}
        for x in _walk_ast(sub_sel):
            if not isinstance(x, A.Ident):
                continue
            if len(x.parts) >= 2:
                if x.parts[-2].lower() not in aliases:
                    return True
            elif not any(x.parts[-1].lower() in cols
                         for _a, cols in tables):
                return True
        return False

    def walk(n):
        if isinstance(n, A.SubqueryExpr):
            if not maybe_correlated(n.select):
                return n           # provably local: eager path handles it
            repl = try_correlated(n.select)
            return repl if repl is not None else n
        if isinstance(n, A.ExistsExpr):
            return n             # semi/anti-join path
        if isinstance(n, A.InExpr) and any(
                isinstance(i, A.SubqueryExpr) for i in n.items):
            return n             # semi/anti-join path
        if not isinstance(n, A.Node):
            return n
        for f in _dc.fields(n):
            v = getattr(n, f.name)
            if isinstance(v, A.Node):
                setattr(n, f.name, walk(v))
            elif isinstance(v, list):
                setattr(n, f.name, [
                    walk(x) if isinstance(x, A.Node)
                    else tuple(walk(y) if isinstance(y, A.Node) else y
                               for y in x) if isinstance(x, tuple)
                    else x
                    for x in v])
        return n

    return walk(node)


def build_select(sel: A.SelectStmt, catalog, default_db: str,
                 ctes: Optional[dict] = None) -> BuiltSelect:
    ctes = ctes or {}
    if sel.from_ is None:
        return _build_no_table(sel)
    child = _build_from(sel.from_, catalog, default_db, ctes)

    # correlated scalar subqueries -> LogicalApply columns (must wrap the
    # child BEFORE items/where build so placeholders resolve)
    applies: list = []
    if SUBQUERY_EXECUTOR.get() is not None:
        def rw(node):
            return _rewrite_scalar_subqueries(
                node, child, catalog, default_db, ctes, applies)
        if sel.where is not None:
            sel.where = rw(sel.where)
        for it in sel.items:
            if not isinstance(it.expr, A.Star):
                it.expr = rw(it.expr)
        # ORDER BY apply columns only make sense pre-aggregation; a
        # correlated subquery in HAVING would need apply-above-aggregate
        # (per-group evaluation) — unsupported, surfaces unknown-column
        if sel.order_by and not (
                sel.group_by
                or _contains_agg(sel.items, sel.having, sel.order_by)):
            sel.order_by = [(rw(e), desc) for e, desc in sel.order_by]
    if applies:
        from .logical import LogicalApply
        cols = list(child.schema.cols) + [
            SchemaCol(nm, t, "__apply__") for _ast, t, nm in applies]
        child = LogicalApply(child, applies, catalog, default_db,
                             Schema(cols))

    if sel.where is not None:
        # WHERE-clause subquery predicates (IN/EXISTS) become semi/anti
        # joins (rule_decorrelate.go analog); the rest build normally
        plain: list[A.Node] = []
        for cj in _split_where_ast(sel.where):
            joined = _try_subquery_conjunct(cj, child, catalog, default_db,
                                            ctes)
            if joined is not None:
                child = joined
            else:
                plain.append(cj)
        if plain:
            cond = ExprBuilder(child.schema).build(_and_ast(plain))
            child = LogicalSelection(child, _split_cnf(cond))

    # expand stars
    items: list[A.SelectItem] = []
    for it in sel.items:
        if isinstance(it.expr, A.Star):
            q = it.expr.table
            for i, c in enumerate(child.schema.cols):
                if (c.qualifier or "") == "__apply__":
                    continue     # apply columns never appear in SELECT *
                if q is None or (c.qualifier or "").lower() == q.lower():
                    items.append(A.SelectItem(A.Ident((c.qualifier, c.name)
                                                      if c.qualifier else (c.name,)),
                                              c.name))
        else:
            items.append(it)

    has_aggs = sel.group_by or _contains_agg(items, sel.having, sel.order_by)
    has_windows = _contains_window(items)
    if has_aggs and has_windows:
        raise PlanError("window functions over GROUP BY not supported yet")
    if has_aggs:
        plan, names = _build_agg_select(sel, items, child)
    elif has_windows:
        plan, names, wplan = _build_window_select(sel, items, child)
        if sel.having is not None:
            raise PlanError("HAVING without GROUP BY not supported")
        plan = _attach_order_limit(sel, plan, names, wplan)
    else:
        eb = ExprBuilder(child.schema)
        exprs = [eb.build(it.expr) for it in items]
        names = [_item_name(it) for it in items]
        plan = _project(child, exprs, names)
        if sel.having is not None:
            raise PlanError("HAVING without GROUP BY not supported")
        plan = _attach_order_limit(sel, plan, names, child)

    if has_aggs:
        plan = _attach_order_limit(sel, plan, names,
                                   plan.children[0] if plan.children else plan,
                                   agg_mode=True)

    if sel.distinct:
        plan = LogicalAggregate(plan, [plan.schema.ref(i)
                                       for i in range(len(plan.schema))], [],
                                Schema(list(plan.schema.cols)))
    _apply_hints(plan, sel.hints)
    return BuiltSelect(plan, names)


from .logical import find_datasource as _find_ds
from .logical import walk_plan as _walk_plan

_JOIN_METHOD_HINTS = {
    "HASH_JOIN": "hash", "TIDB_HJ": "hash",
    "MERGE_JOIN": "merge", "SM_JOIN": "merge", "TIDB_SMJ": "merge",
    "INL_JOIN": "inl", "INL_HASH_JOIN": "inl", "TIDB_INLJ": "inl",
}


def _apply_hints(plan: LogicalPlan, hints: list) -> None:
    """Annotate the logical plan with optimizer hints (the hintProcessor
    role of planner/core/hints): join method, index choice, join order."""
    if not hints:
        return
    joins = [n for n in _walk_plan(plan) if isinstance(n, LogicalJoin)]
    # innermost joins first: the SMALLEST join containing the hinted table
    # is the one the hint names (preorder would always hit the root)
    joins.sort(key=lambda j: sum(1 for _ in _walk_plan(j)))
    for name, args in hints:
        if name in _JOIN_METHOD_HINTS:
            method = _JOIN_METHOD_HINTS[name]
            for t in args:
                ds = _find_ds(plan, t)
                if ds is not None and not ds.hint_join:
                    ds.hint_join = method   # leaf marker survives reorder
                for j in joins:
                    if _find_ds(j, t) is not None and not j.hint_method:
                        j.hint_method = method
                        break
        elif name == "USE_INDEX" and args:
            ds = _find_ds(plan, args[0])
            if ds is not None:
                ds.hint_use = [a.lower() for a in args[1:]] or None
        elif name == "IGNORE_INDEX" and args:
            ds = _find_ds(plan, args[0])
            if ds is not None:
                ds.hint_ignore = [a.lower() for a in args[1:]]
        elif name == "LEADING" and args and joins:
            # join-reorder reads the hint from the GROUP ROOT (the
            # outermost join of the flattened inner-join group)
            joins[-1].hint_leading = list(args)
        # unknown hints are accepted and ignored (MySQL warning semantics)


def _build_no_table(sel: A.SelectStmt) -> BuiltSelect:
    from .logical import DataSource  # dual table: 1 row, no cols
    eb = ExprBuilder(Schema([]))
    exprs = [eb.build(it.expr) for it in sel.items]
    names = [_item_name(it) for it in sel.items]
    plan = LogicalProjection(DualSource(), exprs,
                             Schema([SchemaCol(n, e.dtype)
                                     for n, e in zip(names, exprs)]))
    return BuiltSelect(plan, names)


class DualSource(LogicalPlan):
    """SELECT without FROM: one row, zero columns."""

    def __init__(self):
        self.schema = Schema([])
        self.children = []


def _item_name(it: A.SelectItem) -> str:
    if it.alias:
        return it.alias
    if isinstance(it.expr, A.Ident):
        return it.expr.parts[-1]
    if isinstance(it.expr, A.FuncCall):
        return f"{it.expr.name.lower()}(...)" if it.expr.args else f"{it.expr.name.lower()}()"
    return "expr"


def _split_cnf(e: Expr) -> list[Expr]:
    if isinstance(e, Func) and e.op == "and":
        return _split_cnf(e.args[0]) + _split_cnf(e.args[1])
    return [e]


# --------------------------------------------------------------------- #
# WHERE-clause subqueries -> semi/anti joins (decorrelation)
# --------------------------------------------------------------------- #

def _split_where_ast(n: A.Node) -> list[A.Node]:
    if isinstance(n, A.Binary) and n.op == "AND":
        return _split_where_ast(n.left) + _split_where_ast(n.right)
    return [n]


def _and_ast(conjs: list[A.Node]) -> A.Node:
    out = conjs[0]
    for c in conjs[1:]:
        out = A.Binary("AND", out, c)
    return out


def _try_subquery_conjunct(c: A.Node, child: LogicalPlan, catalog,
                           default_db: str, ctes) -> Optional[LogicalPlan]:
    """If conjunct `c` is an IN-subquery / [NOT] EXISTS predicate, return
    `child` wrapped in the corresponding semi/anti join; else None."""
    if isinstance(c, A.InExpr) and len(c.items) == 1 \
            and isinstance(c.items[0], A.SubqueryExpr):
        return _build_in_subquery(c, child, catalog, default_db, ctes)
    if isinstance(c, A.ExistsExpr):
        return _build_exists(c.select, child, catalog, default_db, ctes,
                             negated=False)
    if isinstance(c, A.Unary) and c.op == "NOT" \
            and isinstance(c.arg, A.ExistsExpr):
        return _build_exists(c.arg.select, child, catalog, default_db, ctes,
                             negated=True)
    return None


def _build_in_subquery(c: A.InExpr, child: LogicalPlan, catalog,
                       default_db: str, ctes) -> LogicalPlan:
    """x [NOT] IN (SELECT y ...) -> semi / null-aware anti join
    (the reference's null-aware anti join, executor/join/)."""
    sub = build_query(c.items[0].select, catalog, default_db, ctes)
    if len(sub.plan.schema) != 1:
        raise PlanError("IN subquery must return exactly one column")
    target = ExprBuilder(child.schema).build(c.target)
    left = child
    li = None
    post_restore = False
    if isinstance(target, ColumnRef):
        li = target.index
    else:
        # computed target: append it as a hidden join-key column
        refs = [child.schema.ref(i) for i in range(len(child.schema))]
        cols = list(child.schema.cols) + [SchemaCol("__in_key__", target.dtype)]
        left = LogicalProjection(child, refs + [target], Schema(cols))
        li = len(child.schema)
        post_restore = True
    join = LogicalJoin("anti" if c.negated else "semi", left, sub.plan,
                       eq_keys=[(li, 0)], other_conds=[],
                       schema=Schema(list(left.schema.cols)),
                       null_aware=c.negated)
    if post_restore:
        refs = [join.schema.ref(i) for i in range(len(child.schema))]
        return LogicalProjection(join, refs, Schema(list(child.schema.cols)))
    return join


def _build_exists(sub: A.SelectStmt, outer: LogicalPlan, catalog,
                  default_db: str, ctes, negated: bool) -> LogicalPlan:
    """[NOT] EXISTS (SELECT ...) -> semi/anti join, decorrelating
    outer-column references in the subquery WHERE into join keys /
    residual conditions (rule_decorrelate.go analog)."""
    kind = "anti" if negated else "semi"
    out_schema = Schema(list(outer.schema.cols))
    # uncorrelated fast path: the whole subquery builds standalone; only
    # its non-emptiness matters, so LIMIT 1 bounds the cross semi/anti
    # join to a single build row
    try:
        bs = build_query(sub, catalog, default_db, ctes)
        limited = LogicalLimit(bs.plan, 1)
        return LogicalJoin(kind, outer, limited, eq_keys=[], other_conds=[],
                           schema=out_schema)
    except PlanError:
        pass
    if getattr(sub, "from_", None) is None:
        raise PlanError("EXISTS subquery needs a FROM clause")
    if sub.group_by or sub.having is not None or sel_has_limit(sub):
        raise PlanError("correlated EXISTS with GROUP BY/HAVING/LIMIT "
                        "not supported")
    inner = _build_from(sub.from_, catalog, default_db, dict(ctes or {}))
    n_outer = len(outer.schema)
    combined = Schema(list(outer.schema.cols) + list(inner.schema.cols))
    eq_keys: list[tuple[int, int]] = []
    others: list[Expr] = []
    inner_conds: list[Expr] = []
    for cj in (_split_where_ast(sub.where) if sub.where is not None else []):
        try:
            inner_conds += _split_cnf(ExprBuilder(inner.schema).build(cj))
            continue
        except PlanError:
            pass
        e = ExprBuilder(combined).build(cj)   # correlated: may still raise
        k = _eq_key_of(e, n_outer)
        if k is not None:
            eq_keys.append(k)
        else:
            others.append(e)
    if inner_conds:
        inner = LogicalSelection(inner, inner_conds)
    return LogicalJoin(kind, outer, inner, eq_keys=eq_keys,
                       other_conds=others, schema=out_schema)


def sel_has_limit(sub) -> bool:
    return getattr(sub, "limit", None) is not None


def _eq_key_of(e: Expr, n_left: int):
    if (isinstance(e, Func) and e.op == "eq"
            and isinstance(e.args[0], ColumnRef)
            and isinstance(e.args[1], ColumnRef)):
        a, b = e.args[0].index, e.args[1].index
        if a < n_left <= b:
            return (a, b - n_left)
        if b < n_left <= a:
            return (b, a - n_left)
    return None


def _walk_ast(n: A.Node, prune=None):
    """Yield every A.Node reachable from n (depth-first, incl. n itself);
    `prune(x)` true stops descent below x (x itself is still yielded)."""
    stack = [n]
    while stack:
        x = stack.pop()
        if not isinstance(x, A.Node):
            continue
        yield x
        if prune is not None and prune(x):
            continue
        for v in vars(x).values():
            if isinstance(v, A.Node):
                stack.append(v)
            elif isinstance(v, (list, tuple)):
                for i in v:
                    if isinstance(i, A.Node):
                        stack.append(i)
                    elif isinstance(i, tuple):
                        stack.extend(y for y in i if isinstance(y, A.Node))


def _is_window_call(x) -> bool:
    return isinstance(x, A.FuncCall) and x.over is not None


def _agg_scan_prune(x) -> bool:
    """Stop descent below window calls (SUM(x) OVER ... is no aggregate)
    and below subqueries (their aggregates belong to the INNER query)."""
    return _is_window_call(x) or isinstance(x, A.SubqueryExpr) \
        or isinstance(x, A.ExistsExpr)


def _contains_agg(items, having, order_by) -> bool:
    roots = [it.expr for it in items]
    if having is not None:
        roots.append(having)
    roots.extend(e for e, _ in order_by or [])
    return any(
        isinstance(x, A.FuncCall) and x.over is None and x.name in AGG_FUNCS
        for r in roots
        for x in _walk_ast(r, prune=_agg_scan_prune))


def _build_agg_select(sel: A.SelectStmt, items, child) -> tuple[LogicalPlan, list[str]]:
    """GROUP BY / aggregate query: LogicalAggregate + projection on top."""
    ceb = ExprBuilder(child.schema)
    # MySQL: GROUP BY may reference select aliases (and positions)
    group_asts = []
    for g in (sel.group_by or []):
        if isinstance(g, A.Lit) and g.kind == "int":
            pos = int(g.value) - 1
            if not (0 <= pos < len(items)):
                raise PlanError(f"GROUP BY position {pos+1} out of range")
            g = items[pos].expr
        else:
            g = _subst_aliases(g, items)
        group_asts.append(g)
    group_irs = [ceb.build(g) for g in group_asts]

    agg_items: list[AggItem] = []
    agg_cache: dict = {}          # dedup identical agg calls

    def resolve_agg(fc: A.FuncCall) -> Expr:
        """Called by ExprBuilder when it hits SUM/COUNT/...; returns a
        placeholder ColumnRef into the agg output schema."""
        key = repr(fc)
        if key in agg_cache:
            return agg_cache[key]
        name = fc.name
        if name == "GROUPING":
            # GROUPING(k...) is resolved against the rollup keys; it
            # lowers post-agg to bit tests over the Expand gid column
            if not sel.rollup:
                raise PlanError("GROUPING only valid with WITH ROLLUP")
            if not fc.args:
                raise PlanError("GROUPING needs at least one argument")
            pos = []
            for a in fc.args:
                ka = ceb.build(a)
                for gi, g in enumerate(group_irs):
                    if ka == g:
                        pos.append(gi)
                        break
                else:
                    raise PlanError(
                        "GROUPING argument must be a GROUP BY expression")
            out = _GroupingRef(tuple(pos))
            agg_cache[key] = out
            return out
        star = len(fc.args) == 1 and isinstance(fc.args[0], A.Star)
        arg = None if star else ceb.build(fc.args[0])
        if name == "AVG":
            s = _add_agg(agg_items, AggFunc.SUM, arg, fc.distinct)
            c = _add_agg(agg_items, AggFunc.COUNT, arg, fc.distinct)
            sref = _AggRef(s, agg_items[s].out_dtype)
            cref = _AggRef(c, agg_items[c].out_dtype)
            out = B.arith("div", sref, cref)
        elif name == "COUNT":
            i = _add_agg(agg_items, AggFunc.COUNT, arg, fc.distinct)
            out = _AggRef(i, agg_items[i].out_dtype)
        elif name == "APPROX_COUNT_DISTINCT":
            # exact host implementation of the approximate contract
            i = _add_agg(agg_items, AggFunc.COUNT, arg, True)
            out = _AggRef(i, agg_items[i].out_dtype)
        elif name in ("STDDEV", "STD", "STDDEV_POP", "STDDEV_SAMP",
                      "VARIANCE", "VAR_POP", "VAR_SAMP"):
            # moment rewrite (reference: aggfuncs var_pop/stddev classes):
            # SUM(x), SUM(x*x), COUNT(x) — all three push to the device psum
            # path; the final expression runs in the post-agg projection.
            # var_pop = E[x^2] - E[x]^2; _samp scales by n/(n-1) (NULL at
            # n<=1 via the div-by-zero->NULL rule).
            if arg is None or not arg.dtype.is_numeric:
                raise PlanError(f"{name} needs a numeric argument")
            if fc.distinct:
                # MySQL rejects DISTINCT here; the moment rewrite would
                # dedupe x*x instead of x and compute a wrong variance
                raise PlanError(f"DISTINCT not supported for {name}")
            xf = B.cast(arg, dt.double(True))
            s1 = _add_agg(agg_items, AggFunc.SUM, xf, fc.distinct)
            s2 = _add_agg(agg_items, AggFunc.SUM,
                          B.arith("mul", xf, xf), fc.distinct)
            c = _add_agg(agg_items, AggFunc.COUNT, xf, fc.distinct)
            s1r = _AggRef(s1, agg_items[s1].out_dtype)
            s2r = _AggRef(s2, agg_items[s2].out_dtype)
            nr = B.cast(_AggRef(c, agg_items[c].out_dtype), dt.double(True))
            mean = B.arith("div", s1r, nr)
            var_pop = B.arith("sub", B.arith("div", s2r, nr),
                              B.arith("mul", mean, mean))
            if name in ("STDDEV_SAMP", "VAR_SAMP"):
                scale = B.arith("div", nr,
                                B.arith("sub", nr, B.lit(1.0)))
                var = B.arith("mul", var_pop, scale)
            else:
                var = var_pop
            if name in ("STDDEV", "STD", "STDDEV_POP", "STDDEV_SAMP"):
                # clamp tiny negative fp residue before sqrt
                out = B.math_func(
                    "sqrt", B.greatest_least("greatest",
                                             [var, B.lit(0.0)]))
            else:
                out = var
        else:
            f = {"SUM": AggFunc.SUM, "MIN": AggFunc.MIN, "MAX": AggFunc.MAX,
                 "BIT_AND": AggFunc.BIT_AND, "BIT_OR": AggFunc.BIT_OR,
                 "BIT_XOR": AggFunc.BIT_XOR,
                 "GROUP_CONCAT": AggFunc.GROUP_CONCAT,
                 "ANY_VALUE": AggFunc.ANY_VALUE,
                 "JSON_ARRAYAGG": AggFunc.JSON_ARRAYAGG}[name]
            if arg is None:
                raise PlanError(f"{name} needs an argument")
            if fc.distinct and f in (AggFunc.BIT_AND, AggFunc.BIT_OR,
                                     AggFunc.BIT_XOR, AggFunc.ANY_VALUE,
                                     AggFunc.JSON_ARRAYAGG):
                raise PlanError(f"DISTINCT not supported for {name}")
            i = _add_agg(agg_items, f, arg, fc.distinct)
            out = _AggRef(i, agg_items[i].out_dtype)
        agg_cache[key] = out
        return out

    eb = ExprBuilder(child.schema, agg_resolver=resolve_agg)
    raw_items = [eb.build(it.expr) for it in items]
    names = [_item_name(it) for it in items]
    having_ast = _subst_aliases(sel.having, items) if sel.having is not None \
        else None
    raw_having = eb.build(having_ast) if having_ast is not None else None

    # aggregate node schema: group cols then agg cols.  WITH ROLLUP routes
    # the child through LogicalExpand (grouping-sets replication): the agg
    # then groups on the Expand's nullable key columns plus its gid column
    # (reference: logical_expand.go:32 builds the same shape).
    L = len(group_irs)
    if sel.rollup and L:
        n_child = len(child.schema)
        key_names = [_expr_name(g, child.schema) for g in group_irs]
        key_dts = [g.dtype.with_nullable(True) for g in group_irs]
        ex_schema = Schema(
            list(child.schema.cols)
            + [SchemaCol(n, t) for n, t in zip(key_names, key_dts)]
            + [SchemaCol("gid", dt.bigint(False))])
        child = LogicalExpand(child, list(group_irs), L + 1, ex_schema)
        gid_ref = ColumnRef(dt.bigint(False), n_child + L, "gid")
        agg_groups = [ColumnRef(t, n_child + j, n)
                      for j, (n, t) in enumerate(zip(key_names, key_dts))]
        agg_groups.append(gid_ref)
        gcols = ([SchemaCol(n, t) for n, t in zip(key_names, key_dts)]
                 + [SchemaCol("gid", dt.bigint(False))])
    else:
        agg_groups = list(group_irs)
        gcols = [SchemaCol(_expr_name(g, child.schema), g.dtype)
                 for g in group_irs]
    acols = [SchemaCol(f"agg#{i}", a.out_dtype) for i, a in enumerate(agg_items)]
    agg_schema = Schema(gcols + acols)
    agg_plan = LogicalAggregate(child, agg_groups, agg_items, agg_schema)

    n_group = len(agg_groups)

    def remap(e: Expr) -> Expr:
        if isinstance(e, _GroupingRef):
            # GROUPING(k_j...): key j is rolled in level gid iff gid+j >= L;
            # multi-arg packs bits MSB-first (MySQL 8 semantics)
            gout = ColumnRef(dt.bigint(False), n_group - 1, "gid")
            out = None
            k = len(e.positions)
            for i, j in enumerate(e.positions):
                bit = B.cast(
                    B.compare("ge", B.arith("add", gout, B.lit(j)),
                              B.lit(L)), dt.bigint(False))
                if k > 1:
                    bit = B.arith("mul", bit, B.lit(1 << (k - 1 - i)))
                out = bit if out is None else B.arith("add", out, bit)
            return out
        if isinstance(e, _AggRef):
            return ColumnRef(e.dtype, n_group + e.agg_index, f"agg#{e.agg_index}")
        for gi, g in enumerate(group_irs):
            if e == g:
                return ColumnRef(agg_schema.cols[gi].dtype, gi,
                                 agg_schema.cols[gi].name)
        if isinstance(e, ColumnRef):
            raise PlanError(
                f"column {e.name!r} must appear in GROUP BY or an aggregate")
        if isinstance(e, Func):
            from ..expr.ir import clone_func
            return clone_func(e, (remap(a) for a in e.args))
        return e

    final_exprs = [remap(e) for e in raw_items]
    plan: LogicalPlan = agg_plan
    if raw_having is not None:
        plan = LogicalSelection(plan, _split_cnf(remap(raw_having)))
    plan = _project(plan, final_exprs, names)
    # stash for ORDER BY resolution against agg schema
    plan._agg_remap = remap          # type: ignore[attr-defined]
    plan._agg_eb = eb                # type: ignore[attr-defined]
    return plan, names


def _subst_aliases(n: A.Node, items: list[A.SelectItem]) -> A.Node:
    """MySQL HAVING/ORDER BY may reference select aliases: substitute the
    aliased expression AST for bare idents matching an alias."""
    aliases = {it.alias.lower(): it.expr for it in items if it.alias}
    import copy

    def go(x):
        if isinstance(x, A.Ident) and len(x.parts) == 1 \
                and x.parts[0].lower() in aliases:
            return copy.deepcopy(aliases[x.parts[0].lower()])
        if isinstance(x, A.Node):
            for f, v in vars(x).items():
                if isinstance(v, A.Node):
                    setattr(x, f, go(v))
                elif isinstance(v, list):
                    setattr(x, f, [go(i) if isinstance(i, A.Node) else i
                                   for i in v])
            return x
        return x

    return go(copy.deepcopy(n))


class _AggRef(ColumnRef):
    """Placeholder for an aggregate output during select-list building."""

    def __init__(self, agg_index: int, dtype: dt.DataType):
        super().__init__(dtype, 100000 + agg_index, f"agg#{agg_index}")
        object.__setattr__(self, "agg_index", agg_index)


class _GroupingRef(ColumnRef):
    """Placeholder for GROUPING(keys...) during select-list building;
    remapped post-agg to bit tests over the Expand gid column."""

    def __init__(self, positions: tuple):
        super().__init__(dt.bigint(False), 200000 + (positions[0] if
                                                     positions else 0),
                         "grouping")
        object.__setattr__(self, "positions", positions)


def _add_agg(agg_items: list[AggItem], func: AggFunc, arg, distinct: bool) -> int:
    if func == AggFunc.COUNT:
        out_t = dt.bigint(False)
    elif func == AggFunc.SUM:
        out_t = sum_out_dtype(arg.dtype)
    elif func in (AggFunc.BIT_AND, AggFunc.BIT_OR, AggFunc.BIT_XOR):
        out_t = dt.ubigint(False)      # MySQL: unsigned 64-bit, never NULL
    elif func in (AggFunc.GROUP_CONCAT, AggFunc.JSON_ARRAYAGG):
        out_t = dt.varchar(True)
    else:
        out_t = arg.dtype
    agg_items.append(AggItem(func, arg, distinct, out_t))
    return len(agg_items) - 1


def _expr_name(e: Expr, schema: Schema) -> str:
    if isinstance(e, ColumnRef):
        return e.name or f"col#{e.index}"
    return "expr"


def _project(child: LogicalPlan, exprs: list[Expr], names: list[str]) -> LogicalProjection:
    sch = Schema([SchemaCol(n, e.dtype) for n, e in zip(names, exprs)])
    return LogicalProjection(child, exprs, sch)


def _attach_order_limit(sel: A.SelectStmt, plan: LogicalPlan,
                        names: list[str], pre_child: LogicalPlan,
                        agg_mode: bool = False) -> LogicalPlan:
    """ORDER BY: aliases > positions > projection names > underlying cols
    (hidden column appended and trimmed by the executor via output_names)."""
    if sel.order_by:
        assert isinstance(plan, LogicalProjection)
        keys = []
        for e_ast, desc in sel.order_by:
            idx = None
            if isinstance(e_ast, A.Lit) and e_ast.kind == "int":
                idx = int(e_ast.value) - 1
                if not (0 <= idx < len(names)):
                    raise PlanError(f"ORDER BY position {idx+1} out of range")
            elif isinstance(e_ast, A.Ident) and len(e_ast.parts) == 1:
                matches = [i for i, n in enumerate(names)
                           if n.lower() == e_ast.parts[0].lower()]
                if matches:
                    idx = matches[0]
            if idx is None:
                # build over the pre-projection schema; append hidden col
                if agg_mode:
                    remap = plan._agg_remap if hasattr(plan, "_agg_remap") else None
                    eb = plan._agg_eb if hasattr(plan, "_agg_eb") else None
                    if eb is None:
                        raise PlanError("cannot resolve ORDER BY expression")
                    ir = remap(eb.build(e_ast))
                else:
                    ir = ExprBuilder(pre_child.schema).build(e_ast)
                plan.exprs.append(ir)
                plan.schema.cols.append(SchemaCol(f"__order#{len(plan.exprs)}",
                                                  ir.dtype))
                idx = len(plan.exprs) - 1
            keys.append((plan.schema.ref(idx), desc))
        if sel.limit is not None:
            plan = LogicalTopN(plan, keys, sel.limit, sel.offset or 0)
        else:
            plan = LogicalSort(plan, keys)
    elif sel.limit is not None:
        plan = LogicalLimit(plan, sel.limit, sel.offset or 0)
    return plan


# --------------------------------------------------------------------- #
# window functions
# --------------------------------------------------------------------- #

WINDOW_FUNCS = {"ROW_NUMBER", "RANK", "DENSE_RANK", "NTILE", "LAG", "LEAD",
                "FIRST_VALUE", "LAST_VALUE", "SUM", "COUNT", "AVG", "MIN",
                "MAX", "PERCENT_RANK", "CUME_DIST"}


def _contains_window(items) -> bool:
    return any(_is_window_call(x) for it in items
               for x in _walk_ast(it.expr))


class _WinRef(ColumnRef):
    """Placeholder for a window output during select-list building."""

    def __init__(self, win_index: int, dtype: dt.DataType):
        super().__init__(dtype, 200000 + win_index, f"win#{win_index}")
        object.__setattr__(self, "win_index", win_index)


def _build_window_select(sel: A.SelectStmt, items, child):
    """Window query (no GROUP BY): LogicalWindow over child + projection.
    Reference: buildWindowFunctions (planner/core/logical_plan_builder.go)."""
    witems: list[WindowItem] = []
    wcache: dict = {}

    def resolve_window(fc: A.FuncCall) -> Expr:
        key = repr(fc)
        if key in wcache:
            return wcache[key]
        item = _build_window_item(fc, child.schema)
        witems.append(item)
        ref = _WinRef(len(witems) - 1, item.out_dtype)
        wcache[key] = ref
        return ref

    eb = ExprBuilder(child.schema, window_resolver=resolve_window)
    raw = [eb.build(it.expr) for it in items]
    names = [_item_name(it) for it in items]
    n_child = len(child.schema)
    wschema = Schema(list(child.schema.cols)
                     + [SchemaCol(f"win#{i}", w.out_dtype)
                        for i, w in enumerate(witems)])
    wplan = LogicalWindow(child, witems, wschema)

    def remap(e: Expr) -> Expr:
        if isinstance(e, _WinRef):
            return ColumnRef(e.dtype, n_child + e.win_index, e.name)
        if isinstance(e, Func):
            from ..expr.ir import clone_func
            return clone_func(e, (remap(a) for a in e.args))
        return e

    exprs = [remap(e) for e in raw]
    return _project(wplan, exprs, names), names, wplan


def _build_window_item(fc: A.FuncCall, schema: Schema) -> WindowItem:
    name = fc.name
    if name not in WINDOW_FUNCS:
        raise PlanError(f"unsupported window function {name}")
    if fc.distinct:
        raise PlanError("DISTINCT in window functions not supported")
    ceb = ExprBuilder(schema)
    star = any(isinstance(a, A.Star) for a in fc.args)
    args = [ceb.build(a) for a in fc.args if not isinstance(a, A.Star)]
    spec = fc.over
    partition = [ceb.build(p) for p in spec.partition_by]
    order = [(ceb.build(e), desc) for e, desc in spec.order_by]
    frame = spec.frame
    if frame is not None and frame[0] == "range":
        for kind, _ in (frame[1], frame[2]):
            if kind in ("preceding", "following"):
                raise PlanError("RANGE frames with numeric offsets "
                                "not supported (use ROWS)")
    fl = name.lower()
    if fl in ("row_number", "rank", "dense_rank"):
        out = dt.bigint(False)
    elif fl in ("percent_rank", "cume_dist"):
        if not order:
            raise PlanError(f"{name} requires ORDER BY in its window")
        out = dt.double(False)
    elif fl == "ntile":
        if not (args and isinstance(args[0], Const)):
            raise PlanError("NTILE needs a constant argument")
        out = dt.bigint(True)
    elif fl == "count":
        out = dt.bigint(False)
        if star:
            args = []
    elif fl == "sum":
        if not args or not args[0].dtype.is_numeric:
            raise PlanError("SUM window needs a numeric argument")
        out = sum_out_dtype(args[0].dtype).with_nullable(True)
    elif fl == "avg":
        if not args or not args[0].dtype.is_numeric:
            raise PlanError("AVG window needs a numeric argument")
        out = dt.double(True)
    elif fl in ("min", "max"):
        if not args:
            raise PlanError(f"{name} needs an argument")
        if args[0].dtype.is_string:
            from ..utils.collate import is_binary
            if not is_binary(args[0].dtype.collation):
                # the host window path compares raw codes (binary order);
                # wrong under ci — reject rather than return wrong values
                raise PlanError(
                    f"{name} over a non-binary collation is not "
                    "supported in window functions")
        out = args[0].dtype.with_nullable(True)
    else:  # lag/lead/first_value/last_value
        if not args:
            raise PlanError(f"{name} needs an argument")
        if fl in ("lag", "lead"):
            for extra in args[1:]:
                if not isinstance(extra, Const):
                    raise PlanError(f"{name} offset/default must be constant")
        out = args[0].dtype.with_nullable(True)
    return WindowItem(fl, args, partition, order, frame, out)


# --------------------------------------------------------------------- #
# set operations
# --------------------------------------------------------------------- #

def _build_setop(stmt: A.SetOpStmt, catalog, default_db: str,
                 ctes: dict) -> BuiltSelect:
    lb = build_query(stmt.left, catalog, default_db, ctes)
    rb = build_query(stmt.right, catalog, default_db, ctes)
    if len(lb.output_names) != len(rb.output_names):
        raise PlanError("set operation operands have different column counts")
    lplan = _trim_to_outputs(lb)
    rplan = _trim_to_outputs(rb)
    names = list(lb.output_names)
    out_cols = []
    for i, nm in enumerate(names):
        t = _unify_dtype(lplan.schema.cols[i].dtype, rplan.schema.cols[i].dtype)
        out_cols.append(SchemaCol(nm, t))
    schema = Schema(out_cols)
    plan: LogicalPlan = LogicalSetOp(stmt.kind, stmt.all, lplan, rplan, schema)

    if stmt.order_by:
        keys = []
        for e_ast, desc in stmt.order_by:
            idx = None
            if isinstance(e_ast, A.Lit) and e_ast.kind == "int":
                idx = int(e_ast.value) - 1
                if not (0 <= idx < len(names)):
                    raise PlanError(f"ORDER BY position {idx+1} out of range")
            elif isinstance(e_ast, A.Ident) and len(e_ast.parts) == 1:
                m = [i for i, n in enumerate(names)
                     if n.lower() == e_ast.parts[0].lower()]
                if m:
                    idx = m[0]
            if idx is None:
                raise PlanError("set-operation ORDER BY must reference an "
                                "output column name or position")
            keys.append((schema.ref(idx), desc))
        if stmt.limit is not None:
            plan = LogicalTopN(plan, keys, stmt.limit, stmt.offset or 0)
        else:
            plan = LogicalSort(plan, keys)
    elif stmt.limit is not None:
        plan = LogicalLimit(plan, stmt.limit, stmt.offset or 0)
    return BuiltSelect(plan, names)


def _trim_to_outputs(built: BuiltSelect) -> LogicalPlan:
    """Drop hidden ORDER BY columns so the plan's schema == output names."""
    p = built.plan
    n = len(built.output_names)
    if len(p.schema) == n:
        return p
    exprs = [p.schema.ref(i) for i in range(n)]
    return _project(p, exprs, list(built.output_names))


_NUMERIC_KINDS = {K.INT64, K.UINT64, K.FLOAT64, K.FLOAT32, K.DECIMAL}


def _unify_dtype(a: dt.DataType, b: dt.DataType) -> dt.DataType:
    """Result type of a set-operation column (MySQL aggregate_2Fields
    analog, simplified)."""
    nullable = a.nullable or b.nullable
    if a.kind == b.kind:
        if a.kind == K.DECIMAL:
            scale = max(a.scale, b.scale)
            ip = max(a.precision - a.scale, b.precision - b.scale)
            return dt.decimal(min(ip + scale, 65), scale, nullable)
        return a.with_nullable(nullable)
    if a.kind in _NUMERIC_KINDS and b.kind in _NUMERIC_KINDS:
        ks = {a.kind, b.kind}
        if ks & {K.FLOAT64, K.FLOAT32}:
            return dt.double(nullable)
        if K.DECIMAL in ks:
            d = a if a.kind == K.DECIMAL else b
            return dt.decimal(max(d.precision, 20 + d.scale), d.scale, nullable)
        return dt.bigint(nullable)     # int64 + uint64
    if {a.kind, b.kind} == {K.DATE, K.DATETIME}:
        return dt.datetime(nullable)
    raise PlanError(f"cannot unify set-operation column types {a} and {b}")


# --------------------------------------------------------------------- #
# recursive CTEs
# --------------------------------------------------------------------- #

def _references_cte(n: A.Node, name: str) -> bool:
    name = name.lower()
    return any(isinstance(x, A.TableName) and x.db is None
               and x.name.lower() == name for x in _walk_ast(n))


def _flatten_union(n: A.Node) -> list[tuple[A.Node, bool]]:
    """Left-deep UNION chain -> [(operand, all_flag_joining_previous)];
    the first operand's flag is unused."""
    if isinstance(n, A.SetOpStmt):
        if n.kind != "union":
            raise PlanError("recursive CTE must combine parts with UNION")
        if n.order_by or n.limit is not None:
            raise PlanError("ORDER BY/LIMIT not allowed in a recursive CTE body")
        return _flatten_union(n.left) + [(n.right, n.all)]
    return [(n, True)]


def _build_recursive_cte(c: A.CTE, catalog, default_db: str,
                         ctes: dict) -> CTEEntry:
    ops = _flatten_union(c.select)
    is_rec = [_references_cte(ast, c.name) for ast, _ in ops]
    if not any(is_rec):
        return CTEEntry(c.name, list(c.columns), c.select, def_ctes=dict(ctes))
    first_rec = is_rec.index(True)
    if first_rec == 0:
        raise PlanError(f"recursive CTE {c.name!r} needs a non-recursive "
                        "seed SELECT first")
    if not all(is_rec[first_rec:]):
        raise PlanError(f"recursive CTE {c.name!r}: seed parts must precede "
                        "recursive parts")
    # UNION DISTINCT anywhere in the chain => dedup semantics
    distinct = any(not flag for _, flag in ops[1:])
    storage = CTEStorage(c.name, distinct)

    seed_ops = ops[:first_rec]
    seed_ast = seed_ops[0][0]
    for ast, flag in seed_ops[1:]:
        seed_ast = A.SetOpStmt("union", flag, seed_ast, ast)
    sb = build_query(seed_ast, catalog, default_db, ctes)
    names = list(c.columns) if c.columns else list(sb.output_names)
    if len(names) != len(sb.output_names):
        raise PlanError(f"CTE {c.name!r} column list count mismatch")
    seed_plan = _trim_to_outputs(sb)
    storage.schema = Schema([
        SchemaCol(nm, col.dtype.with_nullable(True))
        for nm, col in zip(names, seed_plan.schema.cols)])
    storage.seed_logical = seed_plan

    entry = CTEEntry(c.name, names, c.select, def_ctes=dict(ctes),
                     storage=storage, building=True)
    rec_ctes = dict(ctes)
    rec_ctes[c.name.lower()] = entry
    for ast, _ in ops[first_rec:]:
        rb = build_query(ast, catalog, default_db, rec_ctes)
        if len(rb.output_names) != len(names):
            raise PlanError(f"recursive part of CTE {c.name!r} has wrong "
                            "column count")
        rplan = _trim_to_outputs(rb)
        for sc, rc in zip(storage.schema.cols, rplan.schema.cols):
            try:
                _unify_dtype(sc.dtype, rc.dtype)
            except PlanError:
                raise PlanError(
                    f"recursive part of CTE {c.name!r} column {sc.name!r}: "
                    f"type {rc.dtype} incompatible with seed type {sc.dtype}")
        storage.rec_logicals.append(rplan)
    entry.building = False
    return entry


def _build_cte_ref(entry: CTEEntry, alias: str, catalog,
                   default_db: str) -> LogicalPlan:
    if entry.storage is not None:
        st = entry.storage
        role = "working" if entry.building else "result"
        sch = Schema([SchemaCol(col.name, col.dtype, alias)
                      for col in st.schema.cols])
        return LogicalCTEScan(st, role, sch)
    built = build_query(entry.select, catalog, default_db,
                        entry.def_ctes or {})
    names = entry.columns or built.output_names
    if len(names) != len(built.output_names):
        raise PlanError(f"CTE {entry.name!r} column list count mismatch")
    sub = _trim_to_outputs(built)
    sub.schema = Schema([SchemaCol(nm, col.dtype, alias)
                         for nm, col in zip(names, sub.schema.cols)])
    return sub


# --------------------------------------------------------------------- #
# FROM clause
# --------------------------------------------------------------------- #

def _resolve_as_of(tbl, as_of) -> int:
    """AS OF TIMESTAMP literal -> MVCC read ts (staleread processor.go
    analog): ints are raw logical ts; datetime strings map through the
    store's wallclock->ts samples."""
    if getattr(tbl, "kv", None) is None:
        raise PlanError("AS OF TIMESTAMP needs the KV row store")
    if isinstance(as_of, int):
        return as_of
    import datetime as _dt
    try:
        when = _dt.datetime.fromisoformat(str(as_of))
    except ValueError as e:
        raise PlanError(f"bad AS OF TIMESTAMP literal {as_of!r}: {e}")
    try:
        return tbl.kv.ts_at_time(when.timestamp())
    except Exception as e:
        raise PlanError(str(e))


import threading as _threading

_view_expansion = _threading.local()


def _expand_view(view, alias: str, catalog, db: str,
                 ctes: Optional[dict]) -> LogicalPlan:
    """Inline a view reference as a named subquery (reference:
    core/logical_plan_builder.go BuildDataSourceFromView).  The stored
    SELECT text re-parses and re-plans on every reference; a per-thread
    expansion stack rejects recursive view chains."""
    from ..sql.parser import parse_sql
    stack = getattr(_view_expansion, "stack", frozenset())
    key = (db, view.name.lower())
    if key in stack:
        raise PlanError(f"view {view.name!r} references itself "
                        "(recursive views are invalid)")
    _view_expansion.stack = stack | {key}
    try:
        stmt = parse_sql(view.select_sql)[0]
        # view bodies resolve in their own namespace: a CTE in the
        # referencing query must not shadow a base table named inside
        built = build_query(stmt, catalog, db, {})
    finally:
        _view_expansion.stack = stack
    sub = built.plan
    out_names = list(view.columns) or list(built.output_names)
    if len(out_names) != len(built.output_names):
        raise PlanError(
            f"view {view.name!r} column list has {len(out_names)} names "
            f"for {len(built.output_names)} select columns")
    sch = Schema([SchemaCol(n, c.dtype, alias)
                  for n, c in zip(out_names,
                                  sub.schema.cols[:len(out_names)])])
    sub.schema = sch
    return sub


def _build_from(node: A.Node, catalog, default_db: str,
                ctes: Optional[dict] = None) -> LogicalPlan:
    ctes = ctes or {}
    if isinstance(node, A.TableName):
        alias = node.alias or node.name
        if node.db is None and node.name.lower() in ctes:
            return _build_cte_ref(ctes[node.name.lower()], alias, catalog,
                                  default_db)
        db = node.db or default_db
        view = getattr(catalog, "get_view", lambda *_: None)(db, node.name)
        if view is not None:
            return _expand_view(view, alias, catalog, db, ctes)
        tbl = catalog.get_table(db, node.name)
        sch = Schema([SchemaCol(n, t, alias)
                      for n, t in zip(tbl.col_names, tbl.col_types)])
        ds = DataSource(tbl, alias, sch, list(range(len(tbl.col_names))))
        if node.as_of is not None:
            ds.as_of_ts = _resolve_as_of(tbl, node.as_of)
        for kind, names in getattr(node, "index_hints", []):
            # table-factor hints (FROM t USE INDEX (ix)): same plumbing
            # as the /*+ USE_INDEX */ optimizer hints; FORCE == USE here
            low = [x.lower() for x in names]
            if kind in ("use", "force"):
                if low:
                    ds.hint_use = (ds.hint_use or []) + low
                else:
                    ds.hint_use = []    # USE INDEX (): forbid all indexes
            else:
                ds.hint_ignore = (ds.hint_ignore or []) + low
        return ds
    if isinstance(node, A.SubqueryRef):
        built = build_query(node.select, catalog, default_db, ctes)
        sub = built.plan
        sch = Schema([SchemaCol(n, c.dtype, node.alias)
                      for n, c in zip(built.output_names,
                                      sub.schema.cols[:len(built.output_names)])])
        sub.schema = sch
        return sub
    if isinstance(node, A.Join):
        left = _build_from(node.left, catalog, default_db, ctes)
        right = _build_from(node.right, catalog, default_db, ctes)
        sch = Schema(list(left.schema.cols) + list(right.schema.cols))
        join = LogicalJoin(node.kind, left, right, [], [], sch)
        conds: list[Expr] = []
        if node.using:
            for k in node.using:
                li = left.schema.find(k)
                ri = right.schema.find(k)
                if not li or not ri:
                    raise PlanError(f"USING column {k!r} not found")
                join.eq_keys.append((li[0], ri[0]))
            if join.kind == "cross":
                join.kind = "inner"
        if node.on is not None:
            cond = ExprBuilder(sch).build(node.on)
            conds = _split_cnf(cond)
            if join.kind == "cross":
                join.kind = "inner"
        join.other_conds = conds
        return join
    raise PlanError(f"unsupported FROM clause {type(node).__name__}")


__all__ = ["ExprBuilder", "PlanError", "BuiltSelect", "build_select",
           "build_query", "DualSource", "CTEEntry"]
