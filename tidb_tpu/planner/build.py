"""AST -> logical plan builder (name/type resolution).

Reference analog: pkg/planner/core/logical_plan_builder.go (PlanBuilder) —
resolves identifiers against child schemas, types every expression (into
expr/ir.py IR), splits AVG into SUM/COUNT (SURVEY.md §A.4), rewrites
aggregate queries into LogicalAggregate + projection over its output, and
resolves ORDER BY against aliases/positions/underlying columns with hidden
columns, like the reference's havingWindowAndOrderbyExprResolver.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..copr.dag import AggFunc
from ..expr import builders as B
from ..expr.ir import ColumnRef, Const, Expr, Func
from ..sql import ast as A
from ..types import dtypes as dt
from ..types import temporal as tmp
from ..copr.aggregate import sum_out_dtype
from .logical import (AggItem, DataSource, LogicalAggregate, LogicalJoin,
                      LogicalLimit, LogicalPlan, LogicalProjection,
                      LogicalSelection, LogicalSort, LogicalTopN, Schema,
                      SchemaCol)

K = dt.TypeKind

AGG_FUNCS = {"SUM", "COUNT", "AVG", "MIN", "MAX"}

_CMP = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
_ARITH = {"+": "add", "-": "sub", "*": "mul", "/": "div", "DIV": "intdiv",
          "%": "mod"}


class PlanError(ValueError):
    pass


# --------------------------------------------------------------------- #
# expression building over a schema
# --------------------------------------------------------------------- #

class ExprBuilder:
    """AST expression -> typed IR over `schema`.  Aggregate calls are
    rejected unless an agg_resolver intercepts them (select-list path)."""

    def __init__(self, schema: Schema, agg_resolver=None):
        self.schema = schema
        self.agg_resolver = agg_resolver

    def build(self, n: A.Node) -> Expr:
        m = getattr(self, f"_b_{type(n).__name__.lower()}", None)
        if m is None:
            raise PlanError(f"unsupported expression {type(n).__name__}")
        return m(n)

    # ---- leaves ---- #

    def _b_ident(self, n: A.Ident) -> Expr:
        if len(n.parts) == 1:
            q, name = None, n.parts[0]
        else:
            q, name = n.parts[-2], n.parts[-1]
        hits = self.schema.find(name, q)
        if not hits:
            hits = self.schema.find(name, None)
        if not hits:
            raise PlanError(f"unknown column {'.'.join(n.parts)!r}")
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {name!r}")
        return self.schema.ref(hits[0])

    def _b_lit(self, n: A.Lit) -> Expr:
        if n.kind == "int":
            return B.lit(int(n.value))
        if n.kind == "bool":
            return B.lit(int(n.value))
        if n.kind == "decimal":
            return B.decimal_lit(str(n.value))
        if n.kind == "float":
            return B.lit(float(n.value))
        if n.kind == "str":
            return B.lit(str(n.value))
        if n.kind == "null":
            return B.lit(None)
        if n.kind == "date":
            return B.lit(str(n.value), dt.date())
        if n.kind == "datetime":
            return B.lit(str(n.value), dt.datetime())
        if n.kind == "interval":
            raise PlanError("INTERVAL only valid in +/- with a date")
        raise PlanError(f"unknown literal kind {n.kind}")

    # ---- operators ---- #

    def _b_binary(self, n: A.Binary) -> Expr:
        op = n.op
        if op in ("AND", "OR", "XOR"):
            return B.logic(op.lower(), self.build(n.left), self.build(n.right))
        if op in _CMP:
            a, b = self.build(n.left), self.build(n.right)
            a, b = _coerce_compare(a, b)
            return B.compare(_CMP[op], a, b)
        if op in _ARITH:
            # temporal interval arithmetic
            if isinstance(n.right, A.Lit) and n.right.kind == "interval":
                return self._interval_arith(n)
            return B.arith(_ARITH[op], self.build(n.left), self.build(n.right))
        raise PlanError(f"unsupported operator {op}")

    def _interval_arith(self, n: A.Binary) -> Expr:
        base = self.build(n.left)
        iv: A.Lit = n.right
        amt_e = ExprBuilder(self.schema).build(iv.value) \
            if isinstance(iv.value, A.Node) else B.lit(int(iv.value))
        if not isinstance(amt_e, Const):
            raise PlanError("INTERVAL amount must be constant")
        amount = int(str(amt_e.value)) if not isinstance(amt_e.value, int) \
            else amt_e.value
        if n.op == "-":
            amount = -amount
        unit = iv.unit
        if base.dtype.kind not in (K.DATE, K.DATETIME):
            raise PlanError("INTERVAL arithmetic needs a date operand")
        if isinstance(base, Const):
            return _fold_interval_const(base, amount, unit)
        if unit == "DAY" and base.dtype.kind == K.DATE:
            return Func(base.dtype, "add", (base, Const(dt.bigint(False), amount)))
        raise PlanError(f"non-constant INTERVAL {unit} not supported yet")

    def _b_unary(self, n: A.Unary) -> Expr:
        if n.op == "NOT":
            return B.logic("not", self.build(n.arg))
        if n.op == "-":
            a = self.build(n.arg)
            if isinstance(a, Const) and a.dtype.is_numeric:
                return Const(a.dtype, -a.value)
            return B.neg(a)
        raise PlanError(f"unsupported unary {n.op}")

    def _b_inexpr(self, n: A.InExpr) -> Expr:
        if any(isinstance(i, A.SubqueryExpr) for i in n.items):
            raise PlanError("IN (subquery) not supported yet")
        t = self.build(n.target)
        items = [_coerce_to(t.dtype, self.build(i)) for i in n.items]
        e = B.in_list(t, items)
        return B.logic("not", e) if n.negated else e

    def _b_betweenexpr(self, n: A.BetweenExpr) -> Expr:
        t = self.build(n.target)
        lo = _coerce_to(t.dtype, self.build(n.low))
        hi = _coerce_to(t.dtype, self.build(n.high))
        e = B.between(t, lo, hi)
        return B.logic("not", e) if n.negated else e

    def _b_likeexpr(self, n: A.LikeExpr) -> Expr:
        t = self.build(n.target)
        p = self.build(n.pattern)
        e = Func(dt.bigint(t.dtype.nullable), "like", (t, p))
        return B.logic("not", e) if n.negated else e

    def _b_isnullexpr(self, n: A.IsNullExpr) -> Expr:
        e = B.is_null(self.build(n.target))
        return B.logic("not", e) if n.negated else e

    def _b_caseexpr(self, n: A.CaseExpr) -> Expr:
        if n.operand is not None:
            op = self.build(n.operand)
            pairs = []
            for c, v in n.branches:
                cv = _coerce_to(op.dtype, self.build(c))
                pairs.append((B.compare("eq", op, cv), self.build(v)))
        else:
            pairs = [(self.build(c), self.build(v)) for c, v in n.branches]
        els = self.build(n.else_) if n.else_ is not None else None
        return B.case_when(pairs, els)

    def _b_castexpr(self, n: A.CastExpr) -> Expr:
        a = self.build(n.arg)
        tn = n.type_name.upper()
        if tn in ("SIGNED", "SIGNED INTEGER", "INT", "BIGINT"):
            to = dt.bigint()
        elif tn in ("UNSIGNED", "UNSIGNED INTEGER"):
            to = dt.ubigint()
        elif tn in ("DOUBLE", "REAL", "FLOAT"):
            to = dt.double()
        elif tn == "DECIMAL":
            to = dt.decimal(n.prec if n.prec > 0 else 10,
                            n.scale if n.scale >= 0 else 0)
        elif tn == "DATE":
            to = dt.date()
        elif tn in ("DATETIME", "TIMESTAMP"):
            to = dt.datetime()
        else:
            raise PlanError(f"unsupported CAST target {tn}")
        return B.cast(a, to)

    def _b_funccall(self, n: A.FuncCall) -> Expr:
        name = n.name
        if name in AGG_FUNCS:
            if self.agg_resolver is None:
                raise PlanError(f"aggregate {name} not allowed here")
            return self.agg_resolver(n)
        args = [self.build(a) for a in n.args
                if not isinstance(a, A.Star)]
        if name in ("YEAR", "MONTH"):
            return B.temporal_part(name.lower(), args[0])
        if name in ("DAY", "DAYOFMONTH"):
            return B.temporal_part("dayofmonth", args[0])
        if name == "ABS":
            return Func(args[0].dtype, "abs", tuple(args))
        if name == "IF":
            return B.if_(args[0], args[1], args[2])
        if name == "IFNULL":
            return B.ifnull(args[0], args[1])
        if name == "COALESCE":
            return B.coalesce(*args)
        if name == "NULLIF":
            return B.if_(B.compare("eq", args[0], args[1]), B.lit(None), args[0])
        if name == "DATE":
            return B.cast(args[0], dt.date())
        raise PlanError(f"unsupported function {name}")

    def _b_star(self, n: A.Star) -> Expr:
        raise PlanError("* only valid as a top-level select item")

    def _b_subqueryexpr(self, n: A.SubqueryExpr) -> Expr:
        raise PlanError("scalar subquery not supported yet")

    def _b_existsexpr(self, n: A.ExistsExpr) -> Expr:
        raise PlanError("EXISTS not supported yet")


def _fold_interval_const(base: Const, amount: int, unit: str) -> Const:
    if base.dtype.kind == K.DATE:
        days = int(base.value)
        if unit == "DAY":
            return Const(base.dtype, days + amount)
        if unit in ("MONTH", "YEAR"):
            import datetime as _dt
            d = tmp.days_to_date(days)
            months = amount * (12 if unit == "YEAR" else 1)
            mi = d.year * 12 + (d.month - 1) + months
            y, m = divmod(mi, 12)
            import calendar
            day = min(d.day, calendar.monthrange(y, m + 1)[1])
            return Const(base.dtype, tmp.date_to_days(y, m + 1, day))
    raise PlanError(f"INTERVAL {unit} on {base.dtype} not supported")


def _coerce_compare(a: Expr, b: Expr) -> tuple[Expr, Expr]:
    """MySQL-ish implicit casts for comparisons: string literal vs
    temporal/decimal/numeric column resolves at plan time."""
    def conv(s: Expr, target: dt.DataType) -> Expr:
        assert isinstance(s, Const)
        v = s.value
        if target.kind == K.DATE:
            return Const(dt.date(False), tmp.parse_date(str(v)))
        if target.kind == K.DATETIME:
            return Const(dt.datetime(False), tmp.parse_datetime(str(v)))
        if target.kind == K.DECIMAL:
            return B.decimal_lit(str(v))
        if target.kind in (K.INT64, K.UINT64, K.FLOAT64, K.FLOAT32):
            return B.lit(float(v))
        return s

    if isinstance(a, Const) and a.dtype.is_string and not b.dtype.is_string:
        return conv(a, b.dtype), b
    if isinstance(b, Const) and b.dtype.is_string and not a.dtype.is_string:
        return a, conv(b, a.dtype)
    return a, b


def _coerce_to(target: dt.DataType, e: Expr) -> Expr:
    if isinstance(e, Const) and e.dtype.is_string and not target.is_string:
        return _coerce_compare(e, ColumnRef(target, 0))[0]
    return e


# --------------------------------------------------------------------- #
# SELECT building
# --------------------------------------------------------------------- #

@dataclass
class BuiltSelect:
    plan: LogicalPlan
    output_names: list[str]


def build_select(sel: A.SelectStmt, catalog, default_db: str) -> BuiltSelect:
    if sel.from_ is None:
        return _build_no_table(sel)
    child = _build_from(sel.from_, catalog, default_db)

    if sel.where is not None:
        cond = ExprBuilder(child.schema).build(sel.where)
        child = LogicalSelection(child, _split_cnf(cond))

    # expand stars
    items: list[A.SelectItem] = []
    for it in sel.items:
        if isinstance(it.expr, A.Star):
            q = it.expr.table
            for i, c in enumerate(child.schema.cols):
                if q is None or (c.qualifier or "").lower() == q.lower():
                    items.append(A.SelectItem(A.Ident((c.qualifier, c.name)
                                                      if c.qualifier else (c.name,)),
                                              c.name))
        else:
            items.append(it)

    has_aggs = sel.group_by or _contains_agg(items, sel.having, sel.order_by)
    if has_aggs:
        plan, names = _build_agg_select(sel, items, child)
    else:
        eb = ExprBuilder(child.schema)
        exprs = [eb.build(it.expr) for it in items]
        names = [_item_name(it) for it in items]
        plan = _project(child, exprs, names)
        if sel.having is not None:
            raise PlanError("HAVING without GROUP BY not supported")
        plan = _attach_order_limit(sel, plan, names, child)

    if has_aggs:
        plan = _attach_order_limit(sel, plan, names,
                                   plan.children[0] if plan.children else plan,
                                   agg_mode=True)

    if sel.distinct:
        plan = LogicalAggregate(plan, [plan.schema.ref(i)
                                       for i in range(len(plan.schema))], [],
                                Schema(list(plan.schema.cols)))
    return BuiltSelect(plan, names)


def _build_no_table(sel: A.SelectStmt) -> BuiltSelect:
    from .logical import DataSource  # dual table: 1 row, no cols
    eb = ExprBuilder(Schema([]))
    exprs = [eb.build(it.expr) for it in sel.items]
    names = [_item_name(it) for it in sel.items]
    plan = LogicalProjection(DualSource(), exprs,
                             Schema([SchemaCol(n, e.dtype)
                                     for n, e in zip(names, exprs)]))
    return BuiltSelect(plan, names)


class DualSource(LogicalPlan):
    """SELECT without FROM: one row, zero columns."""

    def __init__(self):
        self.schema = Schema([])
        self.children = []


def _item_name(it: A.SelectItem) -> str:
    if it.alias:
        return it.alias
    if isinstance(it.expr, A.Ident):
        return it.expr.parts[-1]
    if isinstance(it.expr, A.FuncCall):
        return f"{it.expr.name.lower()}(...)" if it.expr.args else f"{it.expr.name.lower()}()"
    return "expr"


def _split_cnf(e: Expr) -> list[Expr]:
    if isinstance(e, Func) and e.op == "and":
        return _split_cnf(e.args[0]) + _split_cnf(e.args[1])
    return [e]


def _contains_agg(items, having, order_by) -> bool:
    found = False

    def walk(n):
        nonlocal found
        if isinstance(n, A.FuncCall) and n.name in AGG_FUNCS:
            found = True
        for v in vars(n).values() if hasattr(n, "__dict__") else []:
            if isinstance(v, A.Node):
                walk(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, A.Node):
                        walk(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, A.Node):
                                walk(y)

    for it in items:
        walk(it.expr)
    if having is not None:
        walk(having)
    for e, _ in order_by or []:
        walk(e)
    return found


def _build_agg_select(sel: A.SelectStmt, items, child) -> tuple[LogicalPlan, list[str]]:
    """GROUP BY / aggregate query: LogicalAggregate + projection on top."""
    ceb = ExprBuilder(child.schema)
    # MySQL: GROUP BY may reference select aliases (and positions)
    group_asts = []
    for g in (sel.group_by or []):
        if isinstance(g, A.Lit) and g.kind == "int":
            pos = int(g.value) - 1
            if not (0 <= pos < len(items)):
                raise PlanError(f"GROUP BY position {pos+1} out of range")
            g = items[pos].expr
        else:
            g = _subst_aliases(g, items)
        group_asts.append(g)
    group_irs = [ceb.build(g) for g in group_asts]

    agg_items: list[AggItem] = []
    agg_cache: dict = {}          # dedup identical agg calls

    def resolve_agg(fc: A.FuncCall) -> Expr:
        """Called by ExprBuilder when it hits SUM/COUNT/...; returns a
        placeholder ColumnRef into the agg output schema."""
        key = repr(fc)
        if key in agg_cache:
            return agg_cache[key]
        name = fc.name
        star = len(fc.args) == 1 and isinstance(fc.args[0], A.Star)
        arg = None if star else ceb.build(fc.args[0])
        if name == "AVG":
            s = _add_agg(agg_items, AggFunc.SUM, arg, fc.distinct)
            c = _add_agg(agg_items, AggFunc.COUNT, arg, fc.distinct)
            sref = _AggRef(s, agg_items[s].out_dtype)
            cref = _AggRef(c, agg_items[c].out_dtype)
            out = B.arith("div", sref, cref)
        elif name == "COUNT":
            i = _add_agg(agg_items, AggFunc.COUNT, arg, fc.distinct)
            out = _AggRef(i, agg_items[i].out_dtype)
        else:
            f = {"SUM": AggFunc.SUM, "MIN": AggFunc.MIN, "MAX": AggFunc.MAX}[name]
            if arg is None:
                raise PlanError(f"{name} needs an argument")
            i = _add_agg(agg_items, f, arg, fc.distinct)
            out = _AggRef(i, agg_items[i].out_dtype)
        agg_cache[key] = out
        return out

    eb = ExprBuilder(child.schema, agg_resolver=resolve_agg)
    raw_items = [eb.build(it.expr) for it in items]
    names = [_item_name(it) for it in items]
    having_ast = _subst_aliases(sel.having, items) if sel.having is not None \
        else None
    raw_having = eb.build(having_ast) if having_ast is not None else None

    # aggregate node schema: group cols then agg cols
    gcols = [SchemaCol(_expr_name(g, child.schema), g.dtype) for g in group_irs]
    acols = [SchemaCol(f"agg#{i}", a.out_dtype) for i, a in enumerate(agg_items)]
    agg_schema = Schema(gcols + acols)
    agg_plan = LogicalAggregate(child, group_irs, agg_items, agg_schema)

    n_group = len(group_irs)

    def remap(e: Expr) -> Expr:
        if isinstance(e, _AggRef):
            return ColumnRef(e.dtype, n_group + e.agg_index, f"agg#{e.agg_index}")
        for gi, g in enumerate(group_irs):
            if e == g:
                return ColumnRef(e.dtype, gi, agg_schema.cols[gi].name)
        if isinstance(e, ColumnRef):
            raise PlanError(
                f"column {e.name!r} must appear in GROUP BY or an aggregate")
        if isinstance(e, Func):
            return Func(e.dtype, e.op, tuple(remap(a) for a in e.args))
        return e

    final_exprs = [remap(e) for e in raw_items]
    plan: LogicalPlan = agg_plan
    if raw_having is not None:
        plan = LogicalSelection(plan, _split_cnf(remap(raw_having)))
    plan = _project(plan, final_exprs, names)
    # stash for ORDER BY resolution against agg schema
    plan._agg_remap = remap          # type: ignore[attr-defined]
    plan._agg_eb = eb                # type: ignore[attr-defined]
    return plan, names


def _subst_aliases(n: A.Node, items: list[A.SelectItem]) -> A.Node:
    """MySQL HAVING/ORDER BY may reference select aliases: substitute the
    aliased expression AST for bare idents matching an alias."""
    aliases = {it.alias.lower(): it.expr for it in items if it.alias}
    import copy

    def go(x):
        if isinstance(x, A.Ident) and len(x.parts) == 1 \
                and x.parts[0].lower() in aliases:
            return copy.deepcopy(aliases[x.parts[0].lower()])
        if isinstance(x, A.Node):
            for f, v in vars(x).items():
                if isinstance(v, A.Node):
                    setattr(x, f, go(v))
                elif isinstance(v, list):
                    setattr(x, f, [go(i) if isinstance(i, A.Node) else i
                                   for i in v])
            return x
        return x

    return go(copy.deepcopy(n))


class _AggRef(ColumnRef):
    """Placeholder for an aggregate output during select-list building."""

    def __init__(self, agg_index: int, dtype: dt.DataType):
        super().__init__(dtype, 100000 + agg_index, f"agg#{agg_index}")
        object.__setattr__(self, "agg_index", agg_index)


def _add_agg(agg_items: list[AggItem], func: AggFunc, arg, distinct: bool) -> int:
    if func == AggFunc.COUNT:
        out_t = dt.bigint(False)
    elif func == AggFunc.SUM:
        out_t = sum_out_dtype(arg.dtype)
    else:
        out_t = arg.dtype
    agg_items.append(AggItem(func, arg, distinct, out_t))
    return len(agg_items) - 1


def _expr_name(e: Expr, schema: Schema) -> str:
    if isinstance(e, ColumnRef):
        return e.name or f"col#{e.index}"
    return "expr"


def _project(child: LogicalPlan, exprs: list[Expr], names: list[str]) -> LogicalProjection:
    sch = Schema([SchemaCol(n, e.dtype) for n, e in zip(names, exprs)])
    return LogicalProjection(child, exprs, sch)


def _attach_order_limit(sel: A.SelectStmt, plan: LogicalPlan,
                        names: list[str], pre_child: LogicalPlan,
                        agg_mode: bool = False) -> LogicalPlan:
    """ORDER BY: aliases > positions > projection names > underlying cols
    (hidden column appended and trimmed by the executor via output_names)."""
    if sel.order_by:
        assert isinstance(plan, LogicalProjection)
        keys = []
        for e_ast, desc in sel.order_by:
            idx = None
            if isinstance(e_ast, A.Lit) and e_ast.kind == "int":
                idx = int(e_ast.value) - 1
                if not (0 <= idx < len(names)):
                    raise PlanError(f"ORDER BY position {idx+1} out of range")
            elif isinstance(e_ast, A.Ident) and len(e_ast.parts) == 1:
                matches = [i for i, n in enumerate(names)
                           if n.lower() == e_ast.parts[0].lower()]
                if matches:
                    idx = matches[0]
            if idx is None:
                # build over the pre-projection schema; append hidden col
                if agg_mode:
                    remap = plan._agg_remap if hasattr(plan, "_agg_remap") else None
                    eb = plan._agg_eb if hasattr(plan, "_agg_eb") else None
                    if eb is None:
                        raise PlanError("cannot resolve ORDER BY expression")
                    ir = remap(eb.build(e_ast))
                else:
                    ir = ExprBuilder(pre_child.schema).build(e_ast)
                plan.exprs.append(ir)
                plan.schema.cols.append(SchemaCol(f"__order#{len(plan.exprs)}",
                                                  ir.dtype))
                idx = len(plan.exprs) - 1
            keys.append((plan.schema.ref(idx), desc))
        if sel.limit is not None:
            plan = LogicalTopN(plan, keys, sel.limit, sel.offset or 0)
        else:
            plan = LogicalSort(plan, keys)
    elif sel.limit is not None:
        plan = LogicalLimit(plan, sel.limit, sel.offset or 0)
    return plan


# --------------------------------------------------------------------- #
# FROM clause
# --------------------------------------------------------------------- #

def _build_from(node: A.Node, catalog, default_db: str) -> LogicalPlan:
    if isinstance(node, A.TableName):
        tbl = catalog.get_table(node.db or default_db, node.name)
        alias = node.alias or node.name
        sch = Schema([SchemaCol(n, t, alias)
                      for n, t in zip(tbl.col_names, tbl.col_types)])
        return DataSource(tbl, alias, sch, list(range(len(tbl.col_names))))
    if isinstance(node, A.SubqueryRef):
        built = build_select(node.select, catalog, default_db)
        sub = built.plan
        sch = Schema([SchemaCol(n, c.dtype, node.alias)
                      for n, c in zip(built.output_names,
                                      sub.schema.cols[:len(built.output_names)])])
        sub.schema = sch
        return sub
    if isinstance(node, A.Join):
        left = _build_from(node.left, catalog, default_db)
        right = _build_from(node.right, catalog, default_db)
        sch = Schema(list(left.schema.cols) + list(right.schema.cols))
        join = LogicalJoin(node.kind, left, right, [], [], sch)
        conds: list[Expr] = []
        if node.using:
            for k in node.using:
                li = left.schema.find(k)
                ri = right.schema.find(k)
                if not li or not ri:
                    raise PlanError(f"USING column {k!r} not found")
                join.eq_keys.append((li[0], ri[0]))
            if join.kind == "cross":
                join.kind = "inner"
        if node.on is not None:
            cond = ExprBuilder(sch).build(node.on)
            conds = _split_cnf(cond)
            if join.kind == "cross":
                join.kind = "inner"
        join.other_conds = conds
        return join
    raise PlanError(f"unsupported FROM clause {type(node).__name__}")


__all__ = ["ExprBuilder", "PlanError", "BuiltSelect", "build_select",
           "DualSource"]
