"""Logical plan operators.

Reference analog: pkg/planner/core logical operators (LogicalSelection,
LogicalProjection, LogicalAggregation, LogicalJoin, LogicalSort, ...).
Schemas are ordered lists of named, typed output columns; expression IR
ColumnRefs index into the child's schema by position, exactly like the
reference's column offsets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..copr.dag import AggFunc
from ..expr.ir import ColumnRef, Expr
from ..types import dtypes as dt


@dataclass
class SchemaCol:
    name: str                      # output name
    dtype: dt.DataType
    qualifier: Optional[str] = None  # table alias for resolution


@dataclass
class Schema:
    cols: list[SchemaCol] = field(default_factory=list)

    def __len__(self):
        return len(self.cols)

    def find(self, name: str, qualifier: Optional[str] = None) -> list[int]:
        name = name.lower()
        out = []
        for i, c in enumerate(self.cols):
            if c.name.lower() != name:
                continue
            if qualifier is not None and (c.qualifier or "").lower() != qualifier.lower():
                continue
            out.append(i)
        return out

    def ref(self, i: int) -> ColumnRef:
        c = self.cols[i]
        return ColumnRef(c.dtype, i, c.name)

    def names(self) -> list[str]:
        return [c.name for c in self.cols]


class LogicalPlan:
    schema: Schema
    children: list["LogicalPlan"]


@dataclass
class DataSource(LogicalPlan):
    """Scan of one stored table (reference: logical DataSource)."""
    table: object                  # session.catalog.TableInfo
    alias: str
    schema: Schema = None
    col_offsets: list[int] = None  # into the table's stored columns
    hint_use: list = None          # USE_INDEX(t, ix...) index names
    hint_ignore: list = None       # IGNORE_INDEX(t, ix...)
    as_of_ts: object = None        # stale read: resolved MVCC read ts
    # join-method hint naming this table ('' | 'hash' | 'merge' | 'inl');
    # carried on the LEAF so join-reorder rebuilds don't lose it
    hint_join: str = ""

    def __post_init__(self):
        self.children = []


@dataclass
class LogicalSelection(LogicalPlan):
    child: LogicalPlan
    conditions: list[Expr]

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalProjection(LogicalPlan):
    child: LogicalPlan
    exprs: list[Expr]
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class AggItem:
    func: AggFunc
    arg: Optional[Expr]
    distinct: bool
    out_dtype: dt.DataType


@dataclass
class LogicalAggregate(LogicalPlan):
    child: LogicalPlan
    group_exprs: list[Expr]
    aggs: list[AggItem]
    schema: Schema = None          # group cols then agg cols

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class LogicalExpand(LogicalPlan):
    """Grouping-sets row replication for GROUP BY ... WITH ROLLUP.

    Reference analog: the logical Expand operator
    (pkg/planner/core/operator/logicalop/logical_expand.go:32) executed by
    the engine at unistore/cophandler/mpp.go:638.  Level l of `levels`
    replicates every input row keeping the first len(keys)-l rollup keys
    (the rolled ones become NULL).  Output schema: child columns ++ one
    nullable column per rollup key ++ gid (bigint, = the row's level l),
    so GROUPING() can distinguish rolled NULLs from natural NULLs.
    """
    child: LogicalPlan
    keys: list = None          # rollup key exprs over child schema
    levels: int = 0            # len(keys) + 1 for ROLLUP
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class LogicalJoin(LogicalPlan):
    kind: str          # 'inner' | 'left' | 'right' | 'cross' | 'semi' | 'anti'
    left: LogicalPlan = None
    right: LogicalPlan = None
    # equi-join keys as (left_index, right_index) into child schemas
    eq_keys: list[tuple[int, int]] = field(default_factory=list)
    # residual conditions over the concatenated schema
    other_conds: list[Expr] = field(default_factory=list)
    schema: Schema = None
    # NOT IN semantics (null-aware anti join, rule_decorrelate.go analog):
    # any NULL build key empties the result; NULL probe keys never pass
    null_aware: bool = False
    # optimizer-hint join method: '' | 'hash' | 'merge' | 'inl'
    hint_method: str = ""
    hint_leading: list = None      # LEADING(t1, t2, ...) table order

    def __post_init__(self):
        self.children = [self.left, self.right]


@dataclass
class LogicalApply(LogicalPlan):
    """Correlated scalar subqueries (reference: LogicalApply +
    rule_decorrelate fallback; P8 parallel apply).  Appends one column
    per subquery to the child's schema; each subquery re-evaluates per
    DISTINCT combination of the outer values it references (the apply
    cache, executor/join/apply_cache.go analog)."""
    child: LogicalPlan = None
    # [(sub_ast, out_dtype, name)] — outer refs bind by name at exec time
    subqueries: list = field(default_factory=list)
    catalog: object = None
    default_db: str = ""
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class LogicalSort(LogicalPlan):
    child: LogicalPlan
    keys: list[tuple[Expr, bool]]  # (expr over child schema, desc)

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalLimit(LogicalPlan):
    child: LogicalPlan
    limit: int
    offset: int = 0

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalTopN(LogicalPlan):
    child: LogicalPlan
    keys: list[tuple[Expr, bool]]
    limit: int
    offset: int = 0

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalSetOp(LogicalPlan):
    """UNION / EXCEPT / INTERSECT (reference: LogicalUnionAll + the set-op
    rewrites in logical_plan_builder.go buildSetOpr)."""
    kind: str                      # 'union' | 'except' | 'intersect'
    all: bool = False
    left: LogicalPlan = None
    right: LogicalPlan = None
    schema: Schema = None          # unified output (left names, joined types)

    def __post_init__(self):
        self.children = [self.left, self.right]


@dataclass
class WindowItem:
    """One window function call bound to its OVER spec (reference:
    planner/core WindowFuncDesc + WindowFrame)."""
    func: str                      # row_number|rank|dense_rank|ntile|lag|...
    args: list                     # [Expr] over the window child's schema
    partition: list = field(default_factory=list)    # [Expr]
    order: list = field(default_factory=list)        # [(Expr, desc)]
    frame: Optional[tuple] = None  # parsed frame or None (default frame)
    out_dtype: dt.DataType = None


@dataclass
class LogicalWindow(LogicalPlan):
    """Window functions over child rows; output schema = child columns then
    one column per item, in the child's row order (reference:
    LogicalWindow, executor/window.go)."""
    child: LogicalPlan
    items: list[WindowItem] = field(default_factory=list)
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.child]


class CTEStorage:
    """Shared state of one CTE (reference: util/cteutil.Storage).

    Non-recursive CTEs are inlined at build time and never use this.  A
    recursive CTE materializes here: `seed_logical` + `rec_logicals` are
    lowered lazily by the physical planner; the executor iterates
    seed -> recursive parts (which read `working`) until fixpoint, capping
    at `max_depth` (cte_max_recursion_depth analog, executor/cte.go)."""

    def __init__(self, name: str, distinct: bool, max_depth: int = 1000):
        self.name = name
        self.distinct = distinct
        self.max_depth = max_depth
        self.schema: Schema = None
        self.seed_logical: LogicalPlan = None
        self.rec_logicals: list[LogicalPlan] = []
        self.seed_phys = None
        self.rec_phys: list = []
        self.working = None        # ResultChunk: rows of the last iteration
        self.result = None         # ResultChunk: full materialized result


@dataclass
class LogicalCTEScan(LogicalPlan):
    """Scan of a recursive CTE: the working table inside the recursive
    part, or the materialized result outside it."""
    storage: CTEStorage
    role: str                      # 'working' | 'result'
    schema: Schema = None

    def __post_init__(self):
        self.children = []


def walk_plan(p: LogicalPlan):
    """Preorder walk over a logical plan tree."""
    yield p
    for c in getattr(p, "children", []):
        if c is not None:
            yield from walk_plan(c)


def find_datasource(p: LogicalPlan, name: str):
    """DataSource with the given alias (case-insensitive), or None — the
    one shared alias-resolution walk (hints, LEADING, join-method)."""
    low = name.lower()
    for n in walk_plan(p):
        if isinstance(n, DataSource) and n.alias.lower() == low:
            return n
    return None


def explain_logical(p: LogicalPlan, indent: int = 0) -> str:
    pad = "  " * indent
    name = type(p).__name__
    extra = ""
    if isinstance(p, LogicalSelection):
        extra = " " + ", ".join(map(str, p.conditions))
    elif isinstance(p, LogicalProjection):
        extra = " " + ", ".join(map(str, p.exprs))
    elif isinstance(p, LogicalAggregate):
        extra = (" group=[" + ", ".join(map(str, p.group_exprs)) + "] aggs=["
                 + ", ".join(f"{a.func.value}({a.arg})" for a in p.aggs) + "]")
    elif isinstance(p, DataSource):
        extra = f" table={p.alias}"
    elif isinstance(p, LogicalJoin):
        extra = f" {p.kind} keys={p.eq_keys}"
    out = [pad + name + extra]
    for c in getattr(p, "children", []):
        out.append(explain_logical(c, indent + 1))
    return "\n".join(out)


__all__ = [
    "SchemaCol", "Schema", "LogicalPlan", "DataSource", "LogicalSelection",
    "LogicalProjection", "AggItem", "LogicalAggregate", "LogicalJoin",
    "LogicalSort", "LogicalLimit", "LogicalTopN", "LogicalSetOp",
    "WindowItem", "LogicalWindow", "CTEStorage", "LogicalCTEScan",
    "explain_logical",
]
