"""Logical plan operators.

Reference analog: pkg/planner/core logical operators (LogicalSelection,
LogicalProjection, LogicalAggregation, LogicalJoin, LogicalSort, ...).
Schemas are ordered lists of named, typed output columns; expression IR
ColumnRefs index into the child's schema by position, exactly like the
reference's column offsets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..copr.dag import AggFunc
from ..expr.ir import ColumnRef, Expr
from ..types import dtypes as dt


@dataclass
class SchemaCol:
    name: str                      # output name
    dtype: dt.DataType
    qualifier: Optional[str] = None  # table alias for resolution


@dataclass
class Schema:
    cols: list[SchemaCol] = field(default_factory=list)

    def __len__(self):
        return len(self.cols)

    def find(self, name: str, qualifier: Optional[str] = None) -> list[int]:
        name = name.lower()
        out = []
        for i, c in enumerate(self.cols):
            if c.name.lower() != name:
                continue
            if qualifier is not None and (c.qualifier or "").lower() != qualifier.lower():
                continue
            out.append(i)
        return out

    def ref(self, i: int) -> ColumnRef:
        c = self.cols[i]
        return ColumnRef(c.dtype, i, c.name)

    def names(self) -> list[str]:
        return [c.name for c in self.cols]


class LogicalPlan:
    schema: Schema
    children: list["LogicalPlan"]


@dataclass
class DataSource(LogicalPlan):
    """Scan of one stored table (reference: logical DataSource)."""
    table: object                  # session.catalog.TableInfo
    alias: str
    schema: Schema = None
    col_offsets: list[int] = None  # into the table's stored columns

    def __post_init__(self):
        self.children = []


@dataclass
class LogicalSelection(LogicalPlan):
    child: LogicalPlan
    conditions: list[Expr]

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalProjection(LogicalPlan):
    child: LogicalPlan
    exprs: list[Expr]
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class AggItem:
    func: AggFunc
    arg: Optional[Expr]
    distinct: bool
    out_dtype: dt.DataType


@dataclass
class LogicalAggregate(LogicalPlan):
    child: LogicalPlan
    group_exprs: list[Expr]
    aggs: list[AggItem]
    schema: Schema = None          # group cols then agg cols

    def __post_init__(self):
        self.children = [self.child]


@dataclass
class LogicalJoin(LogicalPlan):
    kind: str                      # 'inner' | 'left' | 'right' | 'cross'
    left: LogicalPlan = None
    right: LogicalPlan = None
    # equi-join keys as (left_index, right_index) into child schemas
    eq_keys: list[tuple[int, int]] = field(default_factory=list)
    # residual conditions over the concatenated schema
    other_conds: list[Expr] = field(default_factory=list)
    schema: Schema = None

    def __post_init__(self):
        self.children = [self.left, self.right]


@dataclass
class LogicalSort(LogicalPlan):
    child: LogicalPlan
    keys: list[tuple[Expr, bool]]  # (expr over child schema, desc)

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalLimit(LogicalPlan):
    child: LogicalPlan
    limit: int
    offset: int = 0

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


@dataclass
class LogicalTopN(LogicalPlan):
    child: LogicalPlan
    keys: list[tuple[Expr, bool]]
    limit: int
    offset: int = 0

    def __post_init__(self):
        self.schema = self.child.schema
        self.children = [self.child]


def explain_logical(p: LogicalPlan, indent: int = 0) -> str:
    pad = "  " * indent
    name = type(p).__name__
    extra = ""
    if isinstance(p, LogicalSelection):
        extra = " " + ", ".join(map(str, p.conditions))
    elif isinstance(p, LogicalProjection):
        extra = " " + ", ".join(map(str, p.exprs))
    elif isinstance(p, LogicalAggregate):
        extra = (" group=[" + ", ".join(map(str, p.group_exprs)) + "] aggs=["
                 + ", ".join(f"{a.func.value}({a.arg})" for a in p.aggs) + "]")
    elif isinstance(p, DataSource):
        extra = f" table={p.alias}"
    elif isinstance(p, LogicalJoin):
        extra = f" {p.kind} keys={p.eq_keys}"
    out = [pad + name + extra]
    for c in getattr(p, "children", []):
        out.append(explain_logical(c, indent + 1))
    return "\n".join(out)


__all__ = [
    "SchemaCol", "Schema", "LogicalPlan", "DataSource", "LogicalSelection",
    "LogicalProjection", "AggItem", "LogicalAggregate", "LogicalJoin",
    "LogicalSort", "LogicalLimit", "LogicalTopN", "explain_logical",
]
