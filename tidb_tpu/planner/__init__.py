from .build import build_select, BuiltSelect, ExprBuilder, PlanError
from .optimize import optimize_plan
from .logical import explain_logical
