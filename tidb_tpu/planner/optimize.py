"""Logical optimization rules.

Reference analog: the fixed-order rewrite list of
pkg/planner/core/optimizer.go:87 (optRuleList) — the TPU build keeps the
rules that matter for the pushdown architecture:

1. predicate pushdown (PPDSolver analog): selections sink below projections
   and into join sides; equi-conditions become hash-join keys
2. constant folding
3. column pruning (ColumnPruner analog): DataSources scan only needed
   columns — critical on TPU where every column is HBM traffic
"""

from __future__ import annotations

import numpy as np

from ..expr.compile import eval_expr
from ..expr.ir import clone_func, ColumnRef, Const, Expr, Func, referenced_columns
from ..types import dtypes as dt
from .build import _split_cnf
from .logical import (DataSource, LogicalAggregate, LogicalJoin, LogicalLimit,
                      LogicalPlan, LogicalProjection, LogicalSelection,
                      LogicalSort, LogicalTopN, Schema, SchemaCol)


# --------------------------------------------------------------------- #
# constant folding
# --------------------------------------------------------------------- #

def _fold_expr(e: Expr) -> Expr:
    if isinstance(e, Func):
        args = tuple(_fold_expr(a) for a in e.args)
        e = clone_func(e, args)
        if args and all(isinstance(a, Const) and not isinstance(a.value, np.ndarray)
                        for a in args) and e.op not in (
                            "dict_lut", "dict_map",
                            # side-effecting/per-row: folding would advance
                            # a sequence (or freeze a per-row value) at
                            # plan time
                            "seq_next", "seq_last", "seq_set"):
            try:
                v, m = eval_expr(np, e, [])
            except Exception:
                return e
            if m is not True and not bool(np.all(m)):
                return Const(dt.null_type(), None)
            val = v.item() if hasattr(v, "item") else v
            if isinstance(val, bool):
                val = int(val)
            return Const(e.dtype, val)
    return e


def _map_exprs(p: LogicalPlan, fn) -> None:
    if isinstance(p, LogicalSelection):
        p.conditions = [fn(c) for c in p.conditions]
    elif isinstance(p, LogicalProjection):
        p.exprs = [fn(e) for e in p.exprs]
    elif isinstance(p, LogicalAggregate):
        p.group_exprs = [fn(g) for g in p.group_exprs]
        for a in p.aggs:
            if a.arg is not None:
                a.arg = fn(a.arg)
    elif isinstance(p, LogicalJoin):
        p.other_conds = [fn(c) for c in p.other_conds]
    elif isinstance(p, (LogicalSort, LogicalTopN)):
        p.keys = [(fn(e), d) for e, d in p.keys]


def fold_constants(p: LogicalPlan) -> LogicalPlan:
    for c in p.children:
        fold_constants(c)
    fn = lambda e: _extract_or_common(_fold_expr(e))
    _map_exprs(p, fn)
    # factor extraction can surface new conjuncts: re-split CNF lists
    if isinstance(p, LogicalSelection):
        p.conditions = [c2 for c in p.conditions for c2 in _split_cnf(c)]
    elif isinstance(p, LogicalJoin):
        p.other_conds = [c2 for c in p.other_conds for c2 in _split_cnf(c)]
    return p


def _extract_or_common(e: Expr) -> Expr:
    """(A AND B) OR (A AND C) -> A AND (B OR C) — extractCommonFactors
    analog (expression/util.go); distributivity holds in Kleene 3VL.
    Without this, Q19-style DNF predicates hide their equi-join keys from
    predicate pushdown."""
    if not (isinstance(e, Func) and e.op == "or"):
        if isinstance(e, Func):
            return clone_func(e,
                        tuple(_extract_or_common(a) for a in e.args))
        return e
    branches = _split_dnf(e)
    conj = [_split_cnf(b) for b in branches]
    common = [c for c in conj[0] if all(c in cs for cs in conj[1:])]
    if not common:
        return e
    residuals = []
    for cs in conj:
        rest = [c for c in cs if c not in common]
        if not rest:
            return _and_all(common)   # one branch fully covered => OR true
        residuals.append(_and_all(rest))
    out = residuals[0]
    from ..expr import builders as B
    for r in residuals[1:]:
        out = B.logic("or", out, r)
    return _and_all(common + [out])


def _split_dnf(e: Expr) -> list[Expr]:
    if isinstance(e, Func) and e.op == "or":
        return _split_dnf(e.args[0]) + _split_dnf(e.args[1])
    return [e]


def _and_all(conds: list[Expr]) -> Expr:
    from ..expr import builders as B
    out = conds[0]
    for c in conds[1:]:
        out = B.logic("and", out, c)
    return out


# --------------------------------------------------------------------- #
# predicate pushdown
# --------------------------------------------------------------------- #

def _subst(e: Expr, exprs: list[Expr]) -> Expr:
    """Replace ColumnRef i with exprs[i] (pushing through a projection)."""
    if isinstance(e, ColumnRef):
        return exprs[e.index]
    if isinstance(e, Func):
        return clone_func(e, (_subst(a, exprs) for a in e.args))
    return e


def _remap(e: Expr, offset: int) -> Expr:
    if isinstance(e, ColumnRef):
        return ColumnRef(e.dtype, e.index + offset, e.name)
    if isinstance(e, Func):
        return clone_func(e, (_remap(a, offset) for a in e.args))
    return e


def push_predicates(p: LogicalPlan, pending: list[Expr] | None = None) -> LogicalPlan:
    """Sink `pending` conditions (over p's schema) as deep as possible."""
    pending = pending or []

    if isinstance(p, LogicalSelection):
        return push_predicates(p.child, pending + list(p.conditions))

    if isinstance(p, LogicalProjection):
        pushable, stay = [], []
        for c in pending:
            # only push through simple column/deterministic exprs
            try:
                pushable.append(_subst(c, p.exprs))
            except IndexError:
                stay.append(c)
        p.child = push_predicates(p.child, pushable)
        p.children = [p.child]
        return _wrap(p, stay)

    if isinstance(p, LogicalJoin):
        n_left = len(p.left.schema)
        if p.kind in ("semi", "anti"):
            # join schema == left schema: pending conds push into the left
            # child.  Right-only residuals sink into the right child (they
            # only restrict the match set — safe for both semi and anti);
            # left-referencing residuals must stay as match conditions
            # (pushing them would wrongly drop/keep anti rows).
            own_keys, own_res, right_conds = [], [], []
            for c in p.other_conds:
                k = _as_eq_key(c, n_left)
                if k is not None:
                    own_keys.append(k)
                    continue
                refs = referenced_columns(c)
                if refs and min(refs) >= n_left:
                    right_conds.append(_remap(c, -n_left))
                else:
                    own_res.append(c)
            p.eq_keys = p.eq_keys + own_keys
            p.other_conds = own_res
            p.left = push_predicates(p.left, pending)
            p.right = push_predicates(p.right, right_conds)
            p.children = [p.left, p.right]
            return p
        if p.kind in ("inner", "cross"):
            left_conds, right_conds, eq_keys, residue = [], [], [], []
            for c in pending + p.other_conds:
                refs = referenced_columns(c)
                if refs and max(refs) < n_left:
                    left_conds.append(c)
                elif refs and min(refs) >= n_left:
                    right_conds.append(c)
                else:
                    k = _as_eq_key(c, n_left)
                    if k is not None:
                        eq_keys.append(k)
                    else:
                        residue.append(c)
            p.other_conds = residue
            p.eq_keys = p.eq_keys + eq_keys
            if p.eq_keys and p.kind == "cross":
                p.kind = "inner"
            p.left = push_predicates(p.left, left_conds)
            p.right = push_predicates(p.right,
                                      [_remap(c, -n_left) for c in right_conds])
            p.children = [p.left, p.right]
            return p
        # outer joins: extract equi keys from the ON conds, push nothing
        # through (null-extension changes filter semantics); pending stays
        # above as a post-join filter
        own_keys, own_res = [], []
        for c in p.other_conds:
            k = _as_eq_key(c, n_left)
            (own_keys.append(k) if k is not None else own_res.append(c))
        p.eq_keys = p.eq_keys + own_keys
        p.other_conds = own_res
        p.left = push_predicates(p.left)
        p.right = push_predicates(p.right)
        p.children = [p.left, p.right]
        return _wrap(p, pending)

    from .logical import LogicalApply
    if isinstance(p, LogicalApply):
        # Apply appends subquery columns AFTER the child's schema:
        # conditions that only touch child columns sink below (they don't
        # observe apply outputs), the rest stay above.  Without this, a
        # WHERE mixing one correlated predicate with ordinary join
        # predicates left the Apply sitting on the raw cross join
        # (rule_decorrelate + PPD ordering in the reference).
        n_child = len(p.child.schema)
        sink, stay = [], []
        for c in pending:
            refs = referenced_columns(c)
            (sink.append(c) if not refs or max(refs) < n_child
             else stay.append(c))
        p.child = push_predicates(p.child, sink)
        p.children = [p.child]
        return _wrap(p, stay)

    if isinstance(p, (LogicalSort, LogicalLimit, LogicalTopN, LogicalAggregate)):
        if isinstance(p, LogicalAggregate):
            # conditions over group cols could sink; keep above for now
            p.child = push_predicates(p.child)
            p.children = [p.child]
            return _wrap(p, pending)
        child = p.children[0]
        if isinstance(p, (LogicalLimit,)):
            # pushing filters below LIMIT changes semantics; keep above
            p.child = push_predicates(child)
            p.children = [p.child]
            return _wrap(p, pending)
        p.child = push_predicates(child, pending)
        p.children = [p.child]
        return p

    # leaves (DataSource, DualSource, subquery roots) and barrier nodes
    # (LogicalExpand): keep .child in sync with children[] so later passes
    # reading either see the same tree
    for i, c in enumerate(p.children):
        p.children[i] = push_predicates(c)
        if getattr(p, "child", None) is c:
            p.child = p.children[i]
    return _wrap(p, pending)


def _wrap(p: LogicalPlan, conds: list[Expr]) -> LogicalPlan:
    conds = [c for c in conds if not _is_true_const(c)]
    if not conds:
        return p
    return LogicalSelection(p, conds)


def _is_true_const(e: Expr) -> bool:
    return isinstance(e, Const) and e.value is not None \
        and not isinstance(e.value, np.ndarray) and bool(e.value)


def _as_eq_key(e: Expr, n_left: int):
    if (isinstance(e, Func) and e.op == "eq"
            and isinstance(e.args[0], ColumnRef)
            and isinstance(e.args[1], ColumnRef)):
        a, b = e.args[0].index, e.args[1].index
        if a < n_left <= b:
            return (a, b - n_left)
        if b < n_left <= a:
            return (b, a - n_left)
    return None


# --------------------------------------------------------------------- #
# column pruning
# --------------------------------------------------------------------- #

def prune_columns(p: LogicalPlan, needed: set[int] | None = None) -> LogicalPlan:
    """Rewrite DataSources to scan only referenced columns; remap refs."""
    if needed is None:
        needed = set(range(len(p.schema)))

    if isinstance(p, DataSource):
        keep = sorted(needed) or [0]   # keep at least one col for row counts
        mapping = {old: new for new, old in enumerate(keep)}
        p.col_offsets = [p.col_offsets[i] for i in keep]
        p.schema = Schema([p.schema.cols[i] for i in keep])
        return p, mapping

    if isinstance(p, LogicalProjection):
        # keep at least one expr: a zero-column chunk loses its row count
        # (EXISTS subqueries project constants nobody references)
        keep = sorted(needed) or [0]
        p.exprs = [p.exprs[i] for i in keep]
        p.schema = Schema([p.schema.cols[i] for i in keep])
        child_needed = set()
        for e in p.exprs:
            child_needed |= referenced_columns(e)
        _, cmap = _prune_child(p, 0, child_needed)
        p.exprs = [map_refs(e, cmap) for e in p.exprs]
        return p, {old: new for new, old in enumerate(keep)}

    if isinstance(p, LogicalSelection):
        child_needed = set(needed)
        for c in p.conditions:
            child_needed |= referenced_columns(c)
        _, cmap = _prune_child(p, 0, child_needed)
        p.conditions = [map_refs(c, cmap) for c in p.conditions]
        p.schema = p.child.schema
        return p, {old: cmap[old] for old in needed}

    if isinstance(p, LogicalAggregate):
        # aggregate output schema is compact already (groups + aggs)
        child_needed = set()
        for g in p.group_exprs:
            child_needed |= referenced_columns(g)
        for a in p.aggs:
            if a.arg is not None:
                child_needed |= referenced_columns(a.arg)
        _, cmap = _prune_child(p, 0, child_needed)
        p.group_exprs = [map_refs(g, cmap) for g in p.group_exprs]
        for a in p.aggs:
            if a.arg is not None:
                a.arg = map_refs(a.arg, cmap)
        return p, {i: i for i in needed}

    if isinstance(p, LogicalJoin):
        n_left = len(p.left.schema)
        child_needed = set(needed)
        for c in p.other_conds:
            child_needed |= referenced_columns(c)
        for l, r in p.eq_keys:
            child_needed.add(l)
            child_needed.add(r + n_left)
        lneed = {i for i in child_needed if i < n_left}
        rneed = {i - n_left for i in child_needed if i >= n_left}
        p.left, lmap = prune_columns(p.left, lneed)
        p.right, rmap = prune_columns(p.right, rneed)
        p.children = [p.left, p.right]
        new_n_left = len(p.left.schema)
        full = {}
        for old in sorted(child_needed):
            if old < n_left:
                full[old] = lmap[old]
            else:
                full[old] = rmap[old - n_left] + new_n_left
        p.eq_keys = [(lmap[l], rmap[r]) for l, r in p.eq_keys]
        p.other_conds = [map_refs(c, full) for c in p.other_conds]
        if p.kind in ("semi", "anti"):
            p.schema = Schema(list(p.left.schema.cols))
        else:
            p.schema = Schema(list(p.left.schema.cols)
                              + list(p.right.schema.cols))
        return p, {old: full[old] for old in needed}

    if isinstance(p, (LogicalSort, LogicalTopN)):
        child_needed = set(needed)
        for e, _ in p.keys:
            child_needed |= referenced_columns(e)
        _, cmap = _prune_child(p, 0, child_needed)
        p.keys = [(map_refs(e, cmap), d) for e, d in p.keys]
        p.schema = p.child.schema
        return p, {old: cmap[old] for old in needed}

    if isinstance(p, LogicalLimit):
        _, cmap = _prune_child(p, 0, set(needed))
        p.schema = p.child.schema
        return p, {old: cmap[old] for old in needed}

    from .logical import LogicalExpand
    if isinstance(p, LogicalExpand):
        # appended key/gid columns stay; prune only the passthrough child
        # columns (plus whatever the rollup keys reference)
        n_child = len(p.child.schema)
        child_needed = {i for i in needed if i < n_child}
        for k in p.keys:
            child_needed |= referenced_columns(k)
        _, cmap = _prune_child(p, 0, child_needed)
        p.keys = [map_refs(k, cmap) for k in p.keys]
        new_n_child = len(p.child.schema)
        tail = p.schema.cols[n_child:]       # key cols + gid
        p.schema = Schema(list(p.child.schema.cols) + list(tail))
        full = {}
        for old in needed:
            full[old] = cmap[old] if old < n_child \
                else new_n_child + (old - n_child)
        return p, full

    # DualSource etc.
    return p, {i: i for i in needed}


def _prune_child(p, i, needed):
    child, cmap = prune_columns(p.children[i], needed)
    p.children[i] = child
    if hasattr(p, "child"):
        p.child = child
    return child, cmap


def map_refs(e: Expr, mapping: dict[int, int]) -> Expr:
    if isinstance(e, ColumnRef):
        return ColumnRef(e.dtype, mapping[e.index], e.name)
    if isinstance(e, Func):
        return clone_func(e, (map_refs(a, mapping) for a in e.args))
    return e


def optimize_plan(plan: LogicalPlan) -> LogicalPlan:
    plan = fold_constants(plan)
    plan = push_predicates(plan)
    plan, _ = prune_columns(plan)
    from .rules import eliminate_aggregation, eliminate_max_min
    plan = eliminate_aggregation(plan)
    plan = eliminate_max_min(plan)
    return plan


__all__ = ["optimize_plan", "fold_constants", "push_predicates",
           "prune_columns", "map_refs"]
