"""Cascades-style memo optimizer.

Reference analog: pkg/planner/cascades/ (cascades.go, the memo package
pkg/planner/memo/group.go, and the property-driven cost search of
core/optimizer.go:1080 physicalOptimize / core/find_best_task.go).

The pipeline stays shared with the heuristic path (the reference's
cascades likewise shares the normalize-rule list, core/optimizer.go:80-85):
constant folding, predicate pushdown, column pruning and index-path
selection run first; this package then

  1. builds a **memo** of groups/group-expressions from the logical tree
     (`memo.py`),
  2. **explores** alternatives — DP join-order enumeration over each
     maximal inner-join group, TopN-through-outer-join pushdown
     (`search.py` transformation rules),
  3. **implements** each group under a required *order property*,
     costing physical alternatives (hash vs merge vs index-lookup join,
     sort enforcer vs order-providing child) with the stats-fed model in
     `cost.py`, and
  4. **extracts** the winning tree back to ordinary logical operators —
     join-method annotations ride `LogicalJoin.hint_method`, satisfied
     sorts are dropped, ordered TopN becomes Limit — so the existing
     device/host lowering (`executor/plan.py to_physical`) stays the
     single code generator.

Enabled per-session via `tidb_enable_cascades_planner` (the reference's
sysvar of the same name).  Any failure falls back to the greedy
join-reorder path, so the flag can never break a query.
"""

from __future__ import annotations


def cascades_optimize(plan, stats_handle):
    """Memo search over `plan`; falls back to greedy reorder on any error."""
    from ..join_reorder import reorder_joins
    try:
        from .search import search
        return search(plan, stats_handle)
    except Exception:
        return reorder_joins(plan, stats_handle)


__all__ = ["cascades_optimize"]
