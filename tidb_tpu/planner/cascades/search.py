"""Memo exploration + property-driven implementation + extraction.

Reference analog: pkg/planner/cascades/cascades.go (the two-phase
explore/implement loop) and core/find_best_task.go (required physical
property = sort order; enforcers).  Properties here are orderings —
tuples of (column index into the group schema, desc) — the same prop the
reference threads as property.PhysicalProperty.SortItems.

Transformation rules (explore):
  * DP join-order enumeration over every maximal inner-join group
    (DPsub over connected subsets, rule_join_reorder.go's DP variant);
    oversized groups keep the greedy order from join_reorder.py.
  * TopN pushdown through the outer side of LEFT/RIGHT joins
    (rule_topn_push_down.go).

Implementation rules (per group expression):
  * Join: hash/broadcast default, sort-merge (provides left-key order —
    HostMergeJoin's documented contract), index-lookup (INL) when the
    inner side is an indexed Selection chain.
  * Sort: materialize, or vanish when a child impl provides the order.
  * TopN: heap, or degenerate to Limit over an order-providing child.
  * Everything else: passthrough (order-preserving ops forward the
    required prop to their child; barriers reset it to empty).

The winning tree extracts back to logical operators: join methods become
`hint_method` annotations (which `executor/plan.py` honors and which
disable device fusion for that join, keeping the order contract sound),
satisfied Sorts disappear, ordered TopN becomes Limit.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from ...expr.ir import ColumnRef, Func, referenced_columns
from ..join_reorder import (_as_local_eq, _col_ndv, _flatten, _leaf_rows,
                            _refs_leaves, _reorder_group)
from ..logical import (DataSource, LogicalAggregate, LogicalExpand,
                       LogicalJoin, LogicalLimit, LogicalPlan,
                       LogicalProjection, LogicalSelection, LogicalSetOp,
                       LogicalSort, LogicalTopN, LogicalWindow, Schema)
from ..optimize import map_refs
from . import cost as C
from .memo import Memo, estimate_rows

DP_MAX_LEAVES = 8       # DPsub beyond this is 3^n; fall back to greedy


# ------------------------------------------------------------------ #
# driver

def search(plan: LogicalPlan, stats_handle) -> LogicalPlan:
    memo = Memo()
    node_gid: dict = {}
    root = _insert(memo, plan, stats_handle, node_gid)
    _explore_joins(memo, plan, stats_handle, node_gid)
    _explore_topn(memo, stats_handle)
    s = _Search(memo, stats_handle)
    s.best(root, ())
    return s.extract(root, ())


def _insert(memo: Memo, plan: LogicalPlan, stats_handle,
            node_gid: dict) -> int:
    child_ids = tuple(_insert(memo, c, stats_handle, node_gid)
                      for c in getattr(plan, "children", [])
                      if c is not None)
    rows = estimate_rows(plan, [memo.group(i).rows for i in child_ids],
                         stats_handle)
    gid = memo.insert_expr(plan, child_ids, None, rows)
    node_gid[id(plan)] = gid
    return gid


# ------------------------------------------------------------------ #
# exploration: DP join order

def _explore_joins(memo, plan, stats_handle, node_gid,
                   parent_inner=False) -> None:
    is_inner = isinstance(plan, LogicalJoin) and plan.kind in ("inner",
                                                               "cross")
    if is_inner and not parent_inner:
        alt = _dp_join_alternative(plan, stats_handle)
        if alt is not None and id(plan) in node_gid:
            memo.insert_tree(alt, stats_handle,
                             into=memo.group(node_gid[id(plan)]))
    for c in getattr(plan, "children", []):
        if c is not None:
            _explore_joins(memo, c, stats_handle, node_gid, is_inner)


def _dp_join_alternative(root: LogicalJoin, stats_handle):
    if getattr(root, "hint_leading", None) or getattr(root, "hint_method",
                                                     ""):
        # user hints pin the order/method: the greedy rebuild honors
        # LEADING and preserves leaf markers; DP would discard them
        return _reorder_group(copy.copy(root), stats_handle)
    leaves_off: list = []
    conds: list = []
    total_cols = _flatten(root, leaves_off, conds, 0)
    leaves = [l for _, l in leaves_off]
    spans = [(off, off + len(l.schema)) for off, l in leaves_off]
    n = len(leaves)
    if n < 2:
        return None
    if n > DP_MAX_LEAVES:
        # greedy fallback produces one alternative tree (shares leaves)
        return _reorder_group(copy.copy(root), stats_handle)
    rows = [_leaf_rows(l, stats_handle) for l in leaves]
    cond_sets = [_refs_leaves(c, spans) for c in conds]

    def _eq_ndv(j: int) -> Optional[float]:
        c = conds[j]
        if not (isinstance(c, Func) and c.op == "eq"
                and len(cond_sets[j]) == 2):
            return None
        best = 1.0
        for r in referenced_columns(c):
            for i, (lo, hi) in enumerate(spans):
                if lo <= r < hi:
                    best = max(best, _col_ndv(leaves[i], r - lo,
                                              stats_handle, rows[i]))
        return best

    ndvs = [_eq_ndv(j) for j in range(len(conds))]
    eq_sel = [1.0 / max(v, 1.0) if v is not None else None for v in ndvs]

    full = (1 << n) - 1
    r_cache: dict = {}

    def R(S: int) -> float:
        got = r_cache.get(S)
        if got is not None:
            return got
        v = 1.0
        for i in range(n):
            if S >> i & 1:
                v *= rows[i]
        for j, ls in enumerate(cond_sets):
            if eq_sel[j] is not None and all(S >> i & 1 for i in ls):
                v *= eq_sel[j]
        v = max(v, 1.0)
        r_cache[S] = v
        return v

    def _connected(S1: int, S2: int) -> bool:
        for j, ls in enumerate(cond_sets):
            if len(ls) < 2:
                continue
            m = 0
            for i in ls:
                m |= 1 << i
            if m & S1 and m & S2 and not m & ~(S1 | S2):
                return True
        return False

    # DPsub: dp[S] = (cost, winning split S1)
    dp: dict = {1 << i: (0.0, 0) for i in range(n)}
    for S in range(1, full + 1):
        if S in dp or bin(S).count("1") < 2:
            continue
        low = S & -S
        best_c, best_s1 = None, None
        S1 = (S - 1) & S
        while S1:
            S2 = S ^ S1
            if S1 & low and S1 in dp and S2 in dp:
                # build() probes with the bigger side; cost the same
                # orientation the rebuild will actually emit
                join_c = min(C.hash_join_cost(R(S1), R(S2), R(S)),
                             C.hash_join_cost(R(S2), R(S1), R(S)))
                if not _connected(S1, S2):
                    join_c *= 4.0        # cartesian discouragement
                c = dp[S1][0] + dp[S2][0] + join_c
                if best_c is None or c < best_c:
                    best_c, best_s1 = c, S1
            S1 = (S1 - 1) & S
        dp[S] = (best_c, best_s1)

    used = [False] * len(conds)

    def build(S: int):
        if bin(S).count("1") == 1:
            i = S.bit_length() - 1
            return leaves[i], list(range(*spans[i]))
        S1 = dp[S][1]
        S2 = S ^ S1
        if R(S2) > R(S1):          # bigger side probes (left)
            S1, S2 = S2, S1
        left, lorig = build(S1)
        right, rorig = build(S2)
        origin = lorig + rorig
        remap = {orig: newi for newi, orig in enumerate(origin)}
        here = set(i for i in range(n) if S >> i & 1)
        eq_keys, others = [], []
        for j, (c, ls) in enumerate(zip(conds, cond_sets)):
            if used[j] or not ls <= here:
                continue
            used[j] = True
            c2 = map_refs(c, remap)
            k = _as_local_eq(c2, len(left.schema), len(right.schema))
            if k is not None:
                eq_keys.append(k)
            else:
                others.append(c2)
        node = LogicalJoin(
            "inner" if (eq_keys or others) else "cross", left, right,
            eq_keys=eq_keys, other_conds=others,
            schema=Schema(list(left.schema.cols) + list(right.schema.cols)))
        return node, origin

    tree, origin = build(full)
    rest_map = {orig: newi for newi, orig in enumerate(origin)}
    unplaced = [map_refs(c, rest_map)
                for j, c in enumerate(conds) if not used[j]]
    if unplaced:
        tree = LogicalSelection(tree, unplaced)
    if origin == list(range(total_cols)):
        return tree
    refs = [tree.schema.ref(rest_map[r]) for r in range(total_cols)]
    return LogicalProjection(tree, refs, Schema(list(root.schema.cols)))


# ------------------------------------------------------------------ #
# exploration: TopN through outer join (rule_topn_push_down.go)

def _explore_topn(memo: Memo, stats_handle) -> None:
    for g in list(memo.groups):
        for expr in list(g.exprs):
            n = expr.node
            if not isinstance(n, LogicalTopN) or not expr.child_ids \
                    or not n.keys:
                continue
            # see through a Projection chain, remapping the sort keys
            keys = list(n.keys)
            cur = memo.group(expr.child_ids[0])
            projs: list = []
            ok = True
            while ok and cur.exprs:
                e0 = cur.exprs[0]
                if not isinstance(e0.node, LogicalProjection) \
                        or not e0.child_ids:
                    break
                mapped = []
                for k, d in keys:
                    src = (e0.node.exprs[k.index]
                           if isinstance(k, ColumnRef)
                           and k.index < len(e0.node.exprs) else None)
                    if not isinstance(src, ColumnRef):
                        ok = False
                        break
                    mapped.append((ColumnRef(src.dtype, src.index,
                                             src.name), d))
                if ok:
                    keys = mapped
                    projs.append(e0)
                    cur = memo.group(e0.child_ids[0])
            if not ok:
                continue
            for jexpr in list(cur.exprs):
                j = jexpr.node
                if not isinstance(j, LogicalJoin) or j.kind not in (
                        "left", "right") or len(jexpr.child_ids) != 2:
                    continue
                _push_topn_through(memo, g, n, keys, projs, cur, jexpr)


def _push_topn_through(memo, topn_group, topn, keys, projs, join_group,
                       jexpr) -> None:
    j = jexpr.node
    lg, rg = (memo.group(i) for i in jexpr.child_ids)
    n_left = len(lg.schema)
    outer = 0 if j.kind == "left" else 1
    lo = 0 if outer == 0 else n_left
    hi = n_left if outer == 0 else n_left + len(rg.schema)
    side_keys = []
    for e, desc in keys:
        if not isinstance(e, ColumnRef) or not lo <= e.index < hi:
            return
        side_keys.append((ColumnRef(e.dtype, e.index - lo, e.name), desc))
    side_g = lg if outer == 0 else rg
    side_node = side_g.exprs[0].node
    inner_topn = LogicalTopN(side_node, side_keys,
                             topn.limit + topn.offset, 0)
    gid = memo.insert_expr(
        inner_topn, (side_g.gid,),
        None, min(side_g.rows, float(topn.limit + topn.offset)))
    j2 = copy.copy(j)
    child_ids = ((gid, rg.gid) if outer == 0 else (lg.gid, gid))
    # the outer side shrank to ≤ limit+offset rows; scale the join (and
    # the projections above, which preserve row count) accordingly
    frac = min(1.0, float(topn.limit + topn.offset)
               / max(side_g.rows, 1.0))
    new_rows = max(join_group.rows * frac, 1.0)
    gid = memo.insert_expr(j2, child_ids, None, new_rows)
    for pexpr in reversed(projs):
        gid = memo.insert_expr(copy.copy(pexpr.node), (gid,), None,
                               new_rows)
    memo.insert_expr(copy.copy(topn), (gid,), topn_group,
                     topn_group.rows)


# ------------------------------------------------------------------ #
# implementation

@dataclass
class Winner:
    cost: float
    expr: object = None            # GroupExpr; None => group-level enforcer
    child_props: tuple = ()
    provides: tuple = ()
    method: str = ""               # join: '' | 'merge' | 'inl'
    transform: str = ""            # '' | 'drop_sort' | 'topn_limit'
    enforce: tuple = ()            # wrap a Sort with this prop on top
    skip_cost: tuple = ()          # child slots costed out-of-band (INL)


def _satisfies(provides: tuple, prop: tuple) -> bool:
    return len(provides) >= len(prop) and provides[:len(prop)] == prop


def _prop_of_keys(keys, width: int) -> Optional[tuple]:
    out = []
    for e, desc in keys:
        if not isinstance(e, ColumnRef) or e.index >= width:
            return None
        out.append((e.index, bool(desc)))
    return tuple(out)


class _Search:
    def __init__(self, memo: Memo, stats_handle):
        self.memo = memo
        self.stats = stats_handle

    def best(self, gid: int, prop: tuple) -> Winner:
        g = self.memo.group(gid)
        got = g.best.get(prop)
        if got is not None:
            return got
        cands: list[Winner] = []
        for expr in g.exprs:
            cands.extend(self._alternatives(g, expr, prop))
        if prop:
            base = self.best(gid, ())
            cands.append(Winner(base.cost + C.sort_cost(g.rows),
                                enforce=prop, provides=prop))
        if not cands:
            raise RuntimeError(f"no implementation for group {gid}")
        w = min(cands, key=lambda c: c.cost)
        g.best[prop] = w
        return w

    # ------------------------------------------------------------- #

    def _child_total(self, expr, child_props, skip=()) -> float:
        return sum(self.best(cid, cp).cost
                   for i, (cid, cp) in enumerate(zip(expr.child_ids,
                                                     child_props))
                   if i not in skip)

    def _alternatives(self, g, expr, prop) -> list:
        n = expr.node
        memo = self.memo
        ch_rows = [memo.group(c).rows for c in expr.child_ids]
        out = []

        def add(local, child_props, provides, **kw):
            if not _satisfies(provides, prop):
                return
            total = local + self._child_total(expr, child_props,
                                              kw.get("skip_cost", ()))
            out.append(Winner(total, expr, tuple(child_props),
                              tuple(provides), **kw))

        if isinstance(n, LogicalJoin):
            self._join_alts(g, expr, prop, ch_rows, add)
        elif isinstance(n, LogicalSort):
            kp = _prop_of_keys(n.keys, len(g.schema))
            provides = kp or ()
            add(C.sort_cost(ch_rows[0]), ((),), provides)
            if kp is not None:
                add(0.0, (kp,), kp, transform="drop_sort")
        elif isinstance(n, LogicalTopN):
            k = float(n.limit + n.offset)
            kp = _prop_of_keys(n.keys, len(g.schema))
            add(C.topn_cost(ch_rows[0], k), ((),), kp or ())
            if kp is not None:
                add(k * 0.2, (kp,), kp, transform="topn_limit")
        elif isinstance(n, LogicalLimit):
            add(float(n.limit + n.offset) * 0.1, (prop,), prop)
        elif isinstance(n, LogicalSelection):
            add(ch_rows[0] * 0.2 * max(len(n.conditions), 1), (prop,), prop)
        elif isinstance(n, LogicalProjection):
            mapped = self._remap_prop_through_proj(n, prop)
            if mapped is not None:
                add(ch_rows[0] * 0.3, (mapped,), prop)
            else:
                add(ch_rows[0] * 0.3, ((),), ())
        elif isinstance(n, LogicalAggregate):
            add(C.agg_cost(ch_rows[0] if ch_rows else 1.0, g.rows),
                tuple(() for _ in expr.child_ids), ())
        elif isinstance(n, DataSource):
            from ...executor.plan import _scan_device_ok
            dev = (not getattr(n.table, "is_memtable", False)
                   and _scan_device_ok(n))
            add(C.scan_cost(g.rows, dev), (), ())
        else:
            # barriers: Window/SetOp/Expand/Apply/CTE/index nodes
            add(g.rows * C.HOST_ROW,
                tuple(() for _ in expr.child_ids), ())
        return out

    def _remap_prop_through_proj(self, n: LogicalProjection,
                                 prop: tuple) -> Optional[tuple]:
        out = []
        for i, desc in prop:
            if i >= len(n.exprs) or not isinstance(n.exprs[i], ColumnRef):
                return None
            out.append((n.exprs[i].index, desc))
        return tuple(out)

    # ------------------------------------------------------------- #

    def _join_alts(self, g, expr, prop, ch_rows, add) -> None:
        n: LogicalJoin = expr.node
        l_rows = ch_rows[0] if ch_rows else 1.0
        r_rows = ch_rows[1] if len(ch_rows) > 1 else 1.0
        nochild = tuple(() for _ in expr.child_ids)
        from ...executor.plan import _join_method_hint
        if _join_method_hint(n):
            # a user hint (node-level or a leaf USE-style marker) pins the
            # method: cost as the default and leave method empty so the
            # extracted copy never stamps over the hint at lowering
            add(C.hash_join_cost(l_rows, r_rows, g.rows), nochild, ())
            return
        # default: host hash / device broadcast (lowering decides)
        add(C.hash_join_cost(l_rows, r_rows, g.rows), nochild, ())
        # sort-merge: provides left-eq-key ascending prefix over numeric
        # keys (HostMergeJoin's key-ordered-output contract).  Order is
        # promised only for INNER joins: an outer join's unmatched NULL
        # keys sort by their encoding, which need not match SQL
        # NULLS-FIRST; string keys order by dictionary rank — excluded
        # to keep the contract exact.
        if (n.eq_keys and not n.null_aware and n.kind in ("inner", "left")
                and len(expr.child_ids) == 2):
            provides = []
            if n.kind == "inner":
                lsch = self.memo.group(expr.child_ids[0]).schema
                for li, _ri in n.eq_keys:
                    if li < len(lsch) \
                            and not lsch.cols[li].dtype.is_string:
                        provides.append((li, False))
                    else:
                        break
            add(C.merge_join_cost(l_rows, r_rows, g.rows), nochild,
                tuple(provides), method="merge")
        # index-lookup (INL): inner side must be a Selection chain over an
        # indexed DataSource; inner scan cost replaced by per-probe lookups
        inner = self._inl_inner(expr, n)
        if inner is not None:
            inner_rows = float(getattr(inner.table, "num_rows", 0) or 1)
            add(C.inl_join_cost(l_rows, inner_rows, g.rows), nochild, (),
                method="inl", skip_cost=(1,))

    def _inl_inner(self, expr, n: LogicalJoin):
        """Mirror executor/plan.py _try_inl_join's structural checks for
        the (outer=left, inner=right) orientation the bare hint takes."""
        from ...utils.collate import is_binary
        if n.kind not in ("inner", "left", "semi", "anti") \
                or len(n.eq_keys) != 1 \
                or (n.kind == "anti" and n.null_aware) \
                or len(expr.child_ids) != 2:
            return None
        li, ri = n.eq_keys[0]
        gid = expr.child_ids[1]
        while True:
            ge = self.memo.group(gid).exprs[0]
            node = ge.node
            if isinstance(node, LogicalSelection):
                gid = ge.child_ids[0]
                continue
            break
        if not isinstance(node, DataSource) \
                or getattr(node.table, "kv", None) is None \
                or getattr(node.table, "is_memtable", False):
            return None
        lsch = self.memo.group(expr.child_ids[0]).schema
        rsch = self.memo.group(expr.child_ids[1]).schema
        if li >= len(lsch) or ri >= len(rsch):
            return None
        ot, it = lsch.cols[li].dtype, rsch.cols[ri].dtype
        if ot.kind != it.kind or ot.scale != it.scale:
            return None
        if it.is_string and not is_binary(it.collation):
            return None
        key_name = rsch.cols[ri].name.lower()
        ix = next((x for x in getattr(node.table, "indexes", [])
                   if x.state == "public"
                   and x.columns[0].lower() == key_name), None)
        return node if ix is not None else None

    # ------------------------------------------------------------- #
    # extraction

    def extract(self, gid: int, prop: tuple) -> LogicalPlan:
        g = self.memo.group(gid)
        w = g.best[prop]
        if w.expr is None:                      # group-level sort enforcer
            child = self.extract(gid, ())
            keys = [(child.schema.ref(i), desc) for i, desc in w.enforce]
            return LogicalSort(child, keys)
        children = [self.extract(cid, cp)
                    for cid, cp in zip(w.expr.child_ids, w.child_props)]
        n = w.expr.node
        if w.transform == "drop_sort":
            return children[0]
        if w.transform == "topn_limit":
            return LogicalLimit(children[0], n.limit, n.offset)
        node = copy.copy(n)
        node.children = children
        if hasattr(node, "child"):
            node.child = children[0] if children else None
        if isinstance(node, LogicalJoin):
            node.left, node.right = children
            if w.method:
                node.hint_method = w.method
        if isinstance(node, LogicalSetOp):
            node.left, node.right = children
        if isinstance(node, LogicalSelection) and children:
            # Selection shares its child's schema object
            node.schema = children[0].schema
        return node


__all__ = ["search", "Winner"]
