"""Memo: groups of logically-equivalent plan expressions.

Reference analog: pkg/planner/memo/group.go + group_expr.go.  A Group is
an equivalence class of logical subtrees sharing one output schema and
one cardinality estimate; a GroupExpr is one operator whose children are
groups.  Expressions are deduplicated by fingerprint so the DP join-order
rule's rebuilt trees share leaf groups with the original tree instead of
duplicating them (the memo's whole point).

Column references are positional in this framework, so alternative join
orders carry their own restoring Projection (exactly like
join_reorder.py's rebuild) — that keeps every expression in a group
schema-identical, which is what makes the groups true equivalence
classes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ...expr.ir import ColumnRef
from ..logical import (DataSource, LogicalAggregate, LogicalApply,
                       LogicalCTEScan, LogicalExpand, LogicalJoin,
                       LogicalLimit, LogicalPlan, LogicalProjection,
                       LogicalSelection, LogicalSetOp, LogicalSort,
                       LogicalTopN, LogicalWindow)


@dataclass
class GroupExpr:
    node: LogicalPlan              # payload; its .children are IGNORED
    child_ids: tuple               # group ids, in child order
    fingerprint: tuple = None


@dataclass
class Group:
    gid: int
    exprs: list = field(default_factory=list)
    schema: object = None
    rows: float = 1000.0           # cardinality estimate (logical property)
    # physical memo: prop key -> Winner (search.py)
    best: dict = field(default_factory=dict)


class Memo:
    def __init__(self):
        self.groups: list[Group] = []
        self._fp_to_group: dict = {}    # expr fingerprint -> gid

    def group(self, gid: int) -> Group:
        return self.groups[gid]

    def new_group(self, schema, rows: float) -> Group:
        g = Group(len(self.groups), schema=schema, rows=rows)
        self.groups.append(g)
        return g

    def insert_expr(self, node: LogicalPlan, child_ids: tuple,
                    group: Optional[Group], rows: float) -> int:
        """Insert one expression; dedup by fingerprint.  Returns the gid
        it landed in (an existing group on a fingerprint hit)."""
        fp = node_fingerprint(node, child_ids)
        hit = self._fp_to_group.get(fp)
        if hit is not None:
            if group is not None and hit != group.gid:
                # the same expression appearing in two groups would merge
                # them in a full cascades engine; alternatives here are
                # only ever inserted into the group they were derived
                # from, so just keep the first placement
                return hit
            return hit
        if group is None:
            group = self.new_group(node.schema, rows)
        group.exprs.append(GroupExpr(node, child_ids, fp))
        self._fp_to_group[fp] = group.gid
        return group.gid

    def insert_tree(self, plan: LogicalPlan, stats_handle,
                    into: Optional[Group] = None) -> int:
        """Recursively insert a logical tree, returning its root gid."""
        child_ids = tuple(self.insert_tree(c, stats_handle)
                          for c in getattr(plan, "children", [])
                          if c is not None)
        rows = estimate_rows(plan, [self.groups[i].rows for i in child_ids],
                             stats_handle)
        return self.insert_expr(plan, child_ids, into, rows)


# ------------------------------------------------------------------ #
# fingerprints

def _exprs_fp(exprs) -> tuple:
    return tuple(str(e) for e in exprs)


def node_fingerprint(n: LogicalPlan, child_ids: tuple) -> tuple:
    t = type(n).__name__
    if isinstance(n, DataSource):
        key = (n.alias.lower(), id(n.table), tuple(n.col_offsets or ()),
               str(getattr(n, "as_of_ts", None)))
    elif isinstance(n, LogicalSelection):
        key = _exprs_fp(n.conditions)
    elif isinstance(n, LogicalProjection):
        key = _exprs_fp(n.exprs)
    elif isinstance(n, LogicalAggregate):
        key = (_exprs_fp(n.group_exprs),
               tuple((a.func.value, str(a.arg), a.distinct) for a in n.aggs))
    elif isinstance(n, LogicalJoin):
        key = (n.kind, tuple(n.eq_keys), _exprs_fp(n.other_conds),
               n.null_aware)
    elif isinstance(n, (LogicalSort, LogicalTopN)):
        key = (tuple((str(e), d) for e, d in n.keys),
               getattr(n, "limit", None), getattr(n, "offset", 0))
    elif isinstance(n, LogicalLimit):
        key = (n.limit, n.offset)
    elif isinstance(n, LogicalSetOp):
        key = (n.kind, n.all)
    elif isinstance(n, LogicalExpand):
        key = (_exprs_fp(n.keys or ()), n.levels)
    elif isinstance(n, LogicalWindow):
        key = tuple((w.func, _exprs_fp(w.args), _exprs_fp(w.partition),
                     tuple((str(e), d) for e, d in w.order), str(w.frame))
                    for w in n.items)
    else:
        # LogicalApply / CTEScan / index nodes: identity (no dedup) —
        # they carry engine handles that positional fingerprints can't
        # capture safely
        key = (id(n),)
    return (t, key, child_ids)


# ------------------------------------------------------------------ #
# cardinality (logical property; reference pkg/planner/cardinality)

def _ds_of_chain(n):
    """DataSource at the bottom of a Selection/Projection chain, if any."""
    cur = n
    while isinstance(cur, (LogicalSelection, LogicalProjection)):
        cur = cur.children[0]
    return cur if isinstance(cur, DataSource) else None


def estimate_rows(n: LogicalPlan, child_rows: list, stats_handle) -> float:
    from ..cardinality import conds_selectivity
    if isinstance(n, DataSource):
        return max(float(getattr(n.table, "num_rows", 0) or 0), 1.0)
    if isinstance(n, LogicalSelection):
        base = child_rows[0] if child_rows else 1.0
        ds = _ds_of_chain(n.children[0])
        if ds is not None and stats_handle is not None:
            st = stats_handle.get(ds.table)
            try:
                return max(base * conds_selectivity(st, n.conditions, ds),
                           1.0)
            except Exception:
                pass
        return max(base * (0.8 ** len(n.conditions)), 1.0)
    if isinstance(n, LogicalJoin):
        l = child_rows[0] if child_rows else 1.0
        r = child_rows[1] if len(child_rows) > 1 else 1.0
        if n.kind in ("semi", "anti"):
            return max(l * 0.5, 1.0)
        if not n.eq_keys:
            return max(l * r, 1.0)
        ndv = max(join_key_ndv(n, stats_handle), 1.0)
        out = l * r / ndv
        if n.kind == "left":
            out = max(out, l)
        elif n.kind == "right":
            out = max(out, r)
        return max(out, 1.0)
    if isinstance(n, LogicalAggregate):
        if not n.group_exprs:
            return 1.0
        base = child_rows[0] if child_rows else 1.0
        ndv = group_ndv(n, stats_handle)
        return max(min(ndv if ndv is not None else base ** 0.75, base), 1.0)
    if isinstance(n, (LogicalTopN, LogicalLimit)):
        base = child_rows[0] if child_rows else 1.0
        return max(min(base, float(n.limit + n.offset)), 1.0)
    if isinstance(n, LogicalSetOp):
        return max(sum(child_rows), 1.0)
    if isinstance(n, LogicalExpand):
        return max((child_rows[0] if child_rows else 1.0) * n.levels, 1.0)
    if isinstance(n, LogicalCTEScan):
        return 1000.0
    return max(child_rows[0] if child_rows else 1000.0, 1.0)


def join_key_ndv(n: LogicalJoin, stats_handle) -> float:
    """Max key-column NDV across both sides (join_reorder's fanout rule)."""
    from ..join_reorder import _col_ndv
    best = 1.0
    for li, ri in n.eq_keys:
        for side, ci in ((n.children[0], li), (n.children[1], ri)):
            rows = getattr(getattr(side, "table", None), "num_rows", None)
            fb = float(rows) if rows else 1000.0
            try:
                best = max(best, _col_ndv(side, ci, stats_handle, fb))
            except Exception:
                pass
    return best


def group_ndv(n: LogicalAggregate, stats_handle) -> Optional[float]:
    """Product of group-key NDVs when every key is a stats-backed column."""
    ds = _ds_of_chain(n.children[0])
    if ds is None or stats_handle is None:
        return None
    st = stats_handle.get(ds.table)
    if st is None:
        return None
    total = 1.0
    for e in n.group_exprs:
        if not isinstance(e, ColumnRef):
            return None
        cs = st.col(ds.schema.cols[e.index].name) \
            if e.index < len(ds.schema.cols) else None
        if cs is None or cs.ndv <= 0:
            return None
        total *= float(cs.ndv)
    return total


__all__ = ["Memo", "Group", "GroupExpr", "node_fingerprint",
           "estimate_rows"]
