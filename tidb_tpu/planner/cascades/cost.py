"""Cost model for the memo search.

Reference analog: pkg/planner/core/plan_cost_ver2.go — per-operator cost
formulas in abstract "row units", weighted so the *relative* choices the
search makes (hash vs merge vs index-lookup join, sort enforcer vs
order-providing child, DP join orders) match what the executors actually
measure on this engine.  Absolute values are meaningless by design, as in
the reference.
"""

from __future__ import annotations

import math

# per-row weights
HOST_ROW = 1.0          # host scan/filter/projection per row
DEV_ROW = 0.25          # device-fused per row (XLA fusion amortizes ops)
DEV_DISPATCH = 20_000.0  # fixed per-program dispatch+transfer overhead
BUILD_ROW = 1.8         # hash-table build per row
PROBE_ROW = 1.0         # hash probe per row
MERGE_ROW = 0.6         # sorted-merge advance per row
SORT_ROW = 0.45         # comparison-sort per row per log2(n)
LOOKUP_ROW = 14.0       # index lookup per probe row per log2(inner)
AGG_ROW = 1.4           # group-hash update per row
OUT_ROW = 0.3           # materializing one output row
TOPN_ROW = 0.8          # heap push per row


def log2(n: float) -> float:
    return math.log2(max(n, 2.0))


def scan_cost(rows: float, device_ok: bool) -> float:
    if device_ok:
        return DEV_DISPATCH + rows * DEV_ROW
    return rows * HOST_ROW


def sort_cost(rows: float) -> float:
    return rows * SORT_ROW * log2(rows)


def hash_join_cost(l_rows: float, r_rows: float, out_rows: float) -> float:
    return r_rows * BUILD_ROW + l_rows * PROBE_ROW + out_rows * OUT_ROW


def merge_join_cost(l_rows: float, r_rows: float, out_rows: float) -> float:
    return (sort_cost(l_rows) + sort_cost(r_rows)
            + (l_rows + r_rows) * MERGE_ROW + out_rows * OUT_ROW)


def inl_join_cost(outer_rows: float, inner_rows: float,
                  out_rows: float) -> float:
    return outer_rows * LOOKUP_ROW * log2(inner_rows) + out_rows * OUT_ROW


def agg_cost(in_rows: float, groups: float) -> float:
    return in_rows * AGG_ROW + groups * OUT_ROW


def topn_cost(in_rows: float, k: float) -> float:
    return in_rows * TOPN_ROW * log2(k)


__all__ = ["scan_cost", "sort_cost", "hash_join_cost", "merge_join_cost",
           "inl_join_cost", "agg_cost", "topn_cost", "log2"]
