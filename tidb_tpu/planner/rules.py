"""Standalone logical rewrite rules beyond the core optimize_plan pipeline.

Reference analogs from the 27-rule list (core/optimizer.go:87-115):
  * eliminate_max_min       — rule_max_min_eliminate.go: a bare MAX/MIN
    over an indexed column becomes TopN(1) over an index-ordered walk,
    turning a full scan into an index seek.
  * eliminate_aggregation   — rule_aggregation_elimination.go: GROUP BY
    covering the table's primary key makes every group one row; the agg
    collapses to a projection.
  * rewrite_skew_distinct   — rule_aggregation_skew_distinctagg.go: a
    grouped DISTINCT aggregate splits into a dedup pre-aggregate on
    (keys, d) and a plain final aggregate — here it doubles as the path
    that keeps DISTINCT work on the device (the inner agg is a plain
    multi-key group-by the fused engine handles), gated by
    tidb_opt_skew_distinct_agg exactly like the reference.
"""

from __future__ import annotations

from typing import Optional

from ..copr.dag import AggFunc
from ..expr import builders as B
from ..expr.ir import ColumnRef
from ..types import dtypes as dt
from .logical import (AggItem, DataSource, LogicalAggregate, LogicalPlan,
                      LogicalProjection, LogicalSelection, LogicalTopN,
                      Schema, SchemaCol)


def _recurse(plan: LogicalPlan, fn) -> LogicalPlan:
    for i, c in enumerate(plan.children):
        plan.children[i] = fn(c)
    if hasattr(plan, "child"):
        plan.child = plan.children[0]
    if len(plan.children) == 2 and hasattr(plan, "left"):
        plan.left, plan.right = plan.children
    return plan


def _chain_ds(n) -> Optional[DataSource]:
    cur = n
    while isinstance(cur, LogicalSelection):
        cur = cur.children[0]
    return cur if isinstance(cur, DataSource) else None


# ------------------------------------------------------------------ #
# MAX/MIN elimination

def eliminate_max_min(plan: LogicalPlan) -> LogicalPlan:
    """MAX(c)/MIN(c) with no GROUP BY over an index-led column: rewrite
    the input to TopN(1) ordered by c so the physical planner's
    index-ordered walk (executor/plan.py _try_index_ordered_topn) serves
    it with an early-stop seek instead of a full scan."""
    plan = _recurse(plan, eliminate_max_min)
    if not isinstance(plan, LogicalAggregate) or plan.group_exprs \
            or len(plan.aggs) != 1:
        return plan
    item = plan.aggs[0]
    if item.func not in (AggFunc.MAX, AggFunc.MIN) \
            or not isinstance(item.arg, ColumnRef):
        return plan
    ds = _chain_ds(plan.children[0])
    if ds is None or getattr(ds.table, "kv", None) is None \
            or getattr(ds.table, "partition", None) is not None \
            or getattr(ds, "as_of_ts", None) is not None \
            or getattr(ds.table, "is_memtable", False):
        return plan
    ci = item.arg.index
    if ci >= len(ds.col_offsets):
        return plan
    col_name = ds.table.col_names[ds.col_offsets[ci]].lower()
    if not any(ix.state == "public" and ix.columns[0].lower() == col_name
               for ix in getattr(ds.table, "indexes", [])):
        return plan
    child = plan.children[0]
    if item.arg.dtype.nullable:
        # MAX/MIN skip NULLs; the ordered walk must too
        # (rule_max_min_eliminate.go injects the same IsNotNull)
        child = LogicalSelection(
            child, [B.logic("not", B.is_null(child.schema.ref(ci)))])
    topn = LogicalTopN(child,
                       [(child.schema.ref(ci), item.func is AggFunc.MAX)],
                       1)
    plan.children[0] = topn
    plan.child = topn
    return plan


# ------------------------------------------------------------------ #
# aggregation elimination over unique keys

_SCALARIZABLE = (AggFunc.COUNT, AggFunc.SUM, AggFunc.MIN, AggFunc.MAX,
                 AggFunc.FIRST, AggFunc.ANY_VALUE)


def eliminate_aggregation(plan: LogicalPlan) -> LogicalPlan:
    """GROUP BY covering the child table's primary key: every group is a
    single row, so aggregates evaluate row-wise and the whole operator
    becomes a Projection (rule_aggregation_elimination.go)."""
    plan = _recurse(plan, eliminate_aggregation)
    if not isinstance(plan, LogicalAggregate) or not plan.group_exprs:
        return plan
    ds = _chain_ds(plan.children[0])
    if ds is None:
        return plan
    pk = [c.lower() for c in getattr(ds.table, "primary_key", [])]
    if not pk:
        return plan
    key_cols = set()
    for e in plan.group_exprs:
        if isinstance(e, ColumnRef) and e.index < len(ds.col_offsets):
            key_cols.add(ds.table.col_names[ds.col_offsets[e.index]]
                         .lower())
    if not set(pk) <= key_cols:
        return plan
    if not all(a.func in _SCALARIZABLE
               and (a.arg is not None or a.func is AggFunc.COUNT)
               for a in plan.aggs):
        return plan
    exprs = list(plan.group_exprs)
    for a in plan.aggs:
        if a.arg is None:                     # COUNT(*)
            exprs.append(B.lit(1, a.out_dtype))
        elif a.func is AggFunc.COUNT:
            exprs.append(B.if_(B.is_null(a.arg),
                               B.lit(0, a.out_dtype),
                               B.lit(1, a.out_dtype)))
        else:
            exprs.append(B.cast(a.arg, a.out_dtype))
    return LogicalProjection(plan.children[0], exprs,
                             Schema(list(plan.schema.cols)))


# ------------------------------------------------------------------ #
# skew-distinct two-stage split

def rewrite_skew_distinct(plan: LogicalPlan) -> LogicalPlan:
    plan = _recurse(plan, rewrite_skew_distinct)
    if not isinstance(plan, LogicalAggregate) or not plan.group_exprs:
        return plan
    dist = [a for a in plan.aggs if a.distinct]
    if not dist:
        return plan
    # all DISTINCT aggs must be COUNT/SUM over one shared argument
    d_arg = dist[0].arg
    if d_arg is None:
        return plan
    for a in dist:
        if a.func not in (AggFunc.COUNT, AggFunc.SUM) or a.arg is None \
                or str(a.arg) != str(d_arg):
            return plan
    plain = [a for a in plan.aggs if not a.distinct]
    if not all(a.func in (AggFunc.COUNT, AggFunc.SUM, AggFunc.MIN,
                          AggFunc.MAX) for a in plain):
        return plan

    child = plan.children[0]
    ng = len(plan.group_exprs)
    # inner: dedup pre-aggregate over (group keys, d)
    inner_groups = list(plan.group_exprs) + [d_arg]
    inner_items = [AggItem(a.func, a.arg, False, a.out_dtype)
                   for a in plain]
    inner_cols = ([SchemaCol(c.name, c.dtype)
                   for c in plan.schema.cols[:ng]]
                  + [SchemaCol("_sdr_d", d_arg.dtype)]
                  + [SchemaCol(f"_sdr_a{i}", a.out_dtype)
                     for i, a in enumerate(plain)])
    inner = LogicalAggregate(child, inner_groups, inner_items,
                             Schema(inner_cols))
    # outer: original keys; DISTINCT aggs read the d key column, plain
    # aggs merge their partials (COUNT merges via SUM)
    outer_groups = [ColumnRef(c.dtype, i, c.name)
                    for i, c in enumerate(inner_cols[:ng])]
    d_ref = ColumnRef(d_arg.dtype, ng, "_sdr_d")
    outer_aggs = []
    pi = 0
    for a in plan.aggs:
        if a.distinct:
            outer_aggs.append(AggItem(a.func, d_ref, False, a.out_dtype))
        else:
            ref = ColumnRef(a.out_dtype, ng + 1 + pi, f"_sdr_a{pi}")
            merge = (AggFunc.SUM if a.func is AggFunc.COUNT else a.func)
            outer_aggs.append(AggItem(merge, ref, False, a.out_dtype))
            pi += 1
    # outer schema must present aggs in the ORIGINAL order
    return LogicalAggregate(inner, outer_groups, outer_aggs,
                            Schema(list(plan.schema.cols)))


__all__ = ["eliminate_max_min", "eliminate_aggregation",
           "rewrite_skew_distinct"]
