"""Index advisor (pkg/planner/indexadvisor analog, heuristic cut).

Mines the statement summary's sample SQL: for single-table SELECTs,
equality predicates on columns that no public index covers become index
candidates, scored by the digest's execution count.  Surfaced via
`ADMIN RECOMMEND INDEX`.
"""

from __future__ import annotations

from ..sql import ast as A
from ..sql.parser import parse_sql


def _eq_cols(node, out: list) -> None:
    """Collect column names compared by equality to literals in a WHERE
    conjunction (the sargable-predicate walk, simplified)."""
    if isinstance(node, A.Binary):
        if node.op == "AND":
            _eq_cols(node.left, out)
            _eq_cols(node.right, out)
            return
        if node.op == "=":
            for a, b in ((node.left, node.right), (node.right, node.left)):
                if isinstance(a, A.Ident) and isinstance(b, A.Lit):
                    out.append(a.parts[-1].lower())


def recommend_indexes(domain, db: str) -> list[tuple]:
    """[(table, columns, est_benefit_execs, sample_sql)] recommendations."""
    scores: dict[tuple, dict] = {}
    for digest, execs, _avg, _mx, _rows, sample, *_extra in \
            domain.stmt_summary.summary_rows():
        try:
            stmts = parse_sql(sample)
        except Exception:
            continue
        for stmt in stmts:
            if not isinstance(stmt, A.SelectStmt) or stmt.where is None \
                    or not isinstance(stmt.from_, A.TableName):
                continue
            tname = stmt.from_.name
            try:
                tbl = domain.catalog.get_table(stmt.from_.db or db, tname)
            except Exception:
                continue
            if getattr(tbl, "is_memtable", False):
                continue
            cols: list = []
            _eq_cols(stmt.where, cols)
            cols = [c for c in cols if c in
                    {n.lower() for n in tbl.col_names}]
            if not cols:
                continue
            # drop candidates already served by an index prefix
            covered = {ix.columns[0].lower()
                       for ix in getattr(tbl, "indexes", [])
                       if ix.state == "public"}
            cols = sorted(set(cols) - covered)
            if not cols:
                continue
            key = (tname, tuple(cols))
            s = scores.setdefault(key, {"execs": 0, "sample": sample})
            s["execs"] += execs
    return [(t, ",".join(cs), s["execs"], s["sample"])
            for (t, cs), s in sorted(scores.items(),
                                     key=lambda kv: -kv[1]["execs"])]


__all__ = ["recommend_indexes"]
