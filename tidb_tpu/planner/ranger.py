"""Predicate -> index range extraction + index-path plan rewrite.

Reference analog: pkg/util/ranger (predicates on index prefixes ->
[start,end) key ranges) and the point-get fast path
(executor/point_get.go, adapter.go:339).  Round-1 scope: equality-prefix
access — an index is usable when the WHERE conjuncts pin a prefix of its
columns with constants; a full pin of a unique index becomes a PointGet,
any other prefix becomes an IndexLookUp range scan.  Inequality ranges on
the first unpinned column extend the scan bounds.  Everything else stays
on the columnar TPU scan path (which is the right default for analytic
predicates — the index path exists for OLTP-selective queries).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..expr.ir import ColumnRef, Const, Expr, Func
from ..types import dtypes as dt
from .logical import (DataSource, LogicalPlan, LogicalSelection, Schema,
                      SchemaCol)

K = dt.TypeKind

_CMP_OPS = {"eq", "lt", "le", "gt", "ge"}


@dataclass
class IndexAccess:
    """A chosen index access path."""
    index: object                       # catalog.IndexInfo
    eq_values: list = field(default_factory=list)   # python values, prefix
    # optional range on the first unpinned column: (low, low_incl, high,
    # high_incl) — None bound = unbounded
    range_col: Optional[str] = None
    low: object = None
    low_incl: bool = True
    high: object = None
    high_incl: bool = True
    residual: list = field(default_factory=list)    # unconsumed conditions
    is_point: bool = False              # full unique prefix => <=1 row


def _const_for(col_type: dt.DataType, c: Const):
    """Const IR value -> python value encodable for this column, or None
    if the types don't line up (None = index unusable for this conjunct,
    always safe).  Must mirror the scan path's const coercions
    (expr/compile.py) or the index would return different rows — decimal
    consts carry SCALED ints at the const's own scale, so every cross-type
    pairing rescales explicitly."""
    from ..types import decimal as dec
    if col_type.is_string:
        from ..utils.collate import is_binary
        if not is_binary(col_type.collation):
            # ci collation: index keys are binary-exact bytes, a binary
            # point/range scan would miss case variants — keep the
            # predicate as a residual filter instead
            return None
    v = c.value
    if v is None:
        return None
    k = col_type.kind
    ck = c.dtype.kind
    if ck == K.DECIMAL and isinstance(v, int):
        # v is scaled by 10^c.dtype.scale
        fs = c.dtype.scale
        if k == K.DECIMAL:
            ts = col_type.scale
            if ts >= fs:
                return v * dec.pow10(ts - fs)
            div = dec.pow10(fs - ts)
            return v // div if v % div == 0 else None
        if k in (K.INT64, K.UINT64):
            div = dec.pow10(fs)
            return v // div if v % div == 0 else None
        if k == K.FLOAT64:
            return v / dec.pow10(fs)
        return None
    if k in (K.INT64, K.UINT64):
        if isinstance(v, (int, bool)):
            return int(v)
        if isinstance(v, float):
            return int(v) if v == int(v) else None
        return None
    if k == K.FLOAT64:
        return float(v) if isinstance(v, (int, float)) else None
    if k == K.FLOAT32:
        return None       # float32 storage rounding vs f64 consts: unsafe
    if k == K.DECIMAL:
        if isinstance(v, int):      # integer literal
            return v * dec.pow10(col_type.scale)
        return None
    if k in (K.DATE, K.DATETIME):
        return int(v) if ck == k and isinstance(v, int) else None
    if k == K.STRING:
        return str(v) if isinstance(v, str) else None
    return None


def _cmp_parts(cond: Expr):
    """cond as (op, col_index, const) with the column on the left, or
    None."""
    if not (isinstance(cond, Func) and cond.op in _CMP_OPS):
        return None
    a, b = cond.args
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    if isinstance(a, ColumnRef) and isinstance(b, Const):
        return cond.op, a.index, b
    if isinstance(b, ColumnRef) and isinstance(a, Const):
        return flip[cond.op], b.index, a
    return None


def match_index(conditions: list[Expr], ds: DataSource,
                index) -> Optional[IndexAccess]:
    """Try to serve `conditions` (CNF over ds.schema) with `index`."""
    tbl = ds.table
    name_of = {i: c.name.lower() for i, c in enumerate(ds.schema.cols)}
    # collect eq and range conds per column name
    eqs: dict[str, object] = {}
    ranges: dict[str, list] = {}
    consumed: dict[int, str] = {}       # condition position -> col name
    for pos, cond in enumerate(conditions):
        p = _cmp_parts(cond)
        if p is None:
            continue
        op, ci, cst = p
        col = name_of[ci]
        col_type = tbl.col_types[tbl.col_names.index(ds.schema.cols[ci].name)]
        v = _const_for(col_type, cst)
        if v is None:
            continue
        if op == "eq" and col not in eqs:
            eqs[col] = v
            consumed[pos] = col
        elif op != "eq":
            ranges.setdefault(col, []).append((op, v, pos))

    prefix = []
    for col in index.columns:
        cl = col.lower()
        if cl in eqs:
            prefix.append(eqs[cl])
        else:
            break
    if not prefix:
        return None
    acc = IndexAccess(index, prefix)
    used_cols = {c.lower() for c in index.columns[:len(prefix)]}
    acc.is_point = index.unique and len(prefix) == len(index.columns)

    # range on the next index column
    if len(prefix) < len(index.columns):
        nxt = index.columns[len(prefix)].lower()
        for op, v, pos in ranges.get(nxt, []):
            if op in ("gt", "ge"):
                if acc.low is None or v > acc.low:
                    acc.low, acc.low_incl = v, op == "ge"
            else:
                if acc.high is None or v < acc.high:
                    acc.high, acc.high_incl = v, op == "le"
        if acc.low is not None or acc.high is not None:
            acc.range_col = nxt

    # residual = everything except consumed eq conds on used columns
    # (range conds stay as residuals — cheap to re-check, keeps bounds
    # logic simple and NULL-safe)
    acc.residual = [c for pos, c in enumerate(conditions)
                    if not (pos in consumed and consumed[pos] in used_cols)]
    return acc


def choose_index(conditions: list[Expr], ds: DataSource,
                 stats=None) -> Optional[IndexAccess]:
    """Pick the best access path.  Without stats: point gets beat longer
    prefixes beat shorter ones (the reference's heuristic).  With stats
    (post-ANALYZE): cost-based — index double-read (seek + per-row random
    fetch, plan_cost_ver2's scan+net factors) vs full vectorized device
    scan; skip the index when the predicate isn't selective enough."""
    tbl = ds.table
    if getattr(tbl, "kv", None) is None:
        return None
    use = getattr(ds, "hint_use", None)
    ignore = getattr(ds, "hint_ignore", None) or []
    best: Optional[IndexAccess] = None
    for ix in getattr(tbl, "indexes", []):
        if ix.state != "public":
            continue
        if ix.name.lower() in ignore:
            continue
        if use is not None and ix.name.lower() not in use:
            continue
        acc = match_index(conditions, ds, ix)
        if acc is None:
            continue
        if best is None or _score(acc) > _score(best):
            best = acc
    if best is None or best.is_point or stats is None:
        return best
    if use is not None:
        return best       # USE_INDEX forces the path past the cost model
    cost_idx = _index_cost(best, ds, stats)
    cost_scan = tbl.num_rows * SCAN_ROW_COST
    return best if cost_idx < cost_scan else None


# cost factors (plan_cost_ver2 analog, calibrated for the TPU split:
# device scans stream whole columns through XLA, index lookups do
# host-side KV seeks + row decodes)
SCAN_ROW_COST = 1.0
IDX_LOOKUP_ROW_COST = 20.0
IDX_SEEK_COST = 30.0


def _index_cost(acc: IndexAccess, ds: DataSource, stats) -> float:
    from .cardinality import cond_selectivity

    tbl = ds.table
    n = tbl.num_rows
    sel = 1.0
    name_to_schema = {c.name.lower(): i for i, c in enumerate(ds.schema.cols)}
    # selectivity of the consumed prefix eq conds + range cond, from stats
    for col, v in zip(acc.index.columns, acc.eq_values):
        ci = name_to_schema.get(col.lower())
        if ci is None:
            continue
        ref = ds.schema.ref(ci)
        sel *= cond_selectivity(stats, Func(ref.dtype, "eq",
                                            (ref, Const(ref.dtype, v))), ds)
    if acc.range_col is not None:
        ci = name_to_schema.get(acc.range_col)
        if ci is not None:
            ref = ds.schema.ref(ci)
            if acc.low is not None:
                sel *= cond_selectivity(
                    stats, Func(ref.dtype, "ge" if acc.low_incl else "gt",
                                (ref, Const(ref.dtype, acc.low))), ds)
            if acc.high is not None:
                sel *= cond_selectivity(
                    stats, Func(ref.dtype, "le" if acc.high_incl else "lt",
                                (ref, Const(ref.dtype, acc.high))), ds)
    est_rows = max(n * sel, 1.0)
    return IDX_SEEK_COST + est_rows * IDX_LOOKUP_ROW_COST


def _score(acc: IndexAccess) -> tuple:
    return (acc.is_point, len(acc.eq_values), acc.range_col is not None)


# ------------------------------------------------------------------ #
# plan rewrite
# ------------------------------------------------------------------ #

@dataclass
class LogicalIndexMerge(LogicalPlan):
    """Union of several index accesses serving one OR predicate
    (index_merge_reader.go)."""
    ds: DataSource = None
    accesses: list = None
    conditions: list = None          # the whole disjunction (re-filter)
    schema: Schema = None

    def __post_init__(self):
        self.children = []
        if self.schema is None:
            self.schema = self.ds.schema


@dataclass
class LogicalIndexScan(LogicalPlan):
    """Index-served scan of a KV table (IndexLookUp / PointGet analog)."""
    ds: DataSource
    access: IndexAccess
    schema: Schema = None

    def __post_init__(self):
        self.children = []
        if self.schema is None:
            self.schema = self.ds.schema


def apply_index_paths(p: LogicalPlan, stats_handle=None) -> LogicalPlan:
    """Replace Selection-over-DataSource with an index access when the
    predicates pin an index prefix (run after optimize_plan so predicate
    pushdown has collected conditions at the scan).  stats_handle, when
    given, enables the cost-based index-vs-scan decision."""
    for i, c in enumerate(p.children):
        nc = apply_index_paths(c, stats_handle)
        p.children[i] = nc
        if getattr(p, "child", None) is c:
            p.child = nc
        if getattr(p, "left", None) is c:
            p.left = nc
        if getattr(p, "right", None) is c:
            p.right = nc
    if isinstance(p, LogicalSelection) and isinstance(p.child, DataSource):
        if getattr(p.child, "as_of_ts", None) is not None:
            return p     # stale reads go through the historical snapshot
        stats = (stats_handle.get(p.child.table)
                 if stats_handle is not None else None)
        acc = choose_index(p.conditions, p.child, stats)
        if acc is not None:
            scan = LogicalIndexScan(p.child, acc)
            if acc.residual:
                return LogicalSelection(scan, acc.residual)
            return scan
        im = _try_index_merge(p, stats)
        if im is not None:
            return im
    return p


def _flatten_or(e: Expr) -> list:
    if isinstance(e, Func) and e.op == "or":
        out = []
        for a in e.args:
            out.extend(_flatten_or(a))
        return out
    return [e]


def _split_and(e: Expr) -> list:
    if isinstance(e, Func) and e.op == "and":
        out = []
        for a in e.args:
            out.extend(_split_and(a))
        return out
    return [e]


def _try_index_merge(p: LogicalSelection, stats):
    """UNION-type IndexMerge (executor/index_merge_reader.go): a single
    top-level OR whose every disjunct pins SOME index becomes a union of
    index accesses; rows fetched by the handle union are re-filtered by
    the whole disjunction, so over-approximating accesses stay sound."""
    if len(p.conditions) != 1:
        return None
    disjuncts = _flatten_or(p.conditions[0])
    if len(disjuncts) < 2:
        return None
    accesses = []
    for d in disjuncts:
        acc = choose_index(_split_and(d), p.child, stats)
        if acc is None:
            return None          # one unindexed disjunct = full scan wins
        accesses.append(acc)
    return LogicalIndexMerge(p.child, accesses, list(p.conditions))


__all__ = ["IndexAccess", "match_index", "choose_index", "LogicalIndexScan",
           "LogicalIndexMerge",
           "apply_index_paths"]
