"""Row TTL: scan-and-delete of expired rows, driven by the timer
framework.

Reference analog: pkg/ttl (18.2k LoC — ttlworker scan/delete task
pipeline over TTL tables, scheduled by pkg/timer).  A table declares
`TTL = col + INTERVAL n unit` at CREATE TABLE; the sweep deletes rows
whose TTL column is older than now - interval, in bounded batches so a
huge expired backlog cannot monopolize the store.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..types import dtypes as dt

BATCH_ROWS = 4096     # delete batch bound (ttlworker scan task size)


def ttl_cutoff_value(col_type, interval_sec: int,
                     now: Optional[float] = None):
    """Encoded threshold for the TTL column: rows with value < cutoff are
    expired."""
    now = time.time() if now is None else now
    cutoff = now - interval_sec
    if col_type.kind == dt.TypeKind.DATE:
        return int(cutoff // 86400)                   # days since epoch
    if col_type.kind == dt.TypeKind.DATETIME:
        return int(cutoff * 1_000_000)                # micros since epoch
    raise ValueError("TTL column must be DATE or DATETIME")


def sweep_table(tbl, now: Optional[float] = None) -> int:
    """Delete expired rows of one TTL table; returns rows deleted."""
    if not tbl.ttl_col or not tbl.ttl_enable:
        return 0
    ci = tbl.col_names.index(tbl.ttl_col)
    cutoff = ttl_cutoff_value(tbl.col_types[ci], tbl.ttl_interval_sec, now)
    deleted = 0
    while True:
        snap = tbl.snapshot()
        col = snap.columns[ci]
        expired = col.validity & (col.data < cutoff)
        idx = np.nonzero(expired)[0]
        if len(idx) == 0:
            return deleted
        batch = idx[:BATCH_ROWS]
        keep = np.ones(snap.num_rows, bool)
        keep[batch] = False
        deleted += tbl.delete_where(keep)
        if len(idx) <= BATCH_ROWS:
            return deleted


def run_ttl_sweep(domain, now: Optional[float] = None) -> dict:
    """One TTL job run over every TTL table (ttlworker JobManager run)."""
    out = {}
    for db, tables in list(domain.catalog.databases.items()):
        for name, tbl in list(tables.items()):
            if getattr(tbl, "ttl_col", None) and tbl.kv is not None:
                n = sweep_table(tbl, now)
                if n:
                    out[f"{db}.{name}"] = n
    return out


__all__ = ["sweep_table", "run_ttl_sweep", "ttl_cutoff_value"]
