"""Device admission scheduler: continuous micro-batching of concurrent
cop tasks (tikv unified-read-pool + inference continuous-batching
analog).  See scheduler.py for the design."""

from .scheduler import (DEFAULT_MAX_COALESCE, DEFAULT_QUEUE_DEPTH,
                        DeviceScheduler, breaker_snapshot_all,
                        scheduler_for)
from .task import (SCHED_GROUP, CopTask, ServerBusyError,
                   TaskCancelledError, current_group, mesh_fingerprint)

__all__ = ["DeviceScheduler", "scheduler_for", "breaker_snapshot_all",
           "CopTask", "ServerBusyError", "TaskCancelledError",
           "SCHED_GROUP", "current_group", "DEFAULT_QUEUE_DEPTH",
           "DEFAULT_MAX_COALESCE", "mesh_fingerprint"]
