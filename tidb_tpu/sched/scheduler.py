"""Per-mesh device admission scheduler: continuous micro-batching of
concurrent cop tasks.

Reference analog: tikv's unified read pool (resource-group-aware
priority queue in front of the storage threads) combined with the
continuous-batching admission loop of inference servers.  One scheduler
owns all launches onto one jax mesh:

- CopClient dispatch no longer calls the device directly; it submits
  `CopTask`s to a BOUNDED admission queue tagged by (program digest,
  capacity shape, resource group).  Overflow raises the MySQL-compatible
  "server is busy" error instead of growing memory without bound.
- A drain loop serves queues in weighted-fair order (stride scheduling
  over per-resource-group virtual time, weights from the group's
  PRIORITY — utils/resourcegroup.py).
- Compatible tasks COALESCE into one launch: identical inputs (same
  snapshot epoch residents) share a single program execution; distinct
  inputs of the same dense-agg program stack along a batch-slot dim and
  run as ONE vmapped program (parallel/spmd.get_batched_program), with
  partial-agg states split back per task.
- Queue-wait / launch / coalesce stats feed utils/metrics (scraped at
  /metrics), the /sched status route, per-statement execdetails
  (`schedWait` in EXPLAIN ANALYZE), and per-group RU accounting.

The drain thread starts lazily on first submit and exits after an idle
period, so embedders that never touch the device pay nothing.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from .task import CopTask, ServerBusyError

DEFAULT_QUEUE_DEPTH = 256
DEFAULT_MAX_COALESCE = 8
IDLE_EXIT_S = 5.0


def _verify_enabled() -> bool:
    """Admission-time plan-contract verification (analysis/contracts):
    on by default, TIDB_TPU_VERIFY_PLAN=0 disables (bisecting aid)."""
    return os.environ.get("TIDB_TPU_VERIFY_PLAN", "") != "0"


class _GroupQ:
    """One resource group's FIFO + stride-scheduler state."""

    __slots__ = ("name", "weight", "vtime", "seq", "queue",
                 "tasks", "wait_ns", "rus")

    def __init__(self, name: str, weight: float, seq: int,
                 vtime: float = 0.0):
        self.name = name
        self.weight = max(weight, 1e-6)
        self.vtime = vtime        # accumulated service / weight
        self.seq = seq            # tie-break: registration order
        self.queue: deque = deque()
        self.tasks = 0            # served (lifetime)
        self.wait_ns = 0
        self.rus = 0.0


class DeviceScheduler:
    """Admission queue + weighted-fair drain loop for one device mesh."""

    def __init__(self, max_depth: int = DEFAULT_QUEUE_DEPTH,
                 max_coalesce: int = DEFAULT_MAX_COALESCE):
        self.max_depth = max_depth
        self.max_coalesce = max_coalesce
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._groups: dict[str, _GroupQ] = {}
        self._depth = 0
        self._gvt = 0.0           # global virtual time (newcomer floor)
        self._thread: Optional[threading.Thread] = None
        self._paused = False
        # lifetime counters (read by /sched, tests, metrics mirror them)
        self.launches = 0
        self.coalesced_launches = 0       # launches serving >= 2 tasks
        self.coalesced_tasks = 0          # tasks that rode a shared launch
        self.batched_launches = 0         # stacked-slot vmap launches
        self.busy_rejects = 0
        self.tasks_done = 0
        from ..utils.metrics import global_registry
        reg = global_registry()
        self._m_depth = reg.gauge("tidb_tpu_sched_queue_depth",
                                  "device admission queue depth")
        self._m_tasks = reg.counter("tidb_tpu_sched_tasks_total",
                                    "cop tasks admitted", labels=("group",))
        self._m_busy = reg.counter("tidb_tpu_sched_busy_total",
                                   "admission rejections (queue full)")
        self._m_launch = reg.counter("tidb_tpu_sched_launch_total",
                                     "device launches", labels=("mode",))
        self._m_coal = reg.counter("tidb_tpu_sched_coalesced_tasks_total",
                                   "tasks served by a shared launch")
        self._m_wait = reg.histogram("tidb_tpu_sched_wait_seconds",
                                     "admission queue wait")
        self._m_ru = reg.counter("tidb_tpu_sched_ru_total",
                                 "request units launched", labels=("group",))

    # ------------------------------------------------------------- #
    # admission
    # ------------------------------------------------------------- #

    def configure(self, max_depth: Optional[int] = None,
                  max_coalesce: Optional[int] = None) -> None:
        """Apply sysvar knobs; negative/None = keep current."""
        if max_depth is not None and max_depth > 0:
            self.max_depth = max_depth
        if max_coalesce is not None and max_coalesce > 0:
            self.max_coalesce = max_coalesce

    def submit(self, task: CopTask) -> CopTask:
        """Enqueue; raises ServerBusyError when the bounded queue is
        full (backpressure instead of unbounded buffering).  Structured
        tasks are contract-verified on admission — a malformed task
        (capacity-shape drift, stale mesh key, invalid DAG) is rejected
        with PlanContractError HERE, in the submitting thread, before
        the drain loop would trace/compile anything."""
        if task.key is not None and _verify_enabled():
            from ..analysis.contracts import verify_task
            verify_task(task)
        with self._cv:
            if self._depth >= self.max_depth:
                self.busy_rejects += 1
                self._m_busy.inc()
                raise ServerBusyError(self.max_depth)
            g = self._groups.get(task.group)
            if g is None:
                g = self._groups[task.group] = _GroupQ(
                    task.group, task.weight, len(self._groups),
                    vtime=self._gvt)
            else:
                g.weight = max(task.weight, 1e-6)
                if not g.queue:
                    # re-activating group: forfeit banked idle time so it
                    # cannot starve others (stride newcomer rule)
                    g.vtime = max(g.vtime, self._gvt)
            g.queue.append(task)
            self._depth += 1
            self._m_depth.set(self._depth)
            self._m_tasks.inc(group=task.group)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="sched-drain", daemon=True)
                self._thread.start()
            self._cv.notify_all()
        return task

    def pause(self) -> None:
        """Hold the drain loop (tests / maintenance); submits still queue."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    # ------------------------------------------------------------- #
    # drain loop
    # ------------------------------------------------------------- #

    def _pick(self) -> Optional[_GroupQ]:
        best = None
        for g in self._groups.values():
            if not g.queue:
                continue
            if best is None or (g.vtime, g.seq) < (best.vtime, best.seq):
                best = g
        return best

    def _take_batch(self) -> list:
        """Pop the fair-ordered head task plus every compatible queued
        task (same program digest + capacity shape + equal DAG), across
        ALL groups — coalescing is cross-session by design.  Each rider
        charges its own group's virtual time."""
        g = self._pick()
        if g is None:
            return []
        lead = g.queue.popleft()
        self._depth -= 1
        g.vtime += 1.0 / g.weight
        self._gvt = g.vtime
        g.tasks += 1
        if lead.cancelled:
            self._m_depth.set(self._depth)
            lead.fail(RuntimeError("cancelled"))
            return [None]          # sentinel: retry pick
        batch = [lead]
        if lead.key is not None:
            for og in self._groups.values():
                if len(batch) >= self.max_coalesce:
                    break
                kept: deque = deque()
                while og.queue:
                    t = og.queue.popleft()
                    if (len(batch) < self.max_coalesce
                            and not t.cancelled and t.key == lead.key
                            and t.mesh is lead.mesh
                            and (t.dag is lead.dag or t.dag == lead.dag)):
                        batch.append(t)
                        self._depth -= 1
                        og.vtime += 1.0 / og.weight
                        og.tasks += 1
                    else:
                        kept.append(t)
                og.queue = kept
        self._m_depth.set(self._depth)
        return batch

    def _loop(self) -> None:
        idle_since = time.monotonic()
        while True:
            with self._cv:
                while self._paused or self._depth == 0:
                    if self._depth == 0 and not self._paused and \
                            time.monotonic() - idle_since > IDLE_EXIT_S:
                        self._thread = None
                        return
                    self._cv.wait(timeout=0.5)
                    if not self._paused and self._depth == 0:
                        continue
                batch = self._take_batch()
            idle_since = time.monotonic()
            if not batch or batch == [None]:
                continue
            now = time.perf_counter_ns()
            for t in batch:
                t.start_ns = now
                t.wait_ns = now - t.submit_ns
            try:
                self._serve(batch)
            except BaseException as e:  # noqa: BLE001 future-style contract
                for t in batch:
                    t.fail(e)
            self._account(batch)

    # ------------------------------------------------------------- #
    # launch
    # ------------------------------------------------------------- #

    def _serve(self, batch: list) -> None:
        lead = batch[0]
        if lead.fn is not None:                     # opaque launch
            try:
                lead.finish(lead.fn())
            except BaseException as e:  # noqa: BLE001
                lead.fail(e)
            self.launches += 1
            self._m_launch.inc(mode="single")
            return
        from ..parallel.spmd import get_batched_program, get_sharded_program
        prog = get_sharded_program(lead.dag, lead.mesh, lead.row_capacity)
        # group riders by input identity: same-token tasks share ONE
        # program execution (in-flight dedup)
        slots: list[list] = []
        by_token: dict = {}
        for t in batch:
            s = by_token.get(t.input_token)
            if s is None:
                s = by_token[t.input_token] = []
                slots.append(s)
            s.append(t)
        mode = "single"
        if len(slots) > 1 and prog.kind == "agg" and not prog.host_merge \
                and not prog.has_extras \
                and all(s[0].aux == () for s in slots):
            # distinct inputs, one dense-agg program: stack along the
            # batch-slot dim, ONE vmapped launch, split states per task
            try:
                bprog = get_batched_program(lead.dag, lead.mesh, len(slots))
                outs = bprog([s[0].cols for s in slots],
                             [s[0].counts for s in slots])
                for s, out in zip(slots, outs):
                    for t in s:
                        t.finish((prog, out))
                self.launches += 1
                self.batched_launches += 1
                self._m_launch.inc(mode="batched")
                self._note_coalesce(batch)
                return
            except Exception:   # planlint: ok - vmap capability probe;
                pass        # op not vmappable on this backend: launch
                            # apart below (same results, no batching win)
        for s in slots:
            out = prog(s[0].cols, s[0].counts, s[0].aux)
            for t in s:
                t.finish((prog, out))
            self.launches += 1
            self._m_launch.inc(
                mode="coalesced" if len(s) > 1 else mode)
        self._note_coalesce(batch)

    def _note_coalesce(self, batch: list) -> None:
        if len(batch) > 1:
            self.coalesced_launches += 1
            self.coalesced_tasks += len(batch)
            self._m_coal.inc(len(batch))
            for t in batch:
                t.coalesced = len(batch)

    def _account(self, batch: list) -> None:
        with self._mu:
            for t in batch:
                self.tasks_done += 1
                g = self._groups.get(t.group)
                rus = t.est_rows / 100.0 + 1.0
                if g is not None:
                    g.wait_ns += t.wait_ns
                    g.rus += rus
                self._m_wait.observe(t.wait_ns / 1e9)
                self._m_ru.inc(rus, group=t.group)

    # ------------------------------------------------------------- #
    # introspection
    # ------------------------------------------------------------- #

    @property
    def depth(self) -> int:
        return self._depth

    def stats(self) -> dict:
        with self._mu:
            return {
                "queue_depth": self._depth,
                "max_depth": self.max_depth,
                "max_coalesce": self.max_coalesce,
                "launches": self.launches,
                "coalesced_launches": self.coalesced_launches,
                "coalesced_tasks": self.coalesced_tasks,
                "batched_launches": self.batched_launches,
                "busy_rejects": self.busy_rejects,
                "tasks_done": self.tasks_done,
                "groups": {
                    g.name: {"weight": g.weight, "tasks": g.tasks,
                             "queued": len(g.queue),
                             "wait_ms": round(g.wait_ns / 1e6, 3),
                             "rus": round(g.rus, 2)}
                    for g in self._groups.values()},
            }


# --------------------------------------------------------------------- #
# per-mesh registry: the scheduler is the mesh's single device executor
# --------------------------------------------------------------------- #

_REGISTRY: dict[int, DeviceScheduler] = {}
_REG_MU = threading.Lock()


def scheduler_for(mesh) -> DeviceScheduler:
    """The (process-wide) scheduler owning launches onto `mesh`.  Keyed
    by mesh identity: every Domain sharing a mesh shares its admission
    queue — device capacity is global, so admission must be too."""
    with _REG_MU:
        s = _REGISTRY.get(id(mesh))
        if s is None:
            s = _REGISTRY[id(mesh)] = DeviceScheduler()
        return s


__all__ = ["DeviceScheduler", "scheduler_for", "DEFAULT_QUEUE_DEPTH",
           "DEFAULT_MAX_COALESCE"]
