"""Per-mesh device admission scheduler: continuous micro-batching of
concurrent cop tasks.

Reference analog: tikv's unified read pool (resource-group-aware
priority queue in front of the storage threads) combined with the
continuous-batching admission loop of inference servers.  One scheduler
owns all launches onto one jax mesh:

- CopClient dispatch no longer calls the device directly; it submits
  `CopTask`s to a BOUNDED admission queue tagged by (program digest,
  capacity shape, resource group).  Overflow raises the MySQL-compatible
  "server is busy" error instead of growing memory without bound.
- A drain loop serves queues in weighted-fair order (stride scheduling
  over per-resource-group virtual time, weights from the group's
  PRIORITY — utils/resourcegroup.py).
- Compatible tasks COALESCE into one launch: identical inputs (same
  snapshot epoch residents) share a single program execution; distinct
  inputs of the same program stack along a batch-slot dim and run as ONE
  vmapped program (spmd.get_batched_program for dense aggs,
  spmd.get_batched_rows_program for compacted row outputs), with
  states/rows split back per task.
- Compatible-but-NON-identical tasks FUSE into one program: queued
  tasks sharing a contract-aware fusion key (one snapshot scan, one
  mesh, one capacity signature — analysis.contracts.fusion_signature,
  no tracing) but differing in filters/aggregates run as ONE
  FusedCopProgram computing every member's payload from a single scan
  pass; results demux back to each waiter (cross-query kernel fusion,
  the Flare shared-scan argument).
- An adaptive micro-batch WINDOW holds the drain briefly for
  stragglers: per fusion key, an EWMA of arrival gaps predicts whether
  a matching task is about to arrive; under bursty open-loop load the
  sub-millisecond wait raises coalesce/fusion rates sharply.
- The drain ENFORCES resource-group RU budgets (rc/): every task is
  priced from its static LaunchCost at submit, and a group whose token
  bucket (plus bounded overdraft) cannot cover its head task's RUs is
  SKIPPED — the exhausted group queues while other groups keep
  launching (no head-of-line blocking across groups), riders from an
  exhausted group may not hitch onto another group's launch, debits
  happen pre-launch at batch admission (fused groups pay the shared
  scan once, riders their marginal bytes), and a throttled task that
  overstays the max-queue deadline fails its waiter with the
  MySQL-compatible ResourceExhaustedError (8252).
- Launches are SUPERVISED (faultline): a transient launch failure
  retries through the store Backoffer's DEVICE_FAILED budget instead of
  failing the waiter; a failing fused/batched launch is DEMUXED and its
  members retried solo so one poisoned plan cannot take down innocent
  riders (fusion never widens a failure domain); a per-program-digest
  circuit breaker (CLOSED -> OPEN -> HALF_OPEN probe) makes repeat
  offenders fail fast at submit with LaunchQuarantinedError — which the
  CopClient degrades to the host oracle path where the plan shape
  allows.  The seeded FaultPlan (faults/plan.py) injects deterministic
  transient/poison faults at the build/launch/drain seams so every one
  of these paths is exercisable on a CPU mesh.
- Every task carries its statement's copscope TraceCtx (obs/): the
  drain records REAL spans from its own thread — queue wait (rc debit
  riding as an attr), copforge compile (hit/miss), launch (predicted
  vs measured ms, per-link transfer bytes), fusion assembly with
  per-member attributed share, transient-retry backoff, OOM/bisect/
  quarantine markers — into the statement's lock-protected span tree
  BEFORE the waiting task finishes, so TRACE and the flight recorder
  always see the scheduler-side story.  Untraced tasks skip it all.
- Queue-wait / launch / coalesce / fusion stats feed utils/metrics
  (scraped at /metrics), the /sched status route, per-statement
  execdetails (`schedWait`/`fused`/`ru` in EXPLAIN ANALYZE), priced
  per-group RU accounting, and measured launch wall time attributed
  per member (shared scan split by marginal bytes) and per program
  digest.

The drain thread starts lazily on first submit and exits after an idle
period, so embedders that never touch the device pay nothing.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Optional

from ..faults import plan as _faults
from ..faults.breaker import CircuitBreaker, LaunchQuarantinedError
from ..rc.controller import (DEFAULT_MAX_QUEUE_S, DEFAULT_OVERDRAFT_RU,
                             ResourceExhaustedError)
from ..rc.pricing import split_device_time, task_rus
from .task import CopTask, ServerBusyError, TaskCancelledError

DEFAULT_QUEUE_DEPTH = 256
DEFAULT_MAX_COALESCE = 8
IDLE_EXIT_S = 5.0
# adaptive micro-batch window: never hold a launch longer than this, and
# only hold at all when the key's EWMA arrival gap predicts a straggler
# inside the cap (2 * gap <= cap)
WINDOW_CAP_US = 1000
# arrival gaps beyond this clamp before feeding the EWMA so one long lull
# cannot poison the estimate forever (it recovers in a few arrivals)
WINDOW_GAP_CLAMP_NS = 50_000_000
WAIT_SAMPLES = 2048              # ring of recent task waits (p50/p99)
# window FEEDBACK (ROADMAP item): per-key EWMA of whether a hold actually
# yielded riders.  A key whose holds rarely pay decays its window toward
# zero (scale = min(1, hit/0.5)); below the floor the hold is skipped
# outright until a hit recovers the estimate.
WINDOW_HIT_INIT = 0.5            # optimistic prior: full window at start
WINDOW_HIT_ALPHA = 0.25          # EWMA step per observed hold outcome
WINDOW_HIT_FLOOR = 0.05          # scale cutoff: ~10 straight misses
# while every queued group is RU-throttled the drain sleeps this long
# between cover re-checks (bucket refill is time-driven; submits still
# notify the condition immediately)
RC_RETRY_S = 0.01
# per-program-digest device-time attribution map: bounded + LRU-evicted
# (analysis/calibrate.BoundedLRU — the same eviction policy the
# calibration correction store uses; the map previously grew per digest
# for the life of the process)
RC_DIGEST_CAP = 64
# copmeter deadline-aware early shedding: a submit whose CORRECTED-cost
# backlog (sum of the queue's measured expected service times) already
# exceeds this is rejected 9003 at the queue head — and an rc-limited
# waiter whose backlog exceeds its own max-queue deadline is rejected
# 8252 — instead of timing out deep in queue.  Only measured digests
# contribute to the backlog, so an uncalibrated process never sheds.
SHED_MAX_BACKLOG_S = 30.0
# supervised-launch transient retry: total Backoffer sleep budget the
# drain will spend re-launching one batch before classifying the
# failure as persistent (DEVICE_FAILED curve, store/backoff.py)
DEFAULT_LAUNCH_RETRY_MS = 2000.0
# seeded jitter for the drain's Backoffer when no FaultPlan is armed:
# retry histories stay reproducible either way
RETRY_JITTER_SEED = 0x5EED


def _verify_enabled() -> bool:
    """Admission-time plan-contract verification (analysis/contracts):
    on by default, TIDB_TPU_VERIFY_PLAN=0 disables (bisecting aid)."""
    return os.environ.get("TIDB_TPU_VERIFY_PLAN", "") != "0"


class _GroupQ:
    """One resource group's FIFO + stride-scheduler state."""

    __slots__ = ("name", "weight", "vtime", "seq", "queue",
                 "tasks", "wait_ns", "rus", "throttled", "device_ns")

    def __init__(self, name: str, weight: float, seq: int,
                 vtime: float = 0.0):
        self.name = name
        self.weight = max(weight, 1e-6)
        self.vtime = vtime        # accumulated service / weight
        self.seq = seq            # tie-break: registration order
        self.queue: deque = deque()
        self.tasks = 0            # served (lifetime)
        self.wait_ns = 0
        self.rus = 0.0            # priced RUs launched (rc/pricing)
        self.throttled = 0        # drain passes that skipped this group
        self.device_ns = 0        # attributed launch wall time


class DeviceScheduler:
    """Admission queue + weighted-fair drain loop for one device mesh."""

    def __init__(self, max_depth: int = DEFAULT_QUEUE_DEPTH,
                 max_coalesce: int = DEFAULT_MAX_COALESCE):
        self.max_depth = max_depth
        self.max_coalesce = max_coalesce
        self.fusion_enable = True         # tidb_tpu_sched_fusion
        self.window_us = -1               # tidb_tpu_sched_window_us
                                          # (-1 adaptive, 0 off, >0 fixed)
        # per-mesh HBM admission budget (tidb_tpu_sched_hbm_budget):
        # -1 = derive from device memory stats on first structured
        # submit (CPU fallback constant), 0 = unlimited, >0 = bytes
        self.hbm_budget = -1
        self._auto_budget: Optional[int] = None
        # resource control (rc/): RU-bucket enforcement at the drain
        # (tidb_tpu_rc_enable / tidb_tpu_rc_overdraft_ru sysvars); the
        # max-queue deadline bounds how long a throttled waiter queues
        self.rc_enable = True
        self.rc_overdraft_ru = DEFAULT_OVERDRAFT_RU
        self.rc_max_queue_s = DEFAULT_MAX_QUEUE_S
        # copmeter closed-loop calibration (analysis/calibrate;
        # tidb_tpu_cost_calibration sysvar): corrected LaunchCost feeds
        # RU pricing, budget admission, fusion caps, the micro-batch
        # window, and deadline-aware shedding.  Off = the static model
        # untouched, no feedback recorded.
        self.calibration_enable = True
        # copgauge (obs/hbm, tidb_tpu_hbm_ledger sysvar): live HBM
        # ledger accounting at launch begin/finish, measured launch
        # watermarks feeding mem_factor calibration, and per-digest
        # roofline attribution.  Off = the static model byte-identical
        # to the pre-copgauge behavior (mem_factor moves only on OOM).
        self.hbm_enable = True
        self._ledger_obj = None
        # coplace (pd/, tidb_tpu_pd sysvar): cross-process coordination
        # plane.  Off (default) = every path byte-identical to the
        # pre-pd behavior; on = breaker quarantines broadcast to peers
        # and /sched grows a "pd" section.  The coordinator itself is
        # per-Domain (session plumbs it); this flag only gates the
        # scheduler-side hooks.
        self.pd_enable = False
        # launch supervision (faultline): per-digest circuit breaker
        # consulted at submit, transient-retry budget spent at the
        # drain; _retry_sleep is the Backoffer sleep seam (tests)
        self.breaker = CircuitBreaker()
        self.launch_retry_ms = DEFAULT_LAUNCH_RETRY_MS
        self._retry_sleep = time.sleep
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._groups: dict[str, _GroupQ] = {}
        self._depth = 0
        self._gvt = 0.0           # global virtual time (newcomer floor)
        self._thread: Optional[threading.Thread] = None
        self._paused = False
        # micro-batch window bookkeeping: fusion key -> last arrival ns /
        # EWMA arrival gap ns / EWMA hold hit-rate (tiny dicts, cleared
        # when they grow)
        self._fk_last: dict = {}
        self._fk_gap: dict = {}
        self._fk_hit: dict = {}
        # recent task waits, for p50/p99 on /sched and in bench
        self._wait_ring: deque = deque(maxlen=WAIT_SAMPLES)
        # lifetime counters (read by /sched, tests, metrics mirror them)
        self.launches = 0
        self.coalesced_launches = 0       # launches serving >= 2 tasks
        self.coalesced_tasks = 0          # tasks that rode a shared launch
        self.batched_launches = 0         # stacked-slot vmap launches
        self.batched_rows_launches = 0    # rows-kind stacked launches
        self.fused_launches = 0           # cross-query fused launches
        self.fused_tasks = 0              # tasks served by a fused launch
        self.window_waits = 0             # drains that held for stragglers
        self.window_hits = 0              # holds that actually gained riders
        self.busy_rejects = 0
        # HBM-budget admission accounting (analysis/copcost LaunchCost)
        self.budget_admitted = 0          # structured tasks costed + admitted
        self.budget_rejects = 0           # solo programs over budget (CostError)
        self.budget_deferrals = 0         # riders left queued by footprint cap
        self.last_launch_bytes = 0        # footprint of the last served batch
        # per-link transfer attribution (shardflow, parallel/topology):
        # statically-priced collective bytes of served tasks, split by
        # link class under the declared host view — the ROADMAP
        # multi-host success metric's static half
        self.transfer_ici_bytes = 0
        self.transfer_dci_bytes = 0
        # buffer-donation accounting (analysis/lifetime DonationPlan)
        self.donated_launches = 0         # launches with donated inputs
        self.donated_tasks = 0            # tasks that requested donation
        self.donated_bytes = 0            # priced input bytes aliased out
        # copforge compile-cache accounting (compilecache/): program
        # resolve/compile time the drain paid, split out of schedWait
        self.compile_ns_total = 0         # summed per-launch resolve time
        self.warm_predicted = 0           # background fused-variant warms
        self.warm_failures = 0            # predictions that failed to
                                          # compile (never surfaced)
        self._warm_alive = 0              # in-flight prediction threads
        self._fusion_seen: dict = {}      # fusion key -> digest -> (dag,
                                          # sds-args) for prediction
        self._fusion_warmed: set = set()  # member-digest combos warmed
        # supervised-launch accounting (faultline)
        self.retried_launches = 0         # serve attempts re-run after a
                                          # transient launch failure
        self.retried_tasks = 0            # member tasks those retries span
        self.bisected_launches = 0        # failed group launches demuxed
                                          # for blast-radius isolation
        self.quarantined = 0              # submits failed fast by an OPEN
                                          # breaker (LaunchQuarantinedError)
        self.value_drifts = 0             # admitted tasks whose observed
                                          # column watermarks escaped the
                                          # plan's declared value interval
                                          # (valueflow stats drift)
        # rc enforcement accounting (rc/controller)
        self.rc_throttled = 0             # drain passes that skipped a group
        self.rc_exhausted = 0             # waiters failed at the deadline
        self.rc_debited_ru = 0.0          # priced RUs debited pre-launch
        # program digest -> device ns, bounded + LRU (shared eviction
        # policy with the calibration correction store)
        from ..analysis.calibrate import BoundedLRU
        self._digest_ns = BoundedLRU(RC_DIGEST_CAP)
        # copmeter accounting (analysis/calibrate)
        self.oom_faults = 0               # OOM-classified launch failures
        self.oom_demuxed = 0              # OOM group launches retried at
                                          # reduced fusion width
        self.shed_rejects = 0             # submits shed at the queue head
                                          # (corrected-cost backlog over
                                          # the waiter's deadline)
        self._backlog_ns = 0              # expected service ns queued
        self.tasks_done = 0
        from ..utils.metrics import global_registry
        reg = global_registry()
        self._m_depth = reg.gauge("tidb_tpu_sched_queue_depth",
                                  "device admission queue depth")
        self._m_tasks = reg.counter("tidb_tpu_sched_tasks_total",
                                    "cop tasks admitted", labels=("group",))
        self._m_busy = reg.counter("tidb_tpu_sched_busy_total",
                                   "admission rejections (queue full)")
        self._m_launch = reg.counter("tidb_tpu_sched_launch_total",
                                     "device launches", labels=("mode",))
        self._m_coal = reg.counter("tidb_tpu_sched_coalesced_tasks_total",
                                   "tasks served by a shared launch")
        self._m_fused = reg.counter("tidb_tpu_sched_fused_tasks_total",
                                    "tasks served by a cross-query "
                                    "fused launch")
        self._m_wait = reg.histogram("tidb_tpu_sched_wait_seconds",
                                     "admission queue wait")
        self._m_ru = reg.counter("tidb_tpu_sched_ru_total",
                                 "request units launched", labels=("group",))
        self._m_budget = reg.gauge("tidb_tpu_sched_hbm_budget_bytes",
                                   "per-mesh HBM admission budget")
        self._m_launch_bytes = reg.gauge(
            "tidb_tpu_sched_launch_bytes",
            "estimated device bytes of the last served launch")
        self._m_badmit = reg.counter(
            "tidb_tpu_sched_budget_admitted_total",
            "structured tasks admitted under the HBM budget")
        self._m_brej = reg.counter(
            "tidb_tpu_sched_budget_rejects_total",
            "tasks rejected pre-trace: footprint over the HBM budget")
        self._m_bdefer = reg.counter(
            "tidb_tpu_sched_budget_deferrals_total",
            "riders deferred from a launch by the summed-footprint cap")
        self._m_ici = reg.counter(
            "tidb_tpu_sched_transfer_ici_bytes_total",
            "statically-priced same-host inter-chip collective bytes "
            "of served tasks (shardflow link attribution)")
        self._m_dci = reg.counter(
            "tidb_tpu_sched_transfer_dci_bytes_total",
            "statically-priced cross-host collective bytes of served "
            "tasks under the declared host view")
        self._m_donated = reg.counter(
            "tidb_tpu_sched_donated_bytes_total",
            "input bytes aliased into outputs by buffer donation")
        self._m_retried = reg.counter(
            "tidb_tpu_sched_retried_total",
            "tasks re-launched after a transient device failure")
        self._m_quar = reg.counter(
            "tidb_tpu_sched_quarantined_total",
            "submits failed fast by an OPEN program circuit breaker")
        self._m_bisect = reg.counter(
            "tidb_tpu_sched_bisected_total",
            "failed group launches demuxed for blast-radius isolation")
        # resource control plane (rc/): admission-side RU enforcement
        self._m_rc_throttle = reg.counter(
            "tidb_tpu_rc_throttled_total",
            "drain passes that skipped an RU-exhausted group",
            labels=("group",))
        self._m_rc_exhaust = reg.counter(
            "tidb_tpu_rc_exhausted_total",
            "waiters failed at the rc max-queue deadline",
            labels=("group",))
        self._m_rc_debit = reg.counter(
            "tidb_tpu_rc_ru_debited_total",
            "priced RUs debited pre-launch", labels=("group",))
        self._m_rc_overdraft = reg.gauge(
            "tidb_tpu_rc_overdraft_ru",
            "bounded RU overdraft the drain tolerates per group")
        self._m_rc_overdraft.set(self.rc_overdraft_ru)
        # copmeter (analysis/calibrate): OOM recovery + early shedding
        self._m_oom = reg.counter(
            "tidb_tpu_sched_oom_total",
            "OOM-classified launch failures recovered without charging "
            "the circuit breaker")
        self._m_shed = reg.counter(
            "tidb_tpu_sched_shed_total",
            "submits shed at the queue head: corrected-cost backlog "
            "already exceeded the waiter's deadline")
        # copscope (obs/): millisecond latency histograms — the
        # prometheus-scrapeable successors of the ad-hoc p50/p99 wait
        # ring (which /sched keeps for back-compat); bench pulls its
        # percentiles from these
        from ..utils.metrics import Histogram
        ms = Histogram.MS_BUCKETS
        self._m_wait_ms = reg.histogram(
            "tidb_tpu_sched_wait_ms",
            "admission queue wait per task (ms)", buckets=ms)
        self._m_launch_ms = reg.histogram(
            "tidb_tpu_sched_launch_ms",
            "device launch wall time per launch (ms)", buckets=ms)
        self._m_compile_ms = reg.histogram(
            "tidb_tpu_sched_compile_ms",
            "program resolve/compile time per launch (ms)", buckets=ms)
        self._m_agg_ms = reg.histogram(
            "tidb_tpu_agg_launch_ms",
            "agg launch wall time by group strategy (ms)", buckets=ms,
            labels=("strategy",))
        # copgauge (obs/hbm): the admission budget mirrored into the
        # tidb_tpu_hbm_* gauge family next to the ledger's
        # resident/watermark gauges
        self._m_hbm_budget = reg.gauge(
            "tidb_tpu_hbm_budget_bytes",
            "per-mesh HBM admission budget (copgauge gauge family "
            "twin of tidb_tpu_sched_hbm_budget_bytes)")

    # ------------------------------------------------------------- #
    # admission
    # ------------------------------------------------------------- #

    def configure(self, max_depth: Optional[int] = None,
                  max_coalesce: Optional[int] = None,
                  fusion: Optional[bool] = None,
                  window_us: Optional[int] = None,
                  hbm_budget: Optional[int] = None,
                  rc_enable: Optional[bool] = None,
                  rc_overdraft: Optional[float] = None,
                  calibration: Optional[bool] = None,
                  hbm_ledger: Optional[bool] = None,
                  pd_enable: Optional[bool] = None) -> None:
        """Apply sysvar knobs; negative/None = keep current (window_us
        and hbm_budget are the exceptions: -1 means adaptive/auto,
        0 disables the hold / the budget)."""
        if max_depth is not None and max_depth > 0:
            self.max_depth = max_depth
        if max_coalesce is not None and max_coalesce > 0:
            self.max_coalesce = max_coalesce
        if fusion is not None:
            self.fusion_enable = bool(fusion)
        if window_us is not None and window_us >= -1:
            self.window_us = int(window_us)
        if hbm_budget is not None and hbm_budget >= -1:
            self.hbm_budget = int(hbm_budget)
        if rc_enable is not None:
            self.rc_enable = bool(rc_enable)
        if rc_overdraft is not None and rc_overdraft >= 0:
            self.rc_overdraft_ru = float(rc_overdraft)
            self._m_rc_overdraft.set(self.rc_overdraft_ru)
        if calibration is not None:
            self.calibration_enable = bool(calibration)
        if hbm_ledger is not None:
            self.hbm_enable = bool(hbm_ledger)
        if pd_enable is not None:
            self.pd_enable = bool(pd_enable)

    # ---- HBM-budget admission (analysis/copcost) -------------------- #

    def effective_budget(self, mesh=None) -> int:
        """Resolved per-mesh budget in bytes; 0 = unlimited.  -1 (auto)
        derives from the mesh's device memory stats once, with a host
        fallback on backends that report none (CPU meshes)."""
        b = self.hbm_budget
        if b >= 0:
            return b
        if self._auto_budget is None:
            if mesh is None:
                return 0          # nothing to derive from yet
            from ..analysis.copcost import mesh_hbm_budget
            self._auto_budget = mesh_hbm_budget(mesh)
            self._m_budget.set(self._auto_budget)
        return self._auto_budget

    # ---- copmeter (analysis/calibrate): measured-cost correction ----- #

    @staticmethod
    def _stable_digest(task) -> Optional[str]:
        """Restart-stable digest of a structured task's program — the
        key the correction store, the copforge manifest, and the
        quarantine purge all share.  None for opaque tasks."""
        if task.dag is None:
            return None
        from ..analysis.compilekey import stable_digest
        return stable_digest(task.dag)

    def _calibrated_cost(self, task, cost):
        """Corrected LaunchCost for admission/pricing (clamped EWMA
        factors from the correction store); the static cost stays on
        ``task.cost_static`` so feedback never compounds on itself."""
        digest = self._stable_digest(task)
        if digest is None:
            return cost
        from ..analysis.calibrate import correction_store
        return correction_store().corrected_cost(digest, cost)

    def _expected_ns(self, task) -> int:
        """Measured expected service time of this task's program (EWMA,
        ns; 0 = never measured) — the shedding backlog unit."""
        if not self.calibration_enable:
            return 0
        digest = self._stable_digest(task)
        if digest is None:
            return 0
        from ..analysis.calibrate import correction_store
        return correction_store().expected_ns(digest)

    def _admit_cost(self, task: CopTask) -> None:
        """Static-footprint gate, run in the submitting thread BEFORE
        the drain loop could trace/compile anything: the task's
        LaunchCost (abstract shape/bytes walk, array metadata only) must
        fit the per-mesh budget, and every device node must have a
        statically derivable bound.  With calibration on, the budget
        comparison (and everything downstream: pricing, fusion caps,
        attribution weights) uses the CORRECTED cost."""
        from ..analysis.copcost import CostError, format_bytes, task_cost
        cost = task.cost_static = task.cost = task_cost(task)
        if cost is None:
            return
        if self.calibration_enable:
            cost = task.cost = self._calibrated_cost(task, cost)
        p = ("sched", type(task.dag).__name__)
        if cost.unbounded:
            raise CostError(
                "cost-unbounded", p,
                "no static device-footprint bound derivable for "
                f"{', '.join(cost.unbounded)}")
        if cost.dense_blowups:
            # degenerate DENSE at large NDV: the plan that 1000x-cliffed
            # (and at sf>=10 crashed) the real-TPU hndv rung — reject
            # pre-trace so selection falls back to the SEGMENT strategy
            path, groups, rows = cost.dense_blowups[0]
            with self._mu:
                self.budget_rejects += 1
            self._m_brej.inc()
            raise CostError(
                "dense-blowup", p,
                f"DENSE aggregation at {path} holds {groups} group "
                f"states for {rows} per-device rows — degenerate "
                "large-NDV dense domain; use a radix strategy "
                "(GroupStrategy.SEGMENT/SCATTER)")
        budget = self.effective_budget(task.mesh)
        # copgauge: the prediction the budget gate enforces — surfaced
        # on the launch span (hbm_predicted) and in EXPLAIN ANALYZE
        # next to the measured peak
        task.hbm_predicted = cost.peak_hbm_bytes
        self._m_hbm_budget.set(budget)
        if budget > 0 and cost.peak_hbm_bytes > budget:
            with self._mu:
                self.budget_rejects += 1
            self._m_brej.inc()
            raise CostError(
                "hbm-budget", p,
                f"estimated peak device bytes "
                f"{format_bytes(cost.peak_hbm_bytes)} exceed the mesh "
                f"admission budget {format_bytes(budget)} "
                "(tidb_tpu_sched_hbm_budget)")
        with self._mu:
            self.budget_admitted += 1
        self._m_badmit.inc()

    def _shed_locked(self, task: CopTask) -> None:
        """Deadline-aware early shedding (copmeter), called with _cv
        held BEFORE the task queues: when the corrected-cost backlog —
        the sum of measured expected service times already queued —
        provably exceeds what this waiter can tolerate, fail it at the
        queue HEAD (rc waiters with the MySQL-compatible 8252, others
        with the 9003 busy error) instead of letting it time out deep
        in queue.  Conservative by construction: only MEASURED digests
        contribute to the backlog, so a cold process never sheds."""
        if not self.calibration_enable or self._backlog_ns <= 0:
            return
        deadline_ns = None
        if task.deadline_ns:
            deadline_ns = int(self.rc_max_queue_s * 1e9)
        elif self._backlog_ns > int(SHED_MAX_BACKLOG_S * 1e9):
            deadline_ns = int(SHED_MAX_BACKLOG_S * 1e9)
        if deadline_ns is None or self._backlog_ns <= deadline_ns:
            return
        self.shed_rejects += 1
        self._m_shed.inc()
        if task.key is not None:
            # same slot hygiene as the busy path: a shed HALF_OPEN
            # probe must release its probe slot
            self.breaker.abort_probe(task.key[0])
        if task.deadline_ns:
            raise ResourceExhaustedError(
                task.group, self._backlog_ns / 1e9, task.rus)
        raise ServerBusyError(self.max_depth)

    def _backlog_sub_locked(self, task: CopTask) -> None:
        """A queued task left the queue (served, expired, cancelled):
        release its expected-service contribution (called with _cv
        held; clamped so bookkeeping drift can never wedge admission)."""
        self._backlog_ns = max(self._backlog_ns - task.svc_ns, 0)

    @staticmethod
    def _marginal_bytes(t: CopTask, lead: CopTask) -> int:
        """Bytes a rider ADDS to lead's launch: its payload only when it
        shares lead's resident scan (fusion / in-flight dedup), its full
        footprint when it brings distinct inputs (batch-slot stacking)."""
        if t.cost is None:
            return 0
        if t.input_token == lead.input_token:
            return t.cost.peak_hbm_bytes - t.cost.input_bytes
        return t.cost.peak_hbm_bytes

    def submit(self, task: CopTask) -> CopTask:
        """Enqueue; raises ServerBusyError when the bounded queue is
        full (backpressure instead of unbounded buffering).  Structured
        tasks are contract-verified AND cost-gated on admission — a
        malformed task (capacity-shape drift, stale mesh key, invalid
        DAG) or an over-budget program is rejected with a structured
        PlanContractError/CostError HERE, in the submitting thread,
        before the drain loop would trace/compile anything."""
        if task.key is not None and _verify_enabled():
            from ..analysis.contracts import verify_task
            verify_task(task)
            self._admit_cost(task)
            if task.value_drift:
                # valueflow watermark drift: the plan's declared value
                # interval no longer contains the observed ANALYZE
                # watermark — never wrong (proofs carry append
                # headroom), but the operator should re-ANALYZE
                with self._mu:
                    self.value_drifts += task.value_drift
        if task.key is not None:
            # circuit breaker: a digest whose launches keep failing is
            # quarantined HERE, in the submitting thread — fail fast
            # with the structured error the client's host fallback
            # understands, instead of re-crashing the device
            try:
                self.breaker.admit(task.key[0])
            except LaunchQuarantinedError:
                with self._mu:
                    self.quarantined += 1
                self._m_quar.inc()
                self._trace_mark(task, "sched.quarantine",
                                 digest=f"{task.key[0] & ((1 << 64) - 1):016x}")
                if task.trace is not None:
                    task.trace.tree.flag("quarantined")
                raise
        # rc pricing happens HERE, in the submitting thread: structured
        # tasks price from the LaunchCost the admission gate just
        # computed, opaque tasks from their row estimate — the drain
        # only compares/debits, never prices
        task.rus = task_rus(task)
        if self.rc_enable and task.rc_group is not None \
                and task.rc_group.limited:
            task.deadline_ns = task.submit_ns + \
                int(self.rc_max_queue_s * 1e9)
        # copmeter: the task's measured expected service time (0 when
        # the digest was never measured) — computed OUTSIDE the lock
        task.svc_ns = self._expected_ns(task)
        with self._cv:
            if self._depth >= self.max_depth:
                self.busy_rejects += 1
                self._m_busy.inc()
                if task.key is not None:
                    # an admitted HALF_OPEN probe that never queues must
                    # release its slot or no probe could ever run
                    self.breaker.abort_probe(task.key[0])
                raise ServerBusyError(self.max_depth)
            self._shed_locked(task)
            g = self._groups.get(task.group)
            if g is None:
                g = self._groups[task.group] = _GroupQ(
                    task.group, task.weight, len(self._groups),
                    vtime=self._gvt)
            else:
                g.weight = max(task.weight, 1e-6)
                if not g.queue:
                    # re-activating group: forfeit banked idle time so it
                    # cannot starve others (stride newcomer rule)
                    g.vtime = max(g.vtime, self._gvt)
            g.queue.append(task)
            self._depth += 1
            self._backlog_ns += task.svc_ns
            self._note_arrival(task)
            self._m_depth.set(self._depth)
            self._m_tasks.inc(group=task.group)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="sched-drain", daemon=True)
                self._thread.start()
            self._cv.notify_all()
        if task.fusion_key is not None and task.key is not None:
            # copforge: a second digest joining this fusion key predicts
            # the fused variant — warm it off-thread (lock released)
            self._predict_fusion(task)
        return task

    def pause(self) -> None:
        """Hold the drain loop (tests / maintenance); submits still queue."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    # ------------------------------------------------------------- #
    # drain loop
    # ------------------------------------------------------------- #

    def _pick(self) -> Optional[_GroupQ]:
        best = None
        for g in self._groups.values():
            if not g.queue:
                continue
            if self._rc_blocked(g):
                continue
            if best is None or (g.vtime, g.seq) < (best.vtime, best.seq):
                best = g
        return best

    # ---- resource-control enforcement (rc/: priced RU admission) ---- #

    def _task_bucket(self, t):
        """The RU bucket governing task ``t``; None = not enforced
        (rc disabled, no group attached, or the group is unlimited)."""
        if not self.rc_enable or t.rc_group is None:
            return None
        return t.rc_group.bucket if t.rc_group.limited else None

    def _rc_blocked(self, g: _GroupQ) -> bool:
        """May this group's HEAD task launch under its RU budget?  A
        blocked group is skipped by the fair pick — it queues while
        sibling groups keep launching (tikv unified-read-pool deadline
        behavior); cancelled heads always pass so the drain can fail
        them out of the queue."""
        head = g.queue[0]
        if head.cancelled:
            return False
        b = self._task_bucket(head)
        if b is None or b.can_cover(head.rus, self.rc_overdraft_ru):
            return False
        g.throttled += 1
        self.rc_throttled += 1
        self._m_rc_throttle.inc(group=g.name)
        return True

    def _rc_covers(self, t, lead) -> bool:
        """May ``t`` ride lead's launch under t's OWN group budget?  A
        rider from an exhausted group must stay queued even when the
        launch itself is free capacity — otherwise fusion would be an
        RU-bypass."""
        b = self._task_bucket(t)
        return b is None or b.can_cover(task_rus(t, lead),
                                        self.rc_overdraft_ru)

    def _rc_debit(self, t, lead=None) -> None:
        """Pre-launch debit at batch admission: the task's priced RUs
        (marginal when it shares lead's resident scan) leave its
        group's bucket BEFORE anything traces or launches.  The check
        ran in _rc_blocked/_rc_covers on this same drain thread, so
        check-then-debit cannot interleave with itself."""
        rus = task_rus(t, lead)
        t.rus_charged = rus
        b = self._task_bucket(t)
        if b is not None:
            b.debit(rus)
            self.rc_debited_ru += rus
            self._m_rc_debit.inc(rus, group=t.group)

    def _rc_expire_locked(self) -> None:
        """Fail throttled waiters that overstayed the max-queue
        deadline with the MySQL-compatible resource-exhausted error
        (called with _cv held).  Only tasks whose bucket STILL cannot
        cover them expire — a covered task merely queued behind load
        keeps waiting for the fair drain."""
        now = time.perf_counter_ns()
        expired = False
        for g in self._groups.values():
            if not g.queue:
                continue
            for t in list(g.queue):
                if not t.deadline_ns or now <= t.deadline_ns:
                    continue
                b = self._task_bucket(t)
                if b is not None and not b.can_cover(
                        t.rus, self.rc_overdraft_ru):
                    g.queue.remove(t)
                    self._depth -= 1
                    self._backlog_sub_locked(t)
                    self.rc_exhausted += 1
                    self._m_rc_exhaust.inc(group=g.name)
                    if t.trace is not None:
                        # the waiter never launched: its whole life was
                        # queue wait — record it with the expiry marked
                        t.trace.add("sched.queue", t.submit_ns, now,
                                    group=g.name, expired=True)
                    t.fail(ResourceExhaustedError(
                        t.group, (now - t.submit_ns) / 1e9, t.rus))
                    expired = True
        if expired:
            self._m_depth.set(self._depth)

    # ---- adaptive micro-batch window (EWMA of arrival gaps) --------- #

    def _note_arrival(self, task) -> None:
        """Track per-fusion-key arrival gaps (called with _cv held).
        Plain coalescing benefits from the window too, so keyed tasks
        without a fusion key track under their task key."""
        fk = task.fusion_key if task.fusion_key is not None else task.key
        if fk is None:
            return
        if len(self._fk_last) > 256:      # hot keys are few; stay tiny
            self._fk_last.clear()
            self._fk_gap.clear()
            self._fk_hit.clear()
        last = self._fk_last.get(fk)
        self._fk_last[fk] = task.submit_ns
        if last is None:
            return
        gap = min(task.submit_ns - last, WINDOW_GAP_CLAMP_NS)
        prev = self._fk_gap.get(fk)
        self._fk_gap[fk] = gap if prev is None else \
            0.7 * prev + 0.3 * gap

    def _window_ns(self, lead) -> int:
        """How long the drain may hold `lead` waiting for stragglers.
        Fixed when the sysvar pins it; adaptive (-1) holds 2x the key's
        EWMA arrival gap SCALED by the key's observed hold hit-rate
        (window feedback: a key whose holds rarely yield riders decays
        its window toward zero and stops paying the hold at all), and
        only when the base window fits the cap — a key whose matches
        arrive slowly never delays its own launch."""
        if lead.key is None:
            return 0
        if self.window_us == 0:
            return 0
        if self.window_us > 0:
            return self.window_us * 1000
        fk = lead.fusion_key if lead.fusion_key is not None else lead.key
        gap = self._fk_gap.get(fk)
        if gap is None:
            return 0
        w = int(2 * gap)
        if w > WINDOW_CAP_US * 1000:
            return 0
        scale = min(1.0, self._fk_hit.get(fk, WINDOW_HIT_INIT)
                    / WINDOW_HIT_INIT)
        if scale < WINDOW_HIT_FLOOR:
            return 0
        w = int(w * scale)
        if self.calibration_enable:
            # copmeter window feed: a hold only pays when it is small
            # next to the launch it delays — cap the hold at a quarter
            # of the digest's MEASURED launch time, so a program the
            # calibration knows to be fast never waits longer than it
            # would run
            exp = self._expected_ns(lead)
            if exp:
                w = min(w, exp // 4)
        return w

    def _note_window_outcome(self, lead, hit: bool) -> None:
        """Feed one hold's outcome back into the key's hit-rate EWMA
        (called with _cv held, right after the hold resolves)."""
        fk = lead.fusion_key if lead.fusion_key is not None else lead.key
        if fk is None:
            return
        prev = self._fk_hit.get(fk, WINDOW_HIT_INIT)
        self._fk_hit[fk] = ((1.0 - WINDOW_HIT_ALPHA) * prev
                            + WINDOW_HIT_ALPHA * (1.0 if hit else 0.0))
        if hit:
            self.window_hits += 1

    # ---- batch assembly --------------------------------------------- #

    def _rides(self, t, lead) -> bool:
        """May `t` share lead's launch?  Same program (in-flight dedup /
        batch-slot stacking) or same fusion key with a different digest
        (cross-query fusion: one scan, many payloads)."""
        if t.cancelled:
            return False
        if (t.key == lead.key and t.mesh is lead.mesh
                and (t.dag is lead.dag or t.dag == lead.dag)):
            return True
        return (self.fusion_enable
                and lead.fusion_key is not None
                and t.fusion_key == lead.fusion_key
                and t.mesh is lead.mesh)

    def _collect_riders(self, lead, batch: list) -> None:
        """Pop every queued rider across ALL groups — coalescing and
        fusion are cross-session by design.  Each rider charges its own
        group's virtual time.  Group size is capped by SUMMED static
        footprint (analysis/copcost LaunchCost) against the mesh budget
        — the scan is paid once, but every distinct payload/input adds
        HBM, so a fused group must fit as a whole — with the member
        count cap (tidb_tpu_sched_max_coalesce) still the outer bound."""
        budget = self.effective_budget(lead.mesh)
        footprint = lead.cost.peak_hbm_bytes if lead.cost is not None else 0
        for og in self._groups.values():
            if len(batch) >= self.max_coalesce:
                break
            kept: deque = deque()
            while og.queue:
                t = og.queue.popleft()
                if len(batch) < self.max_coalesce \
                        and self._rides(t, lead) \
                        and self._rc_covers(t, lead):
                    add = self._marginal_bytes(t, lead)
                    if budget > 0 and footprint and \
                            footprint + add > budget:
                        # over the summed-footprint cap: defer — the
                        # rider stays queued and leads a later launch
                        self.budget_deferrals += 1
                        self._m_bdefer.inc()
                        kept.append(t)
                        continue
                    footprint += add
                    self._rc_debit(t, lead)
                    batch.append(t)
                    self._depth -= 1
                    self._backlog_sub_locked(t)
                    og.vtime += 1.0 / og.weight
                    og.tasks += 1
                else:
                    kept.append(t)
            og.queue = kept

    def _take_batch(self) -> list:
        """Pop the fair-ordered head task plus every compatible queued
        rider; optionally hold inside the micro-batch window so
        stragglers that are statistically about to arrive (EWMA of the
        key's arrival gaps) coalesce/fuse instead of launching apart."""
        self._rc_expire_locked()
        g = self._pick()
        if g is None:
            return []
        lead = g.queue.popleft()
        self._depth -= 1
        self._backlog_sub_locked(lead)
        g.vtime += 1.0 / g.weight
        self._gvt = g.vtime
        g.tasks += 1
        if lead.cancelled:
            self._m_depth.set(self._depth)
            lead.fail(TaskCancelledError())
            return [None]          # sentinel: retry pick
        self._rc_debit(lead)
        batch = [lead]
        if lead.key is not None:
            self._collect_riders(lead, batch)
            w_ns = self._window_ns(lead)
            if w_ns > 0 and len(batch) < self.max_coalesce:
                # wait-for-stragglers: _cv.wait releases the lock, so
                # submits land and notify; re-collect after each wake
                deadline = time.perf_counter_ns() + w_ns
                self.window_waits += 1
                held_at = len(batch)
                while len(batch) < self.max_coalesce:
                    rem_ns = deadline - time.perf_counter_ns()
                    if rem_ns <= 0:
                        break
                    self._cv.wait(rem_ns / 1e9)
                    self._collect_riders(lead, batch)
                # window feedback: did the hold actually gain riders?
                self._note_window_outcome(lead, len(batch) > held_at)
        self._m_depth.set(self._depth)
        return batch

    def _loop(self) -> None:
        idle_since = time.monotonic()
        while True:
            with self._cv:
                while self._paused or self._depth == 0:
                    if self._depth == 0 and not self._paused and \
                            time.monotonic() - idle_since > IDLE_EXIT_S:
                        self._thread = None
                        return
                    self._cv.wait(timeout=0.5)
                    if not self._paused and self._depth == 0:
                        continue
                batch = self._take_batch()
                if not batch and self._depth > 0:
                    # every queued group is RU-throttled: their waiters
                    # stay queued until a bucket refill covers a head
                    # task or the max-queue deadline expires them
                    # (_rc_expire_locked ran inside _take_batch); sleep
                    # briefly — submits still notify the condition
                    self._cv.wait(timeout=RC_RETRY_S)
            idle_since = time.monotonic()
            if not batch or batch == [None]:
                continue
            now = time.perf_counter_ns()
            for t in batch:
                t.start_ns = now
                t.wait_ns = now - t.submit_ns
            self._note_launch_bytes(batch)
            # copgauge: launch-scoped bytes enter the ledger at
            # admission and leave at finish; the measured watermark
            # (stamped by _mem_note inside the serve) feeds it after
            led = self._ledger(batch[0].mesh)
            eph = self._launch_ephemeral_bytes(batch) \
                if led is not None else 0
            if led is not None:
                led.launch_begin(eph)
                self._mem_mark()
            try:
                self._serve_supervised(batch)
            except BaseException as e:  # noqa: BLE001 supervisor safety
                for t in batch:         # net: the drain must never die
                    t.fail(e)
            finally:
                if led is not None:
                    led.launch_end(eph)
                    measured = max(
                        (t.hbm_measured for t in batch), default=0)
                    if measured > 0:
                        led.note_measured(measured)
            self._attribute_launch(batch,
                                   time.perf_counter_ns() - now)
            self._account(batch)

    # ------------------------------------------------------------- #
    # copgauge (obs/hbm): live ledger + measured launch watermarks
    # ------------------------------------------------------------- #

    def _ledger(self, mesh):
        """This mesh's live HBM ledger; None when copgauge is off."""
        if not self.hbm_enable or mesh is None:
            return None
        led = self._ledger_obj
        if led is None:
            from ..obs.hbm import ledger_for
            from .task import mesh_fingerprint
            led = self._ledger_obj = ledger_for(mesh_fingerprint(mesh))
        return led

    def _launch_ephemeral_bytes(self, batch: list) -> int:
        """EPHEMERAL/LOOP-CARRIED bytes this launch adds ON TOP of the
        persistent residents: the lead's peak minus its resident scan
        (live snapshot-cache inputs are already on the ledger's
        persistent side), plus each rider's marginal bytes.  Donated
        bytes are credited at dispatch by construction —
        ``peak_hbm_bytes`` already subtracts ``donated_bytes``."""
        lead = batch[0]
        if lead.cost is None:
            return 0
        n = lead.cost.peak_hbm_bytes
        from ..analysis.lifetime import is_resident
        if is_resident(lead.counts):
            n -= lead.cost.input_bytes
        n += sum(self._marginal_bytes(t, lead) for t in batch[1:])
        return max(n, 0)

    @staticmethod
    def _mem_mark() -> None:
        """Reset the drain thread's executable-memory high-water before
        a serve (the copforge measured-watermark seam)."""
        from ..compilecache import compile_cache
        compile_cache().thread_mem_mark()

    def _mem_note(self, tasks: list, mesh) -> int:
        """Measured peak of the launch that just ran on this thread:
        the compiled memory analysis of the ACTUALLY-SERVED executable
        (per-device, scaled by mesh size), stamped onto every task
        BEFORE finish so waiters/EXPLAIN observe it.  Live memory_stats
        never rides here — the ledger's bounded ``reconcile`` owns that
        poll, off the launch path.  0 = backend reports nothing."""
        if not self.hbm_enable:
            return 0
        from ..compilecache import compile_cache
        per_dev = compile_cache().thread_mem_take()
        if per_dev <= 0:
            return 0
        n_dev = int(mesh.devices.size) if mesh is not None else 1
        measured = per_dev * n_dev
        for t in tasks:
            t.hbm_measured = measured
        return measured

    # ------------------------------------------------------------- #
    # copforge (compilecache/): compile attribution + fusion warmup
    # ------------------------------------------------------------- #

    @staticmethod
    def _cc_mark() -> tuple:
        """Drain-thread snapshot of the compile cache's per-thread
        resolve totals (ns, misses, hits) — deltas around a launch are
        THIS launch's compile bill, uncontaminated by other threads."""
        from ..compilecache import compile_cache
        return compile_cache().thread_snapshot()

    def _cc_note(self, tasks: list, mark: tuple) -> None:
        """Attribute the resolve/compile time since ``mark`` to every
        task of the launch BEFORE it finishes, so waiters always observe
        it: this is the ``compile_wait_ms`` split out of schedWait — a
        deduped rider that queued while the lead traced sees WHERE its
        wait went (satellite: Avg_compile_ms in statements_summary)."""
        from ..compilecache import compile_cache
        ns, misses, _hits = compile_cache().thread_snapshot()
        dns, dmiss = ns - mark[0], misses - mark[1]
        if dns <= 0 and dmiss <= 0:
            return
        self.compile_ns_total += dns
        if dns > 0:
            # copscope: resolve/compile latency histogram (the span
            # twin is recorded per launch in _trace_launch)
            self._m_compile_ms.observe(dns / 1e6)
        for t in tasks:
            t.compile_ns += dns
            if dmiss:
                t.compile_miss = True

    def _predict_fusion(self, task) -> None:
        """Async background warmup of predicted fusion variants: when a
        second distinct program digest joins a fusion key, the fused
        program for the combined member set is probably about to be
        needed — compile it into the warm pool on a background thread
        (bounded) so the first real fused arrival pays a pool hit, not
        a trace.  Never on the drain thread, never surfaced on failure."""
        from ..compilecache import compile_cache
        if not self.fusion_enable or not compile_cache().enable:
            return
        from ..copr import dag as D
        if not isinstance(task.dag, D.Aggregation):
            return          # rows fusion capacities are waiter-owned
        import jax
        sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=getattr(a, "sharding", None)),
            (tuple(task.cols), task.counts, ()))
        with self._mu:
            if len(self._fusion_seen) > 64:
                self._fusion_seen.clear()
            seen = self._fusion_seen.setdefault(task.fusion_key, {})
            seen[task.key[0]] = (task.dag, sds)
            if len(seen) < 2 or len(self._fusion_warmed) > 32 \
                    or self._warm_alive >= 2:
                return
            combo = (task.fusion_key, frozenset(seen))
            if combo in self._fusion_warmed:
                return
            self._fusion_warmed.add(combo)
            members = [dag for dag, _s in seen.values()]
            lead_sds = next(iter(seen.values()))[1]
            self._warm_alive += 1
        mesh = task.mesh

        def warm():
            ok = False
            try:
                from ..parallel.spmd import get_fused_program
                fused = D.FusedDag(tuple(members))
                prog = get_fused_program(fused, mesh)
                prog._cached.warm(lead_sds)
                ok = True
            except Exception:   # noqa: BLE001 - prediction is a pure
                # optimization: an unfusable combo or a backend refusal
                # just means the real arrival compiles as before
                pass
            finally:
                # counters under _mu: up to two warm threads run
                # concurrently, so a bare += here loses updates
                with self._mu:
                    self._warm_alive -= 1
                    if ok:
                        self.warm_predicted += 1
                    else:
                        self.warm_failures += 1

        threading.Thread(target=warm, name="copforge-predict",
                         daemon=True).start()

    # ------------------------------------------------------------- #
    # copscope span recording (obs/): the drain's side of the trace
    # ------------------------------------------------------------- #

    @staticmethod
    def _err_label(e: BaseException) -> str:
        return f"{type(e).__name__}: {str(e)[:80]}"

    @staticmethod
    def _strategy_of(dag) -> Optional[str]:
        s = getattr(dag, "strategy", None)
        return getattr(s, "value", None) if s is not None else None

    @staticmethod
    def _trace_mark(t, name: str, **attrs) -> None:
        """Zero-duration marker span on one task's trace (oom / bisect
        / quarantine / fail seams); no-op when untraced."""
        if t.trace is not None:
            now = time.perf_counter_ns()
            t.trace.add(name, now, now, **attrs)

    def _trace_launch(self, tasks: list, start_ns: int, end_ns: int,
                      mode: str, fused: int = 0) -> None:
        """Record one physical launch's scheduler-side span tree +
        latency histograms, on the DRAIN thread BEFORE the tasks
        finish — a waiter rendering its trace right after wait()
        always sees these spans (no post-finish race).

        Per traced task: a ``sched.queue`` span (submit -> drain
        pickup; rc debit rides it as the ``ru`` attr) and a
        ``sched.launch`` span (resolve + device execution) carrying
        predicted_ms (calibrated LaunchCost via copmeter's predict_ms)
        vs measured_ms, the shardflow per-link transfer breakdown,
        and — as children — the copforge ``sched.compile`` span
        (hit/miss) and the ``sched.fusion`` assembly span with the
        member count and this member's attributed share."""
        wall_ms = (end_ns - start_ns) / 1e6
        self._m_launch_ms.observe(wall_ms)
        for strat in {self._strategy_of(t.dag) for t in tasks} - {None}:
            self._m_agg_ms.observe(wall_ms, strategy=strat)
        if all(t.trace is None for t in tasks):
            return
        lead = tasks[0]
        shares = None
        if fused > 1 or len(tasks) > 1:
            weights = [lead.cost.peak_hbm_bytes
                       if lead.cost is not None else 0]
            weights += [self._marginal_bytes(t, lead) for t in tasks[1:]]
            shares = split_device_time(weights, end_ns - start_ns)
        from ..analysis.calibrate import predict_ms
        for i, t in enumerate(tasks):
            ctx = t.trace
            if ctx is None:
                continue
            attrs = {"mode": mode, "measured_ms": round(wall_ms, 3)}
            if t.cost is not None:
                attrs["predicted_ms"] = round(predict_ms(t.cost), 3)
                bd = t.cost.transfer_breakdown or (0, 0, 0)
                if bd[1] or bd[2]:
                    attrs["ici_bytes"], attrs["dci_bytes"] = bd[1], bd[2]
            # copgauge: the memory axis of the launch span — the
            # admission prediction next to the measured executable peak
            if t.hbm_predicted:
                attrs["hbm_predicted"] = t.hbm_predicted
            if t.hbm_measured:
                attrs["hbm_measured"] = t.hbm_measured
            if t.value_drift:
                # valueflow: declared interval no longer contains the
                # observed watermark (stats drift, not a wrong result)
                attrs["value_drift"] = t.value_drift
            strat = self._strategy_of(t.dag)
            if strat is not None:
                attrs["strategy"] = strat
            if t.retries:
                attrs["retries"] = t.retries
            items = [
                ("sched.queue", t.submit_ns, t.start_ns, ctx.span_id,
                 {"group": t.group, "ru": round(t.rus_charged, 2)}),
                ("sched.launch", start_ns, end_ns, ctx.span_id, attrs),
            ]
            if t.compile_ns:
                items.append((
                    "sched.compile", start_ns, start_ns + t.compile_ns,
                    ("rel", 1),
                    {"result": "miss" if t.compile_miss else "hit"}))
            if fused > 1:
                fat = {"members": fused}
                if shares is not None:
                    fat["share_ms"] = round(shares[i] / 1e6, 3)
                items.append(("sched.fusion", t.start_ns, start_ns,
                              ("rel", 1), fat))
            ctx.tree.add_batch(items)

    def _trace_retry(self, tasks: list, err: BaseException,
                     start_ns: int, end_ns: int) -> None:
        """One transient-failure backoff cycle: a real span covering
        the retry sleep, per affected waiter."""
        label = self._err_label(err)
        for t in tasks:
            if t.trace is not None:
                t.trace.add("sched.retry", start_ns, end_ns,
                            attempt=t.retries, error=label)

    # ------------------------------------------------------------- #
    # launch supervision (faultline)
    # ------------------------------------------------------------- #

    @staticmethod
    def _digests(tasks: list) -> set:
        return {t.key[0] for t in tasks if t.key is not None}

    @staticmethod
    def _is_transient(e: BaseException) -> bool:
        """Retry-worthy launch failures: injected transient faults and
        typed retryable dispatch errors.  Everything else — compile
        errors, device crashes, contract violations — is treated as
        persistent: retrying an identical program would re-crash the
        device, so it fails (and charges the breaker) instead."""
        from ..store.backoff import RegionError
        return isinstance(e, (_faults.TransientFault, RegionError))

    @staticmethod
    def _is_fatal(e: BaseException) -> bool:
        """Never retried, never breaker-charged: cancellation and
        interpreter teardown."""
        from ..copr.coordinator import QueryInterrupted
        return isinstance(e, (TaskCancelledError, QueryInterrupted,
                              KeyboardInterrupt, SystemExit))

    def _launch_backoffer(self):
        from ..store.backoff import Backoffer
        fp = _faults.active()
        rng = fp.backoff_rng() if fp is not None \
            else random.Random(RETRY_JITTER_SEED)
        return Backoffer(max_sleep_ms=self.launch_retry_ms,
                         sleep_fn=self._retry_sleep, rng=rng)

    def _serve_supervised(self, batch: list) -> None:
        """Serve a batch under the retry/breaker contract: transient
        failures re-launch through the DEVICE_FAILED backoff budget
        (already-finished members are never re-run — finish() is
        idempotent and the live filter drops them), persistent failures
        go to blast-radius isolation, cancelled waiters fail typed and
        are never retried.  Successful launches clear their digests'
        breaker state."""
        from ..store.backoff import DEVICE_FAILED, RetryBudgetExceeded
        bo = None
        while True:
            live = []
            for t in batch:
                if t.done:
                    continue
                if t.cancelled:
                    t.fail(TaskCancelledError())
                    continue
                live.append(t)
            if not live:
                return
            try:
                _faults.check("drain")
                self._serve(live)
            except BaseException as e:  # noqa: BLE001 classified below
                if self._is_fatal(e):
                    for t in live:
                        t.fail(e)
                    return
                if _faults.is_oom_error(e):
                    # memory exhaustion is its own class (copmeter): a
                    # healthy program outgrew the budget — recover by
                    # shrinking the launch, never by charging the
                    # poison breaker
                    self._handle_oom([t for t in batch if not t.done], e)
                    return
                if self._is_transient(e):
                    if bo is None:
                        bo = self._launch_backoffer()
                    retry_t0 = time.perf_counter_ns()
                    try:
                        bo.backoff(DEVICE_FAILED, e)
                    except RetryBudgetExceeded as budget:
                        self._isolate(
                            [t for t in batch if not t.done], budget)
                        return
                    self.retried_launches += 1
                    self.retried_tasks += len(live)
                    self._m_retried.inc(len(live))
                    for t in live:
                        t.retries += 1
                    self._trace_retry(live, e, retry_t0,
                                      time.perf_counter_ns())
                    continue
                self._isolate([t for t in batch if not t.done], e)
                return
            else:
                for d in self._digests(live):
                    self.breaker.record_success(d)
                return

    def _handle_oom(self, live: list, err: BaseException) -> None:
        """OOM-classified launch failure (copmeter): RESOURCE_EXHAUSTED
        / XLA-OOM — a healthy program whose modeled footprint was too
        small, NOT a poisoned kernel.  Bump every member digest's
        memory correction (so future admission sees the bigger
        footprint: budget rejection into streaming, smaller fusion
        groups), retry group launches at reduced fusion width (the
        members relaunch solo), and fail a solo launch to its waiter —
        whose CopClient recovers via streamed batching or the host
        oracle.  The poison circuit breaker is NEVER charged: an OOM
        must not quarantine a program that would fit when resized."""
        self.oom_faults += 1
        self._m_oom.inc()
        for t in live:
            self._trace_mark(t, "sched.oom", error=self._err_label(err))
            if t.trace is not None:
                t.trace.tree.flag("oom")
        if self.calibration_enable:
            from ..analysis.calibrate import correction_store
            store = correction_store()
            for digest in sorted({d for d in map(self._stable_digest,
                                                 live) if d is not None}):
                store.observe_oom(digest)
            store.sync_manifest()
        subs: list = []
        by_member: dict = {}
        for t in live:
            k = (t.key, t.input_token)
            g = by_member.get(k)
            if g is None:
                g = by_member[k] = []
                subs.append(g)
            g.append(t)
        if len(subs) <= 1:
            for t in live:
                t.fail(err)
            return
        self.oom_demuxed += 1
        for sub in subs:
            # reduced fusion width: each member relaunches alone; a
            # member that STILL OOMs solo lands in the fail branch
            # above and its waiter's client degrades (stream / host)
            self._serve_supervised(sub)

    def _isolate(self, live: list, err: BaseException) -> None:
        """Blast-radius isolation: a failed GROUP launch (fused members
        and/or batched slots) is demuxed into its (program, input)
        members and each retried SOLO — innocent riders complete, only
        the poisoned member fails its waiter and charges its digest's
        breaker.  Fusion must never widen a failure domain.  A launch
        that was already solo is the bisection base case: fail + charge."""
        subs: list = []
        by_member: dict = {}
        for t in live:
            k = (t.key, t.input_token)
            g = by_member.get(k)
            if g is None:
                g = by_member[k] = []
                subs.append(g)
            g.append(t)
        if len(subs) <= 1:
            for d in self._digests(live):
                self.breaker.record_failure(d)
                if self.breaker.state(d) == "OPEN":
                    # copforge: an OPEN breaker must not warm-replay
                    # after a restart — purge the digest's manifest
                    # entries (no quarantine laundering)
                    self._cc_quarantine(d, live)
            for t in live:
                self._trace_mark(t, "sched.fail",
                                 error=self._err_label(err))
                t.fail(err)
            return
        self.bisected_launches += 1
        self._m_bisect.inc()
        for t in live:
            self._trace_mark(t, "sched.bisect", members=len(subs))
        for sub in subs:
            # recursion bottoms out: a solo member that fails again
            # lands in the len(subs) <= 1 branch above
            self._serve_supervised(sub)

    def _cc_quarantine(self, digest: int, live: list) -> None:
        """Map the breaker's process-local digest to the restart-stable
        one and purge it from the compile cache's warm manifest."""
        from ..analysis.compilekey import stable_digest
        from ..compilecache import compile_cache
        for t in live:
            if t.key is not None and t.key[0] == digest \
                    and t.dag is not None:
                sd = stable_digest(t.dag)
                compile_cache().quarantine(sd)
                if self.pd_enable:
                    # coplace: tombstone the digest for every peer so
                    # a breaker-opened program is not laundered back
                    # through a peer's warm pool (pd/registry)
                    from ..pd import broadcast_quarantine
                    broadcast_quarantine(sd)
                return

    # ------------------------------------------------------------- #
    # launch
    # ------------------------------------------------------------- #

    def _note_launch_bytes(self, batch: list) -> None:
        """Static footprint of the batch about to launch (scan counted
        once per distinct input, payloads summed) — the bytes gauge the
        budget admission reasons in."""
        lead = batch[0]
        if lead.cost is None:
            return
        est = lead.cost.peak_hbm_bytes + sum(
            self._marginal_bytes(t, lead) for t in batch[1:])
        self.last_launch_bytes = est
        self._m_launch_bytes.set(est)

    def _serve(self, batch: list) -> None:
        lead = batch[0]
        if lead.fn is not None:                     # opaque launch
            # failures PROPAGATE so the supervisor classifies them
            # (transient retry vs fail) instead of failing the waiter
            # on the first error
            _faults.check("launch")
            t_l0 = time.perf_counter_ns()
            val = lead.fn()
            self._mem_note([lead], lead.mesh)
            self._trace_launch([lead], t_l0, time.perf_counter_ns(),
                               "opaque")
            lead.finish(val)
            self.launches += 1
            self._m_launch.inc(mode="single")
            return
        # partition by task key: a fusion batch carries several distinct
        # programs over one shared scan
        programs: list[list] = []
        by_key: dict = {}
        for t in batch:
            grp = by_key.get(t.key)
            if grp is None:
                grp = by_key[t.key] = []
                programs.append(grp)
            grp.append(t)
        if len(programs) > 1 and self._serve_fused(programs):
            return
        for grp in programs:
            self._serve_program(grp)
            self._note_coalesce(grp)

    def _serve_fused(self, programs: list) -> bool:
        """ONE launch computing every member program's payload from the
        shared scan; False = refused (contract violation / backend
        can't), caller falls back to per-program launches.  Agg member
        groups run as a FusedCopProgram; rows-kind groups (fusion-breadth
        follow-on) run as a FusedRowsProgram with per-member output
        capacities."""
        from ..copr import dag as D
        from ..parallel.spmd import (get_fused_program,
                                     get_fused_rows_program,
                                     get_sharded_program)
        members = [grp[0] for grp in programs]
        lead = members[0]
        cc0 = self._cc_mark()
        t_l0 = time.perf_counter_ns()     # launch span covers resolve
        try:
            # the launch seam is consulted once PER MEMBER digest: a
            # poisoned member refuses the fused launch (caught below),
            # demuxing to per-program launches where the guilty member
            # fails ALONE — injected faults exercise exactly the
            # blast-radius contract real failures follow
            for m in members:
                _faults.check("launch", m.key[0])
            from ..analysis.contracts import verify_fusion_group
            # EVERY task (riders too): a same-key rider carrying a
            # different input token must refuse the fused scan — its
            # result would come from the wrong snapshot residents
            verify_fusion_group([t for grp in programs for t in grp])
            fused = D.FusedDag(tuple(t.dag for t in members))
            if isinstance(lead.dag, D.Aggregation):
                fprog = get_fused_program(fused, lead.mesh,
                                          donate=lead.donate)
            else:
                fprog = get_fused_rows_program(
                    fused, lead.mesh,
                    tuple(t.row_capacity for t in members))
            outs = fprog(lead.cols, lead.counts)
        except Exception:   # noqa: BLE001 - fusion capability probe:
            return False    # refused groups launch apart below (same
                            # results, no fusion win)
        total = sum(len(grp) for grp in programs)
        all_tasks = [t for grp in programs for t in grp]
        self._cc_note(all_tasks, cc0)
        # fused/coalesced attrs + spans are set BEFORE finish(): the
        # waiter's _note_sched reads task.fused right after wait()
        # returns, so setting them after finish raced the waiter and
        # undercounted `fused`/`coalesced` in EXPLAIN ANALYZE and
        # statements_summary (copscope satellite: the note_sched seam)
        for t in all_tasks:
            t.fused = len(programs)
            t.coalesced = total
        self._mem_note(all_tasks, lead.mesh)
        self._trace_launch(all_tasks, t_l0, time.perf_counter_ns(),
                           "fused", fused=len(programs))
        for grp, out in zip(programs, outs):
            sprog = get_sharded_program(grp[0].dag, grp[0].mesh,
                                        grp[0].row_capacity)
            for t in grp:
                t.finish((sprog, out))
        self.launches += 1
        if fprog._donate_argnums:
            self.donated_launches += 1
        self.fused_launches += 1
        self.fused_tasks += total
        self._m_launch.inc(mode="fused")
        self._m_fused.inc(total)
        return True

    def _serve_program(self, batch: list) -> None:
        """Launch ONE program's tasks: in-flight dedup by input token,
        batch-slot vmap stacking for distinct inputs (dense aggs AND
        compacted row outputs), per-slot launches otherwise."""
        lead = batch[0]
        from ..parallel.spmd import (get_batched_program,
                                     get_batched_rows_program,
                                     get_sharded_program)
        digest = lead.key[0] if lead.key is not None else None
        cc0 = self._cc_mark()
        t_l0 = time.perf_counter_ns()     # launch span covers resolve
        _faults.check("build", digest)
        prog = get_sharded_program(lead.dag, lead.mesh, lead.row_capacity,
                                   donate=lead.donate)
        _faults.check("launch", digest)
        # group riders by input identity: same-token tasks share ONE
        # program execution (in-flight dedup)
        slots: list[list] = []
        by_token: dict = {}
        for t in batch:
            s = by_token.get(t.input_token)
            if s is None:
                s = by_token[t.input_token] = []
                slots.append(s)
            s.append(t)
        if len(slots) > 1 and not prog.host_merge and not prog.has_extras \
                and all(s[0].aux == () for s in slots):
            # distinct inputs, one program: stack along the batch-slot
            # dim, ONE vmapped launch, split states/rows per task
            try:
                if prog.kind == "agg":
                    bprog = get_batched_program(lead.dag, lead.mesh,
                                                len(slots))
                else:
                    bprog = get_batched_rows_program(
                        lead.dag, lead.mesh, lead.row_capacity, len(slots))
                outs = bprog([s[0].cols for s in slots],
                             [s[0].counts for s in slots])
                self._cc_note(batch, cc0)
                # coalesced attr + spans BEFORE finish (waiter race,
                # see _serve_fused)
                for t in batch:
                    t.coalesced = len(batch)
                self._mem_note(batch, lead.mesh)
                self._trace_launch(batch, t_l0,
                                   time.perf_counter_ns(), "batched")
                for s, out in zip(slots, outs):
                    for t in s:
                        t.finish((prog, out))
                self.launches += 1
                if bprog._donate_argnums:
                    # the per-launch stacked copies were donated (the
                    # lifetime plan's batched class), whatever the
                    # member arrays' own lifetime
                    self.donated_launches += 1
                self.batched_launches += 1
                if prog.kind == "rows":
                    self.batched_rows_launches += 1
                self._m_launch.inc(mode="batched")
                return
            except Exception:   # planlint: ok - vmap capability probe;
                pass        # op not vmappable on this backend: launch
                            # apart below (same results, no batching win)
        first = True
        for s in slots:
            t_s0 = t_l0 if first else time.perf_counter_ns()
            first = False
            out = prog(s[0].cols, s[0].counts, s[0].aux)
            # cumulative from the group's entry: a later slot DID wait
            # on the earlier slots' (and the lead's) resolve/compile
            self._cc_note(s, cc0)
            if len(batch) > 1:
                # BEFORE finish (waiter race, see _serve_fused)
                for t in s:
                    t.coalesced = len(batch)
            self._mem_note(s, lead.mesh)
            self._trace_launch(s, t_s0, time.perf_counter_ns(),
                               "coalesced" if len(s) > 1 else "single")
            for t in s:
                t.finish((prog, out))
            self.launches += 1
            if prog._donate_argnums:
                self.donated_launches += 1
            self._m_launch.inc(
                mode="coalesced" if len(s) > 1 else "single")

    def _note_coalesce(self, batch: list) -> None:
        if len(batch) > 1:
            self.coalesced_launches += 1
            self.coalesced_tasks += len(batch)
            self._m_coal.inc(len(batch))
            for t in batch:
                t.coalesced = len(batch)

    def _attribute_launch(self, batch: list, wall_ns: int) -> None:
        """Split one launch's measured wall time across its members by
        marginal bytes — the shared scan belongs to the lead, each
        rider weighs what it ADDED — so per-group and per-digest device
        time stays honest under fusion/coalescing instead of landing
        wholesale on whichever member's group drained the batch."""
        lead = batch[0]
        weights = [lead.cost.peak_hbm_bytes if lead.cost is not None
                   else 0]
        weights += [self._marginal_bytes(t, lead) for t in batch[1:]]
        for t, ns in zip(batch, split_device_time(weights, wall_ns)):
            t.device_ns = ns
        if self.calibration_enable:
            self._observe_launch(batch)
        if self.hbm_enable:
            try:
                self._observe_roofline(batch)
            except Exception:   # noqa: BLE001 - pure observability: a
                # failed attribution (exotic backend, microbench
                # refusal) must never kill the drain thread
                pass

    def _observe_launch(self, batch: list) -> None:
        """copmeter feedback: each SERVED member's attributed wall time
        EWMAs into its digest's correction against the STATIC cost
        (cost_static, never the already-corrected one — feedback must
        not compound on itself), then throttle-persists through the
        copforge manifest so calibration survives restarts."""
        from ..analysis.calibrate import correction_store
        store = correction_store()
        fed = False
        for t in batch:
            if t.failed or t.device_ns <= 0 or t.cost_static is None \
                    or t.compile_miss:
                # cold launches measure the COMPILER, not the program
                # (compile_wait is already split out for EXPLAIN; the
                # wall split here still contains the trace) — only
                # warm launches feed the loop
                continue
            digest = self._stable_digest(t)
            if digest is None:
                continue
            store.observe(digest, t.cost_static, t.device_ns)
            fed = True
        # copgauge: the measured launch watermark EWMAs the digest's
        # mem_factor (clamped, exactly like time_factor) — only for
        # single-program launches, where the measured executable IS the
        # digest's program (a fused measure would mis-attribute every
        # member); riders share the lead's key, so one feed per launch
        lead = batch[0]
        if self.hbm_enable and lead.hbm_measured \
                and not lead.failed and lead.cost_static is not None \
                and all(t.key == lead.key for t in batch):
            digest = self._stable_digest(lead)
            if digest is not None:
                store.observe_mem(digest, lead.cost_static,
                                  lead.hbm_measured)
                fed = True
        if fed:
            store.sync_manifest()

    def _observe_roofline(self, batch: list) -> None:
        """copgauge roofline feedback: each warm measured member's
        attributed wall time + static work terms land in the per-digest
        utilization store (obs/roofline), classifying the digest
        memory-/compute-/launch-bound against the backend peak table."""
        from ..obs.roofline import peaks_for_mesh, roofline_store
        roof = roofline_store()
        for t in batch:
            if t.failed or t.device_ns <= 0 or t.cost_static is None \
                    or t.compile_miss:
                continue
            digest = self._stable_digest(t)
            if digest is None:
                continue
            roof.observe(digest, t.cost_static, t.device_ns,
                         peaks_for_mesh(t.mesh),
                         measured_hbm=t.hbm_measured)

    def _account(self, batch: list) -> None:
        """Post-launch bookkeeping.  RUs were PRICED at submit and
        DEBITED at batch admission (t.rus_charged — rc/pricing from the
        static LaunchCost; the old est_rows/100+1 post-hoc charge is
        retired); this only mirrors them into the per-group stat and
        the tidb_tpu_sched_ru_total counter /sched consumers read."""
        with self._mu:
            for t in batch:
                self.tasks_done += 1
                if t.cost is not None:
                    # per-link attribution: each task's own collective
                    # payload (merge psums, exchanges) — riders pay
                    # theirs, the shared scan's H2D stays intra
                    ici, dci = t.cost.ici_bytes, t.cost.dci_bytes
                    self.transfer_ici_bytes += ici
                    self.transfer_dci_bytes += dci
                    if ici:
                        self._m_ici.inc(ici)
                    if dci:
                        self._m_dci.inc(dci)
                if t.donate:
                    self.donated_tasks += 1
                    saved = t.cost.donated_bytes if t.cost is not None \
                        else 0
                    self.donated_bytes += saved
                    if saved:
                        self._m_donated.inc(saved)
                g = self._groups.get(t.group)
                if g is not None:
                    g.wait_ns += t.wait_ns
                    g.rus += t.rus_charged
                    g.device_ns += t.device_ns
                if t.key is not None and t.device_ns:
                    # bounded + LRU (BoundedLRU, the calibration
                    # store's eviction policy) — no more unbounded
                    # per-digest growth, no more wholesale clear()
                    dk = f"{t.key[0] & 0xffffffffffffffff:016x}"
                    self._digest_ns.bump(dk, t.device_ns)
                self._wait_ring.append(t.wait_ns)
                self._m_wait.observe(t.wait_ns / 1e9)
                self._m_wait_ms.observe(t.wait_ns / 1e6)
                self._m_ru.inc(t.rus_charged, group=t.group)

    # ------------------------------------------------------------- #
    # introspection
    # ------------------------------------------------------------- #

    @property
    def depth(self) -> int:
        return self._depth

    def _calibration_stats(self) -> dict:
        from ..analysis.calibrate import correction_store
        return {"enabled": self.calibration_enable,
                **correction_store().stats()}

    def _hbm_stats(self) -> dict:
        out = {"enabled": self.hbm_enable}
        led = self._ledger_obj
        if led is not None:
            out.update(led.stats())
        return out

    def _pd_stats(self) -> dict:
        """coplace: the /sched ``pd`` section — membership + quota
        shares per attached coordinator (the full store dump lives on
        /pd).  Pure local state, no store I/O from the stats path."""
        if not self.pd_enable:
            return {"enabled": False}
        from ..pd import coordinators
        out = {"enabled": True, "members": []}
        for c in coordinators():
            out["members"].append({
                "member_id": c.member.member_id,
                "epoch": c.member.epoch,
                "degraded": c.member.degraded,
                "degraded_total": c.member.degraded_total,
                "sync_total": c.sync_total,
                "quota_shares": dict(sorted(c.quota.shares.items())),
                "peer_warm": c.registry.peer_warm,
                "claim_denials": c.registry.claim_denials,
            })
        return out

    @staticmethod
    def _pct(samples: list, q: float) -> float:
        if not samples:
            return 0.0
        i = min(int(q * len(samples)), len(samples) - 1)
        return samples[i]

    def stats(self) -> dict:
        with self._mu:
            waits = sorted(self._wait_ring)
            return {
                "queue_depth": self._depth,
                "max_depth": self.max_depth,
                "max_coalesce": self.max_coalesce,
                "fusion": self.fusion_enable,
                "window_us": self.window_us,
                "launches": self.launches,
                "coalesced_launches": self.coalesced_launches,
                "coalesced_tasks": self.coalesced_tasks,
                "batched_launches": self.batched_launches,
                "batched_rows_launches": self.batched_rows_launches,
                "fused_launches": self.fused_launches,
                "fused_tasks": self.fused_tasks,
                "window_waits": self.window_waits,
                "window_hits": self.window_hits,
                "busy_rejects": self.busy_rejects,
                "hbm_budget": self.effective_budget(),
                "budget_admitted": self.budget_admitted,
                "budget_rejects": self.budget_rejects,
                "budget_deferrals": self.budget_deferrals,
                "last_launch_bytes": self.last_launch_bytes,
                "transfer_ici_bytes": self.transfer_ici_bytes,
                "transfer_dci_bytes": self.transfer_dci_bytes,
                "donated_launches": self.donated_launches,
                "donated_tasks": self.donated_tasks,
                "donated_bytes": self.donated_bytes,
                # copforge (compilecache/): drain-paid resolve time +
                # predicted-fusion background warms
                "compile_ms_total": round(self.compile_ns_total / 1e6, 3),
                "warm_predicted": self.warm_predicted,
                "warm_failures": self.warm_failures,
                # launch supervision (faultline): retry/bisect/breaker
                "retried_launches": self.retried_launches,
                "retried_tasks": self.retried_tasks,
                "bisected_launches": self.bisected_launches,
                "quarantined": self.quarantined,
                "value_drifts": self.value_drifts,
                "breaker": self.breaker.snapshot(),
                "faults": _faults.stats(),   # None when unarmed
                "rc_enable": self.rc_enable,
                "rc_overdraft_ru": self.rc_overdraft_ru,
                "rc_throttled": self.rc_throttled,
                "rc_exhausted": self.rc_exhausted,
                "rc_debited_ru": round(self.rc_debited_ru, 2),
                # copmeter (analysis/calibrate): closed-loop state
                "calibration": self._calibration_stats(),
                # copgauge (obs/hbm): the live device-memory ledger
                "hbm": self._hbm_stats(),
                # coplace (pd/): coordination-plane membership
                "pd": self._pd_stats(),
                "oom_faults": self.oom_faults,
                "oom_demuxed": self.oom_demuxed,
                "shed_rejects": self.shed_rejects,
                "backlog_ms": round(self._backlog_ns / 1e6, 3),
                "digest_device_ms": {
                    dk: round(ns / 1e6, 3) for dk, ns in sorted(
                        self._digest_ns.items(),
                        key=lambda kv: -kv[1])[:8]},
                "tasks_done": self.tasks_done,
                "wait_p50_ms": round(self._pct(waits, 0.50) / 1e6, 3),
                "wait_p99_ms": round(self._pct(waits, 0.99) / 1e6, 3),
                "groups": {
                    g.name: {"weight": g.weight, "tasks": g.tasks,
                             "queued": len(g.queue),
                             "wait_ms": round(g.wait_ns / 1e6, 3),
                             "rus": round(g.rus, 2),
                             "throttled": g.throttled,
                             "device_ms": round(g.device_ns / 1e6, 3)}
                    for g in self._groups.values()},
            }


# --------------------------------------------------------------------- #
# per-mesh registry: the scheduler is the mesh's single device executor
# --------------------------------------------------------------------- #

_REGISTRY: dict = {}
_REG_MU = threading.Lock()


def scheduler_for(mesh) -> DeviceScheduler:
    """The (process-wide) scheduler owning launches onto `mesh`.  Keyed
    by the mesh FINGERPRINT (axis names + shape + device ids), not
    id(mesh): device capacity belongs to the chips, so every Domain —
    and every rebuilt Mesh object over the same chips — must share one
    admission queue, and an id() key could false-hit when the allocator
    reuses a dead mesh's address (the columnar device-cache bug)."""
    from .task import mesh_fingerprint
    fp = mesh_fingerprint(mesh)
    with _REG_MU:
        s = _REGISTRY.get(fp)
        if s is None:
            s = _REGISTRY[fp] = DeviceScheduler()
        return s


def breaker_snapshot_all() -> dict:
    """Merged breaker view across every registered scheduler (the
    retry-daemon's last-probe summary and /sched aggregation seam)."""
    with _REG_MU:
        scheds = list(_REGISTRY.values())
    out: dict = {}
    for s in scheds:
        out.update(s.breaker.snapshot())
    return out


__all__ = ["DeviceScheduler", "scheduler_for", "breaker_snapshot_all",
           "DEFAULT_QUEUE_DEPTH", "DEFAULT_MAX_COALESCE",
           "WINDOW_CAP_US", "DEFAULT_LAUNCH_RETRY_MS"]
