"""CopTask: one admission unit of device work.

Reference analog: the request level of tikv's unified read pool +
tidb's copr task queue — every coprocessor launch becomes a queued,
taggable unit instead of an ad-hoc device call.  A task is either

- *structured*: carries (dag, mesh, row_capacity, device inputs) so the
  scheduler itself resolves the compiled program (parallel/spmd cache)
  and may COALESCE it with compatible tasks from other sessions — the
  continuous-batching admission unit, or
- *opaque*: a zero-arg launch closure (shuffle/window programs whose
  signatures differ); still admission-controlled and fair-ordered, never
  coalesced.

The task key tags (program digest, capacity shape, mesh) — the same key
`spmd.get_sharded_program` caches compiled programs on — so the
scheduler can recognize "same program in flight" across sessions.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Callable, Optional

from ..obs.trace import TRACE_CTX as _TRACE_CTX

# the submitting statement's (resource group name, fair-share weight,
# rc ResourceGroup-or-None) — bound by Session.execute around each
# statement; travels into worker threads via contextvars.copy_context
# like KILL_EVENT does.  The third element is the live group object so
# the drain can consult the group's RU bucket (rc/controller) without a
# registry lookup; pre-rc 2-tuples are still accepted.
SCHED_GROUP: contextvars.ContextVar = contextvars.ContextVar(
    "sched_group", default=None)

DEFAULT_GROUP = "default"
DEFAULT_WEIGHT = 8.0


class ServerBusyError(RuntimeError):
    """Admission queue overflow: the MySQL-compatible "server is busy"
    backpressure error (TiDB error space 9003, ErrTiKVServerBusy) — the
    client should back off and retry instead of piling work onto an
    already-saturated device."""

    errno = 9003

    def __init__(self, depth: int):
        super().__init__(
            f"TiKV server is busy (device admission queue full, "
            f"depth={depth}); retry later")


class TaskCancelledError(RuntimeError):
    """The waiter was killed (KILL QUERY / connection teardown) while
    its task queued: the drain fails the lead with THIS typed error so
    the supervised retry layer — and clients — can tell cancellation
    from device failure.  Cancellation is never retried and never
    charges the program's circuit breaker."""

    def __init__(self):
        super().__init__("cop task cancelled before launch")


def current_group() -> tuple:
    """(group name, weight, rc group-or-None) of the calling statement
    context; 2-tuple bindings (pre-rc embedders) gain a None."""
    g = SCHED_GROUP.get()
    if not g:
        return DEFAULT_GROUP, DEFAULT_WEIGHT, None
    if len(g) == 2:
        return g[0], g[1], None
    return g


def _shape_sig(cols, counts) -> tuple:
    """Capacity-shape signature of the stacked device inputs: coalescing
    requires byte-identical program input shapes (the capacity half of
    the compile-cache key)."""
    sig = []
    for v, m in cols:
        sig.append((tuple(v.shape), str(v.dtype), m is None))
    return tuple(sig) + ((tuple(counts.shape),) if counts is not None
                         else ())


# id(mesh) -> fingerprint memo: id() here is only a transient cache slot
# for a live object we hold a reference to, never part of the key itself
_FP_CACHE: dict = {}


def mesh_fingerprint(mesh) -> tuple:
    """Stable identity of a device mesh: axis names, axis shape, and the
    global device ids.  Two Mesh objects over the same chips fingerprint
    identically, so task dedup/coalescing keys survive mesh rebuilds
    (a Domain re-creating its mesh after reconfig) — id(mesh) does not."""
    fp = _FP_CACHE.get(id(mesh))          # planlint: ok - memo slot only
    if fp is None:
        fp = (tuple(mesh.axis_names), tuple(mesh.devices.shape),
              tuple(int(d.id) for d in mesh.devices.reshape(-1)))
        if len(_FP_CACHE) > 16:           # meshes are few; stay tiny
            _FP_CACHE.clear()
        _FP_CACHE[id(mesh)] = fp          # planlint: ok - memo slot only
    return fp


class CopTask:
    """One queued device launch; resolved to (program, out) on wait()."""

    __slots__ = ("key", "dag", "mesh", "row_capacity", "cols", "counts",
                 "aux", "input_token", "fn", "group", "weight",
                 "submit_ns", "start_ns", "wait_ns", "coalesced", "fused",
                 "fusion_key", "cancelled", "_done", "_value", "_exc",
                 "est_rows", "cost", "cost_static", "rc_group", "rus",
                 "rus_charged", "device_ns", "deadline_ns", "svc_ns",
                 "donate", "retries", "compile_ns", "compile_miss",
                 "hbm_predicted", "hbm_measured", "value_drift", "trace")

    def __init__(self, *, key=None, dag=None, mesh=None, row_capacity=0,
                 cols=None, counts=None, aux=(), input_token=None,
                 fusion_key=None, fn: Optional[Callable[[], Any]] = None,
                 group: Optional[str] = None,
                 weight: Optional[float] = None, est_rows: int = 0,
                 rc_group=None, donate: bool = False):
        if group is None:
            group, gw, rcg = current_group()
            if weight is None:
                weight = gw
            if rc_group is None:
                rc_group = rcg
        self.key = key
        self.dag = dag
        self.mesh = mesh
        self.row_capacity = row_capacity
        self.cols = cols
        self.counts = counts
        self.aux = aux
        self.input_token = input_token
        self.fusion_key = fusion_key
        self.fn = fn
        self.group = group
        self.weight = float(weight or DEFAULT_WEIGHT)
        self.est_rows = est_rows
        self.submit_ns = time.perf_counter_ns()
        self.start_ns = 0
        self.wait_ns = 0
        self.coalesced = 1        # tasks served by this task's launch
        self.fused = 0            # member programs in this task's launch
        self.cost = None          # LaunchCost set at admission (copcost;
                                  # calibration-corrected when enabled)
        self.cost_static = None   # the uncorrected LaunchCost — the
                                  # calibration feedback baseline
                                  # (copmeter; never fed back on itself)
        self.rc_group = rc_group  # live rc ResourceGroup (bucket owner)
        self.rus = 1.0            # priced RUs, set at submit (rc/pricing)
        self.rus_charged = 0.0    # RUs actually debited at the drain
        self.device_ns = 0        # attributed share of launch wall time
        self.deadline_ns = 0      # rc max-queue deadline (0 = none)
        self.svc_ns = 0           # measured expected service time the
                                  # shedding backlog accounts (copmeter)
        self.donate = bool(donate)  # launch-unique inputs: donate them
        self.retries = 0          # transient-failure re-launches (drain)
        self.compile_ns = 0       # program resolve/compile time this
                                  # task's launch paid (copforge; 0 = warm)
        self.compile_miss = False  # launch compiled (vs warm-pool hit)
        self.hbm_predicted = 0    # admission HBM prediction (copgauge:
                                  # the calibrated peak_hbm_bytes the
                                  # budget gate enforced)
        self.hbm_measured = 0     # measured launch peak bytes, set by
                                  # the drain BEFORE finish (memory
                                  # stats delta / compiled analysis of
                                  # the served executable; 0 = none)
        self.value_drift = 0      # columns whose observed ANALYZE
                                  # watermark escaped the plan's
                                  # declared value interval (valueflow
                                  # stats drift — surfaced, never fatal)
        # copscope trace propagation (obs/): the submitting statement's
        # TraceCtx rides the task like SCHED_GROUP does, so the drain
        # thread records queue/compile/launch/retry spans under the
        # statement's dispatch span — None = untraced, zero overhead
        self.trace = _TRACE_CTX.get()
        self.cancelled = False
        self._done = threading.Event()
        self._value = None
        self._exc = None

    # -------- factory helpers -------- #

    @classmethod
    def structured(cls, dag, mesh, row_capacity, cols, counts, aux,
                   est_rows: int = 0, donate: bool = False) -> "CopTask":
        from ..copr.dag import dag_digest
        fp = mesh_fingerprint(mesh)
        sig = _shape_sig(cols, counts)
        # donation is baked into the compiled executable's input
        # aliasing, so the donating variant keys (and fuses) apart —
        # a donating and a non-donating task must never dedup together
        key = (dag_digest(dag), fp, int(row_capacity), sig, bool(donate))
        # input identity for in-flight dedup: the snapshot's resident
        # device cache returns the SAME array objects per epoch, so two
        # sessions over one snapshot share ids; the task pins the refs.
        # Identity is the POINT here (same buffers = one launch serves
        # both), so id() is correct, unlike in the persistent key above.
        token = (id(cols), id(counts), id(aux))    # planlint: ok - see above
        # cross-query fusion key (contract-aware, NO tracing): tasks
        # sharing one snapshot scan (same resident arrays = same epoch),
        # one mesh, and one capacity signature, whose chains are in the
        # fusable contract class, may compute their payloads in ONE
        # program even when their digests differ.
        fusion_key = None
        if aux == ():
            from ..analysis.contracts import fusion_signature
            fsig = fusion_signature(dag)
            if fsig is not None:
                fusion_key = (token, fp, sig, fsig, bool(donate))
        return cls(key=key, dag=dag, mesh=mesh, row_capacity=row_capacity,
                   cols=cols, counts=counts, aux=aux, input_token=token,
                   fusion_key=fusion_key, est_rows=est_rows,
                   donate=donate)

    @classmethod
    def opaque(cls, fn: Callable[[], Any], est_rows: int = 0) -> "CopTask":
        return cls(fn=fn, est_rows=est_rows)

    # -------- completion -------- #

    @property
    def done(self) -> bool:
        """Resolved (served or failed) — the supervised drain filters
        already-finished members out of a retried batch."""
        return self._done.is_set()

    @property
    def failed(self) -> bool:
        """Resolved WITH an error — failed launches must not feed the
        calibration loop (their wall time measures the failure path)."""
        return self._done.is_set() and self._exc is not None

    def finish(self, value) -> None:
        if self._done.is_set():
            return
        self._value = value
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        if self._done.is_set():      # a served task keeps its result
            return
        self._exc = exc
        self._done.set()

    def wait(self):
        """Block until the scheduler serves this task.  Cooperative with
        KILL QUERY: polls the caller's kill event between waits; a killed
        waiter marks itself cancelled so the drain loop skips it."""
        from ..copr.coordinator import QueryInterrupted, check_killed
        while not self._done.wait(0.05):
            try:
                check_killed()
            except QueryInterrupted:
                self.cancelled = True
                raise
        if self._exc is not None:
            raise self._exc
        return self._value


__all__ = ["CopTask", "ServerBusyError", "TaskCancelledError",
           "SCHED_GROUP", "current_group", "DEFAULT_GROUP",
           "DEFAULT_WEIGHT", "mesh_fingerprint"]
