"""CLI entry point: server + ecosystem tools in one binary.

Reference analog: cmd/tidb-server/main.go (serve) plus the separate
Dumpling / Lightning binaries (SURVEY.md §2.8) — subcommands of
`python -m tidb_tpu`:

  serve      start the MySQL wire server + HTTP status API
  dump       logical export from a running server (dumpling)
  import     CSV load into a running server over the wire (lightning's
             tidb backend mode)

BR-style snapshot backup/restore (tools.br.backup/restore) and the
direct-ingest import (tools.lightning.import_csv) are embedded APIs:
they operate on an in-process Domain's KV store, which has no
cross-process surface to point a standalone binary at.
"""

from __future__ import annotations

import argparse
import sys


def _domain(args=None):
    from .session.session import Domain
    data_dir = getattr(args, "data_dir", None)
    if data_dir:
        return Domain(data_dir=data_dir,
                      sync=bool(getattr(args, "sync_wal", False)))
    return Domain()


def cmd_serve(args) -> int:
    import time
    from .config import apply_to_domain, load_config
    from .server import MySQLServer, StatusServer
    cfg = load_config(getattr(args, "config", None))
    # precedence: explicit CLI flag > config file > built-in default
    # (argparse defaults are None sentinels so an explicit flag at its
    # default value still wins)
    if args.host is None:
        args.host = cfg.host
    if args.port is None:
        args.port = cfg.port
    if args.status_port is None:
        args.status_port = cfg.status_port
    if getattr(args, "data_dir", None) is None:
        args.data_dir = cfg.data_dir
    if not getattr(args, "sync_wal", False):
        args.sync_wal = cfg.sync_wal
    dom = _domain(args)
    apply_to_domain(cfg, dom)
    dom.start_background()
    srv = MySQLServer(dom, host=args.host, port=args.port)
    port = srv.start()
    st = StatusServer(dom, host=args.host, port=args.status_port)
    sport = st.start()
    print(f"tidb-tpu server listening on {args.host}:{port} "
          f"(status :{sport})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("shutting down...", flush=True)
        srv.close()
        st.close()
    return 0


def cmd_dump(args) -> int:
    """Wire-based logical export from a RUNNING server (how dumpling
    actually operates; the embedded snapshot-consistent variant is
    tools.dump_database)."""
    import csv
    import os
    from .server.client import Client
    from .sql.bind import sql_literal
    os.makedirs(args.out, exist_ok=True)
    c = Client(args.host, args.port, user=args.user,
               password=args.password, db=args.db)
    tables = [r[0] for r in c.query("show tables")]
    total = 0
    for t in tables:
        cols = [r[0] for r in c.query(f"show columns from {t}")]
        rows = c.query(f"select * from {t}")
        total += len(rows)
        path = os.path.join(args.out, f"{args.db}.{t}.000000000.{args.format}")
        if args.format == "csv":
            with open(path, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(cols)
                for r in rows:
                    w.writerow(["\\N" if v is None else v for v in r])
        else:
            with open(path, "w") as f:
                for off in range(0, len(rows), 200):
                    chunk = rows[off:off + 200]
                    vals = ",\n".join(
                        "(" + ",".join(sql_literal(v) for v in r) + ")"
                        for r in chunk)
                    if chunk:
                        f.write(f"INSERT INTO `{t}` VALUES\n{vals};\n")
    c.close()
    print(f"dumped {total} rows from {len(tables)} tables to {args.out}")
    return 0


def cmd_import(args) -> int:
    """Wire-based CSV load (lightning's 'tidb' backend: batched INSERTs
    through the SQL path; the direct-KV local backend is the embedded
    tools.lightning.import_csv)."""
    import csv
    from .server.client import Client
    from .sql.bind import sql_literal
    c = Client(args.host, args.port, user=args.user,
               password=args.password, db=args.db)
    with open(args.file, newline="") as f:
        rows = list(csv.reader(f))
    if rows:
        rows = rows[1:]  # header
    total = 0
    for off in range(0, len(rows), args.batch):
        chunk = rows[off:off + args.batch]
        vals = ",".join(
            "(" + ",".join("NULL" if v in ("", "\\N") else sql_literal(v)
                           for v in r) + ")"
            for r in chunk)
        c.execute(f"insert into {args.table} values {vals}")
        total += len(chunk)
    c.close()
    print(f"imported {total} rows into {args.db}.{args.table}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tidb_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="run the MySQL wire server")
    s.add_argument("--host", default=None)
    s.add_argument("--port", type=int, default=None)
    s.add_argument("--status-port", type=int, default=None)
    s.add_argument("--config", default=None,
                   help="TOML config file (pkg/config analog)")
    s.add_argument("--data-dir", default=None,
                   help="durable storage dir (WAL + catalog-on-KV); "
                        "omit for in-memory")
    s.add_argument("--sync-wal", action="store_true",
                   help="fdatasync every commit record")
    s.set_defaults(fn=cmd_serve)

    d = sub.add_parser("dump", help="logical export from a running "
                                    "server (dumpling)")
    d.add_argument("--host", default="127.0.0.1")
    d.add_argument("--port", type=int, default=4000)
    d.add_argument("--user", default="root")
    d.add_argument("--password", default="")
    d.add_argument("--db", default="test")
    d.add_argument("--out", required=True)
    d.add_argument("--format", choices=("sql", "csv"), default="sql")
    d.set_defaults(fn=cmd_dump)

    i = sub.add_parser("import", help="CSV load into a running server "
                                      "(lightning tidb-backend mode)")
    i.add_argument("--host", default="127.0.0.1")
    i.add_argument("--port", type=int, default=4000)
    i.add_argument("--user", default="root")
    i.add_argument("--password", default="")
    i.add_argument("--db", default="test")
    i.add_argument("--table", required=True)
    i.add_argument("--file", required=True)
    i.add_argument("--batch", type=int, default=200)
    i.set_defaults(fn=cmd_import)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
