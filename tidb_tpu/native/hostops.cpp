// Host-side columnar aggregation primitives (C++17, ctypes ABI).
//
// Reference analog: the tight per-row loops inside TiDB's hash
// aggregation executor (pkg/executor/aggregate/agg_hash_executor.go:94)
// and unistore's coprocessor closure executor (closure_exec.go:468).
// The TPU engine's CPU fallback routes high-NDV group-by through
// np.bincount, whose mandatory weight/bin dtype conversions cost 3-4x
// the compulsory memory traffic; these loops count straight off the
// narrow physical column representation (chunk/column.py narrowed()).
//
// Counts use an int32 table: the engine bounds rows per batch below
// 2^31 (the limb-exact SUM fence), so no group count can overflow.

#include <cstdint>
#include <cstring>

extern "C" {

// table[key[i] - lo]++ for every i; caller zeroes `table` (size `range`).
void hops_count_i32(const int32_t* keys, int64_t n, int64_t lo,
                    int32_t* table) {
    for (int64_t i = 0; i < n; i++) table[keys[i] - lo]++;
}

void hops_count_i64(const int64_t* keys, int64_t n, int64_t lo,
                    int32_t* table) {
    for (int64_t i = 0; i < n; i++) table[keys[i] - lo]++;
}

// inv[i] = lookup[key[i] - lo] (dense group-id assignment through the
// occupied-slot lookup built from the count table).
void hops_gather_i32(const int32_t* keys, int64_t n, int64_t lo,
                     const int32_t* lookup, int64_t* inv) {
    for (int64_t i = 0; i < n; i++) inv[i] = lookup[keys[i] - lo];
}

void hops_gather_i64(const int64_t* keys, int64_t n, int64_t lo,
                     const int32_t* lookup, int64_t* inv) {
    for (int64_t i = 0; i < n; i++) inv[i] = lookup[keys[i] - lo];
}

}  // extern "C"
