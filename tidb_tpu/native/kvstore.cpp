// Percolator-model MVCC key-value engine.
//
// Reference analog: the in-process storage engine
// pkg/store/mockstore/unistore/tikv/mvcc.go (MVCCStore over badger +
// lockstore) and, behind it, TiKV's txn model: three logical column
// families —
//   data:  (key, start_ts)  -> row value
//   lock:  key              -> {start_ts, primary, op}
//   write: (key, commit_ts) -> {start_ts, op}
// with the 2PC protocol: Prewrite (lock + stage data), Commit (write
// record + unlock), Rollback, and snapshot reads that see the latest
// commit <= read_ts and fail on conflicting locks.
//
// This is a fresh C++17 implementation designed for the TPU framework's
// host runtime: an ordered std::map keyed by user key holding per-key
// version chains (newest-first vectors), guarded by a shared_mutex.  It is
// the transactional row store whose snapshots feed columnarization
// (store/columnar.py); the C ABI below is consumed via ctypes
// (tidb_tpu/store/kv.py).  Scan results are returned through a per-call
// arena so no allocation contracts cross the FFI.

// Durability (reference: unistore's badger-backed MVCC persists all CFs,
// mvcc.go:50): committed writes stream to a write-ahead log; kv_checkpoint
// compacts the whole committed state into a snapshot file and truncates
// the WAL.  In-flight (locked, uncommitted) state is intentionally NOT
// logged — the client lives in the same process, so a crash aborts its
// open transactions exactly like percolator lock cleanup would.
//
// File layout at <path>: "<path>.snap" (replayable compacted stream) +
// "<path>.wal" (appended commit records).  Record:
//   [u8 op][u64 start_ts][u64 commit_ts][u32 klen][u32 vlen][key][value]
// A torn tail record (crash mid-append) is detected and ignored.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <chrono>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

#ifdef _WIN32
#else
#include <unistd.h>
#endif

namespace {

enum ErrCode : int32_t {
  OK = 0,
  ERR_LOCKED = 1,          // conflicting lock -> caller backs off
  ERR_WRITE_CONFLICT = 2,  // newer commit than start_ts
  ERR_NOT_FOUND = 3,
  ERR_TXN_MISMATCH = 4,    // commit/rollback without matching lock
  ERR_ALREADY_ROLLED_BACK = 5,
  ERR_DEADLOCK = 6,        // waits-for cycle: requester is the victim
  ERR_LOCK_WAIT_TIMEOUT = 7,
  ERR_WAL = 8,             // WAL write failed: durability lost, commit refused
};

enum Op : uint8_t { OP_PUT = 0, OP_DELETE = 1, OP_ROLLBACK = 2 };

struct Lock {
  uint64_t start_ts = 0;
  std::string primary;
  Op op = OP_PUT;
  std::string value;  // staged data
  bool present = false;
  // pessimistic locks (tikv KvPessimisticLock analog): taken at DML time,
  // upgraded to a prewrite lock at commit, never staged to the WAL
  bool pessimistic = false;
};

struct WriteRec {
  uint64_t commit_ts;
  uint64_t start_ts;
  Op op;
};

struct VersionChain {
  Lock lock;
  // newest-first by commit_ts
  std::vector<WriteRec> writes;
  // staged/committed values keyed by start_ts
  std::map<uint64_t, std::string> data;
};

// ------------------------------------------------------------------ //
// LSM layer: immutable sorted runs (badger-LSM analog, mvcc.go:50).
//
// The mutable std::map is the MEMTABLE: all locks and fresh writes live
// there.  kv_flush freezes every unlocked key's committed chain into an
// immutable Run — flat sorted key array + columnar version arrays + a
// bloom filter — and erases it from the memtable.  Reads merge memtable
// (newest) over runs (newest-last); compaction merges runs and applies
// the GC safepoint as a filter (gc-by-compaction, not whole-store scan).
// Runs are an in-memory layout: durability stays WAL + checkpoint.
// ------------------------------------------------------------------ //

struct Run {
  std::vector<std::string> keys;      // sorted ascending
  std::vector<uint32_t> woff;         // keys.size()+1 prefix offsets
  std::vector<WriteRec> writes;       // per key newest-first
  std::vector<std::string> vals;      // parallel to writes (PUT payload)
  std::vector<uint64_t> bloom;        // bit array, power-of-two words
  uint64_t bloom_mask = 0;

  static uint64_t h1(const std::string& k) {
    uint64_t h = 1469598103934665603ull;
    for (char c : k) { h ^= static_cast<uint8_t>(c); h *= 1099511628211ull; }
    return h;
  }
  void bloom_build() {
    size_t bits = 64;
    while (bits < keys.size() * 10) bits <<= 1;
    bloom.assign(bits / 64, 0);
    bloom_mask = bits - 1;
    for (const auto& k : keys) {
      uint64_t a = h1(k), b = a * 0x9e3779b97f4a7c15ull + 1;
      bloom[(a & bloom_mask) >> 6] |= 1ull << (a & 63);
      bloom[(b & bloom_mask) >> 6] |= 1ull << (b & 63);
    }
  }
  bool maybe(const std::string& k) const {
    if (bloom.empty()) return false;
    uint64_t a = h1(k), b = a * 0x9e3779b97f4a7c15ull + 1;
    return (bloom[(a & bloom_mask) >> 6] >> (a & 63) & 1)
        && (bloom[(b & bloom_mask) >> 6] >> (b & 63) & 1);
  }
  // index of k, or -1 (binary search over the flat sorted array)
  int64_t find(const std::string& k) const {
    auto it = std::lower_bound(keys.begin(), keys.end(), k);
    if (it == keys.end() || *it != k) return -1;
    return it - keys.begin();
  }
  int64_t lower(const std::string& k) const {
    return std::lower_bound(keys.begin(), keys.end(), k) - keys.begin();
  }
};

struct Store {
  std::map<std::string, VersionChain> keys;
  // immutable sorted runs, NEWEST LAST; shared_ptr so readers finishing
  // under the shared lock never race a compaction swap
  std::vector<std::shared_ptr<Run>> runs;
  uint64_t gc_safepoint = 0;
  size_t flush_threshold = 1 << 16;   // memtable keys before auto-flush
  size_t max_runs = 8;                // compaction trigger
  uint64_t commits_since_check = 0;
  mutable std::shared_mutex mu;
  uint64_t ts_counter = 1;  // simple TSO for embedded use (PD analog)
  // durability (empty path = in-memory only)
  std::string path;
  FILE* wal = nullptr;
  bool sync = false;
  bool wal_failed = false;  // a WAL write failed: refuse further commits
  // pessimistic lock waiting + deadlock detection (detector.go analog):
  // waits_for[waiter_start_ts] = holder_start_ts (a txn waits on at most
  // one key at a time, so single edges suffice)
  std::condition_variable_any lock_cv;
  std::map<uint64_t, uint64_t> waits_for;
};

// true if following waits_for edges from `from` reaches `target`
bool wf_reaches(const Store* s, uint64_t from, uint64_t target) {
  uint64_t cur = from;
  for (size_t hops = 0; hops < s->waits_for.size() + 1; ++hops) {
    auto it = s->waits_for.find(cur);
    if (it == s->waits_for.end()) return false;
    cur = it->second;
    if (cur == target) return true;
  }
  return false;
}

void apply_committed(Store* s, const std::string& key, uint64_t start_ts,
                     uint64_t commit_ts, Op op, const std::string& value) {
  auto& vc = s->keys[key];
  // replay must be idempotent and order-independent: a crash between the
  // checkpoint rename and the WAL truncation leaves records present in
  // BOTH files, so dedupe by (commit_ts, start_ts) and insert at the
  // sorted (newest-first) position rather than blindly at the front
  auto pos = vc.writes.begin();
  for (; pos != vc.writes.end(); ++pos) {
    if (pos->commit_ts == commit_ts && pos->start_ts == start_ts) return;
    if (pos->commit_ts < commit_ts) break;
  }
  if (op == OP_PUT) vc.data[start_ts] = value;
  vc.writes.insert(pos, WriteRec{commit_ts, start_ts, op});
  if (commit_ts > s->ts_counter) s->ts_counter = commit_ts;
  if (start_ts > s->ts_counter) s->ts_counter = start_ts;
}

// Serialize ONE record; returns false on any short write.  The single
// writer shared by the WAL appender and the checkpointer (the reader is
// replay_file) so the on-disk format lives in one place per direction.
bool write_record(FILE* f, const std::string& key, uint64_t start_ts,
                  uint64_t commit_ts, Op op, const std::string& value) {
  uint8_t o = static_cast<uint8_t>(op);
  uint32_t kl = key.size(), vl = (op == OP_PUT) ? value.size() : 0;
  if (std::fwrite(&o, 1, 1, f) != 1) return false;
  if (std::fwrite(&start_ts, 8, 1, f) != 1) return false;
  if (std::fwrite(&commit_ts, 8, 1, f) != 1) return false;
  if (std::fwrite(&kl, 4, 1, f) != 1) return false;
  if (std::fwrite(&vl, 4, 1, f) != 1) return false;
  if (kl && std::fwrite(key.data(), 1, kl, f) != kl) return false;
  if (vl && std::fwrite(value.data(), 1, vl, f) != vl) return false;
  return true;
}

// Append + flush one commit record.  Any failure poisons the WAL
// (wal_failed): the caller fails the commit and all later ones — never
// silently degrade to acking non-durable writes.
bool log_commit(Store* s, const std::string& key, uint64_t start_ts,
                uint64_t commit_ts, Op op, const std::string& value) {
  if (s->wal == nullptr) return true;
  bool ok = write_record(s->wal, key, start_ts, commit_ts, op, value);
  ok = ok && std::fflush(s->wal) == 0;
#ifndef _WIN32
  if (ok && s->sync) ok = fdatasync(fileno(s->wal)) == 0;
#endif
  return ok;
}

// Replay one record stream; stops cleanly at a torn tail.  Returns the
// byte offset of the last complete record so the caller can truncate the
// tear before appending (appending after garbage would strand every
// later record behind an unparseable header).
long replay_file(Store* s, const std::string& fname) {
  FILE* f = std::fopen(fname.c_str(), "rb");
  if (f == nullptr) return 0;
  long good = 0;
  for (;;) {
    uint8_t o;
    uint64_t sts, cts;
    uint32_t kl, vl;
    if (std::fread(&o, 1, 1, f) != 1) break;
    if (std::fread(&sts, 8, 1, f) != 1) break;
    if (std::fread(&cts, 8, 1, f) != 1) break;
    if (std::fread(&kl, 4, 1, f) != 1) break;
    if (std::fread(&vl, 4, 1, f) != 1) break;
    std::string key(kl, '\0'), val(vl, '\0');
    if (kl && std::fread(key.data(), 1, kl, f) != kl) break;
    if (vl && std::fread(val.data(), 1, vl, f) != vl) break;
    apply_committed(s, key, sts, cts, static_cast<Op>(o), val);
    good = std::ftell(f);
  }
  std::fclose(f);
  return good;
}

struct Arena {
  std::vector<std::string> bufs;
  const char* push(const std::string& s) {
    bufs.push_back(s);
    return bufs.back().data();
  }
};

// thread-local: each OS thread gets its own result buffer, so a kv_get
// pointer stays valid until the *same* thread's next kv_get — the ctypes
// caller copies immediately after return on that thread.
thread_local std::string g_err;

int32_t check_lock_conflict(const VersionChain& vc, uint64_t read_ts,
                            uint64_t caller_start_ts) {
  if (!vc.lock.present) return OK;
  if (vc.lock.pessimistic) return OK;  // no staged write: reads pass
  if (vc.lock.start_ts == caller_start_ts) return OK;  // own lock
  if (vc.lock.start_ts <= read_ts) return ERR_LOCKED;
  return OK;  // lock from a future txn doesn't block this snapshot
}

const WriteRec* latest_write_le(const VersionChain& vc, uint64_t ts) {
  for (const auto& w : vc.writes) {
    if (w.commit_ts <= ts && w.op != OP_ROLLBACK) return &w;
  }
  return nullptr;
}

// newest write <= ts for key across the runs (newest run first); sets
// *val to the PUT payload.  Returns false when no run holds one.
bool runs_latest_le(const Store* s, const std::string& k, uint64_t ts,
                    const WriteRec** w_out, const std::string** val_out) {
  for (auto rit = s->runs.rbegin(); rit != s->runs.rend(); ++rit) {
    const Run& r = **rit;
    if (!r.maybe(k)) continue;
    int64_t i = r.find(k);
    if (i < 0) continue;
    for (uint32_t j = r.woff[i]; j < r.woff[i + 1]; ++j) {
      const WriteRec& w = r.writes[j];
      if (w.commit_ts <= ts && w.op != OP_ROLLBACK) {
        *w_out = &w;
        *val_out = &r.vals[j];
        return true;
      }
    }
  }
  return false;
}

// conflict view for prewrite/pessimistic-lock: newest non-rollback commit
// across memtable+runs, plus whether a rollback record exists for
// start_ts.  (memtable is always newer than any run for a key.)
void conflict_view(const Store* s, const VersionChain* vc,
                   const std::string& k, uint64_t start_ts,
                   uint64_t* newest_commit, bool* rolled_back) {
  *newest_commit = 0;
  *rolled_back = false;
  auto scan_list = [&](const WriteRec* ws, size_t n) {
    for (size_t i = 0; i < n; ++i) {
      const WriteRec& w = ws[i];
      if (w.op == OP_ROLLBACK) {
        if (w.start_ts == start_ts) *rolled_back = true;
        continue;
      }
      if (*newest_commit == 0) *newest_commit = w.commit_ts;
      // keep scanning only for rollback-by-start_ts
    }
  };
  if (vc != nullptr && !vc->writes.empty())
    scan_list(vc->writes.data(), vc->writes.size());
  for (auto rit = s->runs.rbegin(); rit != s->runs.rend(); ++rit) {
    const Run& r = **rit;
    if (!r.maybe(k)) continue;
    int64_t i = r.find(k);
    if (i < 0) continue;
    scan_list(&r.writes[r.woff[i]], r.woff[i + 1] - r.woff[i]);
  }
}

// GC filter shared by kv_gc (memtable) and compaction (runs): which
// writes of a newest-first chain survive `safepoint`.
std::vector<char> gc_live_mask(const std::vector<WriteRec>& ws,
                               uint64_t safepoint) {
  const WriteRec* keep = nullptr;
  for (const auto& w : ws)
    if (w.commit_ts <= safepoint && w.op != OP_ROLLBACK) { keep = &w; break; }
  std::vector<char> live(ws.size(), 0);
  for (size_t i = 0; i < ws.size(); ++i) {
    const WriteRec& w = ws[i];
    live[i] = w.commit_ts > safepoint
              || (keep && w.op != OP_ROLLBACK
                  && w.commit_ts == keep->commit_ts);
  }
  return live;
}

// merge every run (newest-last) into one, applying the GC safepoint as a
// compaction filter.  Caller holds the unique lock.
int64_t compact_runs(Store* s) {
  if (s->runs.size() <= 1 && s->gc_safepoint == 0) return 0;
  auto merged = std::make_shared<Run>();
  int64_t dropped = 0;
  // per-run cursors over sorted keys
  std::vector<size_t> cur(s->runs.size(), 0);
  for (;;) {
    const std::string* next = nullptr;
    for (size_t r = 0; r < s->runs.size(); ++r) {
      if (cur[r] >= s->runs[r]->keys.size()) continue;
      const std::string& k = s->runs[r]->keys[cur[r]];
      if (next == nullptr || k < *next) next = &k;
    }
    if (next == nullptr) break;
    std::string key = *next;
    // newest-first chain: newest run's records first
    std::vector<WriteRec> ws;
    std::vector<std::string> vs;
    for (size_t r = s->runs.size(); r-- > 0;) {
      Run& src = *s->runs[r];
      if (cur[r] >= src.keys.size() || src.keys[cur[r]] != key) continue;
      size_t i = cur[r]++;
      for (uint32_t j = src.woff[i]; j < src.woff[i + 1]; ++j) {
        ws.push_back(src.writes[j]);
        vs.push_back(std::move(src.vals[j]));
      }
    }
    auto live = gc_live_mask(ws, s->gc_safepoint);
    std::vector<WriteRec> kept_w;
    std::vector<std::string> kept_v;
    for (size_t i = 0; i < ws.size(); ++i) {
      if (live[i]) {
        kept_w.push_back(ws[i]);
        kept_v.push_back(std::move(vs[i]));
      } else {
        ++dropped;
      }
    }
    // fully dead key (tombstoned before the safepoint): drop entirely
    bool all_dead = true;
    for (const auto& w : kept_w)
      if (w.op != OP_ROLLBACK) { all_dead = false; break; }
    if (kept_w.empty() || all_dead) continue;
    merged->keys.push_back(std::move(key));
    for (size_t i = 0; i < kept_w.size(); ++i) {
      merged->writes.push_back(kept_w[i]);
      merged->vals.push_back(std::move(kept_v[i]));
    }
    merged->woff.push_back(
        static_cast<uint32_t>(merged->writes.size()));
  }
  // woff holds per-key END offsets so far; convert to prefix offsets
  std::vector<uint32_t> off(merged->keys.size() + 1, 0);
  for (size_t i = 0; i < merged->keys.size(); ++i)
    off[i + 1] = merged->woff[i];
  merged->woff = std::move(off);
  merged->bloom_build();
  s->runs.clear();
  if (!merged->keys.empty()) s->runs.push_back(std::move(merged));
  return dropped;
}

// freeze every unlocked memtable key's committed chain into a new run.
// Caller holds the unique lock.  Returns keys moved.
int64_t flush_memtable(Store* s) {
  auto run = std::make_shared<Run>();
  run->woff.push_back(0);
  for (auto it = s->keys.begin(); it != s->keys.end();) {
    VersionChain& vc = it->second;
    if (vc.lock.present || vc.writes.empty()) { ++it; continue; }
    run->keys.push_back(it->first);
    for (const auto& w : vc.writes) {
      run->writes.push_back(w);
      auto dit = vc.data.find(w.start_ts);
      run->vals.push_back(
          (w.op == OP_PUT && dit != vc.data.end()) ? dit->second
                                                   : std::string());
    }
    run->woff.push_back(static_cast<uint32_t>(run->writes.size()));
    it = s->keys.erase(it);
  }
  if (run->keys.empty()) return 0;
  int64_t moved = static_cast<int64_t>(run->keys.size());
  run->bloom_build();
  s->runs.push_back(std::move(run));
  if (s->runs.size() > s->max_runs) compact_runs(s);
  return moved;
}

}  // namespace

extern "C" {

void* kv_open() { return new Store(); }

// Durable open: replay <path>.snap + <path>.wal, then append to the WAL.
// sync != 0 fdatasyncs every commit record (fflush-only otherwise).
void* kv_open_at(const char* path, int32_t plen, uint8_t sync) {
  auto* s = new Store();
  s->path.assign(path, plen);
  s->sync = sync != 0;
  replay_file(s, s->path + ".snap");
  long wal_good = replay_file(s, s->path + ".wal");
  s->ts_counter += 1;  // strictly above anything persisted
#ifndef _WIN32
  truncate((s->path + ".wal").c_str(), wal_good);  // drop any torn tail
#else
  (void)wal_good;
#endif
  s->wal = std::fopen((s->path + ".wal").c_str(), "ab");
  if (s->wal == nullptr) {  // unwritable dir/disk: fail loudly, never
    delete s;               // silently degrade to non-durable
    return nullptr;
  }
  return s;
}

// Compact the committed state into <path>.snap and truncate the WAL.
// Returns number of records written, or -1 when the store is in-memory.
int64_t kv_checkpoint(void* h) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  if (s->path.empty()) return -1;
  std::string tmp = s->path + ".snap.tmp";
  FILE* f = std::fopen(tmp.c_str(), "wb");
  if (f == nullptr) return -1;
  int64_t n = 0;
  bool ok = true;
  // runs first (older data), then memtable; replay dedupes by
  // (commit_ts, start_ts) and orders by insertion, so either works
  for (const auto& run : s->runs) {
    if (!ok) break;
    for (size_t i = 0; ok && i < run->keys.size(); ++i) {
      for (uint32_t j = run->woff[i + 1]; j-- > run->woff[i];) {
        const WriteRec& w = run->writes[j];   // oldest-first
        if (w.op == OP_ROLLBACK) continue;
        ok = write_record(f, run->keys[i], w.start_ts, w.commit_ts,
                          w.op, run->vals[j]);
        ++n;
        if (!ok) break;
      }
    }
  }
  for (const auto& [key, vc] : s->keys) {
    if (!ok) break;
    // oldest-first so replay's insertion rebuilds newest-first
    for (auto it = vc.writes.rbegin(); ok && it != vc.writes.rend(); ++it) {
      if (it->op == OP_ROLLBACK) continue;
      std::string val;
      if (it->op == OP_PUT) {
        auto dit = vc.data.find(it->start_ts);
        if (dit == vc.data.end()) continue;
        val = dit->second;
      }
      ok = write_record(f, key, it->start_ts, it->commit_ts, it->op, val);
      ++n;
    }
  }
  ok = ok && std::fflush(f) == 0;
#ifndef _WIN32
  ok = ok && fdatasync(fileno(f)) == 0;
#endif
  std::fclose(f);
  if (!ok) {                       // partial snapshot: keep .snap + WAL
    std::remove(tmp.c_str());
    return -2;
  }
  if (std::rename(tmp.c_str(), (s->path + ".snap").c_str()) != 0) {
    std::remove(tmp.c_str());
    return -2;
  }
  if (s->wal != nullptr) {
    std::fclose(s->wal);
    s->wal = std::fopen((s->path + ".wal").c_str(), "wb");  // truncate
    if (s->wal == nullptr) {
      s->wal_failed = true;
      return -2;  // caller must treat as fatal
    }
  }
  return n;
}

void kv_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (s->wal != nullptr) std::fclose(s->wal);
  delete s;
}

uint64_t kv_alloc_ts(void* h) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  return ++s->ts_counter;
}

// Prewrite one mutation. op: 0=put, 1=delete.
int32_t kv_prewrite(void* h, const char* key, int32_t klen, const char* val,
                    int32_t vlen, const char* primary, int32_t plen,
                    uint64_t start_ts, uint8_t op) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  std::string k(key, klen);
  auto& vc = s->keys[k];
  if (vc.lock.present && vc.lock.start_ts != start_ts) {
    return ERR_LOCKED;
  }
  // prewriting over our own pessimistic lock skips the conflict check:
  // kv_pessimistic_lock already validated against for_update_ts, and
  // commits in (start_ts, for_update_ts] are permitted in this mode
  bool own_pess = vc.lock.present && vc.lock.pessimistic
                  && vc.lock.start_ts == start_ts;
  uint64_t newest = 0;
  bool rolled_back = false;
  conflict_view(s, &vc, k, start_ts, &newest, &rolled_back);
  if (!own_pess) {
    // write conflict: any commit after start_ts (memtable or runs)
    for (const auto& w : vc.writes) {
      if (w.commit_ts > start_ts && w.op == OP_ROLLBACK
          && w.start_ts == start_ts) {
        return ERR_ALREADY_ROLLED_BACK;
      }
      break;
    }
    if (newest > start_ts) return ERR_WRITE_CONFLICT;
  }
  // rollback record for this exact start_ts => txn was aborted
  if (rolled_back) return ERR_ALREADY_ROLLED_BACK;
  vc.lock.present = true;
  vc.lock.pessimistic = false;   // upgrade: pessimistic -> prewrite lock
  vc.lock.start_ts = start_ts;
  vc.lock.primary.assign(primary, plen);
  vc.lock.op = static_cast<Op>(op);
  vc.lock.value.assign(val ? val : "", val ? vlen : 0);
  return OK;
}

int32_t kv_commit(void* h, const char* key, int32_t klen, uint64_t start_ts,
                  uint64_t commit_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto it = s->keys.find(std::string(key, klen));
  if (it == s->keys.end()) return ERR_TXN_MISMATCH;
  auto& vc = it->second;
  if (!vc.lock.present || vc.lock.start_ts != start_ts) {
    // idempotent commit: already committed?
    for (const auto& w : vc.writes) {
      if (w.start_ts == start_ts && w.op != OP_ROLLBACK) return OK;
    }
    return ERR_TXN_MISMATCH;
  }
  if (vc.lock.pessimistic) return ERR_TXN_MISMATCH;  // prewrite first
  if (s->wal_failed) return ERR_WAL;
  // log BEFORE applying: a failed WAL write must fail the commit, not
  // silently ack a non-durable one
  if (s->wal != nullptr) {
    if (!log_commit(s, it->first, start_ts, commit_ts, vc.lock.op,
                    vc.lock.value)) {
      s->wal_failed = true;
      return ERR_WAL;
    }
  }
  if (vc.lock.op == OP_PUT) {
    vc.data[start_ts] = std::move(vc.lock.value);
  }
  vc.writes.insert(vc.writes.begin(),
                   WriteRec{commit_ts, start_ts, vc.lock.op});
  vc.lock = Lock{};
  s->lock_cv.notify_all();
  // amortized auto-flush: freeze the memtable once it outgrows the
  // threshold (checked every 1024 commits to keep the hot path flat)
  if (++s->commits_since_check >= 1024) {
    s->commits_since_check = 0;
    if (s->keys.size() >= s->flush_threshold) flush_memtable(s);
  }
  return OK;
}

int32_t kv_rollback(void* h, const char* key, int32_t klen,
                    uint64_t start_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto& vc = s->keys[std::string(key, klen)];
  if (vc.lock.present && vc.lock.start_ts == start_ts) {
    vc.lock = Lock{};
    s->lock_cv.notify_all();
  }
  // tombstone so a late prewrite of the same txn fails
  vc.writes.insert(vc.writes.begin(),
                   WriteRec{start_ts, start_ts, OP_ROLLBACK});
  vc.data.erase(start_ts);
  return OK;
}

// Snapshot point get.  out/out_len point into a thread-local buffer valid
// until the next kv_get on the same thread.
int32_t kv_get(void* h, const char* key, int32_t klen, uint64_t ts,
               const char** out, int32_t* out_len) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  std::string k(key, klen);
  auto it = s->keys.find(k);
  if (it != s->keys.end()) {
    const auto& vc = it->second;
    int32_t lc = check_lock_conflict(vc, ts, 0);
    if (lc != OK) return lc;
    const WriteRec* w = latest_write_le(vc, ts);
    if (w != nullptr) {   // memtable writes are newer than any run's
      if (w->op == OP_DELETE) return ERR_NOT_FOUND;
      auto dit = vc.data.find(w->start_ts);
      if (dit == vc.data.end()) return ERR_NOT_FOUND;
      g_err = dit->second;
      *out = g_err.data();
      *out_len = static_cast<int32_t>(g_err.size());
      return OK;
    }
  }
  const WriteRec* w = nullptr;
  const std::string* val = nullptr;
  if (!runs_latest_le(s, k, ts, &w, &val)) return ERR_NOT_FOUND;
  if (w->op == OP_DELETE) return ERR_NOT_FOUND;
  g_err = *val;
  *out = g_err.data();
  *out_len = static_cast<int32_t>(g_err.size());
  return OK;
}

// Snapshot range scan [start, end).  Returns number of pairs (<= limit),
// or the negative error code on lock conflict.  Results are written as
// length-prefixed records into the caller-provided buffer:
//   [u32 klen][key][u32 vlen][value] ...
// If the buffer is too small, returns what fits and sets *truncated=1 with
// *resume_key of the next key (paging analog).
int32_t kv_scan(void* h, const char* start, int32_t slen, const char* end,
                int32_t elen, uint64_t ts, int32_t limit, char* buf,
                int64_t buf_cap, int64_t* used, uint8_t* truncated) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  std::string sk(start, slen), ek(end, elen);
  auto it = s->keys.lower_bound(sk);
  // k-way merge: memtable iterator + one cursor per run (runs sorted)
  std::vector<size_t> rcur(s->runs.size());
  for (size_t r = 0; r < s->runs.size(); ++r)
    rcur[r] = static_cast<size_t>(s->runs[r]->lower(sk));
  int32_t n = 0;
  int64_t off = 0;
  *truncated = 0;
  while (n < limit) {
    // smallest key across sources
    const std::string* next = nullptr;
    bool from_mem = false;
    if (it != s->keys.end() && (ek.empty() || it->first < ek)) {
      next = &it->first;
      from_mem = true;
    }
    for (size_t r = 0; r < s->runs.size(); ++r) {
      const Run& run = *s->runs[r];
      if (rcur[r] >= run.keys.size()) continue;
      const std::string& k = run.keys[rcur[r]];
      if (!ek.empty() && k >= ek) continue;
      if (next == nullptr || k < *next) {
        next = &k;
        from_mem = false;
      }
    }
    if (next == nullptr) break;
    std::string key = *next;
    // resolve version: memtable first (newer), then runs
    const std::string* val = nullptr;
    bool deleted = false;
    if (from_mem || (it != s->keys.end() && it->first == key)) {
      const auto& vc = it->second;
      if (check_lock_conflict(vc, ts, 0) != OK) return -ERR_LOCKED;
      const WriteRec* w = latest_write_le(vc, ts);
      if (w != nullptr) {
        if (w->op == OP_DELETE) {
          deleted = true;
        } else {
          auto dit = vc.data.find(w->start_ts);
          if (dit != vc.data.end()) val = &dit->second;
          else deleted = true;
        }
      }
      ++it;
    }
    if (val == nullptr && !deleted) {
      // run cursors already sit on this key: resolve newest-run-first
      // without re-searching (the per-key binary search would dominate)
      for (size_t r = s->runs.size(); r-- > 0 && val == nullptr
                                      && !deleted;) {
        const Run& run = *s->runs[r];
        if (rcur[r] >= run.keys.size() || run.keys[rcur[r]] != key)
          continue;
        for (uint32_t j = run.woff[rcur[r]];
             j < run.woff[rcur[r] + 1]; ++j) {
          const WriteRec& w = run.writes[j];
          if (w.commit_ts <= ts && w.op != OP_ROLLBACK) {
            if (w.op == OP_PUT) val = &run.vals[j];
            else deleted = true;
            break;
          }
        }
      }
    }
    // advance every run cursor sitting on this key
    for (size_t r = 0; r < s->runs.size(); ++r) {
      const Run& run = *s->runs[r];
      if (rcur[r] < run.keys.size() && run.keys[rcur[r]] == key)
        ++rcur[r];
    }
    if (val == nullptr) continue;
    int64_t need = 8 + static_cast<int64_t>(key.size())
                   + static_cast<int64_t>(val->size());
    if (off + need > buf_cap) {
      *truncated = 1;
      break;
    }
    uint32_t kl = key.size(), vl = val->size();
    std::memcpy(buf + off, &kl, 4); off += 4;
    std::memcpy(buf + off, key.data(), kl); off += kl;
    std::memcpy(buf + off, &vl, 4); off += 4;
    std::memcpy(buf + off, val->data(), vl); off += vl;
    ++n;
  }
  *used = off;
  return n;
}

// Full version history of one key, newest-first (status-API /mvcc
// introspection; reference pkg/server/handler mvcc handlers).  Walks the
// memtable chain then runs newest-first, skipping rollbacks.  Per
// version: [commit_ts u64][op u8][vlen i32][payload].  Returns the
// emitted count; *truncated set when max_n or the buffer cut it short.
int32_t kv_versions(void* h, const char* key, int32_t klen, int32_t max_n,
                    char* buf, int64_t buf_cap, int64_t* used,
                    uint8_t* truncated) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  std::string k(key, klen);
  int32_t n = 0;
  int64_t off = 0;
  *truncated = 0;
  bool full = false;
  auto emit = [&](const WriteRec& w, const std::string* val) {
    if (n >= max_n) { *truncated = 1; full = true; return; }
    int32_t vlen = (w.op == OP_PUT && val != nullptr)
        ? static_cast<int32_t>(val->size()) : 0;
    if (off + 13 + vlen > buf_cap) { *truncated = 1; full = true; return; }
    std::memcpy(buf + off, &w.commit_ts, 8); off += 8;
    buf[off++] = static_cast<char>(w.op);
    std::memcpy(buf + off, &vlen, 4); off += 4;
    if (vlen > 0) { std::memcpy(buf + off, val->data(), vlen); off += vlen; }
    ++n;
  };
  auto it = s->keys.find(k);
  if (it != s->keys.end()) {
    for (const auto& w : it->second.writes) {
      if (full) break;
      if (w.op == OP_ROLLBACK) continue;
      const std::string* val = nullptr;
      if (w.op == OP_PUT) {
        auto dit = it->second.data.find(w.start_ts);
        if (dit != it->second.data.end()) val = &dit->second;
      }
      emit(w, val);
    }
  }
  for (auto rit = s->runs.rbegin(); !full && rit != s->runs.rend(); ++rit) {
    const Run& r = **rit;
    if (!r.maybe(k)) continue;
    int64_t i = r.find(k);
    if (i < 0) continue;
    for (uint32_t j = r.woff[i]; !full && j < r.woff[i + 1]; ++j) {
      const WriteRec& w = r.writes[j];
      if (w.op == OP_ROLLBACK) continue;
      emit(w, w.op == OP_PUT ? &r.vals[j] : nullptr);
    }
  }
  *used = off;
  return n;
}

// MVCC garbage collection: drop versions not visible at safepoint
// (gcworker analog, pkg/store/gcworker/gc_worker.go).
int64_t kv_gc(void* h, uint64_t safepoint) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  int64_t dropped = 0;
  for (auto it = s->keys.begin(); it != s->keys.end();) {
    auto& vc = it->second;
    const WriteRec* keep = latest_write_le(vc, safepoint);
    std::vector<WriteRec> nw;
    for (const auto& w : vc.writes) {
      bool live = w.commit_ts > safepoint || (keep && w.commit_ts == keep->commit_ts);
      if (live) {
        nw.push_back(w);
      } else {
        vc.data.erase(w.start_ts);
        ++dropped;
      }
    }
    vc.writes = std::move(nw);
    if (vc.writes.empty() && !vc.lock.present && vc.data.empty()) {
      it = s->keys.erase(it);
    } else {
      ++it;
    }
  }
  // runs GC by COMPACTION FILTER: record the safepoint and merge, so
  // dead versions drop during the rewrite instead of a dedicated scan
  s->gc_safepoint = safepoint;
  dropped += compact_runs(s);
  return dropped;
}

// Freeze unlocked memtable keys into an immutable sorted run.
int64_t kv_flush(void* h) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  return flush_memtable(s);
}

int64_t kv_run_count(void* h) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  return static_cast<int64_t>(s->runs.size());
}

// In-process point-get micro-bench (bench harness only): n random gets
// over the CURRENT committed state, returns total nanoseconds.  Lives in
// C++ so the measurement excludes ctypes call overhead.
int64_t kv_bench_gets(void* h, int64_t n, uint64_t seed, uint64_t ts) {
  auto* s = static_cast<Store*>(h);
  // collect a key sample under the lock (memtable + runs)
  std::vector<std::string> sample;
  {
    std::shared_lock lk(s->mu);
    size_t total = s->keys.size();
    for (const auto& run : s->runs) total += run->keys.size();
    size_t stride = total / 65536 + 1;   // uniform over the key space
    size_t i = 0;
    for (const auto& [k, vc] : s->keys) {
      (void)vc;
      if (i++ % stride == 0) sample.push_back(k);
    }
    for (const auto& run : s->runs) {
      for (size_t j = 0; j < run->keys.size(); ++j) {
        if (i++ % stride == 0) sample.push_back(run->keys[j]);
      }
    }
  }
  if (sample.empty()) return 0;
  uint64_t x = seed | 1;
  const char* out;
  int32_t out_len;
  auto t0 = std::chrono::steady_clock::now();
  for (int64_t i = 0; i < n; ++i) {
    x ^= x << 13; x ^= x >> 7; x ^= x << 17;   // xorshift
    const std::string& k = sample[x % sample.size()];
    kv_get(h, k.data(), static_cast<int32_t>(k.size()), ts, &out,
           &out_len);
  }
  auto t1 = std::chrono::steady_clock::now();
  return std::chrono::duration_cast<std::chrono::nanoseconds>(t1 - t0)
      .count();
}

void kv_set_flush_threshold(void* h, int64_t n) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  s->flush_threshold = n > 0 ? static_cast<size_t>(n) : (1ull << 62);
}

// Acquire a pessimistic lock (KvPessimisticLock, unistore/tikv/server.go
// :237).  Blocks up to wait_ms while another txn holds the key, with
// waits-for-cycle detection (detector.go): the REQUESTER is the deadlock
// victim.  for_update_ts guards against commits later than what the
// statement read (write-conflict -> caller refreshes and retries).
int32_t kv_pessimistic_lock(void* h, const char* key, int32_t klen,
                            const char* primary, int32_t plen,
                            uint64_t start_ts, uint64_t for_update_ts,
                            int32_t wait_ms) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  std::string k(key, klen);
  auto deadline = std::chrono::steady_clock::now()
                  + std::chrono::milliseconds(wait_ms);
  for (;;) {
    auto& vc = s->keys[k];
    uint64_t newest = 0;
    bool rolled_back = false;
    conflict_view(s, &vc, k, start_ts, &newest, &rolled_back);
    if (rolled_back) return ERR_ALREADY_ROLLED_BACK;
    if (newest > for_update_ts) return ERR_WRITE_CONFLICT;
    if (!vc.lock.present) {
      vc.lock.present = true;
      vc.lock.pessimistic = true;
      vc.lock.start_ts = start_ts;
      vc.lock.primary.assign(primary, plen);
      vc.lock.op = OP_PUT;
      vc.lock.value.clear();
      return OK;
    }
    if (vc.lock.start_ts == start_ts) return OK;  // re-entrant
    uint64_t holder = vc.lock.start_ts;
    // adding edge start_ts -> holder: cycle iff holder (transitively)
    // already waits on us
    if (wf_reaches(s, holder, start_ts)) return ERR_DEADLOCK;
    s->waits_for[start_ts] = holder;
    bool timed_out = !s->lock_cv.wait_until(lk, deadline, [&] {
      auto it2 = s->keys.find(k);
      return it2 == s->keys.end() || !it2->second.lock.present
             || it2->second.lock.start_ts == start_ts;
    });
    s->waits_for.erase(start_ts);
    if (timed_out) return ERR_LOCK_WAIT_TIMEOUT;
  }
}

// Release a pessimistic lock without aborting the txn (statement rollback
// / unlock of keys that were locked but not written).
int32_t kv_pessimistic_rollback(void* h, const char* key, int32_t klen,
                                uint64_t start_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto it = s->keys.find(std::string(key, klen));
  if (it == s->keys.end()) return OK;
  auto& vc = it->second;
  if (vc.lock.present && vc.lock.pessimistic
      && vc.lock.start_ts == start_ts) {
    vc.lock = Lock{};
    s->lock_cv.notify_all();
  }
  return OK;
}

int64_t kv_num_keys(void* h) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  // distinct keys across memtable + runs (a flushed key may have been
  // re-written into the memtable; count it once)
  if (s->runs.empty()) return static_cast<int64_t>(s->keys.size());
  int64_t n = static_cast<int64_t>(s->keys.size());
  for (const auto& run : s->runs) {
    for (const auto& k : run->keys) {
      if (s->keys.find(k) == s->keys.end()) ++n;
    }
  }
  if (s->runs.size() > 1) {
    // subtract keys double-counted across runs
    for (size_t a = 1; a < s->runs.size(); ++a) {
      for (const auto& k : s->runs[a]->keys) {
        for (size_t b = 0; b < a; ++b) {
          if (s->runs[b]->find(k) >= 0) {
            if (s->keys.find(k) == s->keys.end()) --n;
            break;
          }
        }
      }
    }
  }
  return n;
}

}  // extern "C"
