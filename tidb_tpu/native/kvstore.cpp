// Percolator-model MVCC key-value engine.
//
// Reference analog: the in-process storage engine
// pkg/store/mockstore/unistore/tikv/mvcc.go (MVCCStore over badger +
// lockstore) and, behind it, TiKV's txn model: three logical column
// families —
//   data:  (key, start_ts)  -> row value
//   lock:  key              -> {start_ts, primary, op}
//   write: (key, commit_ts) -> {start_ts, op}
// with the 2PC protocol: Prewrite (lock + stage data), Commit (write
// record + unlock), Rollback, and snapshot reads that see the latest
// commit <= read_ts and fail on conflicting locks.
//
// This is a fresh C++17 implementation designed for the TPU framework's
// host runtime: an ordered std::map keyed by user key holding per-key
// version chains (newest-first vectors), guarded by a shared_mutex.  It is
// the transactional row store whose snapshots feed columnarization
// (store/columnar.py); the C ABI below is consumed via ctypes
// (tidb_tpu/store/kv.py).  Scan results are returned through a per-call
// arena so no allocation contracts cross the FFI.

// Durability (reference: unistore's badger-backed MVCC persists all CFs,
// mvcc.go:50): committed writes stream to a write-ahead log; kv_checkpoint
// compacts the whole committed state into a snapshot file and truncates
// the WAL.  In-flight (locked, uncommitted) state is intentionally NOT
// logged — the client lives in the same process, so a crash aborts its
// open transactions exactly like percolator lock cleanup would.
//
// File layout at <path>: "<path>.snap" (replayable compacted stream) +
// "<path>.wal" (appended commit records).  Record:
//   [u8 op][u64 start_ts][u64 commit_ts][u32 klen][u32 vlen][key][value]
// A torn tail record (crash mid-append) is detected and ignored.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <chrono>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <vector>

#ifdef _WIN32
#else
#include <unistd.h>
#endif

namespace {

enum ErrCode : int32_t {
  OK = 0,
  ERR_LOCKED = 1,          // conflicting lock -> caller backs off
  ERR_WRITE_CONFLICT = 2,  // newer commit than start_ts
  ERR_NOT_FOUND = 3,
  ERR_TXN_MISMATCH = 4,    // commit/rollback without matching lock
  ERR_ALREADY_ROLLED_BACK = 5,
  ERR_DEADLOCK = 6,        // waits-for cycle: requester is the victim
  ERR_LOCK_WAIT_TIMEOUT = 7,
  ERR_WAL = 8,             // WAL write failed: durability lost, commit refused
};

enum Op : uint8_t { OP_PUT = 0, OP_DELETE = 1, OP_ROLLBACK = 2 };

struct Lock {
  uint64_t start_ts = 0;
  std::string primary;
  Op op = OP_PUT;
  std::string value;  // staged data
  bool present = false;
  // pessimistic locks (tikv KvPessimisticLock analog): taken at DML time,
  // upgraded to a prewrite lock at commit, never staged to the WAL
  bool pessimistic = false;
};

struct WriteRec {
  uint64_t commit_ts;
  uint64_t start_ts;
  Op op;
};

struct VersionChain {
  Lock lock;
  // newest-first by commit_ts
  std::vector<WriteRec> writes;
  // staged/committed values keyed by start_ts
  std::map<uint64_t, std::string> data;
};

struct Store {
  std::map<std::string, VersionChain> keys;
  mutable std::shared_mutex mu;
  uint64_t ts_counter = 1;  // simple TSO for embedded use (PD analog)
  // durability (empty path = in-memory only)
  std::string path;
  FILE* wal = nullptr;
  bool sync = false;
  bool wal_failed = false;  // a WAL write failed: refuse further commits
  // pessimistic lock waiting + deadlock detection (detector.go analog):
  // waits_for[waiter_start_ts] = holder_start_ts (a txn waits on at most
  // one key at a time, so single edges suffice)
  std::condition_variable_any lock_cv;
  std::map<uint64_t, uint64_t> waits_for;
};

// true if following waits_for edges from `from` reaches `target`
bool wf_reaches(const Store* s, uint64_t from, uint64_t target) {
  uint64_t cur = from;
  for (size_t hops = 0; hops < s->waits_for.size() + 1; ++hops) {
    auto it = s->waits_for.find(cur);
    if (it == s->waits_for.end()) return false;
    cur = it->second;
    if (cur == target) return true;
  }
  return false;
}

void apply_committed(Store* s, const std::string& key, uint64_t start_ts,
                     uint64_t commit_ts, Op op, const std::string& value) {
  auto& vc = s->keys[key];
  // replay must be idempotent and order-independent: a crash between the
  // checkpoint rename and the WAL truncation leaves records present in
  // BOTH files, so dedupe by (commit_ts, start_ts) and insert at the
  // sorted (newest-first) position rather than blindly at the front
  auto pos = vc.writes.begin();
  for (; pos != vc.writes.end(); ++pos) {
    if (pos->commit_ts == commit_ts && pos->start_ts == start_ts) return;
    if (pos->commit_ts < commit_ts) break;
  }
  if (op == OP_PUT) vc.data[start_ts] = value;
  vc.writes.insert(pos, WriteRec{commit_ts, start_ts, op});
  if (commit_ts > s->ts_counter) s->ts_counter = commit_ts;
  if (start_ts > s->ts_counter) s->ts_counter = start_ts;
}

// Serialize ONE record; returns false on any short write.  The single
// writer shared by the WAL appender and the checkpointer (the reader is
// replay_file) so the on-disk format lives in one place per direction.
bool write_record(FILE* f, const std::string& key, uint64_t start_ts,
                  uint64_t commit_ts, Op op, const std::string& value) {
  uint8_t o = static_cast<uint8_t>(op);
  uint32_t kl = key.size(), vl = (op == OP_PUT) ? value.size() : 0;
  if (std::fwrite(&o, 1, 1, f) != 1) return false;
  if (std::fwrite(&start_ts, 8, 1, f) != 1) return false;
  if (std::fwrite(&commit_ts, 8, 1, f) != 1) return false;
  if (std::fwrite(&kl, 4, 1, f) != 1) return false;
  if (std::fwrite(&vl, 4, 1, f) != 1) return false;
  if (kl && std::fwrite(key.data(), 1, kl, f) != kl) return false;
  if (vl && std::fwrite(value.data(), 1, vl, f) != vl) return false;
  return true;
}

// Append + flush one commit record.  Any failure poisons the WAL
// (wal_failed): the caller fails the commit and all later ones — never
// silently degrade to acking non-durable writes.
bool log_commit(Store* s, const std::string& key, uint64_t start_ts,
                uint64_t commit_ts, Op op, const std::string& value) {
  if (s->wal == nullptr) return true;
  bool ok = write_record(s->wal, key, start_ts, commit_ts, op, value);
  ok = ok && std::fflush(s->wal) == 0;
#ifndef _WIN32
  if (ok && s->sync) ok = fdatasync(fileno(s->wal)) == 0;
#endif
  return ok;
}

// Replay one record stream; stops cleanly at a torn tail.  Returns the
// byte offset of the last complete record so the caller can truncate the
// tear before appending (appending after garbage would strand every
// later record behind an unparseable header).
long replay_file(Store* s, const std::string& fname) {
  FILE* f = std::fopen(fname.c_str(), "rb");
  if (f == nullptr) return 0;
  long good = 0;
  for (;;) {
    uint8_t o;
    uint64_t sts, cts;
    uint32_t kl, vl;
    if (std::fread(&o, 1, 1, f) != 1) break;
    if (std::fread(&sts, 8, 1, f) != 1) break;
    if (std::fread(&cts, 8, 1, f) != 1) break;
    if (std::fread(&kl, 4, 1, f) != 1) break;
    if (std::fread(&vl, 4, 1, f) != 1) break;
    std::string key(kl, '\0'), val(vl, '\0');
    if (kl && std::fread(key.data(), 1, kl, f) != kl) break;
    if (vl && std::fread(val.data(), 1, vl, f) != vl) break;
    apply_committed(s, key, sts, cts, static_cast<Op>(o), val);
    good = std::ftell(f);
  }
  std::fclose(f);
  return good;
}

struct Arena {
  std::vector<std::string> bufs;
  const char* push(const std::string& s) {
    bufs.push_back(s);
    return bufs.back().data();
  }
};

// thread-local: each OS thread gets its own result buffer, so a kv_get
// pointer stays valid until the *same* thread's next kv_get — the ctypes
// caller copies immediately after return on that thread.
thread_local std::string g_err;

int32_t check_lock_conflict(const VersionChain& vc, uint64_t read_ts,
                            uint64_t caller_start_ts) {
  if (!vc.lock.present) return OK;
  if (vc.lock.pessimistic) return OK;  // no staged write: reads pass
  if (vc.lock.start_ts == caller_start_ts) return OK;  // own lock
  if (vc.lock.start_ts <= read_ts) return ERR_LOCKED;
  return OK;  // lock from a future txn doesn't block this snapshot
}

const WriteRec* latest_write_le(const VersionChain& vc, uint64_t ts) {
  for (const auto& w : vc.writes) {
    if (w.commit_ts <= ts && w.op != OP_ROLLBACK) return &w;
  }
  return nullptr;
}

}  // namespace

extern "C" {

void* kv_open() { return new Store(); }

// Durable open: replay <path>.snap + <path>.wal, then append to the WAL.
// sync != 0 fdatasyncs every commit record (fflush-only otherwise).
void* kv_open_at(const char* path, int32_t plen, uint8_t sync) {
  auto* s = new Store();
  s->path.assign(path, plen);
  s->sync = sync != 0;
  replay_file(s, s->path + ".snap");
  long wal_good = replay_file(s, s->path + ".wal");
  s->ts_counter += 1;  // strictly above anything persisted
#ifndef _WIN32
  truncate((s->path + ".wal").c_str(), wal_good);  // drop any torn tail
#else
  (void)wal_good;
#endif
  s->wal = std::fopen((s->path + ".wal").c_str(), "ab");
  if (s->wal == nullptr) {  // unwritable dir/disk: fail loudly, never
    delete s;               // silently degrade to non-durable
    return nullptr;
  }
  return s;
}

// Compact the committed state into <path>.snap and truncate the WAL.
// Returns number of records written, or -1 when the store is in-memory.
int64_t kv_checkpoint(void* h) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  if (s->path.empty()) return -1;
  std::string tmp = s->path + ".snap.tmp";
  FILE* f = std::fopen(tmp.c_str(), "wb");
  if (f == nullptr) return -1;
  int64_t n = 0;
  bool ok = true;
  for (const auto& [key, vc] : s->keys) {
    if (!ok) break;
    // oldest-first so replay's insertion rebuilds newest-first
    for (auto it = vc.writes.rbegin(); ok && it != vc.writes.rend(); ++it) {
      if (it->op == OP_ROLLBACK) continue;
      std::string val;
      if (it->op == OP_PUT) {
        auto dit = vc.data.find(it->start_ts);
        if (dit == vc.data.end()) continue;
        val = dit->second;
      }
      ok = write_record(f, key, it->start_ts, it->commit_ts, it->op, val);
      ++n;
    }
  }
  ok = ok && std::fflush(f) == 0;
#ifndef _WIN32
  ok = ok && fdatasync(fileno(f)) == 0;
#endif
  std::fclose(f);
  if (!ok) {                       // partial snapshot: keep .snap + WAL
    std::remove(tmp.c_str());
    return -2;
  }
  if (std::rename(tmp.c_str(), (s->path + ".snap").c_str()) != 0) {
    std::remove(tmp.c_str());
    return -2;
  }
  if (s->wal != nullptr) {
    std::fclose(s->wal);
    s->wal = std::fopen((s->path + ".wal").c_str(), "wb");  // truncate
    if (s->wal == nullptr) {
      s->wal_failed = true;
      return -2;  // caller must treat as fatal
    }
  }
  return n;
}

void kv_close(void* h) {
  auto* s = static_cast<Store*>(h);
  if (s->wal != nullptr) std::fclose(s->wal);
  delete s;
}

uint64_t kv_alloc_ts(void* h) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  return ++s->ts_counter;
}

// Prewrite one mutation. op: 0=put, 1=delete.
int32_t kv_prewrite(void* h, const char* key, int32_t klen, const char* val,
                    int32_t vlen, const char* primary, int32_t plen,
                    uint64_t start_ts, uint8_t op) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  std::string k(key, klen);
  auto& vc = s->keys[k];
  if (vc.lock.present && vc.lock.start_ts != start_ts) {
    return ERR_LOCKED;
  }
  // prewriting over our own pessimistic lock skips the conflict check:
  // kv_pessimistic_lock already validated against for_update_ts, and
  // commits in (start_ts, for_update_ts] are permitted in this mode
  bool own_pess = vc.lock.present && vc.lock.pessimistic
                  && vc.lock.start_ts == start_ts;
  if (!own_pess) {
    // write conflict: any commit (or rollback of us) after start_ts
    for (const auto& w : vc.writes) {
      if (w.commit_ts > start_ts) {
        if (w.op == OP_ROLLBACK && w.start_ts != start_ts) continue;
        return w.op == OP_ROLLBACK ? ERR_ALREADY_ROLLED_BACK
                                   : ERR_WRITE_CONFLICT;
      }
      break;  // writes are newest-first; older ones can't conflict
    }
  }
  // rollback record for this exact start_ts => txn was aborted
  for (const auto& w : vc.writes) {
    if (w.op == OP_ROLLBACK && w.start_ts == start_ts) {
      return ERR_ALREADY_ROLLED_BACK;
    }
  }
  vc.lock.present = true;
  vc.lock.pessimistic = false;   // upgrade: pessimistic -> prewrite lock
  vc.lock.start_ts = start_ts;
  vc.lock.primary.assign(primary, plen);
  vc.lock.op = static_cast<Op>(op);
  vc.lock.value.assign(val ? val : "", val ? vlen : 0);
  return OK;
}

int32_t kv_commit(void* h, const char* key, int32_t klen, uint64_t start_ts,
                  uint64_t commit_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto it = s->keys.find(std::string(key, klen));
  if (it == s->keys.end()) return ERR_TXN_MISMATCH;
  auto& vc = it->second;
  if (!vc.lock.present || vc.lock.start_ts != start_ts) {
    // idempotent commit: already committed?
    for (const auto& w : vc.writes) {
      if (w.start_ts == start_ts && w.op != OP_ROLLBACK) return OK;
    }
    return ERR_TXN_MISMATCH;
  }
  if (vc.lock.pessimistic) return ERR_TXN_MISMATCH;  // prewrite first
  if (s->wal_failed) return ERR_WAL;
  // log BEFORE applying: a failed WAL write must fail the commit, not
  // silently ack a non-durable one
  if (s->wal != nullptr) {
    if (!log_commit(s, it->first, start_ts, commit_ts, vc.lock.op,
                    vc.lock.value)) {
      s->wal_failed = true;
      return ERR_WAL;
    }
  }
  if (vc.lock.op == OP_PUT) {
    vc.data[start_ts] = std::move(vc.lock.value);
  }
  vc.writes.insert(vc.writes.begin(),
                   WriteRec{commit_ts, start_ts, vc.lock.op});
  vc.lock = Lock{};
  s->lock_cv.notify_all();
  return OK;
}

int32_t kv_rollback(void* h, const char* key, int32_t klen,
                    uint64_t start_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto& vc = s->keys[std::string(key, klen)];
  if (vc.lock.present && vc.lock.start_ts == start_ts) {
    vc.lock = Lock{};
    s->lock_cv.notify_all();
  }
  // tombstone so a late prewrite of the same txn fails
  vc.writes.insert(vc.writes.begin(),
                   WriteRec{start_ts, start_ts, OP_ROLLBACK});
  vc.data.erase(start_ts);
  return OK;
}

// Snapshot point get.  out/out_len point into a thread-local buffer valid
// until the next kv_get on the same thread.
int32_t kv_get(void* h, const char* key, int32_t klen, uint64_t ts,
               const char** out, int32_t* out_len) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  auto it = s->keys.find(std::string(key, klen));
  if (it == s->keys.end()) return ERR_NOT_FOUND;
  const auto& vc = it->second;
  int32_t lc = check_lock_conflict(vc, ts, 0);
  if (lc != OK) return lc;
  const WriteRec* w = latest_write_le(vc, ts);
  if (w == nullptr || w->op == OP_DELETE) return ERR_NOT_FOUND;
  auto dit = vc.data.find(w->start_ts);
  if (dit == vc.data.end()) return ERR_NOT_FOUND;
  g_err = dit->second;
  *out = g_err.data();
  *out_len = static_cast<int32_t>(g_err.size());
  return OK;
}

// Snapshot range scan [start, end).  Returns number of pairs (<= limit),
// or the negative error code on lock conflict.  Results are written as
// length-prefixed records into the caller-provided buffer:
//   [u32 klen][key][u32 vlen][value] ...
// If the buffer is too small, returns what fits and sets *truncated=1 with
// *resume_key of the next key (paging analog).
int32_t kv_scan(void* h, const char* start, int32_t slen, const char* end,
                int32_t elen, uint64_t ts, int32_t limit, char* buf,
                int64_t buf_cap, int64_t* used, uint8_t* truncated) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  std::string sk(start, slen), ek(end, elen);
  auto it = s->keys.lower_bound(sk);
  int32_t n = 0;
  int64_t off = 0;
  *truncated = 0;
  for (; it != s->keys.end() && n < limit; ++it) {
    if (!ek.empty() && it->first >= ek) break;
    const auto& vc = it->second;
    if (check_lock_conflict(vc, ts, 0) != OK) return -ERR_LOCKED;
    const WriteRec* w = latest_write_le(vc, ts);
    if (w == nullptr || w->op == OP_DELETE) continue;
    auto dit = vc.data.find(w->start_ts);
    if (dit == vc.data.end()) continue;
    int64_t need = 8 + static_cast<int64_t>(it->first.size())
                   + static_cast<int64_t>(dit->second.size());
    if (off + need > buf_cap) {
      *truncated = 1;
      break;
    }
    uint32_t kl = it->first.size(), vl = dit->second.size();
    std::memcpy(buf + off, &kl, 4); off += 4;
    std::memcpy(buf + off, it->first.data(), kl); off += kl;
    std::memcpy(buf + off, &vl, 4); off += 4;
    std::memcpy(buf + off, dit->second.data(), vl); off += vl;
    ++n;
  }
  *used = off;
  return n;
}

// MVCC garbage collection: drop versions not visible at safepoint
// (gcworker analog, pkg/store/gcworker/gc_worker.go).
int64_t kv_gc(void* h, uint64_t safepoint) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  int64_t dropped = 0;
  for (auto it = s->keys.begin(); it != s->keys.end();) {
    auto& vc = it->second;
    const WriteRec* keep = latest_write_le(vc, safepoint);
    std::vector<WriteRec> nw;
    for (const auto& w : vc.writes) {
      bool live = w.commit_ts > safepoint || (keep && w.commit_ts == keep->commit_ts);
      if (live) {
        nw.push_back(w);
      } else {
        vc.data.erase(w.start_ts);
        ++dropped;
      }
    }
    vc.writes = std::move(nw);
    if (vc.writes.empty() && !vc.lock.present && vc.data.empty()) {
      it = s->keys.erase(it);
    } else {
      ++it;
    }
  }
  return dropped;
}

// Acquire a pessimistic lock (KvPessimisticLock, unistore/tikv/server.go
// :237).  Blocks up to wait_ms while another txn holds the key, with
// waits-for-cycle detection (detector.go): the REQUESTER is the deadlock
// victim.  for_update_ts guards against commits later than what the
// statement read (write-conflict -> caller refreshes and retries).
int32_t kv_pessimistic_lock(void* h, const char* key, int32_t klen,
                            const char* primary, int32_t plen,
                            uint64_t start_ts, uint64_t for_update_ts,
                            int32_t wait_ms) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  std::string k(key, klen);
  auto deadline = std::chrono::steady_clock::now()
                  + std::chrono::milliseconds(wait_ms);
  for (;;) {
    auto& vc = s->keys[k];
    for (const auto& w : vc.writes) {
      if (w.op == OP_ROLLBACK) {
        if (w.start_ts == start_ts) return ERR_ALREADY_ROLLED_BACK;
        continue;
      }
      if (w.commit_ts > for_update_ts) return ERR_WRITE_CONFLICT;
      break;
    }
    if (!vc.lock.present) {
      vc.lock.present = true;
      vc.lock.pessimistic = true;
      vc.lock.start_ts = start_ts;
      vc.lock.primary.assign(primary, plen);
      vc.lock.op = OP_PUT;
      vc.lock.value.clear();
      return OK;
    }
    if (vc.lock.start_ts == start_ts) return OK;  // re-entrant
    uint64_t holder = vc.lock.start_ts;
    // adding edge start_ts -> holder: cycle iff holder (transitively)
    // already waits on us
    if (wf_reaches(s, holder, start_ts)) return ERR_DEADLOCK;
    s->waits_for[start_ts] = holder;
    bool timed_out = !s->lock_cv.wait_until(lk, deadline, [&] {
      auto it2 = s->keys.find(k);
      return it2 == s->keys.end() || !it2->second.lock.present
             || it2->second.lock.start_ts == start_ts;
    });
    s->waits_for.erase(start_ts);
    if (timed_out) return ERR_LOCK_WAIT_TIMEOUT;
  }
}

// Release a pessimistic lock without aborting the txn (statement rollback
// / unlock of keys that were locked but not written).
int32_t kv_pessimistic_rollback(void* h, const char* key, int32_t klen,
                                uint64_t start_ts) {
  auto* s = static_cast<Store*>(h);
  std::unique_lock lk(s->mu);
  auto it = s->keys.find(std::string(key, klen));
  if (it == s->keys.end()) return OK;
  auto& vc = it->second;
  if (vc.lock.present && vc.lock.pessimistic
      && vc.lock.start_ts == start_ts) {
    vc.lock = Lock{};
    s->lock_cv.notify_all();
  }
  return OK;
}

int64_t kv_num_keys(void* h) {
  auto* s = static_cast<Store*>(h);
  std::shared_lock lk(s->mu);
  return static_cast<int64_t>(s->keys.size());
}

}  // extern "C"
