"""Resource control plane (pkg/resourcegroup + tikv resource_control
analog): RU pricing of device launches from their static LaunchCost
(rc/pricing), per-group token buckets with bounded overdraft
(rc/bucket), admission-time enforcement wired into the scheduler drain
plus statement accounting (rc/controller), and the runaway watch with
KILL / COOLDOWN / SWITCH_GROUP actions (rc/runaway).

``utils/resourcegroup`` remains as a thin re-export shim for existing
importers.
"""

from .bucket import TokenBucket
from .controller import (DEFAULT_MAX_QUEUE_S, DEFAULT_OVERDRAFT_RU,
                         PRIORITY_WEIGHTS, ResourceExhaustedError,
                         ResourceGroup, ResourceGroupManager,
                         charge_statement)
from .pricing import cost_rus, plan_rus, statement_rus, task_rus
from .runaway import RunawayError, RunawayRecord, RunawayRing

__all__ = ["TokenBucket", "ResourceGroup", "ResourceGroupManager",
           "ResourceExhaustedError", "RunawayError", "RunawayRecord",
           "RunawayRing", "charge_statement", "cost_rus", "task_rus",
           "plan_rus", "statement_rus", "PRIORITY_WEIGHTS",
           "DEFAULT_OVERDRAFT_RU", "DEFAULT_MAX_QUEUE_S"]
