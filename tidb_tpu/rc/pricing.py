"""RU pricing: convert a launch's static LaunchCost into request units.

Reference analog: the RU model of pkg/resourcegroup (tikv's
resource_control request-unit coefficients price read bytes + CPU).
Here the priced quantity is DEVICE work, and PR 4's static cost model
(analysis/copcost.LaunchCost) supplies it BEFORE any trace: peak
resident HBM bytes, host<->device transfer bytes, and a FLOP estimate —
the linear-algebra view of query cost (LAQP, arXiv:2306.08367) reduced
to three weighted terms.  Pricing therefore happens at ADMISSION, which
is what lets the scheduler drain enforce a group's token bucket before
launching anything (rc/controller).

Fused/coalesced groups price the shared scan once: the lead member pays
full price, each rider sharing the lead's resident scan pays only its
marginal bytes (peak minus the shared input residency — the same
marginal-bytes split the HBM-budget drain cap uses).

Coefficients are module constants (not sysvars): they define the RU
*unit* and changing them re-denominates every bucket in flight.

Closed-loop calibration (copmeter, analysis/calibrate): with
``tidb_tpu_cost_calibration`` on, the LaunchCost a task carries into
``task_rus`` is the CORRECTED one — the scheduler replaces ``task.cost``
at admission with the digest's clamped measured corrections (the static
cost stays on ``task.cost_static``), so pricing self-tunes per digest
without this module changing: the clamp bounds the swing to [1/8, 8]
and ``MIN_TASK_RU`` still floors every task, so calibrated pricing can
never undercut the per-request floor.
"""

from __future__ import annotations

import math
from typing import Optional

# 1 RU per 64 KiB transferred — the reference's read-byte coefficient
# (tikv resource_control: ~64KiB/RU for reads); transfer is the scarce
# PCIe/ICI resource a launch consumes exactly once.
RU_PER_TRANSFER_BYTE = 1.0 / (64 << 10)
# Per-link collective rates (shardflow, parallel/topology): same-host
# ICI collective bytes price like any transfer; cross-host DCI bytes
# are the pod's scarcest resource and price 4x — so admission and
# fairness stay honest when the declared host view splits a mesh
# (ROADMAP: "price cross-host transfer bytes separately from on-chip
# ICI").  The multiplier is a unit definition, not a sysvar, for the
# same re-denomination reason as the base coefficients.
RU_PER_ICI_BYTE = RU_PER_TRANSFER_BYTE
RU_PER_DCI_BYTE = 4.0 * RU_PER_TRANSFER_BYTE
# Residency is cheaper than transfer: the bytes sit in HBM for the
# launch but mostly alias the shared snapshot upload.  1 RU per MiB.
RU_PER_RESIDENT_BYTE = 1.0 / (1 << 20)
# 1 RU per 10 MFLOP: on-chip arithmetic is the cheapest resource.
RU_PER_FLOP = 1.0 / 10e6
# Every admitted task costs at least one RU (the reference's per-request
# floor) so unlimited metadata queries still drain a finite bucket.
MIN_TASK_RU = 1.0


def cost_rus(cost, *, shared_scan: bool = False) -> float:
    """RUs of one launch priced from its LaunchCost.  ``shared_scan``
    prices a rider whose resident scan input is already paid for by the
    launch lead (fusion / in-flight dedup): only its marginal bytes —
    payload, intermediates, outputs — count."""
    resident = cost.peak_hbm_bytes
    transfer = cost.transfer_bytes
    if shared_scan:
        resident = max(resident - cost.input_bytes, 0)
        transfer = max(transfer - cost.input_bytes, 0)
    # per-link collective terms (shardflow): a rider's merge/exchange
    # collectives are its OWN payload, never part of the shared scan,
    # so they price unscaled either way
    ici = getattr(cost, "ici_bytes", 0)
    dci = getattr(cost, "dci_bytes", 0)
    rus = (resident * RU_PER_RESIDENT_BYTE
           + transfer * RU_PER_TRANSFER_BYTE
           + ici * RU_PER_ICI_BYTE
           + dci * RU_PER_DCI_BYTE
           + cost.flops * RU_PER_FLOP)
    if not math.isfinite(rus):
        return float(MIN_TASK_RU)
    return max(float(MIN_TASK_RU), rus)


def task_rus(task, lead=None) -> float:
    """RUs of one CopTask at the drain.  Structured tasks price from
    their admission-time LaunchCost; a rider sharing ``lead``'s input
    token prices at its marginal bytes.  Opaque tasks (shuffle/window
    closures own their capacities) fall back to the legacy row estimate
    — still pre-launch, still floored at one RU."""
    cost = getattr(task, "cost", None)
    if cost is None:
        return max(float(MIN_TASK_RU), task.est_rows / 100.0 + 1.0)
    shared = (lead is not None and lead is not task
              and task.input_token is not None
              and task.input_token == lead.input_token)
    return cost_rus(cost, shared_scan=shared)


def statement_rus(rows_touched: int) -> float:
    """Host-side fallback charge for statements that never launched a
    device program (the pre-rc row-count formula, kept ONLY for the
    host path — device work is priced by cost_rus at admission)."""
    return max(float(MIN_TASK_RU), rows_touched / 100.0 + 1.0)


def split_device_time(costs: list, total_ns: int) -> list:
    """Attribute one measured launch wall time across its members,
    proportional to each member's marginal bytes (the shared scan is
    the lead's; riders weight by what they ADDED).  ``costs`` is a list
    of per-member weights (bytes); zero/unknown weights split evenly.
    Returns per-member ns summing to ``total_ns``."""
    n = len(costs)
    if n == 0:
        return []
    weights = [max(float(c or 0), 0.0) for c in costs]
    tot = sum(weights)
    if tot <= 0:
        share = total_ns // n
        out = [share] * n
        out[0] += total_ns - share * n
        return out
    out = [int(total_ns * w / tot) for w in weights]
    out[0] += total_ns - sum(out)
    return out


def plan_rus(cost) -> Optional[float]:
    """RU price of a whole built plan's rolled-up LaunchCost (the
    analysis gate's pricing-rot check).  None when the plan implies no
    device work at all (host-only statements are not RU-priced)."""
    if not cost.transfer_bytes and not cost.flops:
        return None
    return cost_rus(cost)


__all__ = ["cost_rus", "task_rus", "statement_rus", "split_device_time",
           "plan_rus", "RU_PER_TRANSFER_BYTE", "RU_PER_RESIDENT_BYTE",
           "RU_PER_ICI_BYTE", "RU_PER_DCI_BYTE",
           "RU_PER_FLOP", "MIN_TASK_RU"]
