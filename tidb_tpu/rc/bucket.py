"""Per-group RU token bucket: burst, bounded overdraft, lazy refill.

Reference analog: the tikv-side resource_control token bucket the
tidb resource-group client debits against.  One bucket per resource
group, shared by every session in the group (the server is thread-per-
connection), so every operation takes the bucket's leaf lock.  The
bucket never sleeps and never calls out under its lock — blocking
throttles live in the controller/scheduler layers, which decide WHEN to
consult the bucket; the bucket only answers "can this debit be
covered?" and keeps monotonic-time refill state.

Debt model: the drain debits a task's priced RUs the moment it admits
the task into a launch (pre-launch, post-check), so balances can dip
below zero up to the configured overdraft — bounded post-paid debt,
like the reference's token client — and refill pays the debt down
before banking burst.
"""

from __future__ import annotations

import threading
import time

# burst window in seconds of refill the bucket may bank; BURSTABLE
# groups bank 10x (the pre-rc ResourceGroup burst semantics, kept)
BURST_S = 1.0
BURSTABLE_FACTOR = 10.0


class TokenBucket:
    """Thread-safe RU bucket.  ``rate`` <= 0 means unlimited: every
    cover check passes and debits are recorded but never throttle."""

    __slots__ = ("_rate", "_burst_s", "_tokens", "_last", "_mu",
                 "debited", "credited")

    def __init__(self, rate: float = 0.0, burstable: bool = False):
        self._mu = threading.Lock()
        self._rate = float(max(rate, 0.0))
        self._burst_s = BURST_S * (BURSTABLE_FACTOR if burstable else 1.0)
        self._tokens = self._cap()
        self._last = time.monotonic()
        self.debited = 0.0        # lifetime RUs debited (stats)
        self.credited = 0.0       # lifetime RUs credited back (stats)

    # -- configuration ------------------------------------------------ #

    def _cap(self) -> float:
        return self._rate * self._burst_s

    def set_limit(self, rate: float, burstable: bool) -> None:
        """ALTER RESOURCE GROUP: re-rate in place, keeping accumulated
        balance/debt (clamped into the new burst cap)."""
        with self._mu:
            self._refill_locked(time.monotonic())
            self._rate = float(max(rate, 0.0))
            self._burst_s = BURST_S * (BURSTABLE_FACTOR if burstable
                                       else 1.0)
            self._tokens = min(self._tokens, self._cap())

    @property
    def rate(self) -> float:
        return self._rate

    @property
    def limited(self) -> bool:
        return self._rate > 0

    # -- refill + balance --------------------------------------------- #

    def _refill_locked(self, now: float) -> None:
        if self._rate <= 0:
            return
        dt = now - self._last
        if dt > 0:
            self._tokens = min(self._tokens + dt * self._rate, self._cap())
        self._last = now

    @property
    def balance(self) -> float:
        """Current tokens (negative = debt), refreshed lazily."""
        with self._mu:
            self._refill_locked(time.monotonic())
            return self._tokens

    @property
    def debt(self) -> float:
        return max(0.0, -self.balance)

    # -- the three operations the control plane needs ------------------ #

    def can_cover(self, rus: float, overdraft: float = 0.0) -> bool:
        """May a debit of ``rus`` proceed without exceeding the bounded
        overdraft?  Pure check — the drain debits separately once it
        commits the task to a launch (single drain thread, so the
        check-then-debit pair cannot race with itself)."""
        if self._rate <= 0:
            return True
        with self._mu:
            self._refill_locked(time.monotonic())
            return self._tokens - rus >= -float(max(overdraft, 0.0))

    def debit(self, rus: float) -> None:
        """Unconditional debit (the caller already passed can_cover, or
        is taking sanctioned debt: cooldown double-charge, post-paid
        host fallback)."""
        with self._mu:
            self._refill_locked(time.monotonic())
            self._tokens -= float(rus)
            self.debited += float(rus)

    def credit(self, rus: float) -> None:
        """Refund (SWITCH_GROUP re-pricing moves a statement's debit to
        the target group).  Credits may exceed the burst cap briefly;
        the next refill clamps."""
        with self._mu:
            self._refill_locked(time.monotonic())
            self._tokens = min(self._tokens + float(rus), self._cap())
            self.credited += float(rus)

    def try_postpaid(self, rus: float) -> bool:
        """Legacy post-paid discipline for the host statement path: any
        positive balance admits the charge (possibly into debt); an
        empty bucket refuses, and the controller sleeps outside the
        lock.  True = debited."""
        if self._rate <= 0:
            return True
        with self._mu:
            self._refill_locked(time.monotonic())
            if self._tokens > 0:
                self._tokens -= float(rus)
                self.debited += float(rus)
                return True
            return False

    def deficit(self, rus: float) -> float:
        """RUs short of covering ``rus`` from a positive balance — the
        quantity the blocking host path converts into sleep time."""
        with self._mu:
            self._refill_locked(time.monotonic())
            return max(0.0, rus - self._tokens)

    def force_debit(self, rus: float) -> None:
        """Test/chaos seam: push the bucket into arbitrary debt without
        touching refill state (exhausting a group deterministically)."""
        with self._mu:
            self._tokens -= float(rus)


__all__ = ["TokenBucket", "BURST_S", "BURSTABLE_FACTOR"]
