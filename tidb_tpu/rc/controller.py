"""Resource control plane: group registry, budgeting, and statement
accounting.

Reference analog: pkg/resourcegroup + TiKV's unified read pool
(SURVEY §2.7).  The pre-rc port charged RUs AFTER execution from
``est_rows/100 + 1``, so an exhausted group still launched device
programs and only its next statement blocked.  This module owns the
other half of the fix (rc/pricing + rc/bucket are the first half):

- ``ResourceGroup`` couples the group meta (RU_PER_SEC, BURSTABLE,
  QUERY_LIMIT, PRIORITY, SWITCH_GROUP target) with its ``TokenBucket``
  and travels INTO the scheduler on every CopTask, so the weighted-fair
  drain can refuse to serve a group whose bucket (plus bounded
  overdraft) cannot cover the next task's priced RUs — admission-time
  enforcement, no head-of-line blocking across groups
  (sched/scheduler._pick consults ``bucket.can_cover``).
- ``charge_statement`` keeps the post-execution seam for what only the
  statement boundary knows: the runaway watch over queue+execution wall
  time (rc/runaway: KILL / COOLDOWN / SWITCH_GROUP) and the legacy
  row-count charge for HOST-only statements (device work is priced and
  debited pre-launch at the drain; charging it again here would double
  bill).
- ``ResourceExhaustedError`` is the MySQL-compatible failure the drain
  raises when a throttled task overstays its max-queue deadline (TiDB
  error space 8252, ErrResourceGroupRequestFailed analog).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from .bucket import TokenBucket
from .pricing import statement_rus
from .runaway import RunawayError, RunawayRing, is_runaway

# PRIORITY -> device-scheduler fair-share weight (stride scheduling in
# sched/scheduler.py; the reference's resource-group PRIORITY feeds
# tikv's unified read pool the same way)
PRIORITY_WEIGHTS = {"low": 1.0, "medium": 8.0, "high": 16.0}

# bounded overdraft the drain tolerates before throttling a group
# (engine default; tidb_tpu_rc_overdraft_ru overrides per deployment)
DEFAULT_OVERDRAFT_RU = 64.0
# how long a throttled task may queue before failing its waiter with
# ResourceExhaustedError (DeviceScheduler.rc_max_queue_s; tests shrink)
DEFAULT_MAX_QUEUE_S = 10.0


class ResourceExhaustedError(RuntimeError):
    """A resource group's RU bucket stayed exhausted past the max-queue
    deadline: the waiter fails instead of occupying the admission queue
    forever (tikv unified-read-pool deadline behavior).  MySQL/TiDB
    error number 8252 ('Exceeded resource group quota limitation')."""

    errno = 8252

    def __init__(self, group: str, waited_s: float, rus: float):
        super().__init__(
            f"Exceeded resource group quota limitation: group "
            f"{group!r} could not cover {rus:.1f} RU within "
            f"{waited_s:.1f}s (bucket exhausted; raise RU_PER_SEC or "
            "retry later)")


@dataclass
class ResourceGroup:
    """One group's meta + live RU bucket.  Every session of the group
    shares this object; the bucket serializes internally and
    ``runaway_count`` updates under ``_mu``."""

    name: str
    ru_per_sec: int = 0            # 0 = unlimited
    burstable: bool = False
    exec_elapsed_sec: float = 0.0  # 0 = no runaway watch
    runaway_action: str = "kill"   # kill | cooldown | switch_group
    priority: str = "medium"       # low | medium | high (sched weight)
    switch_target: str = ""        # SWITCH_GROUP(<name>) destination
    runaway_count: int = 0
    bucket: TokenBucket = None
    _mu: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        if self.bucket is None:
            self.bucket = TokenBucket(self.ru_per_sec, self.burstable)

    @property
    def sched_weight(self) -> float:
        return PRIORITY_WEIGHTS.get(self.priority, 8.0)

    @property
    def limited(self) -> bool:
        return self.ru_per_sec > 0

    def note_runaway(self) -> None:
        with self._mu:
            self.runaway_count += 1

    def consume(self, rus: float, max_wait_sec: float = 5.0) -> float:
        """Blocking post-paid charge for the HOST statement path (no
        device launch to gate): any positive balance admits the charge
        into bounded debt; an empty bucket sleeps OUTSIDE the lock until
        refill covers it or the wait budget runs out.  Returns seconds
        slept — the reference token client's throttle."""
        if not self.limited:
            return 0.0
        slept = 0.0
        while True:
            if self.bucket.try_postpaid(rus):
                return slept
            need = min(self.bucket.deficit(rus) / self.ru_per_sec,
                       max_wait_sec - slept)
            if need <= 0:
                self.bucket.debit(rus)   # waited long enough; take debt
                return slept
            step = min(need, 0.05)
            time.sleep(step)
            slept += step


class ResourceGroupManager:
    """Domain-level group registry (resource group meta + runaway
    settings; infoschema RESOURCE_GROUPS analog).  The group MAP is
    guarded by ``_lock``; per-group state by the group's own bucket/_mu
    leaf locks — ``_lock`` is never held across a bucket operation."""

    def __init__(self):
        self._groups: dict[str, ResourceGroup] = {
            "default": ResourceGroup("default")}
        self._lock = threading.Lock()
        self.runaway_ring = RunawayRing()

    def _validate(self, action: Optional[str],
                  switch_target: Optional[str],
                  priority: Optional[str]) -> None:
        if priority is not None and priority not in PRIORITY_WEIGHTS:
            raise ValueError(f"bad PRIORITY {priority!r}")
        if action == "switch_group":
            if not switch_target:
                raise ValueError("ACTION=SWITCH_GROUP needs a target "
                                 "group: SWITCH_GROUP(<name>)")
            if self.get(switch_target) is None:
                raise ValueError(
                    f"SWITCH_GROUP target {switch_target!r} does not "
                    "exist")

    def create(self, name: str, ru_per_sec: Optional[int],
               burstable: Optional[bool] = None,
               exec_elapsed_sec: Optional[float] = None,
               action: Optional[str] = None,
               if_not_exists: bool = False,
               priority: Optional[str] = None,
               switch_target: Optional[str] = None) -> ResourceGroup:
        self._validate(action, switch_target, priority)
        with self._lock:
            if name in self._groups:
                if if_not_exists:
                    return self._groups[name]    # no-op, keep the group
                raise ValueError(f"resource group {name!r} exists")
            g = ResourceGroup(name, ru_per_sec or 0, bool(burstable),
                              exec_elapsed_sec or 0.0, action or "kill",
                              priority or "medium", switch_target or "")
            self._groups[name] = g
            return g

    def alter(self, name: str, ru_per_sec: Optional[int],
              burstable: Optional[bool], exec_elapsed_sec: Optional[float],
              action: Optional[str],
              priority: Optional[str] = None,
              switch_target: Optional[str] = None) -> ResourceGroup:
        """Merge only the options named in the statement; state
        (bucket balance/debt, runaway counters) is preserved."""
        self._validate(action, switch_target, priority)
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                raise ValueError(f"unknown resource group {name!r}")
            if ru_per_sec is not None:
                g.ru_per_sec = ru_per_sec
            if burstable is not None:
                g.burstable = burstable
            if exec_elapsed_sec is not None:
                g.exec_elapsed_sec = exec_elapsed_sec
            if action is not None:
                g.runaway_action = action
                g.switch_target = switch_target or ""
            if priority is not None:
                g.priority = priority
        if ru_per_sec is not None or burstable is not None:
            g.bucket.set_limit(g.ru_per_sec, g.burstable)
        return g

    def drop(self, name: str, if_exists: bool = False) -> None:
        with self._lock:
            if name == "default":
                raise ValueError("cannot drop the default resource group")
            if name not in self._groups:
                if if_exists:
                    return
                raise ValueError(f"unknown resource group {name!r}")
            for g in self._groups.values():
                if g.switch_target == name:
                    g.switch_target = ""     # orphaned target: disarm
                    if g.runaway_action == "switch_group":
                        g.runaway_action = "cooldown"
            del self._groups[name]

    def get(self, name: str) -> Optional[ResourceGroup]:
        with self._lock:
            return self._groups.get(name)

    def groups_snapshot(self) -> list:
        """Stable-ordered snapshot of the live groups (coplace: the pd
        quota pool iterates limited groups each renewal round without
        holding the registry lock across bucket operations)."""
        with self._lock:
            return [self._groups[name] for name in sorted(self._groups)]

    def rows(self) -> list[tuple]:
        with self._lock:
            groups = list(self._groups.values())
        out = []
        for g in groups:
            action = g.runaway_action.upper()
            if g.runaway_action == "switch_group" and g.switch_target:
                action = f"SWITCH_GROUP({g.switch_target})"
            out.append((g.name, g.ru_per_sec or None,
                        "YES" if g.burstable else "NO",
                        g.exec_elapsed_sec or None, action,
                        g.runaway_count, g.priority.upper()))
        return out

    def resource_stats(self) -> dict:
        """Per-group budget state for the /resource status route."""
        with self._lock:
            groups = list(self._groups.values())
        out = {}
        for g in groups:
            out[g.name] = {
                "ru_per_sec": g.ru_per_sec,
                "burstable": g.burstable,
                "priority": g.priority,
                "balance": round(g.bucket.balance, 2),
                "debt": round(g.bucket.debt, 2),
                "debited_ru": round(g.bucket.debited, 2),
                "runaway_count": g.runaway_count,
                "runaway_action": g.runaway_action,
                "switch_target": g.switch_target,
            }
        return out


def charge_statement(group: ResourceGroup, rows_touched: int,
                     elapsed_sec: float, *, sched_wait_sec: float = 0.0,
                     device_rus: float = 0.0,
                     manager: Optional[ResourceGroupManager] = None,
                     sql: str = "") -> str:
    """Post-execution accounting seam.

    Device work was priced from its LaunchCost and debited at the drain
    (``device_rus`` reports it); only HOST-only statements still charge
    the legacy row-count RU here, post-paid and blocking.  The runaway
    watch covers queue+execution wall time (``elapsed_sec`` includes
    the admission wait) and applies the group's action: KILL raises,
    COOLDOWN double-charges, SWITCH_GROUP moves the statement's debit
    to the target group.  Returns the name of the group that ended up
    paying (== group.name unless a runaway switch re-priced it)."""
    host_rus = statement_rus(rows_touched) if device_rus <= 0 else 0.0
    payer = group
    if is_runaway(group, elapsed_sec):
        group.note_runaway()
        action = group.runaway_action
        target = None
        if action == "switch_group" and manager is not None:
            target = manager.get(group.switch_target)
            if target is None or target is group:
                action, target = "cooldown", None   # disarmed target
        if manager is not None:
            manager.runaway_ring.add(
                group.name, action,
                target.name if target is not None else "", sql,
                elapsed_sec, sched_wait_sec)
        if action == "kill":
            raise RunawayError(
                f"query exceeded EXEC_ELAPSED "
                f"{group.exec_elapsed_sec}s (resource group "
                f"{group.name!r})")
        if action == "cooldown":
            # demotion = the statement pays double: device work debits
            # its priced RUs a second time (sanctioned debt), host work
            # doubles its row charge below
            if device_rus > 0:
                group.bucket.debit(device_rus)
            host_rus *= 2.0
        elif target is not None:
            # re-price against the target group: the pre-launch device
            # debit moves buckets, and any host charge pays there too
            if device_rus > 0:
                group.bucket.credit(device_rus)
                target.bucket.debit(device_rus)
            payer = target
    if host_rus > 0:
        payer.consume(host_rus)
    return payer.name


__all__ = ["ResourceGroup", "ResourceGroupManager", "RunawayError",
           "ResourceExhaustedError", "charge_statement",
           "PRIORITY_WEIGHTS", "DEFAULT_OVERDRAFT_RU",
           "DEFAULT_MAX_QUEUE_S"]
