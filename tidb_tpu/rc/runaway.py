"""Runaway watch: queue+execution wall-time budget per resource group.

Reference analog: pkg/resourcegroup/runaway — a QUERY_LIMIT
(EXEC_ELAPSED = '...', ACTION = ...) marks statements exceeding the
budget as runaway.  Upgrades over the pre-rc watch:

- the watched time is QUEUE + EXECUTION wall time: a statement that
  spent its life throttled in the admission queue counts (the budget is
  a user-visible latency promise, not a CPU meter);
- three actions: KILL raises, COOLDOWN demotes the charge (the
  statement pays double), SWITCH_GROUP(<name>) re-prices the statement
  against the target group — its device debit moves buckets, so a
  runaway analytics query spends the batch group's RUs, not the
  interactive group's;
- every decision appends to a bounded ring of runaway records surfaced
  on /resource (the reference's mysql.tidb_runaway_queries table).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

RUNAWAY_RING_CAP = 256

RUNAWAY_ACTIONS = ("kill", "cooldown", "switch_group")


class RunawayError(RuntimeError):
    """Statement exceeded the group's EXEC_ELAPSED budget with
    ACTION=KILL (runaway detector).  TiDB error space 8253
    (ErrResourceGroupQueryRunawayInterrupted)."""

    errno = 8253


@dataclass(frozen=True)
class RunawayRecord:
    ts: float            # wall-clock seconds (time.time)
    group: str
    action: str          # kill | cooldown | switch_group
    target: str          # SWITCH_GROUP destination ('' otherwise)
    sql: str             # statement text sample (truncated)
    elapsed_s: float     # queue + execution wall time
    sched_wait_s: float  # the queue share of elapsed_s

    def as_dict(self) -> dict:
        return {"ts": self.ts, "group": self.group, "action": self.action,
                "target": self.target, "sql": self.sql,
                "elapsed_s": round(self.elapsed_s, 4),
                "sched_wait_s": round(self.sched_wait_s, 4)}


class RunawayRing:
    """Bounded, thread-safe ring of runaway decisions (newest last)."""

    def __init__(self, cap: int = RUNAWAY_RING_CAP):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=cap)
        self.total = 0

    def add(self, group: str, action: str, target: str, sql: str,
            elapsed_s: float, sched_wait_s: float) -> RunawayRecord:
        rec = RunawayRecord(time.time(), group, action, target or "",
                            sql[:256], elapsed_s, sched_wait_s)
        with self._mu:
            self._ring.append(rec)
            self.total += 1
        from ..utils.metrics import global_registry
        global_registry().counter(
            "tidb_tpu_rc_runaway_total",
            "runaway statements detected", labels=("action",)).inc(
                action=action)
        return rec

    def records(self, n: int = 32) -> list:
        with self._mu:
            return [r.as_dict() for r in list(self._ring)[-n:]]


def is_runaway(group, elapsed_s: float) -> bool:
    """Does ``elapsed_s`` of queue+execution wall time bust the group's
    EXEC_ELAPSED budget?"""
    return bool(group.exec_elapsed_sec
                and elapsed_s > group.exec_elapsed_sec)


__all__ = ["RunawayError", "RunawayRecord", "RunawayRing", "is_runaway",
           "RUNAWAY_ACTIONS", "RUNAWAY_RING_CAP"]
