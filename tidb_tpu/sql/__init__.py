from . import ast
from .lexer import tokenize, Token, LexError
from .parser import Parser, ParseError, parse_sql, parse_one

__all__ = ["ast", "tokenize", "Token", "LexError", "Parser", "ParseError",
           "parse_sql", "parse_one"]
