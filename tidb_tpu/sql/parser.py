"""Recursive-descent SQL parser (MySQL-dialect subset).

Reference analog: pkg/parser (goyacc grammar parser.y, 17k lines).  The TPU
rebuild uses a hand-written Pratt/recursive-descent parser over the subset
the engine executes: SELECT (joins/group/having/order/limit, subqueries in
FROM), INSERT/UPDATE/DELETE, CREATE/DROP TABLE/DATABASE, EXPLAIN [ANALYZE],
SHOW, SET, BEGIN/COMMIT/ROLLBACK, TRUNCATE, ANALYZE TABLE.

Operator precedence mirrors MySQL: OR < XOR < AND < NOT < comparison/IN/
BETWEEN/LIKE/IS < bitor < bitand < shift < add < mul < unary.
"""

from __future__ import annotations

from typing import Optional

from . import ast as A
from .lexer import Token, tokenize


class ParseError(ValueError):
    def __init__(self, msg: str, tok: Token):
        super().__init__(f"{msg} near {tok.text!r} (pos {tok.pos})")
        self.tok = tok


class Parser:
    def __init__(self, sql: str):
        toks = tokenize(sql)
        # hint comments are only meaningful right after SELECT; anywhere
        # else they behave like ordinary comments (dropped), so SQL such
        # as `UPDATE /*+ x */ t SET ...` still parses
        self.toks = [t for j, t in enumerate(toks)
                     if t.kind != "hint"
                     or (j > 0 and toks[j - 1].kind == "kw"
                         and toks[j - 1].text == "SELECT")]
        self.i = 0
        self.sql = sql           # raw text (binding statement capture)

    def _stmt_text_until(self, stop_kw) -> str:
        """Raw SQL text of an embedded statement, from the current token
        up to `stop_kw` (a top-level keyword followed by SELECT/WITH —
        distinguishes binding USING from join USING) or end-of-statement.
        Advances past the captured tokens."""
        start = self.cur.pos
        j = self.i
        while j < len(self.toks):
            t = self.toks[j]
            if t.kind == "eof" or (t.kind == "op" and t.text == ";"):
                break
            if (stop_kw and t.kind == "kw" and t.text == stop_kw
                    and j + 1 < len(self.toks)
                    and self.toks[j + 1].kind == "kw"
                    and self.toks[j + 1].text in ("SELECT", "WITH")):
                break
            j += 1
        end = self.toks[j].pos if j < len(self.toks) else len(self.sql)
        self.i = j
        return self.sql[start:end].strip()

    # ---------------- token helpers ---------------- #

    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def advance(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        return self.cur.kind == "kw" and self.cur.text in kws

    def at_op(self, *ops: str) -> bool:
        return self.cur.kind == "op" and self.cur.text in ops

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.advance()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.advance()
            return True
        return False

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            raise ParseError(f"expected {kw}", self.cur)
        return self.advance()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise ParseError(f"expected {op!r}", self.cur)
        return self.advance()

    def ident(self) -> str:
        t = self.cur
        if t.kind == "ident":
            return self.advance().text
        # non-reserved keywords usable as identifiers
        if t.kind == "kw" and t.text in _NONRESERVED:
            return self.advance().text
        raise ParseError("expected identifier", t)

    # ---------------- entry ---------------- #

    def parse(self) -> list[A.Node]:
        stmts = []
        while self.cur.kind != "eof":
            if self.accept_op(";"):
                continue
            start = self.cur.pos
            node = self.statement()
            end = self.cur.pos        # pos of ';' or eof token
            node.text_span = (start, end)
            stmts.append(node)
            if self.cur.kind != "eof":
                self.expect_op(";")
        return stmts

    def statement(self) -> A.Node:
        if self.at_kw("SELECT", "WITH") or self.at_op("("):
            return self.select_query()
        # TRACE is non-reserved (MySQL-compatible): match contextually,
        # only when followed by a statement-starting keyword
        if (self.cur.kind == "ident" and self.cur.text.upper() == "TRACE"
                and self.toks[self.i + 1].kind == "kw"):
            self.advance()
            return A.TraceStmt(self.statement())
        if self.at_kw("EXPLAIN", "DESCRIBE"):
            self.advance()
            if (self.cur.kind == "ident"
                    and self.cur.text.upper() == "FORMAT"
                    and self.toks[self.i + 1].text == "="):
                self.advance()      # EXPLAIN FORMAT = 'brief'|'row'|...
                self.expect_op("=")
                (self._str_lit() if self.cur.kind == "str"
                 else self.ident())
            # DESCRIBE <table> = SHOW COLUMNS FROM <table>
            elif self.cur.kind == "ident" \
                    and self.toks[self.i + 1].text != "(":
                return A.ShowStmt("columns", self.ident())
            analyze = self.accept_kw("ANALYZE")
            return A.Explain(self.statement(), analyze)
        if self.at_kw("CREATE"):
            return self.create_stmt()
        if self.at_kw("ALTER"):
            return self.alter_stmt()
        if self.at_kw("DROP"):
            return self.drop_stmt()
        if self.at_kw("INSERT"):
            return self.insert_stmt()
        if self.at_kw("REPLACE"):
            return self.insert_stmt(replace=True)
        if self.at_kw("LOAD"):
            return self.load_data_stmt()
        if self.at_kw("UPDATE"):
            return self.update_stmt()
        if self.at_kw("DELETE"):
            return self.delete_stmt()
        if (self.cur.kind in ("kw", "ident")
                and self.cur.text.upper() == "KILL"):
            self.advance()
            query_only = True
            if self._accept_word("QUERY"):
                query_only = True
            elif self._accept_word("CONNECTION"):
                query_only = False
            elif self._accept_word("TIDB"):
                self._accept_word("QUERY") or self._accept_word(
                    "CONNECTION")
            return A.KillStmt(self._int_lit(), query_only)
        if self.at_kw("USE"):
            self.advance()
            return A.UseDatabase(self.ident())
        if self.at_kw("SHOW"):
            return self.show_stmt()
        if self.at_kw("SET"):
            return self.set_stmt()
        if self.at_kw("BEGIN"):
            self.advance()
            mode = ""
            if self.cur.kind == "ident" and self.cur.text.upper() in (
                    "PESSIMISTIC", "OPTIMISTIC"):
                mode = self.advance().text.lower()
            return A.TxnStmt("begin", mode)
        if self.at_kw("START"):
            self.advance()
            self.expect_kw("TRANSACTION")
            return A.TxnStmt("begin")
        if self.at_kw("COMMIT"):
            self.advance()
            return A.TxnStmt("commit")
        if self.at_kw("ROLLBACK"):
            self.advance()
            return A.TxnStmt("rollback")
        if self.at_kw("TRUNCATE"):
            self.advance()
            self.accept_kw("TABLE")
            return A.TruncateTable(self.ident())
        if self.at_kw("ANALYZE"):
            self.advance()
            self.expect_kw("TABLE")
            an = A.AnalyzeTable(self.ident())
            if self._accept_word("PREDICATE"):
                if not self._accept_word("COLUMNS"):
                    raise ParseError("expected COLUMNS after PREDICATE",
                                     self.cur)
                an.predicate_columns = True
            elif self._accept_word("COLUMNS"):
                an.columns = [self.ident()]
                while self.accept_op(","):
                    an.columns.append(self.ident())
            if self.accept_kw("WITH"):
                t = self.advance()
                if t.kind not in ("int", "float", "decimal"):
                    raise ParseError("expected a sample rate", t)
                if not self._accept_word("SAMPLERATE"):
                    raise ParseError("expected SAMPLERATE", self.cur)
                an.sample_rate = float(t.text)
            return an
        if self.cur.kind == "ident" and self.cur.text.upper() in (
                "PREPARE", "EXECUTE", "DEALLOCATE"):
            return self._prepare_family()
        if self.cur.kind == "ident" and self.cur.text.upper() == "PLAN":
            self.advance()
            if not self._accept_word("REPLAYER"):
                raise ParseError("expected REPLAYER after PLAN", self.cur)
            if not self._accept_word("DUMP"):
                raise ParseError("expected DUMP", self.cur)
            self.expect_kw("EXPLAIN")
            return A.PlanReplayerDump(self._stmt_text_until(None))
        if self.cur.kind == "ident" and self.cur.text.upper() == "SPLIT":
            self.advance()
            self.expect_kw("TABLE")
            name = self.ident()
            t = self.cur
            if not (t.kind == "ident" and t.text.upper() == "REGIONS"):
                raise ParseError("expected REGIONS", t)
            self.advance()
            return A.SplitTable(name, self._int_lit())
        if self.at_kw("ADMIN"):
            return self.admin_stmt()
        if self.at_kw("GRANT"):
            return self.grant_stmt()
        if self.at_kw("REVOKE"):
            return self.revoke_stmt()
        if self.at_kw("FLUSH"):
            self.advance()
            self.expect_kw("PRIVILEGES")
            return A.FlushStmt("privileges")
        raise ParseError("unsupported statement", self.cur)

    def admin_stmt(self) -> A.AdminStmt:
        self.expect_kw("ADMIN")
        if self.accept_kw("SHOW"):
            # ADMIN SHOW DDL JOBS
            t = self.cur
            if t.kind == "ident" and t.text.upper() == "DDL":
                self.advance()
                t2 = self.cur
                if t2.kind == "ident" and t2.text.upper() == "JOBS":
                    self.advance()
                    return A.AdminStmt("show ddl jobs")
                raise ParseError("expected JOBS after ADMIN SHOW DDL", t2)
            raise ParseError("unsupported ADMIN SHOW", t)
        if self.accept_kw("CHECK"):
            self.expect_kw("TABLE")
            return A.AdminStmt("check table", self.ident())
        if self.cur.kind == "ident" and self.cur.text.upper() == "RECOMMEND":
            self.advance()
            self.expect_kw("INDEX")
            return A.AdminStmt("recommend index")
        if self.cur.kind == "ident" and self.cur.text.upper() == "CHECKSUM":
            self.advance()
            self.expect_kw("TABLE")
            return A.AdminStmt("checksum table", self.ident())
        raise ParseError("unsupported ADMIN", self.cur)

    def _prepare_family(self) -> A.Node:
        word = self.advance().text.upper()
        if word == "PREPARE":
            name = self.ident()
            self.expect_kw("FROM")
            t = self.cur
            if t.kind != "str":
                raise ParseError("expected statement string", t)
            self.advance()
            return A.PrepareStmt(name, t.text)
        if word == "EXECUTE":
            name = self.ident()
            using: list[str] = []
            if self.at_kw("USING"):
                self.advance()
                while True:
                    self.expect_op("@")
                    using.append(self.ident())
                    if not self.accept_op(","):
                        break
            return A.ExecutePrepared(name, using)
        # DEALLOCATE PREPARE name
        if self.cur.kind == "ident" and self.cur.text.upper() == "PREPARE":
            self.advance()
        return A.DeallocateStmt(self.ident())

    # ---------------- users & privileges ---------------- #

    def _user_spec(self) -> A.UserSpec:
        t = self.cur
        if t.kind == "str":
            name = self.advance().text
        else:
            name = self.ident()
        host = "%"
        if self.accept_op("@"):
            t = self.cur
            host = self.advance().text if t.kind == "str" else self.ident()
        return A.UserSpec(name, host)

    def _user_password_list(self):
        out = []
        while True:
            spec = self._user_spec()
            pwd = None
            if self.accept_kw("IDENTIFIED"):
                self.expect_kw("BY")
                t = self.cur
                if t.kind != "str":
                    raise ParseError("expected password string", t)
                pwd = self.advance().text
            out.append((spec, pwd))
            if not self.accept_op(","):
                return out

    def _priv_list(self) -> list[str]:
        privs = []
        if self.accept_kw("ALL"):
            self.accept_kw("PRIVILEGES")
            return ["ALL"]
        while True:
            t = self.cur
            if t.kind not in ("kw", "ident"):
                raise ParseError("expected privilege", t)
            name = self.advance().text.upper()
            if name == "CREATE" and self.accept_kw("USER"):
                name = "CREATE USER"
            privs.append(name)
            if not self.accept_op(","):
                return privs

    def _priv_level(self) -> tuple[str, str]:
        """db.table | db.* | *.* | * (current db) | table"""
        if self.accept_op("*"):
            if self.accept_op("."):
                self.expect_op("*")
                return "*", "*"
            # bare '*' is MySQL's current-database level, NOT global
            return "", "*"
        name = self.ident()
        if self.accept_op("."):
            if self.accept_op("*"):
                return name, "*"
            return name, self.ident()
        return "", name      # current-db table

    def grant_stmt(self) -> A.GrantStmt:
        self.expect_kw("GRANT")
        privs = self._priv_list()
        self.expect_kw("ON")
        db, table = self._priv_level()
        self.expect_kw("TO")
        users = [self._user_spec()]
        while self.accept_op(","):
            users.append(self._user_spec())
        return A.GrantStmt(privs, db, table, users)

    def revoke_stmt(self) -> A.RevokeStmt:
        self.expect_kw("REVOKE")
        privs = self._priv_list()
        self.expect_kw("ON")
        db, table = self._priv_level()
        self.expect_kw("FROM")
        users = [self._user_spec()]
        while self.accept_op(","):
            users.append(self._user_spec())
        return A.RevokeStmt(privs, db, table, users)

    # ---------------- SELECT / set operations / WITH ---------------- #

    def select_query(self) -> A.Node:
        """Full query: [WITH [RECURSIVE] ...] select-expr with UNION/
        EXCEPT/INTERSECT chains (INTERSECT binds tighter, like MySQL 8)."""
        ctes: list[A.CTE] = []
        recursive = False
        if self.at_kw("WITH"):
            ctes, recursive = self.with_clause()
        node = self._set_op_expr()
        if ctes:  # don't clobber a parenthesized inner query's own WITH list
            node.ctes = ctes + node.ctes
            node.recursive = recursive or node.recursive
        return node

    def with_clause(self) -> tuple[list[A.CTE], bool]:
        self.expect_kw("WITH")
        recursive = self.accept_kw("RECURSIVE")
        ctes = []
        while True:
            name = self.ident()
            cols: list[str] = []
            if self.accept_op("("):
                cols.append(self.ident())
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            self.expect_kw("AS")
            self.expect_op("(")
            sel = self.select_query()
            self.expect_op(")")
            ctes.append(A.CTE(name, cols, sel))
            if not self.accept_op(","):
                break
        return ctes, recursive

    def _set_op_expr(self) -> A.Node:
        """UNION/EXCEPT level (lowest precedence).  A trailing ORDER BY /
        LIMIT consumed by the last non-parenthesized operand is hoisted to
        the whole set operation (MySQL semantics); an intermediate operand
        carrying one is an error ("incorrect usage of UNION and ORDER BY")."""
        left, leaf = self._intersect_chain()
        while self.at_kw("UNION", "EXCEPT"):
            kind = self.advance().text.lower()
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            self._no_trailing(leaf)
            right, leaf = self._intersect_chain()
            left = A.SetOpStmt(kind, all_, left, right)
        if isinstance(left, A.SetOpStmt):
            if leaf is not None and (leaf.order_by or leaf.limit is not None):
                left.order_by, leaf.order_by = leaf.order_by, []
                left.limit, left.offset = leaf.limit, leaf.offset
                leaf.limit = leaf.offset = None
            self._trailing_order_limit(left)
        elif leaf is None:
            # single parenthesized select: (SELECT ...) ORDER BY ... LIMIT n
            self._trailing_order_limit(left)
        return left

    def _intersect_chain(self):
        left, leaf = self._set_operand()
        while self.at_kw("INTERSECT"):
            self.advance()
            all_ = self.accept_kw("ALL")
            if not all_:
                self.accept_kw("DISTINCT")
            self._no_trailing(leaf)
            right, leaf = self._set_operand()
            left = A.SetOpStmt("intersect", all_, left, right)
        return left, leaf

    def _set_operand(self):
        """One operand: a SELECT, or a parenthesized query (whose ORDER BY/
        LIMIT stay local).  Returns (node, hoistable_leaf_or_None)."""
        if self.accept_op("("):
            inner = self.select_query()
            self.expect_op(")")
            return inner, None
        sel = self.select_stmt()
        return sel, sel

    def _no_trailing(self, leaf):
        if leaf is not None and (leaf.order_by or leaf.limit is not None):
            raise ParseError("incorrect usage of UNION and ORDER BY/LIMIT "
                             "(parenthesize the operand)", self.cur)

    def _order_by_list(self) -> list[tuple[A.Node, bool]]:
        """expr [ASC|DESC] {, ...} — caller consumed ORDER BY."""
        out = []
        while True:
            e = self.expr()
            desc = False
            if self.accept_kw("DESC"):
                desc = True
            else:
                self.accept_kw("ASC")
            out.append((e, desc))
            if not self.accept_op(","):
                break
        return out

    def _limit_clause(self) -> tuple[int, Optional[int]]:
        """n | off, n | n OFFSET off — caller consumed LIMIT."""
        a = self._int_lit()
        if self.accept_op(","):
            return self._int_lit(), a
        if self.accept_kw("OFFSET"):
            return a, self._int_lit()
        return a, None

    def _trailing_order_limit(self, node: A.Node):
        """ORDER BY / LIMIT after a parenthesized final operand."""
        if self.at_kw("ORDER") and not node.order_by:
            self.advance()
            self.expect_kw("BY")
            node.order_by = self._order_by_list()
        if node.limit is None and self.accept_kw("LIMIT"):
            node.limit, node.offset = self._limit_clause()

    def select_stmt(self) -> A.SelectStmt:
        self.expect_kw("SELECT")
        s = A.SelectStmt()
        if self.cur.kind == "hint":
            s.hints = _parse_hints(self.advance().text)
        if self.accept_kw("DISTINCT"):
            s.distinct = True
        else:
            self.accept_kw("ALL")
        while True:
            s.items.append(self.select_item())
            if not self.accept_op(","):
                break
        if self.accept_kw("FROM"):
            s.from_ = self.table_refs()
        if self.accept_kw("WHERE"):
            s.where = self.expr()
        if self.at_kw("GROUP"):
            self.advance()
            self.expect_kw("BY")
            while True:
                s.group_by.append(self.expr())
                if not self.accept_op(","):
                    break
            if self.accept_kw("WITH"):
                # only WITH ROLLUP may follow a GROUP BY list
                if not self._accept_word("ROLLUP"):
                    raise ParseError("expected ROLLUP after WITH", self.cur)
                s.rollup = True
        if self.accept_kw("HAVING"):
            s.having = self.expr()
        if self.at_kw("ORDER"):
            self.advance()
            self.expect_kw("BY")
            s.order_by = self._order_by_list()
        if self.accept_kw("LIMIT"):
            s.limit, s.offset = self._limit_clause()
        if self.at_kw("FOR"):
            # FOR UPDATE | FOR SHARE [NOWAIT]: locking read clause
            self.advance()
            if self.accept_kw("UPDATE"):
                s.for_update = True
            elif self._accept_word("SHARE"):
                s.for_update = False   # share locks are a no-op here
            else:
                raise ParseError("expected UPDATE or SHARE after FOR",
                                 self.cur)
            self._accept_word("NOWAIT")
        elif self._accept_word("LOCK"):
            self.expect_kw("IN")
            self._accept_word("SHARE")
            if not self.accept_kw("MODE"):
                self._accept_word("MODE")
        return s

    def _int_lit(self) -> int:
        t = self.cur
        if t.kind != "int":
            raise ParseError("expected integer", t)
        self.advance()
        return int(t.text)

    def _str_lit(self) -> str:
        t = self.cur
        if t.kind != "str":
            raise ParseError("expected string literal", t)
        self.advance()
        return t.text

    def select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.advance()
            return A.SelectItem(A.Star())
        # t.* lookahead
        if (self.cur.kind == "ident" and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "."
                and self.toks[self.i + 2].kind == "op"
                and self.toks[self.i + 2].text == "*"):
            t = self.advance().text
            self.advance()
            self.advance()
            return A.SelectItem(A.Star(table=t))
        e = self.expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self._alias_name()
        elif self.cur.kind == "ident" or (self.cur.kind == "kw"
                                          and self.cur.text in _NONRESERVED):
            alias = self.ident()
        elif self.cur.kind == "str":
            alias = self.advance().text
        return A.SelectItem(e, alias)

    def _alias_name(self) -> str:
        if self.cur.kind == "str":
            return self.advance().text
        return self.ident()

    # ---------------- FROM / joins ---------------- #

    def table_refs(self) -> A.Node:
        left = self.table_ref()
        while True:
            if self.accept_op(","):
                right = self.table_ref()
                left = A.Join("cross", left, right, None)
                continue
            kind = None
            if self.at_kw("JOIN", "INNER", "CROSS"):
                if self.accept_kw("INNER") or self.accept_kw("CROSS"):
                    pass
                self.expect_kw("JOIN")
                kind = "inner"
            elif self.at_kw("LEFT", "RIGHT"):
                side = self.advance().text.lower()
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = side
            else:
                break
            right = self.table_ref()
            on = None
            using = None
            if self.accept_kw("ON"):
                on = self.expr()
            elif self.accept_kw("USING"):
                self.expect_op("(")
                using = [self.ident()]
                while self.accept_op(","):
                    using.append(self.ident())
                self.expect_op(")")
            if kind == "inner" and on is None and using is None:
                kind = "cross"
            left = A.Join(kind, left, right, on, using)
        return left

    def table_ref(self) -> A.Node:
        if self.accept_op("("):
            if self.at_kw("SELECT", "WITH"):
                sub = self.select_query()
                self.expect_op(")")
                self.accept_kw("AS")
                return A.SubqueryRef(sub, self.ident())
            inner = self.table_refs()
            self.expect_op(")")
            return inner
        name = self.ident()
        db = None
        if self.accept_op("."):
            db, name = name, self.ident()
        as_of = None
        if (self.at_kw("AS") and self.i + 1 < len(self.toks)
                and self.toks[self.i + 1].kind in ("kw", "ident")
                and self.toks[self.i + 1].text.upper() == "OF"):
            # stale read: t AS OF TIMESTAMP <literal>
            self.advance()   # AS
            self.advance()   # OF
            if not (self.cur.kind in ("kw", "ident")
                    and self.cur.text.upper() == "TIMESTAMP"):
                raise ParseError("expected TIMESTAMP after AS OF", self.cur)
            self.advance()
            t = self.advance()
            if t.kind in ("int", "decimal", "float"):
                as_of = int(float(t.text))
            elif t.kind == "str":
                as_of = t.text
            else:
                raise ParseError("AS OF TIMESTAMP needs a literal", t)
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.cur.kind == "ident" and self.cur.text.upper() not in (
                "USE", "IGNORE", "FORCE"):
            alias = self.ident()
        tn = A.TableName(name, db, alias, as_of)
        # index hints: t USE|IGNORE|FORCE INDEX|KEY (ix, ...)
        while (self.cur.kind in ("kw", "ident")
               and self.cur.text.upper() in ("USE", "IGNORE", "FORCE")):
            kind = self.advance().text.lower()
            if not (self.accept_kw("INDEX") or self.accept_kw("KEY")
                    or self._accept_word("INDEX")
                    or self._accept_word("KEY")):
                raise ParseError(f"expected INDEX after {kind.upper()}",
                                 self.cur)
            names = self._paren_name_list()
            tn.index_hints.append((kind, names))
        return tn

    # ---------------- DDL ---------------- #

    def create_stmt(self) -> A.Node:
        self.expect_kw("CREATE")
        if self.at_kw("GLOBAL", "SESSION", "BINDING"):
            scope = "session"       # TiDB default scope is SESSION
            if self.at_kw("GLOBAL", "SESSION"):
                scope = self.advance().text.lower()
            self.expect_kw("BINDING")
            self.expect_kw("FOR")
            orig = self._stmt_text_until("USING")
            self.expect_kw("USING")
            bind = self._stmt_text_until(None)
            return A.CreateBinding(scope, orig, bind)
        if self.cur.kind == "ident" and self.cur.text.upper() == "RESOURCE":
            self.advance()
            self.expect_kw("GROUP")
            ine = self._if_not_exists()
            return self._resource_group_body(self.ident().lower(), ine,
                                             False)
        if self.accept_kw("DATABASE"):
            ine = self._if_not_exists()
            return A.CreateDatabase(self.ident(), ine)
        if self.accept_kw("USER"):
            ine = self._if_not_exists()
            return A.CreateUser(self._user_password_list(), ine)
        or_replace = False
        if self.accept_kw("OR"):
            if not (self.at_kw("REPLACE")
                    or (self.cur.kind == "ident"
                        and self.cur.text.upper() == "REPLACE")):
                raise ParseError("expected REPLACE after CREATE OR",
                                 self.cur)
            self.advance()
            or_replace = True
        if self.cur.kind == "ident" and self.cur.text.upper() == "VIEW":
            self.advance()
            name = self.ident()
            cols: list = []
            if self.at_op("("):
                cols = self._paren_name_list()
            self.expect_kw("AS")
            sql = self._stmt_text_until(None)
            parse_sql(sql)                 # validate the view body NOW
            return A.CreateView(name, cols, sql, or_replace)
        if or_replace:
            raise ParseError("expected VIEW after CREATE OR REPLACE",
                             self.cur)
        unique = self.accept_kw("UNIQUE")
        if self.accept_kw("INDEX") or (unique and self.accept_kw("KEY")):
            ine = self._if_not_exists()
            name = self.ident()
            self.expect_kw("ON")
            table = self.ident()
            dbq = None
            if self.accept_op("."):
                dbq, table = table, self.ident()
            cols = self._paren_name_list()
            return A.CreateIndex(name, table, dbq, cols, unique, ine)
        if unique:
            raise ParseError("expected INDEX after CREATE UNIQUE", self.cur)
        if self._accept_word("SEQUENCE"):
            return self._create_sequence()
        temporary = self._accept_word("TEMPORARY")
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        name = self.ident()
        db = None
        if self.accept_op("."):
            db, name = name, self.ident()
        self.expect_op("(")
        ct = A.CreateTable(name, db=db, if_not_exists=ine,
                           temporary=temporary)
        while True:
            if self.at_kw("PRIMARY"):
                self.advance()
                self.expect_kw("KEY")
                self.expect_op("(")
                ct.primary_key = [self.ident()]
                while self.accept_op(","):
                    ct.primary_key.append(self.ident())
                self.expect_op(")")
            elif self.at_kw("UNIQUE", "INDEX", "KEY"):
                uniq = self.accept_kw("UNIQUE")
                if not self.accept_kw("INDEX"):
                    self.accept_kw("KEY")
                iname = None
                if self.cur.kind == "ident":
                    iname = self.ident()
                cols = self._paren_name_list()
                ct.indexes.append((iname, cols, uniq))
            elif (self.cur.kind in ("kw", "ident")
                  and self.cur.text.upper() in ("CONSTRAINT", "FOREIGN")):
                fname = None
                if self._accept_word("CONSTRAINT"):
                    if self.cur.kind == "ident" \
                            and self.cur.text.upper() != "FOREIGN":
                        fname = self.ident()
                if not self._accept_word("FOREIGN"):
                    raise ParseError("expected FOREIGN KEY", self.cur)
                self.expect_kw("KEY")
                if self.cur.kind == "ident":   # optional index name
                    self.ident()
                cols = self._paren_name_list()
                if len(cols) != 1:
                    raise ParseError(
                        "only single-column FOREIGN KEY supported",
                        self.cur)
                ct.foreign_keys.append(self._references_clause(
                    fname, cols[0]))
            else:
                cd = self.column_def()
                ct.columns.append(cd)
                if cd.references is not None:
                    rt, rc, od = cd.references
                    ct.foreign_keys.append(A.ForeignKeyDef(
                        None, cd.name, rt, rc, od))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        # table options: TTL is honored, the rest (ENGINE=, CHARSET=...)
        # are accepted and ignored
        while not (self.at_op(";") or self.cur.kind == "eof"):
            if self.cur.kind == "ident" and self.cur.text.upper() == "TTL":
                self.advance()
                self.expect_op("=")
                col = self.ident()
                self.expect_op("+")
                self.expect_kw("INTERVAL")
                n = self._int_lit()
                unit = self.advance().text.upper()
                secs = {"SECOND": 1, "MINUTE": 60, "HOUR": 3600,
                        "DAY": 86400, "WEEK": 7 * 86400,
                        "MONTH": 30 * 86400, "YEAR": 365 * 86400}.get(unit)
                if secs is None:
                    raise ParseError("bad TTL unit", self.cur)
                if ct.ttl is None:
                    ct.ttl = A.TTLOption(col, n * secs)
                else:
                    ct.ttl.column, ct.ttl.interval_sec = col, n * secs
            elif (self.cur.kind in ("kw", "ident")
                  and self.cur.text.upper() == "PARTITION"):
                self.advance()
                self.expect_kw("BY")
                ct.partition = self._partition_spec()
            elif (self.cur.kind == "ident"
                  and self.cur.text.upper() == "TTL_ENABLE"):
                self.advance()
                self.expect_op("=")
                t = self.advance()   # 'ON' / 'OFF' string literal
                if ct.ttl is None:
                    ct.ttl = A.TTLOption()
                ct.ttl.enable = t.text.upper() != "OFF"
            else:
                self.advance()
        for c in ct.columns:
            if c.primary_key and c.name not in ct.primary_key:
                ct.primary_key.append(c.name)
        return ct

    def _accept_word(self, w: str) -> bool:
        """Accept a keyword OR identifier spelled `w` (non-reserved words
        like HASH/MAXVALUE lex as idents)."""
        if self.cur.kind in ("kw", "ident") and self.cur.text.upper() == w:
            self.advance()
            return True
        return False

    def _partition_spec(self) -> A.PartitionSpec:
        """RANGE (col) (PARTITION p VALUES LESS THAN (n|MAXVALUE), ...)
        | HASH (col) PARTITIONS n   (parser.y PartitionOpt subset;
        bounds are integer literals — the meta-model keeps them as ints)."""
        if self._accept_word("RANGE"):
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            self.expect_op("(")
            parts = []
            while True:
                if not self._accept_word("PARTITION"):
                    raise ParseError("expected PARTITION", self.cur)
                pname = self.ident()
                self.expect_kw("VALUES")
                if not (self._accept_word("LESS")
                        and self._accept_word("THAN")):
                    raise ParseError("expected LESS THAN", self.cur)
                if self._accept_word("MAXVALUE"):
                    bound = None
                else:
                    self.expect_op("(")
                    if self._accept_word("MAXVALUE"):
                        bound = None
                    else:
                        neg = self.accept_op("-")
                        bound = self._int_lit()
                        if neg:
                            bound = -bound
                    self.expect_op(")")
                parts.append((pname, bound))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            bounds = [b for _, b in parts if b is not None]
            if bounds != sorted(bounds) or (
                    None in [b for _, b in parts[:-1]]):
                raise ParseError("RANGE partition bounds must ascend "
                                 "(MAXVALUE last)", self.cur)
            return A.PartitionSpec("range", col, parts)
        if self._accept_word("HASH"):
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            if not self._accept_word("PARTITIONS"):
                raise ParseError("expected PARTITIONS", self.cur)
            n = self._int_lit()
            if not 1 <= n <= 1024:
                raise ParseError("PARTITIONS must be 1..1024", self.cur)
            return A.PartitionSpec(
                "hash", col, [(f"p{i}", None) for i in range(n)], n)
        raise ParseError("expected RANGE or HASH partitioning", self.cur)

    def _paren_name_list(self) -> list[str]:
        """Index column list; prefix lengths col(10) and ASC/DESC are
        accepted and ignored, as are trailing index options."""
        self.expect_op("(")
        out = []
        while True:
            out.append(self.ident())
            if self.accept_op("("):        # prefix length
                self._int_lit()
                self.expect_op(")")
            if not self.accept_kw("DESC"):
                self.accept_kw("ASC")
            if not self.accept_op(","):
                break
        self.expect_op(")")
        self._skip_index_options()
        return out

    def _skip_index_options(self):
        """USING BTREE|HASH, COMMENT '...', VISIBLE/INVISIBLE."""
        while True:
            if self.accept_kw("USING"):
                self.ident()
            elif self.accept_kw("COMMENT"):
                self.advance()             # string literal
            elif self.cur.kind == "ident" and self.cur.text.upper() in (
                    "VISIBLE", "INVISIBLE", "BTREE", "HASH"):
                self.advance()
            else:
                return

    def alter_stmt(self) -> A.Node:
        self.expect_kw("ALTER")
        if self.accept_kw("USER"):
            return A.AlterUser(self._user_password_list())
        if self.cur.kind == "ident" and self.cur.text.upper() == "RESOURCE":
            self.advance()
            self.expect_kw("GROUP")
            return self._resource_group_body(self.ident().lower(), False,
                                             True)
        self.expect_kw("TABLE")
        table = self.ident()
        dbq = None
        if self.accept_op("."):
            dbq, table = table, self.ident()
        at = A.AlterTable(table, db=dbq)
        while True:
            if self.accept_kw("ADD"):
                uniq = self.accept_kw("UNIQUE")
                if self.accept_kw("INDEX") or self.accept_kw("KEY") or uniq:
                    iname = self.ident() if self.cur.kind == "ident" else None
                    cols = self._paren_name_list()
                    at.actions.append(("add_index", iname, cols, uniq))
                else:
                    self.accept_kw("COLUMN")
                    at.actions.append(("add_column", self.column_def()))
            elif self.accept_kw("DROP"):
                if self.accept_kw("INDEX") or self.accept_kw("KEY"):
                    at.actions.append(("drop_index", self.ident()))
                else:
                    self.accept_kw("COLUMN")
                    at.actions.append(("drop_column", self.ident()))
            else:
                raise ParseError("unsupported ALTER TABLE action", self.cur)
            if not self.accept_op(","):
                break
        return at

    def _if_not_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _create_sequence(self) -> "A.CreateSequence":
        """CREATE SEQUENCE name [START [WITH] n] [INCREMENT [BY] n]
        [MINVALUE n | NOMINVALUE] [MAXVALUE n | NOMAXVALUE]
        [CACHE n | NOCACHE] [CYCLE | NOCYCLE]
        (reference: parser sequence options, ddl/sequence.go)."""
        ine = self._if_not_exists()
        cs = A.CreateSequence(self.ident(), if_not_exists=ine)

        def int_val() -> int:
            neg = self.accept_op("-")
            t = self.advance()
            if t.kind != "int":
                raise ParseError("expected integer sequence option", t)
            return -int(t.text) if neg else int(t.text)

        while self.cur.kind in ("kw", "ident"):
            w = self.cur.text.upper()
            if w == "START":
                self.advance()
                self._accept_word("WITH")
                cs.start = int_val()
            elif w == "INCREMENT":
                self.advance()
                self._accept_word("BY")
                cs.increment = int_val()
            elif w == "MINVALUE":
                self.advance()
                cs.min_value = int_val()
            elif w == "MAXVALUE":
                self.advance()
                cs.max_value = int_val()
            elif w in ("NOMINVALUE", "NOMAXVALUE", "NOCACHE", "NOCYCLE"):
                self.advance()
            elif w == "CACHE":
                self.advance()
                cs.cache = max(int_val(), 1)
            elif w == "CYCLE":
                self.advance()
                cs.cycle = True
            else:
                break
        return cs

    def column_def(self) -> A.ColumnDef:
        name = self.ident()
        tname, prec, scale = self.type_name()
        cd = A.ColumnDef(name, tname, prec, scale,
                         members=self._type_members)
        while True:
            if self.accept_kw("NOT"):
                self.expect_kw("NULL")
                cd.not_null = True
            elif self.accept_kw("NULL"):
                pass
            elif self.at_kw("PRIMARY"):
                self.advance()
                self.expect_kw("KEY")
                cd.primary_key = True
                cd.not_null = True
            elif self.accept_kw("UNIQUE"):
                self.accept_kw("KEY")
            elif self.accept_kw("DEFAULT"):
                cd.default = self.expr()
            elif self.accept_kw("AUTO_INCREMENT"):
                cd.auto_increment = True
            elif (self.at_kw("AS")
                  or (self.cur.kind in ("kw", "ident")
                      and self.cur.text.upper() == "GENERATED")):
                # [GENERATED ALWAYS] AS (expr) [VIRTUAL|STORED]
                if not self.at_kw("AS"):
                    self.advance()           # GENERATED
                    self._accept_word("ALWAYS")
                self.expect_kw("AS")
                self.expect_op("(")
                cd.generated = self.expr()
                self.expect_op(")")
                if self._accept_word("STORED"):
                    cd.generated_stored = True
                else:
                    self._accept_word("VIRTUAL")
            elif self.accept_kw("COMMENT"):
                self.advance()  # string
            elif self.at_kw("CHARACTER"):
                self.advance()
                self.expect_kw("SET")
                self.ident()
            elif self.accept_kw("COLLATE"):
                cd.collation = self.ident().lower()
            elif (self.cur.kind in ("kw", "ident")
                  and self.cur.text.upper() == "REFERENCES"):
                self.advance()
                fk = self._references_clause(None, cd.name, inline=True)
                cd.references = (fk.ref_table, fk.ref_column, fk.on_delete)
            else:
                break
        return cd

    def _references_clause(self, fname, column,
                           inline: bool = False) -> "A.ForeignKeyDef":
        """[REFERENCES already consumed when inline] parent (col)
        [ON DELETE RESTRICT|CASCADE|NO ACTION] [ON UPDATE RESTRICT|...]"""
        if not inline and not self._accept_word("REFERENCES"):
            raise ParseError("expected REFERENCES", self.cur)
        parent = self.ident()
        cols = self._paren_name_list()
        if len(cols) != 1:
            raise ParseError("only single-column REFERENCES supported",
                             self.cur)
        on_delete = "restrict"
        while self.at_kw("ON"):
            self.advance()
            if self.accept_kw("DELETE"):
                act = self.advance().text.upper()
                if act == "NO":
                    self._accept_word("ACTION")
                    act = "RESTRICT"
                if act not in ("RESTRICT", "CASCADE"):
                    raise ParseError(
                        f"unsupported ON DELETE {act}", self.cur)
                on_delete = act.lower()
            elif self.accept_kw("UPDATE"):
                act = self.advance().text.upper()
                if act == "NO":
                    self._accept_word("ACTION")
                    act = "RESTRICT"
                # only RESTRICT is enforced at update time; reject anything
                # else instead of silently downgrading CASCADE/SET NULL
                if act != "RESTRICT":
                    raise ParseError(
                        f"unsupported ON UPDATE {act}", self.cur)
            else:
                raise ParseError("expected DELETE or UPDATE after ON",
                                 self.cur)
        return A.ForeignKeyDef(fname, column, parent, cols[0], on_delete)

    def type_name(self) -> tuple[str, int, int]:
        t = self.cur
        if t.kind not in ("ident", "kw"):
            raise ParseError("expected type name", t)
        self.advance()
        name = t.text.upper()
        prec = scale = -1
        self._type_members = ()
        if name in ("ENUM", "SET"):
            self.expect_op("(")
            vals = [self._str_lit()]
            while self.accept_op(","):
                vals.append(self._str_lit())
            self.expect_op(")")
            self._type_members = tuple(vals)
        elif self.accept_op("("):
            prec = self._int_lit()
            if self.accept_op(","):
                scale = self._int_lit()
            self.expect_op(")")
        # UNSIGNED / ZEROFILL modifiers
        while self.cur.kind == "ident" and self.cur.text.upper() in (
                "UNSIGNED", "ZEROFILL", "SIGNED"):
            name += " " + self.advance().text.upper()
        return name, prec, scale

    # ---------------- DML ---------------- #

    def drop_stmt(self) -> A.Node:
        self.expect_kw("DROP")
        if self.at_kw("GLOBAL", "SESSION", "BINDING"):
            scope = "session"       # TiDB default scope is SESSION
            if self.at_kw("GLOBAL", "SESSION"):
                scope = self.advance().text.lower()
            self.expect_kw("BINDING")
            self.expect_kw("FOR")
            return A.DropBinding(scope, self._stmt_text_until(None))
        if self.cur.kind == "ident" and self.cur.text.upper() == "RESOURCE":
            self.advance()
            self.expect_kw("GROUP")
            ie = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            return A.DropResourceGroup(self.ident().lower(), ie)
        if self.accept_kw("USER"):
            ie = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            users = [self._user_spec()]
            while self.accept_op(","):
                users.append(self._user_spec())
            return A.DropUser(users, ie)
        if self.accept_kw("DATABASE"):
            ie = self.accept_kw("IF") and self.expect_kw("EXISTS") is not None
            return A.DropDatabase(self.ident(), ie)
        if self._accept_word("SEQUENCE"):
            ie = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            return A.DropSequence(self.ident(), ie)
        if self.accept_kw("INDEX"):
            ie = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            name = self.ident()
            self.expect_kw("ON")
            table = self.ident()
            dbq = None
            if self.accept_op("."):
                dbq, table = table, self.ident()
            return A.DropIndex(name, table, dbq, ie)
        if self.cur.kind == "ident" and self.cur.text.upper() == "VIEW":
            self.advance()
            ie = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                ie = True
            names = [self.ident()]
            while self.accept_op(","):
                names.append(self.ident())
            return A.DropView(names, ie)
        temporary = self._accept_word("TEMPORARY")
        self.expect_kw("TABLE")
        ie = False
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            ie = True

        def qname():
            # (db | None, name) tuple: a dotted string would mis-split
            # backtick identifiers that CONTAIN dots (`a.b`)
            n = self.ident()
            if self.accept_op("."):
                return (n, self.ident())
            return (None, n)

        names = [qname()]
        while self.accept_op(","):
            names.append(qname())
        return A.DropTable(names, ie, temporary)

    def insert_stmt(self, replace: bool = False) -> A.Insert:
        ignore = False
        if replace:
            self.expect_kw("REPLACE")
        else:
            self.expect_kw("INSERT")
            ignore = self.accept_kw("IGNORE")
        self.expect_kw("INTO")
        name = self.ident()
        dbq = None
        if self.accept_op("."):
            dbq, name = name, self.ident()
        ins = A.Insert(name, db=dbq, replace=replace, ignore=ignore)
        if self.accept_op("("):
            ins.columns = [self.ident()]
            while self.accept_op(","):
                ins.columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("SELECT", "WITH"):
            ins.select = self.select_query()
            self._maybe_on_dup(ins)
            return ins
        if self.at_kw("SET"):
            # INSERT ... SET col = expr, ... (single-row sugar)
            self.advance()
            while True:
                ins.columns.append(self.ident())
                self.expect_op("=")
                (ins.rows or ins.rows.append([]) or ins.rows)  # ensure row
                ins.rows[0].append(self.expr())
                if not self.accept_op(","):
                    break
            self._maybe_on_dup(ins)
            return ins
        self.expect_kw("VALUES")
        while True:
            self.expect_op("(")
            row = [self.expr()]
            while self.accept_op(","):
                row.append(self.expr())
            self.expect_op(")")
            ins.rows.append(row)
            if not self.accept_op(","):
                break
        self._maybe_on_dup(ins)
        return ins

    def _maybe_on_dup(self, ins: "A.Insert") -> None:
        """ON DUPLICATE KEY UPDATE col = expr, ... (upsert clause)."""
        if not self.at_kw("ON"):
            return
        self.advance()
        if not self._accept_word("DUPLICATE"):
            raise ParseError("expected DUPLICATE after ON", self.cur)
        self.expect_kw("KEY")
        self.expect_kw("UPDATE")
        while True:
            col = self.ident()
            self.expect_op("=")
            ins.on_dup.append((col, self.expr()))
            if not self.accept_op(","):
                break

    def _resource_group_body(self, name: str, ine: bool,
                             replace: bool) -> A.CreateResourceGroup:
        """RU_PER_SEC = N [BURSTABLE] [QUERY_LIMIT = (EXEC_ELAPSED = '1s'
        [,] ACTION = KILL|COOLDOWN)] (resource-group option grammar)."""
        rg = A.CreateResourceGroup(name, if_not_exists=ine, replace=replace)
        while True:
            if self.cur.kind != "ident":
                break
            opt = self.cur.text.upper()
            if opt == "RU_PER_SEC":
                self.advance()
                self.expect_op("=")
                rg.ru_per_sec = self._int_lit()
            elif opt == "BURSTABLE":
                self.advance()
                rg.burstable = True
            elif opt == "PRIORITY":
                self.advance()
                self.expect_op("=")
                tok = self.cur
                pr = self.advance().text.lower()
                if pr not in ("low", "medium", "high"):
                    raise ParseError("PRIORITY must be LOW|MEDIUM|HIGH",
                                     tok)
                rg.priority = pr
            elif opt == "QUERY_LIMIT":
                self.advance()
                self.expect_op("=")
                self.expect_op("(")
                while not self.at_op(")"):
                    if self.cur.kind == "eof":
                        raise ParseError("unterminated QUERY_LIMIT",
                                         self.cur)
                    sub = self.cur.text.upper()
                    self.advance()
                    self.expect_op("=")
                    if sub == "EXEC_ELAPSED":
                        tok = self.cur
                        txt = self._str_lit().strip().lower()
                        mult = 1.0
                        for suf, m in (("ms", 1e-3), ("s", 1.0),
                                       ("m", 60.0), ("h", 3600.0)):
                            if txt.endswith(suf):
                                txt = txt[:-len(suf)]
                                mult = m
                                break
                        try:
                            rg.exec_elapsed_sec = float(txt) * mult
                        except ValueError:
                            raise ParseError(
                                "bad EXEC_ELAPSED duration", tok)
                    elif sub == "ACTION":
                        tok = self.cur
                        act = self.advance().text.lower()
                        if act == "switch_group":
                            # SWITCH_GROUP(<name>): runaway statements
                            # re-price against the target group
                            self.expect_op("(")
                            rg.switch_target = self.ident().lower()
                            self.expect_op(")")
                        elif act not in ("kill", "cooldown"):
                            raise ParseError(
                                "ACTION must be KILL, COOLDOWN or "
                                "SWITCH_GROUP(<group>)", tok)
                        rg.action = act
                    else:
                        raise ParseError(f"unknown QUERY_LIMIT option "
                                         f"{sub}", self.cur)
                    self.accept_op(",")
                self.expect_op(")")
            else:
                break
        return rg

    def load_data_stmt(self) -> A.LoadData:
        self.expect_kw("LOAD")
        self.expect_kw("DATA")
        self.accept_kw("LOCAL")
        self.expect_kw("INFILE")
        ld = A.LoadData(path=self._str_lit())
        if self.accept_kw("REPLACE"):
            ld.replace = True
        elif self.accept_kw("IGNORE"):
            ld.ignore = True              # without it, dup keys ERROR
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        ld.table = self.ident()
        if self.accept_kw("FIELDS") or self.accept_kw("COLUMNS"):
            while True:
                if self.accept_kw("TERMINATED"):
                    self.expect_kw("BY")
                    ld.field_sep = self._str_lit()
                elif self.accept_kw("ENCLOSED"):
                    self.expect_kw("BY")
                    ld.enclosed = self._str_lit()
                elif self.accept_kw("OPTIONALLY"):
                    self.expect_kw("ENCLOSED")
                    self.expect_kw("BY")
                    ld.enclosed = self._str_lit()
                else:
                    break
        if self.accept_kw("LINES"):
            self.expect_kw("TERMINATED")
            self.expect_kw("BY")
            ld.line_sep = self._str_lit()
        if self.accept_kw("IGNORE"):
            ld.ignore_lines = self._int_lit()
            self.expect_kw("LINES")
        if self.accept_op("("):
            ld.columns = [self.ident()]
            while self.accept_op(","):
                ld.columns.append(self.ident())
            self.expect_op(")")
        return ld

    def update_stmt(self) -> A.Update:
        self.expect_kw("UPDATE")
        name = self.ident()
        dbq = None
        if self.accept_op("."):
            dbq, name = name, self.ident()
        self.expect_kw("SET")
        u = A.Update(name, db=dbq)
        while True:
            col = self.ident()
            self.expect_op("=")
            u.assignments.append((col, self.expr()))
            if not self.accept_op(","):
                break
        if self.accept_kw("WHERE"):
            u.where = self.expr()
        u.order_by, u.limit = self._dml_order_limit()
        return u

    def delete_stmt(self) -> A.Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        name = self.ident()
        dbq = None
        if self.accept_op("."):
            dbq, name = name, self.ident()
        d = A.Delete(name, db=dbq)
        if self.accept_kw("WHERE"):
            d.where = self.expr()
        d.order_by, d.limit = self._dml_order_limit()
        return d

    def _dml_order_limit(self):
        """[ORDER BY ...] [LIMIT n] tail of single-table UPDATE/DELETE."""
        order = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.expr()
                desc = bool(self.accept_kw("DESC")) \
                    or (self.accept_kw("ASC") and False)
                order.append((e, desc))
                if not self.accept_op(","):
                    break
        limit = None
        if self.accept_kw("LIMIT"):
            limit = self._int_lit()
        return order, limit

    def _show_like(self, st: "A.ShowStmt") -> "A.ShowStmt":
        if self.accept_kw("LIKE"):
            st.like = self._str_lit()
        return st

    def show_stmt(self) -> A.ShowStmt:
        self.expect_kw("SHOW")
        if self.accept_kw("CREATE"):
            self.expect_kw("TABLE")
            return A.ShowStmt("create table", self.ident())
        if self.accept_kw("BINDINGS"):
            return A.ShowStmt("bindings")       # target None = both scopes
        if self.at_kw("GLOBAL", "SESSION") \
                and self.toks[self.i + 1].kind == "kw" \
                and self.toks[self.i + 1].text == "BINDINGS":
            scope = self.advance().text.lower()
            self.advance()
            return A.ShowStmt("bindings", scope)
        if self.accept_kw("TABLES"):
            return A.ShowStmt("tables")
        if self.accept_kw("DATABASES"):
            return A.ShowStmt("databases")
        if self.accept_kw("COLUMNS"):
            self.expect_kw("FROM")
            return A.ShowStmt("columns", self.ident())
        if self.accept_kw("VARIABLES"):
            return self._show_like(A.ShowStmt("variables"))
        if self._accept_word("STATUS"):
            return self._show_like(A.ShowStmt("status"))
        if self.accept_kw("GLOBAL", "SESSION"):
            if self._accept_word("STATUS"):
                return self._show_like(A.ShowStmt("status"))
            self.expect_kw("VARIABLES")
            return self._show_like(A.ShowStmt("variables"))
        if self.accept_kw("INDEX", "KEYS"):
            self.expect_kw("FROM")
            return A.ShowStmt("index", self.ident())
        if self.accept_kw("GRANTS"):
            if self.accept_kw("FOR"):
                spec = self._user_spec()
                return A.ShowStmt("grants", f"{spec.user}@{spec.host}")
            return A.ShowStmt("grants")
        if self.accept_kw("COLLATION") or self._accept_word("COLLATION"):
            return self._show_like(A.ShowStmt("collation"))
        if self._accept_word("CHARACTER") or self._accept_word("CHARSET"):
            self._accept_word("SET")
            return self._show_like(A.ShowStmt("charset"))
        if self.cur.kind == "ident" and self.cur.text.upper() in (
                "STATS_META", "STATS_HISTOGRAMS", "STATS_TOPN",
                "STATEMENTS_SUMMARY", "SLOW_QUERIES", "PROCESSLIST"):
            kind = self.cur.text.lower()
            self.advance()
            return A.ShowStmt(kind)
        raise ParseError("unsupported SHOW", self.cur)

    def set_stmt(self) -> A.Node:
        self.expect_kw("SET")
        if self.cur.kind == "ident" and self.cur.text.upper() == "RESOURCE":
            self.advance()
            self.expect_kw("GROUP")
            return A.SetResourceGroup(self.ident().lower())
        if self._accept_word("NAMES"):
            # SET NAMES <charset> [COLLATE <collation>] -> the three
            # connection charset vars (MySQL handshake compat)
            cs = (self._str_lit() if self.cur.kind == "str"
                  else self.ident())
            coll = None
            if self.accept_kw("COLLATE") or self._accept_word("COLLATE"):
                coll = (self._str_lit() if self.cur.kind == "str"
                        else self.ident())
            st = A.SetStmt("session")
            for v in ("character_set_client", "character_set_results",
                      "character_set_connection"):
                st.assignments.append((v, A.Lit(cs, "str")))
            if coll:
                st.assignments.append(
                    ("collation_connection", A.Lit(coll, "str")))
            return st
        scope = "session"
        if self.accept_kw("GLOBAL"):
            scope = "global"
        elif self.accept_kw("SESSION"):
            scope = "session"
        if self._accept_word("TRANSACTION"):
            # SET [SESSION|GLOBAL] TRANSACTION ISOLATION LEVEL ... |
            # READ ONLY|WRITE -> transaction_* sysvars
            st = A.SetStmt(scope)
            while True:
                if self._accept_word("ISOLATION"):
                    self._accept_word("LEVEL")
                    parts = [self.ident().upper()]
                    if parts[0] in ("READ", "REPEATABLE"):
                        parts.append(self.ident().upper())
                    level = "-".join(parts)
                    st.assignments.append(
                        ("transaction_isolation", A.Lit(level, "str")))
                elif self._accept_word("READ"):
                    ro = 1 if self._accept_word("ONLY") else (
                        self._accept_word("WRITE") and 0)
                    st.assignments.append(
                        ("transaction_read_only", A.Lit(int(ro), "int")))
                else:
                    raise ParseError(
                        "expected ISOLATION LEVEL or READ", self.cur)
                if not self.accept_op(","):
                    return st
        st = A.SetStmt(scope)
        while True:
            user_var = False
            if self.accept_op("@"):
                if self.accept_op("@"):    # @@[scope.]sysvar
                    if self.cur.kind == "kw":
                        self.advance()
                        self.expect_op(".")
                else:                      # @uservar
                    user_var = True
            name = self.ident()
            if not self.accept_op("=") and not self.accept_op(":="):
                raise ParseError("expected =", self.cur)
            # MySQL boolean sysvar forms: ON/OFF are keywords, not exprs
            if self.at_kw("ON"):
                self.advance()
                val = A.Lit(1, "int")
            elif (self.cur.kind == "ident"
                  and self.cur.text.upper() == "OFF"):
                self.advance()
                val = A.Lit(0, "int")
            else:
                val = self.expr()
            (st.user_vars if user_var else st.assignments).append((name, val))
            if not self.accept_op(","):
                break
        return st

    # ---------------- expressions (precedence climbing) ---------------- #

    def expr(self) -> A.Node:
        return self.or_expr()

    def or_expr(self) -> A.Node:
        left = self.xor_expr()
        while self.at_kw("OR") or self.at_op("||"):
            self.advance()
            left = A.Binary("OR", left, self.xor_expr())
        return left

    def xor_expr(self) -> A.Node:
        left = self.and_expr()
        while self.accept_kw("XOR"):
            left = A.Binary("XOR", left, self.and_expr())
        return left

    def and_expr(self) -> A.Node:
        left = self.not_expr()
        while self.at_kw("AND") or self.at_op("&&"):
            self.advance()
            left = A.Binary("AND", left, self.not_expr())
        return left

    def not_expr(self) -> A.Node:
        if self.accept_kw("NOT"):
            return A.Unary("NOT", self.not_expr())
        return self.predicate()

    def predicate(self) -> A.Node:
        left = self.bit_or()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">=", "<=>"):
                op = self.advance().text
                if op == "!=":
                    op = "<>"
                right = self.bit_or()
                left = A.Binary(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    sub = self.select_query()
                    self.expect_op(")")
                    left = A.InExpr(left, [A.SubqueryExpr(sub)], negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = A.InExpr(left, items, negated)
                continue
            if self.accept_kw("BETWEEN"):
                low = self.bit_or()
                self.expect_kw("AND")
                high = self.bit_or()
                left = A.BetweenExpr(left, low, high, negated)
                continue
            if self.accept_kw("LIKE"):
                left = A.LikeExpr(left, self.bit_or(), negated)
                continue
            if self._accept_word("REGEXP") or self._accept_word("RLIKE"):
                node = A.FuncCall("REGEXP_LIKE", [left, self.bit_or()])
                left = A.Unary("NOT", node) if negated else node
                continue
            if negated:
                self.i = save
                break
            if self.accept_kw("IS"):
                neg = self.accept_kw("NOT")
                if self.accept_kw("NULL"):
                    left = A.IsNullExpr(left, neg)
                elif self.accept_kw("TRUE"):
                    e = A.Binary("<>", left, A.Lit(0, "int"))
                    left = A.Unary("NOT", e) if neg else e
                elif self.accept_kw("FALSE"):
                    e = A.Binary("=", left, A.Lit(0, "int"))
                    left = A.Unary("NOT", e) if neg else e
                else:
                    raise ParseError("expected NULL/TRUE/FALSE after IS", self.cur)
                continue
            break
        return left

    def bit_or(self) -> A.Node:
        left = self.bit_and()
        while self.at_op("|"):
            self.advance()
            left = A.Binary("|", left, self.bit_and())
        return left

    def bit_and(self) -> A.Node:
        left = self.shift()
        while self.at_op("&"):
            self.advance()
            left = A.Binary("&", left, self.shift())
        return left

    def shift(self) -> A.Node:
        left = self.additive()
        while self.at_op("<<", ">>"):
            op = self.advance().text
            left = A.Binary(op, left, self.additive())
        return left

    def additive(self) -> A.Node:
        left = self.multiplicative()
        while self.at_op("+", "-"):
            op = self.advance().text
            left = A.Binary(op, left, self.multiplicative())
        return left

    def multiplicative(self) -> A.Node:
        left = self.unary()
        while True:
            if self.at_op("*", "/", "%"):
                op = self.advance().text
                left = A.Binary(op, left, self.unary())
            elif self.at_kw("DIV"):
                self.advance()
                left = A.Binary("DIV", left, self.unary())
            elif self.at_kw("MOD"):
                self.advance()
                left = A.Binary("%", left, self.unary())
            else:
                break
        return left

    def unary(self) -> A.Node:
        if self.at_op("-"):
            self.advance()
            return A.Unary("-", self.unary())
        if self.at_op("+"):
            self.advance()
            return self.unary()
        if self.at_op("~"):
            self.advance()
            return A.Unary("~", self.unary())
        return self.primary()

    def primary(self) -> A.Node:
        t = self.cur
        if t.kind == "op" and t.text == "@":
            self.advance()
            if self.accept_op("@"):
                scope = ""
                if self.cur.kind in ("kw", "ident") and \
                        self.cur.text.upper() in ("GLOBAL", "SESSION"):
                    scope = self.advance().text.lower()
                    self.expect_op(".")
                return A.SysVar(self.ident().lower(), scope)
            return A.SysVar(self.ident().lower(), user=True)
        if (t.kind == "kw" and t.text in ("DATABASE", "SCHEMA")
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "("):
            name = t.text
            self.advance()
            self.expect_op("(")
            self.expect_op(")")
            return A.FuncCall(name, [])
        if (t.kind == "kw" and t.text == "INSERT"
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "("):
            # INSERT(str, pos, len, newstr) — the string function
            self.advance()
            self.expect_op("(")
            args = [self.expr()]
            while self.accept_op(","):
                args.append(self.expr())
            self.expect_op(")")
            return A.FuncCall("INSERT", args)
        if (t.kind == "kw" and t.text == "VALUES"
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "("):
            # VALUES(col) inside ON DUPLICATE KEY UPDATE assignments
            self.advance()
            self.expect_op("(")
            inner = self.expr()
            self.expect_op(")")
            return A.FuncCall("VALUES", [inner])
        if t.kind == "int":
            self.advance()
            return A.Lit(int(t.text), "int")
        if t.kind == "decimal":
            self.advance()
            return A.Lit(t.text, "decimal")
        if t.kind == "float":
            self.advance()
            return A.Lit(float(t.text), "float")
        if t.kind == "str":
            self.advance()
            return A.Lit(t.text, "str")
        if self.accept_kw("NULL"):
            return A.Lit(None, "null")
        if self.accept_kw("TRUE"):
            return A.Lit(1, "bool")
        if self.accept_kw("FALSE"):
            return A.Lit(0, "bool")
        if self.at_kw("DATE") and self.toks[self.i + 1].kind == "str":
            self.advance()
            return A.Lit(self.advance().text, "date")
        if self.at_kw("TIMESTAMP") and self.toks[self.i + 1].kind == "str":
            self.advance()
            return A.Lit(self.advance().text, "datetime")
        if self.accept_kw("INTERVAL"):
            val = self.expr()
            unit = self.advance().text.upper()
            return A.Lit(val, "interval", unit)
        if self.at_kw("CASE"):
            return self.case_expr()
        if self.at_kw("CAST", "CONVERT"):
            return self.cast_expr()
        if self.accept_kw("EXISTS"):
            self.expect_op("(")
            sub = self.select_query()
            self.expect_op(")")
            return A.ExistsExpr(sub)
        if self.accept_op("("):
            if self.at_kw("SELECT", "WITH"):
                sub = self.select_query()
                self.expect_op(")")
                return A.SubqueryExpr(sub)
            e = self.expr()
            self.expect_op(")")
            return e
        # function call or identifier
        if t.kind == "ident" or (t.kind == "kw" and t.text in _FUNC_KEYWORDS
                                 ) or (t.kind == "kw" and t.text in _NONRESERVED):
            name = self.advance().text
            if self.at_op("("):
                return self.func_call(name)
            parts = [name]
            while self.at_op(".") and self.toks[self.i + 1].kind in ("ident", "kw"):
                self.advance()
                parts.append(self.ident())
            return A.Ident(tuple(parts))
        raise ParseError("unexpected token in expression", t)

    def case_expr(self) -> A.CaseExpr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.expr()
        branches = []
        while self.accept_kw("WHEN"):
            c = self.expr()
            self.expect_kw("THEN")
            branches.append((c, self.expr()))
        else_ = None
        if self.accept_kw("ELSE"):
            else_ = self.expr()
        self.expect_kw("END")
        return A.CaseExpr(operand, branches, else_)

    def cast_expr(self) -> A.CastExpr:
        self.advance()  # CAST | CONVERT
        self.expect_op("(")
        arg = self.expr()
        if not self.accept_kw("AS"):
            self.expect_op(",")  # CONVERT(x, type)
        tname, prec, scale = self.type_name()
        self.expect_op(")")
        return A.CastExpr(arg, tname, prec, scale)

    def func_call(self, name: str) -> A.Node:
        self.expect_op("(")
        nm = name.upper()
        # SQL-standard special argument forms
        if nm in ("SUBSTRING", "SUBSTR", "MID"):
            first = self.expr()
            if self.accept_kw("FROM"):
                fc = A.FuncCall("SUBSTRING")
                fc.args = [first, self.expr()]
                if self.accept_kw("FOR"):
                    fc.args.append(self.expr())
                self.expect_op(")")
                return fc
            fc = A.FuncCall("SUBSTRING")
            fc.args = [first]
            while self.accept_op(","):
                fc.args.append(self.expr())
            self.expect_op(")")
            return fc
        if nm == "TRIM":
            mode = "BOTH"
            if self.cur.kind == "ident" and self.cur.text.upper() in (
                    "BOTH", "LEADING", "TRAILING"):
                mode = self.advance().text.upper()
                remstr = None
                if not self.at_kw("FROM"):
                    remstr = self.expr()
                self.expect_kw("FROM")
                target = self.expr()
            else:
                first = self.expr()
                if self.accept_kw("FROM"):
                    remstr, target = first, self.expr()
                else:
                    remstr, target = None, first
            self.expect_op(")")
            fc = A.FuncCall({"BOTH": "TRIM", "LEADING": "LTRIM",
                             "TRAILING": "RTRIM"}[mode])
            fc.args = [target] + ([remstr] if remstr is not None else [])
            return fc
        if nm == "EXTRACT":
            unit = self.advance().text.upper()
            self.expect_kw("FROM")
            fc = A.FuncCall("EXTRACT")
            fc.args = [A.Lit(unit, "str"), self.expr()]
            self.expect_op(")")
            return fc
        if nm == "POSITION":
            a = self.bit_or()   # stop below IN so `x IN y` doesn't swallow it
            self.expect_kw("IN")
            fc = A.FuncCall("POSITION")
            fc.args = [a, self.expr()]
            self.expect_op(")")
            return fc
        fc = A.FuncCall(nm)
        if self.at_op("*"):
            self.advance()
            self.expect_op(")")
            fc.args = [A.Star()]
        else:
            if self.accept_kw("DISTINCT"):
                fc.distinct = True
            if not self.at_op(")"):
                fc.args.append(self.expr())
                while self.accept_op(","):
                    fc.args.append(self.expr())
            self.expect_op(")")
        if self.accept_kw("OVER"):
            fc.over = self.window_spec()
        return fc

    def window_spec(self) -> A.WindowSpec:
        self.expect_op("(")
        ws = A.WindowSpec()
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            ws.partition_by.append(self.expr())
            while self.accept_op(","):
                ws.partition_by.append(self.expr())
        if self.at_kw("ORDER"):
            self.advance()
            self.expect_kw("BY")
            ws.order_by = self._order_by_list()
        if self.at_kw("ROWS", "RANGE"):
            unit = self.advance().text.lower()
            ws.frame = (unit,) + self._frame_bounds()
        self.expect_op(")")
        return ws

    def _frame_bounds(self) -> tuple:
        if self.accept_kw("BETWEEN"):
            lo = self._frame_bound()
            self.expect_kw("AND")
            hi = self._frame_bound()
        else:
            lo = self._frame_bound()
            hi = ("current", 0)
        return lo, hi

    def _frame_bound(self) -> tuple[str, int]:
        if self.accept_kw("UNBOUNDED"):
            if self.accept_kw("PRECEDING"):
                return ("unbounded_preceding", 0)
            self.expect_kw("FOLLOWING")
            return ("unbounded_following", 0)
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return ("current", 0)
        n = self._int_lit()
        if self.accept_kw("PRECEDING"):
            return ("preceding", n)
        self.expect_kw("FOLLOWING")
        return ("following", n)


# keywords that can also start function calls (YEAR(x), DATE(x), IF(...))
_FUNC_KEYWORDS = {"YEAR", "MONTH", "DAY", "HOUR", "MINUTE", "SECOND", "IF",
                  "DATE", "TIME", "SUBSTRING", "TRUNCATE", "LEFT", "RIGHT",
                  "MOD", "CHARACTER", "REPLACE"}

# keywords allowed as plain identifiers (column/table names)
_NONRESERVED = {"YEAR", "MONTH", "DAY", "HOUR", "MINUTE", "SECOND", "DATE",
                "TIME", "TIMESTAMP", "COMMENT", "ENGINE", "CHARSET",
                "DATABASES", "TABLES", "VARIABLES", "COLUMNS", "GLOBAL",
                "SESSION", "KEY", "DEFAULT", "ADMIN", "CHECK", "BEGIN",
                "TRANSACTION", "TRUNCATE", "ROW", "ROWS", "RANGE", "OVER",
                "PARTITION", "CURRENT", "WINDOW", "RECURSIVE", "PRECEDING",
                "FOLLOWING", "UNBOUNDED", "USER", "GRANTS", "PRIVILEGES",
                "PASSWORD", "FLUSH", "IDENTIFIED",
                "DATA", "LOCAL", "FIELDS", "LINES", "TERMINATED",
                "ENCLOSED", "OPTIONALLY", "INFILE"}


_HINT_RE = None


def _parse_hints(body: str) -> list[tuple]:
    """`NAME(arg, ...) NAME2(...) ...` -> [(NAME, [args])] (the
    parser_driver optimizer-hint grammar, simplified)."""
    import re
    global _HINT_RE
    if _HINT_RE is None:
        _HINT_RE = re.compile(
            r"([A-Za-z_][A-Za-z0-9_]*)\s*(?:\(([^)]*)\))?")
    out = []
    for m in _HINT_RE.finditer(body):
        args = [a.strip().strip("`") for a in (m.group(2) or "").split(",")
                if a.strip()]
        out.append((m.group(1).upper(), args))
    return out


def parse_sql(sql: str) -> list[A.Node]:
    return Parser(sql).parse()


def parse_one(sql: str) -> A.Node:
    stmts = parse_sql(sql)
    if len(stmts) != 1:
        raise ValueError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


__all__ = ["Parser", "ParseError", "parse_sql", "parse_one"]
