"""Placeholder scanning/binding for prepared statements.

Reference analog: the param-marker handling of pkg/parser (ParamMarkerExpr)
+ expression.ParamMarker binding in plan cache — here params are bound by
splicing SQL literals before parse, shared by the wire-protocol
COM_STMT_EXECUTE path and SQL-level EXECUTE ... USING.
"""

from __future__ import annotations


def scan_sql(sql: str):
    """Yield (char, masked) where masked chars are inside string literals,
    backtick identifiers, or comments — a '?' there is not a placeholder
    (mirrors the lexer's string/comment handling)."""
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch in ("'", '"', "`"):
            quote = ch
            yield ch, True
            i += 1
            while i < n:
                yield sql[i], True
                if sql[i] == "\\" and quote != "`" and i + 1 < n:
                    i += 1
                    yield sql[i], True
                elif sql[i] == quote:
                    i += 1
                    break
                i += 1
            continue
        if ch == "#" or (ch == "-" and sql[i:i + 2] == "--"):
            while i < n and sql[i] != "\n":
                yield sql[i], True
                i += 1
            continue
        if ch == "/" and sql[i:i + 2] == "/*":
            end = sql.find("*/", i + 2)
            end = n if end < 0 else end + 2
            while i < end:
                yield sql[i], True
                i += 1
            continue
        yield ch, False
        i += 1


def count_placeholders(sql: str) -> int:
    return sum(1 for ch, masked in scan_sql(sql)
               if ch == "?" and not masked)


def strip_placeholders(sql: str) -> str:
    """Replace ? with a literal so the statement parses at PREPARE time."""
    return "".join("0" if ch == "?" and not masked else ch
                   for ch, masked in scan_sql(sql))


def bind_placeholders(sql: str, params: list) -> str:
    out = []
    it = iter(params)
    for ch, masked in scan_sql(sql):
        if ch == "?" and not masked:
            out.append(sql_literal(next(it)))
        else:
            out.append(ch)
    return "".join(out)


def sql_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return repr(v)
    import decimal as pydec
    if isinstance(v, pydec.Decimal):
        return str(v)
    s = str(v).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{s}'"


__all__ = ["scan_sql", "count_placeholders", "strip_placeholders",
           "bind_placeholders", "sql_literal"]
