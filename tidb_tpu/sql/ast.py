"""Untyped SQL AST.

Reference analog: pkg/parser/ast (StmtNode/ExprNode hierarchy).  The planner
(planner/build.py) resolves names and types, turning these into the typed
expression IR (expr/ir.py) — same two-stage design as the reference's
ast.ExprNode -> expression.Expression conversion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


class Node:
    pass


# ---------------- expressions ---------------- #

@dataclass
class Ident(Node):
    parts: tuple[str, ...]          # (col,) or (table, col) or (db, table, col)


@dataclass
class SysVar(Node):
    """@@[scope.]sysvar or @uservar in expression position."""
    name: str
    scope: str = ""                 # "" | "session" | "global"
    user: bool = False


@dataclass
class Star(Node):
    table: Optional[str] = None     # t.* support


@dataclass
class Lit(Node):
    value: Any                      # python value
    kind: str                       # 'int' | 'decimal' | 'float' | 'str' | 'null' | 'bool' | 'date' | 'datetime' | 'interval'
    unit: Optional[str] = None      # interval unit


@dataclass
class Unary(Node):
    op: str                         # '-' | 'NOT' | '+' | '~'
    arg: Node = None


@dataclass
class Binary(Node):
    op: str                         # '+','-','*','/','DIV','%','=','<>','<','<=','>','>=','AND','OR','XOR'
    left: Node = None
    right: Node = None


@dataclass
class WindowSpec(Node):
    """OVER (...) clause (reference: ast.WindowSpec, pkg/parser)."""
    partition_by: list[Node] = field(default_factory=list)
    order_by: list[tuple[Node, bool]] = field(default_factory=list)
    # frame: None | ('rows', (lo_kind, lo_n), (hi_kind, hi_n)) with kinds
    # 'unbounded_preceding' | 'preceding' | 'current' | 'following' |
    # 'unbounded_following'
    frame: Optional[tuple] = None


@dataclass
class FuncCall(Node):
    name: str                       # uppercased
    args: list[Node] = field(default_factory=list)
    distinct: bool = False          # COUNT(DISTINCT x)
    over: Optional[WindowSpec] = None  # window function call


@dataclass
class CaseExpr(Node):
    operand: Optional[Node]
    branches: list[tuple[Node, Node]] = field(default_factory=list)
    else_: Optional[Node] = None


@dataclass
class InExpr(Node):
    target: Node
    items: list[Node] = field(default_factory=list)
    negated: bool = False


@dataclass
class BetweenExpr(Node):
    target: Node
    low: Node = None
    high: Node = None
    negated: bool = False


@dataclass
class LikeExpr(Node):
    target: Node
    pattern: Node = None
    negated: bool = False


@dataclass
class IsNullExpr(Node):
    target: Node
    negated: bool = False


@dataclass
class CastExpr(Node):
    arg: Node
    type_name: str                  # 'SIGNED','UNSIGNED','DOUBLE','DECIMAL(p,s)','CHAR','DATE','DATETIME'
    prec: int = -1
    scale: int = -1


@dataclass
class SubqueryExpr(Node):
    select: "SelectStmt" = None
    # scalar subquery / IN (subquery) contexts resolved by planner


@dataclass
class ExistsExpr(Node):
    select: "SelectStmt" = None
    negated: bool = False


# ---------------- table refs ---------------- #

@dataclass
class TableName(Node):
    name: str
    db: Optional[str] = None
    alias: Optional[str] = None
    # stale read: AS OF TIMESTAMP <literal> (sessiontxn/staleread) —
    # an int literal is a raw logical ts, a string parses as a datetime
    as_of: Optional[object] = None
    # table-factor index hints: [('use'|'ignore'|'force', [names])]
    index_hints: list = field(default_factory=list)


@dataclass
class SubqueryRef(Node):
    select: "SelectStmt" = None
    alias: str = ""


@dataclass
class Join(Node):
    kind: str                       # 'inner' | 'left' | 'right' | 'cross'
    left: Node = None
    right: Node = None
    on: Optional[Node] = None
    using: Optional[list[str]] = None


# ---------------- statements ---------------- #

@dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclass
class SelectStmt(Node):
    items: list[SelectItem] = field(default_factory=list)
    from_: Optional[Node] = None
    where: Optional[Node] = None
    group_by: list[Node] = field(default_factory=list)
    rollup: bool = False            # GROUP BY ... WITH ROLLUP
    having: Optional[Node] = None
    order_by: list[tuple[Node, bool]] = field(default_factory=list)  # (expr, desc)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    ctes: list["CTE"] = field(default_factory=list)
    recursive: bool = False         # WITH RECURSIVE
    hints: list[tuple] = field(default_factory=list)  # [(NAME, [args])]
    for_update: bool = False        # SELECT ... FOR UPDATE locking read


@dataclass
class SetOpStmt(Node):
    """UNION / EXCEPT / INTERSECT of two queries (reference:
    ast.SetOprStmt).  Chains are left-deep trees of SetOpStmt."""
    kind: str                       # 'union' | 'except' | 'intersect'
    all: bool = False               # UNION ALL vs DISTINCT
    left: Node = None               # SelectStmt | SetOpStmt
    right: Node = None
    order_by: list[tuple[Node, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: list["CTE"] = field(default_factory=list)
    recursive: bool = False


@dataclass
class CTE(Node):
    """One WITH-list element (reference: ast.CommonTableExpression)."""
    name: str
    columns: list[str] = field(default_factory=list)
    select: Node = None             # SelectStmt | SetOpStmt


@dataclass
class ColumnDef(Node):
    name: str
    type_name: str                  # normalized, e.g. 'BIGINT','DECIMAL','VARCHAR'
    prec: int = -1
    scale: int = -1
    not_null: bool = False
    primary_key: bool = False
    default: Optional[Node] = None
    auto_increment: bool = False
    collation: str = ""             # COLLATE clause ('' = table/charset default)
    members: tuple = ()             # ENUM('a','b') / SET(...) member list
    references: Optional[tuple] = None  # (ref_table, ref_col, on_delete)
    generated: Optional[Node] = None    # [GENERATED ALWAYS] AS (expr)
    generated_stored: bool = False      # STORED vs VIRTUAL


@dataclass
class TTLOption(Node):
    """TTL = col + INTERVAL n unit (reference: ast.TableOption TTL)."""
    column: str = ""
    interval_sec: int = 0
    enable: bool = True


@dataclass
class CreateTable(Node):
    name: str
    db: Optional[str] = None         # CREATE TABLE db.name
    columns: list[ColumnDef] = field(default_factory=list)
    primary_key: list[str] = field(default_factory=list)
    if_not_exists: bool = False
    # inline index defs: (name_or_None, [cols], unique)
    indexes: list[tuple] = field(default_factory=list)
    ttl: Optional[TTLOption] = None
    partition: Optional[PartitionSpec] = None
    foreign_keys: list = field(default_factory=list)  # [ForeignKeyDef]
    temporary: bool = False          # CREATE TEMPORARY TABLE (session-scoped)


@dataclass
class CreateSequence(Node):
    """Reference analog: pkg/ddl/sequence.go + parser sequence options."""
    name: str
    start: int = 1
    increment: int = 1
    min_value: Optional[int] = None
    max_value: Optional[int] = None
    cache: int = 1000
    cycle: bool = False
    if_not_exists: bool = False


@dataclass
class DropSequence(Node):
    name: str
    if_exists: bool = False


@dataclass
class CreateIndex(Node):
    name: str
    table: str
    db: Optional[str] = None
    columns: list[str] = field(default_factory=list)
    unique: bool = False
    if_not_exists: bool = False


@dataclass
class DropIndex(Node):
    name: str
    table: str
    db: Optional[str] = None
    if_exists: bool = False


@dataclass
class AlterTable(Node):
    """Round-1 actions: ('add_index', name, cols, unique) |
    ('drop_index', name) | ('add_column', ColumnDef) |
    ('drop_column', name)."""
    table: str
    db: Optional[str] = None
    actions: list[tuple] = field(default_factory=list)


@dataclass
class PartitionSpec:
    """PARTITION BY clause (reference: parser.y PartitionOpt; model
    meta/model PartitionInfo).  kind 'range': parts = [(name, upper-bound
    int | None for MAXVALUE)], ordered ascending.  kind 'hash': num
    partitions named p0..p{n-1}."""
    kind: str                      # 'range' | 'hash'
    column: str
    parts: list = field(default_factory=list)
    num: int = 0


@dataclass
class ForeignKeyDef:
    """FOREIGN KEY (col) REFERENCES parent(col) [ON DELETE action]
    (parser.y ReferenceDef analog; model meta/model FKInfo)."""
    name: str
    column: str
    ref_table: str
    ref_column: str
    on_delete: str = "restrict"    # restrict | cascade


@dataclass
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW name [(cols)] AS select (parser.y
    CreateViewStmt analog); the select is kept as SQL text and re-planned
    at every expansion, so schema changes flow through."""
    name: str
    columns: list = field(default_factory=list)
    select_sql: str = ""
    or_replace: bool = False


@dataclass
class PlanReplayerDump(Node):
    """PLAN REPLAYER DUMP EXPLAIN <sql> (executor/plan_replayer.go):
    bundle plan + schema + stats + sysvars into a zip for offline
    reproduction."""
    sql: str = ""


@dataclass
class DropView(Node):
    names: list = field(default_factory=list)
    if_exists: bool = False


@dataclass
class DropTable(Node):
    # (db | None, name) tuples — tuples, not dotted strings, so backtick
    # identifiers containing dots round-trip
    names: list[tuple] = field(default_factory=list)
    if_exists: bool = False
    temporary: bool = False      # DROP TEMPORARY TABLE: temp scope ONLY


@dataclass
class CreateDatabase(Node):
    name: str = ""
    if_not_exists: bool = False


@dataclass
class DropDatabase(Node):
    name: str = ""
    if_exists: bool = False


@dataclass
class UseDatabase(Node):
    name: str = ""


@dataclass
class Insert(Node):
    table: str = ""
    db: Optional[str] = None
    columns: list[str] = field(default_factory=list)
    rows: list[list[Node]] = field(default_factory=list)
    select: Optional[SelectStmt] = None
    replace: bool = False           # REPLACE INTO: delete conflicts first
    ignore: bool = False            # INSERT IGNORE: skip dup-key rows
    # ON DUPLICATE KEY UPDATE assignments [(col, expr)] — expr may use
    # VALUES(col) to reference the proposed row (executor/insert.go upsert)
    on_dup: list = field(default_factory=list)


@dataclass
class LoadData(Node):
    """LOAD DATA INFILE (executor/load_data.go analog)."""
    path: str = ""
    table: str = ""
    columns: list[str] = field(default_factory=list)
    field_sep: str = "\t"
    enclosed: str = ""
    line_sep: str = "\n"
    ignore_lines: int = 0
    replace: bool = False
    ignore: bool = False            # IGNORE keyword: skip dup-key rows


@dataclass
class Update(Node):
    table: str = ""
    db: Optional[str] = None
    assignments: list[tuple[str, Node]] = field(default_factory=list)
    where: Optional[Node] = None
    order_by: list = field(default_factory=list)   # [(expr, desc)]
    limit: Optional[int] = None


@dataclass
class Delete(Node):
    table: str = ""
    db: Optional[str] = None
    where: Optional[Node] = None
    order_by: list = field(default_factory=list)   # [(expr, desc)]
    limit: Optional[int] = None


@dataclass
class Explain(Node):
    stmt: Node = None
    analyze: bool = False


@dataclass
class TraceStmt(Node):
    stmt: Node = None


@dataclass
class ShowStmt(Node):
    kind: str = ""                  # 'tables' | 'databases' | 'variables' | 'columns'
    target: Optional[str] = None
    like: Optional[str] = None      # SHOW ... LIKE 'pattern' filter


@dataclass
class SetStmt(Node):
    scope: str = "session"
    assignments: list[tuple[str, Node]] = field(default_factory=list)
    # SET @name = expr (user-defined variables, reference: ast.VariableAssignment IsSystem=false)
    user_vars: list[tuple[str, Node]] = field(default_factory=list)


@dataclass
class PrepareStmt(Node):
    """PREPARE name FROM 'sql' (reference: ast.PrepareStmt)."""
    name: str = ""
    sql: str = ""


@dataclass
class ExecutePrepared(Node):
    """EXECUTE name [USING @a, @b] (reference: ast.ExecuteStmt)."""
    name: str = ""
    using: list[str] = field(default_factory=list)


@dataclass
class DeallocateStmt(Node):
    name: str = ""


@dataclass
class TxnStmt(Node):
    kind: str = ""                  # 'begin' | 'commit' | 'rollback'
    mode: str = ""                  # begin only: '' | 'pessimistic' | 'optimistic'


@dataclass
class AnalyzeTable(Node):
    name: str = ""
    columns: list = field(default_factory=list)   # ANALYZE ... COLUMNS c,...
    predicate_columns: bool = False               # ... PREDICATE COLUMNS
    sample_rate: Optional[float] = None           # WITH r SAMPLERATE


@dataclass
class TruncateTable(Node):
    name: str = ""


# ---------------- users & privileges (reference: ast/misc.go
# CreateUserStmt/GrantStmt, pkg/privilege) ---------------- #

@dataclass
class UserSpec(Node):
    user: str = ""
    host: str = "%"


@dataclass
class CreateUser(Node):
    users: list[tuple[UserSpec, Optional[str]]] = field(default_factory=list)
    if_not_exists: bool = False     # (spec, password)


@dataclass
class AlterUser(Node):
    users: list[tuple[UserSpec, Optional[str]]] = field(default_factory=list)


@dataclass
class DropUser(Node):
    users: list[UserSpec] = field(default_factory=list)
    if_exists: bool = False


@dataclass
class GrantStmt(Node):
    privs: list[str] = field(default_factory=list)  # 'SELECT'... | 'ALL'
    db: str = "*"
    table: str = "*"
    users: list[UserSpec] = field(default_factory=list)


@dataclass
class RevokeStmt(Node):
    privs: list[str] = field(default_factory=list)
    db: str = "*"
    table: str = "*"
    users: list[UserSpec] = field(default_factory=list)


@dataclass
class KillStmt(Node):
    conn_id: int
    query_only: bool = True      # KILL QUERY vs KILL CONNECTION


@dataclass
class FlushStmt(Node):
    what: str = "privileges"


@dataclass
class AdminStmt(Node):
    """ADMIN SHOW DDL JOBS | ADMIN CHECK TABLE t | ADMIN RECOMMEND INDEX
    (reference: ast.AdminStmt)."""
    kind: str = ""      # 'show ddl jobs' | 'check table' | 'recommend index'
    target: Optional[str] = None


@dataclass
class CreateBinding(Node):
    """CREATE [GLOBAL|SESSION] BINDING FOR <stmt> USING <hinted stmt>."""
    scope: str = "global"
    original_sql: str = ""
    bind_sql: str = ""


@dataclass
class DropBinding(Node):
    scope: str = "global"
    original_sql: str = ""


@dataclass
class CreateResourceGroup(Node):
    """CREATE/ALTER RESOURCE GROUP (pkg/resourcegroup meta).  None =
    option not named in the statement (ALTER merges, CREATE defaults)."""
    name: str = ""
    ru_per_sec: Optional[int] = None
    burstable: Optional[bool] = None
    exec_elapsed_sec: Optional[float] = None
    action: Optional[str] = None   # kill | cooldown | switch_group
    switch_target: Optional[str] = None  # SWITCH_GROUP(<name>) target
    priority: Optional[str] = None  # low | medium | high (sched weight)
    if_not_exists: bool = False
    replace: bool = False          # ALTER form


@dataclass
class DropResourceGroup(Node):
    name: str = ""
    if_exists: bool = False


@dataclass
class SetResourceGroup(Node):
    name: str = ""


@dataclass
class SplitTable(Node):
    """SPLIT TABLE t REGIONS n (region-split analog: re-shard the scan
    fan-out)."""
    table: str = ""
    regions: int = 0


__all__ = [n for n in dir() if n[0].isupper()]
