"""SQL lexer.

Reference analog: pkg/parser's lexer (lexer.go, misc.go keyword table).
Hand-written scanner over a MySQL-dialect subset: identifiers (plain and
backtick-quoted), case-insensitive keywords, integer/decimal/float literals,
single/double-quoted strings with '' and backslash escapes, operators,
`--`/`#`/`/* */` comments.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "OFFSET", "AS", "AND", "OR", "NOT", "XOR", "IN", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON",
    "USING", "ASC", "DESC", "DISTINCT", "ALL", "UNION", "EXCEPT",
    "INTERSECT", "CREATE", "TABLE", "DROP", "INSERT", "INTO", "VALUES",
    "UPDATE", "SET", "DELETE", "PRIMARY", "KEY", "UNIQUE", "INDEX", "IF",
    "EXISTS", "DATABASE", "DATABASES", "USE", "SHOW", "TABLES", "EXPLAIN",
    "ANALYZE", "DATE", "TIME", "TIMESTAMP", "INTERVAL", "YEAR", "MONTH",
    "DAY", "HOUR", "MINUTE", "SECOND", "CAST", "CONVERT", "DIV", "MOD",
    "DESCRIBE", "DESC", "BEGIN", "COMMIT", "ROLLBACK", "START",
    "TRANSACTION", "DEFAULT", "AUTO_INCREMENT", "COMMENT", "ENGINE",
    "CHARSET", "COLLATE", "CHARACTER", "SUBSTRING", "TRUNCATE", "GLOBAL",
    "SESSION", "VARIABLES", "COLUMNS", "ADMIN", "CHECK", "WITH", "ALTER",
    "ADD", "KEYS", "COLUMN",
    "RECURSIVE", "OVER", "PARTITION", "ROWS", "RANGE", "UNBOUNDED",
    "PRECEDING", "FOLLOWING", "CURRENT", "ROW", "WINDOW",
    "USER", "GRANT", "REVOKE", "GRANTS", "IDENTIFIED", "PRIVILEGES", "TO",
    "FLUSH", "PASSWORD", "FOR",
    "REPLACE", "IGNORE", "LOAD", "DATA", "INFILE", "LOCAL", "FIELDS",
    "TERMINATED", "ENCLOSED", "OPTIONALLY", "LINES",
    "BINDING", "BINDINGS",
}

# multi-char operators first (maximal munch)
OPERATORS = ["<=>", "<<", ">>", "<>", "!=", "<=", ">=", "||", "&&", ":=",
             "=", "<", ">", "+", "-", "*", "/", "%", "(", ")", ",", ".",
             ";", "|", "&", "^", "~", "@"]


@dataclass(frozen=True)
class Token:
    kind: str      # 'kw' | 'ident' | 'int' | 'decimal' | 'float' | 'str' | 'op' | 'eof'
    text: str      # uppercased for kw
    pos: int


class LexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i) or c == "#":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            if sql.startswith("/*+", i):
                # optimizer hint comment (parser_driver hint analog):
                # surface the body as one token for the hint parser
                toks.append(Token("hint", sql[i + 3:j].strip(), i))
            i = j + 2
            continue
        if c == "`":
            j = i + 1
            while j < n and sql[j] != "`":
                j += 1
            if j >= n:
                raise LexError(f"unterminated identifier at {i}")
            toks.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c in "'\"":
            s, j = _scan_string(sql, i)
            toks.append(Token("str", s, i))
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            tok, j = _scan_number(sql, i)
            toks.append(tok)
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            up = word.upper()
            if up in KEYWORDS:
                toks.append(Token("kw", up, i))
            else:
                toks.append(Token("ident", word, i))
            i = j
            continue
        for op in OPERATORS:
            if sql.startswith(op, i):
                toks.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token("eof", "", n))
    return toks


def _scan_string(sql: str, i: int) -> tuple[str, int]:
    quote = sql[i]
    out = []
    j = i + 1
    n = len(sql)
    while j < n:
        c = sql[j]
        if c == "\\" and j + 1 < n:
            nxt = sql[j + 1]
            out.append({"n": "\n", "t": "\t", "0": "\0", "r": "\r"}.get(nxt, nxt))
            j += 2
            continue
        if c == quote:
            if j + 1 < n and sql[j + 1] == quote:  # '' escape
                out.append(quote)
                j += 2
                continue
            return "".join(out), j + 1
        out.append(c)
        j += 1
    raise LexError(f"unterminated string at {i}")


def _scan_number(sql: str, i: int) -> tuple[Token, int]:
    j = i
    n = len(sql)
    seen_dot = seen_exp = False
    while j < n:
        c = sql[j]
        if c.isdigit():
            j += 1
        elif c == "." and not seen_dot and not seen_exp:
            seen_dot = True
            j += 1
        elif c in "eE" and not seen_exp and j > i:
            if j + 1 < n and (sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                seen_exp = True
                j += 2
            else:
                break
        else:
            break
    text = sql[i:j]
    if seen_exp:
        kind = "float"
    elif seen_dot:
        kind = "decimal"   # MySQL: exact numeric literal
    else:
        kind = "int"
    return Token(kind, text, i), j


__all__ = ["Token", "tokenize", "LexError", "KEYWORDS"]
