from .dag import (AggDesc, AggFunc, Aggregation, CopNode, GroupStrategy,
                  Limit, Projection, Selection, TableScan, TopN,
                  output_dtypes)
from .exec import CopProgram, DeviceBatch, get_program
from .aggregate import GroupKeyMeta, finalize, merge_states, sum_out_dtype

__all__ = [
    "AggDesc", "AggFunc", "Aggregation", "CopNode", "GroupStrategy", "Limit",
    "Projection", "Selection", "TableScan", "TopN", "output_dtypes",
    "CopProgram", "DeviceBatch", "get_program", "GroupKeyMeta", "finalize",
    "merge_states", "sum_out_dtype",
]
