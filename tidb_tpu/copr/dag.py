"""Coprocessor DAG — the serialized pushdown plan.

Reference analog: tipb.DAGRequest / tipb.Executor (the protobuf executor
tree TiDB ships to TiKV/TiFlash coprocessors; see SURVEY.md §A.1 for the
exact node set the in-repo engine handles: TableScan, Selection, Projection,
Aggregation, StreamAgg, TopN, Limit, ExchangeSender/Receiver...).

The TPU build keeps the same tree shape as the unit of pushdown, but the
"coprocessor" compiles the whole tree into ONE fused XLA program per plan
digest (the closure-executor analog, unistore/cophandler/closure_exec.go:468)
instead of interpreting operators row-batch by row-batch.  Nodes are frozen
dataclasses so a DAG hashes to a jit-cache key (analog of the cop cache,
pkg/store/copr/coprocessor_cache.go).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Tuple

from ..expr.ir import Expr
from ..types import dtypes as dt


class AggFunc(enum.Enum):
    COUNT = "count"          # COUNT(expr): non-null count; arg None = COUNT(*)
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    FIRST = "first"          # group key passthrough
    # AVG never reaches the coprocessor: the planner splits it into
    # SUM + COUNT exactly like the reference (SURVEY.md §A.4).  The
    # variance/stddev family is likewise rewritten to SUM/SUM(x^2)/COUNT.
    # Host-side aggregates (aggfuncs breadth; _bind_agg keeps them off the
    # device program):
    BIT_AND = "bit_and"
    BIT_OR = "bit_or"
    BIT_XOR = "bit_xor"
    GROUP_CONCAT = "group_concat"
    ANY_VALUE = "any_value"
    JSON_ARRAYAGG = "json_arrayagg"


@dataclass(frozen=True)
class AggDesc:
    """Aggregate function descriptor (expression/aggregation analog)."""
    func: AggFunc
    arg: Optional[Expr]          # None only for COUNT(*)
    out_dtype: dt.DataType

    def __str__(self) -> str:
        return f"{self.func.value}({self.arg if self.arg is not None else '*'})"


@dataclass(frozen=True)
class CopNode:
    def children(self) -> Tuple["CopNode", ...]:
        return ()


@dataclass(frozen=True)
class TableScan(CopNode):
    """Reads columns of one shard (region analog).  `col_offsets` index into
    the shard's stored column order; the scan's output schema is exactly
    these columns in this order (tipb.TableScan carries ColumnInfos)."""
    col_offsets: Tuple[int, ...]
    col_dtypes: Tuple[dt.DataType, ...]


@dataclass(frozen=True)
class Selection(CopNode):
    child: CopNode = None  # type: ignore[assignment]
    conditions: Tuple[Expr, ...] = ()

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Projection(CopNode):
    child: CopNode = None  # type: ignore[assignment]
    exprs: Tuple[Expr, ...] = ()

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Expand(CopNode):
    """Grouping-sets row replication (GROUP BY ... WITH ROLLUP).

    Reference analog: tipb ExecType_TypeExpand executed at
    unistore/cophandler/mpp.go:638, planned by logical_expand.go:32.
    Output schema: child columns ++ one nullable column per rollup key ++
    gid (int64).  Level l of `levels` replicates every live row keeping
    the first len(keys)-l keys (rolled keys masked NULL); gid = l, so
    GROUPING() lowers to bit tests over gid and rolled NULLs stay
    distinguishable from natural NULLs.
    """
    child: CopNode = None  # type: ignore[assignment]
    keys: Tuple[Expr, ...] = ()
    levels: int = 0

    def children(self):
        return (self.child,)


class GroupStrategy(enum.Enum):
    SCALAR = "scalar"    # no GROUP BY: one output row
    DENSE = "dense"      # small known key domain -> dense group ids
    SORT = "sort"        # device multi-key sort + segment reduce
    SEGMENT = "segment"  # hash -> radix bucket partition + segment reduce
                         # (high NDV: one single-key sort regardless of key
                         # arity, bucket count from stats/copcost)
    SCATTER = "scatter"  # hash -> MULTI-PASS scatter radix partition +
                         # segment reduce (copr/radix.py): per-pass bucket
                         # histogram + exclusive-cumsum offsets + stable
                         # gather/scatter reorder, O(passes*n) data
                         # movement instead of lax.sort's O(n log n)
                         # comparator lanes; optional Pallas TPU kernel
                         # for the fused histogram+scatter inner loop


# strategies whose per-device group tables merge HOST-side (per-device
# group sets are not aligned, so there is no elementwise collective
# merge); consumers: spmd/shuffle host_merge policy, the client's
# regrow loop, contracts/fusion classes
HOST_MERGE_STRATEGIES = (GroupStrategy.SORT, GroupStrategy.SEGMENT,
                         GroupStrategy.SCATTER)

# strategies whose per-device group table is a pow2 `num_buckets` radix
# space regrown from observed __ngroups__ (the hash-partitioned pair)
RADIX_STRATEGIES = (GroupStrategy.SEGMENT, GroupStrategy.SCATTER)

# SCATTER radix geometry (jax-free so contracts/copcost can price passes
# without importing the kernel module): each pass orders RADIX_BITS of
# the partition key — the Pallas kernel as one 2^RADIX_BITS-digit
# histogram+scatter counting sort, the XLA lowering as RADIX_BITS 1-bit
# stable partition subpasses (identical stable permutation either way).
RADIX_BITS = 8
# residual hash bits ordered BELOW the log2(B) bucket bits: two groups
# colliding in the bucket bits alone would interleave into per-run
# duplicate segments (the table overflows toward O(rows) at modest
# NDV); eight residual bits cut that collision space 256x for under one
# extra pass, so observed __ngroups__ stays ~NDV like SEGMENT's
# full-hash ordering.  Remaining collisions are the usual duplicates,
# merged host-side by true key equality.
RADIX_RESIDUAL_BITS = 8
# the partition key must fit int32 (kernel lanes): bucket + residual
# bits clamp to 30, plus one dead-row tail bit above them
RADIX_KEY_BITS_MAX = 30
# rows per kernel grid step (copr/pallas/radix_kernel.TILE reads this):
# sizes the per-tile histogram/offset arrays both on device and in the
# copcost pricing, so the model and the kernel agree by construction
RADIX_TILE = 512
# contract ceiling on the pass count: above this the partition does more
# full-data passes than the comparator sort it replaces would ever pay —
# a malformed (astronomically regrown) bucket space, rejected pre-trace
# and surfaced as a COST-RADIX-PASSES gate finding
MAX_RADIX_PASSES = 8


def radix_key_bits(num_buckets: int) -> int:
    """Ordered partition-key bits for a pow2 bucket space: log2(B)
    bucket bits + residual bits (int32-clamped) + the dead-row tail
    bit.  Shared by the kernels, copcost pricing, and contracts."""
    log2b = max(int(num_buckets - 1).bit_length(), 0)
    return min(log2b + RADIX_RESIDUAL_BITS, RADIX_KEY_BITS_MAX) + 1


def radix_passes(num_buckets: int) -> int:
    """Scatter-partition pass count, RADIX_BITS digit bits per pass.
    The copcost pricing, the contract ceiling, the fusion signature,
    and the kernels all share this one formula.  Computed from the raw
    (unclamped) bit span so an absurd bucket space PRICES absurd —
    the COST-RADIX-PASSES / capacity-shape seam."""
    log2b = max(int(num_buckets - 1).bit_length(), 0)
    return -(-(log2b + RADIX_RESIDUAL_BITS + 1) // RADIX_BITS)


@dataclass(frozen=True)
class Aggregation(CopNode):
    """Partial (per-shard) hash aggregation.

    DENSE strategy: every group-by item must have a known finite code domain
    (dict-encoded string column, or planner-bounded int).  `domain_sizes[i]`
    is that size **including** a NULL slot when nullable; the fused kernel
    reduces into a dense (prod(domain_sizes),) state vector — the psum seam.
    SORT strategy handles unbounded domains via multi-key sort +
    segment-reduce into a fixed-capacity group table.
    SEGMENT strategy is the high-NDV device path: group keys avalanche-hash
    to a power-of-two `num_buckets` radix space whose top bits are the
    bucket id, ONE single-key partition pass orders rows bucket-major
    (residual hash ordering inside each bucket comes free), and each
    bucket's runs segment-reduce into a (num_buckets,) state table
    (copr/segment.py).
    SCATTER strategy replaces that single giant sort with a multi-pass
    scatter radix partition (copr/radix.py): radix_passes(num_buckets)
    stable counting-sort passes (histogram + exclusive cumsum + scatter
    reorder) order rows bucket-major in O(passes*n) data movement.
    `prehashed` (SEGMENT/SCATTER): the LAST scan column carries the
    precomputed per-row key hash, so bucket-space regrow re-entries skip
    re-hashing the key tuple (store/client hoists it once per statement).
    `narrow_sums` (SCALAR/DENSE): agg indexes whose int/decimal SUM the
    planner PROVED (analysis/valueflow, from ANALYZEd column stats) can
    never escape int64 across the whole table — those states accumulate
    a single int64 word instead of (hi, lo) limbs.  Part of the frozen
    hash, so narrow and limb programs key, cache, and fuse apart.
    """
    child: CopNode = None  # type: ignore[assignment]
    group_by: Tuple[Expr, ...] = ()
    aggs: Tuple[AggDesc, ...] = ()
    strategy: GroupStrategy = GroupStrategy.SCALAR
    domain_sizes: Tuple[int, ...] = ()   # DENSE only, aligned with group_by
    group_capacity: int = 0              # SORT only: max distinct groups/shard
    num_buckets: int = 0                 # SEGMENT/SCATTER: pow2 radix space
                                         # = state-table capacity per device
    prehashed: bool = False              # SEGMENT/SCATTER: last scan column
                                         # is the hoisted int64 key hash
    narrow_sums: Tuple[int, ...] = ()    # SCALAR/DENSE: agg indexes with a
                                         # valueflow-proven single-word SUM

    def children(self):
        return (self.child,)

    @property
    def num_groups(self) -> int:
        n = 1
        for s in self.domain_sizes:
            n *= s
        return n

    @property
    def state_capacity(self) -> int:
        """Per-device group-table capacity of a host-merged strategy."""
        return (self.num_buckets
                if self.strategy in RADIX_STRATEGIES
                else self.group_capacity)


@dataclass(frozen=True)
class TopN(CopNode):
    """Per-shard TopN (root merges shard tops, reference cophandler/topn.go).
    `sort_key`/`desc` is the single-key form; `sort_keys` (a tuple of
    (expr, desc) pairs, priority order) carries multi-column ORDER BY —
    the device sorts all keys in one lax.sort (cophandler/topn.go
    multi-ByItem analog)."""
    child: CopNode = None  # type: ignore[assignment]
    sort_key: Expr = None  # type: ignore[assignment]
    desc: bool = False
    limit: int = 0
    nulls_last: bool = False  # MySQL: NULLs first ASC, last DESC
    sort_keys: Tuple = ()     # ((Expr, desc), ...): overrides sort_key/desc

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class Limit(CopNode):
    child: CopNode = None  # type: ignore[assignment]
    limit: int = 0

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class LookupJoin(CopNode):
    """Broadcast lookup join against a host-materialized build side.

    Reference analog: the MPP broadcast join (ExchangeType_Broadcast +
    HashJoinProbeExec, cophandler/mpp_exec.go).  Two device strategies:

    - `unique=True` (FK->unique-PK): each probe row matches at most one
      build row, so the join is a sorted-lookup gather with NO output
      expansion — static shapes, MXU/VPU-friendly (SURVEY.md §2.10 P3).
    - `unique=False` (m:n): sorted-range lookup (lo/hi searchsorted) +
      cumsum slot assignment expands matches into an `out_capacity`-row
      batch; the true output size is reported in the program's extras so
      the dispatcher can regrow and retry (the paging discipline,
      SURVEY.md §5.7).  This replaces the reference's multi-match hash
      probe (join/hash_join_v2.go) — range-gather beats hash tables on TPU.

    The build side arrives as auxiliary program inputs (host-materialized,
    replicated to every device): aux[0] = sorted build keys (int64),
    aux[1] = permutation into build rows, aux[2:] = build columns.
    Output schema = probe schema ++ build columns (probe schema only for
    semi/anti); `kind` inner|left|semi|anti."""
    child: CopNode = None  # type: ignore[assignment]
    probe_key: Expr = None  # type: ignore[assignment]
    kind: str = "inner"
    build_dtypes: Tuple[dt.DataType, ...] = ()
    unique: bool = True
    out_capacity: int = 0          # unique=False only
    null_aware: bool = False       # anti only: NOT IN semantics
    # which aux GROUP carries this join's build side: a fused program may
    # chain several broadcast joins (the fragment tree cut at broadcast
    # exchanges, physicalop/fragment.go analog) — each join level reads
    # its own (sorted keys, perm, build columns) group
    aux_slot: int = 0

    def children(self):
        return (self.child,)


@dataclass(frozen=True)
class FusedDag(CopNode):
    """Multi-payload device program root: N member chains sharing one scan.

    Reference analog: shared-scan / multi-query optimization in compiled
    engines (Flare compiles shared work into one native kernel instead of
    re-executing it per query).  The admission scheduler groups queued
    cop tasks whose chains read the SAME snapshot scan (identical stacked
    device inputs, same mesh) but differ in filters/aggregates, and fuses
    them into ONE program whose output is a tuple with one leaf per
    member — the scan's HBM pass is paid once and XLA CSEs the shared
    subtrees (flatten, masks, common predicates) across members.

    Members must each be fully in-program aggregation chains (the
    contract class checked by analysis.contracts.fusion_signature); the
    node is frozen so the fused program caches on its digest exactly
    like any other cop DAG."""
    members: Tuple[CopNode, ...] = ()

    def children(self):
        return self.members


@dataclass(frozen=True)
class WindowShuffleSpec:
    """Device window-function program spec.

    Reference analog: TiFlash's MPP window execution — an exchange hash-
    partitioned on PARTITION BY feeds per-node sort + window operators
    (cophandler/mpp_exec.go window path, executor/window.go semantics).
    TPU redesign: the scan chain runs per device, rows hash-partition
    over the mesh by PARTITION BY keys via lax.all_to_all, each device
    multi-key-sorts its partitions once and computes every window item
    with segment ops — ONE shard_map program, exchange bytes on ICI.

    `items` is a tuple of (func, arg_expr_or_None, out_dtype);
    supported funcs: row_number | rank | dense_rank (need ORDER BY) and
    count | sum | min | max | avg over the WHOLE partition (no ORDER BY,
    default unbounded frame).  Output schema: child columns ++ one
    column per item (row order unspecified, like any unordered SELECT)."""
    child: CopNode
    partition_keys: Tuple = ()      # (Expr, ...) over child output
    order_keys: Tuple = ()          # ((Expr, desc), ...)
    items: Tuple = ()               # ((func, arg, out_dtype), ...)


@dataclass(frozen=True)
class ShuffleJoinSpec:
    """Cross-device repartition (shuffle) hash join program spec.

    Reference analog: the MPP HashPartition exchange + hash join
    (physicalop/physical_exchange_sender.go:109, executor/shuffle.go:86).
    TPU redesign: both sides' scan chains run per device, rows hash-
    partition over the mesh via lax.all_to_all (parallel/exchange.py), then
    each device runs the sorted-range expand join on its partition and the
    `top` chain (selection/projection/agg/topn/limit) over the join output
    — all inside ONE shard_map program, so exchange bytes ride ICI.

    `left`/`right` are CopNode chains rooted at their own TableScans;
    `left_key`/`right_key` are int64-comparable exprs over each chain's
    output.  `top`'s leaf TableScan reads the joined schema
    (left_dtypes ++ right_dtypes; probe side only for semi/anti)."""
    left: CopNode
    right: CopNode
    left_key: Expr
    right_key: Expr
    kind: str                       # inner | left | semi | anti
    left_dtypes: Tuple[dt.DataType, ...]
    right_dtypes: Tuple[dt.DataType, ...]
    top: CopNode


def output_dtypes(node: CopNode) -> Tuple[dt.DataType, ...]:
    """Schema of a node's output batch/states."""
    if isinstance(node, TableScan):
        return node.col_dtypes
    if isinstance(node, (Selection, Limit)):
        return output_dtypes(node.child)
    if isinstance(node, TopN):
        return output_dtypes(node.child)
    if isinstance(node, Expand):
        return (output_dtypes(node.child)
                + tuple(k.dtype.with_nullable(True) for k in node.keys)
                + (dt.bigint(False),))
    if isinstance(node, Projection):
        return tuple(e.dtype for e in node.exprs)
    if isinstance(node, Aggregation):
        return tuple(a.out_dtype for a in node.aggs)
    if isinstance(node, LookupJoin):
        if node.kind in ("semi", "anti"):
            return output_dtypes(node.child)
        return output_dtypes(node.child) + node.build_dtypes
    if isinstance(node, FusedDag):
        # one payload per member; the scheduler demuxes leaves, nothing
        # downstream consumes a concatenated schema
        return tuple(t for m in node.members for t in output_dtypes(m))
    raise TypeError(node)


def iter_nodes(node: CopNode):
    """Every node of a pushed DAG, root first (pre-order).  The static
    passes (analysis/contracts, copcost, lifetime) walk DAGs constantly;
    one shared iterator keeps their traversal order identical."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        stack.extend(n.children())


def find_expand_join(node: CopNode):
    """The (at most one) non-unique LookupJoin in a pushed DAG, or None —
    programs containing one report true join output size via extras."""
    if isinstance(node, LookupJoin) and not node.unique \
            and node.kind in ("inner", "left"):
        return node
    for c in node.children():
        found = find_expand_join(c)
        if found is not None:
            return found
    return None


def to_multimatch(node: CopNode, out_capacity: int) -> CopNode:
    """Rebuild the DAG with its LookupJoin switched to the non-unique
    (expanding) strategy — the dispatcher's runtime answer to discovering
    duplicate build keys (the reference decides hash-probe shape from NDV
    the same way, join/hash_join_v2.go build-side stats)."""
    import dataclasses
    if isinstance(node, LookupJoin):
        return dataclasses.replace(node, unique=False,
                                   out_capacity=out_capacity)
    if not node.children():
        return node
    kids = tuple(to_multimatch(c, out_capacity) for c in node.children())
    if isinstance(node, (Selection, Projection, Expand, Limit, TopN,
                         Aggregation)):
        return dataclasses.replace(node, child=kids[0])
    return node


def rewrite_lookup(node: CopNode, pred=None, **changes) -> CopNode:
    """Rebuild the DAG with the (pred-matching) LookupJoin's fields
    replaced (runtime strategy switches: multi-match regrow etc.)."""
    import dataclasses
    if isinstance(node, LookupJoin) and (pred is None or pred(node)):
        return dataclasses.replace(node, **changes)
    if not node.children():
        return node
    kids = tuple(rewrite_lookup(c, pred, **changes)
                 for c in node.children())
    if isinstance(node, (Selection, Projection, Expand, Limit, TopN,
                         Aggregation, LookupJoin)):
        return dataclasses.replace(node, child=kids[0])
    return node


def drop_lookup(node: CopNode, keep: bool) -> CopNode:
    """Replace the semi/anti LookupJoin with its probe chain outright:
    `keep=True` passes every probe row (anti vs an empty build),
    `keep=False` passes none (NOT IN with a NULL build key) via a
    constant-false Selection.  Exact — no sentinel keys that could
    collide with real data."""
    import dataclasses

    from ..expr.ir import Const
    if isinstance(node, LookupJoin):
        if keep:
            return node.child
        return Selection(node.child, (Const(dt.bigint(False), 0),))
    if not node.children():
        return node
    kids = tuple(drop_lookup(c, keep) for c in node.children())
    if isinstance(node, (Selection, Projection, Expand, Limit, TopN,
                         Aggregation, LookupJoin)):
        return dataclasses.replace(node, child=kids[0])
    return node


def rewrite_expand_capacity(node: CopNode, new_cap: int) -> CopNode:
    """Rebuild the DAG with the non-unique LookupJoin's out_capacity
    replaced (the dispatcher's regrow-and-retry step)."""
    return rewrite_lookup(node, pred=lambda j: not j.unique,
                          out_capacity=new_cap)


def chain_str(node: CopNode) -> str:
    """Compact fragment chain for EXPLAIN, leaf first:
    'TableScan>Selection>Expand>Aggregation[sort]'."""
    parts = []
    cur = node
    while cur is not None:
        name = type(cur).__name__
        if isinstance(cur, Aggregation):
            name += f"[{cur.strategy.value}]"
        parts.append(name)
        kids = cur.children()
        cur = kids[0] if kids else None
    return ">".join(reversed(parts))


def dag_digest(node: CopNode) -> int:
    """Stable-ish digest used as the jit-compile cache key together with the
    shard capacity bucket (SURVEY.md §A.6)."""
    return hash(node)


__all__ = [
    "AggFunc", "AggDesc", "CopNode", "TableScan", "Selection", "Projection",
    "Expand", "GroupStrategy", "HOST_MERGE_STRATEGIES", "RADIX_STRATEGIES",
    "RADIX_BITS", "RADIX_RESIDUAL_BITS", "MAX_RADIX_PASSES",
    "radix_passes", "radix_key_bits", "Aggregation",
    "TopN", "Limit", "LookupJoin",
    "FusedDag", "ShuffleJoinSpec", "output_dtypes", "dag_digest",
    "iter_nodes", "find_expand_join", "rewrite_lookup", "drop_lookup",
    "chain_str", "rewrite_expand_capacity",
]
