"""Per-query coordinator: in-flight fragment registry + cross-connection
cancellation (VERDICT r3 #9).

Reference analog: pkg/executor/mppcoordmanager (per-query registry of
dispatched MPP tasks, cancel fan-out) + the KILL path
(server/conn.go killConn -> executor interruption).  Execution here is
cooperative: every dispatch loop, retry/backoff iteration, streamed
batch, and host chunk boundary calls ``check_killed()``; KILL QUERY sets
the target session's kill event and the victim raises
``QueryInterrupted`` at its next checkpoint (MySQL error 1317 semantics).
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Optional

# the ACTIVE statement's kill event — set by the session around each
# statement; travels into worker threads via contextvars.copy_context
KILL_EVENT: contextvars.ContextVar = contextvars.ContextVar(
    "kill_event", default=None)

# the active statement's coordinator handle (fragment registry)
QUERY_HANDLE: contextvars.ContextVar = contextvars.ContextVar(
    "query_handle", default=None)


class QueryInterrupted(RuntimeError):
    def __init__(self):
        super().__init__("Query execution was interrupted")


def check_killed() -> None:
    """Cancellation point: cheap enough for per-chunk/per-dispatch use."""
    ev = KILL_EVENT.get()
    if ev is not None and ev.is_set():
        raise QueryInterrupted()


class QueryHandle:
    """One statement's registration: live fragments for observability,
    plus device-scheduler accounting (queue wait / coalesced launches)
    surfaced in EXPLAIN ANALYZE as `schedWait` and in the statement
    summary."""

    __slots__ = ("conn_id", "sql", "started", "fragments", "_mu",
                 "sched_wait_ns", "sched_tasks", "sched_coalesced",
                 "sched_fused", "sched_rus", "sched_retried", "degraded",
                 "compile_ns", "compile_misses",
                 "hbm_predicted", "hbm_measured")

    def __init__(self, conn_id: int, sql: str):
        self.conn_id = conn_id
        self.sql = sql
        self.started = time.time()
        self.fragments: list = []
        self._mu = threading.Lock()
        self.sched_wait_ns = 0     # admission-queue wait, all cop tasks
        self.sched_tasks = 0       # device launches admitted
        self.sched_coalesced = 0   # tasks that rode a shared launch
        self.sched_fused = 0       # tasks served by a cross-query
                                   # fused launch (EXPLAIN `fused`)
        self.sched_rus = 0.0       # priced RUs debited for this
                                   # statement's device work (rc/)
        self.sched_retried = 0     # transient-failure re-launches the
                                   # drain spent on this statement's
                                   # tasks (EXPLAIN `retried`)
        self.degraded = 0          # cop dispatches served by the host
                                   # oracle after a launch quarantine
        self.compile_ns = 0        # program resolve/compile time this
                                   # statement's launches paid (copforge
                                   # compile cache; the compile_wait_ms
                                   # split out of schedWait)
        self.compile_misses = 0    # launches that compiled (vs warm hit)
        self.hbm_predicted = 0     # summed admission HBM predictions of
                                   # this statement's cop tasks (copgauge)
        self.hbm_measured = 0      # summed measured launch peaks (0 =
                                   # backend reported none / ledger off)

    def note_fragment(self, desc: str) -> None:
        with self._mu:
            self.fragments.append((desc, time.time()))

    def note_sched(self, wait_ns: int, coalesced: int,
                   fused: int = 0, rus: float = 0.0,
                   retried: int = 0, compile_ns: int = 0,
                   compile_miss: bool = False,
                   hbm_predicted: int = 0,
                   hbm_measured: int = 0) -> None:
        """Call seam contract (audited, ISSUE 13): ``fused`` is the
        MEMBER COUNT of the launch that served this task (scheduler
        ``_serve_fused`` sets ``task.fused = len(programs)``), so any
        real fusion — 2 members included — satisfies ``fused > 1`` and
        counts the task once.  The scheduler must set ``task.fused`` /
        ``task.coalesced`` BEFORE ``task.finish()``: this method runs
        on the waiter thread right after ``wait()`` returns, and a
        post-finish assignment raced it (the historical undercount).
        ``sched_tasks``/``sched_fused`` flow unchanged into EXPLAIN
        ANALYZE (``tasks:``/``fused:``) and statements_summary
        (Sum_sched_tasks/Sum_fused) so the two surfaces agree."""
        with self._mu:
            self.sched_wait_ns += int(wait_ns)
            self.sched_tasks += 1
            if coalesced > 1:
                self.sched_coalesced += 1
            if fused > 1:
                self.sched_fused += 1
            self.sched_rus += float(rus)
            self.sched_retried += int(retried)
            self.compile_ns += int(compile_ns)
            if compile_miss:
                self.compile_misses += 1
            self.hbm_predicted += int(hbm_predicted)
            self.hbm_measured += int(hbm_measured)

    def note_degraded(self) -> None:
        with self._mu:
            self.degraded += 1


class Coordinator:
    """Domain-wide registry of running statements (mppcoordmanager)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._active: dict[int, QueryHandle] = {}

    def begin(self, conn_id: int, sql: str) -> QueryHandle:
        h = QueryHandle(conn_id, sql)
        with self._mu:
            self._active[conn_id] = h
        return h

    def end(self, conn_id: int) -> None:
        with self._mu:
            self._active.pop(conn_id, None)

    def get(self, conn_id: int) -> Optional[QueryHandle]:
        with self._mu:
            return self._active.get(conn_id)

    def snapshot(self) -> list:
        with self._mu:
            return [(h.conn_id, h.sql, h.started, list(h.fragments))
                    for h in self._active.values()]


__all__ = ["Coordinator", "QueryHandle", "QueryInterrupted",
           "KILL_EVENT", "QUERY_HANDLE", "check_killed"]
