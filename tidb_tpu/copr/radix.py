"""SCATTER-strategy device group-by: multi-pass scatter radix partition
+ segment reduce (the high-NDV follow-on to copr/segment.py).

Motivation (ROADMAP "kill the real-TPU high-NDV cliff"): SEGMENT's
partition pass is one giant single-key ``lax.sort`` — O(n log n)
comparator lanes on hardware built for streaming data movement, and on
real TPU the hndv bench rung still ran at 0.05x a single numpy core
(BENCH_TPU.json).  Flare (PAPERS.md) is the precedent for replacing a
general-purpose engine's sort-based shuffle with native specialized
partitioning; HiFrames compiles dataframe aggregations to tight
partition loops the same way.

Algorithm (per device, static shapes, one traced program):

1. Group keys hash exactly as SEGMENT (copr/segment.key_hash, or the
   hoisted ``prehashed`` column).  The top log2(B) bits of the hash are
   the radix bucket id over the pow2 ``num_buckets`` space; dead rows
   take a tail bucket ``B`` (one extra bit) so they sort last.
2. ``radix_passes(B)`` STABLE counting-sort passes order rows
   bucket-major, RADIX_BITS per pass, LSB digit first: per pass a
   bucket-digit histogram, an exclusive cumsum of bucket offsets, and a
   gather/scatter reorder of the row-index permutation — O(passes * n)
   data movement, no comparator network.  Two interchangeable
   lowerings produce the IDENTICAL stable permutation:

   - XLA (default off-TPU): each RADIX_BITS-digit pass runs as
     RADIX_BITS 1-bit stable partition subpasses — a 1-bit counting
     sort degenerates to one cumsum (the histogram+offsets of a 2-digit
     space) plus one scatter, all fully vectorized.
   - Pallas (default on TPU; ``tidb_tpu_radix_pallas`` sysvar): the
     fused histogram+scatter inner loop runs as hand-written TPU
     kernels (copr/pallas/radix_kernel.py), tile-parallel over the
     grid, exercised in tier-1 through Pallas INTERPRET mode on the
     CPU mesh so the kernel path is tested without hardware.

   Both are stable LSD radix sorts of the same bucket key, so the
   final permutation — and therefore every downstream state — is
   bit-identical between them and across regrows.
3. The shared partition->states suffix of copr/segment.py
   (states_from_partition) detects segment boundaries and
   scatter-reduces into the (num_buckets,) state table: hash collisions
   still split into duplicate partials merged host-side by true key
   equality, ``__ngroups__`` still drives the client's bucket regrow.

Within a bucket, rows keep batch order (stable passes) rather than
residual-hash order, so two groups sharing a bucket may interleave into
extra duplicate segments; at the high NDV this strategy is selected for
(buckets ~ 1.25x groups) multi-group buckets are rare, and duplicates
are merged host-side exactly like hash collisions — correctness never
depends on occupancy, only the observed ``__ngroups__`` does (and the
regrow loop already converges on it: more buckets = more ordered bits
= fewer interleavings).
"""

from __future__ import annotations

import functools
import os

import jax.numpy as jnp
import numpy as np

from ..ops.sortkeys import INT64_MAX
from . import dag as D
from .segment import batch_hash, states_from_partition

# --------------------------------------------------------------------- #
# Pallas gate: sysvar tidb_tpu_radix_pallas (default auto)
#   auto - Pallas kernels on TPU backends, XLA lowering elsewhere
#   on   - Pallas everywhere (interpret mode off-TPU: the tier-1 seam)
#   off  - XLA lowering everywhere
# --------------------------------------------------------------------- #

_PALLAS_MODES = ("auto", "on", "off")
_PALLAS_MODE = [os.environ.get("TIDB_TPU_RADIX_PALLAS", "auto") or "auto"]


def set_pallas_mode(mode: str) -> None:
    m = str(mode).strip().lower()
    if m in ("1", "true"):
        m = "on"
    elif m in ("0", "false"):
        m = "off"
    if m not in _PALLAS_MODES:
        raise ValueError(
            f"tidb_tpu_radix_pallas must be one of {_PALLAS_MODES}, "
            f"got {mode!r}")
    _PALLAS_MODE[0] = m


def pallas_mode() -> str:
    return _PALLAS_MODE[0]


def _pallas_choice(platform: str):
    """(use_pallas, interpret) for the platform a program is being
    traced for.  Interpret mode runs the SAME kernel body through the
    Pallas interpreter — how tier-1 exercises the kernel path on the
    CPU mesh."""
    m = pallas_mode()
    if m == "on":
        return True, platform != "tpu"
    if m == "off":
        return False, False
    return platform == "tpu", False


def cache_token(dag) -> str:
    """Program-cache key component for the Pallas gate: the mode is
    baked into a SCATTER program at trace time, so flipping the sysvar
    must key a fresh program instead of serving the other lowering from
    an lru/compile cache.  Non-SCATTER dags return a constant token —
    their traces never consult the gate."""
    try:
        for n in D.iter_nodes(dag):
            if isinstance(n, D.Aggregation) \
                    and n.strategy is D.GroupStrategy.SCATTER:
                return pallas_mode()
    except (TypeError, AttributeError):
        pass
    return ""


# --------------------------------------------------------------------- #
# the multi-pass scatter partition
# --------------------------------------------------------------------- #

def _partition_xla(bid, bits: int, n: int):
    """Stable LSD radix partition, one bit per subpass, pure XLA: the
    1-bit counting sort's histogram+offsets degenerate to a single
    cumsum (offsets = [0, total_zeros]) and the reorder is one scatter
    of the index permutation — O(n) streaming work per subpass, no
    comparator lanes.  RADIX_BITS subpasses == one priced pass."""
    idx = jnp.arange(n, dtype=jnp.int32)
    pos_iota = jnp.arange(n, dtype=jnp.int32)
    for s in range(bits):
        b = ((bid[idx] >> jnp.int32(s)) & jnp.int32(1)).astype(jnp.int32)  # valueflow: ok - masked to one bit
        zb = jnp.cumsum(jnp.int32(1) - b, dtype=jnp.int32)  # incl. zeros
        nz = zb[n - 1]
        # zeros keep order at offset 0; ones at offset total_zeros
        pos = jnp.where(b == 0, zb - 1, nz + pos_iota - zb)
        idx = jnp.zeros((n,), jnp.int32).at[pos].set(idx)
    return idx


def _partition_pallas(bid, bits: int, n: int, interpret: bool):
    """Stable LSD radix partition via the Pallas counting-sort kernels
    (copr/pallas/radix_kernel.py), RADIX_BITS-digit passes.  Rows pad
    to the kernel tile with a beyond-dead-bucket key so pads stay at
    the very tail of every stable pass and slice back off exactly."""
    from .pallas.radix_kernel import TILE, counting_sort_pass
    n_pad = -(-n // TILE) * TILE
    pad = n_pad - n
    if pad:
        tailkey = jnp.int32((1 << bits) - 1)
        bid = jnp.concatenate([bid, jnp.full((pad,), tailkey, jnp.int32)])
    idx = jnp.arange(n_pad, dtype=jnp.int32)
    digit_mask = jnp.int32((1 << D.RADIX_BITS) - 1)
    for p in range(-(-bits // D.RADIX_BITS)):
        dig = (bid[idx] >> jnp.int32(p * D.RADIX_BITS)) & digit_mask
        idx = counting_sort_pass(dig.astype(jnp.int32), idx, interpret)  # valueflow: ok - digit_mask bounds to RADIX_BITS bits
    return idx[:n]


def scatter_permutation(h, sel, num_buckets: int, n: int, platform: str):
    """Row permutation ordering rows bucket-major over the pow2
    ``num_buckets`` radix space: the partition key is the top
    log2(B) + RADIX_RESIDUAL_BITS bits of the uint64 hash (bucket id
    major, residual hash minor — the residual bits keep co-bucketed
    groups from interleaving into duplicate segments), dead rows in a
    tail key one bit above.  Dispatches to the Pallas kernels or the
    XLA lowering per the gate; both produce THE stable permutation of
    the partition key, so results are bit-identical."""
    bits = D.radix_key_bits(num_buckets)
    key_bits = bits - 1                   # top bit = dead-row tail key
    # np scalar: stays 64-bit regardless of the embedder's x64 flag
    key = (h >> np.uint64(64 - key_bits)).astype(jnp.int32)  # valueflow: ok - top key_bits <= 31 bits survive the shift
    key = jnp.where(sel, key, jnp.int32(1 << key_bits))
    use_pallas, interpret = _pallas_choice(platform)
    if use_pallas:
        return _partition_pallas(key, bits, n, interpret)
    return _partition_xla(key, bits, n)


def agg_scatter_states(agg: D.Aggregation, batch, ev, memo) -> dict:
    """SCATTER-strategy per-device partial states: multi-pass scatter
    radix partition + the shared segment-reduce suffix.  State layout,
    host merge, and the ``__ngroups__`` regrow contract are identical
    to SEGMENT — only the partition pass differs."""
    from .exec import _sel_array, group_keyinfo, trace_platform
    B = agg.num_buckets
    assert B > 0 and (B & (B - 1)) == 0, \
        "SCATTER aggregation needs a power-of-two num_buckets"
    assert D.radix_passes(B) <= D.MAX_RADIX_PASSES, \
        "SCATTER pass count exceeds MAX_RADIX_PASSES (contract-checked)"
    n = len(batch.cols[0][0]) if batch.cols else 0
    sel = _sel_array(batch.sel, n)

    keyinfo = group_keyinfo(agg, batch, ev, memo, n)
    h = batch_hash(agg, batch, keyinfo, n)
    idx = scatter_permutation(h, sel, B, n, trace_platform())
    # boundary detection compares the FULL hash (not just bucket bits):
    # same int64 view + dead-row parking convention as SEGMENT
    hv = jnp.where(sel, h.astype(jnp.int64), INT64_MAX)
    return states_from_partition(agg, batch, ev, keyinfo, hv[idx], idx,
                                 sel[idx], n)


# --------------------------------------------------------------------- #
# prehash hoist (regrow re-entries reuse the hashed keys)
# --------------------------------------------------------------------- #

def prehash_plan(agg: D.Aggregation, hash_offset: int):
    """If this radix-strategy aggregation can hoist its key hash, return
    ``(prehashed_dag, leaf_scan)`` — the rebuilt dag whose leaf scan
    reads one extra int64 column at ``hash_offset`` (the stacked hash
    array the client appends), plus the ORIGINAL leaf scan the hash
    program evaluates keys over; else None.  Hoistable: a plain
    TableScan(+Selection) chain — a Projection/Expand/join would change
    the batch schema the appended column rides on."""
    import dataclasses
    if agg.strategy not in D.RADIX_STRATEGIES or agg.prehashed:
        return None
    chain = []
    cur = agg.child
    while isinstance(cur, D.Selection):
        chain.append(cur)
        cur = cur.child
    if not isinstance(cur, D.TableScan):
        return None
    from ..types import dtypes as dt
    new_scan = D.TableScan(cur.col_offsets + (hash_offset,),
                           cur.col_dtypes + (dt.bigint(False),))
    node: D.CopNode = new_scan
    for sel_node in reversed(chain):
        node = dataclasses.replace(sel_node, child=node)
    return dataclasses.replace(agg, child=node, prehashed=True), cur


class HashProgram:
    """Tiny sharded program computing the per-row uint64 key hash over
    the stacked scan columns, stored as int64 in the same (S, C) layout
    — launched ONCE per statement so every bucket-space regrow re-entry
    reuses it (the prehash satellite).  Dead/pad rows hash too (their
    lanes are masked downstream by ``sel``), so no live-count input is
    needed and the program is capacity-independent.  Resolves through
    the copforge compile cache like every spmd builder (keyed on a
    minimal keys-only dag + a ``keyhash`` variant tag), so the hash
    program warms/persists and never re-compiles on the serving path
    after a restart."""

    def __init__(self, scan: D.TableScan, group_by: tuple, mesh):
        import jax
        from jax.sharding import PartitionSpec as P

        from ..compilecache import cached_call
        from ..expr.compile import Evaluator
        from ..parallel.mesh import SHARD_AXIS, shard_map
        self.mesh = mesh
        self.scan = scan
        self.group_by = group_by
        # the keys-only dag identifying WHAT is hashed (scan + key
        # exprs); num_buckets never shapes the program
        key_dag = D.Aggregation(scan, tuple(group_by), (),
                                D.GroupStrategy.SCATTER, num_buckets=1)

        def device_fn(cols, counts):
            del counts
            from .exec import DeviceBatch, group_keyinfo
            from .segment import key_hash
            s, c = cols[0][0].shape
            flat = [(v.reshape(-1), True if m is None else m.reshape(-1))
                    for v, m in cols]
            picked = [flat[off] for off in scan.col_offsets]
            batch = DeviceBatch(list(picked), True)
            keyinfo = group_keyinfo(key_dag, batch, Evaluator(jnp), {},
                                    s * c)
            hv = key_hash(keyinfo, s * c).astype(jnp.int64)
            return hv.reshape(s, c)

        self._fn = jax.jit(shard_map(
            device_fn, mesh=mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P(SHARD_AXIS)))
        self._cached = cached_call(self._fn, key_dag, mesh, "solo",
                                   extra=("keyhash",))

    def __call__(self, cols, counts):
        return self._cached(tuple(cols), counts)


@functools.lru_cache(maxsize=64)
def get_hash_program(scan: D.TableScan, group_by: tuple,
                     mesh) -> HashProgram:
    return HashProgram(scan, group_by, mesh)


# --------------------------------------------------------------------- #
# per-pass phase microbench (the bench hndv rung's breakdown)
# --------------------------------------------------------------------- #

def phase_bench(n: int, num_buckets: int, iters: int = 3) -> dict:
    """Measured per-pass phase times (histogram / cumsum / scatter ms)
    of the partition over synthetic digits, plus the priced pass count
    — the bench JSON's ``radix_breakdown``.  Single-device: the phases
    are per-device work, the mesh only multiplies them.  Rows cap at
    2^20 (the reported ``rows``) so the advisory microbench never
    dominates a rung's wall/memory budget."""
    import time

    import jax
    n = max(min(n, 1 << 20), 1)       # host int: bench-sized row cap
    rng = np.random.default_rng(17)
    dig = jnp.asarray(rng.integers(0, 1 << D.RADIX_BITS, n),
                      dtype=jnp.int32)
    idx = jnp.arange(n, dtype=jnp.int32)
    nd = 1 << D.RADIX_BITS

    @jax.jit
    def hist_phase(d):
        return jnp.zeros((nd,), jnp.int32).at[d].add(1)

    @jax.jit
    def cumsum_phase(h):
        return jnp.cumsum(h, dtype=jnp.int32) - h

    @jax.jit
    def scatter_phase(d, ix):
        zb = jnp.cumsum(jnp.int32(1) - (d & 1), dtype=jnp.int32)
        pos_iota = jnp.arange(n, dtype=jnp.int32)
        pos = jnp.where((d & 1) == 0, zb - 1, zb[n - 1] + pos_iota - zb)
        return jnp.zeros((n,), jnp.int32).at[pos].set(ix)

    def timed(fn, *args):
        jax.block_until_ready(fn(*args))          # compile outside timing
        best = float("inf")
        for _ in range(max(iters, 1)):
            t = time.time()
            jax.block_until_ready(fn(*args))
            best = min(best, time.time() - t)
        return round(best * 1e3, 3)

    hist = hist_phase(dig)
    return {"passes": D.radix_passes(num_buckets), "rows": n,
            "histogram_ms": timed(hist_phase, dig),
            "cumsum_ms": timed(cumsum_phase, hist),
            "scatter_ms": timed(scatter_phase, dig, idx)}


__all__ = ["agg_scatter_states", "scatter_permutation", "prehash_plan",
           "get_hash_program", "HashProgram", "set_pallas_mode",
           "pallas_mode", "cache_token", "phase_bench"]
