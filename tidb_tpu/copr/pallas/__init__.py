"""Hand-written Pallas TPU kernels for coprocessor hot paths.

This package holds the repo's Pallas kernels — established by the
SCATTER radix-partition kernel (radix_kernel.py) and gated module-wide
by the TPU-PALLAS-SHAPE lint rule (analysis/lint.py): kernel bodies
here must keep static grid/block shapes and never reach for host
callbacks, the two patterns that silently destroy TPU kernel
performance or portability.  Every kernel must be exercisable through
Pallas INTERPRET mode so tier-1 covers the kernel path on the CPU mesh.
"""

from .radix_kernel import TILE, counting_sort_pass

__all__ = ["TILE", "counting_sort_pass"]
