"""Pallas TPU kernels for the scatter radix partition inner loop.

The repo's FIRST Pallas kernels — the pattern-setter for every future
hot-path kernel (ROADMAP: "hand-write the histogram/scatter inner loop
as a Pallas TPU kernel").  One priced radix pass = one stable counting
sort over a 2^RADIX_BITS-digit space, split into the two kernels below
(see /opt accelerator guide: VMEM-tiled, VPU-shaped one-hot compute,
static grids):

- ``_hist_kernel``: per-tile digit histogram.  Each grid step loads one
  (TILE,) digit block into VMEM and reduces a (TILE, N_DIGITS) one-hot
  compare along the tile axis — pure VPU work, no scatter.
- ``_scatter_kernel``: the FUSED histogram+scatter inner loop.  Each
  grid step recomputes its tile's one-hot (cheaper in-register than a
  second HBM round-trip), turns the running cumsum into stable
  within-tile ranks, adds the tile's exclusive digit base offsets, and
  stores the permutation values at their final positions.

Between the kernels sits one exclusive cumsum over the tiny
(N_DIGITS * n_tiles,) histogram — digit-major so tile t's digit-d rows
land after every earlier tile's digit-d rows: stability across tiles,
which is what makes the multi-pass LSD composition a true sort and
keeps the result bit-identical to the XLA 1-bit lowering
(copr/radix._partition_xla).

Interpret mode (``interpret=True``) runs the SAME kernel bodies through
the Pallas interpreter — tier-1 exercises this path on the CPU mesh, so
the kernels are tested without TPU hardware; compiled mode is the
real-TPU hardware-window follow-up recorded in TPU_ATTEMPTS.jsonl.

Shape discipline (TPU-PALLAS-SHAPE gate rule): every grid and block
shape below is static — derived from the padded row count and the
module constants, never from traced values — and nothing here may call
back into the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..dag import RADIX_BITS, RADIX_TILE

# rows per grid step: one VMEM-resident block of digits/values.  At 512
# the (TILE, N_DIGITS) one-hot is 512KiB of int32 lanes — comfortably
# inside VMEM next to the value block — while amortizing grid overhead;
# the constant lives in copr/dag so copcost prices the same tiling.
TILE = RADIX_TILE
N_DIGITS = 1 << RADIX_BITS


def _hist_kernel(dig_ref, hist_ref):
    """Per-tile digit histogram: (TILE,) digits -> (1, N_DIGITS) counts
    via a one-hot compare + tile-axis sum (VPU-shaped, no scatter)."""
    digs = dig_ref[:]
    onehot = digs[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (TILE, N_DIGITS), 1)
    hist_ref[0, :] = jnp.sum(onehot, axis=0, dtype=jnp.int32)


def _scatter_kernel(dig_ref, val_ref, off_ref, out_ref):
    """Fused histogram+scatter: recompute the tile's one-hot, derive
    stable within-tile ranks from its running cumsum, and store each
    value at base_offset[digit] + rank — the reorder half of one
    counting-sort pass."""
    digs = dig_ref[:]
    vals = val_ref[:]
    base = off_ref[0, :]
    onehot = digs[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (TILE, N_DIGITS), 1)
    oh = onehot.astype(jnp.int32)  # valueflow: ok - one-hot lane, [0, 1]
    rank = jnp.cumsum(oh, axis=0, dtype=jnp.int32) - oh  # exclusive/digit
    within = jnp.sum(jnp.where(onehot, rank, 0), axis=1, dtype=jnp.int32)
    pos = base[digs] + within

    def body(i, carry):
        out_ref[pos[i]] = vals[i]
        return carry

    jax.lax.fori_loop(0, TILE, body, 0)


def counting_sort_pass(dig, val, interpret: bool = False):
    """One stable counting-sort pass: reorder ``val`` by the N_DIGITS-
    valued ``dig`` keys, preserving order within equal digits.  Row
    count must be a TILE multiple (copr/radix pads with a tail key).
    Returns the reordered values; composing passes LSB-digit-first
    yields the stable LSD radix sort of the full bucket id."""
    n = dig.shape[0]
    n_tiles = n // TILE
    hist = pl.pallas_call(
        _hist_kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,))],
        out_specs=pl.BlockSpec((1, N_DIGITS), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((n_tiles, N_DIGITS), jnp.int32),
        interpret=interpret,
    )(dig)
    # exclusive cumsum over (digit, tile)-major counts: digit d's tile t
    # base = all smaller digits + digit d's earlier tiles (stability)
    flat = hist.T.reshape(-1)
    offs = (jnp.cumsum(flat, dtype=jnp.int32) - flat).reshape(
        N_DIGITS, n_tiles).T
    return pl.pallas_call(
        _scatter_kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,)),
                  pl.BlockSpec((TILE,), lambda t: (t,)),
                  pl.BlockSpec((1, N_DIGITS), lambda t: (t, 0))],
        out_specs=pl.BlockSpec((n,), lambda t: (0,)),
        out_shape=jax.ShapeDtypeStruct((n,), val.dtype),
        interpret=interpret,
    )(dig, val, offs)


__all__ = ["TILE", "N_DIGITS", "counting_sort_pass"]
