"""Aggregation partial-state merge + finalize (host side).

Reference analog: the root-side final HashAgg workers
(pkg/executor/aggregate/agg_hash_final_worker.go) merging cop-side partial
states, per the partial-state contract of SURVEY.md §A.4: partial states
travel as plain named arrays; algebraic merges are sums/mins/maxs, so the
SPMD path replaces this whole module with psum/pmin/pmax on-device
(parallel/collectives.py) — this host path is used for single-shard results,
uneven leftovers, and as the differential-testing oracle.

Decimal SUM exactness: device partials are (hi, lo) int64 limb sums;
recombination (hi<<32)+lo happens here in Python ints (arbitrary precision),
then range-checks back into decimal64.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ..chunk.column import Column, StringDict
from ..types import dtypes as dt
from . import dag as D

K = dt.TypeKind


@dataclass
class GroupKeyMeta:
    """How to decode one dense group-key radix back into values."""
    dtype: dt.DataType
    size: int                      # domain size incl. NULL slot if nullable
    dictionary: Optional[StringDict] = None


# --------------------------------------------------------------------- #
# merge
# --------------------------------------------------------------------- #

_MERGE = {
    "count": "sum", "sum": "sum", "hi": "sum", "lo": "sum", "cnt": "sum",
    "min": "min", "max": "max", "__rows__": "sum",
}


def merge_field(name: str, a, b):
    how = _MERGE[name]
    if how == "sum":
        return a + b
    return np.minimum(a, b) if how == "min" else np.maximum(a, b)


def merge_states(states_list: Sequence[dict]) -> dict:
    """Merge per-shard partial states.  Sums are merged in object dtype so
    limb totals can't overflow int64 across many shards."""
    def promote(name, arr):
        arr = np.asarray(arr)
        if _MERGE[name] == "sum" and arr.dtype == np.int64:
            return arr.astype(object)
        return arr

    out: dict = {}
    for st in states_list:
        for key, val in st.items():
            if isinstance(val, dict):
                tgt = out.setdefault(key, {})
                for f, arr in val.items():
                    arr = promote(f, arr)
                    tgt[f] = arr if f not in tgt else merge_field(f, tgt[f], arr)
            else:
                arr = promote(key, val)
                out[key] = arr if key not in out else merge_field(key, out[key], arr)
    return out


def _np_key_code(val: np.ndarray, valid: np.ndarray,
                 dtype: dt.DataType) -> np.ndarray:
    """Bit-stable int64 representation of group-key values for host-side
    equality grouping (floats via the order-preserving bitcast so NaN
    groups with NaN; NULLs zeroed — the null flag column disambiguates)."""
    v = np.asarray(val)
    if dtype.is_float:
        f = v.astype(np.float64)
        f = np.where(f == 0, 0.0, f)  # -0.0 groups with +0.0 (SQL equality)
        b = np.ascontiguousarray(f).view(np.int64)
        c = np.where(b < 0, -(b + 1) + (-2 ** 63), b)
    else:
        c = v.astype(np.int64)
    return np.where(np.asarray(valid), c, 0)


def merge_sorted_states(agg: D.Aggregation,
                        per_dev: Sequence[dict]) -> dict:
    """Merge SORT-strategy per-device group tables: trim each to its live
    group count, concatenate, and re-group by key equality (np.unique) —
    the root-side final-HashAgg-worker role for unbounded key domains.
    Sums merge in object ints (exact)."""
    k = len(agg.group_by)
    tables: list[dict] = []
    for st in per_dev:
        g = int(st["__ngroups__"])
        trimmed = {name: {f: np.asarray(a)[:g] for f, a in v.items()}
                   if isinstance(v, dict) else np.asarray(v)[:g]
                   for name, v in st.items() if name != "__ngroups__"}
        tables.append(trimmed)

    def cat(path):
        parts = []
        for t in tables:
            v = t
            for p in path:
                v = v[p]
            parts.append(v)
        return np.concatenate(parts) if parts else np.empty(0)

    mat = np.empty((len(cat(("__rows__",))), 2 * k), np.int64)
    key_vals, key_valids = [], []
    for j, e in enumerate(agg.group_by):
        val = cat((f"k{j}", "val"))
        valid = cat((f"k{j}", "valid")).astype(bool)
        key_vals.append(val)
        key_valids.append(valid)
        mat[:, 2 * j] = (~valid).astype(np.int64)
        mat[:, 2 * j + 1] = _np_key_code(val, valid, e.dtype)

    uniq, first_idx, inv = np.unique(mat, axis=0, return_index=True,
                                     return_inverse=True)
    ng = len(uniq)

    def regroup(name, arr):
        how = _MERGE[name]
        arr = np.asarray(arr)
        if how == "sum":
            if arr.dtype == np.int64:
                arr = arr.astype(object)  # exact limb/count merge
            out = np.zeros(ng, dtype=arr.dtype)
            np.add.at(out, inv, arr)
            return out
        if arr.dtype.kind == "f":
            sentinel = np.inf if how == "min" else -np.inf
        else:
            info = np.iinfo(arr.dtype)  # sentinel in the ARRAY's dtype —
            sentinel = info.max if how == "min" else info.min
        init = np.full(ng, sentinel, arr.dtype)
        (np.minimum if how == "min" else np.maximum).at(init, inv, arr)
        return init

    merged: dict = {"__rows__": regroup("__rows__", cat(("__rows__",)))}
    for j in range(k):
        merged[f"k{j}"] = {"val": key_vals[j][first_idx],
                           "valid": key_valids[j][first_idx]}
    for i in range(len(agg.aggs)):
        name = f"a{i}"
        merged[name] = {f: regroup(f, cat((name, f)))
                        for f in tables[0][name]} if tables else {}
    return merged


def finalize_sorted(agg: D.Aggregation, merged: dict,
                    key_meta: Sequence[GroupKeyMeta]
                    ) -> tuple[list[Column], list[Column]]:
    """(group_key_columns, agg_value_columns) for SORT-strategy results."""
    key_cols = []
    for j, m in enumerate(key_meta):
        val = merged[f"k{j}"]["val"]
        valid = merged[f"k{j}"]["valid"]
        npdt = m.dtype.np_dtype()
        data = (np.array([int(x) for x in val], dtype=object)
                if npdt == object else val.astype(npdt))
        key_cols.append(Column(m.dtype, data, valid, m.dictionary))
    agg_cols = [_finalize_one(a, merged[f"a{i}"])
                for i, a in enumerate(agg.aggs)]
    return key_cols, agg_cols


# --------------------------------------------------------------------- #
# finalize
# --------------------------------------------------------------------- #

def finalize(agg: D.Aggregation, merged: dict,
             key_meta: Sequence[GroupKeyMeta]) -> tuple[list[Column], list[Column]]:
    """Turn merged states into (group_key_columns, agg_value_columns),
    dropping empty dense groups (occupancy == 0)."""
    rows = np.asarray(merged["__rows__"])
    if agg.strategy == D.GroupStrategy.SCALAR:
        live = np.array([0])  # single pseudo-group; SQL returns 1 row
        rows = rows.reshape(1)
    else:
        live = np.nonzero(rows > 0)[0]

    key_cols = _decode_group_keys(live, key_meta) \
        if agg.strategy == D.GroupStrategy.DENSE else []

    agg_cols: list[Column] = []
    for i, a in enumerate(agg.aggs):
        st = {f: np.asarray(v).reshape(-1)[live] for f, v in merged[f"a{i}"].items()}
        agg_cols.append(_finalize_one(a, st))
    return key_cols, agg_cols


def _decode_group_keys(live: np.ndarray,
                       key_meta: Sequence[GroupKeyMeta]) -> list[Column]:
    """Invert the mixed-radix dense group id (exec._dense_group_ids)."""
    cols: list[Column] = []
    rem = live.astype(np.int64)
    strides = []
    s = 1
    for m in reversed(key_meta):
        strides.append(s)
        s *= m.size
    strides.reverse()
    for m, stride in zip(key_meta, strides):
        code = (rem // stride) % m.size
        if m.dtype.nullable:
            valid = code > 0
            code = np.maximum(code - 1, 0)
        else:
            valid = np.ones(len(code), bool)
        data = code.astype(m.dtype.np_dtype())
        cols.append(Column(m.dtype, data, valid, m.dictionary))
    return cols


def _finalize_one(a: D.AggDesc, st: dict) -> Column:
    n = len(next(iter(st.values())))
    out_t = a.out_dtype
    if a.func == D.AggFunc.COUNT:
        return Column(out_t, np.asarray(st["count"], np.int64),
                      np.ones(n, bool))
    cnt = np.asarray(st["cnt"], dtype=object)
    valid = (cnt > 0).astype(bool)
    if a.func == D.AggFunc.SUM:
        if "hi" in st:  # decimal limbs
            total = (st["hi"].astype(object) << 32) + st["lo"].astype(object)
            data = np.where(valid, total, 0)
        else:
            data = np.where(valid, st["sum"], 0)
        if out_t.kind != K.FLOAT64:
            _check_decimal_range(data, out_t.prec)
        if out_t.np_dtype() == object:
            data = np.array([int(x) for x in data], dtype=object)
        else:
            data = data.astype(out_t.np_dtype())
        return Column(out_t, data, valid)
    if a.func in (D.AggFunc.MIN, D.AggFunc.MAX):
        field = "min" if a.func == D.AggFunc.MIN else "max"
        data = np.where(valid, st[field], 0).astype(out_t.np_dtype())
        return Column(out_t, data, valid)
    raise NotImplementedError(a.func)


def _check_decimal_range(total: np.ndarray, prec: int) -> None:
    # MySQL raises ER_DATA_OUT_OF_RANGE when a decimal result exceeds its
    # declared precision (mydecimal.go overflow)
    if prec <= 0:
        prec = dt.DECIMAL_MAX_PRECISION
    lim = 10 ** prec
    bad = [int(t) for t in np.asarray(total).reshape(-1) if abs(int(t)) >= lim]
    if bad:
        raise OverflowError(
            f"DECIMAL sum out of range (> {prec} digits): {bad[0]}")


def sum_out_dtype(arg_t: dt.DataType) -> dt.DataType:
    """MySQL result type of SUM(arg): decimals widen by 22 digits
    (reference: expression/aggregation typeinfer, DECIMAL(min(p+22,65),s))
    bounded to the 38-digit exact limb representation."""
    if arg_t.kind == K.DECIMAL:
        p = arg_t.prec if arg_t.prec > 0 else dt.DECIMAL64_MAX_PRECISION
        return dt.decimal_wide(p + 22, arg_t.scale)
    if arg_t.kind in (K.FLOAT32, K.FLOAT64):
        return dt.double()
    return dt.decimal_wide(dt.DECIMAL_MAX_PRECISION, 0)  # SUM(int)


__all__ = ["GroupKeyMeta", "merge_states", "finalize", "sum_out_dtype"]
