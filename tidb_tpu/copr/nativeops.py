"""ctypes loader for the native host aggregation primitives.

Reference analog: the reference's aggregation hot loops are compiled Go
(agg_hash_executor.go); ours are C++ (native/hostops.cpp) behind numpy
fallbacks — `count_keys`/`gather_lookup` return None-equivalent behavior
by the caller checking `available()` first.  Build failures degrade to
the numpy path silently: the native library is an accelerator, never a
correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir, "native"))
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpuhostops.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            src = os.path.join(_NATIVE_DIR, "hostops.cpp")
            if (not os.path.exists(_LIB_PATH)
                    or os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)):
                subprocess.run(["make", "-C", _NATIVE_DIR,
                                "libtpuhostops.so"],
                               check=True, capture_output=True)
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                # ABI mismatch (built on a newer glibc): rebuild locally
                subprocess.run(["make", "-B", "-C", _NATIVE_DIR,
                                "libtpuhostops.so"],
                               check=True, capture_output=True)
                lib = ctypes.CDLL(_LIB_PATH)
            I64, I32P, I64P = (ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_int32),
                               ctypes.POINTER(ctypes.c_int64))
            lib.hops_count_i32.argtypes = [I32P, I64, I64, I32P]
            lib.hops_count_i64.argtypes = [I64P, I64, I64, I32P]
            lib.hops_gather_i32.argtypes = [I32P, I64, I64, I32P, I64P]
            lib.hops_gather_i64.argtypes = [I64P, I64, I64, I32P, I64P]
            _lib = lib
        except (OSError, subprocess.CalledProcessError, AttributeError):
            # only the expected degradations fall back to numpy: no
            # toolchain / failed build (CalledProcessError), unloadable
            # .so (OSError), stale library missing a symbol
            # (AttributeError).  Anything else is a real bug and raises.
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def count_keys(keys: np.ndarray, lo: int, rng: int) -> Optional[np.ndarray]:
    """Histogram of (keys - lo) over [0, rng) as int32 counts, or None
    when the native library is unavailable / dtype unsupported."""
    lib = _load()
    if lib is None or keys.dtype not in (np.int32, np.int64):
        return None
    keys = np.ascontiguousarray(keys)
    table = np.zeros(rng, np.int32)
    if keys.dtype == np.int32:
        lib.hops_count_i32(_ptr(keys, ctypes.c_int32), len(keys), lo,
                           _ptr(table, ctypes.c_int32))
    else:
        lib.hops_count_i64(_ptr(keys, ctypes.c_int64), len(keys), lo,
                           _ptr(table, ctypes.c_int32))
    return table


def gather_lookup(keys: np.ndarray, lo: int,
                  lookup: np.ndarray) -> Optional[np.ndarray]:
    """inv[i] = lookup[keys[i] - lo] (int64 group ids), or None."""
    lib = _load()
    if lib is None or keys.dtype not in (np.int32, np.int64):
        return None
    keys = np.ascontiguousarray(keys)
    lookup = np.ascontiguousarray(lookup, np.int32)
    inv = np.empty(len(keys), np.int64)
    if keys.dtype == np.int32:
        lib.hops_gather_i32(_ptr(keys, ctypes.c_int32), len(keys), lo,
                            _ptr(lookup, ctypes.c_int32),
                            _ptr(inv, ctypes.c_int64))
    else:
        lib.hops_gather_i64(_ptr(keys, ctypes.c_int64), len(keys), lo,
                            _ptr(lookup, ctypes.c_int32),
                            _ptr(inv, ctypes.c_int64))
    return inv


__all__ = ["available", "count_keys", "gather_lookup"]
