"""SEGMENT-strategy device group-by: hash -> radix bucket partition +
per-bucket segment reduce (the high-NDV aggregation kernel).

Reference analog: the parallel HashAgg the reference runs for
high-cardinality group-by (pkg/executor/aggregate/agg_hash_executor.go:94)
and the group-by-as-segment-reduction formulation of "Accelerating
Machine Learning Queries with Linear Algebra Query Processing"
(PAPERS.md).  Hash tables lose to partition+segment ops on TPU
(SURVEY.md §7 hard part 4); the SORT strategy already exploits that, but
its comparator carries 1 + 2*k int lanes per row and at millions of
groups the multi-operand sort is what turned the real-TPU hndv bench
rung into a 1000x cliff (BENCH_TPU.json `hndv_vs_numpy` 0.05x).

Algorithm (per device, one traced program, static shapes throughout):

1. Group keys lower to the same canonical (zeroed value, null flag,
   order-preserving int64 code) triples the SORT path uses
   (copr/exec.group_keyinfo).
2. The key tuple avalanche-hashes (splitmix64 finalizer folded per key)
   into ONE uint64.  The top log2(num_buckets) bits are the radix bucket
   id over the power-of-two bucket space the planner/copcost derived
   from stats NDV, so partitioning rows bucket-major and ordering each
   bucket's residual key space happen in a single single-key partition
   pass — regardless of group-key arity.  A ``prehashed`` aggregation
   reads the hash from its LAST scan column instead (the client hoists
   hashing out of the bucket-space regrow loop, store/client).
3. Segment boundaries fall where the hash or any true key code/null flag
   changes between adjacent live rows.  The code comparison makes a
   64-bit hash collision produce DUPLICATE partial groups, never merged
   ones: the host final merge (copr/aggregate.merge_sorted_states)
   re-groups by true key equality, so a duplicate costs one table slot
   while a collision-merged group would be silently wrong.
4. Rows segment-reduce (`jax.ops.segment_sum`-style ``.at[gids]``
   scatters) into a (num_buckets,) state table; ``__ngroups__`` reports
   the true distinct count so the dispatcher regrows ``num_buckets`` and
   re-runs on overflow — the paging analog (SURVEY.md §5.7).

The partition-to-states suffix (boundary detect + scatter-reduce) is
shared with the SCATTER strategy (copr/radix.py), which replaces the
single giant ``lax.sort`` of step 2-3 with a multi-pass scatter radix
partition — same state layout, same collision-to-duplicate contract.

Like SORT, the per-device tables merge HOST-side with the stacked shard
layout of parallel/spmd.py (per-device group sets are unaligned — no
elementwise psum merge exists); int/decimal SUM limbs still ride the
2^31 limb-exactness fence of copr/exec._one_agg_state.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.sortkeys import INT64_MAX
from . import dag as D

# splitmix64 finalizer constants (Steele et al.); numpy scalars so the
# uint64 lanes stay 64-bit regardless of the embedder's x64 default
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_S30 = np.uint64(30)
_S27 = np.uint64(27)
_S31 = np.uint64(31)

# trace-count observability for the prehash satellite: every TRACE of
# the avalanche hash bumps this (tests pin that a bucket-space regrow
# sequence hashes the key tuple exactly once — in the hoisted hash
# program, not once per capacity re-entry)
HASH_TRACES = [0]


def _finalize64(z):
    """splitmix64 avalanche: every input bit reaches every output bit,
    so the TOP log2(B) bits are a uniform radix bucket id."""
    z = (z ^ (z >> _S30)) * _MIX1
    z = (z ^ (z >> _S27)) * _MIX2
    return z ^ (z >> _S31)


def key_hash(keyinfo, n):
    """One uint64 avalanche hash per row over the canonical key tuple.
    NULL flags fold in (a NULL key and a zero key must land in
    different buckets with overwhelming probability; exactness does not
    depend on it — boundary detection compares flags too)."""
    HASH_TRACES[0] += 1
    h = jnp.full((n,), _GOLDEN, jnp.uint64)
    for _vz, m, nullf, code in keyinfo:
        cu = code.astype(jnp.uint64)
        if m is not True:
            cu = cu + nullf.astype(jnp.uint64) * _GOLDEN
        h = _finalize64(h ^ cu)
    return h


def batch_hash(agg: D.Aggregation, batch, keyinfo, n):
    """Per-row uint64 key hash: the hoisted LAST scan column when the
    aggregation is ``prehashed`` (store/client computes it once per
    statement so regrow re-entries skip the k-key avalanche chain),
    else freshly avalanched from the canonical key tuple."""
    if agg.prehashed:
        hv = batch.cols[-1][0]
        # stored as int64 (device column dtype); two's-complement cast
        # restores the original uint64 bit pattern exactly
        return hv.astype(jnp.uint64)
    return key_hash(keyinfo, n)


def states_from_partition(agg: D.Aggregation, batch, ev, keyinfo,
                          hv_s, idx, sel_s, n) -> dict:
    """Shared partition->states suffix of the SEGMENT and SCATTER
    strategies: given rows permuted bucket-major by ``idx`` (with the
    permuted hash ``hv_s`` and live mask ``sel_s``), detect segment
    boundaries where the hash OR any true key code/null flag changes
    (the collision-to-duplicate guarantee) and scatter-reduce each
    segment into a (num_buckets,) state table."""
    from .exec import _ensure_array, _one_agg_state, _reduce
    B = agg.num_buckets
    # segment boundary: live row whose hash OR any true key differs from
    # the previous row (the collision-to-duplicate guarantee)
    diff = jnp.arange(n, dtype=jnp.int64) == 0
    diff = diff | (hv_s != jnp.roll(hv_s, 1))
    for _vz, m, nullf, code in keyinfo:
        cd_s = code[idx]
        diff = diff | (cd_s != jnp.roll(cd_s, 1))
        if m is not True:
            nf_s = nullf[idx]
            diff = diff | (nf_s != jnp.roll(nf_s, 1))
    newgrp = sel_s & diff
    gid = jnp.cumsum(newgrp.astype(jnp.int64)) - 1
    ngroups = jnp.sum(newgrp.astype(jnp.int64))
    gids = jnp.where(sel_s, gid, B)        # dead rows -> dropped scatter

    states: dict = {"__ngroups__": ngroups}
    states["__rows__"] = _reduce(sel_s.astype(jnp.int64), sel_s, gids, B,
                                 "sum")
    for j, (vz, m, _nf, _cd) in enumerate(keyinfo):
        val = jnp.zeros((B,), vz.dtype).at[gids].set(vz[idx], mode="drop")
        valid = jnp.zeros((B,), bool).at[gids].set(
            jnp.ones(n, bool)[idx] if m is True else m[idx], mode="drop")
        states[f"k{j}"] = {"val": val, "valid": valid}

    # aggregate over the PERMUTED batch so arg rows line up with gids
    pcols = [(_ensure_array(v, n)[idx],
              True if m is True else m[idx]) for v, m in batch.cols]
    pmemo: dict = {}
    for i, a in enumerate(agg.aggs):
        if a.func == D.AggFunc.COUNT and a.arg is None:
            states[f"a{i}"] = {"count": states["__rows__"]}
            continue
        av, am = ev.eval(a.arg, pcols, pmemo)
        states[f"a{i}"] = _one_agg_state(a, av, am, sel_s, gids, B, n)
    return states


def agg_segment_states(agg: D.Aggregation, batch, ev, memo) -> dict:
    """SEGMENT-strategy per-device partial states: radix-partition rows
    by hash bucket via ONE single-key ``lax.sort``, segment-reduce each
    bucket's key runs into a (num_buckets,) group table.  Same state
    layout as the SORT path (k{j} val/valid, a{i}, __rows__,
    __ngroups__) so merge/finalize and the regrow loop stay one code
    path."""
    from .exec import _sel_array, group_keyinfo
    B = agg.num_buckets
    assert B > 0 and (B & (B - 1)) == 0, \
        "SEGMENT aggregation needs a power-of-two num_buckets"
    n = len(batch.cols[0][0]) if batch.cols else 0
    sel = _sel_array(batch.sel, n)

    keyinfo = group_keyinfo(agg, batch, ev, memo, n)
    hv = batch_hash(agg, batch, keyinfo, n).astype(jnp.int64)
    # dead rows park at the tail; a live row hashing to INT64_MAX merely
    # interleaves with them, and its gids stay correct via sel_s below
    hv = jnp.where(sel, hv, INT64_MAX)
    # the radix partition pass: ONE single-key sort orders rows by
    # (bucket id = top bits, residual hash = low bits) at once
    hv_s, idx = lax.sort((hv, jnp.arange(n, dtype=jnp.int64)), num_keys=1)
    sel_s = sel[idx]
    return states_from_partition(agg, batch, ev, keyinfo, hv_s, idx,
                                 sel_s, n)


__all__ = ["agg_segment_states", "key_hash", "batch_hash",
           "states_from_partition", "HASH_TRACES"]
