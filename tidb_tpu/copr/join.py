"""Device-side m:n join expansion: sorted-range lookup + cumsum slots.

Reference analog: the multi-match hash probe of the parallel hash join
(pkg/executor/join/hash_join_v2.go — partitioned build, concurrent probe
workers chasing hash-bucket chains).  Hash tables with chained buckets are
hostile to TPU (data-dependent loops, scatter-heavy); the TPU redesign
keeps the build side SORTED by key so a probe is two `searchsorted` ops
(lo/hi) giving each probe row's match count, and output rows are assigned
by cumsum — every step a dense vector op with static shapes.

The output batch has a fixed `out_capacity`; the true required size is
returned so the dispatcher can regrow and retry (kv.Request.Paging
grow-from-min analog, SURVEY.md §5.7).
"""

from __future__ import annotations

import jax.numpy as jnp


def match_ranges(sorted_keys, n_live, probe_keys, probe_ok):
    """Per-probe-row match ranges against a sorted build-key array.

    sorted_keys: (B,) int64, live keys sorted ascending in the first
    `n_live` slots (the rest arbitrary — callers park dead rows at the end
    with an INT64_MAX fill).  n_live: traced scalar or python int.
    probe_ok: bool mask (False = NULL/dead probe key -> matches nothing).
    Returns (lo, hi, cnt): int32/int64 arrays, cnt == matches per row.
    Clamping lo/hi to n_live keeps sentinel-valued dead slots out of the
    ranges even when a live key equals INT64_MAX.
    """
    lo = jnp.searchsorted(sorted_keys, probe_keys, side="left")
    hi = jnp.searchsorted(sorted_keys, probe_keys, side="right")
    lo = jnp.minimum(lo, n_live)
    hi = jnp.minimum(hi, n_live)
    cnt = jnp.where(probe_ok, hi - lo, 0)
    return lo, hi, cnt


def expand_slots(sel, cnt, kind: str, out_capacity: int):
    """Assign output slots for an inner/left expand join.

    sel: live probe rows; cnt: matches per probe row (0 where dead).
    Left joins give every live-but-unmatched probe row one null-extension
    slot.  Returns (probe_idx, offset, valid_out, is_ext, total):
      probe_idx (OC,) — which probe row fills each output slot,
      offset    (OC,) — 0-based index into that row's match range,
      valid_out (OC,) — slot holds a real output row,
      is_ext    (OC,) — slot is a left-join null extension,
      total     ()    — true output size (compare vs out_capacity).
    """
    n = cnt.shape[0]
    if kind == "left":
        cnt_ext = jnp.where(sel & (cnt == 0), 1, cnt)
    else:
        cnt_ext = cnt
    cum = jnp.cumsum(cnt_ext)
    starts = cum - cnt_ext
    total = cum[-1] if n else jnp.int64(0)
    j = jnp.arange(out_capacity, dtype=cum.dtype)
    pi = jnp.clip(jnp.searchsorted(cum, j, side="right"), 0, max(n - 1, 0))
    offset = j - starts[pi]
    valid_out = j < total
    is_ext = valid_out & (cnt[pi] == 0)
    return pi, offset, valid_out, is_ext, total


def gather_expand(batch_cols, sel, probe_key_ok, build_cols, perm,
                  lo, cnt, kind: str, out_capacity: int):
    """Materialize the expanded join output.

    batch_cols: probe [(value, mask|True)]; build_cols likewise (already
    row-aligned with `perm`'s target space); perm: sorted-order ->
    original-build-row permutation; lo/cnt from match_ranges.
    Returns (out_cols, out_sel, total) where out_cols = probe ++ build.
    """
    pi, offset, valid_out, is_ext, total = expand_slots(
        sel, cnt, kind, out_capacity)
    out_cols = []
    for v, m in batch_cols:
        gv = v[pi]
        gm = True if m is True else m[pi]
        out_cols.append((gv, gm))
    b = perm.shape[0]
    brow = perm[jnp.clip(lo[pi] + offset, 0, max(b - 1, 0))]
    bvalid_base = ~is_ext
    for v, m in build_cols:
        gv = v[brow]
        gm = bvalid_base if m is True else (m[brow] & bvalid_base)
        out_cols.append((gv, gm))
    return out_cols, valid_out, total


__all__ = ["match_ranges", "expand_slots", "gather_expand"]
