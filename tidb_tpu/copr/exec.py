"""Fused coprocessor execution: DAG -> one jit-compiled XLA program.

Reference analog: unistore/cophandler/closure_exec.go:468 — the fused
scan→selection→agg/topN/limit single-pass "closure" executor that is the
CPU hot loop the TPU kernels replace.  Where the reference builds a Go
closure per DAG, we trace the DAG once into jnp ops and let XLA fuse the
whole pipeline into a handful of HBM-bandwidth-bound kernels; programs are
cached per (dag digest, shard capacity) like the cop cache keys on
(region version, request digest) (coprocessor_cache.go, SURVEY.md §A.6).

Execution model: static shapes only (XLA).  A shard is a fixed-capacity
batch of columns; live rows are tracked with a selection mask `sel` instead
of compaction (dynamic shapes).  Row-returning plans compact on device into
a caller-chosen capacity via cumsum-scatter; if the result overflows, the
dispatcher retries with a larger capacity — the paging analog
(kv.Request.Paging, SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..expr.compile import Evaluator, vand
from ..ops.sortkeys import INT64_MAX, INT64_MIN, sortable_int64
from ..types import dtypes as dt
from . import dag as D

K = dt.TypeKind

# Dense grouped reduction: below this group count, reduce via broadcast
# compare (VPU-friendly, fuses into the scan); above, scatter-add.
DENSE_BROADCAST_MAX_GROUPS = 64


@dataclass
class DeviceBatch:
    """Columns + live-row selection mask flowing between fused operators.

    `extras` carries named traced scalars that must surface to the
    dispatcher alongside the result — today the true output size of an
    expanding join, so the paging loop can regrow its capacity."""
    cols: list  # list[(value, valid)]
    sel: Any    # bool array | True
    extras: dict = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.extras is None:
            self.extras = {}


def _ensure_array(v, n):
    if hasattr(v, "shape") and v.shape:
        return v
    return jnp.full((n,), v)  # planlint: ok - dtype follows the operand


def _sel_array(sel, n):
    return jnp.ones((n,), bool) if sel is True else sel


# --------------------------------------------------------------------- #
# Aggregation partial states (the psum seam, SURVEY.md §A.4)
# --------------------------------------------------------------------- #

# platform the program being TRACED will run on — set by the program
# builders from their actual device placement (a CPU mesh on a TPU host,
# e.g. dryrun_multichip, must still take the CPU strategy); falls back
# to the process default backend
_TRACE_PLATFORM: list = [None]


def set_trace_platform(platform):
    _TRACE_PLATFORM[0] = platform


def trace_platform() -> str:
    return _TRACE_PLATFORM[0] or jax.default_backend()


def _reduce(vals, mask, gids, num_groups, how: str):
    """Masked (optionally grouped) reduction.

    how: 'sum' | 'min' | 'max'.  gids None => scalar reduction.
    Grouped: dense (G,) output.  Strategy is PER-PLATFORM: on TPU a
    broadcast one-hot compare for small G fuses into the streaming scan
    pass (scatter lowering on TPU can serialize); on CPU the (G, N)
    broadcast costs G x the scan traffic per aggregate and XLA's
    scatter-add is cheap — measured 14x on TPC-H Q1 — so CPU always
    scatters."""
    neutral = {"sum": 0, "min": _max_of(vals.dtype), "max": _min_of(vals.dtype)}[how]
    v = jnp.where(mask, vals, jnp.asarray(neutral, vals.dtype))
    if gids is None:
        return getattr(jnp, how)(v)
    broadcast_max = (0 if trace_platform() == "cpu"
                     else DENSE_BROADCAST_MAX_GROUPS)
    if num_groups <= broadcast_max:
        onehot = gids[None, :] == jnp.arange(num_groups, dtype=gids.dtype)[:, None]
        vv = jnp.where(onehot, v[None, :], jnp.asarray(neutral, vals.dtype))
        return getattr(jnp, how)(vv, axis=1)
    out = jnp.full((num_groups,), neutral, vals.dtype)
    if how == "sum":
        return out.at[gids].add(v, mode="drop")
    return getattr(out.at[gids], how)(v, mode="drop")


def _max_of(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    return jnp.iinfo(dtype).max


def _min_of(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


def _one_agg_state(a: D.AggDesc, av, am, sel, gids, num_groups, n,
                   narrow: bool = False) -> dict:
    """Partial state for one AggDesc over (possibly grouped) rows.

    Layout (all named arrays so psum/pmin/pmax merges are mechanical —
    see parallel/collectives.py MERGE_SPECS):
      count -> {count}
      sum   -> decimal/int: {hi, lo, cnt} (int64 limb split, exact when
               recombined host-side); proven-narrow decimal/int
               (analysis/valueflow): {sum, cnt} single int64 word;
               float: {sum, cnt}
      min   -> {min, cnt};  max -> {max, cnt}
    """
    av = _ensure_array(av, n)
    mask = sel if am is True else (sel & am)
    if a.func == D.AggFunc.COUNT:
        return {"count": _reduce(mask.astype(jnp.int64), mask, gids,
                                 num_groups, "sum")}
    cnt = _reduce(mask.astype(jnp.int64), mask, gids, num_groups, "sum")
    if a.func == D.AggFunc.SUM:
        kind = a.arg.dtype.kind
        if kind in (K.FLOAT64, K.FLOAT32):
            return {"sum": _reduce(av.astype(jnp.float64), mask, gids,
                                   num_groups, "sum"), "cnt": cnt}
        if narrow:
            # valueflow proved Σv over the WHOLE table (all shards, all
            # batches, with headroom) stays inside int64, so the per-batch
            # sum and every psum/host partial can't wrap either: one int64
            # word, half the state bytes, no limb fence.  Bit-identical to
            # the limb path (Σhi<<32 + Σlo == Σv in two's complement).
            return {"sum": _reduce(av.astype(jnp.int64), mask, gids,
                                   num_groups, "sum"), "cnt": cnt}
        # decimal AND integer sums accumulate as (hi, lo) int64 limbs.
        # Exactness argument (types/decimal.py): per row |hi| < 2^32 and
        # lo < 2^32, so with n < 2^31 rows per batch neither limb sum can
        # wrap int64; recombination is exact.  n is a static shape, so
        # this fence is free.
        if n >= 2 ** 31:
            raise OverflowError(
                f"shard batch of {n} rows exceeds the 2^31 limb-exact "
                "SUM bound; use more/smaller shards")
        v = av.astype(jnp.int64)
        hi = _reduce(v >> 32, mask, gids, num_groups, "sum")
        lo = _reduce(v & 0xFFFFFFFF, mask, gids, num_groups, "sum")
        return {"hi": hi, "lo": lo, "cnt": cnt}
    if a.func == D.AggFunc.MIN:
        return {"min": _reduce(av, mask, gids, num_groups, "min"),
                "cnt": cnt}
    if a.func == D.AggFunc.MAX:
        return {"max": _reduce(av, mask, gids, num_groups, "max"),
                "cnt": cnt}
    raise NotImplementedError(a.func)


def agg_states(agg: D.Aggregation, scan_cols, row_count, ev: Evaluator,
               aux) -> tuple:
    """Execute agg.child and build partial states.

    An Expand child (WITH ROLLUP) aggregates LEVEL BY LEVEL over the
    un-expanded batch instead of materializing the levels×n replication:
    each grouping-set level synthesizes its key/gid columns over the SAME
    n-row child batch, builds DENSE partial states, and merges them with
    the shard-merge combiners — identical math, 1/levels the peak HBM
    (the levels×n materialization OOM-crashed the v5e worker at SF=10).
    Returns (states, child_batch-for-extras).

    TPU-only: on CPU the materialized expand fuses into one pass and
    measures slightly faster; on TPU the replication is what OOMs."""
    ch = agg.child
    if isinstance(ch, D.Expand) \
            and agg.strategy == D.GroupStrategy.DENSE \
            and trace_platform() == "tpu":
        base = _exec_node(ch.child, scan_cols, row_count, ev, aux)
        return _expand_level_states(agg, ch, base, ev), base
    batch = _exec_node(ch, scan_cols, row_count, ev, aux)
    return _agg_partial_states(agg, batch, ev, {}), batch


def _expand_level_states(agg: D.Aggregation, exp: D.Expand,
                         base: DeviceBatch, ev: Evaluator) -> dict:
    from .aggregate import _MERGE
    n = len(base.cols[0][0]) if base.cols else 0
    L = len(exp.keys)
    memo: dict = {}
    child_cols = [(_ensure_array(v, n), m) for v, m in base.cols]
    keyvals = []
    for k in exp.keys:
        v, m = ev.eval(k, base.cols, memo)
        keyvals.append((_ensure_array(v, n), m))

    def combine(name, a, b):
        how = _MERGE[name]
        if how == "sum":
            return a + b
        return jnp.minimum(a, b) if how == "min" else jnp.maximum(a, b)

    merged: dict = {}
    for lvl in range(exp.levels):
        cols = list(child_cols)
        for j, (v, m) in enumerate(keyvals):
            if lvl + j < L:            # key j live on this level
                cols.append((v, m))
            else:                      # rolled: NULL for every row
                cols.append((v, jnp.zeros(n, bool)))
        cols.append((jnp.full(n, lvl, jnp.int64), True))
        st = _agg_partial_states(
            agg, DeviceBatch(cols, base.sel, base.extras), ev, {})
        if not merged:
            merged = st
        else:
            for k, v in st.items():
                if isinstance(v, dict):
                    merged[k] = {f: combine(f, merged[k][f], a)
                                 for f, a in v.items()}
                else:
                    merged[k] = combine(k, merged[k], v)
    return merged


def _agg_partial_states(agg: D.Aggregation, batch: DeviceBatch, ev: Evaluator,
                        memo: dict):
    """Per-shard partial-state pytree for an Aggregation node.

    SCALAR/DENSE: fixed group domain, psum-mergeable across shards.
    SORT: unbounded key domain via multi-key sort + segment-reduce into
    a fixed-capacity group table (host merge across shards) — the TPU
    answer to the reference's high-NDV parallel HashAgg
    (pkg/executor/aggregate/agg_hash_executor.go:94); hash tables lose to
    sort+segment ops on TPU (SURVEY.md §7 hard part 4).
    SEGMENT: the high-NDV refinement — keys avalanche-hash into one
    uint64 radix space, a SINGLE-key partition pass buckets rows, and
    each bucket's runs segment-reduce (copr/segment.py).
    SCATTER: SEGMENT with the giant sort replaced by a multi-pass
    scatter radix partition — histogram + exclusive cumsum + stable
    scatter reorder per pass, O(passes*n) data movement, optionally a
    Pallas TPU kernel for the inner loop (copr/radix.py).
    Adds '__rows__' (COUNT(*) per group) for occupancy.
    """
    if agg.strategy == D.GroupStrategy.SCATTER:
        from .radix import agg_scatter_states
        return agg_scatter_states(agg, batch, ev, memo)
    if agg.strategy == D.GroupStrategy.SEGMENT:
        from .segment import agg_segment_states
        return agg_segment_states(agg, batch, ev, memo)
    if agg.strategy == D.GroupStrategy.SORT:
        return _agg_sort_states(agg, batch, ev, memo)

    n = len(batch.cols[0][0]) if batch.cols else 0
    sel = _sel_array(batch.sel, n)

    gids = None
    num_groups = 1
    if agg.strategy == D.GroupStrategy.DENSE:
        gids = _dense_group_ids(agg, batch, ev, memo)
        num_groups = agg.num_groups

    states: dict[str, Any] = {}
    states["__rows__"] = _reduce(sel.astype(jnp.int64), sel, gids, num_groups, "sum")
    for i, a in enumerate(agg.aggs):
        if a.func == D.AggFunc.COUNT and a.arg is None:
            states[f"a{i}"] = {"count": states["__rows__"]}
            continue
        av, am = ev.eval(a.arg, batch.cols, memo)
        states[f"a{i}"] = _one_agg_state(a, av, am, sel, gids, num_groups, n,
                                         narrow=(i in agg.narrow_sums))
    return states


def group_keyinfo(agg: D.Aggregation, batch: DeviceBatch, ev: Evaluator,
                  memo: dict, n: int) -> list:
    """Canonical per-group-key (zeroed value, mask, null flag, order-
    preserving int64 code) tuples — the shared key representation of the
    SORT and SEGMENT strategies.  NULL values are zeroed so all NULLs
    share one group; -0.0 groups with +0.0 (SQL equality, not bit
    equality)."""
    keyinfo = []
    for e in agg.group_by:
        v, m = ev.eval(e, batch.cols, memo)
        v = _ensure_array(v, n)
        if v.dtype == bool:
            v = v.astype(jnp.int64)
        nullf = (jnp.zeros(n, jnp.int32) if m is True
                 else (~m).astype(jnp.int32))  # valueflow: ok - bool lane, [0, 1]
        vz = v if m is True else jnp.where(m, v, jnp.zeros((), v.dtype))
        if e.dtype.is_float:
            vz = jnp.where(vz == 0, jnp.zeros((), vz.dtype), vz)
        code = sortable_int64(jnp, vz, e.dtype.is_float,
                              e.dtype.kind == K.UINT64)
        keyinfo.append((vz, m, nullf, code))
    return keyinfo


def _agg_sort_states(agg: D.Aggregation, batch: DeviceBatch, ev: Evaluator,
                     memo: dict):
    """SORT-strategy grouped aggregation: one multi-key lax.sort, segment
    boundaries by key change, scatter-reduce into a (group_capacity,)
    state table.

    Per key j the states carry {'val', 'valid'} gathered from the group's
    rows (NULL values zeroed so all NULLs share one group), plus
    '__ngroups__' — the TRUE distinct-group count, so the dispatcher can
    regrow capacity and re-run when it exceeds group_capacity (the paging
    analog, SURVEY.md §5.7)."""
    G = agg.group_capacity
    assert G > 0, "SORT aggregation needs group_capacity"
    n = len(batch.cols[0][0]) if batch.cols else 0
    sel = _sel_array(batch.sel, n)

    keyinfo = group_keyinfo(agg, batch, ev, memo, n)

    dead = (~sel).astype(jnp.int32)  # valueflow: ok - bool lane, [0, 1]
    ops: list = [dead]
    for _vz, _m, nullf, code in keyinfo:
        ops += [nullf, code]
    ops.append(jnp.arange(n, dtype=jnp.int64))
    *sorted_keys, idx = lax.sort(tuple(ops), num_keys=1 + 2 * len(keyinfo))
    sel_s = sel[idx]

    # group boundary: live row whose key tuple differs from the previous
    diff = jnp.arange(n, dtype=jnp.int64) == 0
    for j in range(len(keyinfo)):
        nf_s, cd_s = sorted_keys[1 + 2 * j], sorted_keys[2 + 2 * j]
        diff = diff | (nf_s != jnp.roll(nf_s, 1)) | (cd_s != jnp.roll(cd_s, 1))
    newgrp = sel_s & diff
    gid = jnp.cumsum(newgrp.astype(jnp.int64)) - 1
    ngroups = jnp.sum(newgrp.astype(jnp.int64))
    gids = jnp.where(sel_s, gid, G)        # dead rows -> dropped scatter

    states: dict[str, Any] = {"__ngroups__": ngroups}
    states["__rows__"] = _reduce(sel_s.astype(jnp.int64), sel_s, gids, G, "sum")
    for j, (vz, m, _nf, _cd) in enumerate(keyinfo):
        val = jnp.zeros((G,), vz.dtype).at[gids].set(vz[idx], mode="drop")
        valid = jnp.zeros((G,), bool).at[gids].set(
            jnp.ones(n, bool)[idx] if m is True else m[idx], mode="drop")
        states[f"k{j}"] = {"val": val, "valid": valid}

    # aggregate over the PERMUTED batch so arg rows line up with gids
    pcols = [(_ensure_array(v, n)[idx],
              True if m is True else m[idx]) for v, m in batch.cols]
    pmemo: dict = {}
    for i, a in enumerate(agg.aggs):
        if a.func == D.AggFunc.COUNT and a.arg is None:
            states[f"a{i}"] = {"count": states["__rows__"]}
            continue
        av, am = ev.eval(a.arg, pcols, pmemo)
        states[f"a{i}"] = _one_agg_state(a, av, am, sel_s, gids, G, n)
    return states


def _dense_group_ids(agg: D.Aggregation, batch: DeviceBatch, ev: Evaluator,
                     memo: dict):
    """Mixed-radix dense group id from the group-by key codes.

    Key domain [0, size_i); nullable keys get slot 0 for NULL and codes
    shifted by one (domain_sizes already include the NULL slot)."""
    n = len(batch.cols[0][0])
    gid = jnp.zeros((n,), jnp.int32)
    for e, size in zip(agg.group_by, agg.domain_sizes):
        v, m = ev.eval(e, batch.cols, memo)
        v = _ensure_array(v, n).astype(jnp.int32)  # valueflow: ok - DENSE key domain <= MAX_DENSE_GROUPS < 2^31
        if e.dtype.nullable:
            code = v + 1 if m is True else jnp.where(m, v + 1, 0)
        else:
            code = v
        gid = gid * jnp.int32(size) + code
    return gid


# --------------------------------------------------------------------- #
# Row output: device-side compaction (paging analog)
# --------------------------------------------------------------------- #

def compact(batch: DeviceBatch, capacity: int):
    """Pack live rows to the front of fixed-size output buffers via
    cumsum-scatter.  Returns (cols, count); rows past `capacity` are
    dropped — callers compare count vs capacity and re-run bigger."""
    n = len(batch.cols[0][0]) if batch.cols else 0
    sel = _sel_array(batch.sel, n)
    pos = jnp.cumsum(sel) - 1
    idx = jnp.where(sel, pos, capacity)  # out-of-bounds => dropped
    out_cols = []
    for v, m in batch.cols:
        v = _ensure_array(v, n)
        if v.dtype == bool:
            v = v.astype(jnp.int64)
        data = jnp.zeros((capacity,), v.dtype).at[idx].set(v, mode="drop")
        valid = jnp.zeros((capacity,), bool).at[idx].set(
            _sel_array(m, n) if m is not True else jnp.ones((n,), bool),
            mode="drop")
        out_cols.append((data, valid))
    return out_cols, jnp.sum(sel)


# --------------------------------------------------------------------- #
# Node execution (traced)
# --------------------------------------------------------------------- #

def _exec_node(node: D.CopNode, scan_cols: Sequence, row_count, ev: Evaluator,
               aux: Sequence = ()):
    if isinstance(node, D.TableScan):
        cols = [scan_cols[off] for off in node.col_offsets]
        n = len(cols[0][0]) if cols else 0
        if getattr(row_count, "ndim", 0) == 0:
            sel = jnp.arange(n, dtype=jnp.int64) < row_count
        else:
            # caller supplied a precomputed live-row mask (e.g. several
            # flattened shards with per-shard row counts, parallel/spmd.py)
            sel = row_count
        return DeviceBatch(list(cols), sel)

    if isinstance(node, D.Selection):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        memo: dict = {}
        sel = batch.sel
        n = len(batch.cols[0][0])
        for cond in node.conditions:
            v, m = ev.eval(cond, batch.cols, memo)
            v = _ensure_array(v, n)
            if v.dtype != bool:
                v = v != 0
            keep = v if m is True else (v & m)  # NULL -> filtered out
            sel = keep if sel is True else (sel & keep)
        return DeviceBatch(batch.cols, sel, batch.extras)

    if isinstance(node, D.Projection):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        memo = {}
        n = len(batch.cols[0][0])
        cols = []
        for e in node.exprs:
            v, m = ev.eval(e, batch.cols, memo)
            cols.append((_ensure_array(v, n), m))
        return DeviceBatch(cols, batch.sel, batch.extras)

    if isinstance(node, D.Expand):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        n = len(batch.cols[0][0]) if batch.cols else 0
        L = len(node.keys)
        LV = node.levels
        memo = {}
        sel = _sel_array(batch.sel, n)
        out_cols = []
        for v, m in batch.cols:
            v = _ensure_array(v, n)
            out_cols.append((jnp.tile(v, LV),
                             True if m is True else jnp.tile(m, LV)))
        lvl = jnp.repeat(jnp.arange(LV, dtype=jnp.int64), n)
        for j, k in enumerate(node.keys):
            v, m = ev.eval(k, batch.cols, memo)
            v = jnp.tile(_ensure_array(v, n), LV)
            keep = (lvl + j) < L       # key j live on levels l < L - j
            mj = keep if m is True else (jnp.tile(m, LV) & keep)
            out_cols.append((v, mj))
        out_cols.append((lvl, True))
        return DeviceBatch(out_cols, jnp.tile(sel, LV), batch.extras)

    if isinstance(node, D.Limit):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        n = len(batch.cols[0][0])
        sel = _sel_array(batch.sel, n)
        keep = sel & (jnp.cumsum(sel) <= node.limit)
        return DeviceBatch(batch.cols, keep, batch.extras)

    if isinstance(node, D.TopN):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        return _exec_topn(node, batch, ev)

    if isinstance(node, D.LookupJoin):
        batch = _exec_node(node.child, scan_cols, row_count, ev, aux)
        return _exec_lookup_join(node, batch, ev, aux)

    raise TypeError(node)


def _exec_lookup_join(node: D.LookupJoin, batch: DeviceBatch, ev: Evaluator,
                      aux) -> DeviceBatch:
    """Sorted-lookup join (see dag.LookupJoin).  aux is a tuple of GROUPS,
    one per chained join level; group layout: [0]=(sorted build keys,),
    [1]=(perm,), [2:]=build columns."""
    n = len(batch.cols[0][0])
    grp = aux[node.aux_slot]
    sorted_keys = grp[0][0]
    perm = grp[1][0]
    build_cols = grp[2:]
    kv, km = ev.eval(node.probe_key, batch.cols, {})
    kv = _ensure_array(kv, n).astype(jnp.int64)

    if node.unique and node.kind in ("inner", "left"):
        idx = jnp.searchsorted(sorted_keys, kv)
        idxc = jnp.clip(idx, 0, sorted_keys.shape[0] - 1)
        matched = sorted_keys[idxc] == kv
        if km is not True:
            matched = matched & km
        brow = perm[idxc]
        out_cols = list(batch.cols)
        for bv, bm in build_cols:
            gv = bv[brow]
            gm = matched if bm is True else (bm[brow] & matched)
            out_cols.append((gv, gm))
        sel = batch.sel
        if node.kind == "inner":
            sel = matched if sel is True else (sel & matched)
        return DeviceBatch(out_cols, sel, batch.extras)

    from .join import gather_expand, match_ranges
    sel = _sel_array(batch.sel, n)
    key_ok = sel if km is True else (sel & km)
    lo, _hi, cnt = match_ranges(sorted_keys, sorted_keys.shape[0], kv, key_ok)

    if node.kind in ("semi", "anti"):
        keep = (cnt > 0) if node.kind == "semi" else (cnt == 0)
        if node.kind == "anti" and node.null_aware and km is not True:
            keep = keep & km       # NOT IN: NULL probe key -> filtered
        return DeviceBatch(batch.cols, sel & keep, batch.extras)

    oc = node.out_capacity
    assert oc > 0, "non-unique LookupJoin needs out_capacity"
    probe = [(_ensure_array(v, n), m) for v, m in batch.cols]
    out_cols, out_sel, total = gather_expand(
        probe, sel, key_ok, list(build_cols), perm, lo, cnt, node.kind, oc)
    extras = dict(batch.extras)
    extras["join_total"] = total
    return DeviceBatch(out_cols, out_sel, extras)


def _exec_topn(node: D.TopN, batch: DeviceBatch, ev: Evaluator) -> DeviceBatch:
    """Per-shard TopN via a stable multi-key lax.sort + head-k gather.

    Sort keys, ascending, in priority order: (1) dead-row flag so filtered
    rows always sort last, (2) NULL flag encoding MySQL ordering (NULLs
    first ASC, last DESC), (3) the order-preserving int64 key — bitwise-NOT
    for DESC, an exact overflow-free order reversal.  No clamping: every
    distinct key value keeps its rank (review finding: clamping collapsed
    the extreme key values at the limit boundary)."""
    memo: dict = {}
    n = len(batch.cols[0][0])
    sel = _sel_array(batch.sel, n)
    dead = (~sel).astype(jnp.int32)  # valueflow: ok - bool lane, [0, 1]
    operands = [dead]
    for e, desc in (node.sort_keys or ((node.sort_key, node.desc),)):
        v, m = ev.eval(e, batch.cols, memo)
        v = _ensure_array(v, n)
        key = sortable_int64(jnp, v, e.dtype.is_float,
                             e.dtype.kind == K.UINT64)
        if desc:
            key = ~key           # exact descending order, no overflow
        if m is True:
            nullflag = jnp.zeros(n, jnp.int32)
        else:
            # NULL sorts first in ASC, last in DESC
            flag = jnp.where(m, 1, 0) if not desc else jnp.where(m, 0, 1)
            nullflag = flag.astype(jnp.int32)  # valueflow: ok - literal 0/1 lanes
        operands += [nullflag, key]
    nk = len(operands)
    *_, idx = lax.sort(tuple(operands)
                       + (jnp.arange(n, dtype=jnp.int64),), num_keys=nk)
    k = min(node.limit, n)
    idx = idx[:k]
    live = jnp.sum(sel)
    out_sel = jnp.arange(k, dtype=jnp.int64) < jnp.minimum(live, k)
    cols = []
    for cv, cm in batch.cols:
        cv = _ensure_array(cv, n)
        cols.append((cv[idx],
                     (cm[idx] if cm is not True else True)))
    return DeviceBatch(cols, out_sel, batch.extras)


# --------------------------------------------------------------------- #
# Program build + cache
# --------------------------------------------------------------------- #

class CopProgram:
    """A compiled coprocessor program for one DAG shape.

    kind == 'agg': __call__(scan_cols, row_count) -> partial-state pytree
    kind == 'rows': -> (cols, count) compacted to `row_capacity`
    """

    def __init__(self, dag_root: D.CopNode, row_capacity: int = 0):
        self.root = dag_root
        self.row_capacity = row_capacity
        self.agg = _find_agg(dag_root)
        self.kind = "agg" if self.agg is not None else "rows"
        # programs containing an expanding join return an extras dict
        # (true join output size) after the result, for the regrow loop
        self.has_extras = D.find_expand_join(dag_root) is not None
        self._fn = jax.jit(self._trace)

    def _trace(self, scan_cols, row_count, aux_cols=()):
        # single-device programs run on the process default backend:
        # reset any platform a prior CPU-mesh trace left sticky
        set_trace_platform(None)
        # At the jit boundary "all valid" is encoded as None (a pytree node,
        # hence static structure); inside the trace it becomes the literal
        # True the Evaluator's fast paths key on.
        scan_cols = [(v, True if m is None else m) for v, m in scan_cols]
        aux_cols = tuple(
            tuple((v, True if m is None else m) for v, m in grp)
            for grp in aux_cols)
        ev = Evaluator(jnp)
        if self.agg is not None:
            states, batch = agg_states(self.agg, scan_cols, row_count, ev,
                                       aux_cols)
            return (states, batch.extras) if self.has_extras else states
        batch = _exec_node(self.root, scan_cols, row_count, ev, aux_cols)
        cols, cnt = compact(batch, self.row_capacity)
        return (cols, cnt, batch.extras) if self.has_extras else (cols, cnt)

    def __call__(self, scan_cols, row_count, aux_cols=()):
        return self._fn(scan_cols, row_count, aux_cols)


def _find_agg(node: D.CopNode) -> Optional[D.Aggregation]:
    """The pushdown DAG holds at most one Aggregation, as the root
    (mirrors tipb: agg is the final pushed executor)."""
    if isinstance(node, D.Aggregation):
        return node
    return None


@functools.lru_cache(maxsize=256)
def _cached_program(dag_root: D.CopNode, row_capacity: int,
                    radix_token: str) -> CopProgram:
    del radix_token          # key component only (Pallas-gate variant)
    return CopProgram(dag_root, row_capacity)


def get_program(dag_root: D.CopNode, row_capacity: int = 0) -> CopProgram:
    """jit-program cache keyed on (dag digest, capacity) — the analog of the
    coprocessor cache + plan-digest jit cache (SURVEY.md §A.6).  SCATTER
    programs additionally key on the Pallas-gate mode: the lowering is
    baked in at trace time, so a sysvar flip must build a fresh program."""
    from .radix import cache_token
    return _cached_program(dag_root, row_capacity, cache_token(dag_root))


__all__ = ["DeviceBatch", "CopProgram", "get_program", "compact",
           "group_keyinfo"]
