"""Host (CPU) execution of SORT/SEGMENT/SCATTER-strategy group-by
aggregation.

Per-platform engine choice (VERDICT r2 #2): the reference aggregates
high-NDV group-by with a CPU hash table (parallel HashAgg,
pkg/executor/aggregate/agg_hash_executor.go:94).  The TPU answer is the
device sort/radix-partition + segment-reduce programs
(copr/exec._agg_sort_states, copr/segment.py), but those programs
lowered to XLA-CPU measured 56x slower than numpy's sorting unique.  So
on a CPU mesh the CopClient routes the whole aggregation here:
one np.unique (plus a stable argsort when any aggregate needs per-row
segment reduction) producing the exact same partial-state pytree the
device program emits, so merge/finalize stay one code path
(copr/aggregate.merge_sorted_states).

The hot shape — single non-nullable int64 key, COUNT(*) only — reduces to
exactly `np.unique(key, return_index, return_counts)`, i.e. the numpy
oracle itself.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..expr.compile import Evaluator
from ..types import dtypes as dt
from . import dag as D
from .aggregate import _MERGE, _np_key_code, merge_states

K = dt.TypeKind


def _host_scan_chain(node: D.CopNode, snap,
                     allow_mask: bool = False,
                     rng: Optional[tuple] = None) -> Optional[tuple]:
    """Evaluate a TableScan[->Selection][->Projection] chain over the host
    snapshot columns.  Returns (cols, live_mask) where live_mask is None
    when rows were compacted; with allow_mask, HIGH-selectivity filters
    (>90% kept) skip the per-column compaction copies and return the
    boolean mask instead — the dense-agg consumer routes dead rows to a
    trim group, one pass instead of seven takes.  None = out of scope."""
    chain = []
    cur = node
    while True:
        chain.append(cur)
        if isinstance(cur, D.TableScan):
            break
        if isinstance(cur, (D.Selection, D.Projection, D.Expand)):
            cur = cur.child
            continue
        return None
    chain.reverse()

    ev = Evaluator(np)
    cols = None
    lo, hi = rng if rng is not None else (0, snap.num_rows)
    n = hi - lo
    live = None
    for op in chain:
        if isinstance(op, D.TableScan):
            cols = []
            for off in op.col_offsets:
                c = snap.columns[off]
                # narrow physical representation: the hardened evaluator
                # (expr/compile.py _iwiden/_cmp_fit) computes at logical
                # width where it matters; scans read 1-4 B/row
                phys = c.narrowed()
                data = phys if rng is None else phys[lo:hi]
                if c.all_valid():       # cached full-column reduce
                    valid = True
                elif rng is None:
                    valid = c.validity
                else:
                    v = c.validity[lo:hi]
                    valid = True if v.all() else v
                cols.append((data, valid))
        elif isinstance(op, D.Selection):
            memo: dict = {}
            keep = np.ones(n, bool) if live is None else live
            for cond in op.conditions:
                v, m = ev.eval(cond, cols, memo)
                v = np.broadcast_to(np.asarray(v), (n,))
                if v.dtype != bool:
                    v = v != 0
                if m is not True:
                    keep = keep & v & np.broadcast_to(np.asarray(m), (n,))
                else:
                    keep = keep & v
            nk = np.count_nonzero(keep)    # one reduce serves both checks
            if nk == n:
                continue
            if allow_mask and nk > 0.9 * n:
                live = keep
                continue
            idx = np.nonzero(keep)[0]
            cols = [(np.asarray(v)[idx] if np.ndim(v) else v,
                     m if m is True else m[idx]) for v, m in cols]
            n = len(idx)
            live = None
        elif isinstance(op, D.Expand):
            # rollup grouping sets: compact any pending mask first so the
            # replication multiplies only live rows, then np.tile
            if live is not None:
                idx = np.nonzero(live)[0]
                cols = [(np.asarray(v)[idx] if np.ndim(v) else v,
                         m if m is True else m[idx]) for v, m in cols]
                n = len(idx)
                live = None
            memo = {}
            L = len(op.keys)
            LV = op.levels
            keyvals = [ev.eval(k, cols, memo) for k in op.keys]
            out = []
            for v, m in cols:
                v = np.broadcast_to(np.asarray(v), (n,))
                out.append((np.tile(v, LV), True if m is True
                            else np.tile(np.broadcast_to(
                                np.asarray(m), (n,)), LV)))
            lvl = np.repeat(np.arange(LV, dtype=np.int64), n)
            for j, (v, m) in enumerate(keyvals):
                v = np.tile(np.broadcast_to(np.asarray(v), (n,)), LV)
                keep = (lvl + j) < L
                mv = keep if m is True else (
                    np.tile(np.broadcast_to(np.asarray(m), (n,)), LV) & keep)
                out.append((v, mv))
            out.append((lvl, True))
            cols = out
            n = n * LV
        else:  # Projection
            memo = {}
            out = []
            for e in op.exprs:
                v, m = ev.eval(e, cols, memo)
                out.append((np.broadcast_to(np.asarray(v), (n,)), m))
            cols = out
    return cols, live


def _group_codes(combined: np.ndarray, need_inv: bool):
    """(unique codes, per-group row counts int64, inverse|None).

    NDV-adaptive strategy (the reference picks hash vs stream agg from
    NDV; numpy's levers are different): when the observed code range is
    narrow relative to n, an O(n) histogram beats the O(n log n) sorting
    unique by 2-4x; otherwise fall back to np.unique.  The histogram runs
    in the native counting loop (native/hostops.cpp) when built — it
    reads the narrow physical key array directly, where np.bincount's
    mandatory bin/weight conversions cost 3-4x the compulsory traffic."""
    from . import nativeops
    n = len(combined)
    if n:
        if combined.dtype.itemsize < 4:
            # int8/int16 subtraction below could wrap (range may exceed
            # the narrow width); int32 always holds the shifted codes
            combined = combined.astype(np.int32)
        vmin = int(combined.min())
        vmax = int(combined.max())
        rng = vmax - vmin + 1
        if rng < (1 << 31) and rng <= max(2 * n, 1 << 22):
            cnts = nativeops.count_keys(combined, vmin, rng)
            if cnts is None:
                cnts = np.bincount(combined - vmin, minlength=rng)
            nz = np.flatnonzero(cnts)
            uniq = nz + vmin
            rows = cnts[nz].astype(np.int64)
            if not need_inv:
                return uniq, rows, None
            lookup = np.zeros(rng, np.int32)
            lookup[nz] = np.arange(len(nz), dtype=np.int32)
            inv = nativeops.gather_lookup(combined, vmin, lookup)
            if inv is None:
                inv = lookup[combined - vmin].astype(np.int64)
            return uniq, rows, inv
    if need_inv:
        uniq, inv, rows = np.unique(combined, return_inverse=True,
                                    return_counts=True)
        return uniq, rows.astype(np.int64), inv
    uniq, rows = np.unique(combined, return_counts=True)
    return uniq, rows.astype(np.int64), None


def host_rollup_agg(agg: D.Aggregation, snap) -> Optional[dict]:
    """Rollup fast path: Aggregation over an Expand whose group keys are
    exactly (expand key cols..., gid).

    Instead of replicating every row levels x (the literal Expand
    semantics, still the device program's shape), aggregate the BASE
    level once and derive each rollup level by re-aggregating the tiny
    group table — the classic sorted-rollup optimization (the reference's
    Expand feeds a single-pass hash agg; MySQL's filesort rollup rolls
    subtotals the same way).  Returns host_sort_agg-shaped states, or
    None when the DAG is not rollup-shaped."""
    ex = agg.child
    if not isinstance(ex, D.Expand):
        return None
    from ..expr.ir import ColumnRef
    n_base = len(D.output_dtypes(ex.child))
    L = len(ex.keys)
    gb = agg.group_by
    if len(gb) != L + 1 or ex.levels != L + 1:
        return None
    for j, g in enumerate(gb):
        if not (isinstance(g, ColumnRef) and g.index == n_base + j):
            return None
    # aggregate args must read only base columns
    for a in agg.aggs:
        if a.arg is not None and any(
                r.index >= n_base for r in _refs(a.arg)):
            return None
    base = D.Aggregation(ex.child, ex.keys, agg.aggs,
                         D.GroupStrategy.SORT,
                         group_capacity=agg.group_capacity)
    st0 = host_sort_agg(base, snap)
    if st0 is None:
        return None
    ng0 = int(st0["__ngroups__"])

    def level_states(lvl: int) -> dict:
        """Roll the base table up to keep the first L-lvl keys (every
        level derives independently from the base table st0)."""
        keep = L - lvl
        kv = [st0[f"k{j}"] for j in range(keep)]
        if keep:
            codes = [_np_key_code(np.asarray(k["val"]),
                                  np.asarray(k["valid"]), gb[j].dtype)
                     for j, k in enumerate(kv)]
            nulls = [~np.asarray(k["valid"]) for k in kv]
            mat = np.stack(codes + [nf.astype(np.int64) for nf in nulls],
                           axis=1)
            uniq, first, inv = np.unique(mat, axis=0, return_index=True,
                                         return_inverse=True)
            ng = len(uniq)
        else:
            first = np.zeros(1, np.int64) if ng0 else np.zeros(0, np.int64)
            inv = np.zeros(ng0, np.int64)
            ng = 1 if ng0 else 0

        def regroup(name, a):
            a = np.asarray(a)
            how = _MERGE[name]
            if how == "sum":
                out = np.zeros(ng, a.dtype)
                np.add.at(out, inv, a)       # exact at any magnitude
                return out
            neutral = (np.inf if how == "min" else -np.inf) \
                if a.dtype.kind == "f" else (
                    np.iinfo(a.dtype).max if how == "min"
                    else np.iinfo(a.dtype).min)
            out = np.full(ng, neutral, a.dtype)
            (np.minimum if how == "min" else np.maximum).at(out, inv, a)
            return out

        states: dict = {"__rows__": regroup("__rows__", st0["__rows__"])}
        for i in range(len(agg.aggs)):
            states[f"a{i}"] = {f: regroup(f, v)
                               for f, v in st0[f"a{i}"].items()}
        for j in range(L):
            if j < keep:
                states[f"k{j}"] = {
                    "val": np.asarray(st0[f"k{j}"]["val"])[first],
                    "valid": np.asarray(st0[f"k{j}"]["valid"])[first]}
            else:    # rolled key: NULL at this level
                z = np.zeros(ng, np.asarray(st0[f"k{j}"]["val"]).dtype)
                states[f"k{j}"] = {"val": z, "valid": np.zeros(ng, bool)}
        states[f"k{L}"] = {"val": np.full(ng, lvl, np.int64),
                           "valid": np.ones(ng, bool)}
        states["__ngroups__"] = np.int64(ng)
        return states

    parts = [None] * (L + 1)
    # level 0 is the base table itself plus the gid key
    lvl0: dict = {"__rows__": np.asarray(st0["__rows__"])}
    for i in range(len(agg.aggs)):
        lvl0[f"a{i}"] = {f: np.asarray(v)
                         for f, v in st0[f"a{i}"].items()}
    for j in range(L):
        lvl0[f"k{j}"] = {"val": np.asarray(st0[f"k{j}"]["val"]),
                         "valid": np.asarray(st0[f"k{j}"]["valid"])}
    lvl0[f"k{L}"] = {"val": np.zeros(ng0, np.int64),
                     "valid": np.ones(ng0, bool)}
    lvl0["__ngroups__"] = np.int64(ng0)
    parts[0] = lvl0
    for lvl in range(1, L + 1):
        parts[lvl] = level_states(lvl)

    out: dict = {"__ngroups__": np.int64(sum(int(p["__ngroups__"])
                                             for p in parts))}
    out["__rows__"] = np.concatenate([p["__rows__"] for p in parts])
    for i in range(len(agg.aggs)):
        out[f"a{i}"] = {f: np.concatenate([p[f"a{i}"][f] for p in parts])
                        for f in parts[0][f"a{i}"]}
    for j in range(L + 1):
        out[f"k{j}"] = {
            "val": np.concatenate([p[f"k{j}"]["val"] for p in parts]),
            "valid": np.concatenate([p[f"k{j}"]["valid"] for p in parts])}
    return out


def _refs(e):
    from ..expr.ir import ColumnRef, Func
    if isinstance(e, ColumnRef):
        yield e
    elif isinstance(e, Func):
        for a in e.args:
            yield from _refs(a)


def host_sort_agg(agg: D.Aggregation, snap) -> Optional[dict]:
    """SORT-strategy partial states over host columns, or None when the
    child DAG / aggregate set is outside this path's scope."""
    if not agg.group_by:
        return None
    if isinstance(agg.child, D.Expand):
        out = host_rollup_agg(agg, snap)
        if out is not None:
            return out
    if any(g.dtype.is_wide_decimal for g in agg.group_by):
        return None          # object keys: generic HostAgg groups them
    for a in agg.aggs:
        if a.func not in (D.AggFunc.COUNT, D.AggFunc.SUM, D.AggFunc.MIN,
                          D.AggFunc.MAX):
            return None
        if a.arg is not None and a.arg.dtype.is_wide_decimal:
            return None      # object values: exact python aggregation
    if snap.num_rows >= 2 ** 31 and any(
            a.func == D.AggFunc.SUM
            and a.arg.dtype.kind not in (K.FLOAT64, K.FLOAT32)
            for a in agg.aggs):
        # beyond the single-table limb-exact SUM bound: let the device
        # program split rows across shards instead of aborting
        return None
    chain = _host_scan_chain(agg.child, snap)
    if chain is None:
        return None
    cols, _live = chain
    n = len(cols[0][0]) if cols else 0

    ev = Evaluator(np)
    memo: dict = {}
    # canonical per-key (code, nullflag) in the device program's zeroing
    # semantics: NULLs zeroed + flagged, -0.0 groups with +0.0.
    # `valid is True` stays a sentinel — materializing np.ones(n) per
    # all-valid key cost two full passes on the rollup rung.
    key_vals, key_valids, key_codes = [], [], []
    for e in agg.group_by:
        v, m = ev.eval(e, cols, memo)
        v = np.broadcast_to(np.asarray(v), (n,))
        all_valid = m is True
        valid = True if all_valid else np.broadcast_to(np.asarray(m), (n,))
        vz = v if all_valid else np.where(valid, v, np.zeros((), v.dtype))
        if e.dtype.is_float:
            vz = np.where(vz == 0, np.zeros((), vz.dtype), vz)
        key_vals.append(vz)
        key_valids.append(valid)
        if all_valid and not e.dtype.is_float:
            # already canonical: ints/codes compare bit-stably.  Signed
            # narrow physical arrays pass through unwidened — the native
            # counting loop reads them at physical width
            code = vz if vz.dtype.kind == "i" else vz.astype(np.int64)
        else:
            code = _np_key_code(vz, np.asarray(valid), e.dtype)
        key_codes.append(code)

    # combine keys into one int id.  Fast path: direct mixed-radix
    # packing over per-key OBSERVED ranges — one linear pass per key, at
    # the narrowest width that holds the radix product (a 6-slot rollup
    # key domain packs in int16, not 8-byte temporaries).  The np.unique
    # factorization fallback costs a sort per key and dominated the
    # rollup rung ~40:1 before this path existed.
    combined = None
    if n and len(key_codes) >= 2:   # single-key ids pass through unshifted
        spans = []
        total = 1
        for code, valid in zip(key_codes, key_valids):
            vmin = int(code.min())
            vmax = int(code.max())
            allv = valid is True
            w = (vmax - vmin + 1) * (1 if allv else 2)
            spans.append((vmin, w, allv))
            total *= w
            if total >= 2 ** 62:
                break
        if total < 2 ** 62:
            # strict bounds: every per-key radix w divides total, so
            # total < 2**15 guarantees tgt(w) is representable too
            tgt = (np.int16 if total < 2 ** 15 else
                   np.int32 if total < 2 ** 31 else np.int64)
            combined = np.zeros(n, tgt)
            for (vmin, w, allv), code, valid in zip(spans, key_codes,
                                                    key_valids):
                np.multiply(combined, tgt(w), out=combined)
                if allv and vmin == 0:
                    np.add(combined, code, out=combined,
                           casting="unsafe")
                    continue
                # field = (code - vmin)[*2 + nullflag], computed one
                # width up from the code so the shift cannot wrap
                up = {1: np.int16, 2: np.int32}.get(
                    code.dtype.itemsize, np.int64)
                f = np.subtract(code, vmin, dtype=up)
                if not allv:
                    np.add(f, f, out=f)
                    np.add(f, ~valid, out=f, casting="unsafe")
                np.add(combined, f, out=combined, casting="unsafe")
    if combined is None:
        # pairwise factorized radices: a sort per key, but works for any
        # key domain (values stay < n^2 < 2^63)
        def _nf(j):
            kv = key_valids[j]
            return 0 if kv is True else (~kv).astype(np.int64)

        combined = key_codes[0]
        if key_valids[0] is not True:
            if combined.size and -2 ** 62 < int(combined.min()) \
                    and int(combined.max()) < 2 ** 62:
                combined = combined * np.int64(2) + _nf(0)
            else:
                u = np.unique(combined, return_inverse=True)[1]
                combined = u * np.int64(2) + _nf(0)
        for j in range(1, len(key_codes)):
            ua, inv_a = np.unique(combined, return_inverse=True)
            ub, inv_b = np.unique(key_codes[j], return_inverse=True)
            combined = inv_a.astype(np.int64) * np.int64(2 * len(ub)) \
                + inv_b.astype(np.int64) * 2 \
                + _nf(j)

    # per-row group ids are only needed beyond COUNT(*), and a group
    # representative row only when the key can't be decoded from its own
    # code (return_index forces a 4x slower stable argsort inside
    # np.unique, so avoid it entirely: representatives come from a
    # scatter of row ids through inv instead)
    k0 = agg.group_by[0]
    decodable_key = (len(agg.group_by) == 1 and key_valids[0] is True
                     and not k0.dtype.is_float)
    need_inv = (not decodable_key
                or any(not (a.func == D.AggFunc.COUNT and a.arg is None)
                       for a in agg.aggs))
    uniq, rows, inv = _group_codes(combined, need_inv)
    ng = len(uniq)

    states: dict = {"__ngroups__": np.int64(ng),
                    "__rows__": rows.astype(np.int64)}
    if decodable_key:
        # single non-null non-float key: the unique codes ARE the values
        states["k0"] = {"val": uniq.astype(key_vals[0].dtype),
                        "valid": np.ones(ng, bool)}
    else:
        # any row of a group yields the same (zeroed value, nullflag)
        rep = np.empty(ng, np.int64)
        rep[inv] = np.arange(n)
        for j, (vz, valid) in enumerate(zip(key_vals, key_valids)):
            states[f"k{j}"] = {"val": vz[rep],
                               "valid": (np.ones(ng, bool) if valid is True
                                         else valid[rep])}

    def seg_sum(vals):
        # bincount beats np.add.at ~10x; float64 weights are the natural
        # accumulator for float sums
        return np.bincount(inv, weights=vals,
                           minlength=ng)[:ng].astype(vals.dtype)

    for i, a in enumerate(agg.aggs):
        if a.func == D.AggFunc.COUNT and a.arg is None:
            states[f"a{i}"] = {"count": rows.astype(np.int64)}
            continue
        av, am = ev.eval(a.arg, cols, memo)
        av = np.broadcast_to(np.asarray(av), (n,))
        mask = (np.ones(n, bool) if am is True
                else np.broadcast_to(np.asarray(am), (n,)))
        cnt = np.bincount(inv[mask], minlength=ng).astype(np.int64)
        if a.func == D.AggFunc.COUNT:
            states[f"a{i}"] = {"count": cnt}
            continue
        if a.func == D.AggFunc.SUM:
            if a.arg.dtype.kind in (K.FLOAT64, K.FLOAT32):
                v = np.where(mask, av.astype(np.float64), 0.0)
                states[f"a{i}"] = {"sum": seg_sum(v), "cnt": cnt}
                continue
            if n >= 2 ** 31:
                raise OverflowError(
                    f"{n} rows exceed the 2^31 limb-exact SUM bound")
            v = np.where(mask, av, av.dtype.type(0) if hasattr(av, "dtype")
                         else 0)
            vmax = int(v.max()) if len(v) else 0
            vmin = int(v.min()) if len(v) else 0
            one_limb = 0 <= vmin and vmax < 2 ** 32
            if not one_limb and v.dtype != np.int64:
                v = v.astype(np.int64)
            hi, lo = _seg_sum_int(inv, v, ng, one_limb)
            states[f"a{i}"] = {"hi": hi, "lo": lo, "cnt": cnt}
            continue
        # MIN / MAX: neutral-fill invalid rows, segment-reduce in the
        # value's own dtype (uint64 must not be squeezed through int64)
        v = np.asarray(av)
        if v.dtype.kind == "f":
            v = v.astype(np.float64)
            neutral = np.inf if a.func == D.AggFunc.MIN else -np.inf
        else:
            if v.dtype.kind not in "iu":
                v = v.astype(np.int64)
            info = np.iinfo(v.dtype)
            neutral = info.max if a.func == D.AggFunc.MIN else info.min
        red = np.minimum if a.func == D.AggFunc.MIN else np.maximum
        v = np.where(mask, v, v.dtype.type(neutral))
        out = np.full(ng, neutral, v.dtype)
        red.at(out, inv, v)
        states[f"a{i}"] = {("min" if a.func == D.AggFunc.MIN else "max"):
                           out, "cnt": cnt}
    return states


_SEG_CHUNK = 1 << 20


def _seg_sum_int(gid: np.ndarray, v: np.ndarray, size: int,
                 one_limb: bool) -> tuple[np.ndarray, np.ndarray]:
    """Exact per-group (hi, lo) 32-bit-limb sums of int values via chunked
    np.bincount: each <=2^20-row chunk's float64 weight accumulation stays
    below 2^52 in magnitude (exact — float64 is exact for negative weights
    under the same bound, so the signed hi limb needs no bias), and chunk
    results accumulate in int64 — ~3x faster than np.add.at's scatter
    loop on this host.

    one_limb (all values in [0, 2^32)): `v` may be ANY int width — narrow
    physical columns feed bincount directly, skipping the astype and mask
    passes.  Two-limb: `v` must be int64."""
    lo = np.zeros(size, np.int64)
    hi = np.zeros(size, np.int64)
    for s in range(0, len(v), _SEG_CHUNK):
        g = gid[s:s + _SEG_CHUNK]
        vv = v[s:s + _SEG_CHUNK]
        if one_limb:
            lo += np.bincount(g, weights=vv,
                              minlength=size)[:size].astype(np.int64)
            continue
        lo += np.bincount(g, weights=vv & 0xFFFFFFFF,
                          minlength=size)[:size].astype(np.int64)
        # arithmetic shift: (v>>32)*2^32 + (v&0xFFFFFFFF) == v exactly,
        # including negatives; |hi| <= 2^31 so the chunk sum stays exact
        hi += np.bincount(g, weights=vv >> 32,
                          minlength=size)[:size].astype(np.int64)
    return hi, lo


_DENSE_CHUNK = 1 << 20


def host_dense_agg(agg: D.Aggregation, snap) -> Optional[dict]:
    """DENSE/SCALAR-strategy partial states over host columns (the CPU
    engine choice for Q1-shaped small-domain group-bys).

    Chunk-at-a-time (the reference executor\'s chunk discipline,
    executor.go Next-with-chunk): expression temporaries for a <=2^20-row
    chunk stay cache-hot, measured ~3x faster than full-width passes at
    SF=10 on a bandwidth-limited host.  Per-chunk partial states merge
    through the same merge_states path the device shards use.  None =
    out of scope."""
    for a in agg.aggs:
        if a.func not in (D.AggFunc.COUNT, D.AggFunc.SUM, D.AggFunc.MIN,
                          D.AggFunc.MAX):
            return None
        if a.arg is not None and a.arg.dtype.is_wide_decimal:
            return None      # object values: generic HostAgg path
    total = snap.num_rows
    ranges = [(lo, min(lo + _DENSE_CHUNK, total))
              for lo in range(0, total, _DENSE_CHUNK)] or [(0, 0)]
    out = []
    for rng in ranges:
        st = _dense_chunk_states(agg, snap, rng)
        if st is None:
            return None
        out.append(st)
    return out[0] if len(out) == 1 else merge_states(out)


def _dense_chunk_states(agg: D.Aggregation, snap, rng) -> Optional[dict]:
    chain = _host_scan_chain(agg.child, snap, allow_mask=True, rng=rng)
    if chain is None:
        return None
    cols, live = chain
    n = len(cols[0][0]) if cols else 0
    ev = Evaluator(np)
    memo: dict = {}

    if agg.strategy == D.GroupStrategy.DENSE:
        G = 1
        gid = np.zeros(n, np.int64)
        for e, size in zip(agg.group_by, agg.domain_sizes):
            v, m = ev.eval(e, cols, memo)
            v = np.broadcast_to(np.asarray(v), (n,)).astype(np.int64)
            if e.dtype.nullable:
                code = v + 1 if m is True else np.where(m, v + 1, 0)
            else:
                code = v
            gid = gid * int(size) + code
            G *= int(size)
    else:                                  # SCALAR
        G = 1
        gid = np.zeros(n, np.int64)

    if live is not None:
        # uncompacted high-selectivity filter: dead rows route to a trim
        # group past G (single pass instead of per-column takes)
        gid = np.where(live, gid, np.int64(G))
    full_cnt = np.bincount(gid, minlength=G + 1).astype(np.int64)
    rows = full_cnt[:G]
    states: dict = {"__rows__": rows}
    for i, a in enumerate(agg.aggs):
        if a.func == D.AggFunc.COUNT and a.arg is None:
            states[f"a{i}"] = {"count": rows}
            continue
        av, am = ev.eval(a.arg, cols, memo)
        av = np.broadcast_to(np.asarray(av), (n,))
        # dead (filtered) rows already route to the trim slot past G, so
        # only the aggregate's OWN null mask needs applying to values
        all_valid = am is True
        if all_valid:
            cnt = rows
            mask = None
        else:
            mask = np.broadcast_to(np.asarray(am), (n,))
            cnt = np.bincount(gid[mask],
                              minlength=G + 1)[:G].astype(np.int64)
        if a.func == D.AggFunc.COUNT:
            states[f"a{i}"] = {"count": cnt}
        elif a.func == D.AggFunc.SUM:
            if a.arg.dtype.kind in (K.FLOAT64, K.FLOAT32):
                v = av.astype(np.float64)
                if mask is not None:
                    v = np.where(mask, v, 0.0)
                out = np.bincount(gid, weights=v, minlength=G + 1)
                states[f"a{i}"] = {"sum": out[:G], "cnt": cnt}
            else:
                if n >= 2 ** 31:
                    return None        # past the limb-exact bound
                v = av
                if mask is not None:
                    v = np.where(mask, v, v.dtype.type(0))
                vmax = int(v.max()) if len(v) else 0
                vmin = int(v.min()) if len(v) else 0
                one_limb = 0 <= vmin and vmax < 2 ** 32
                if not one_limb and v.dtype != np.int64:
                    v = v.astype(np.int64)
                hi, lo = _seg_sum_int(gid, v, G + 1, one_limb)
                states[f"a{i}"] = {"hi": hi[:G], "lo": lo[:G],
                                   "cnt": cnt}
        else:
            v = np.asarray(av)
            if v.dtype.kind == "f":
                v = v.astype(np.float64)
                neutral = np.inf if a.func == D.AggFunc.MIN else -np.inf
            else:
                if v.dtype.kind not in "iu":
                    v = v.astype(np.int64)
                info = np.iinfo(v.dtype)
                neutral = (info.max if a.func == D.AggFunc.MIN
                           else info.min)
            if mask is not None:
                v = np.where(mask, v, v.dtype.type(neutral))
            out = np.full(G + 1, neutral, v.dtype)
            (np.minimum if a.func == D.AggFunc.MIN
             else np.maximum).at(out, gid, v)
            states[f"a{i}"] = {("min" if a.func == D.AggFunc.MIN
                                else "max"): out[:G], "cnt": cnt}
    return states


__all__ = ["host_sort_agg", "host_dense_agg"]
