"""Generic timer framework driving background workers.

Reference analog: pkg/timer (9.5k LoC: timer store + runtime firing
hooks, used by TTL among others) — a single scheduler thread fires
registered timers at their interval; each timer records last-fire state
and errors; `trigger()` fires one synchronously (the test hook, like
the reference's manual timer store updates).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class Timer:
    name: str
    interval: float
    fn: Callable[[], object]
    enabled: bool = True
    last_fire: float = 0.0
    fire_count: int = 0
    last_error: str = ""


class TimerFramework:
    def __init__(self, tick: float = 0.5):
        self._timers: dict[str, Timer] = {}
        self._mu = threading.Lock()
        self._tick = tick
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, name: str, interval: float,
                 fn: Callable[[], object]) -> Timer:
        t = Timer(name, interval, fn)
        with self._mu:
            self._timers[name] = t
        return t

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="timer-fw", daemon=True)
            self._thread.start()

    def close(self):
        self._closed.set()

    def trigger(self, name: str):
        """Fire one timer synchronously (test/manual hook)."""
        with self._mu:
            t = self._timers[name]
        self._fire(t)

    def timers(self) -> list[Timer]:
        with self._mu:
            return list(self._timers.values())

    # ---------------------------------------------------------- #

    def _loop(self):
        while not self._closed.wait(self._tick):
            now = time.time()
            with self._mu:
                due = [t for t in self._timers.values()
                       if t.enabled and now - t.last_fire >= t.interval]
            for t in due:
                self._fire(t)

    def _fire(self, t: Timer):
        t.last_fire = time.time()
        t.fire_count += 1
        try:
            t.fn()
            t.last_error = ""
        except Exception as e:   # background workers never kill the loop
            t.last_error = f"{type(e).__name__}: {e}"


__all__ = ["TimerFramework", "Timer"]
