"""faultline: deterministic fault injection + launch supervision
primitives (seeded FaultPlan seams, per-digest circuit breaker).  The
scheduler drain and CopClient consult these; see plan.py / breaker.py
for the design."""

from .breaker import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
                      LaunchQuarantinedError, digest_hex)
from .plan import (SEAMS, FaultPlan, FaultRule, InjectedFault,
                   MemoryFault, PoisonFault, TransientFault, active,
                   check, clear, install, install_spec, is_oom_error,
                   stats)

__all__ = ["FaultPlan", "FaultRule", "InjectedFault", "TransientFault",
           "PoisonFault", "MemoryFault", "is_oom_error", "SEAMS",
           "install", "install_spec", "clear", "active", "check",
           "stats", "CircuitBreaker", "LaunchQuarantinedError",
           "digest_hex", "CLOSED", "OPEN", "HALF_OPEN"]
